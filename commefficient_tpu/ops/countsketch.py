"""TPU-native Count Sketch — blocked, matmul-based, zero random access.

Re-implements the semantics of the reference's ``csvec`` dependency
(``csvec/csvec.py``, ~350 LoC: ``CSVec.accumulateVec`` ~L120-160, ``__add__``
~L160-180, ``_findAllValues``/``_findHHK`` ~L190-260, ``unSketch`` ~L260-290,
``l2estimate`` ~L290-310) with a hash-family layout chosen FOR the TPU rather
than translated from CUDA.

Why not the classic layout: the reference scatters each coordinate to a
random bucket (``scatter_add``) and gathers random buckets back — on GPUs
those are atomic-add/gather at memory bandwidth, but the TPU is a
contiguous-vector machine with no fast random access (measured on v5e:
a 50k-element scatter into 6.5M costs ~24 ms — microseconds of matmul).

Layout (this module, v5 — "banded"):
  * Coordinates are split into CHUNKS of ``m``. Chunk q hashes its
    within-chunk offsets into a WINDOW of ``V = band * stride`` buckets
    starting at ``q * stride`` of the global row, so neighboring chunks'
    windows OVERLAP and each coordinate's collision pool is V (~5k)
    buckets, not a private per-chunk pool. One static ``[m, V]`` one-hot
    realizes a whole row as a single ``[nc, m] x [m, V]`` MXU matmul
    followed by ``band`` static shifted adds (overlap-add) — no scatter,
    no gather. Estimation is the windowed view (static slices) and the
    transposed matmul, then median across rows.
  * Before any row layout, ONE seed-derived static permutation of
    ``scramble_block``-sized coordinate blocks (a cheap row-gather)
    decorrelates parameter structure from chunk structure; each row then
    applies a distinct-prime RIFFLE (``reshape(f, L/f).T`` transpose) so
    partner sets differ across rows.

v3/v4 POSTMORTEM (do not regress to disjoint pools): with per-chunk
PRIVATE pools (v3 riffles only, v4 + scramble), a coordinate can only
collide inside its chunk's ~300 buckets. FetchSGD's error sketch
accumulates STRUCTURED mass (layer-correlated magnitudes, long waits for
small coordinates), and per-chunk collision noise grows with the hot
chunks — the extract-and-subtract feedback loop then amplifies phantom
estimates: measured on ResNet-9 at paper-scale settings (d/c=13, k=d/130,
lr 0.4, momentum 0.9) as exponential divergence (train loss 459 after 6
epochs; NaN under several variants), while an EXACT classic scatter
sketch under identical server algebra converged (acc 0.315). Banding
restores a classic-grade collision scope at MXU cost: the same config
converges at acc 0.340 with band=16 at default matmul precision
(scripts/sketch_lab.py reproduces the whole comparison; forcing
Precision.HIGHEST changes nothing but costs 3x — the divergence was never
a precision problem). Single-shot estimate quality was IDENTICAL across
layouts (recall@k ~0.38 on a real gradient) — only the iterated feedback
loop separates them; test any future layout change with the lab's
multi-epoch run, not one-shot properties.

Linearity is the contract that makes federated aggregation exact:
``sketch(a) + sketch(b) == sketch(a + b)`` (bit-exact in float32 mode up to
float addition order), so ``lax.psum`` of worker tables IS the sketch of the
summed update. Precision caveat: on TPU the matmul paths run at the default
(bf16-pass) matmul precision, so matmul-path results (sketch_vec,
estimate_all) carry ~2^-8 RELATIVE rounding vs the exact gather/scatter
paths (sketch_sparse, estimate_at) — exact on CPU, ~4e-3 relative on TPU.
Training is insensitive (accumulate and EF-subtract share the matmul path,
so the rounding cancels to first order; lab-verified), and forcing
Precision.HIGHEST costs 3x for no accuracy change.

``num_blocks`` (reference: GPU-memory hash-reuse chunking, csvec.py
~L60-100) is here the memory knob for FULL-d estimation: with
``num_blocks > 1``, ``estimate_all`` runs the exact gather path over
``num_blocks`` coordinate slices under ``lax.map``, bounding the transient
to ``r * d/num_blocks`` instead of the matmul path's ``r * d_eff`` stack
(2.5 GB at GPT-2 scale d=124M r=5 — the same scale the reference needs
``numBlocks=20`` at). Semantics are identical (pinned by
test_num_blocks_invariance); speed is lower (gather is the TPU slow path),
which is the same memory-for-speed trade the reference's flag makes.

All functions are pure and jit/vmap/shard_map-friendly.
"""

from __future__ import annotations

import functools as _functools
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

_M1 = np.uint32(0x85EBCA6B)
_M2 = np.uint32(0xC2B2AE35)
_GOLDEN = np.uint32(0x9E3779B9)

# Mersenne prime for the optional 4-universal polynomial hash family
# ("poly4", the reference csvec's guarantee class, csvec.py ~L10-80).
# 2^31 - 1 keeps every Horner product a*x < 2^62 inside uint64 on the host.
_MERSENNE_P = np.uint64(2**31 - 1)


def _poly4_eval(x: np.ndarray, coeffs: np.ndarray) -> np.ndarray:
    """((c0 x^3 + c1 x^2 + c2 x + c3) mod p) for uint64 x < p — Horner with
    every intermediate < 2^62, exact in uint64. 4-wise independent over the
    seed-random coefficients (degree-3 polynomial over GF(p))."""
    # Exactness (every Horner product < 2^62) AND 4-universality both
    # require inputs inside the field: x < p. A silent wrap here would
    # degrade the guarantee class without failing loudly (ADVICE r3).
    if x.size and int(x.max()) >= int(_MERSENNE_P):
        raise ValueError(
            f"poly4 hash input {int(x.max())} >= p=2^31-1; the 4-universal "
            "family is only defined over GF(p) — use hash_family='fmix32' "
            "at this scale"
        )
    acc = np.zeros_like(x) + coeffs[0]
    for a in coeffs[1:]:
        acc = (acc * x + a) % _MERSENNE_P
    return acc

_P31 = np.uint32(2**31 - 1)  # numpy scalar: embeds as a literal inside
# Pallas kernel bodies (a jnp scalar would be a captured constant that
# pallas_call rejects)


def _fold31(y: jnp.ndarray) -> jnp.ndarray:
    """One Mersenne fold: y (< 2^32) -> congruent value <= 2^31."""
    return (y & _P31) + (y >> jnp.uint32(31))


def _modmul31(a: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """(a * x) mod (2^31 - 1), exact, for a, x < 2^31 - 1 — uint32 only.

    TPUs (and Pallas kernel bodies) have no uint64, so the Horner products
    of the poly4 family are evaluated in 16-bit limbs: a*x = H*2^32 +
    M*2^16 + L with H = ah*xh < 2^30, M = ah*xl + al*xh < 2^32, L = al*xl
    < 2^32 (each fits uint32). With 2^31 === 1 (mod p): H*2^32 === 2H, and
    M*2^16 folds as (M >> 15) + ((M & 0x7fff) << 16). Every partial sum is
    folded before it can overflow; the result is reduced to < p, matching
    the host uint64 ``% p`` bit-for-bit (pinned by
    tests/test_countsketch_pallas.py)."""
    u16 = jnp.uint32(16)
    mask16 = jnp.uint32(0xFFFF)
    ah, al = a >> u16, a & mask16
    xh, xl = x >> u16, x & mask16
    H = ah * xh
    M = ah * xl + al * xh
    L = al * xl
    t0 = H << jnp.uint32(1)                                   # < 2^31
    t1 = (M >> jnp.uint32(15)) + ((M & jnp.uint32(0x7FFF)) << u16)
    t1 = _fold31(_fold31(t1))                                 # <= p
    t2 = _fold31(_fold31(L))                                  # <= p
    acc = _fold31(_fold31(t0 + t1))                           # <= p
    acc = _fold31(_fold31(acc + t2))                          # <= p
    return jnp.where(acc >= _P31, acc - _P31, acc)            # < p


def _poly4_u32(x: jnp.ndarray, coeffs) -> jnp.ndarray:
    """Horner evaluation of the seed-derived degree-3 polynomial over
    GF(2^31-1) in uint32 — identical values to the host uint64
    ``_poly4_eval`` for inputs < p. ``coeffs`` are static python ints.
    Safe both in regular jit traces and inside Pallas kernel bodies."""
    acc = jnp.full(x.shape, jnp.uint32(int(coeffs[0])))
    for a in coeffs[1:]:
        acc = _modmul31(acc, x) + jnp.uint32(int(a))          # <= p + p - 1
        acc = _fold31(_fold31(acc))
        acc = jnp.where(acc >= _P31, acc - _P31, acc)
    return acc


def _is_prime(n: int) -> bool:
    if n < 2:
        return False
    i = 2
    while i * i <= n:
        if n % i == 0:
            return False
        i += 1
    return True


def _next_prime_geq(n: int) -> int:
    n = max(n, 2)
    while not _is_prime(n):
        n += 1
    return n


@_functools.lru_cache(maxsize=None)
def _riffle_factors(d: int, m: int, r: int) -> tuple:
    """Per-row riffle factors (always distinct).

    A pair of coordinates at distance delta is co-chunked in row f only
    when ``delta < m/f`` (its "window") or delta lands near a multiple of
    L/f. The median over r rows is corrupted only when >= ceil(r/2) rows
    co-chunk the same pair, so the factor set must keep the number of
    rows whose window covers any given delta BELOW that.

    Strong regime (nc >= m, i.e. d >= ~m^2 — the CV production scales;
    GPT-2's d/c~100 pushes m above sqrt(d) for pool size and lands in the
    small regime with ~330-bucket pools):
    factors are (1, ~sqrt(m), then for row i >= 2 a prime near nc/g_i
    with g_i the i-th odd-indexed prime (2, 3, 5, ...)). Those rows have
    window m/f ~= g_i m^2/d of order one, so near pairs co-chunk in at
    most ~2 rows, AND — critically — their far-pair lattices have
    spacings G = L/f ~= g_i * m that are pairwise DISTINCT (a pair lands
    on >= 2 giant rows' lattices only at lcm-scale spacings). Taking
    consecutive primes >= nc instead makes L = m*f and G = m for EVERY
    giant row — identical far-pair partner sets across rows, the v2
    repeated-partner pathology at lattice scale (measured: |Se| -> 1e9 in
    the fixed-input iteration). mf ~= d also keeps padding ~O(1%).

    Small regime (nc < m): a geometric prime ladder 1..~nc, bumping any
    factor out of the bad padding zone mf in (d/2, d) (where L = 2mf
    nearly doubles the row and halves its bucket pool). Windows can't
    shrink below m/nc > 1 without multi-x padding, so near pairs remain
    co-chunked in several rows; with the >=128 bucket pools this measures
    stable in the FetchSGD feedback iteration, but adversarially tight
    heavy-hitter clusters can still produce phantoms at this scale (the
    strong regime, or an explicit smaller ``m``, avoids them).
    """
    nc0 = max(1, -(-d // m))

    def lattice(f: int) -> int:
        # padded lattice spacing G = L/f in units of m: ceil(nc0/f)
        return -(-nc0 // f)

    def pick(target: int, fs: list, used_g: set) -> int:
        """Smallest prime >= target whose f AND padded lattice spacing G
        are both unused. G-distinctness is the invariant (two rows with
        equal G share their entire far-pair partner lattice — the v2
        repeated-partner pathology at lattice scale; composite/bumped
        factors hit this through padding, e.g. f=5 and f=6 at nc0=10 both
        give G=2m). When every G >= target is exhausted (tiny nc0), fall
        back to a distinct prime with the least-used G."""
        f = _next_prime_geq(max(2, target))
        for _ in range(10_000):
            if f not in fs and lattice(f) not in used_g:
                return f
            f = _next_prime_geq(f + 1)
            if lattice(f) <= 1 and 1 in used_g:
                break  # G saturated at m; no distinct G above here
        f = _next_prime_geq(max(2, target))
        while f in fs:
            f = _next_prime_geq(f + 1)
        return f

    fs = [1]
    used_g = {lattice(1)}
    if r == 1:
        return tuple(fs)
    if nc0 >= m:
        targets = [max(2, int(round(m ** 0.5)))]
        g = 2
        for _ in range(2, r):
            targets.append(max(2, nc0 // g))
            g = _next_prime_geq(g + 1)
    else:
        targets = [
            max(2, int(round(nc0 ** (row / max(r - 1, 1)))))
            for row in range(1, r)
        ]
    for t in targets:
        if 0.5 < (m * t) / d < 1.0:  # bad padding zone: jump past ~nc
            t = nc0
        f = pick(t, fs, used_g)
        fs.append(f)
        used_g.add(lattice(f))
    return tuple(fs)


def _mix32(x: jnp.ndarray, key) -> jnp.ndarray:
    """murmur3 fmix32 with a key fold — uint32 in, well-scrambled uint32 out."""
    x = (x ^ key).astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * _M1
    x = x ^ (x >> 13)
    x = x * _M2
    x = x ^ (x >> 16)
    return x


class CountSketch(NamedTuple):
    """Static spec of a Count Sketch (the analog of a ``CSVec`` instance).

    The reference couples spec + table + device state in one class; here the
    spec is a hashable static NamedTuple (safe to close over under ``jit``)
    and the table is a plain ``[r, c]`` float array threaded functionally.

    ``c`` is a TARGET column count: each row realizes ``nc_row * s_row``
    columns (rows pad independently for their riffle factors; ``s_row``
    re-targets c per row, clamped to a multiple of 8) and the table width
    ``c_actual`` is the max over rows — within a few percent of the
    request for large d.
    """

    d: int  # length of the vectors being sketched
    c: int  # requested columns (buckets) per row
    r: int  # rows (independent repetitions; median across them)
    num_blocks: int = 1  # >1: chunk estimate_all's memory (module docstring)
    seed: int = 42  # hash seed; equal seeds => equal hashes everywhere
    m: Any = None  # chunk size (coords per bucket block); None = adaptive
    dtype: Any = jnp.float32  # matmul dtype (measured: no v5e speed delta)
    # Global block-scramble (v4). REAL gradients have correlated
    # neighborhoods (a conv kernel's coords sit contiguously in the flat
    # vector with comparable magnitudes). Riffles alone cannot separate
    # pairs closer than m/nc, so a whole correlated cluster co-chunks in
    # most rows and collides inside the tiny per-chunk bucket pool with
    # prob ~cluster/s PER ROW — the median breaks and FetchSGD's feedback
    # loop amplifies the corruption (measured: ResNet-9 training diverges,
    # loss 459 after 6 epochs, while a classic scatter sketch on identical
    # server algebra converges). One static seed-derived permutation of
    # ``scramble_block``-sized blocks, shared by all rows and applied
    # before the per-row riffle/chunk layout, scatters any contiguous
    # cluster uniformly over the chunks: residual same-chunk cluster mass
    # drops from ~cluster/s to ~block/s in >=3 rows simultaneously with
    # probability ~(block/s)^3 — classic-grade. Cost: one [nb, block]
    # row-gather per sketch/estimate — and the ROW SIZE of that gather is
    # the sketch path's measured hot spot (r4, v5e, d=6.5M/c=500k: whole
    # sketch_vec 14.9 ms at block=8 vs 7.9 ms at block=64; estimate_all
    # 21.8 -> 15.2 ms — 8-float rows are a worst case for the TPU gather
    # engine, 64-float rows ~2x faster end-to-end). block=64 keeps the
    # splitting property comfortably: (block/s)^3 at the headline
    # geometry (s=312) is ~0.9% per cluster, and the r4 stability checks
    # (quarter-scale lab, full-scale 7x357k accuracy run, adversarial
    # structured-input tests) hold at 64 — see CHANGELOG_r4. BUT a block
    # must stay small relative to the CHUNK, or a tied contiguous cluster
    # rides one block into one chunk and corrupts the median (the
    # adversarial equal-magnitude test catches exactly this at lab m=64),
    # so None (default) resolves adaptively via ``sblock``:
    # min(64, max(8, chunk_m // 64)) — 64 at production chunk sizes
    # (m=4096 CV, m=8192+ GPT-2), back to 8 at small-m lab geometries.
    # Explicit int pins it; 0 disables (pre-v4 layout).
    scramble_block: Optional[int] = None

    @property
    def sblock(self) -> int:
        """Realized scramble block (see scramble_block field note)."""
        if self.scramble_block is not None:
            # ADVICE r4: a stray non-int (e.g. a float from a config sweep)
            # would flow through sblock/d_eff layout arithmetic unchecked
            # and corrupt the geometry silently — reject it here.
            if (
                not isinstance(self.scramble_block, (int, np.integer))
                or isinstance(self.scramble_block, bool)
            ):
                raise TypeError(
                    "scramble_block must be an int (got "
                    f"{self.scramble_block!r}); it is layout arithmetic, "
                    "not a tunable float"
                )
            return int(self.scramble_block)
        return min(64, max(8, self.chunk_m // 64))
    # Banded buckets (v5). With disjoint per-chunk pools, a coordinate can
    # only ever collide inside its chunk's s (~300) buckets; FetchSGD's
    # error sketch accumulates STRUCTURED mass and the feedback loop
    # measurably diverges at paper-scale d/c even after the scramble and
    # full-f32 matmuls, while a classic (global-bucket) scatter sketch
    # converges under identical server algebra. Banding interpolates the
    # two at MXU cost: chunk q hashes its offsets into a WINDOW of
    # V = band * stride buckets starting at q * stride, so windows of
    # neighboring chunks overlap and each coordinate's collision pool
    # grows 16-64x while the row stays ONE [nc, m] x [m, V] einsum plus
    # ``band`` static shifted adds (overlap-add; no scatter, no gather).
    # band=1 reproduces the disjoint-pool v4 layout; cost scales ~linearly
    # with band (still sub-ms per row at CV scale).
    band: int = 16
    # Hash family for the offset-slot and sign hashes. "fmix32" (default,
    # production): stateless murmur fmix32 — empirically validated
    # (uniformity/decorrelation tests + the multi-epoch lab) but with no
    # independence guarantee. "poly4": seed-derived degree-3 polynomials
    # over GF(2^31 - 1) — the 4-universal guarantee class of the
    # reference's csvec (~L10-80), provided as the lab A/B backstop
    # (VERDICT r2 item 7) so any suspected hash pathology can be tested
    # against a provable family. Scale note: the EINSUM backend's matmul
    # path materializes the [d_eff] poly4 sign vector host-side (fine at
    # CV scale, prohibitive at D=124M); the PALLAS backend evaluates the
    # polynomial in-kernel over uint32 GF(2^31-1) arithmetic (_poly4_u32)
    # and the gather path (_row_cols_signs) does the same on the fly, so
    # backend="pallas" makes poly4 a production-scale family.
    hash_family: str = "fmix32"
    # Kernel backend for the MATMUL-path entry points — sketch_vec,
    # estimate_all's full-d path, and everything built on them
    # (sketch_add_vec, unsketch, unsketch_dense, the round's server
    # algebra). "einsum" (default): the banded [m, V] one-hot einsum +
    # overlap-add above. "pallas": tiled Pallas TPU kernels
    # (ops/pallas/countsketch_kernels.py) that generate the one-hot, the
    # signs, and the band overlap-add INSIDE the kernel — no materialized
    # [m, V] one-hot constant, no [nc, V] window round-trip, no [d_eff]
    # sign vector; interpret mode on CPU, Mosaic on TPU. The two backends
    # share one geometry/hash mapping and agree to fp32 rounding (float
    # summation order differs; pinned by tests/test_countsketch_pallas).
    # Gather/scatter-path ops (sketch_sparse, estimate_at, num_blocks>1
    # estimation) are not matmul-bound and stay backend-agnostic.
    backend: str = "einsum"
    # STORAGE dtype of the [r, c_actual] table (distinct from ``dtype``,
    # the matmul OPERAND dtype). float32 (default): bit-exact tables, the
    # r1-r5 production path — every golden recording pins it. bfloat16:
    # tables are stored/psummed/carried in bf16 while every accumulation
    # (the in-row einsum/kernel reductions, the server momentum/error
    # algebra) stays f32 — halving table HBM traffic and the device_encode
    # psum's collective bytes at GPT-2 scale ([5, 5M] table: 100 MB -> 50
    # MB per round per link). bf16 shares f32's exponent range (no
    # overflow risk), so the cost is ~2^-8 relative rounding at each
    # downcast; the LINEAR aggregation contract (compress/) then holds to
    # that tolerance instead of bit-exactly (pinned by
    # tests/test_countsketch_bf16.py). Estimation upcasts to f32 on read.
    table_dtype: Any = jnp.float32

    # -- derived static geometry ------------------------------------------
    @property
    def d_eff(self) -> int:
        """Scrambled-space length: d padded to a block multiple."""
        b = self.sblock
        return _ceil_mult(self.d, b) if b else self.d

    @property
    def chunk_m(self) -> int:
        """Chunk size. Adaptive default: grow m (512..32768, powers of 2)
        until each chunk gets >= 256 buckets.

        Measured alternative when the floor binds (r5, runs/r5_sketch5.log
        + r5_r7probe.log): at r=7 x c=357k the floor forces m=8192/s=432
        and a 1.42x-wide einsum window per row; pinning ``m=4096``
        (s=224, just under the floor) with ``band=24`` (restores the
        overlap-add collision pool to V ~ 5184) trains to 0.9004 vs the
        default geometry's 0.8997 at 25% less wall-clock. Do NOT go
        further down: m=2048 (s=112) diverges — the floor is a real
        stability boundary, band is the safe recovery lever.

        The bucket-pool target is STABILITY-critical, not a tuning nicety:
        with small pools the per-chunk victim sets are so small that
        FetchSGD's extract-and-subtract feedback loop amplifies collision
        noise instead of damping it. Measured on the fixed-input
        iteration at d=6.6M, c=d/13 (t=59 |Se|max; classic scatter sketch
        = 1526): s=40 -> 2.8e13, s=80 -> 8.7e6, s=160 -> 6981, s=312 ->
        1812, s=624 -> 1680. s~256+ is classic-equivalent; the adaptive
        rule targets that. The larger m also keeps the per-chunk floor of
        8 from inflating the realized table at large d/c (the cap bounds
        the [m, s] one-hot operand at ~40 MB). NB the d/c RATIO itself has
        a measured stability envelope independent of this geometry: the
        r3 lab measured d/c<=25 stable and d/c>=50 diverging for EVERY
        layout tried (banded, global pools, classic scatter, poly4) —
        FetchSGD-style virtual-error feedback runs out of SNR, so GPT-2
        scale needs c >= D/25 (FederatedSession warns; CHANGELOG_r3)."""
        if self.m is not None:
            return min(self.m, _ceil_mult(self.d, 8))
        m = 512
        while m < 32768 and self.d / m > self.c / 256:
            m *= 2
        return min(m, _ceil_mult(self.d, 8))

    @property
    def nc(self) -> int:
        # chunk count of the LARGEST row (each row pads independently so
        # its riffle factor divides its padded length)
        return max(self._nc_row(r) for r in range(self.r))

    def _factor(self, row: int) -> int:
        return _riffle_factors(self.d, self.chunk_m, self.r)[row]

    def _L_row(self, row: int) -> int:
        """Per-row padded length: smallest multiple of m * factor >= d_eff
        (the scrambled-space length the row layouts actually operate on)."""
        return _ceil_mult(self.d_eff, self.chunk_m * self._factor(row))

    def _nc_row(self, row: int) -> int:
        return self._L_row(row) // self.chunk_m

    def u_row(self, row: int) -> int:
        """Band width (windows per chunk) for this row, capped by nc.

        Band width does NOT rescue the d/c~100 regime: the r3 lab measured
        band=16 and global windows (band >= nc, pool = half the row)
        diverging IDENTICALLY at quarter scale (loss ~2e17 by epoch 12,
        fmix32 and poly4 alike, lr 0.04 and 0.08 alike) — see the
        hash_family note and CHANGELOG_r3 for the regime account."""
        return max(1, min(self.band or 1, self._nc_row(row)))

    def s_row(self, row: int) -> int:
        """Bucket STRIDE per chunk for THIS row: chunk q's window starts at
        ``q * s_row``; the realized row width is (nc + u - 1) * s_row,
        targeted at the requested c. (Per-row, so a heavily padded row
        must not shrink every other row's bucket pool.)"""
        raw = max(1, round(self.c / (self._nc_row(row) + self.u_row(row) - 1)))
        return max(8, round(raw / 8) * 8)  # nearest multiple of 8

    def V_row(self, row: int) -> int:
        """Bucket-pool (window) size per chunk: band * stride."""
        return self.u_row(row) * self.s_row(row)

    @property
    def s(self) -> int:
        return self.s_row(0)

    @property
    def c_actual(self) -> int:
        return max(
            (self._nc_row(r) + self.u_row(r) - 1) * self.s_row(r)
            for r in range(self.r)
        )

    @property
    def table_shape(self) -> tuple[int, int]:
        return (self.r, self.c_actual)

    def empty(self, dtype=None) -> jnp.ndarray:
        """A zeroed sketch table (``CSVec.zero()`` analog, csvec.py ~L110).
        Allocated in ``table_dtype`` unless overridden."""
        return jnp.zeros(
            self.table_shape, dtype=self.table_dtype if dtype is None else dtype
        )

    # -- per-row hash ingredients (all static-shape, derived from seed) ----
    def _row_key(self, row: int) -> np.uint32:
        x = (row ^ self.seed) & 0xFFFFFFFF
        for _ in range(2):
            x = ((x ^ (x >> 16)) * int(_M1)) & 0xFFFFFFFF
        return np.uint32(x ^ int(_GOLDEN))

    def _poly4_coeffs(self, row: int, purpose: int) -> np.ndarray:
        """[4] uint64 in [1, p): seed-derived coefficients for this row's
        degree-3 hash polynomial (purpose 0 = bucket slots, 1 = signs)."""
        # host rng at TRACE time, on purpose: SeedSequence((spec.seed,
        # row, purpose)) is a pure function of the sketch spec, so every
        # trace bakes the SAME coefficient table — replay/retrace-safe
        # by construction (pinned by the golden parity recordings).
        # lint: allow[traced-purity] seed-derived trace-time constants
        rng = np.random.default_rng(
            np.random.SeedSequence([int(self.seed) & 0x7FFFFFFF, row, purpose])
        )
        return rng.integers(1, int(_MERSENNE_P), size=4).astype(np.uint64)

    def _row_signs(self, row: int) -> jnp.ndarray:
        """[d_eff] ±1, hashed from the SCRAMBLED-space index (v4: sketching
        happens in scrambled space; ``_row_cols_signs`` maps an original
        coordinate to its scrambled position before hashing, so all entry
        points agree)."""
        if self.hash_family == "poly4":
            idx = np.arange(self.d_eff, dtype=np.uint64)
            bits = _poly4_eval(idx, self._poly4_coeffs(row, 1)) & np.uint64(1)
            return jnp.asarray(1.0 - 2.0 * bits.astype(np.float32))
        idx = jnp.arange(self.d_eff, dtype=jnp.uint32)
        bits = _mix32(idx, self._row_key(row) ^ _GOLDEN) & jnp.uint32(1)
        return 1.0 - 2.0 * bits.astype(jnp.float32)

    def _offset_slots(self, row: int) -> jnp.ndarray:
        """[m] int32 in-window bucket per within-chunk offset (shared by all
        chunks; chunk q's window starts at ``q * s_row``)."""
        if self.hash_family == "poly4":
            off = np.arange(self.chunk_m, dtype=np.uint64)
            slots = _poly4_eval(off, self._poly4_coeffs(row, 0)) % np.uint64(
                self.V_row(row)
            )
            return jnp.asarray(slots.astype(np.int32))
        off = jnp.arange(self.chunk_m, dtype=jnp.uint32)
        return (
            _mix32(off, self._row_key(row)) % jnp.uint32(self.V_row(row))
        ).astype(jnp.int32)

    def _row_onehot(self, row: int) -> jnp.ndarray:
        """[m, V] static one-hot of ``_offset_slots`` — the whole row's hash
        as one small matmul operand."""
        slots = self._offset_slots(row)
        return (
            slots[:, None] == jnp.arange(self.V_row(row), dtype=jnp.int32)
        ).astype(self.dtype)


@_functools.lru_cache(maxsize=None)
def _scramble_perms(d_eff: int, block: int, seed: int):
    """(sperm, inv_sperm) over the d_eff/block blocks: output block j of the
    scramble reads input block sperm[j]; input block B lands at output
    position inv_sperm[B]. Seed-derived (equal seeds => equal scramble on
    every host/device, like the hashes)."""
    nb = d_eff // block
    # pure numpy (callable under an active jax trace): same fmix32 rounds
    key = np.uint32((seed * 2654435761) & 0xFFFFFFFF)
    x = np.arange(nb, dtype=np.uint32) ^ key
    with np.errstate(over="ignore"):
        x ^= x >> np.uint32(16)
        x *= _M1
        x ^= x >> np.uint32(13)
        x *= _M2
        x ^= x >> np.uint32(16)
    sperm = np.argsort(x, kind="stable").astype(np.int32)
    inv = np.empty_like(sperm)
    inv[sperm] = np.arange(nb, dtype=np.int32)
    return sperm, inv


def _median_rows(ests: jnp.ndarray) -> jnp.ndarray:
    """Median over axis 0 of an [r, d] stack — min/max selection networks
    for the common small odd r (r=3: 3 ops; r=5: 7 ops), else jnp.median.

    jnp.median lowers to a full XLA sort: measured 4.9 ms net for [5, 6.5M]
    on v5e where the 5-element network costs 2.8 ms (r4 perf probe). The
    networks return exactly the middle element, bit-equal to jnp.median
    for odd r (pinned by tests)."""
    mn, mx = jnp.minimum, jnp.maximum
    r = ests.shape[0]
    if r == 1:
        return ests[0]
    if r == 3:
        a, b, c = ests[0], ests[1], ests[2]
        return mx(mn(a, b), mn(mx(a, b), c))
    if r == 5:
        a, b, c, d, e = ests[0], ests[1], ests[2], ests[3], ests[4]
        a, b = mn(a, b), mx(a, b)
        c, d = mn(c, d), mx(c, d)
        a, c = mn(a, c), mx(a, c)  # a: min of {a,b,c,d}
        b, d = mn(b, d), mx(b, d)  # d: max of {a,b,c,d}
        b, c = mn(b, c), mx(b, c)  # median(all) = median of {b, c, e}
        return mx(b, mn(c, e))
    return jnp.median(ests, axis=0)


def _scramble(spec: "CountSketch", v: jnp.ndarray) -> jnp.ndarray:
    """[d] -> [d_eff] scrambled (block-permuted) vector."""
    b = spec.sblock
    if not b:
        return v
    sperm, _ = _scramble_perms(spec.d_eff, b, spec.seed)
    vp = jnp.pad(v, (0, spec.d_eff - spec.d))
    return vp.reshape(-1, b)[jnp.asarray(sperm)].reshape(spec.d_eff)


def _unscramble(spec: "CountSketch", v_s: jnp.ndarray) -> jnp.ndarray:
    """[d_eff] scrambled -> [d] original order."""
    b = spec.sblock
    if not b:
        return v_s[: spec.d]
    _, inv = _scramble_perms(spec.d_eff, b, spec.seed)
    return v_s.reshape(-1, b)[jnp.asarray(inv)].reshape(spec.d_eff)[: spec.d]


def _to_layout(spec: "CountSketch", x_d: jnp.ndarray, row: int) -> jnp.ndarray:
    """[d] position-ordered -> [nc_row, m] chunk layout for this row.

    Riffle with factor f: original coordinate p lands at riffled index
    ``(p mod G) * f + p // G`` with ``G = L_row / f`` — realized as
    ``reshape(f, G).T``, a contiguous transpose. Chunks are then
    contiguous blocks of m. f=1 rows are plain contiguous chunking.
    """
    f, L = spec._factor(row), spec._L_row(row)
    xp = jnp.pad(x_d, (0, L - spec.d_eff))
    if f > 1:
        xp = xp.reshape(f, L // f).T.reshape(L)
    return xp.reshape(L // spec.chunk_m, spec.chunk_m)


def _from_layout(spec: "CountSketch", x_chunks: jnp.ndarray, row: int) -> jnp.ndarray:
    """[nc_row, m] chunk layout -> [d] position-ordered (inverse)."""
    f, L = spec._factor(row), spec._L_row(row)
    xp = x_chunks.reshape(L)
    if f > 1:
        xp = xp.reshape(L // f, f).T.reshape(L)
    return xp[: spec.d_eff]


def _ceil_mult(x: int, q: int) -> int:
    return -(-x // q) * q


def _overlap_add(spec: CountSketch, O: jnp.ndarray, row: int) -> jnp.ndarray:
    """[nc, V] per-chunk windows -> flat row via ``band`` shifted adds
    (chunk q's window covers positions [q*t, q*t + V))."""
    nc, u, t = spec._nc_row(row), spec.u_row(row), spec.s_row(row)
    if u == 1:
        return O.reshape(nc * t)
    Or = O.reshape(nc, u, t)
    # parallel form: u statically-shifted padded copies summed in one
    # reduction (the sequential .at[i:i+nc].add chain serialized u
    # dynamic-update-slices)
    stack = jnp.stack(
        [
            jnp.pad(Or[:, i, :], ((i, u - 1 - i), (0, 0)))
            for i in range(u)
        ]
    )
    return stack.sum(0).reshape((nc + u - 1) * t)


def _overlap_gather(spec: CountSketch, row_vec: jnp.ndarray, row: int) -> jnp.ndarray:
    """Inverse view: flat row -> [nc, V] per-chunk windows (static slices)."""
    nc, u, t = spec._nc_row(row), spec.u_row(row), spec.s_row(row)
    if u == 1:
        return row_vec[: nc * t].reshape(nc, t)
    acc = row_vec[: (nc + u - 1) * t].reshape(nc + u - 1, t)
    return jnp.stack([acc[i : i + nc] for i in range(u)], axis=1).reshape(
        nc, u * t
    )


def _sketch_one_row(spec: CountSketch, v_s: jnp.ndarray, row: int) -> jnp.ndarray:
    # v_s is already in scrambled space ([d_eff]); signs are scrambled-keyed
    sv = _to_layout(spec, v_s * spec._row_signs(row), row)
    # NB matmul precision: the default (fast bf16-pass) path measures
    # STABLE in the FetchSGD feedback loop once the banded layout is in
    # place (lab acc 0.340 at paper-scale settings, vs 0.305 with
    # Precision.HIGHEST at 3x the matmul cost) — the one-hot operand is
    # exact in bf16 and the ~2^-8 relative bucket noise is far below the
    # collision noise floor. The divergence postmortem (module docstring)
    # was a LAYOUT problem, not a precision problem.
    out = jnp.einsum(
        "cm,ms->cs",
        sv.astype(spec.dtype),
        spec._row_onehot(row),
        preferred_element_type=jnp.float32,
    )
    out = _overlap_add(spec, out, row)
    return jnp.pad(out, (0, spec.c_actual - out.shape[0]))


def _use_pallas(spec: CountSketch) -> bool:
    """Backend dispatch for the matmul-path ops (see the ``backend`` field
    note). Centralized so an unknown backend fails loudly at every entry."""
    b = spec.backend
    if b not in ("einsum", "pallas"):
        raise ValueError(
            f"CountSketch.backend must be 'einsum' or 'pallas', got {b!r}"
        )
    return b == "pallas"


def sketch_vec(spec: CountSketch, v: jnp.ndarray) -> jnp.ndarray:
    """Sketch a dense [d] vector into an [r, c_actual] table.

    Equivalent of ``CSVec.accumulateVec`` (csvec.py ~L120-160) applied to a
    fresh table. Linear: ``sketch_vec(a+b) == sketch_vec(a)+sketch_vec(b)``
    (the scramble and layouts are fixed permutations, the matmul is linear).
    ``spec.backend`` picks the kernel realization; the table is the same to
    fp32 rounding either way.
    """
    if _use_pallas(spec):
        from commefficient_tpu.ops.pallas import sketch_vec_pallas

        return sketch_vec_pallas(spec, v)
    v = _scramble(spec, v.astype(jnp.float32))  # ONE block-gather, all rows
    table = jnp.stack([_sketch_one_row(spec, v, r) for r in range(spec.r)])
    # rows accumulate in f32 (preferred_element_type above); only the
    # FINAL table downcasts to the storage dtype (a no-op for the f32
    # default — convert_element_type to the same dtype folds away)
    return table.astype(spec.table_dtype)


def sketch_add_vec(spec: CountSketch, table: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """``table += sketch(v)`` — the in-place accumulate of the reference,
    expressed functionally (csvec.py ``accumulateVec`` ~L120-160)."""
    return table + sketch_vec(spec, v)


def table_sqnorm_estimate(table: jnp.ndarray) -> jnp.ndarray:
    """AMS estimate of ``||v||^2`` from v's CountSketch table [r, c]: each
    row's squared norm is an unbiased estimate of ``||v||^2`` (signs are
    4-universal), and the median over rows tames collision outliers — the
    classic AMS/CountSketch F2 estimator. Free relative to an unsketch: no
    estimate pass, no [d] transient. Used by the telemetry diagnostics
    (sketch-mode norm scalars, the replicated AND FSDP rounds). The f32
    upcast matters for bf16-stored tables: a bf16 sum-of-squares would lose
    the estimate to accumulation rounding (a no-op for the f32 default)."""
    return jnp.median(jnp.sum(jnp.square(table.astype(jnp.float32)), axis=1))


def _estimate_one_row(spec: CountSketch, table_row: jnp.ndarray, row: int) -> jnp.ndarray:
    tab = _overlap_gather(spec, table_row, row)
    est = jnp.einsum(
        "cs,ms->cm",
        tab.astype(spec.dtype),
        spec._row_onehot(row),
        preferred_element_type=jnp.float32,
    )
    # scrambled-space estimate [d_eff]; estimate_all unscrambles after the
    # median so the block-gather happens once, not once per row
    return _from_layout(spec, est, row) * spec._row_signs(row)


def estimate_all(spec: CountSketch, table: jnp.ndarray) -> jnp.ndarray:
    """Median-of-rows estimates for ALL d coordinates.

    ``CSVec._findAllValues`` analog (csvec.py ~L190-260): per row, gather
    each coordinate's bucket value times sign (here: transposed matmul),
    then median across the r estimates (in scrambled space), then ONE
    block-gather back to original coordinate order.

    ``num_blocks > 1`` switches to the memory-bounded path: the exact
    gather estimate (``estimate_at``) over ``num_blocks`` coordinate
    slices, sequenced by ``lax.map`` so only one slice's ``[r, d/B]``
    transient is live at a time (vs the matmul path's full ``[r, d_eff]``
    stack). Same values (one-hot matmul sums exactly one term per
    coordinate, so the two paths agree to float rounding; bit-equal on
    CPU), lower peak memory, slower — the reference ``numBlocks`` trade.

    ``spec.backend`` picks the kernel realization of the full-d matmul
    path (einsum | pallas); the num_blocks gather path is backend-agnostic.
    """
    use_pallas = _use_pallas(spec)  # validate the backend string even on
    # the gather path below — every entry point fails loudly on a typo
    # named_scope marker (no ops added): the scope name survives into the
    # compiled HLO's op metadata, so tests can pin that a lowered program
    # contains NO full-d estimate — the sharded-decode acceptance
    # criterion (tests/test_sketch_decode.py's HLO pin)
    with jax.named_scope("estimate_all"):
        if spec.num_blocks > 1:
            B = spec.num_blocks
            blk = -(-spec.d // B)
            idx = jnp.arange(B * blk, dtype=jnp.uint32).reshape(B, blk)
            idx = jnp.minimum(idx, jnp.uint32(spec.d - 1))  # pad: repeat last
            est = jax.lax.map(lambda ix: estimate_at(spec, table, ix), idx)
            return est.reshape(B * blk)[: spec.d]
        if use_pallas:
            from commefficient_tpu.ops.pallas import estimate_all_pallas

            return estimate_all_pallas(spec, table)
        ests = jnp.stack(
            [_estimate_one_row(spec, table[r], r) for r in range(spec.r)]
        )
        return _unscramble(spec, _median_rows(ests))


def _scrambled_pos(spec: CountSketch, idx: jnp.ndarray) -> jnp.ndarray:
    """Original coordinate index -> its position in scrambled space."""
    b = spec.sblock
    if not b:
        return idx
    _, inv = _scramble_perms(spec.d_eff, b, spec.seed)
    inv = jnp.asarray(inv).astype(jnp.uint32)
    return inv[(idx // jnp.uint32(b)).astype(jnp.int32)] * jnp.uint32(b) + (
        idx % jnp.uint32(b)
    )


def _row_cols_signs(spec: CountSketch, idx: jnp.ndarray, row: int):
    """(column index, sign) of each ORIGINAL coordinate in ``idx`` for one
    row — the gather/scatter-side view of the same mapping ``sketch_vec``
    realizes with scramble + riffle + chunk layout + one-hot matmul."""
    idx = idx.astype(jnp.uint32)
    spos = _scrambled_pos(spec, idx)
    f, L = spec._factor(row), spec._L_row(row)
    G = jnp.uint32(L // f)
    # riffled index of scrambled position p: (p mod G) * f + p // G
    pos = (spos % G) * jnp.uint32(f) + spos // G
    chunk = (pos // jnp.uint32(spec.chunk_m)).astype(jnp.int32)
    off = pos % jnp.uint32(spec.chunk_m)
    s_r = spec.s_row(row)
    if spec.hash_family == "poly4":
        # slots gather from the [m] static table (host polynomial — m is
        # bounded at any scale); signs are evaluated ON THE FLY over
        # GF(2^31-1) in uint32 (_poly4_u32 — bit-identical to the host
        # uint64 path), so the gather path never materializes a [d_eff]
        # sign vector either and poly4 stays usable at GPT-2 scale.
        if spec.d_eff >= int(_MERSENNE_P):
            raise ValueError(
                f"poly4 scrambled-space length {spec.d_eff} >= p=2^31-1; "
                "the 4-universal family is only defined over GF(p) — use "
                "hash_family='fmix32' at this scale"
            )
        h = spec._offset_slots(row)[off.astype(jnp.int32)]
        bits = _poly4_u32(
            spos, tuple(int(c) for c in spec._poly4_coeffs(row, 1))
        ) & jnp.uint32(1)
        sign = 1.0 - 2.0 * bits.astype(jnp.float32)
        return chunk * s_r + h, sign
    h = (
        _mix32(off, spec._row_key(row)) % jnp.uint32(spec.V_row(row))
    ).astype(jnp.int32)
    # signs are keyed by the SCRAMBLED position (applied pre-layout in
    # _sketch_one_row), slots by the within-chunk offset
    bits = _mix32(spos, spec._row_key(row) ^ _GOLDEN) & jnp.uint32(1)
    sign = 1.0 - 2.0 * bits.astype(jnp.float32)
    return chunk * s_r + h, sign


def estimate_at(spec: CountSketch, table: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Median-of-rows point estimates for a subset of coordinates
    (``CSVec._findValues`` analog, csvec.py ~L190-230). Small-k gather path."""

    def one_row(row: int):
        cols, sign = _row_cols_signs(spec, idx, row)
        # explicit f32 read: bf16-stored tables estimate in f32 (no-op
        # for the f32 default)
        return table[row, cols].astype(jnp.float32) * sign

    ests = jnp.stack([one_row(r) for r in range(spec.r)])
    return _median_rows(ests)


def sketch_sparse(spec: CountSketch, idx: jnp.ndarray, vals: jnp.ndarray) -> jnp.ndarray:
    """Sketch a k-sparse vector given as (indices [k], values [k]).

    Same hash mapping as ``sketch_vec`` of the dense materialization (see
    ``_row_cols_signs``) via O(r·k) scatter-adds — bit-identical on CPU;
    on TPU the dense path's matmul carries ~2^-8 relative rounding (module
    docstring precision caveat). NB on
    TPU a dense ``sketch_vec`` matmul often beats this for k ≳ 10^4 —
    scatter is the slow path on this hardware; this exists for small-k and
    host-side uses. Coordinates may repeat; repeats accumulate.
    """
    vals = vals.astype(jnp.float32)

    def one_row(row: int):
        cols, sign = _row_cols_signs(spec, idx, row)
        return jnp.zeros((spec.c_actual,), jnp.float32).at[cols].add(vals * sign)

    return jnp.stack([one_row(r) for r in range(spec.r)])


def sketch_segment(spec: CountSketch, offset: int, vals: jnp.ndarray) -> jnp.ndarray:
    """Sketch the contiguous flat-[d] segment ``[offset, offset + n)``
    given its values (any shape; raveled) — the per-leaf building block of
    the sketch-fused backward. ``offset`` is STATIC (a python int: each
    param leaf's position in the ``ravel_pytree`` layout). Same hash
    mapping as ``sketch_sparse`` at ``idx = offset + arange(n)``, so by
    linearity the sum of every leaf's segment sketch IS the sketch of the
    full flat gradient — without the [d] concat ever existing."""
    flat = vals.reshape(-1).astype(jnp.float32)
    idx = jnp.uint32(int(offset)) + jnp.arange(flat.shape[0], dtype=jnp.uint32)
    return sketch_sparse(spec, idx, flat)


@_functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def sketch_grad_tap(spec: CountSketch, offset: int, leaf, table):
    """Identity on ``leaf`` whose TRANSPOSE sketches the leaf's cotangent.

    The sketch-fused backward (parallel/round.py make_sketch_grad_one):
    thread every param leaf through a tap that shares one dummy zeros
    ``table`` [r, c_actual] f32, then differentiate the loss w.r.t. that
    table — each tap's backward rule emits
    ``sketch_segment(spec, offset, dL/dleaf)`` as the table's cotangent,
    JAX's fan-in accumulation sums them, and the result is the sketch of
    the full flat gradient. The per-leaf cotangents are consumed where AD
    produces them; ``ravel_pytree``'s flat [D] concat (the transpose of
    ``unravel``) is never traced because the params vector itself is not
    differentiated. Forward is the identity on ``leaf`` (the zeros table
    contributes nothing), so the loss value is untouched."""
    del table
    return leaf


def _sketch_grad_tap_fwd(spec, offset, leaf, table):
    del table
    return leaf, None


def _sketch_grad_tap_bwd(spec, offset, _res, ct):
    # leaf cotangent passes through untouched (correct if a caller also
    # differentiates the params; unused -> DCE'd); the table cotangent is
    # this leaf's segment sketch
    return ct, sketch_segment(spec, offset, ct)


sketch_grad_tap.defvjp(_sketch_grad_tap_fwd, _sketch_grad_tap_bwd)


def unsketch_sparse(
    spec: CountSketch, table: jnp.ndarray, k: int, *, approx: bool = False
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Recover the top-k heavy hitters as (indices [k], values [k]).

    ``CSVec.unSketch`` analog (csvec.py ~L260-290): median estimates for all
    coordinates, then global top-k by magnitude. ``approx=True`` uses
    ``lax.approx_max_k`` (TPU-native, faster, ~0.95 recall) — callers opt in.
    """
    est = estimate_all(spec, table)
    if approx:
        _, hh_idx = jax.lax.approx_max_k(jnp.abs(est), k)
    else:
        _, hh_idx = jax.lax.top_k(jnp.abs(est), k)
    return hh_idx, est[hh_idx]


def unsketch(
    spec: CountSketch, table: jnp.ndarray, k: int, *, approx: bool = False
) -> jnp.ndarray:
    """``unsketch_sparse`` materialized as a dense [d] vector, k nonzeros."""
    hh_idx, vals = unsketch_sparse(spec, table, k, approx=approx)
    return jnp.zeros(spec.d, dtype=vals.dtype).at[hh_idx].set(vals)


def unsketch_dense(spec: CountSketch, table: jnp.ndarray, k: int) -> jnp.ndarray:
    """Top-k heavy hitters as a dense [d] vector via THRESHOLD selection —
    no sort, no scatter (both are slow on TPU; see ops.topk).

    Same contract as ``unsketch`` except selection is by a binary-searched
    magnitude threshold, so the nonzero count is ≤ k (ties at the threshold
    are dropped rather than arbitrarily broken — at most a handful of
    coordinates on float gradients).
    """
    from commefficient_tpu.ops.topk import topk_threshold_dense

    est = estimate_all(spec, table)
    return topk_threshold_dense(est, k)


def l2_estimate(spec: CountSketch, table: jnp.ndarray) -> jnp.ndarray:
    """Estimate of the L2 norm of the sketched vector: median of row norms
    (``CSVec.l2estimate``, csvec.py ~L290-310)."""
    return jnp.median(jnp.linalg.norm(table.astype(jnp.float32), axis=1))
