"""TPU-native Count Sketch.

Re-implements the semantics of the reference's ``csvec`` dependency
(``csvec/csvec.py``, ~350 LoC: ``CSVec.accumulateVec`` ~L120-160, ``__add__``
~L160-180, ``_findAllValues``/``_findHHK`` ~L190-260, ``unSketch`` ~L260-290,
``l2estimate`` ~L290-310) as pure JAX functions, designed TPU-first:

* **Stateless on-the-fly hashing.** The reference precomputes per-row
  bucket/sign tables with a 4-universal polynomial hash over the Mersenne
  prime 2^61-1 and caches ``[r, d]`` int64 tables on the accelerator
  (``csvec.py`` ~L30-110). On TPU that layout is hostile twice over: int64
  arithmetic needs x64 mode, and the hash cache costs ``r*d`` HBM reads per
  accumulate. We instead derive buckets and signs *inside the computation*
  from ``(seed, row, index)`` with a murmur3-style uint32 finalizer — zero
  bytes of hash state, identical determinism guarantees (server and every
  worker shard derive identical hashes from the shared seed), and the same
  pairwise-independence properties Count Sketch needs in practice.

* **Linearity is the contract.** ``sketch_vec(a) + sketch_vec(b) ==
  sketch_vec(a + b)`` exactly (up to float addition order), which is what lets
  the federated round aggregate worker sketches with a single ``lax.psum``
  instead of the reference's shared-memory gather.

* **``num_blocks`` reinterpreted.** In the reference, ``numBlocks`` chunks the
  vector so hash tables can be reused to save GPU memory (``csvec.py``
  ~L60-100). With stateless hashing there is no table to save, so here
  ``num_blocks`` bounds the *working-set* of the heavy-hitter estimate: the
  median-of-rows estimate over all ``d`` coordinates is computed blockwise
  with ``lax.map`` over ``num_blocks`` chunks, capping peak memory at
  ``r * ceil(d/num_blocks)`` floats (vital at d ~= 124M for GPT-2).

All functions are pure and jit/vmap/shard_map-friendly; nothing here touches
Python control flow on traced values.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

_M1 = np.uint32(0x85EBCA6B)
_M2 = np.uint32(0xC2B2AE35)
_GOLDEN = np.uint32(0x9E3779B9)


def _mix32(x: jnp.ndarray, key: jnp.ndarray) -> jnp.ndarray:
    """murmur3 fmix32 with a key fold — uint32 in, well-scrambled uint32 out."""
    x = (x ^ key).astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * _M1
    x = x ^ (x >> 13)
    x = x * _M2
    x = x ^ (x >> 16)
    return x


class CountSketch(NamedTuple):
    """Static spec of a Count Sketch table (the analog of a ``CSVec`` instance).

    The reference couples spec + table + device state in one class; here the
    spec is a hashable static NamedTuple (safe to close over under ``jit``)
    and the table is a plain ``[r, c]`` float32 array threaded functionally.
    """

    d: int  # length of the vectors being sketched
    c: int  # columns (buckets per row)
    r: int  # rows (independent hash repetitions; median taken across them)
    num_blocks: int = 1  # working-set chunking for full-d estimates
    seed: int = 42  # hash seed; equal seeds => equal hashes everywhere

    @property
    def table_shape(self) -> tuple[int, int]:
        return (self.r, self.c)

    def empty(self, dtype=jnp.float32) -> jnp.ndarray:
        """A zeroed sketch table (``CSVec.zero()`` analog, csvec.py ~L110)."""
        return jnp.zeros((self.r, self.c), dtype=dtype)

    def _row_keys(self) -> jnp.ndarray:
        """[r] uint32 per-row hash keys derived from the seed."""
        rows = jnp.arange(self.r, dtype=jnp.uint32)
        return _mix32(rows + _GOLDEN, jnp.uint32(self.seed))

    def buckets_signs(self, idx: jnp.ndarray, row: jnp.ndarray):
        """Hash coordinate indices for one row.

        Args:
          idx: [n] int32/uint32 coordinate indices in [0, d).
          row: scalar uint32 row key (an element of ``_row_keys()``).
        Returns:
          (buckets [n] int32 in [0, c), signs [n] float32 in {-1, +1}).
        """
        idx = idx.astype(jnp.uint32)
        h = _mix32(idx, row)
        buckets = (h % jnp.uint32(self.c)).astype(jnp.int32)
        # Sign is hashed from the raw index, not from h: a full 32-bit
        # collision in h must still yield decorrelated signs, else colliding
        # pairs bias the row estimate additively instead of zero-mean.
        s = _mix32(idx, row ^ _GOLDEN)
        signs = (1.0 - 2.0 * (s & jnp.uint32(1)).astype(jnp.float32))
        return buckets, signs


def sketch_vec(spec: CountSketch, v: jnp.ndarray) -> jnp.ndarray:
    """Sketch a dense [d] vector into an [r, c] table.

    Equivalent of ``CSVec.accumulateVec`` (csvec.py ~L120-160) applied to a
    fresh table. Linear: ``sketch_vec(a+b) == sketch_vec(a)+sketch_vec(b)``.
    Row-at-a-time ``lax.map`` keeps peak memory at O(d) rather than O(r*d).
    """
    v = v.astype(jnp.float32)
    idx = jnp.arange(spec.d, dtype=jnp.uint32)

    def one_row(row_key):
        buckets, signs = spec.buckets_signs(idx, row_key)
        return jax.ops.segment_sum(signs * v, buckets, num_segments=spec.c)

    return jax.lax.map(one_row, spec._row_keys())


def sketch_add_vec(spec: CountSketch, table: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """``table += sketch(v)`` — the in-place accumulate of the reference,
    expressed functionally (csvec.py ``accumulateVec`` ~L120-160)."""
    return table + sketch_vec(spec, v)


def estimate_at(spec: CountSketch, table: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Median-of-rows point estimates for a subset of coordinates.

    ``CSVec._findValues`` analog (csvec.py ~L190-230): for each index, gather
    each row's bucket value times sign, then take the median across the r
    estimates.
    """
    row_keys = spec._row_keys()

    def one_row(args):
        row_key, row_table = args
        buckets, signs = spec.buckets_signs(idx, row_key)
        return row_table[buckets] * signs

    ests = jax.lax.map(one_row, (row_keys, table))  # [r, n]
    return jnp.median(ests, axis=0)


def estimate_all(spec: CountSketch, table: jnp.ndarray) -> jnp.ndarray:
    """Median estimates for ALL d coordinates, computed blockwise.

    ``CSVec._findAllValues`` analog (csvec.py ~L190-260). ``spec.num_blocks``
    bounds peak memory: each block materializes only
    ``r * ceil(d/num_blocks)`` floats.
    """
    block = -(-spec.d // spec.num_blocks)  # ceil
    padded = block * spec.num_blocks
    starts = jnp.arange(spec.num_blocks, dtype=jnp.int32) * block

    def one_block(start):
        idx = start.astype(jnp.uint32) + jnp.arange(block, dtype=jnp.uint32)
        return estimate_at(spec, table, idx)

    ests = jax.lax.map(one_block, starts).reshape(padded)
    return ests[: spec.d]


def unsketch(spec: CountSketch, table: jnp.ndarray, k: int) -> jnp.ndarray:
    """Recover the top-k heavy hitters as a dense [d] vector with k nonzeros.

    ``CSVec.unSketch`` analog (csvec.py ~L260-290): median estimates for all
    coordinates, then global top-k by magnitude, then scatter back to dense.
    """
    est = estimate_all(spec, table)
    _, hh_idx = jax.lax.top_k(jnp.abs(est), k)
    out = jnp.zeros(spec.d, dtype=est.dtype)
    return out.at[hh_idx].set(est[hh_idx])


def l2_estimate(spec: CountSketch, table: jnp.ndarray) -> jnp.ndarray:
    """Estimate of the L2 norm of the sketched vector: median of row norms
    (``CSVec.l2estimate``, csvec.py ~L290-310)."""
    return jnp.median(jnp.linalg.norm(table.astype(jnp.float32), axis=1))
