"""TPU-native Count Sketch — blocked, matmul-based, zero random access.

Re-implements the semantics of the reference's ``csvec`` dependency
(``csvec/csvec.py``, ~350 LoC: ``CSVec.accumulateVec`` ~L120-160, ``__add__``
~L160-180, ``_findAllValues``/``_findHHK`` ~L190-260, ``unSketch`` ~L260-290,
``l2estimate`` ~L290-310) with a hash-family layout chosen FOR the TPU rather
than translated from CUDA.

Why not the classic layout: the reference scatters each coordinate to a
random bucket (``scatter_add``) and gathers random buckets back — on GPUs
those are atomic-add/gather at memory bandwidth, but the TPU is a
contiguous-vector machine with no fast random access (measured on v5e:
a 50k-element scatter into 6.5M costs ~24 ms — microseconds of matmul).

Blocked design (this module, v2):
  * Coordinates are split into contiguous CHUNKS of ``m``; each chunk owns a
    private block of ``s`` buckets, so the table has ``c = ceil(d/m) * s``
    columns. Within a chunk, the bucket of a coordinate is a murmur-style
    hash of its WITHIN-CHUNK OFFSET, shared across chunks — so one static
    ``[m, s]`` one-hot matrix realizes the whole row as a single
    ``[nc, m] x [m, s]`` MXU matmul. No scatter, no per-chunk one-hot
    materialization (v1 generated ``d*s`` one-hot entries on the VPU per
    row — 30-50x slower than the MXU matmul).
  * Per-row CYCLIC ROLL of the coordinate axis (a contiguous memory op)
    shifts chunk boundaries, and ALTERNATE ROWS use a STRIDED chunk layout
    (coordinate p -> chunk p mod nc, realized as a transpose — another
    contiguous op): a pair of coordinates that shares a chunk (hence a
    possibly-colliding bucket) in the contiguous rows is spread across
    chunks in the strided rows, so no pair collides in every row and the
    median rejects clustered-heavy-hitter crowding. Per-row SIGNS (hashed
    from the ORIGINAL coordinate) make residual collision terms zero-mean.
  * Estimation is the transposed matmul ``[nc, s] x [s, m]`` (again MXU),
    followed by median across rows — no gather.

Sharing the offset-keyed hash across chunks does not change the collision
statistics that matter: collisions only exist WITHIN a chunk (each chunk
owns its own bucket block), a pair in the same chunk collides with
probability 1/s per row exactly as in the classic sketch, and rows stay
independent (per-row hash keys + roll + stride). Variance matches the
classic sketch at equal table size: a coordinate's collision noise is
||v_chunk||^2/s ~= ||v||^2 * (m/d)/s = ||v||^2/c.

Linearity is the contract that makes federated aggregation exact:
``sketch(a) + sketch(b) == sketch(a + b)`` (bit-exact in float32 mode up to
float addition order), so ``lax.psum`` of worker tables IS the sketch of the
summed update.

``num_blocks`` from the reference API (hash-reuse chunking for GPU memory,
csvec.py ~L60-100) is accepted for config parity but unused: the blocked
layout is already tiled and no transient exceeds the table size.

All functions are pure and jit/vmap/shard_map-friendly.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

_M1 = np.uint32(0x85EBCA6B)
_M2 = np.uint32(0xC2B2AE35)
_GOLDEN = np.uint32(0x9E3779B9)


def _mix32(x: jnp.ndarray, key) -> jnp.ndarray:
    """murmur3 fmix32 with a key fold — uint32 in, well-scrambled uint32 out."""
    x = (x ^ key).astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * _M1
    x = x ^ (x >> 13)
    x = x * _M2
    x = x ^ (x >> 16)
    return x


class CountSketch(NamedTuple):
    """Static spec of a Count Sketch (the analog of a ``CSVec`` instance).

    The reference couples spec + table + device state in one class; here the
    spec is a hashable static NamedTuple (safe to close over under ``jit``)
    and the table is a plain ``[r, c]`` float array threaded functionally.

    ``c`` is a TARGET column count: the realized count is
    ``ceil(d/m) * s`` with ``s = round(c / ceil(d/m))`` clamped to a
    multiple of 8 — within a few percent of the request for large d.
    """

    d: int  # length of the vectors being sketched
    c: int  # requested columns (buckets) per row
    r: int  # rows (independent repetitions; median across them)
    num_blocks: int = 1  # reference-API parity; unused (see module docstring)
    seed: int = 42  # hash seed; equal seeds => equal hashes everywhere
    m: Any = None  # chunk size (coords per bucket block); None = adaptive
    dtype: Any = jnp.float32  # matmul dtype; bfloat16 halves time on MXU

    # -- derived static geometry ------------------------------------------
    @property
    def chunk_m(self) -> int:
        """Chunk size. Adaptive default: grow m (512..8192, powers of 2)
        until each chunk gets >= 32 buckets, so the per-chunk floor of 8
        can't inflate the realized table far beyond the request at large
        d/c ratios (GPT-2 scale: d=124M, c=1.25M needs m=4096)."""
        if self.m is not None:
            return min(self.m, _ceil_mult(self.d, 8))
        m = 512
        while m < 8192 and self.d / m > self.c / 32:
            m *= 2
        return min(m, _ceil_mult(self.d, 8))

    @property
    def nc(self) -> int:
        return -(-self.d // self.chunk_m)

    @property
    def s(self) -> int:
        raw = max(1, round(self.c / self.nc))
        return max(8, round(raw / 8) * 8)  # nearest multiple of 8

    @property
    def c_actual(self) -> int:
        return self.nc * self.s

    @property
    def d_padded(self) -> int:
        return self.nc * self.chunk_m

    @property
    def table_shape(self) -> tuple[int, int]:
        return (self.r, self.c_actual)

    def empty(self, dtype=jnp.float32) -> jnp.ndarray:
        """A zeroed sketch table (``CSVec.zero()`` analog, csvec.py ~L110)."""
        return jnp.zeros(self.table_shape, dtype=dtype)

    # -- per-row hash ingredients (all static-shape, derived from seed) ----
    def _row_key(self, row: int) -> np.uint32:
        x = (row ^ self.seed) & 0xFFFFFFFF
        for _ in range(2):
            x = ((x ^ (x >> 16)) * int(_M1)) & 0xFFFFFFFF
        return np.uint32(x ^ int(_GOLDEN))

    def _roll(self, row: int) -> int:
        """Per-row coordinate shift: staggers chunk boundaries across rows."""
        return (row * self.chunk_m) // max(self.r, 1) + row

    def _strided(self, row: int) -> bool:
        """Alternate rows lay chunks out strided (p -> chunk p mod nc)."""
        return row % 2 == 1 and self.nc > 1

    def _row_signs(self, row: int) -> jnp.ndarray:
        """[d_padded] ±1, hashed from the ORIGINAL coordinate index."""
        idx = jnp.arange(self.d_padded, dtype=jnp.uint32)
        bits = _mix32(idx, self._row_key(row) ^ _GOLDEN) & jnp.uint32(1)
        return 1.0 - 2.0 * bits.astype(jnp.float32)

    def _offset_slots(self, row: int) -> jnp.ndarray:
        """[m] int32 bucket per within-chunk offset (shared by all chunks)."""
        off = jnp.arange(self.chunk_m, dtype=jnp.uint32)
        return (_mix32(off, self._row_key(row)) % jnp.uint32(self.s)).astype(
            jnp.int32
        )

    def _row_onehot(self, row: int) -> jnp.ndarray:
        """[m, s] static one-hot of ``_offset_slots`` — the whole row's hash
        as one small matmul operand."""
        slots = self._offset_slots(row)
        return (slots[:, None] == jnp.arange(self.s, dtype=jnp.int32)).astype(
            self.dtype
        )


def _to_layout(spec: "CountSketch", x_flat: jnp.ndarray, row: int) -> jnp.ndarray:
    """[d_padded] position-ordered -> [nc, m] chunk layout for this row.

    Contiguous rows: position p -> (chunk p // m, offset p % m).
    Strided rows:    position p -> (chunk p % nc, offset p // nc).
    """
    if spec._strided(row):
        return x_flat.reshape(spec.chunk_m, spec.nc).T
    return x_flat.reshape(spec.nc, spec.chunk_m)


def _from_layout(spec: "CountSketch", x_chunks: jnp.ndarray, row: int) -> jnp.ndarray:
    """[nc, m] chunk layout -> [d_padded] position-ordered (inverse)."""
    if spec._strided(row):
        return x_chunks.T.reshape(spec.d_padded)
    return x_chunks.reshape(spec.d_padded)


def _ceil_mult(x: int, q: int) -> int:
    return -(-x // q) * q


def _sketch_one_row(spec: CountSketch, v_padded: jnp.ndarray, row: int) -> jnp.ndarray:
    sv = v_padded * spec._row_signs(row)
    sv = _to_layout(spec, jnp.roll(sv, spec._roll(row)), row)
    out = jnp.einsum(
        "cm,ms->cs",
        sv.astype(spec.dtype),
        spec._row_onehot(row),
        preferred_element_type=jnp.float32,
    )
    return out.reshape(spec.c_actual)


def sketch_vec(spec: CountSketch, v: jnp.ndarray) -> jnp.ndarray:
    """Sketch a dense [d] vector into an [r, c_actual] table.

    Equivalent of ``CSVec.accumulateVec`` (csvec.py ~L120-160) applied to a
    fresh table. Linear: ``sketch_vec(a+b) == sketch_vec(a)+sketch_vec(b)``.
    """
    v = v.astype(jnp.float32)
    vp = jnp.pad(v, (0, spec.d_padded - spec.d))
    return jnp.stack([_sketch_one_row(spec, vp, r) for r in range(spec.r)])


def sketch_add_vec(spec: CountSketch, table: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """``table += sketch(v)`` — the in-place accumulate of the reference,
    expressed functionally (csvec.py ``accumulateVec`` ~L120-160)."""
    return table + sketch_vec(spec, v)


def _estimate_one_row(spec: CountSketch, table_row: jnp.ndarray, row: int) -> jnp.ndarray:
    tab = table_row.reshape(spec.nc, spec.s)
    est = jnp.einsum(
        "cs,ms->cm",
        tab.astype(spec.dtype),
        spec._row_onehot(row),
        preferred_element_type=jnp.float32,
    )
    est = jnp.roll(_from_layout(spec, est, row), -spec._roll(row))
    return est * spec._row_signs(row)


def estimate_all(spec: CountSketch, table: jnp.ndarray) -> jnp.ndarray:
    """Median-of-rows estimates for ALL d coordinates.

    ``CSVec._findAllValues`` analog (csvec.py ~L190-260): per row, gather
    each coordinate's bucket value times sign (here: transposed matmul),
    then median across the r estimates.
    """
    ests = jnp.stack(
        [_estimate_one_row(spec, table[r], r) for r in range(spec.r)]
    )
    return jnp.median(ests, axis=0)[: spec.d]


def _row_cols_signs(spec: CountSketch, idx: jnp.ndarray, row: int):
    """(column index, sign) of each ORIGINAL coordinate in ``idx`` for one
    row — the gather/scatter-side view of the same mapping
    ``_sketch_one_row`` realizes with roll + layout + one-hot matmul."""
    idx = idx.astype(jnp.uint32)
    pos = (idx + jnp.uint32(spec._roll(row) % spec.d_padded)) % jnp.uint32(
        spec.d_padded
    )
    if spec._strided(row):
        chunk = (pos % jnp.uint32(spec.nc)).astype(jnp.int32)
        off = pos // jnp.uint32(spec.nc)
    else:
        chunk = (pos // jnp.uint32(spec.chunk_m)).astype(jnp.int32)
        off = pos % jnp.uint32(spec.chunk_m)
    h = (_mix32(off, spec._row_key(row)) % jnp.uint32(spec.s)).astype(jnp.int32)
    # signs are keyed by the ORIGINAL coordinate (applied pre-roll in
    # _sketch_one_row), slots by the within-chunk offset
    bits = _mix32(idx, spec._row_key(row) ^ _GOLDEN) & jnp.uint32(1)
    sign = 1.0 - 2.0 * bits.astype(jnp.float32)
    return chunk * spec.s + h, sign


def estimate_at(spec: CountSketch, table: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Median-of-rows point estimates for a subset of coordinates
    (``CSVec._findValues`` analog, csvec.py ~L190-230). Small-k gather path."""

    def one_row(row: int):
        cols, sign = _row_cols_signs(spec, idx, row)
        return table[row, cols] * sign

    ests = jnp.stack([one_row(r) for r in range(spec.r)])
    return jnp.median(ests, axis=0)


def sketch_sparse(spec: CountSketch, idx: jnp.ndarray, vals: jnp.ndarray) -> jnp.ndarray:
    """Sketch a k-sparse vector given as (indices [k], values [k]).

    Identical result to ``sketch_vec`` of the dense materialization (same
    hash mapping, see ``_row_cols_signs``) via O(r·k) scatter-adds. NB on
    TPU a dense ``sketch_vec`` matmul often beats this for k ≳ 10^4 —
    scatter is the slow path on this hardware; this exists for small-k and
    host-side uses. Coordinates may repeat; repeats accumulate.
    """
    vals = vals.astype(jnp.float32)

    def one_row(row: int):
        cols, sign = _row_cols_signs(spec, idx, row)
        return jnp.zeros((spec.c_actual,), jnp.float32).at[cols].add(vals * sign)

    return jnp.stack([one_row(r) for r in range(spec.r)])


def unsketch_sparse(
    spec: CountSketch, table: jnp.ndarray, k: int, *, approx: bool = False
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Recover the top-k heavy hitters as (indices [k], values [k]).

    ``CSVec.unSketch`` analog (csvec.py ~L260-290): median estimates for all
    coordinates, then global top-k by magnitude. ``approx=True`` uses
    ``lax.approx_max_k`` (TPU-native, faster, ~0.95 recall) — callers opt in.
    """
    est = estimate_all(spec, table)
    if approx:
        _, hh_idx = jax.lax.approx_max_k(jnp.abs(est), k)
    else:
        _, hh_idx = jax.lax.top_k(jnp.abs(est), k)
    return hh_idx, est[hh_idx]


def unsketch(
    spec: CountSketch, table: jnp.ndarray, k: int, *, approx: bool = False
) -> jnp.ndarray:
    """``unsketch_sparse`` materialized as a dense [d] vector, k nonzeros."""
    hh_idx, vals = unsketch_sparse(spec, table, k, approx=approx)
    return jnp.zeros(spec.d, dtype=vals.dtype).at[hh_idx].set(vals)


def unsketch_dense(spec: CountSketch, table: jnp.ndarray, k: int) -> jnp.ndarray:
    """Top-k heavy hitters as a dense [d] vector via THRESHOLD selection —
    no sort, no scatter (both are slow on TPU; see ops.topk).

    Same contract as ``unsketch`` except selection is by a binary-searched
    magnitude threshold, so the nonzero count is ≤ k (ties at the threshold
    are dropped rather than arbitrarily broken — at most a handful of
    coordinates on float gradients).
    """
    from commefficient_tpu.ops.topk import topk_threshold_dense

    est = estimate_all(spec, table)
    return topk_threshold_dense(est, k)


def l2_estimate(spec: CountSketch, table: jnp.ndarray) -> jnp.ndarray:
    """Estimate of the L2 norm of the sketched vector: median of row norms
    (``CSVec.l2estimate``, csvec.py ~L290-310)."""
    return jnp.median(jnp.linalg.norm(table.astype(jnp.float32), axis=1))
