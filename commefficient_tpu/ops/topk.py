"""Top-k sparsification primitives.

The reference sparsifies with ``torch.topk`` over the flat gradient/error
vector (``fed_worker.py`` ~L200-240 for local_topk, ``fed_aggregator.py``
``_server_helper_true_topk`` ~L440-480 for server-side top-k). Here the same
semantics are ``jax.lax.top_k`` over the flat [d] vector, with an optional
``jax.lax.approx_max_k`` fast path for very large d (TPU-native, documented
recall guarantees) that callers must opt into.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def topk_sparsify(v: jnp.ndarray, k: int, *, approx: bool = False):
    """Return (values [k], indices [k]) of the k largest-|.| entries of flat v."""
    mag = jnp.abs(v)
    if approx:
        _, idx = jax.lax.approx_max_k(mag, k)
    else:
        _, idx = jax.lax.top_k(mag, k)
    return v[idx], idx


def topk_dense(v: jnp.ndarray, k: int, *, approx: bool = False) -> jnp.ndarray:
    """Dense [d] vector keeping only the top-k entries of v by magnitude."""
    vals, idx = topk_sparsify(v, k, approx=approx)
    return jnp.zeros_like(v).at[idx].set(vals)


def mask_out_indices(v: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Zero the given coordinates — the error-feedback "forget what was sent"
    step (``Ve[hh]=0`` in fed_aggregator.py ~L440-480)."""
    return v.at[idx].set(0.0)
