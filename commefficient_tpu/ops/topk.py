"""Top-k sparsification primitives.

The reference sparsifies with ``torch.topk`` over the flat gradient/error
vector (``fed_worker.py`` ~L200-240 for local_topk, ``fed_aggregator.py``
``_server_helper_true_topk`` ~L440-480 for server-side top-k). Here the same
semantics are ``jax.lax.top_k`` over the flat [d] vector, with an optional
``jax.lax.approx_max_k`` fast path for very large d (TPU-native, documented
recall guarantees) that callers must opt into.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def topk_sparsify(v: jnp.ndarray, k: int, *, approx: bool = False):
    """Return (values [k], indices [k]) of the k largest-|.| entries of flat v."""
    mag = jnp.abs(v)
    if approx:
        _, idx = jax.lax.approx_max_k(mag, k)
    else:
        _, idx = jax.lax.top_k(mag, k)
    return v[idx], idx


def topk_dense(v: jnp.ndarray, k: int, *, approx: bool = False) -> jnp.ndarray:
    """Dense [d] vector keeping only the top-k entries of v by magnitude."""
    vals, idx = topk_sparsify(v, k, approx=approx)
    return jnp.zeros_like(v).at[idx].set(vals)


def topk_threshold_dense(v: jnp.ndarray, k: int, iters: int = 32) -> jnp.ndarray:
    """Dense top-≤k by magnitude via binary-searched threshold — the TPU
    fast path: no sort (lax.top_k is ~40 ms at d=6.5M on v5e) and no
    scatter (~24 ms for 50k updates), just ``iters`` vectorized passes over
    |v| (~33 µs each at d=6.5M).

    Selects ``|v| >= t`` for the smallest tested ``t`` whose selection count
    is ≤ k, so the result has AT MOST k nonzeros; exact ties at the
    threshold are dropped rather than arbitrarily broken. MEASURED
    (scripts/topk_tie_loss.py, r3): on real float32 ResNet-9 round
    gradients at d=6.5M, k=50k — fresh and partially trained, both
    synthetic variants — the dropped count is exactly 0 and the l1 mass
    gap vs ``lax.top_k`` is 0.0; float32 gradient magnitudes essentially
    never tie within the 2^-32-relative bisection resolution. (Re-measure
    with that script before top-k'ing low-precision vectors, where ties
    are plausible.)
    """
    mag = jnp.abs(v)
    hi0 = jnp.max(mag)

    def body(_, bounds):
        lo, hi = bounds
        mid = 0.5 * (lo + hi)
        too_many = jnp.sum(mag >= mid) > k
        return jnp.where(too_many, mid, lo), jnp.where(too_many, hi, mid)

    # lo derives from hi0 (not a literal) so it inherits v's full vma type —
    # under shard_map a literal init would be axis-invariant while the body
    # output varies, a carry type mismatch (seen in local_topk workers)
    lo, hi = jax.lax.fori_loop(0, iters, body, (hi0 * 0.0, hi0))
    # hi is the smallest tested threshold with count <= k; (mag > 0) guards
    # the all-zero vector (hi stays 0 there and >= would select everything).
    # Degenerate case: >k coordinates tie at the max, so NO magnitude
    # threshold selects <=k — honor the at-most-k contract by dropping the
    # tied set entirely (error feedback retains it for later rounds).
    hi = jnp.where(jnp.sum(mag >= hi) > k, jnp.inf, hi)
    return v * ((mag >= hi) & (mag > 0))


def topk_threshold_sharded(v_local: jnp.ndarray, k: int, axis_name: str,
                           iters: int = 32) -> jnp.ndarray:
    """``topk_threshold_dense`` over a vector SHARDED along ``axis_name`` —
    each device holds a [d/W] slice and returns its slice of the global
    top-<=k selection. The bisection is identical; only the max and the
    selection counts become collectives (one scalar pmax + one scalar psum
    per iteration — nothing vector-sized crosses the ICI). Used by the
    FSDP round (parallel/fsdp.py) to extract a globally-top-k update from
    the sharded error vector without ever materializing [d] anywhere.
    """
    mag = jnp.abs(v_local)
    hi0 = jax.lax.pmax(jnp.max(mag), axis_name)

    def body(_, bounds):
        lo, hi = bounds
        mid = 0.5 * (lo + hi)
        count = jax.lax.psum(jnp.sum(mag >= mid), axis_name)
        too_many = count > k
        return jnp.where(too_many, mid, lo), jnp.where(too_many, hi, mid)

    lo, hi = jax.lax.fori_loop(0, iters, body, (hi0 * 0.0, hi0))
    # same degenerate-tie contract as the dense kernel (see its docstring)
    hi = jnp.where(
        jax.lax.psum(jnp.sum(mag >= hi), axis_name) > k, jnp.inf, hi
    )
    return v_local * ((mag >= hi) & (mag > 0))


def compact_nonzero(v: jnp.ndarray, k: int):
    """Compact a ≤k-sparse dense vector into fixed-size ``(idx [kb], val
    [kb])`` buffers (``kb = min(k, len(v))``), positions ascending, padded
    with ``(0, 0.0)`` — the TPU-friendly compaction the sharded sketch
    decode and the sparse telemetry paths are built on.

    No sort and no len(v)-sized scatter (both are the TPU slow paths —
    ``lax.top_k`` measures ~40 ms at d=6.5M, a 50k scatter ~24 ms): one
    ``cumsum`` pass over the mask gives each selected element its output
    slot, and ``searchsorted`` over that monotone prefix-count inverts the
    mapping with kb vectorized binary searches (gathers, not scatters).
    Consumers rely on the padding contract: padded entries carry val==0.0
    so a downstream ``.at[idx].add(val)`` / ``sketch_sparse`` treats them
    as no-ops, and masks derived from ``val != 0`` drop them from norms.
    A vector with MORE than k nonzeros keeps the first kb by position
    (callers in this codebase always pass the output of a top-≤k
    selection, which cannot exceed k).
    """
    n = v.shape[0]
    # k sizes the fixed output buffers, so it CANNOT be a tracer — a
    # traced k would already fail shape inference on the arange below
    # lint: allow[traced-purity] k is a static Python int by contract
    kb = min(int(k), n)
    csum = jnp.cumsum((v != 0).astype(jnp.int32))
    total = csum[-1]
    # slot j (1-indexed) lives at the first position whose prefix count
    # reaches j; past-the-end probes return n and are masked below
    idx = jnp.searchsorted(
        csum, jnp.arange(1, kb + 1, dtype=jnp.int32), side="left"
    )
    idx = jnp.minimum(idx, n - 1).astype(jnp.int32)
    valid = jnp.arange(kb, dtype=jnp.int32) < total
    return jnp.where(valid, idx, 0), jnp.where(valid, v[idx], 0.0)


def mask_out_indices(v: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Zero the given coordinates — the error-feedback "forget what was sent"
    step (``Ve[hh]=0`` in fed_aggregator.py ~L440-480)."""
    return v.at[idx].set(0.0)
