"""Sparse allreduce over a mesh axis — O(W·k) pair exchange, not O(D).

FetchSGD's whole premise is that the transmitted object is small
(arXiv:2007.07682), yet a dense ``jax.lax.psum`` over the model dimension
moves all D slots regardless of sparsity.  This module aggregates
≤k-sparse vectors by exchanging fixed-size ``(idx, val)`` pair buffers
instead, in the style of Near-Optimal Sparse Allreduce (arXiv:2201.07598):
compact the nonzeros (``ops.topk.compact_nonzero``), exchange only the
pairs, rebuild the sum by scatter-add.  All functions run INSIDE
``shard_map`` over the named axis.

Two exchange schedules:

* ``sparse_allreduce`` — one ``all_gather`` of every shard's pair buffer,
  then a local scatter-add.  The output is REPLICATED (axis-invariant),
  which is what ``shard_map`` ``out_specs=P()`` demands: on
  varying-manual-axes JAX only psum/all_gather outputs are invariant, so
  round paths that keep a replicated server MUST consume this form (a
  ``ppermute`` output is varying and cannot leave the shard_map as
  ``P()``).  Per-chip receive volume: W·k pairs — the O(W·k) bound the
  XLA collective audit enforces.

* ``sparse_allreduce_sharded`` — balanced index-range partitioning +
  recursive-halving ``ppermute`` (the recursive-doubling dual): the index
  space [0, Dp) halves each step; each chip forwards the pair buffer for
  the half it does NOT keep to its hypercube partner and scatter-adds the
  buffer it receives.  After log2(W) steps chip i holds exactly its
  balanced range [i·S, (i+1)·S) of the global sparse sum (S = Dp/W).
  Per-step buffer capacities double (k, 2k, 4k, ...) so total volume is
  (W-1)·k pairs per chip.  The output is VARYING
  (``out_specs=P(WORKERS)``) — for consumers whose server state is itself
  sharded over the axis (true_topk's sparse server update).

Both forms equal the dense psum up to f32 summation order.  Pair buffers
are fixed-size with ``(0, 0.0)`` padding, so scatter-adding padding is a
no-op and every shape is static (zero retraces).

Collective/compute overlap (``overlap_collectives='layerwise'``): the
segmented twins here split ONE collective into independent per-segment
collectives so XLA's latency-hiding scheduler may run them concurrently
with surrounding compute (or each other).  Segmentation never touches
arithmetic: ``all_gather_pairs(segments=S)`` is pure data movement (the
ordered concatenation of segment gathers IS the monolithic gather,
bit-equal), and ``psum_segments`` relies on an all-reduce being
ELEMENTWISE — each element's cross-worker sum happens once, in ring
order, whichever collective op carries it, so per-segment psums are
bit-equal to one psum of the concatenated segments (no reassociation
within a segment).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from commefficient_tpu.ops.topk import compact_nonzero

Array = jax.Array

# Default segment count for the layerwise-overlap chunked exchanges: 4
# in-flight collectives is enough for the latency-hiding scheduler to
# pipeline without shrinking any single message below the bandwidth-bound
# regime at the W*k pair sizes the sparse modes move.
OVERLAP_SEGMENTS = 4


def _segment_bounds(n: int, segments: int):
    """Static [start, stop) bounds splitting [0, n) into up to
    ``segments`` contiguous near-equal chunks (every chunk non-empty)."""
    s = max(1, min(int(segments), int(n)))
    step = -(-n // s)
    return [(a, min(a + step, n)) for a in range(0, n, step)]


def compact_pairs(v: Array, capacity: int) -> Tuple[Array, Array]:
    """``(idx, val)`` pair buffer of the first ``capacity`` nonzeros of
    dense [n] ``v`` — the single spelling of the exchange contract
    (i32 indices, ``(0, 0.0)`` padding, drop-beyond-capacity semantics
    documented on ``ops.topk.compact_nonzero``)."""
    return compact_nonzero(v, capacity)


def all_gather_pairs(idx: Array, val: Array, axis_name: str,
                     segments=None) -> Tuple[Array, Array]:
    """Concatenate every shard's [kb] pair buffer into replicated
    [N·kb] buffers (N = axis size).  Invariant output — legal to return
    from ``shard_map`` under ``out_specs=P()``.

    ``segments=S`` (layerwise overlap) splits the [kb] payload into up
    to S contiguous chunks, each exchanged by its own ``all_gather``;
    concatenating the [N, kb_s] gathers along the pair axis rebuilds the
    exact monolithic [N, kb] layout, so the flattened output — and
    everything scatter-added from it — is BIT-equal to ``segments=None``
    (pure data movement, no arithmetic).  ``None`` (default) traces the
    single-gather program byte-identically to pre-overlap builds."""
    if segments is None or int(segments) <= 1 or idx.shape[0] <= 1:
        g_idx = jax.lax.all_gather(idx, axis_name).reshape(-1)
        g_val = jax.lax.all_gather(val, axis_name).reshape(-1)
        return g_idx, g_val
    bounds = _segment_bounds(idx.shape[0], segments)
    g_idx = jnp.concatenate(
        [jax.lax.all_gather(idx[a:b], axis_name) for a, b in bounds], axis=1
    ).reshape(-1)
    g_val = jnp.concatenate(
        [jax.lax.all_gather(val[a:b], axis_name) for a, b in bounds], axis=1
    ).reshape(-1)
    return g_idx, g_val


def psum_segments(segments, axis_name):
    """Sum each segment array across ``axis_name`` with its OWN psum —
    independent collectives the latency-hiding scheduler may issue as
    soon as each segment's producer finishes (the layerwise-overlap form
    of one monolithic psum over the concatenated segments).

    An all-reduce is elementwise: every element's cross-worker sum is
    performed once, in the axis reduction order, regardless of which
    collective op carries it — so this is BIT-equal, element for
    element, to ``psum(concat(segments))`` split back apart
    (``tests/test_overlap_collectives.py`` pins it on a real mesh).
    Segments may differ in shape; dtypes follow each input."""
    return tuple(jax.lax.psum(s, axis_name) for s in segments)


def psum_segments_fused(segments, axis_name):
    """The monolithic twin of ``psum_segments``: ONE psum of the
    flattened-and-concatenated segments, split back to the input shapes.
    Exists as the bit-equality reference for the overlap pin (and as the
    spelling of the claim: segmentation changes only which collective
    carries an element, never its reduction).  All segments must share a
    dtype (they do — per-leaf-group sketch tables)."""
    flat = jnp.concatenate([s.reshape(-1) for s in segments])
    summed = jax.lax.psum(flat, axis_name)
    out, off = [], 0
    for s in segments:
        n = s.size
        out.append(summed[off:off + n].reshape(s.shape))
        off += n
    return tuple(out)


def scatter_add_pairs(dim: int, idx: Array, val: Array) -> Array:
    """Dense [dim] vector holding the scatter-add of the pairs.
    Duplicate indices accumulate; the ``(0, 0.0)`` padding pairs add
    nothing."""
    # lint: allow[traced-purity] dim is a static Python int by contract
    n = int(dim)
    return jnp.zeros((n,), val.dtype).at[idx].add(val)


def sparse_allreduce(v: Array, capacity: int, axis_name: str,
                     segments=None) -> Array:
    """Allreduce a ≤capacity-sparse dense [d] vector across ``axis_name``
    by exchanging only (idx, val) pairs: compact → all_gather → local
    scatter-add.  Returns the replicated dense [d] sum (invariant), equal
    to ``psum(v, axis_name)`` up to f32 summation order whenever each
    shard's ``v`` has at most ``capacity`` nonzeros.  ``segments``
    chunks the gather (layerwise overlap) — the gathered pairs, and
    therefore the single scatter-add consuming them, are bit-equal to
    the monolithic exchange (see ``all_gather_pairs``)."""
    idx, val = compact_pairs(v, capacity)
    g_idx, g_val = all_gather_pairs(idx, val, axis_name, segments=segments)
    return scatter_add_pairs(v.shape[0], g_idx, g_val)


def sparse_allreduce_sharded(v: Array, k: int, axis_name: str, *,
                             axis_size: int, axis_sizes=None) -> Array:
    """Reduce-scatter a ≤k-sparse dense [d] vector across ``axis_name``
    via recursive-halving ``ppermute`` pair exchange.

    Chip i returns its balanced index range [i·S, (i+1)·S) of the global
    sparse sum, S = ceil(d / axis_size) (tail padded with zeros).  Equal
    to slicing ``psum(v)`` up to f32 summation order.  The output is
    varying over the axis — return it from ``shard_map`` with
    ``out_specs=P(axis)``, never ``P()``.

    ``axis_size`` must be the DECLARED mesh axis size (a power of two for
    the hypercube schedule); the permutation tables are derived from it,
    never hardcoded.

    Multi-host meshes (multihost/): pass the ``(HOSTS, WORKERS)`` tuple
    as ``axis_name`` plus ``axis_sizes=(H, W_local)`` and the schedule
    becomes TWO-LEVEL — the intra-host hypercube bits run first (cheap
    ICI hops while buffer capacities are smallest), then the cross-host
    bits (DCN hops carry the already-halved index ranges).  Total hop
    count stays log2(axis_size); the returned slice for flat chip
    ``h·W_local + w`` is identical to the single-axis schedule's (both
    equal slicing the psum, up to f32 summation order).
    """
    if isinstance(axis_name, (tuple, list)):
        return _sparse_allreduce_sharded_two_level(
            v, k, tuple(axis_name),
            axis_size=axis_size, axis_sizes=axis_sizes,
        )
    # lint: allow[traced-purity] axis_size is the static mesh axis size
    n_dev = int(axis_size)
    if n_dev <= 0 or (n_dev & (n_dev - 1)) != 0:
        raise ValueError(
            f"sparse_allreduce_sharded needs a power-of-two axis size for "
            f"the recursive-halving schedule, got {n_dev}"
        )
    d = v.shape[0]
    shard = -(-d // n_dev)
    dp = shard * n_dev
    # lint: allow[traced-purity] k is a static Python int by contract
    cap = min(int(k), dp)
    acc = jnp.pad(v, (0, dp - d))
    me = jax.lax.axis_index(axis_name)
    coords = jnp.arange(dp, dtype=jnp.int32)
    start = jnp.zeros((), jnp.int32)  # my active range: [start, start+length)
    length = dp
    bit = n_dev >> 1
    while bit:  # static unroll: log2(axis_size) exchange steps
        half = length // 2
        # partner tables from the declared axis size — never literal ints
        perm = [(i, i ^ bit) for i in range(n_dev)]
        upper = (me & bit) != 0  # this step I keep the upper half
        keep_start = start + jnp.where(upper, half, 0)
        send_start = start + jnp.where(upper, 0, half)
        send = (coords >= send_start) & (coords < send_start + half)
        idx, val = compact_nonzero(jnp.where(send, acc, 0.0), cap)
        r_idx = jax.lax.ppermute(idx, axis_name, perm)
        r_val = jax.lax.ppermute(val, axis_name, perm)
        # the sent half now belongs to the partner; fold in what arrived
        acc = jnp.where(send, 0.0, acc).at[r_idx].add(r_val)
        start, length = keep_start, half
        cap = min(cap * 2, dp)  # accumulated sparsity doubles per step
        bit >>= 1
    return jax.lax.dynamic_slice(acc, (start,), (shard,))


def _sparse_allreduce_sharded_two_level(v: Array, k: int, axis_name, *,
                                        axis_size: int, axis_sizes) -> Array:
    """The two-level hop schedule behind ``sparse_allreduce_sharded`` on a
    ``(hosts, workers)`` axis tuple — intra-host hypercube bits first,
    then cross-host.

    The single-axis schedule tracks one contiguous kept range, which
    forces high-bit-first ordering; here the kept set is a boolean mask
    over coordinate blocks instead, which admits ANY bit order while
    preserving the identity chip↔range mapping consumers rely on (chip
    with flat index m ends holding block m — the slice ``axis_index``
    locates).  At the step for flat bit b, a chip sends exactly the kept
    coords whose owning block differs from its own index at b, to the
    partner differing at that one bit: ``ppermute`` over the WORKERS
    axis for intra-host bits (b < W_local), over the HOSTS axis for
    cross-host bits (b = hb·W_local).  After all log2(axis_size) steps
    the kept set is precisely the chip's own block.
    """
    if axis_sizes is None or len(axis_name) != 2 or len(axis_sizes) != 2:
        raise ValueError(
            "two-level sparse_allreduce_sharded needs a 2-axis tuple "
            f"axis_name with matching axis_sizes=(hosts, workers); got "
            f"axis_name={axis_name!r}, axis_sizes={axis_sizes!r}"
        )
    # lint: allow[traced-purity] axis sizes are static mesh axis sizes
    n_hi, n_lo = (int(s) for s in axis_sizes)
    for n in (n_hi, n_lo):
        if n <= 0 or (n & (n - 1)) != 0:
            raise ValueError(
                f"two-level sparse_allreduce_sharded needs power-of-two "
                f"axis sizes for the hypercube schedule, got {axis_sizes}"
            )
    n_dev = n_hi * n_lo
    if int(axis_size) != n_dev:
        raise ValueError(
            f"axis_size={axis_size} != product of axis_sizes {axis_sizes}"
        )
    d = v.shape[0]
    shard = -(-d // n_dev)
    dp = shard * n_dev
    # lint: allow[traced-purity] k is a static Python int by contract
    cap = min(int(k), dp)
    acc = jnp.pad(v, (0, dp - d))
    # flat chip index over the tuple: host-major, identical to the
    # single-axis index of the same device order (mesh.make_mesh keeps
    # the device order unchanged between the 3- and 4-axis forms)
    me = jax.lax.axis_index(axis_name)
    blocks = jnp.arange(dp, dtype=jnp.int32) // shard  # owning block per coord
    kept = jnp.ones((dp,), bool)
    # static hop schedule: intra-host (low) flat bits first, then
    # cross-host (high) — log2(n_lo) + log2(n_hi) == log2(n_dev) steps.
    # Partner tables come from the declared axis sizes, never literals.
    steps = []
    b = 1
    while b < n_lo:
        steps.append((axis_name[1], [(i, i ^ b) for i in range(n_lo)], b))
        b <<= 1
    hb = 1
    while hb < n_hi:
        steps.append(
            (axis_name[0], [(i, i ^ hb) for i in range(n_hi)], hb * n_lo)
        )
        hb <<= 1
    for hop_axis, perm, bit in steps:
        # send: kept coords whose owner differs from me at this bit —
        # exactly the partner's half of my kept set
        diff = ((blocks ^ me) & bit) != 0
        send = kept & diff
        idx, val = compact_nonzero(jnp.where(send, acc, 0.0), cap)
        r_idx = jax.lax.ppermute(idx, hop_axis, perm)
        r_val = jax.lax.ppermute(val, hop_axis, perm)
        # the sent coords now belong to the partner; fold in what arrived
        acc = jnp.where(send, 0.0, acc).at[r_idx].add(r_val)
        kept = kept & ~diff
        cap = min(cap * 2, dp)  # accumulated sparsity doubles per step
    return jax.lax.dynamic_slice(acc, (me * shard,), (shard,))
