"""Sparse collective primitives — (idx, val) pair exchange over the mesh.

See ``sparse_allreduce`` for the design notes (gather form vs the
recursive-halving ``ppermute`` form, and which ``shard_map`` out_specs
each is legal under), and the segmented twins (``psum_segments``,
``all_gather_pairs(segments=...)``) backing
``overlap_collectives='layerwise'``.
"""

from commefficient_tpu.ops.collectives.sparse_allreduce import (
    OVERLAP_SEGMENTS,
    all_gather_pairs,
    compact_pairs,
    psum_segments,
    psum_segments_fused,
    scatter_add_pairs,
    sparse_allreduce,
    sparse_allreduce_sharded,
)

__all__ = [
    "OVERLAP_SEGMENTS",
    "all_gather_pairs",
    "compact_pairs",
    "psum_segments",
    "psum_segments_fused",
    "scatter_add_pairs",
    "sparse_allreduce",
    "sparse_allreduce_sharded",
]
