"""Sparse collective primitives — (idx, val) pair exchange over the mesh.

See ``sparse_allreduce`` for the design notes (gather form vs the
recursive-halving ``ppermute`` form, and which ``shard_map`` out_specs
each is legal under).
"""

from commefficient_tpu.ops.collectives.sparse_allreduce import (
    all_gather_pairs,
    compact_pairs,
    scatter_add_pairs,
    sparse_allreduce,
    sparse_allreduce_sharded,
)

__all__ = [
    "all_gather_pairs",
    "compact_pairs",
    "scatter_add_pairs",
    "sparse_allreduce",
    "sparse_allreduce_sharded",
]
