// Native data-loader kernel: fused gather + cifar10-fast augmentation.
//
// The reference's data path leans on torch's DataLoader, whose worker pool
// and collation run in libtorch's native code (SURVEY.md §2 L4 — the
// framework itself ships no first-party native files, the speed comes from
// the library). This is the TPU build's equivalent: the per-round batch
// assembly — gather W*B sample rows by index, reflect-pad(4) + random
// crop(HxW) + horizontal flip + cutout(2*cut_half) — as one cache-friendly
// OpenMP pass over the source array, called from Python via ctypes (the
// GIL is released for the duration of the call, so it overlaps the TPU
// step under the sampler's prefetch thread).
//
// Semantics contract: bit-identical float32 output to the vectorized numpy
// path in commefficient_tpu/data/cifar.py (pure copies and zeroing — no
// arithmetic), pinned by tests/test_native_loader.py.

#include <cmath>
#include <cstdint>
#include <cstring>

namespace {

// numpy pad(mode="reflect") index map: no edge repeat.
inline int reflect(int t, int n) {
  if (t < 0) return -t;
  if (t >= n) return 2 * n - 2 - t;
  return t;
}

template <typename T>
void gather_augment_impl(const T* data, int H, int W, int C,
                         const int64_t* idx, int64_t n, const int32_t* ys,
                         const int32_t* xs, const uint8_t* flips,
                         const int32_t* cys, const int32_t* cxs, int pad,
                         int cut_half, const float* fill, T* out) {
  const int64_t img = (int64_t)H * W * C;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const T* src = data + idx[i] * img;
    T* dst = out + i * img;
    if (ys == nullptr) {
      std::memcpy(dst, src, (size_t)img * sizeof(T));
      continue;
    }
    const int y0 = ys[i] - pad;
    const int x0 = xs[i] - pad;
    const bool fl = flips[i] != 0;
    const int cy0 = cys[i] - cut_half, cy1 = cys[i] + cut_half;
    const int cx0 = cxs[i] - cut_half, cx1 = cxs[i] + cut_half;
    for (int r = 0; r < H; ++r) {
      const T* srow = src + (int64_t)reflect(y0 + r, H) * W * C;
      T* drow = dst + (int64_t)r * W * C;
      const bool rcut = (r >= cy0 && r < cy1);
      for (int col = 0; col < W; ++col) {
        T* dpix = drow + (int64_t)col * C;
        if (rcut && col >= cx0 && col < cx1) {
          // cutout fill: per-channel value in source-dtype scale (the
          // dataset mean for uint8 pipelines — see CifarAugment)
          for (int ch = 0; ch < C; ++ch)
            dpix[ch] = fill ? T(fill[ch]) : T(0);
        } else {
          // flip happens on the CROPPED image (numpy order: crop, flip,
          // cutout), so the flipped source column is W-1-col pre-crop.
          const int jj = fl ? (W - 1 - col) : col;
          const T* spix = srow + (int64_t)reflect(x0 + jj, W) * C;
          for (int ch = 0; ch < C; ++ch) dpix[ch] = spix[ch];
        }
      }
    }
  }
}

// Bilinear sampling coordinate for resizing a crop_len axis to out_len
// (torch/PIL align_corners=False): src = (dst + 0.5) * crop/out - 0.5.
inline void bilin(int t, int out_len, int crop_len, int* lo, int* hi,
                  float* w) {
  float g = ((float)t + 0.5f) * ((float)crop_len / (float)out_len) - 0.5f;
  if (g < 0.0f) g = 0.0f;
  const float mx = (float)crop_len - 1.0f;
  if (g > mx) g = mx;
  *lo = (int)g;  // g >= 0: trunc == floor
  *hi = *lo + 1 < crop_len ? *lo + 1 : crop_len - 1;
  *w = g - (float)*lo;
}

// Fused gather + random-resized-crop (bilinear) + hflip — the ImageNet
// train transform (see data/imagenet.py ImageNetAugment). Lerp form
// a + (b - a) * t in float32, matching the numpy/jnp paths (FMA
// contraction under -O3 can differ in the last bit; the equivalence tests
// allow 1 uint8 LSB).
template <typename T>
void gather_rrc_impl(const T* data, int H, int W, int C, const int64_t* idx,
                     int64_t n, const int32_t* ys, const int32_t* xs,
                     const int32_t* hs, const int32_t* ws,
                     const uint8_t* flips, T* out) {
  const int64_t img = (int64_t)H * W * C;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const T* src = data + idx[i] * img;
    T* dst = out + i * img;
    const int ch = hs[i], cw = ws[i];
    const bool fl = flips[i] != 0;
    // x-axis coordinates depend only on (col, W, cw): hoist the W
    // bilin calls out of the row loop (LUTs on the stack; W <= 4096)
    int x0s[4096], x1s[4096];
    float wxs[4096];
    for (int col = 0; col < W && col < 4096; ++col)
      bilin(col, W, cw, &x0s[col], &x1s[col], &wxs[col]);
    for (int r = 0; r < H; ++r) {
      int y0, y1;
      float wy;
      bilin(r, H, ch, &y0, &y1, &wy);
      const T* row0 = src + (int64_t)(ys[i] + y0) * W * C;
      const T* row1 = src + (int64_t)(ys[i] + y1) * W * C;
      T* drow = dst + (int64_t)r * W * C;
      for (int col = 0; col < W; ++col) {
        // flip is applied AFTER the resize: output col reads resized
        // column W-1-col for flipped images
        const int cc = fl ? (W - 1 - col) : col;
        int x0, x1;
        float wx;
        if (cc < 4096) {
          x0 = x0s[cc]; x1 = x1s[cc]; wx = wxs[cc];
        } else {
          bilin(cc, W, cw, &x0, &x1, &wx);
        }
        const T* p00 = row0 + (int64_t)(xs[i] + x0) * C;
        const T* p01 = row0 + (int64_t)(xs[i] + x1) * C;
        const T* p10 = row1 + (int64_t)(xs[i] + x0) * C;
        const T* p11 = row1 + (int64_t)(xs[i] + x1) * C;
        T* dpix = drow + (int64_t)col * C;
        for (int c = 0; c < C; ++c) {
          const float a = (float)p00[c], b = (float)p01[c];
          const float d0 = (float)p10[c], d1 = (float)p11[c];
          const float top = a + (b - a) * wx;
          const float bot = d0 + (d1 - d0) * wx;
          const float v = top + (bot - top) * wy;
          if (sizeof(T) == 1) {
            float rv = nearbyintf(v);
            if (rv < 0.0f) rv = 0.0f;
            if (rv > 255.0f) rv = 255.0f;
            dpix[c] = (T)rv;
          } else {
            dpix[c] = (T)v;
          }
        }
      }
    }
  }
}

}  // namespace

extern "C" {

// data: [N, H, W, C] (contiguous), idx: [n] int64 sample rows.
// out:  [n, H, W, C], same dtype as data.
// ys/xs: [n] crop offsets in the padded image (0 .. 2*pad).
// flips: [n] 0/1 horizontal flip. cys/cxs: [n] cutout centers (0 .. H/W).
// Passing ys == nullptr skips augmentation entirely (pure gather).
void fedloader_gather_augment(const float* data, int64_t N, int H, int W,
                              int C, const int64_t* idx, int64_t n,
                              const int32_t* ys, const int32_t* xs,
                              const uint8_t* flips, const int32_t* cys,
                              const int32_t* cxs, int pad, int cut_half,
                              const float* fill, float* out) {
  (void)N;
  gather_augment_impl<float>(data, H, W, C, idx, n, ys, xs, flips, cys, cxs,
                             pad, cut_half, fill, out);
}

// uint8 variant: the training pipeline ships batches uint8 end-to-end (the
// host->device link is the bottleneck; normalization happens on device).
void fedloader_gather_augment_u8(const uint8_t* data, int64_t N, int H,
                                 int W, int C, const int64_t* idx, int64_t n,
                                 const int32_t* ys, const int32_t* xs,
                                 const uint8_t* flips, const int32_t* cys,
                                 const int32_t* cxs, int pad, int cut_half,
                                 const float* fill, uint8_t* out) {
  (void)N;
  gather_augment_impl<uint8_t>(data, H, W, C, idx, n, ys, xs, flips, cys,
                               cxs, pad, cut_half, fill, out);
}

// data: [N, H, W, C]; idx: [n]; ys/xs/hs/ws: [n] integer crop boxes;
// flips: [n] 0/1. out: [n, H, W, C] (each crop resized back to H x W).
void fedloader_gather_rrc(const float* data, int64_t N, int H, int W, int C,
                          const int64_t* idx, int64_t n, const int32_t* ys,
                          const int32_t* xs, const int32_t* hs,
                          const int32_t* ws, const uint8_t* flips,
                          float* out) {
  (void)N;
  gather_rrc_impl<float>(data, H, W, C, idx, n, ys, xs, hs, ws, flips, out);
}

void fedloader_gather_rrc_u8(const uint8_t* data, int64_t N, int H, int W,
                             int C, const int64_t* idx, int64_t n,
                             const int32_t* ys, const int32_t* xs,
                             const int32_t* hs, const int32_t* ws,
                             const uint8_t* flips, uint8_t* out) {
  (void)N;
  gather_rrc_impl<uint8_t>(data, H, W, C, idx, n, ys, xs, hs, ws, flips, out);
}

// Plain indexed gather: out[i, :] = data[idx[i], :], row_elems elements of
// elem_size bytes each (dtype-agnostic byte copy).
void fedloader_gather_rows(const char* data, const int64_t* idx, int64_t n,
                           int64_t row_bytes, char* out) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    std::memcpy(out + i * row_bytes, data + idx[i] * row_bytes,
                (size_t)row_bytes);
  }
}

}  // extern "C"
