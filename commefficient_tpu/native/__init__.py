"""Native (C++) runtime kernels, loaded via ctypes.

The reference gets its data-path speed from libtorch's native DataLoader
workers (SURVEY.md §2 L4); this package is the TPU build's first-party
equivalent: small C++ kernels for the host-side work that sits between the
federated sampler and ``jax.device_put`` — fused gather+augment batch
assembly (fedloader.cc). ctypes releases the GIL for the duration of each
call, so under the sampler's prefetch thread the host batch assembly
overlaps the TPU round.

The library is compiled on first use with the baked-in ``g++`` (no
pip/pybind11 — plain ``-shared -fPIC``, see ENVIRONMENT constraints) and
cached next to the source; every entry point has a pure-numpy fallback, so
the framework runs unchanged where a toolchain is missing.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "fedloader.cc")
_LIB_PATH = os.path.join(_DIR, "libfedloader.so")

_lock = threading.Lock()
_lib = None
_build_failed = False

_F32P = ctypes.POINTER(ctypes.c_float)
_I64P = ctypes.POINTER(ctypes.c_int64)
_I32P = ctypes.POINTER(ctypes.c_int32)
_U8P = ctypes.POINTER(ctypes.c_uint8)


def _compile() -> bool:
    # Build to a per-process temp path and os.replace() into place: a second
    # process (multi-host launch, parallel pytest) dlopening a partially
    # written .so would fail or crash; rename on the same filesystem is
    # atomic (ADVICE r2).
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
    flag_sets = [
        ["-O3", "-march=native", "-fopenmp"],
        ["-O3", "-fopenmp"],
        ["-O3"],
    ]
    try:
        for flags in flag_sets:
            cmd = ["g++", *flags, "-shared", "-fPIC", "-o", tmp, _SRC]
            try:
                r = subprocess.run(cmd, capture_output=True, timeout=120)
            except (FileNotFoundError, subprocess.TimeoutExpired):
                return False
            if r.returncode == 0:
                os.replace(tmp, _LIB_PATH)
                return True
        return False
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def _bind(path: str):
    lib = ctypes.CDLL(path)
    for name, ptr in (
        ("fedloader_gather_augment", _F32P),
        ("fedloader_gather_augment_u8", _U8P),
    ):
        fn = getattr(lib, name)
        fn.argtypes = [
            ptr, ctypes.c_int64, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            _I64P, ctypes.c_int64,
            _I32P, _I32P, _U8P, _I32P, _I32P,
            ctypes.c_int, ctypes.c_int, _F32P, ptr,
        ]
        fn.restype = None
    lib.fedloader_gather_rows.argtypes = [
        ctypes.c_char_p, _I64P, ctypes.c_int64, ctypes.c_int64, ctypes.c_char_p,
    ]
    lib.fedloader_gather_rows.restype = None
    for name, ptr in (
        ("fedloader_gather_rrc", _F32P),
        ("fedloader_gather_rrc_u8", _U8P),
    ):
        fn = getattr(lib, name)
        fn.argtypes = [
            ptr, ctypes.c_int64, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            _I64P, ctypes.c_int64,
            _I32P, _I32P, _I32P, _I32P, _U8P, ptr,
        ]
        fn.restype = None
    return lib


def load() -> Optional[ctypes.CDLL]:
    """The bound library, building it if needed; None when unavailable."""
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        stale = not os.path.exists(_LIB_PATH) or (
            os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)
        )
        if stale and not _compile():
            _build_failed = True
            return None
        try:
            _lib = _bind(_LIB_PATH)
        except OSError:
            _build_failed = True
            return None
        return _lib


def available() -> bool:
    return load() is not None


def gather_augment(
    data: np.ndarray,
    idx: np.ndarray,
    plan=None,
    *,
    pad: int = 4,
    cut_half: int = 4,
    fill: Optional[np.ndarray] = None,
) -> Optional[np.ndarray]:
    """out[i] = augment(data[idx[i]]) via the native kernel.

    ``data`` is [N, H, W, C] float32 or uint8 (the training pipeline ships
    uint8 — 4x less host->device traffic). ``plan`` is an AugmentPlan
    (ys/xs/flips/cys/cxs arrays, see data.cifar.CifarAugment) or None for a
    pure gather. ``fill`` is the [C] cutout fill in source-dtype scale
    (None = zeros; pipelines fill the dataset mean for uint8 — see
    CifarAugment). Returns None when the native library is unavailable
    (callers fall back to numpy).
    """
    lib = load()
    if lib is None or data.ndim != 4:
        return None
    if data.dtype == np.uint8:
        fn, ptr = lib.fedloader_gather_augment_u8, _U8P
    elif data.dtype == np.float32:
        fn, ptr = lib.fedloader_gather_augment, _F32P
    else:
        return None
    data = np.ascontiguousarray(data)
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    _check_idx(idx, data.shape[0])
    n = int(idx.shape[0])
    _, h, w, c = data.shape
    out = np.empty((n, h, w, c), data.dtype)
    if plan is None:
        null32, null8 = _I32P(), _U8P()
        args = (null32, null32, null8, null32, null32, 0, 0, _F32P())
    else:
        ys = np.ascontiguousarray(plan.ys, np.int32)
        xs = np.ascontiguousarray(plan.xs, np.int32)
        flips = np.ascontiguousarray(plan.flips, np.uint8)
        cys = np.ascontiguousarray(plan.cys, np.int32)
        cxs = np.ascontiguousarray(plan.cxs, np.int32)
        fill_arr = (
            np.zeros((c,), np.float32)
            if fill is None
            else np.ascontiguousarray(np.broadcast_to(fill, (c,)), dtype=np.float32)
        )
        args = (
            ys.ctypes.data_as(_I32P), xs.ctypes.data_as(_I32P),
            flips.ctypes.data_as(_U8P),
            cys.ctypes.data_as(_I32P), cxs.ctypes.data_as(_I32P),
            pad, cut_half, fill_arr.ctypes.data_as(_F32P),
        )
    fn(
        data.ctypes.data_as(ptr), data.shape[0], h, w, c,
        idx.ctypes.data_as(_I64P), n, *args,
        out.ctypes.data_as(ptr),
    )
    return out


def gather_rrc(data: np.ndarray, idx: np.ndarray, plan) -> Optional[np.ndarray]:
    """out[i] = random_resized_crop(data[idx[i]], plan[i]) via the native
    kernel — the ImageNet train transform (data.imagenet.ImageNetAugment).

    ``plan`` is an RRCPlan (ys/xs/hs/ws int32 crop boxes + flips). Returns
    None when the library is unavailable (callers fall back to numpy).
    Interpolated pixels can differ from the numpy path by 1 uint8 LSB
    (FMA contraction under -O3) — pinned by tests/test_imagenet_augment.py.
    """
    lib = load()
    if lib is None or data.ndim != 4:
        return None
    if data.dtype == np.uint8:
        fn, ptr = lib.fedloader_gather_rrc_u8, _U8P
    elif data.dtype == np.float32:
        fn, ptr = lib.fedloader_gather_rrc, _F32P
    else:
        return None
    data = np.ascontiguousarray(data)
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    _check_idx(idx, data.shape[0])
    n = int(idx.shape[0])
    _, h, w, c = data.shape
    ys = np.ascontiguousarray(plan.ys, np.int32)
    xs = np.ascontiguousarray(plan.xs, np.int32)
    hs = np.ascontiguousarray(plan.hs, np.int32)
    ws = np.ascontiguousarray(plan.ws, np.int32)
    # the kernel reads plan[i] for every i < n unchecked: a plan built for
    # a smaller batch would be a silent out-of-bounds heap read
    if not (len(ys) == len(xs) == len(hs) == len(ws) == len(plan.flips) == n):
        raise ValueError(
            f"plan arrays must match idx length {n}, got "
            f"{[len(a) for a in (ys, xs, hs, ws, plan.flips)]}"
        )
    # the kernel reads rows ys+hs-1 / cols xs+ws-1 unchecked: validate the
    # crop boxes like _check_idx validates sample indices
    if n and (
        int(hs.min()) < 1 or int(ws.min()) < 1
        or int(ys.min()) < 0 or int(xs.min()) < 0
        # int64 sums: int32 ys+hs could wrap negative for corrupt plans
        # and sneak past the max() check
        or int((ys.astype(np.int64) + hs).max()) > h
        or int((xs.astype(np.int64) + ws).max()) > w
    ):
        raise IndexError("RRC crop box out of image bounds")
    flips = np.ascontiguousarray(plan.flips, np.uint8)
    out = np.empty((n, h, w, c), data.dtype)
    fn(
        data.ctypes.data_as(ptr), data.shape[0], h, w, c,
        idx.ctypes.data_as(_I64P), n,
        ys.ctypes.data_as(_I32P), xs.ctypes.data_as(_I32P),
        hs.ctypes.data_as(_I32P), ws.ctypes.data_as(_I32P),
        flips.ctypes.data_as(_U8P),
        out.ctypes.data_as(ptr),
    )
    return out


def _check_idx(idx: np.ndarray, n_rows: int) -> None:
    """The C kernels do no bounds checking ((void)N in fedloader.cc) — a
    corrupt or negative index would be a silent out-of-bounds READ in the
    OpenMP copy loop. Validate on the Python side instead (ADVICE r2);
    numpy's min/max over an index batch is noise next to the copy itself."""
    if idx.size and (int(idx.min()) < 0 or int(idx.max()) >= n_rows):
        raise IndexError(
            f"gather index out of range: [{int(idx.min())}, {int(idx.max())}] "
            f"vs {n_rows} data rows"
        )


def gather_rows(data: np.ndarray, idx: np.ndarray) -> Optional[np.ndarray]:
    """out[i] = data[idx[i]] for any fixed-row-size array; None = no lib."""
    lib = load()
    if lib is None or data.dtype == object:
        return None
    data = np.ascontiguousarray(data)
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    _check_idx(idx, data.shape[0])
    n = int(idx.shape[0])
    row_bytes = int(data.dtype.itemsize) * (
        int(np.prod(data.shape[1:], dtype=np.int64)) if data.ndim > 1 else 1
    )
    out = np.empty((n,) + data.shape[1:], data.dtype)
    lib.fedloader_gather_rows(
        data.ctypes.data_as(ctypes.c_char_p), idx.ctypes.data_as(_I64P), n,
        row_bytes, out.ctypes.data_as(ctypes.c_char_p),
    )
    return out
