"""RoundPrefetcher — realize round t+1..t+depth's host work off the
critical path.

One background worker thread walks the GLOBAL round index (the sampler,
the fedsim environment and the lr schedule are all pure functions of
``(seed, stream, round_idx)`` — epoch boundaries are bookkeeping, not
state), realizing one ``RoundWork`` per round:

  * the non-IID sampler draw + fused batch assembly (or the index-only
    form when the session holds device-resident data),
  * the fedavg microbatch reshape,
  * the fedsim ``RoundEnv`` (masks/chaos for that round),
  * the schedule lr,
  * eager H2D staging of the round's arrays onto the mesh
    (``FederatedSession.stage_round_payload`` / ``stage_round_indices`` —
    the session's own sharding objects, so the dispatch-time
    ``device_put`` is an identity).

Because every input is that pure function of the round index, prefetching
COMMUTES with execution: the RoundWork stream is bit-identical to what the
synchronous loop would have realized, in the same order (pinned by
tests/test_pipeline.py). The queue is bounded at ``depth`` items, so at
most ``depth`` rounds of batches are staged ahead (HBM bound:
depth x one round's batch bytes).

Fault discipline (the part that must never hang):

  * a worker-thread exception (corrupt batch, exhausted iterator, fedsim
    validation error, a failing H2D) is captured WITH its traceback and
    re-raised at the consuming round — ``get(step)`` is where the train
    loop sees it, and the runner's crash path then drains in-flight
    rounds + dumps the flight record exactly as for a synchronous crash;
  * ``get`` polls with a timeout and fails loudly if the worker died
    without enqueueing anything (a bug, not a wait);
  * ``close`` drains the queue, signals stop, and joins the worker; the
    worker's bounded-queue puts poll the stop flag (the
    data/sampler.prefetch discipline), so shutdown cannot deadlock on a
    full queue.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, NamedTuple, Optional


class RoundWork(NamedTuple):
    """One round's fully realized, staged inputs.

    Exactly one of ``batch`` (host-batch path: staged ``{k: [W, B, ...]}``
    device arrays, microbatch-reshaped for fedavg) and ``idx`` (index
    path: staged ``[W, B]`` int32 sample indices, with ``plan`` the staged
    augmentation plan) is set. ``env`` is the round's fedsim RoundEnv
    (None when the simulator is off). ``cohort`` is the staged
    clientstore StagedCohort — the cohort's hosted [W, D] vel/err device
    rows, gathered + H2D'd on this worker thread so the bank read
    overlaps the previous round's compute; None unless the session hosts
    client state (``--client_store host|mmap``). The dispatcher checks
    its staleness version and regathers if the same client was updated
    inside the pipeline window, so depth > 0 stays bit-exact. ``host_ms``
    is the wall-clock the worker spent realizing + staging this round —
    the host serial time the pipeline moved off the critical path."""

    step: int
    lr: float
    client_ids: Any  # host numpy [W] int32
    batch: Optional[dict]
    idx: Any
    plan: Any
    env: Any
    host_ms: float
    cohort: Any = None


_END = object()


class PrefetchWorkerDied(RuntimeError):
    """The prefetch worker exited without delivering the next item or an
    exception — a bug in the worker loop, surfaced instead of a hang."""


class RoundPrefetcher:
    """Bounded-depth background realization of ``RoundWork`` items.

    ``start_step``/``stop_step`` bound the global round range (a resumed
    run starts at its restored step). ``use_indices`` selects the
    device-resident index form. ``spans`` (a telemetry.PhaseSpans or
    None) gets the prefetch lane's ``prefetch_realize``/``prefetch_stage``
    spans on the WORKER thread's own track (thread-aware tids)."""

    def __init__(self, *, session, sampler, lr_fn, depth: int,
                 start_step: int = 0, stop_step: int = 0,
                 microbatches: int = 0, use_indices: bool = False,
                 spans=None, replay_until: int = 0):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.session = session
        self.sampler = sampler
        self.lr_fn = lr_fn
        self.depth = int(depth)
        self.start_step = int(start_step)
        self.stop_step = int(stop_step)
        # resilience/ replay fence: rounds below it re-execute after a
        # divergence rollback, so their fedsim envs realize with
        # replay=True (transient nan_client injections suppressed —
        # fedsim/faults.py). The engine passes the session's replay
        # horizon when it restarts the window after a recovery.
        self.replay_until = int(replay_until)
        self.microbatches = int(microbatches)
        self.use_indices = bool(use_indices)
        self.spans = spans
        self._q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        # true staged-WORK count (the occupancy numerator): qsize would
        # also count the _END sentinel and queued worker exceptions,
        # over-reporting pipeline/occupancy at the window's tail
        self._staged = 0
        self._staged_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="round-prefetch", daemon=True
        )
        self._started = False

    # -- worker side -------------------------------------------------------
    def _span(self, name: str, step: int):
        if self.spans is None:
            from contextlib import nullcontext

            return nullcontext()
        from commefficient_tpu.telemetry.trace import round_trace_id

        # every prefetch span names the round it is REALIZING (schema
        # v11) — the Perfetto tree links this lane's work to the
        # dispatch-lane spans of the same round
        return self.spans.span(name, step=step,
                               trace_id=round_trace_id(step))

    def _realize(self, step: int) -> RoundWork:
        t0 = time.perf_counter()
        sess, L = self.session, self.microbatches
        with self._span("prefetch_realize", step):
            if self.use_indices:
                cids, idx, plan = self.sampler.sample_round_indices(step)
                batch = None
            else:
                cids, batch = self.sampler.sample_round(step)
                if L:  # fedavg [W, L, B/L, ...] convention
                    batch = {
                        k: v.reshape(v.shape[0], L, v.shape[1] // L,
                                     *v.shape[2:])
                        for k, v in batch.items()
                    }
                idx = plan = None
            env = (sess.fedsim_env.round_env(
                       step, replay=step < self.replay_until)
                   if sess.fedsim_env is not None else None)
            lr = float(self.lr_fn(step))
        with self._span("prefetch_stage", step):
            # eager H2D: round step's arrays start copying to the mesh NOW,
            # while the device still computes earlier rounds
            if self.use_indices:
                cids, idx, plan = sess.stage_round_indices(cids, idx, plan)
                cohort = None
            else:
                cids, batch = sess.stage_round_payload(cids, batch)
                # hosted client rows (clientstore/): bank gather + H2D
                # off the critical path too — None for device stores;
                # the gather span inherits this round's trace id
                if hasattr(sess, "stage_cohort_rows"):
                    from commefficient_tpu.telemetry.trace import (
                        round_trace_id,
                    )

                    cohort = sess.stage_cohort_rows(
                        cids, trace_id=round_trace_id(step))
                else:
                    cohort = None
        return RoundWork(
            step=step, lr=lr, client_ids=cids, batch=batch, idx=idx,
            plan=plan, env=env, host_ms=(time.perf_counter() - t0) * 1e3,
            cohort=cohort,
        )

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _run(self):
        try:
            if self.spans is not None:
                # name this worker's span track (schema v5 thread_name
                # metadata) so the prefetch lane renders labeled
                self.spans.register_lane("round-prefetch")
            for step in range(self.start_step, self.stop_step):
                if self._stop.is_set():
                    return
                if not self._put(self._realize(step)):
                    return
                with self._staged_lock:
                    self._staged += 1
            self._put(_END)
        except BaseException as e:  # noqa: BLE001 — re-raised at consumer
            self._put(e)

    # -- consumer side -----------------------------------------------------
    def start(self) -> "RoundPrefetcher":
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    def get(self, step: int) -> RoundWork:
        """The next staged round, which MUST be ``step`` (the in-order
        contract — a mismatch means the caller and the worker disagree
        about the round clock, a bug worth failing on, not training on).
        Re-raises a worker exception with its original traceback; raises
        ``PrefetchWorkerDied`` instead of hanging if the worker is gone."""
        if not self._started:
            raise RuntimeError("RoundPrefetcher.get before start()")
        while True:
            try:
                item = self._q.get(timeout=0.5)
                break
            except queue.Empty:
                if not self._thread.is_alive():
                    # the worker may have enqueued its final item (the
                    # fault, _END, or the round itself) in the instant
                    # between our timeout and this liveness check — drain
                    # once more before declaring it dead, else the real
                    # worker exception would be masked by this generic one
                    try:
                        item = self._q.get_nowait()
                        break
                    except queue.Empty:
                        raise PrefetchWorkerDied(
                            f"prefetch worker died before staging round "
                            f"{step} (no item, no exception) — see the "
                            "worker thread's stderr for the real failure"
                        ) from None
        if item is _END:
            raise PrefetchWorkerDied(
                f"prefetch exhausted at round {step}: the worker covered "
                f"[{self.start_step}, {self.stop_step}) and the consumer "
                "asked past it"
            )
        if isinstance(item, BaseException):
            # the original traceback rides on the exception object — the
            # consuming round sees the true worker-side failure frames
            raise item
        if item.step != step:
            raise RuntimeError(
                f"prefetch order violated: staged round {item.step}, "
                f"consumer expected {step}"
            )
        with self._staged_lock:
            self._staged -= 1
        return item

    @property
    def staged_rounds(self) -> int:
        """Rounds of real WORK currently staged ahead (0..depth) — the
        occupancy numerator. Counts only RoundWork items (incremented
        after the worker's put, decremented at the consumer's get), so
        the _END sentinel / a queued worker exception never inflate the
        gauge at the window's tail."""
        with self._staged_lock:
            return min(max(self._staged, 0), self.depth)

    def close(self, timeout: float = 10.0) -> bool:
        """Stop the worker and join it; returns True iff the join
        completed. Drains the queue so a worker blocked on a full queue
        wakes immediately (its puts also poll the stop flag)."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        if self._started:
            self._thread.join(timeout=timeout)
            return not self._thread.is_alive()
        return True
