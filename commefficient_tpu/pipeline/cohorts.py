"""CohortScheduler — the asyncfed cohort feed on the PR 9 prefetcher.

The buffered-asynchronous engine (asyncfed/engine.py) launches cohorts,
not rounds, and a cohort's host work is exactly a round's: sample the
participants, assemble the batch, realize the fedsim environment, stage
the arrays onto the mesh. So the scheduler IS a ``RoundPrefetcher`` with
the step axis reinterpreted as the cohort index — the same worker thread,
in-order ``get`` contract, crash propagation, and replay-horizon
discipline, with two cohort-specific twists:

* the learning rate is ``lr_fn(launch_version[cohort])``, the server
  version the cohort snapshots at launch (NOT the cohort index — under
  concurrency C > 1 a cohort's launch version lags its index);
* staging always takes the host-batch path (``use_indices=False``): the
  launch program consumes staged batches regardless of
  ``cfg.device_data`` (the apply side is where the round's state lives).

Keeping ``C`` (the engine passes ``depth >= C``) cohorts staged ahead is
what lets the engine keep C cohorts in flight with zero host work on the
critical path.
"""

from __future__ import annotations

from typing import Optional, Sequence

from commefficient_tpu.pipeline.prefetch import RoundPrefetcher, RoundWork


class CohortScheduler:
    """In-order cohort realization for the asyncfed engine."""

    def __init__(self, *, session, sampler, lr_fn,
                 launch_versions: Sequence[int], start_cohort: int = 0,
                 stop_cohort: int, depth: int, microbatches: int = 0,
                 spans=None, replay_until: int = 0):
        versions = tuple(int(v) for v in launch_versions)

        def cohort_lr(c: int) -> float:
            return float(lr_fn(versions[c]))

        self._prefetcher = RoundPrefetcher(
            session=session,
            sampler=sampler,
            lr_fn=cohort_lr,
            depth=max(1, int(depth)),
            start_step=int(start_cohort),
            stop_step=int(stop_cohort),
            microbatches=microbatches,
            use_indices=False,
            spans=spans,
            replay_until=int(replay_until),
        )

    def start(self) -> "CohortScheduler":
        self._prefetcher.start()
        return self

    def get(self, cohort: int) -> RoundWork:
        """Blocking in-order fetch of cohort ``cohort``'s realized work
        (``RoundWork`` with ``step`` == the cohort index)."""
        return self._prefetcher.get(cohort)

    @property
    def staged_cohorts(self) -> int:
        return self._prefetcher.staged_rounds

    @property
    def prefetch_host_ms(self) -> float:
        return getattr(self._prefetcher, "host_ms", 0.0)

    def close(self, timeout: Optional[float] = 10.0) -> None:
        self._prefetcher.close(timeout)
