"""Pipelined round execution — overlap host staging and H2D with device
compute.

FetchSGD's round loop is dispatch-bound on the device, but every round
used to pay its host serial time FIRST: client sampling + batch assembly,
fedsim environment realization, schedule lr, and the ``device_put`` H2D
copy all ran on the critical path before ``round_dispatch`` (the PR-7
phase spans measure each). Like sketched-SGD's pipelined worker loop
(arXiv:1903.04488 §5), round t+1's host work is independent of round t's
result — every rng stream in this repo is a pure function of
``(seed, stream, round_idx)`` — so it can be realized ahead, bit-exactly:

  * ``prefetch``: ``RoundPrefetcher`` — a bounded-depth worker thread
    realizing ``RoundWork`` items up to ``--pipeline_depth`` rounds ahead,
    with eager H2D staging through the session's own sharding objects.
  * ``engine``: ``PipelinedRounds`` — the driver owning the in-flight
    window and the determinism contracts (controller barrier order,
    policy-lag rule, checkpoint fencing, crash draining); see its module
    docstring.
  * ``cohorts``: ``CohortScheduler`` — the same prefetcher with the step
    axis reinterpreted as the asyncfed cohort index (launch-version lr,
    always host-batch staging); the buffered-asynchronous engine
    (asyncfed/) keeps C cohorts staged ahead through it.
  * ``scan_engine``: ``ScanRounds`` — the orthogonal dispatch-side
    amortization (``--scan_rounds K``): K rounds per XLA dispatch via
    ``lax.scan`` over the device-resident index round, sampler indices
    staged per EPOCH, telemetry packs stacked and drained at scan exit;
    blocks chop at every state-observation boundary so K > 1 is pinned
    equal to K = 1 on params and the drained scalar sequence.

``--pipeline_depth 0`` (default) constructs NOTHING: the train loops keep
the legacy synchronous path, golden parity recordings and level-0 HLO are
untouched (the telemetry_level-0 / fedsim-always discipline). Any depth
is bit-exact vs depth 0 (pinned by tests/test_pipeline.py, including
under fedsim dropout and a mid-run compression-ladder switch).

Layering: this package imports ``parallel`` (the session's staging hooks)
and is imported only by ``train/`` and bench — nothing below it knows the
pipeline exists.
"""

from commefficient_tpu.pipeline.cohorts import CohortScheduler
from commefficient_tpu.pipeline.engine import PipelinedRounds
from commefficient_tpu.pipeline.prefetch import (
    PrefetchWorkerDied,
    RoundPrefetcher,
    RoundWork,
)
from commefficient_tpu.pipeline.scan_engine import ScanRounds

__all__ = [
    "CohortScheduler",
    "PipelinedRounds",
    "PrefetchWorkerDied",
    "RoundPrefetcher",
    "RoundWork",
    "ScanRounds",
]
