"""ScanRounds — K rounds per XLA dispatch via ``lax.scan`` over the
device-resident index round.

The per-round dispatch path pays host serial time per round even when the
inputs are staged: python argument marshaling, the jit call boundary, the
runtime enqueue — ~ms per dispatch through a tunneled TPU runtime, which
at GPT-2 round times is noise but at amortized-sketch round times is not.
This engine executes blocks of up to ``cfg.scan_rounds`` rounds as ONE
jitted program whose body is the SAME unjitted index-round closure the
per-round path wraps (``FederatedSession.raw_round_idx_fn`` — one round
trace shared by construction):

  * **Sampler indices staged per epoch.** At epoch entry the epoch's
    ``[spe, W, B]`` sampler draws, client ids, augmentation plans, lrs
    and fedsim envs are realized host-side in one pass (each a pure
    function of the round index — the prefetcher's determinism contract)
    and committed to the mesh with ONE ``device_put`` per array, not one
    per round.
  * **Telemetry packs stacked.** The scan's ys stack each round's metric
    dict into ``[L]`` device arrays; the engine yields per-round views
    of those stacks, so the runner's deferred-drain discipline is
    untouched — packs drain at the same points (epoch end,
    pre-checkpoint), and the drained scalar SEQUENCE is pinned equal to
    per-round dispatch (tests/test_scan_engine.py).
  * **Blocks chop at every state-observation boundary.** The runner acts
    on ``session.state`` only at checkpoint saves (``will_save``), vault
    snapshots (``will_snapshot``) and epoch ends; a scanned block's
    intermediate states exist only on-device, so blocks END exactly at
    those boundaries (``checkpoint_every`` / ``snapshot_every``
    multiples, epoch end) — the state the runner sees at such a step is
    bit-identical to the synchronous loop's. Anything that must act
    host-side between two ARBITRARY rounds (the control plane's
    pre-dispatch decision, round-granular preemption) is refused at
    Config validation instead of silently misbehaving.
  * **Deferred-drain / resilience composition.** A ``DivergenceError``
    still fires at the drain; a rollback restores the vault snapshot
    wholesale and the runner re-enters ``epoch_rounds`` at the rollback
    step — the engine is stateless across blocks (``restart`` is just a
    staging-cache drop), and its first block after re-entry starts at
    the rollback round with freshly realized (replay-aware) envs.

Distinct block lengths compile once each (at most a handful per run: K,
the pre-boundary remainders, the epoch tail); every length gets its own
RetraceSentinel signature stream (``round_scan_fn[xL]``), so a length's
first trace is an expected compile and any later drift on it is a
counted retrace — the prewarm discipline at scan granularity.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


class ScanRounds:
    """One per train loop when ``cfg.scan_rounds > 1`` (train/runner.py).

    API-compatible with ``PipelinedRounds`` where the runner touches it:
    ``start(resume_step)``, ``epoch_rounds(epoch, start_step)``,
    ``restart(step)``, ``close()``, ``stats()``.
    """

    def __init__(self, cfg, session, sampler, lr_fn, num_rounds: int,
                 steps_per_epoch: Optional[int] = None, spans=None,
                 profiler=None):
        if cfg.scan_rounds <= 1:
            raise ValueError(
                "ScanRounds needs cfg.scan_rounds > 1 (0/1 = the per-round "
                "dispatch path — build nothing)"
            )
        if getattr(session, "_dev_data", None) is None:
            raise ValueError(
                "scan_rounds > 1 needs device-resident data (the index "
                "round): the session attached none — the dataset exceeded "
                "device_data_max_mb, the sampler is not fusable, or the "
                "mode forced host batches. Drop scan_rounds or fix the "
                "device-data gate (FederatedSession.maybe_attach_data)."
            )
        if session.controller is not None:
            raise ValueError(
                "scan_rounds > 1 with a controller should have been "
                "refused at Config validation (per-round pre-dispatch "
                "decisions cannot run inside a scanned block)"
            )
        self.cfg = cfg
        self.session = session
        self.spans = spans
        self.profiler = profiler
        self.K = int(cfg.scan_rounds)
        self.num_rounds = int(num_rounds)
        self.steps_per_epoch = int(
            steps_per_epoch if steps_per_epoch is not None
            else sampler.steps_per_epoch()
        )
        self._sampler = sampler
        self._lr_fn = lr_fn
        # ONE raw round closure shared by every block length: rebuilding
        # it per L would re-run the compressor construction (duplicate
        # dampening/geometry warnings) and only guarantee equivalent —
        # not identical — closures across lengths
        self._raw_round = session.raw_round_idx_fn()
        self._scan_fns: dict = {}  # block length L -> jitted scan program
        # aggregate stats (bench's scan leg / the runner's info line)
        self._rounds = 0
        self._dispatches = 0

    # -- lifecycle (PipelinedRounds API parity) ----------------------------
    def start(self, resume_step: int = 0) -> "ScanRounds":
        del resume_step  # stateless across blocks; staging is per-epoch
        return self

    def restart(self, step: int) -> None:
        """Resilience recovery fence: nothing is staged across
        ``epoch_rounds`` calls, so a rollback needs no quiesce — the
        runner's re-entry at the rollback step restages that epoch's
        remainder with replay-aware envs (the session's horizon)."""
        if self.spans is not None:
            with self.spans.span(f"scan_recovery_restart:round{step}",
                                 step=int(step)):
                pass

    def close(self) -> None:
        """No worker thread to join — present for engine API parity."""

    # -- block plan --------------------------------------------------------
    def _boundaries(self):
        """Step multiples a block must not cross (the runner observes
        ``session.state`` there): checkpoint saves and vault snapshots.
        ``will_save``/``will_snapshot`` fire on ``step % every == 0`` with
        step = round + 1, so a gate at T means a block ends AT round T-1
        (covers rounds [..., T))."""
        gates = []
        if self.cfg.checkpoint_every > 0 and self.cfg.checkpoint_dir:
            gates.append(int(self.cfg.checkpoint_every))
        if self.cfg.recovery_enabled:
            gates.append(int(self.cfg.snapshot_every))
        return gates

    def _blocks(self, start: int, stop: int):
        """Chop [start, stop) into scan blocks of <= K rounds that end at
        every boundary gate (yields (block_start, block_len))."""
        gates = self._boundaries()
        s = start
        while s < stop:
            e = min(s + self.K, stop)
            for g in gates:
                # first multiple of g STRICTLY after s bounds the block:
                # the runner must see state at round (mult - 1)'s yield
                nxt = (s // g + 1) * g
                e = min(e, nxt)
            yield s, e - s
            s = e

    # -- per-epoch staging -------------------------------------------------
    def _stage_range(self, start: int, stop: int):
        """Realize rounds [start, stop)'s inputs host-side (sampler draws,
        plans, lrs, fedsim envs — each a pure function of the round
        index), then commit each STACKED array to the mesh once. Returns
        (staged dict, per-round host ``fedsim/*`` stats list)."""
        sess = self.session
        with self._span("scan_stage", start):
            cids, idxs, plans, lrs = [], [], [], []
            live, corrupt, cnt, stats = [], [], [], []
            fedsim = sess.fedsim_env is not None
            for r in range(start, stop):
                c, i, p = self._sampler.sample_round_indices(r)
                cids.append(c)
                idxs.append(i)
                plans.append(p)
                lrs.append(float(self._lr_fn(r)))
                if fedsim:
                    env = sess.fedsim_env.round_env(
                        r, replay=r < sess._replay_horizon
                    )
                    if sess._client_blacklist is not None:
                        env = sess._blacklist_env(env, c)
                    live.append(env.live)
                    corrupt.append(env.corrupt)
                    cnt.append(env.live_count)
                    stats.append(dict(env.stats))
                else:
                    stats.append({})
            # epoch stacks commit REPLICATED: the leading axis is the
            # ROUND, not a mesh axis (the per-round [W] sharding the
            # direct path uses would mis-shard dim 0 here); the scan body
            # slices each round's inputs and the round's own shard_map
            # partitions them — and the whole epoch's indices are KBs.
            put_r = lambda a: jax.device_put(  # noqa: E731
                jnp.asarray(a), sess._replicated
            )
            staged = {
                "cids": put_r(np.stack(cids).astype(np.int32)),
                "idx": put_r(np.stack(idxs).astype(np.int32)),
                # plans stack element-wise ([L] leading axis per plan
                # array); () when the augmenter ships no plan
                "plan": tuple(
                    put_r(np.stack([p[j] for p in plans]))
                    for j in range(len(plans[0]))
                ) if plans and plans[0] else (),
                "lr": put_r(np.asarray(lrs, np.float32)),
                "env": (
                    (put_r(np.stack(live).astype(np.float32)),
                     put_r(np.stack(corrupt).astype(np.float32)),
                     put_r(np.asarray(cnt, np.float32)))
                    if fedsim else ()
                ),
            }
        return staged, stats

    # -- the scanned program ----------------------------------------------
    def _scan_fn(self, L: int):
        """The jitted L-round block program (cached per distinct L). Body
        = the session's raw index-round closure; xs = the staged per-round
        inputs; ys = the stacked metric packs."""
        if L in self._scan_fns:
            return self._scan_fns[L]
        sess = self.session
        raw = self._raw_round
        fedsim = sess.fedsim_env is not None

        def scan_block(state, data, cids_L, idx_L, plan_L, lr_L, env_L):
            def body(st, xs):
                cids, idx, plan, lr, env = xs
                st2, metrics = raw(st, data, cids, idx, plan, lr,
                                   env=env if fedsim else ())
                return st2, metrics

            xs = (cids_L, idx_L, plan_L, lr_L, env_L)
            return jax.lax.scan(body, state, xs)

        fn = jax.jit(
            sess.retrace_sentinel.wrap(scan_block, f"round_scan_fn[x{L}]"),
            donate_argnums=(0,),
        )
        self._scan_fns[L] = fn
        return fn

    # -- the per-epoch round source (what the runner iterates) -------------
    def epoch_rounds(self, epoch: int, start_step: int):
        """Yield ``(step, lr, metrics)`` for epoch ``epoch``'s rounds at or
        past ``start_step`` — same triples, same order, same drain points
        as the synchronous loop; each block of <= K rounds is one device
        dispatch and each yielded metrics dict is a per-round view of the
        block's stacked telemetry pack."""
        sess = self.session
        spe = self.steps_per_epoch
        lo = max(epoch * spe, start_step)
        hi = min((epoch + 1) * spe, self.num_rounds)
        if lo >= hi:
            return
        staged, host_stats = self._stage_range(lo, hi)
        for bstart, blen in self._blocks(lo, hi):
            o = bstart - lo
            sl = lambda a: a[o:o + blen] if not isinstance(a, tuple) else (  # noqa: E731
                tuple(x[o:o + blen] for x in a)
            )
            if self.profiler is not None:
                self.profiler.step(bstart)
            if self.spans is not None:
                self.spans.step(bstart)
            with self._span("round_dispatch", bstart) as sp:
                sess.state, packs = self._scan_fn(blen)(
                    sess.state, sess._dev_data, sl(staged["cids"]),
                    sl(staged["idx"]), sl(staged["plan"]), sl(staged["lr"]),
                    sl(staged["env"]),
                )
                if sp is not None:
                    sp.fence(packs["loss"][-1])
            sess._round_clock += blen
            sess._replay_horizon = max(sess._replay_horizon,
                                       sess._round_clock)
            self._rounds += blen
            self._dispatches += 1
            for i in range(blen):
                s = bstart + i
                stats = sess._host_round_stats(host_stats[s - lo])
                metrics = {k: v[i] for k, v in packs.items()}
                if self.cfg.telemetry_level >= 1:
                    # constant key set across the run (pack_metric_dicts);
                    # rides the existing pipeline/ scalar namespace
                    metrics["pipeline/scan_rounds_per_dispatch"] = float(blen)
                yield s, float(self._lr_fn(s)), (
                    {**metrics, **stats} if stats else metrics
                )

    def _span(self, name: str, step: int):
        if self.spans is None:
            from contextlib import nullcontext

            return nullcontext()
        return self.spans.span(name, step=int(step))

    # -- aggregate stats (runner info line / bench) ------------------------
    def stats(self) -> dict:
        return {
            "rounds": self._rounds,
            "dispatches": self._dispatches,
            "rounds_per_dispatch": self._rounds / max(self._dispatches, 1),
            "block_lengths": sorted(self._scan_fns),
        }
