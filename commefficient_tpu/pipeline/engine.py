"""PipelinedRounds — the driver that owns the in-flight round window.

The round dispatch itself was already asynchronous (XLA enqueue returns
immediately); what serialized the loop was everything BEFORE dispatch:
sampler draw + batch assembly, fedsim environment realization, schedule
lr, and the ``device_put`` H2D copy — all host-serial on the critical
path (the PR-7 phase spans measure them per round). This engine moves all
of it onto the ``RoundPrefetcher``'s worker thread, ``cfg.pipeline_depth``
rounds ahead, and keeps the DISPATCH ORDER — and with it every
correctness contract — identical to the synchronous loop:

  * **Controller barrier.** ``BudgetController.on_round_start`` still runs
    host-side immediately before each round's dispatch (inside
    ``session.train_round*``), in round order — byte accounting, budget
    clamps and ``BudgetExhaustedError`` fire exactly where depth 0 fires
    them. Staged work is rung-INVARIANT (a ladder varies
    k/num_cols/rank, never batch geometry, env masks or lr), and every
    rung's round program is AOT-prewarmed, so a rung switch quiesces
    nothing physical: the dispatch-table swap + state migration happen at
    the barrier and the staged window dispatches through the NEW rung's
    prewarmed program — ``xla/retraces`` stays 0 (the engine registers a
    switch listener purely to mark the quiesce in the span track).
  * **Policy lag.** Adaptive policies observe drained metrics through the
    same ``drain_round_metrics`` rider at the same drain points (epoch
    end, pre-checkpoint) as depth 0 — the engine never drains early, so
    the observation-before-decision order, and therefore the rung
    sequence, is a pure function of the run and bit-identical across
    depths; a checkpoint resume reproduces it (the controller blob saw
    the same drains).
  * **Checkpoint fence.** Drains precede saves (the runner's
    ``will_save`` discipline), and the save itself fetches the device
    state — the in-flight window holds only FUTURE rounds' pure inputs,
    so restore is bit-identical to synchronous execution.
  * **Crash paths.** A worker-thread fault re-raises at the consuming
    round with its original traceback; the runner's crash flush then
    drains the dispatched in-flight rounds and the flight dump carries
    their true round indices — same forensics as a synchronous crash.

``pipeline/*`` telemetry (level >= 1, schema v5) rides each round's
metric dict: ``pipeline/occupancy`` (staged/depth at fetch, in [0, 1]),
``pipeline/host_stall_ms`` (time the consumer blocked waiting for staged
work — the residual host serial time the depth did NOT hide), and
``pipeline/staged_rounds`` (the integer occupancy numerator).
"""

from __future__ import annotations

import time
from typing import Optional

from commefficient_tpu.pipeline.prefetch import RoundPrefetcher


class PipelinedRounds:
    """One per train loop when ``cfg.pipeline_depth > 0``.

    ``lr_fn`` must be the loop's schedule (pure in the round index);
    ``num_rounds`` the run length (steps_per_epoch x num_epochs).
    ``spans``/``profiler`` are the loop's PhaseSpans/StepProfiler (either
    may be None); the prefetch lane's spans land on the worker thread's
    own track."""

    def __init__(self, cfg, session, sampler, lr_fn, num_rounds: int,
                 steps_per_epoch: Optional[int] = None, spans=None,
                 profiler=None):
        if cfg.pipeline_depth < 1:
            raise ValueError(
                "PipelinedRounds needs cfg.pipeline_depth >= 1 (depth 0 "
                "is the synchronous loop — build nothing)"
            )
        self.cfg = cfg
        self.session = session
        self.spans = spans
        self.profiler = profiler
        self.depth = int(cfg.pipeline_depth)
        self.num_rounds = int(num_rounds)
        self.steps_per_epoch = int(
            steps_per_epoch if steps_per_epoch is not None
            else sampler.steps_per_epoch()
        )
        self._use_idx = getattr(session, "_dev_data", None) is not None
        self._sampler = sampler
        self._lr_fn = lr_fn
        self._prefetcher: Optional[RoundPrefetcher] = None
        # running telemetry sums (bench/stats; per-round scalars ride the
        # metric dicts at telemetry_level >= 1)
        self._rounds = 0
        self._stall_ms_sum = 0.0
        self._occupancy_sum = 0.0
        self._host_ms_sum = 0.0
        self.quiesces = 0
        self.restarts = 0  # resilience recovery fences (restart())
        if session.controller is not None:
            session.controller.add_switch_listener(self._on_rung_switch)

    # -- lifecycle ---------------------------------------------------------
    def _build_prefetcher(self, start_step: int) -> RoundPrefetcher:
        return RoundPrefetcher(
            session=self.session,
            sampler=self._sampler,
            lr_fn=self._lr_fn,
            depth=self.depth,
            start_step=int(start_step),
            stop_step=self.num_rounds,
            microbatches=getattr(self.cfg, "round_microbatches", 0),
            use_indices=self._use_idx,
            spans=self.spans,
            # rounds the session has already executed realize as replays
            # (transient chaos suppressed) — 0 on a fresh start, the
            # session's horizon after a recovery restart
            replay_until=getattr(self.session, "_replay_horizon", 0),
        ).start()

    def start(self, resume_step: int = 0) -> "PipelinedRounds":
        """Start the run-long prefetcher at ``resume_step`` (the global
        round the loop will dispatch next — a resumed run's restored
        step). Idempotent per engine; call once before the epoch loop."""
        if self._prefetcher is None:
            self._prefetcher = self._build_prefetcher(resume_step)
        return self

    def restart(self, step: int) -> None:
        """Resilience recovery fence: the in-flight window staged FUTURE
        rounds of a trajectory a rollback just rewound, so — exactly like
        a checkpoint fence — quiesce it (stop + join the worker, drop the
        staged work) and restage from ``step``, the rollback target. The
        replayed rounds realize their envs with replay=True via the
        session's horizon, and the new window dispatches through the same
        prewarmed programs (zero retraces)."""
        if self._prefetcher is None:
            raise RuntimeError("PipelinedRounds.restart before start()")
        self._prefetcher.close()
        self._prefetcher = self._build_prefetcher(step)
        self.restarts += 1
        if self.spans is not None:
            with self.spans.span(f"pipeline_recovery_restart:round{step}",
                                 step=int(step)):
                pass

    def close(self) -> None:
        """Stop + join the prefetch worker (crash paths included — the
        runner calls this in its finally block)."""
        if self._prefetcher is not None:
            self._prefetcher.close()

    # -- the per-epoch round source (what the runner iterates) -------------
    def epoch_rounds(self, epoch: int, start_step: int):
        """Yield ``(step, lr, metrics)`` for epoch ``epoch``'s rounds at or
        past ``start_step``, dispatching each through the session exactly
        as the synchronous loop would (same controller barrier, same
        metric dict — plus the ``pipeline/*`` scalars at level >= 1)."""
        if self._prefetcher is None:
            raise RuntimeError("PipelinedRounds.epoch_rounds before start()")
        spe = self.steps_per_epoch
        for step in range(max(epoch * spe, start_step), (epoch + 1) * spe):
            staged = self._prefetcher.staged_rounds
            t0 = time.perf_counter()
            work = self._prefetcher.get(step)  # re-raises worker faults
            stall_ms = (time.perf_counter() - t0) * 1e3
            if self.profiler is not None:
                self.profiler.step(step)
            if self.spans is not None:
                self.spans.step(step)
            metrics = self._dispatch(work)
            occupancy = staged / self.depth
            self._rounds += 1
            self._stall_ms_sum += stall_ms
            self._occupancy_sum += occupancy
            self._host_ms_sum += work.host_ms
            if self.cfg.telemetry_level >= 1:
                # constant key set across the run, as pack_metric_dicts
                # requires (the xla/retraces discipline)
                metrics = {
                    **metrics,
                    "pipeline/occupancy": float(occupancy),
                    "pipeline/host_stall_ms": float(stall_ms),
                    "pipeline/staged_rounds": float(staged),
                }
            yield step, work.lr, metrics

    def _dispatch(self, work):
        sess = self.session
        if self._use_idx:
            return sess.train_round_indices(
                work.client_ids, work.idx, work.plan, work.lr, env=work.env
            )
        return sess.train_round(
            work.client_ids, work.batch, work.lr, env=work.env,
            cohort=work.cohort,
        )

    # -- rung-switch quiesce marker ----------------------------------------
    def _on_rung_switch(self, step: int, old: int, new: int) -> None:
        """Controller switch listener: the staged window needs no
        restaging (rung-invariant inputs; prewarmed per-rung programs),
        so the quiesce is an accounting/span marker, not a flush."""
        self.quiesces += 1
        if self.spans is not None:
            with self.spans.span(f"pipeline_quiesce:rung{old}->rung{new}",
                                 step=step):
                pass

    # -- aggregate stats (bench.py's sketch_pipelined leg) -----------------
    def stats(self) -> dict:
        n = max(self._rounds, 1)
        return {
            "rounds": self._rounds,
            "occupancy": self._occupancy_sum / n,
            "host_stall_ms": self._stall_ms_sum / n,
            "prefetch_host_ms": self._host_ms_sum / n,
            "quiesces": self.quiesces,
        }
