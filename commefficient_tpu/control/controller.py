"""BudgetController — the closed loop that owns rung dispatch + accounting.

Placement in the round pipeline (FederatedSession.train_round*):

    fs_env, fs_stats = session._fedsim_round_env(...)   # host masks
    controller.on_round_start(round_clock, fs_stats)    # decide + switch
    session.round_fn(...)                               # ACTIVE rung's
                                                        # prewarmed program

``on_round_start`` runs BEFORE dispatch, entirely host-side: it asks the
policy for the next rung, clamps the choice against the byte budget
(raising ``BudgetExhaustedError`` before the offending round ever runs),
switches the session's active rung when the decision changed (a
dispatch-table swap of the AOT-prewarmed per-rung round program plus a
``Compressor.migrate_state`` pass over the server-state leaves — never a
retrace), and accounts the round's bytes with EXACTLY the CommLedger's
arithmetic (live-count-aware under fedsim masking), so the controller's
budget view and the ledger can never disagree.

Telemetry flows the other way at drain time: ``observe_drained`` feeds
each drained round's ``diag/*`` scalars to the policy (the ``ef_feedback``
loop's input), and ``scalars()`` rides ``control/rung`` /
``control/switches`` / ``control/budget_remaining_bytes`` on every round's
metric dict — which is also how the per-rung ledger accounting recovers
the active rung per drained round.

Controller state (active rung, switch count, byte spend, policy slots) is
a small float64 blob carried in checkpoints (utils/checkpoint.py), so a
resumed run reproduces the uninterrupted run's rung sequence bit-exactly:
decisions are pure functions of (blob state, round index, drained
telemetry), and drains happen before checkpoint saves.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from commefficient_tpu.control.policy import (
    BudgetExhaustedError,
    DecisionContext,
    FixedPolicy,
    get_policy,
)

_BLOB_VERSION = 3
# blob layout: [version, rung, switches, rounds_seen, spent_up, spent_down,
#               last_switch_round, min_rung, fleet_width, async_k, async_c,
#               retunes, last_retune_round, *policy slots] — float64 is
# exact for every field (byte counts stay far below 2^53). v2 added the
# resilience demotion floor ``min_rung`` at index 7; v3 adds the fleet
# width at capture at index 8 (-1 when the run schedules no fleet events;
# ADVISORY — restore re-derives the width from the round schedule) and
# the asyncfed retune state at 9-12. Older blobs still load, with the
# missing fields defaulting (floor 0; config-initial K/C, zero retunes).
_BLOB_FIXED = 13
_BLOB_FIXED_V2 = 8
_BLOB_FIXED_V1 = 7


class BudgetController:
    """One per session when ``cfg.control_policy != 'none'``."""

    def __init__(self, cfg, session, num_rounds: int):
        self.cfg = cfg
        self.session = session
        self.num_rounds = int(num_rounds)
        self.policy = get_policy(cfg)
        if isinstance(self.policy, FixedPolicy):
            # schedule round ranges vs the run length — only the train
            # loop knows it (same late validation as fedsim chaos rounds)
            self.policy.validate_rounds(self.num_rounds)
        self.num_rungs = len(session.rungs)
        self.budget_bytes: Optional[int] = (
            int(cfg.budget_mb * 1_000_000) if cfg.budget_mb > 0 else None
        )
        self.masked = bool(cfg.fedsim_enabled)
        self._bytes = [session.rung_bytes_per_round(i)
                       for i in range(self.num_rungs)]
        self._comps = [r.compressor for r in session.rungs]
        self.switches = 0
        self.rounds_seen = 0
        self.spent_up = 0
        self.spent_down = 0
        self.last_switch_round = -1
        # resilience demotion floor (resilience/policy.py DemotePolicy):
        # rung indices below it are off-limits — a divergence-driven
        # degradation that outlives the policy's own decisions (every
        # on_round_start clamps to it) and rides the checkpoint blob so a
        # resumed run stays demoted.
        self.min_rung = 0
        # rung-switch observers (pipeline/engine.py registers one): called
        # host-side, AFTER the dispatch-table swap + state migration and
        # BEFORE the round dispatches — the pipelined engine's quiesce
        # point. ``on_round_start`` stays a PRE-STAGING barrier in the
        # pipeline sense: staged work is rung-INVARIANT (batch geometry,
        # env masks and lr never depend on the rung), so a switch
        # invalidates nothing in the in-flight window, and every rung's
        # program is AOT-prewarmed — the listener lets the engine account/
        # span the quiesce without re-deriving any of that.
        self._switch_listeners = []
        # asyncfed (K, C) retune state (staleness_aware policy): the
        # controller owns the authoritative pair — the engine registers a
        # retune listener and rebuilds its arrival schedule when the pair
        # moves. Present (at the config's initial values) for every
        # policy; only ADAPTS_ASYNC policies ever move it.
        self.async_k = int(cfg.async_buffer)
        self.async_c = int(cfg.async_concurrency)
        self.retunes = 0
        self.last_retune_round = -1
        self._retune_listeners = []
        session.controller = self

    def add_switch_listener(self, fn) -> None:
        """Register ``fn(step, old_rung, new_rung)``, called at each rung
        switch (see ``_switch_listeners`` above). Listeners must be pure
        observers — raising would abort the round the switch serves."""
        self._switch_listeners.append(fn)

    def add_retune_listener(self, fn) -> None:
        """Register ``fn(step, k, c)``, called when an ADAPTS_ASYNC
        policy moves the asyncfed (buffer K, concurrency C) pair — the
        engine's hook for rebuilding its pre-simulated arrival schedule.
        Same observer discipline as the switch listeners."""
        self._retune_listeners.append(fn)

    # -- byte accounting (mirrors telemetry.CommLedger exactly) ------------
    def _live_avail(self, fs_stats: Optional[Dict[str, float]]):
        s = fs_stats or {}
        # elastic-fleet rounds account at the round's REALIZED width (the
        # fedsim/* rates are relative to it) — exactly CommLedger._counts
        W = int(round(float(s.get("fleet/width", self.cfg.num_workers))))
        rate = s.get("fedsim/participation_rate")
        live = W if rate is None else int(round(float(rate) * W))
        avail = W - int(round(float(s.get("fedsim/dropped", 0.0))))
        return live, avail

    def round_bytes(self, rung: int, live: int, avail: int) -> int:
        """One round's ledger bytes at ``rung`` given the realized
        participation — the same arithmetic CommLedger.on_round applies,
        through the same ``masked_upload_floats`` compressor hook."""
        bpr = self._bytes[rung]
        if self.masked:
            # bytes-per-float through the compressor hook, like the
            # ledger (ledger.py on_round): 2 B/float for bf16 sketch
            # tables — a hardcoded 4 would double-bill those runs and
            # fire BudgetExhaustedError at half the real budget
            comp = self._comps[rung]
            up = (comp.upload_bytes_per_float()
                  * comp.masked_upload_floats(live))
            down = avail * bpr["download_bytes"]
        else:
            up, down = bpr["upload_bytes"], bpr["download_bytes"]
        return int(up) + int(down)

    def _spend(self, rung: int, live: int, avail: int) -> None:
        bpr = self._bytes[rung]
        if self.masked:
            comp = self._comps[rung]
            self.spent_up += (comp.upload_bytes_per_float()
                              * comp.masked_upload_floats(live))
            self.spent_down += avail * bpr["download_bytes"]
        else:
            self.spent_up += bpr["upload_bytes"]
            self.spent_down += bpr["download_bytes"]

    @property
    def spent_bytes(self) -> int:
        return self.spent_up + self.spent_down

    # -- the per-round decision --------------------------------------------
    def on_round_start(self, step: int,
                       fs_stats: Optional[Dict[str, float]] = None) -> int:
        """Pick (and switch to) the rung round ``step`` dispatches at;
        returns it. Raises ``BudgetExhaustedError`` when even the cheapest
        rung would overshoot the budget — BEFORE the round runs."""
        live, avail = self._live_avail(fs_stats)
        rung = self.session.active_rung
        s = fs_stats or {}
        # buffered-async per-update signals (asyncfed/engine.py rides them
        # in fs_stats unconditionally) — None on synchronous rounds
        stale = s.get("async/staleness_mean")
        eff = s.get("async/effective_participation")
        fill = s.get("async/buffer_fill")
        ctx = DecisionContext(
            step=step, num_rounds=self.num_rounds, rung=rung,
            num_rungs=self.num_rungs,
            round_bytes=lambda r: self.round_bytes(r, live, avail),
            spent_bytes=self.spent_bytes, budget_bytes=self.budget_bytes,
            last_switch_round=self.last_switch_round,
            hysteresis=self.cfg.control_hysteresis,
            staleness_mean=None if stale is None else float(stale),
            effective_participation=None if eff is None else float(eff),
            buffer_fill=None if fill is None else float(fill),
            num_workers=self.cfg.num_workers,
        )
        target = self.policy.decide(ctx)
        target = min(max(int(target), 0), self.num_rungs - 1)
        # resilience demotion floor: a divergence-demoted run never climbs
        # back above the floor, whatever the policy says (higher index ==
        # cheaper rung, so the clamp is a max)
        target = max(target, self.min_rung)
        if self.budget_bytes is not None:
            # hard clamp, policy-independent: demote to the most expensive
            # rung that still fits the remaining budget; nothing fits ->
            # stop before dispatching a round the cap cannot pay for
            while (target < self.num_rungs
                   and self.spent_bytes + self.round_bytes(
                       target, live, avail) > self.budget_bytes):
                target += 1
            if target >= self.num_rungs:
                cheapest = self.num_rungs - 1
                raise BudgetExhaustedError(
                    step=step, budget_bytes=self.budget_bytes,
                    spent_bytes=self.spent_bytes,
                    cheapest_round_bytes=self.round_bytes(
                        cheapest, live, avail),
                    rung=cheapest,
                )
        if target != rung:
            self.session.set_active_rung(target, migrate=True)
            self.switches += 1
            self.last_switch_round = step
            for fn in self._switch_listeners:
                fn(step, rung, target)
        if self.policy.ADAPTS_ASYNC:
            self._maybe_retune(step, ctx)
        self._spend(target, live, avail)
        self.rounds_seen += 1
        return target

    def _maybe_retune(self, step: int, ctx: DecisionContext) -> None:
        """Ask an ADAPTS_ASYNC policy for the next asyncfed (K, C) pair,
        clamp it to the engine's legality window (1 <= K <= W, C >= 1),
        and notify the retune listeners on a change. Hysteresis mirrors
        the rung walk's: no retune within ``control_hysteresis`` rounds
        of the last one, so the schedule rebuild cannot thrash."""
        if (self.last_retune_round >= 0
                and step - self.last_retune_round
                < self.cfg.control_hysteresis):
            return
        k, c = self.policy.decide_async(ctx, self.async_k, self.async_c)
        k = min(max(int(k), 1), int(self.cfg.num_workers))
        c = max(int(c), 1)
        if (k, c) == (self.async_k, self.async_c):
            return
        self.async_k, self.async_c = k, c
        self.retunes += 1
        self.last_retune_round = step
        for fn in self._retune_listeners:
            fn(step, k, c)

    def demote(self, step: int) -> int:
        """Resilience recovery action (resilience/policy.py DemotePolicy):
        floor the ladder one rung cheaper than the CURRENT rung and switch
        to it now — through the same AOT-prewarmed ``set_active_rung`` +
        ``migrate_state`` path as a policy switch, so the demotion is
        never a retrace. Returns the new active rung (== the old one iff
        already at the cheapest rung, in which case nothing changes and
        the caller treats the demotion as unavailable)."""
        old = self.session.active_rung
        # descend from the EFFECTIVE rung — the active rung clamped to
        # the floor: a rollback may have re-activated a pre-demotion rung
        # from a stale snapshot blob, but every on_round_start clamps
        # back to the floor, so one-cheaper-than-effective is the true
        # descent (repeated recoveries walk DOWN the ladder, never replay
        # the rung that just diverged)
        effective = max(old, self.min_rung)
        target = min(effective + 1, self.num_rungs - 1)
        if target == effective:
            # already floored at the cheapest rung — return the active
            # rung unchanged so the caller sees the demotion as
            # unavailable
            return old
        self.min_rung = max(self.min_rung, target)
        self.session.set_active_rung(target, migrate=True)
        self.switches += 1
        self.last_switch_round = int(step)
        for fn in self._switch_listeners:
            fn(int(step), old, target)
        return target

    # -- telemetry ---------------------------------------------------------
    def scalars(self) -> Dict[str, float]:
        """Host scalars riding THIS round's metric dict (constant key set,
        as pack_metric_dicts requires). ``control/rung`` is the rung the
        round ran at — the per-rung ledger accounting recovers it from
        here; ``budget_remaining_bytes`` is what is left AFTER this
        round's spend (only emitted when a budget is set — constant across
        the run either way)."""
        out = {
            "control/rung": float(self.session.active_rung),
            "control/switches": float(self.switches),
        }
        if self.budget_bytes is not None:
            out["control/budget_remaining_bytes"] = float(
                self.budget_bytes - self.spent_bytes
            )
        if self.policy.ADAPTS_ASYNC:
            # (K, C) decision trail (schema v13) — capability-gated, so
            # the key set stays constant for the run either way
            out["control/async_k"] = float(self.async_k)
            out["control/async_c"] = float(self.async_c)
            out["control/retunes"] = float(self.retunes)
        return out

    def observe_drained(self, step: int, scalars: Dict[str, float]) -> None:
        """Drain rider (utils.logging.drain_round_metrics): feed one
        drained round's scalars to the policy, in step order."""
        self.policy.observe(step, scalars)

    def snapshot(self) -> dict:
        """The controller block flight dumps and the metrics run-header
        carry — enough to attribute a divergence to a rung switch."""
        out = {
            "policy": self.cfg.control_policy,
            "ladder": self.cfg.ladder,
            "rung": int(self.session.active_rung),
            "num_rungs": self.num_rungs,
            "switches": int(self.switches),
            "rounds_seen": int(self.rounds_seen),
            "last_switch_round": int(self.last_switch_round),
        }
        if self.budget_bytes is not None:
            out["budget_bytes"] = int(self.budget_bytes)
            out["budget_remaining_bytes"] = int(
                self.budget_bytes - self.spent_bytes
            )
        if getattr(self.cfg, "fleet_enabled", False):
            out["fleet_width"] = int(
                getattr(self.session, "_fleet_width", self.cfg.num_workers)
            )
        if self.policy.ADAPTS_ASYNC:
            out["async_k"] = int(self.async_k)
            out["async_c"] = int(self.async_c)
            out["retunes"] = int(self.retunes)
        return out

    def describe(self) -> str:
        bits = [f"policy={self.cfg.control_policy}",
                f"rungs={self.num_rungs}",
                f"start_rung={self.session.active_rung}"]
        if self.budget_bytes is not None:
            bits.append(f"budget={self.budget_bytes / 1e6:g} MB")
        return "control: " + " ".join(bits)

    # -- prewarm (zero mid-run retraces) -----------------------------------
    def prewarm(self, sampler, lr: float) -> int:
        """AOT-lower every rung's round program for the run's REAL round-0
        signature (FederatedSession.prewarm_rungs), so a later rung switch
        dispatches an already-traced program and the RetraceSentinel's
        per-rung signature streams are seeded — any later signature drift
        is a counted (or hard-failed) retrace, never a silent one."""
        session = self.session
        if getattr(session, "_dev_data", None) is not None:
            ids, idx, plan = sampler.sample_round_indices(0)
            return session.prewarm_rungs_indices(ids, idx, plan, lr)
        ids, batch = sampler.sample_round(0)
        L = getattr(self.cfg, "round_microbatches", 0)
        if L:  # fedavg [W, L, B/L, ...] convention
            batch = {
                k: v.reshape(v.shape[0], L, v.shape[1] // L, *v.shape[2:])
                for k, v in batch.items()
            }
        return session.prewarm_rungs(ids, batch, lr)

    # -- checkpoint state --------------------------------------------------
    def state_blob(self) -> np.ndarray:
        # fleet width at capture (v3, ADVISORY — see load): -1 marks a
        # run with no fleet events, so forensics can tell "fleet off"
        # from "fleet at base width"
        fleet_w = (
            int(getattr(self.session, "_fleet_width", self.cfg.num_workers))
            if getattr(self.cfg, "fleet_enabled", False) else -1
        )
        return np.asarray(
            [_BLOB_VERSION, self.session.active_rung, self.switches,
             self.rounds_seen, self.spent_up, self.spent_down,
             self.last_switch_round, self.min_rung, fleet_w,
             self.async_k, self.async_c, self.retunes,
             self.last_retune_round, *self.policy.state()],
            np.float64,
        )

    def load_state_blob(self, blob) -> None:
        blob = np.asarray(blob, np.float64)
        version = int(blob[0])
        if version not in (1, 2, _BLOB_VERSION):
            raise ValueError(
                f"controller checkpoint blob version {version} != "
                f"{_BLOB_VERSION} — checkpoint from an incompatible build"
            )
        fixed = {1: _BLOB_FIXED_V1, 2: _BLOB_FIXED_V2,
                 _BLOB_VERSION: _BLOB_FIXED}[version]
        want = fixed + self.policy.STATE_SLOTS
        if blob.shape != (want,):
            raise ValueError(
                f"controller checkpoint blob has shape {blob.shape}, "
                f"expected ({want},) for policy "
                f"{self.cfg.control_policy!r} — the checkpoint was written "
                "under a different control config"
            )
        rung = int(blob[1])
        if not 0 <= rung < self.num_rungs:
            raise ValueError(
                f"controller checkpoint names rung {rung}, but this "
                f"session's ladder has {self.num_rungs} rung(s) — restore "
                "with the ladder the checkpoint was written under"
            )
        # the restored FedState leaves are ALREADY in the saved rung's
        # layout (the checkpoint template matched) — swap dispatch only
        self.session.set_active_rung(rung, migrate=False)
        self.switches = int(blob[2])
        self.rounds_seen = int(blob[3])
        self.spent_up = int(blob[4])
        self.spent_down = int(blob[5])
        self.last_switch_round = int(blob[6])
        # v1 blobs (pre-resilience) carry no demotion floor — default 0.
        # Monotone on purpose: a resilience rollback may load a snapshot
        # blob captured BEFORE a demote recovery raised the floor, and
        # the floor must outlive that rewind (else a second divergence in
        # the same window re-demotes to the same rung forever instead of
        # descending the ladder). A fresh controller starts at 0, so a
        # checkpoint resume still adopts the saved floor exactly.
        self.min_rung = max(self.min_rung,
                            0 if version == 1 else int(blob[7]))
        if version >= 3:
            # blob[8] (fleet width at capture) is ADVISORY: the session
            # re-derives the width from the round schedule in
            # sync_round_clock, which runs on every restore path — a
            # stale width here must never override the pure schedule
            self.async_k = int(blob[9])
            self.async_c = int(blob[10])
            self.retunes = int(blob[11])
            self.last_retune_round = int(blob[12])
            for fn in self._retune_listeners:
                fn(self.last_retune_round, self.async_k, self.async_c)
        self.policy.load_state(tuple(blob[fixed:]))


def build_controller(cfg, session, num_rounds: int) -> Optional[
        BudgetController]:
    """The single construction gate (mirrors fedsim.build_environment):
    a controller iff the config turns the control plane on; None keeps
    every caller on the untouched fast path."""
    if not getattr(cfg, "control_enabled", False):
        return None
    return BudgetController(cfg, session, num_rounds)


def controller_header(session) -> dict:
    """The run-header/flight controller block for a session — available at
    SESSION build (before the controller exists; MetricsWriter writes its
    header at construction), so it reports the initial rung and the static
    ladder/policy identity. ``{}`` for control-less sessions."""
    rungs = getattr(session, "rungs", None)
    if rungs is None or not getattr(session.cfg, "control_enabled", False):
        return {}
    return {"controller": {
        "policy": session.cfg.control_policy,
        "ladder": session.cfg.ladder,
        "rung": int(session.active_rung),
        "num_rungs": len(rungs),
    }}
