"""Compression ladder — the ordered rung set a controller switches between.

Ladder grammar (the ``--ladder`` flag, same validate-at-construction
discipline as fedsim's chaos strings):

    field=v1,v2,...[;field=w1,w2,...]

  * ``field`` is one of the rung-tunable compression parameters
    (``LADDER_FIELDS``): ``k``, ``num_cols``, ``powersgd_rank``. Every
    other Config field is shared by all rungs.
  * Each field lists ONE value per rung; multiple fields (``;``-separated)
    must list the same number of values — rung i takes the i-th value of
    every listed field.
  * Rungs must be ordered most-expensive first: rung 0 is the highest-
    fidelity/highest-byte setting and each later rung is strictly cheaper
    (validated against the realized ``bytes_per_round`` at session build,
    where the compressor geometry is known — e.g. the sketch table's
    realized ``r * c_actual``).

Example: ``--ladder "k=60000,30000,10000"`` is a three-rung ladder that
only varies the extraction sparsity;
``--ladder "k=50000,25000;num_cols=500000,250000"`` shrinks the sketch
table along with k.

Each rung resolves to a full ``Config`` via ``base.replace(**overrides)``
at parse time, so an invalid rung (e.g. ``powersgd_rank=0``) fails with
the Config's own validation error, named per rung, before anything is
built. Layering: this module is host-side and duck-types the config (same
no-cycle pattern as fedsim — ``utils.config`` imports it lazily for flag
validation).
"""

from __future__ import annotations

from typing import Tuple

# Config fields a rung may override. Everything here changes only the
# compression OPERATING POINT (payload size / extraction sparsity), never
# the federation shape or the optimization semantics — that is what makes
# a mid-run switch meaningful rather than a different experiment.
LADDER_FIELDS = ("k", "num_cols", "powersgd_rank")

_GRAMMAR = (
    '";"-separated "field=v1,v2,..." lists with field in '
    f"{LADDER_FIELDS} and one value per rung (all fields the same "
    'length), e.g. "k=60000,30000,10000" or '
    '"k=50000,25000;num_cols=500000,250000"'
)


def _fail(spec: str, why: str) -> ValueError:
    return ValueError(f"bad ladder {spec!r}: {why}. Grammar: {_GRAMMAR}")


def parse_ladder(spec: str) -> Tuple[dict, ...]:
    """Parse a ladder string into one override dict per rung; '' -> ().
    Raises ValueError (with the grammar) on any syntax problem."""
    if not spec or not spec.strip():
        return ()
    fields = {}
    for raw in spec.split(";"):
        part = raw.strip()
        if "=" not in part:
            raise _fail(spec, f"segment {part!r} lacks '=values'")
        name, _, vals_s = part.partition("=")
        name = name.strip()
        if name not in LADDER_FIELDS:
            raise _fail(spec, f"unknown ladder field {name!r}")
        if name in fields:
            raise _fail(spec, f"field {name!r} listed twice")
        vals = []
        for v in vals_s.split(","):
            v = v.strip()
            try:
                vals.append(int(v))
            except ValueError:
                raise _fail(
                    spec, f"{name}={v!r} is not an integer"
                ) from None
        if not vals:
            raise _fail(spec, f"field {name!r} lists no values")
        if any(v < 1 for v in vals):
            raise _fail(spec, f"{name} values must be >= 1, got {vals}")
        fields[name] = vals
    lengths = {len(v) for v in fields.values()}
    if len(lengths) != 1:
        raise _fail(
            spec,
            "every field must list one value per rung — got lengths "
            + ", ".join(f"{k}:{len(v)}" for k, v in sorted(fields.items())),
        )
    n = lengths.pop()
    return tuple(
        {name: vals[i] for name, vals in fields.items()} for i in range(n)
    )


def ladder_configs(cfg) -> tuple:
    """The per-rung Config tuple for ``cfg``: one ``cfg.replace(**rung)``
    per parsed rung, or ``(cfg,)`` when the ladder is empty (a controller
    over a single implicit rung — pure budget enforcement). Each rung's
    replace re-runs Config validation, so an override combination the base
    config would reject (e.g. a sketch envelope violation stays a warning,
    but ``powersgd_rank=0`` is an error) fails HERE with the rung named."""
    rungs = parse_ladder(cfg.ladder)
    if not rungs:
        return (cfg,)
    out = []
    for i, ov in enumerate(rungs):
        try:
            out.append(cfg.replace(**ov))
        except ValueError as e:
            raise ValueError(
                f"ladder rung {i} ({ov}) produces an invalid config: {e}"
            ) from e
    return tuple(out)


def validate_rung_costs(bytes_per_rung) -> None:
    """Enforce the ladder's cost ordering: per-round total bytes
    NON-INCREASING with rung index (rung 0 = most expensive / highest
    fidelity). Policies lean on this — ``ef_feedback`` steps index-1 to
    SPEND more and index+1 to SAVE, and ``budget_pacing`` scans from 0
    for the most expensive affordable rung. Ties are legal: a sketch
    ``k`` ladder moves the extraction fidelity without touching the
    table's link bytes (FetchSGD accounting: the uplink IS the table) —
    byte-identical rungs still order by fidelity for the ef loop, they
    are just indistinguishable to pacing. ``bytes_per_rung`` is a
    sequence of bytes_per_round dicts in rung order (the session computes
    them from each rung's realized compressor geometry)."""
    totals = [
        int(b["upload_bytes"]) + int(b["download_bytes"])
        for b in bytes_per_rung
    ]
    for i in range(1, len(totals)):
        if totals[i] > totals[i - 1]:
            raise ValueError(
                f"ladder rung {i} costs {totals[i]:,} B/round, MORE than "
                f"rung {i - 1} ({totals[i - 1]:,} B/round) — order rungs "
                "most-expensive first (the realized cost can differ from "
                "the request, e.g. the sketch table's blocked layout; "
                f"per-rung totals: {totals})"
            )
