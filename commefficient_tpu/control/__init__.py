"""Adaptive communication-budget control plane.

FetchSGD (arXiv:2007.07682) fixes its compression operating point (k,
sketch columns, powersgd rank) once per run, but the EF analysis it leans
on (arXiv:1903.04488; sharpened by arXiv:2305.15264) says the USEFUL
compression level varies over training — early rounds tolerate aggressive
compression, late rounds pay for it in error-feedback residual growth.
This repo already measures those signals (``diag/ef_residual_norm``,
level-2 fidelity, fedsim participation, the audited per-round bytes);
this package closes the loop:

  * ``ladder``     — an ordered rung set, each rung a validated
                     compression-parameter delta over the base Config
                     (``--ladder "k=60000,30000,10000"``). Every rung's
                     round program is resolved at session build and
                     AOT-prewarmed for the run's real round signature, so
                     a rung switch is a dispatch-table lookup — NEVER a
                     silent mid-run retrace (per-rung RetraceSentinel
                     signature streams pin it).
  * ``policy``     — pluggable host-side rung selection: ``fixed``
                     (round-range schedule), ``budget_pacing`` (spend
                     ``--budget_mb`` evenly over the remaining rounds,
                     hard-stopping with ``BudgetExhaustedError`` when even
                     the cheapest rung would overshoot), ``ef_feedback``
                     (closed loop on EF-residual slope + fidelity, with
                     hysteresis).
  * ``controller`` — the loop itself: reads drained telemetry, picks next
                     round's rung, migrates compressor-private state
                     across rungs (``Compressor.migrate_state``), emits
                     ``control/*`` scalars, accounts bytes with exactly
                     the CommLedger's arithmetic, and checkpoints its
                     state so resume reproduces the rung sequence
                     bit-exactly.

``control_policy='none'`` (default) builds NOTHING: the session is
single-rung, no controller exists, and the compiled round is bit-identical
to a pre-control build (golden parity recordings pin it) — the same
python-level gate discipline as ``telemetry_level 0`` and
``availability='always'``.

Layering: host-side logic over compress/-provided accounting hooks;
``parallel/api.py`` and the train entries import this package,
``utils/config.py`` imports ``ladder``/``policy`` lazily for flag
validation (the fedsim no-cycle pattern). Policy-string dispatch lives in
``policy.py`` (and config validation) ONLY — enforced by
scripts/check_mode_dispatch.py.
"""

from commefficient_tpu.control.controller import (
    BudgetController,
    build_controller,
    controller_header,
)
from commefficient_tpu.control.ladder import (
    LADDER_FIELDS,
    ladder_configs,
    parse_ladder,
    validate_rung_costs,
)
from commefficient_tpu.control.policy import (
    CONTROL_POLICIES,
    BudgetExhaustedError,
    ControlPolicy,
    get_policy,
    initial_rung_index,
    parse_schedule,
)

__all__ = [
    "BudgetController",
    "BudgetExhaustedError",
    "CONTROL_POLICIES",
    "ControlPolicy",
    "LADDER_FIELDS",
    "build_controller",
    "controller_header",
    "get_policy",
    "initial_rung_index",
    "ladder_configs",
    "parse_ladder",
    "parse_schedule",
    "validate_rung_costs",
]
