"""Host-side rung-selection policies + the byte-budget hard stop.

A policy is pure host logic deciding WHICH ladder rung the next round
dispatches; it never touches device state (the controller owns migration
and dispatch). Three are registered, mirroring the compress/ registry
discipline — policy-string branching lives HERE (and in utils/config.py's
validation), enforced by scripts/check_mode_dispatch.py:

  * ``fixed``          — a round-range schedule (``--control_schedule
                         "0-99=2,100-=0"``): deterministic rung per round
                         index, the control-plane analog of a piecewise lr
                         schedule.
  * ``budget_pacing``  — spend the remaining ``--budget_mb`` evenly over
                         the remaining rounds: each round it picks the most
                         expensive rung whose per-round bytes fit the
                         remaining-budget/remaining-rounds allowance, so
                         the run drops down the ladder as the ledger's
                         cumulative bytes approach the cap.
  * ``staleness_aware`` — closed loop on the buffered-async telemetry
                         (``async/staleness_mean`` band, plus the
                         normalized buffer backlog): walks the ladder
                         DOWN (cheaper rung) while cohorts arrive stale,
                         climbs back when they are fresh, and adapts the
                         engine's (K, C) pair toward the target band via
                         the controller's retune listeners. asyncfed-only
                         (Config-validated).
  * ``ef_feedback``    — closed loop on the error-feedback telemetry
                         (``diag/ef_residual_norm`` slope, plus any level-2
                         ``*_rel_err`` fidelity scalar): climbs to a more
                         expensive rung when the EF bank grows faster than
                         ``control_ef_up`` (compression is eating signal
                         the bank can't keep absorbing — the arXiv:2305.15264
                         EF-growth regime), steps to a cheaper rung when
                         the slope falls below ``control_ef_down``.
                         ``control_hysteresis`` rounds must pass between
                         switches, and the up/down thresholds are distinct,
                         so the loop cannot oscillate every round
                         (tests/test_control.py pins the property).

Every policy decision is a pure function of (policy state, round index,
drained telemetry history) — the controller checkpoints that state, so a
resumed run reproduces the uninterrupted run's rung sequence bit-exactly.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

CONTROL_POLICIES = ("none", "fixed", "budget_pacing", "ef_feedback",
                    "staleness_aware")

_SCHEDULE_GRAMMAR = (
    'comma-separated "A-B=rung" round ranges (B empty = open-ended, '
    'e.g. "0-99=2,100-199=1,200-=0"); ranges must ascend and not overlap'
)


class BudgetExhaustedError(RuntimeError):
    """The byte budget cannot admit another round even at the cheapest
    rung. Raised BEFORE the offending round is dispatched, so the ledger's
    cumulative bytes never exceed the cap."""

    def __init__(self, *, step: int, budget_bytes: int, spent_bytes: int,
                 cheapest_round_bytes: int, rung: int):
        self.step = step
        self.budget_bytes = budget_bytes
        self.spent_bytes = spent_bytes
        super().__init__(
            f"communication budget exhausted at round {step}: "
            f"{spent_bytes:,} B of the {budget_bytes:,} B budget spent, and "
            f"even the cheapest rung ({rung}) needs "
            f"{cheapest_round_bytes:,} B for the next round. The run "
            f"completed {step} full rounds within budget. Raise --budget_mb, "
            "extend the ladder with a cheaper rung, or treat this as the "
            "honest end of a fixed-budget run (scripts/accuracy_run.py "
            "records it as a truncated row)."
        )


def parse_schedule(spec: str) -> Tuple[Tuple[int, Optional[int], int], ...]:
    """``control_schedule`` -> ((start, end_inclusive_or_None, rung), ...).
    Syntax-validated here; rung indices vs the ladder length are checked by
    Config (both strings live there), and round ranges vs the run length by
    the controller at train-entry time (only the train loop knows it)."""

    def fail(why):
        return ValueError(
            f"bad control_schedule {spec!r}: {why}. Grammar: "
            f"{_SCHEDULE_GRAMMAR}"
        )

    if not spec or not spec.strip():
        return ()
    out = []
    for raw in spec.split(","):
        part = raw.strip()
        rng_s, sep, rung_s = part.partition("=")
        if not sep:
            raise fail(f"segment {part!r} lacks '=rung'")
        a, sep2, b = rng_s.partition("-")
        try:
            start = int(a)
            end = int(b) if (sep2 and b.strip()) else (start if not sep2
                                                       else None)
            rung = int(rung_s)
        except ValueError:
            raise fail(f"segment {part!r} is not A-B=rung") from None
        if start < 0 or (end is not None and end < start) or rung < 0:
            raise fail(f"segment {part!r} has a negative/descending range "
                       "or rung")
        if out:
            prev_end = out[-1][1]
            if prev_end is None:
                raise fail("an open-ended range must be last")
            if start <= prev_end:
                raise fail(f"range starting at {start} overlaps the "
                           f"previous range ending at {prev_end}")
        out.append((start, end, rung))
    return tuple(out)


class DecisionContext:
    """What a policy sees each round — assembled by the controller."""

    def __init__(self, *, step: int, num_rounds: int, rung: int,
                 num_rungs: int, round_bytes, spent_bytes: int,
                 budget_bytes: Optional[int], last_switch_round: int,
                 hysteresis: int, staleness_mean: Optional[float] = None,
                 effective_participation: Optional[float] = None,
                 buffer_fill: Optional[float] = None,
                 num_workers: Optional[int] = None):
        self.step = step
        self.num_rounds = num_rounds
        self.rung = rung
        self.num_rungs = num_rungs
        # round_bytes(rung_idx) -> this round's ledger bytes at that rung
        # (live-count-aware under fedsim masking)
        self.round_bytes = round_bytes
        self.spent_bytes = spent_bytes
        self.budget_bytes = budget_bytes
        self.last_switch_round = last_switch_round
        self.hysteresis = hysteresis
        # v8 buffered-async per-update signals (asyncfed/engine.py):
        # None on synchronous rounds. ``staleness_aware`` keys its rung
        # walk and (K, C) retunes off them; every other shipped policy
        # ignores them, so its sync/async rung sequences stay comparable
        # run-to-run. ``buffer_fill`` is the RAW delivered-unconsumed
        # count after the fire (asyncfed/schedule.py buffer_fill_after) —
        # consumers normalize by K themselves.
        self.staleness_mean = staleness_mean
        self.effective_participation = effective_participation
        self.buffer_fill = buffer_fill
        self.num_workers = num_workers


class ControlPolicy:
    """Base policy: never moves. Subclass + add to ``POLICIES``."""

    name = "?"
    # float64 slots this policy persists in the controller's checkpoint
    # blob (beyond the controller's own); loaded back verbatim on resume
    STATE_SLOTS = 0
    # capability, not a mode string (scripts/check_mode_dispatch.py):
    # True when decide_async may move the asyncfed (K, C) pair — the
    # controller then emits control/async_k|async_c|retunes and the
    # engine registers a retune listener
    ADAPTS_ASYNC = False

    def __init__(self, cfg):
        self.cfg = cfg

    def initial_rung(self, num_rungs: int) -> int:
        return 0

    def observe(self, step: int, scalars: Dict[str, float]) -> None:
        """Feed one DRAINED round's scalars (step order). Policies that
        don't consume telemetry ignore it."""

    def decide(self, ctx: DecisionContext) -> int:
        return ctx.rung

    def decide_async(self, ctx: DecisionContext, k: int, c: int):
        """Propose the asyncfed (buffer K, concurrency C) pair for the
        NEXT update — called by the controller only when ``ADAPTS_ASYNC``
        (and clamped/hysteresis-gated there). Base: hold."""
        return k, c

    def state(self) -> tuple:
        return ()

    def load_state(self, slots: tuple) -> None:
        pass


class FixedPolicy(ControlPolicy):
    """Round-range schedule: the rung is a pure function of the round
    index (``parse_schedule``); rounds outside every range stay at rung 0."""

    name = "fixed"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.schedule = parse_schedule(cfg.control_schedule)

    def validate_rounds(self, num_rounds: int) -> None:
        for start, end, rung in self.schedule:
            bad = start if start >= num_rounds else (
                end if end is not None and end >= num_rounds else None
            )
            if bad is not None:
                raise ValueError(
                    f"control_schedule range {start}-"
                    f"{'' if end is None else end}={rung} references round "
                    f"{bad}, but this run has only {num_rounds} rounds "
                    "(steps_per_epoch x num_epochs) — shrink the schedule "
                    "or lengthen the run"
                )

    def rung_at(self, step: int) -> int:
        for start, end, rung in self.schedule:
            if start <= step and (end is None or step <= end):
                return rung
        return 0

    def initial_rung(self, num_rungs: int) -> int:
        return min(self.rung_at(0), num_rungs - 1)

    def decide(self, ctx: DecisionContext) -> int:
        return min(self.rung_at(ctx.step), ctx.num_rungs - 1)


class BudgetPacingPolicy(ControlPolicy):
    """Even pacing against the byte budget: allowance = remaining bytes /
    remaining rounds; pick the most expensive rung that fits it. Monotone
    in practice (the allowance only shrinks when running rich), and the
    controller's hard clamp below it guarantees the cap is never crossed."""

    name = "budget_pacing"

    def decide(self, ctx: DecisionContext) -> int:
        remaining = ctx.budget_bytes - ctx.spent_bytes
        allowance = remaining / max(ctx.num_rounds - ctx.step, 1)
        for r in range(ctx.num_rungs):  # rung 0 = most expensive
            if ctx.round_bytes(r) <= allowance:
                return r
        return ctx.num_rungs - 1


class EfFeedbackPolicy(ControlPolicy):
    """Closed loop on the error-feedback telemetry.

    ``observe`` tracks the per-round relative slope of
    ``diag/ef_residual_norm`` ((ef_t - ef_{t-1}) / max(ef_{t-1}, eps) —
    drain order == step order, so consecutive drained rounds are
    consecutive rounds) and the worst level-2 fidelity scalar (any
    ``diag/*_rel_err``: sketch round-trip error, powersgd reconstruction
    residual). ``decide`` climbs one rung toward more bytes when the slope
    exceeds ``control_ef_up`` or fidelity exceeds ``control_fidelity_max``
    (> 0 to enable), steps one rung cheaper when the slope is below
    ``control_ef_down``, and otherwise holds. Hysteresis: no decision
    within ``control_hysteresis`` rounds of the last switch, and
    ``control_ef_up > control_ef_down`` (Config-validated), so a signal
    sitting between the thresholds holds — the loop cannot flap every
    round. Starts at the CHEAPEST rung (aggressive early compression is
    exactly the regime FetchSGD's own EF dynamics tolerate; the loop
    climbs when the telemetry says otherwise)."""

    name = "ef_feedback"
    STATE_SLOTS = 3  # prev_ef, last_slope, last_fidelity

    def __init__(self, cfg):
        super().__init__(cfg)
        self.prev_ef: Optional[float] = None
        self.last_slope: Optional[float] = None
        self.last_fidelity: Optional[float] = None

    def initial_rung(self, num_rungs: int) -> int:
        return num_rungs - 1

    def observe(self, step: int, scalars: Dict[str, float]) -> None:
        ef = scalars.get("diag/ef_residual_norm")
        if ef is not None and math.isfinite(float(ef)):
            ef = float(ef)
            if self.prev_ef is not None:
                self.last_slope = (ef - self.prev_ef) / max(
                    self.prev_ef, 1e-30
                )
            self.prev_ef = ef
        fids = [
            float(v) for k, v in scalars.items()
            if k.startswith("diag/") and k.endswith("_rel_err")
            and math.isfinite(float(v))
        ]
        if fids:
            self.last_fidelity = max(fids)

    def decide(self, ctx: DecisionContext) -> int:
        if (ctx.last_switch_round >= 0
                and ctx.step - ctx.last_switch_round < ctx.hysteresis):
            return ctx.rung
        cfg = self.cfg
        fid_bad = (
            cfg.control_fidelity_max > 0
            and self.last_fidelity is not None
            and self.last_fidelity > cfg.control_fidelity_max
        )
        if self.last_slope is None and not fid_bad:
            return ctx.rung  # nothing drained yet
        if fid_bad or (self.last_slope is not None
                       and self.last_slope > cfg.control_ef_up):
            return max(ctx.rung - 1, 0)  # climb: spend more bytes
        if (self.last_slope is not None
                and self.last_slope < cfg.control_ef_down):
            return min(ctx.rung + 1, ctx.num_rungs - 1)  # descend: save
        return ctx.rung

    def state(self) -> tuple:
        nan = float("nan")
        return (
            nan if self.prev_ef is None else self.prev_ef,
            nan if self.last_slope is None else self.last_slope,
            nan if self.last_fidelity is None else self.last_fidelity,
        )

    def load_state(self, slots: tuple) -> None:
        def opt(v):
            return None if math.isnan(v) else float(v)

        self.prev_ef, self.last_slope, self.last_fidelity = map(opt, slots)


class StalenessAwarePolicy(ControlPolicy):
    """Closed loop on the buffered-async staleness telemetry.

    Rung walk (``decide``): when ``async/staleness_mean`` sits above
    ``control_staleness_hi``, cohorts are arriving so late that their
    gradients mostly fight the server's newer parameters — spend FEWER
    bytes on them (one rung cheaper per decision); below
    ``control_staleness_lo`` the fleet is keeping up and the loop climbs
    back toward full fidelity. The band is Config-validated open
    (``hi > lo``) and every move honors ``control_hysteresis``, so a
    signal inside the band holds and the loop cannot flap every update
    (tests/test_control.py pins the property, like ``ef_feedback``).

    (K, C) retune (``decide_async``): drives the normalized buffer
    backlog ``buffer_fill / K`` into the ``[control_fill_lo,
    control_fill_hi]`` band — backlog over the band grows K (each server
    aggregate absorbs more of the queue), staleness over its band sheds
    concurrency toward 1 (fewer in-flight cohorts age less) then shrinks
    K once the backlog allows, and a fresh fleet restores concurrency up
    to the configured ``--async_concurrency``. One move per decision;
    the controller clamps to ``1 <= K <= num_workers`` and applies the
    retune hysteresis.

    Stateless on purpose (``STATE_SLOTS = 0``): every decision is a pure
    function of the per-update DecisionContext, so checkpoint resume
    needs only the controller's own (K, C, retunes) slots."""

    name = "staleness_aware"
    ADAPTS_ASYNC = True

    def decide(self, ctx: DecisionContext) -> int:
        if (ctx.last_switch_round >= 0
                and ctx.step - ctx.last_switch_round < ctx.hysteresis):
            return ctx.rung
        stale = ctx.staleness_mean
        if stale is None:
            return ctx.rung  # synchronous round / nothing fired yet
        cfg = self.cfg
        if stale > cfg.control_staleness_hi:
            return min(ctx.rung + 1, ctx.num_rungs - 1)  # cheaper
        if stale < cfg.control_staleness_lo:
            return max(ctx.rung - 1, 0)  # climb back to fidelity
        return ctx.rung

    def decide_async(self, ctx: DecisionContext, k: int, c: int):
        stale, fill = ctx.staleness_mean, ctx.buffer_fill
        if stale is None or fill is None:
            return k, c
        cfg = self.cfg
        norm = float(fill) / max(k, 1)
        if norm > cfg.control_fill_hi and ctx.num_workers is not None \
                and k < ctx.num_workers:
            return k + 1, c  # backlog over band: absorb more per fire
        if stale > cfg.control_staleness_hi:
            if c > 1:
                return k, c - 1  # fewer in-flight cohorts age less
            if norm <= cfg.control_fill_lo and k > 1:
                return k - 1, c  # starved AND stale: fire smaller buffers
            return k, c
        if stale < cfg.control_staleness_lo and c < cfg.async_concurrency:
            return k, c + 1  # fresh fleet: restore configured concurrency
        return k, c


POLICIES = {
    p.name: p for p in (FixedPolicy, BudgetPacingPolicy, EfFeedbackPolicy,
                        StalenessAwarePolicy)
}


def get_policy(cfg) -> ControlPolicy:
    """Construct the policy for ``cfg.control_policy`` (never "none" —
    ``build_controller`` gates that before reaching here)."""
    try:
        cls = POLICIES[cfg.control_policy]
    except KeyError:
        raise ValueError(
            f"unknown control policy {cfg.control_policy!r}; registered: "
            f"{tuple(sorted(POLICIES))}"
        ) from None
    return cls(cfg)


def initial_rung_index(cfg, num_rungs: int) -> int:
    """The rung a fresh session starts on — needed at SESSION build (the
    controller is constructed later, once the train loop knows the run
    length), so it is a pure function of the config."""
    if cfg.control_policy == "none":
        return 0
    return get_policy(cfg).initial_rung(num_rungs)
