"""The asyncfed round programs — one launch, one apply, per rung.

The synchronous round (parallel/round.py) is one fused XLA program:
per-client gradients -> compress -> psum -> server update. Buffered
asynchrony splits it at the only seam the algebra allows — AFTER each
client's transmit is computed, BEFORE anything is summed:

* ``launch_fn`` runs one cohort's per-client half against the params
  snapshot at launch: the [W, D] raw transmit rows (pre-encode, pre-sum),
  the updated per-client momentum/error rows, and the per-client
  loss/aux. It reuses ``make_per_client`` — the exact closure the
  synchronous worker shard vmaps — so a launched row is bit-identical to
  the row the synchronous round would have produced from the same params.

* ``apply_fn`` consumes K rows (padded to a fixed [W, ...] so any buffer
  fill / concurrency compiles ONE program — zero retraces), weights each
  by its staleness discount ``(1+s)^(-alpha)`` times its fedsim live
  mask, sums, device-encodes (linear, so encode(sum w*row) ==
  sum w*encode(row) — the psum-safety contract every compressor already
  signs), and runs the shared aggregation tail + server phase
  (``make_aggregate_tail`` / ``server_phase``). ``server_phase`` sees
  ``count = sum(weights)``: the effective participation the update
  renormalizes by, exactly the fedsim live count when alpha=0.

Bit-identity anchor (K=W, C=1, staleness_exponent=0 == the synchronous
round, pinned across modes by tests/test_asyncfed.py): every weight is
the 0/1 live mask, ``row * 1.0`` is bitwise ``row`` (NaN included),
``jnp.where(w > 0, ., 0.0)`` reproduces the synchronous dead-slot zeros,
the canonical (cohort, slot) consumption order makes the sum's reduction
order the synchronous one, ``fold_in(key, version)`` equals
``fold_in(key, state.step)``, and ``count == live_count`` exactly (small
ints in f32) — so agg, the server algebra, and the params update match
bit for bit.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from commefficient_tpu.compress import get_compressor
from commefficient_tpu.ops.countsketch import CountSketch
from commefficient_tpu.parallel.mesh import (
    worker_axes,
    worker_axis_size,
)
from commefficient_tpu.parallel.round import (
    FedState,
    make_aggregate_tail,
    make_decode_mapped,
    make_grad_one,
    make_per_client,
    resolve_aggregation,
    server_phase,
)
from commefficient_tpu.utils.config import Config
from commefficient_tpu.utils.jax_compat import pcast, shard_map

P = jax.sharding.PartitionSpec


def build_async_round_fns(
    cfg: Config,
    loss_fn: Callable,
    unravel: Callable,
    mesh,
    spec: Optional[CountSketch] = None,
    *,
    d: int,
    launch_hook: Optional[Callable] = None,
    apply_hook: Optional[Callable] = None,
):
    """Build ``(launch_fn, apply_fn)`` for one rung config.

    ``launch_fn(params_vec, client_vel, client_err, client_ids [W],
    batch {k: [W, ...]}, version, lr, env=(live, corrupt)) ->
    (rows [W, D], vel_rows, err_rows, loss_rows [W], aux_rows)`` — jitted,
    donates nothing (params/client state stay live for the next launch).

    ``apply_fn(state, rows, vel_rows, err_rows, loss_rows, aux_rows,
    client_ids [W], weights [W], wsum, lr) -> (new_state, metrics)`` —
    jitted, donates ``state``. ``weights`` are the per-slot staleness
    discounts times the live mask (0 for padding slots); the where-gate
    keeps a zero-weight slot's NaN (corrupt payload, or a padded repeat
    of one) out of the sum. Client vel/err rows write back per slot in
    canonical (cohort, slot) order — deterministic last-wins when two
    consumed contributions carry the same client.

    ``launch_hook``/``apply_hook``: RetraceSentinel trace hooks (pure
    python at trace time, zero traced ops).
    """
    comp = get_compressor(cfg, d=d, spec=spec)
    comp.resolved_dampening()
    W = cfg.num_workers
    f32 = jnp.float32
    lm = cfg.local_momentum
    use_fedsim = bool(cfg.fedsim_enabled)
    grad_one = make_grad_one(cfg, loss_fn, unravel, mesh)
    # multihost meshes: every collective and shard spec below rides the
    # (HOSTS, WORKERS) tuple, same resolution as the synchronous round
    axes = worker_axes(mesh)
    Wd = worker_axis_size(mesh)
    plan = resolve_aggregation(cfg, comp, Wd)
    per_client = make_per_client(cfg, comp, grad_one, use_fedsim=use_fedsim)
    aggregate_tail = make_aggregate_tail(cfg, comp, plan, W=W, Wd=Wd, d=d,
                                         axes=axes)
    decode_mapped = make_decode_mapped(cfg, comp, mesh, plan, d=d, Wd=Wd)

    # ---- launch: the per-client half of worker_shard ---------------------
    def launch_shard(params_vec, batch, client_ids, vel_rows, err_rows, rng,
                     lr, *fs):
        # same vma discipline as the synchronous worker shard: varying
        # params keep AD shard-local so each client sees its own gradient
        params_vec = pcast(params_vec, axes, to="varying")
        return jax.vmap(
            lambda b, cid, vel, err, *fs_: per_client(
                params_vec, b, cid, vel, err, rng, lr, *fs_
            )
        )(batch, client_ids, vel_rows, err_rows, *fs)

    shard_spec = P(axes)
    in_specs = (P(), shard_spec, shard_spec, shard_spec, shard_spec, P(), P())
    if use_fedsim:
        in_specs = in_specs + (shard_spec, shard_spec)  # live mask, corrupt
    launch_mapped = shard_map(
        launch_shard,
        mesh=mesh,
        in_specs=in_specs,
        # raw per-client rows leave sharded: the apply consumes them row-
        # wise, nothing is reduced at launch time
        out_specs=(shard_spec,) * 5,
    )

    def launch_fn(params_vec, client_vel, client_err, client_ids, batch,
                  version, lr, env=()):
        if launch_hook is not None:  # trace time only, no ops
            launch_hook(params_vec, client_ids, batch, version, lr, env=env)
        # rng from the LAUNCH version: at the anchor version == state.step,
        # so fold_in reproduces the synchronous round's stream exactly
        rng = jax.random.fold_in(jax.random.key(cfg.seed), version)
        fs = ()
        if use_fedsim:
            if not env:
                raise ValueError(
                    "fedsim is enabled (cfg.fedsim_enabled) but no env was "
                    "passed — supply env=(live_mask [W], corrupt [W]) from "
                    "the cohort's FedEnvironment.round_env realization "
                    "(asyncfed.AsyncFederation does this)"
                )
            fs = tuple(env)
        # same participant-row gather as the synchronous round_fn
        vel_rows = (
            client_vel[client_ids] if lm > 0 else jnp.zeros((W, 1), f32)
        )
        err_rows = (
            client_err[client_ids]
            if cfg.error_type == "local"
            else jnp.zeros((W, 1), f32)
        )
        return launch_mapped(
            params_vec, batch, client_ids, vel_rows, err_rows, rng, lr, *fs
        )

    # ---- apply: weighted buffer drain + the shared server tail -----------
    def apply_shard(rows, loss_rows, aux_rows, weights):
        w_loc = rows.shape[0]
        wcol = weights[:, None]
        # where, not multiply: a zero-weight slot (dead client, or the
        # fixed-shape padding repeating a consumed slot) contributes
        # EXACTLY 0.0 even when its row is NaN — the same gate the
        # synchronous masked round applies pre-sum. A live slot's
        # row * 1.0 is bitwise the row (alpha=0 anchor).
        contrib = jnp.where(wcol > 0, rows * wcol, 0.0)
        local = jnp.sum(contrib, axis=0)
        loss_local = jnp.sum(jnp.where(weights > 0, loss_rows * weights, 0.0))
        ext = lambda m, a: m.reshape(m.shape + (1,) * (a.ndim - 1))  # noqa: E731
        aux = jax.tree.map(
            lambda a: jnp.sum(
                jnp.where(ext(weights, a) > 0, a * ext(weights, a), 0.0),
                axis=0,
            ),
            aux_rows,
        )
        # encode the weighted sum once per device (linearity: equals the
        # sum of weighted encodings; identical to the synchronous shard's
        # encode-of-sum at the anchor)
        local = comp.device_encode(local)
        return aggregate_tail(local, loss_local, aux, w_loc)

    apply_mapped = shard_map(
        apply_shard,
        mesh=mesh,
        in_specs=(shard_spec, shard_spec, shard_spec, shard_spec),
        out_specs=(shard_spec if plan.sparse_state else P(), P(), P()),
    )

    def apply_fn(state: FedState, rows, vel_rows, err_rows, loss_rows,
                 aux_rows, client_ids, weights, wsum, lr):
        if apply_hook is not None:  # trace time only, no ops
            apply_hook(client_ids, weights, wsum, lr)
        agg, loss, aux = apply_mapped(rows, loss_rows, aux_rows, weights)
        new_params, new_m, new_e, new_comp, metrics = server_phase(
            cfg, comp, plan, decode_mapped, state, agg, loss, aux, lr,
            count=wsum, client_err_rows=err_rows,
        )
        # per-slot writeback in canonical (cohort, slot) order: slot i's
        # row lands iff its weight is live; the unrolled loop makes a
        # duplicate client id a deterministic last-wins (the synchronous
        # batched scatter is elementwise identical for distinct ids)
        client_vel = state.client_vel
        client_err = state.client_err
        if lm > 0:
            for i in range(W):
                client_vel = client_vel.at[client_ids[i]].set(
                    jnp.where(weights[i] > 0, vel_rows[i],
                              client_vel[client_ids[i]])
                )
        if cfg.error_type == "local":
            for i in range(W):
                client_err = client_err.at[client_ids[i]].set(
                    jnp.where(weights[i] > 0, err_rows[i],
                              client_err[client_ids[i]])
                )
        return (
            FedState(new_params, new_m, new_e, client_vel, client_err,
                     state.step + 1, new_comp),
            metrics,
        )

    return jax.jit(launch_fn), jax.jit(apply_fn, donate_argnums=(0,))
