"""Buffered-asynchronous federation schedule — the host-side event clock.

The asyncfed engine (asyncfed/engine.py) keeps ``C`` client cohorts in
flight and fires a server update whenever ``K`` of the in-flight
contributions have arrived (buffered asynchronous aggregation, FedBuff —
arXiv:2106.06639 — layered on FetchSGD's stateless-client compression).
Devices never see wall time: this module pre-simulates the run's whole
arrival process into a deterministic sequence of ``UpdateSpec``s — which
cohorts launch before each update, which ``(cohort, slot)`` contributions
the update consumes, and each contribution's staleness — as a pure
function of ``(seed, arrival_rate, num_workers, K, C)``. Everything
downstream (engine dispatch, the staleness discount, telemetry, the
resilience vault replay) keys off this sequence, so an asyncfed run is
exactly as reproducible and resumable as a synchronous one.

Per-slot arrival delays are exponential with rate ``cfg.arrival_rate`` —
the same process the synchronous ``availability='poisson'`` model
projects to round granularity (fedsim/availability.py) — drawn from a
dedicated rng stream (``ASYNC_STREAM``, one generator per cohort) so
overlapping cohorts' arrivals interleave in continuous time without
perturbing the fedsim masks or the sampler's batch draws.

Semantics pinned here (tests/test_asyncfed.py leans on each):

* **Staleness** is the server-version delta between a contribution's
  launch snapshot and the update that consumes it:
  ``s = fire_version - launch_version[cohort]``.
* **Consumption order**: an update consumes the K OLDEST arrivals, but
  lists them in canonical ``(cohort, slot)`` order — a jnp.sum over
  permuted rows changes f32 rounding, so the canonical order makes the
  aggregate a function of the consumed SET (arrival-order independent)
  and makes the K=W, C=1 anchor's slot order exactly ``0..W-1``, i.e.
  the synchronous round's reduction order (bit-identity).
* **In flight** means launched and not yet fully DELIVERED. A cohort
  whose arrivals are all buffered but unconsumed is done transmitting —
  counting it in flight would deadlock K < W at C=1 (W=8, K=5: the
  cohort delivers 8, the fire consumes 5, 3 stay buffered; the relaunch
  must not wait on them).
* **Fire before top-up**: the update fires at the triggering arrival,
  THEN fresh cohorts launch against the post-update version — so at
  C=1, K=W cohort ``u+1`` launches at version ``u+1`` and every
  contribution's staleness is 0 (the synchronous anchor).
"""

from __future__ import annotations

import heapq
from typing import Dict, List, NamedTuple, Tuple

import numpy as np

# distinct rng stream tag: (seed, ASYNC_STREAM, cohort) can never collide
# with the sampler's (seed, round) or fedsim's (seed, FEDSIM_STREAM, round)
ASYNC_STREAM = 0xA5F3D


class UpdateSpec(NamedTuple):
    """One server update's realized schedule."""

    index: int  # update index == the server version it produces - 1
    slots: Tuple[Tuple[int, int], ...]  # K consumed (cohort, slot), sorted
    staleness: Tuple[int, ...]  # per consumed slot, aligned with ``slots``
    launches_before: Tuple[int, ...]  # cohorts to launch before assembling
    buffer_fill_after: int  # delivered-unconsumed contributions post-fire
    concurrent_after: int  # cohorts in flight after the post-fire top-up


def cohort_delays(seed: int, cohort: int, num_workers: int,
                  rate: float) -> np.ndarray:
    """One cohort's per-slot arrival delays (round-deadline units) —
    deterministic from ``(seed, cohort)`` alone. Unit exponentials scaled
    after the fact so ``rate=inf`` (every delay exactly 0 — the degenerate
    synchronous limit) draws through the same rng cursor."""
    rng = np.random.default_rng((seed, ASYNC_STREAM, cohort))
    scale = 0.0 if np.isinf(rate) else 1.0 / rate
    return rng.exponential(1.0, num_workers) * scale


class AsyncSchedule:
    """The pre-simulated run: ``updates[u]`` scripts update ``u``.

    ``launch_version[c]`` is the server version cohort ``c`` snapshots at
    launch; ``num_cohorts`` counts only cohorts some update actually
    launches (trailing simulated top-ups past the last fire are dropped —
    the engine never runs them)."""

    def __init__(self, *, seed: int, num_workers: int, buffer_k: int,
                 concurrency: int, arrival_rate: float, num_updates: int):
        W = int(num_workers)
        K = int(buffer_k)
        C = int(concurrency)
        if not 1 <= K <= W:
            raise ValueError(f"buffer_k must be in [1, num_workers]; got {K}")
        if C < 1:
            raise ValueError(f"concurrency must be >= 1; got {C}")
        self.seed = int(seed)
        self.num_workers = W
        self.buffer_k = K
        self.concurrency = C
        self.arrival_rate = float(arrival_rate)

        heap: List[Tuple[float, int, int]] = []  # (arrival, cohort, slot)
        launch_version: List[int] = []
        pending_launch: List[int] = []
        undelivered: Dict[int, int] = {}
        buffer: List[Tuple[int, int]] = []  # delivered-unconsumed, FIFO
        updates: List[UpdateSpec] = []
        version = 0
        now = 0.0

        def launch():
            c = len(launch_version)
            launch_version.append(version)
            delays = cohort_delays(self.seed, c, W, self.arrival_rate)
            for s in range(W):
                # ties (rate=inf: every delay 0) break by (cohort, slot)
                # tuple order — deterministic, launch-order arrivals
                heapq.heappush(heap, (now + float(delays[s]), c, s))
            undelivered[c] = W
            pending_launch.append(c)

        for _ in range(C):
            launch()
        while len(updates) < int(num_updates):
            if not heap:  # pragma: no cover — every launched slot arrives
                raise AssertionError("asyncfed schedule: event heap drained "
                                     "with updates still owed")
            now, c, s = heapq.heappop(heap)
            undelivered[c] -= 1
            if undelivered[c] == 0:
                del undelivered[c]  # fully delivered -> no longer in flight
            buffer.append((c, s))
            fired = None
            if len(buffer) >= K:
                oldest = buffer[:K]
                del buffer[:K]
                consumed = tuple(sorted(oldest))  # canonical (cohort, slot)
                fired = UpdateSpec(
                    index=len(updates),
                    slots=consumed,
                    staleness=tuple(version - launch_version[cc]
                                    for cc, _ in consumed),
                    launches_before=tuple(pending_launch),
                    buffer_fill_after=len(buffer),
                    concurrent_after=0,  # backfilled after the top-up
                )
                pending_launch.clear()
                version += 1
            # top-up AFTER the fire so fresh cohorts snapshot the updated
            # params; skipped once the run's updates are all scripted (the
            # engine would never launch them)
            while (len(undelivered) < C
                   and len(updates) + (1 if fired else 0) < int(num_updates)):
                launch()
            if fired is not None:
                updates.append(
                    fired._replace(concurrent_after=len(undelivered))
                )

        self.updates: Tuple[UpdateSpec, ...] = tuple(updates)
        self.launch_version: Tuple[int, ...] = tuple(launch_version)
        # only cohorts some update launches exist to the engine; launches
        # are assigned in cohort-index order, so this is a prefix count
        self.num_cohorts = sum(len(u.launches_before) for u in updates)

    def launched_before(self, update: int) -> int:
        """Cohorts launched before update ``update`` assembles — the
        engine's cold-restart window derivation."""
        return sum(len(self.updates[u].launches_before)
                   for u in range(update))
