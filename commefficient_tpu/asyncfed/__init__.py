"""Buffered-asynchronous federation (``--async_buffer K``).

FetchSGD's synchronous round blocks every update on the slowest of W
participants. This package layers FedBuff-style buffered asynchrony
(arXiv:2106.06639) on the existing compress/EF/momentum pipeline: the
server keeps ``C`` cohorts in flight (``--async_concurrency``), fires an
update once ``K`` contributions have arrived, and weights each
contribution by the polynomial staleness discount ``(1+s)^(-alpha)``
(``--staleness_exponent``) before it enters the shared aggregation tail.

Three pieces:

* ``schedule``: ``AsyncSchedule`` — the pre-simulated deterministic
  arrival process (per-cohort exponential delays on a dedicated rng
  stream); every downstream consumer keys off its ``UpdateSpec``s.
* ``round``: ``build_async_round_fns`` — the synchronous round split at
  the per-client/aggregate seam into a ``launch_fn`` (params snapshot ->
  per-client transmit rows) and an ``apply_fn`` (weighted buffer drain ->
  server update), sharing the synchronous helpers so the K=W, C=1,
  alpha=0 anchor reduces bit-identically to ``build_round_fn``.
* ``engine``: ``AsyncFederation`` — the round-source driver (same
  protocol as ``pipeline.PipelinedRounds``) owning the in-flight window,
  cohort staging (``pipeline.CohortScheduler``), staleness weighting,
  overlap telemetry, and the vault snapshot riders.

``--async_buffer 0`` (default) constructs nothing — the synchronous
engines and their golden recordings are untouched.
"""

from commefficient_tpu.asyncfed.engine import AsyncFederation
from commefficient_tpu.asyncfed.round import build_async_round_fns
from commefficient_tpu.asyncfed.schedule import (
    ASYNC_STREAM,
    AsyncSchedule,
    UpdateSpec,
    cohort_delays,
)

__all__ = [
    "ASYNC_STREAM",
    "AsyncFederation",
    "AsyncSchedule",
    "UpdateSpec",
    "build_async_round_fns",
    "cohort_delays",
]
