"""AsyncFederation — the buffered-asynchronous round engine.

One engine step == one SERVER UPDATE (the runner's ``(step, lr, metrics)``
unit stays a round, so the drain/checkpoint/crash scaffold is untouched).
Per update ``u`` the engine:

1. launches the cohorts ``AsyncSchedule.updates[u].launches_before``
   scripts — each realized in cohort order by a ``CohortScheduler``
   (pipeline/cohorts.py) and dispatched through the active rung's
   ``launch_fn`` against the CURRENT params (server version ``u``);
2. assembles the update's K consumed ``(cohort, slot)`` contributions
   (canonical order — see asyncfed/schedule.py) into fixed [W, ...]
   buffers, padding with zero-weight repeats so every apply at any
   buffer fill or concurrency dispatches ONE compiled program (the
   retrace sentinel pins zero retraces across cohort overlap);
3. weights slot ``i`` by ``live_i * (1 + staleness_i)^(-alpha)`` and
   applies through the active rung's ``apply_fn`` (donating the state,
   like the synchronous round).

Telemetry: per-update ``fedsim/*`` scalars are the consumed-slot mixture
of the contributing cohorts' stats (at K=W, C=1 exactly the cohort's own
— the ledger's masked billing then reconciles byte-for-byte with the
synchronous run), plus ``async/*`` overlap scalars (staleness mean/max,
buffer fill, concurrent cohorts, effective participation) that also feed
the control plane's join inputs.

Double-buffered rounds (``cfg.async_double_buffer``): the apply's host
fence is deferred until AFTER the next update's cohort launches have
dispatched (``_drain_deferred``), so update ``u+1``'s compute is already
queued when the host waits on ``u``'s aggregation collectives — XLA's
async scheduling then overlaps the two. Strictly a host-side fencing
change: the device programs and their dispatch order are untouched, so
the K=W, C=1, alpha=0 synchronous reduction stays bit-identical and the
vault rollback replay is unaffected (every exit path drains first).

Ladder interplay: a mid-run rung switch (control/) changes which
``(launch_fn, apply_fn)`` pair subsequent dispatches use. In-flight rows
launched under the old rung are dense [D] transmits in every mode, so
they aggregate under the NEW rung's apply — semantically the contribution
is re-encoded under the new rung (the ladder's migration story for
in-flight work).

Resilience: the in-flight window (pending cohort outputs, consumed
counts, cohort horizon) rides the drain-certified vault snapshot via
``snapshot_extra``/``restore_extra``, so a rollback replays
bit-identically — including contributions launched before the rollback
point. A plain checkpoint resume (no vault extras) instead cold-restarts
the window: the schedule-pinned pending cohorts relaunch against the
RESUMED params (their scheduled launch versions keep the rng and
staleness bookkeeping deterministic), which is deterministic going
forward but not bit-identical to the uninterrupted run — the FedBuff
trade every practical async system makes on cold restart.
"""

from __future__ import annotations

import time
from contextlib import nullcontext
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.asyncfed.schedule import AsyncSchedule, UpdateSpec
from commefficient_tpu.pipeline.cohorts import CohortScheduler


class AsyncFederation:
    """Buffered-asynchronous round source (``cfg.async_buffer > 0``).

    Same constructor/protocol shape as ``pipeline.PipelinedRounds``:
    ``start(resume_step)``, ``epoch_rounds(epoch, start_step)`` yielding
    ``(step, lr, metrics)``, ``restart(step)``, ``close()``, ``stats()``
    — plus ``snapshot_extra``/``restore_extra`` for the vault rider."""

    def __init__(self, cfg, session, sampler, lr_fn, num_rounds,
                 steps_per_epoch=None, spans=None, profiler=None):
        self.cfg = cfg
        self.session = session
        self.sampler = sampler
        self.lr_fn = lr_fn
        self.num_rounds = int(num_rounds)
        self.steps_per_epoch = int(steps_per_epoch or num_rounds)
        self.spans = spans
        self.profiler = profiler
        self.W = int(cfg.num_workers)
        self._alpha = float(cfg.staleness_exponent)
        # engine-local (K, C): the cfg's static values normally; under an
        # ADAPTS_ASYNC control policy the controller owns the live pair
        # (its state blob restores the retuned values before start(), so
        # a checkpoint resume dispatches the retuned schedule, not the
        # cfg one)
        self._k = int(cfg.async_buffer)
        self._c = int(cfg.async_concurrency)
        ctl = session.controller
        if ctl is not None and getattr(ctl.policy, "ADAPTS_ASYNC", False):
            self._k = int(ctl.async_k)
            self._c = int(ctl.async_c)
        self.schedule = self._build_schedule()
        self._scheduler: Optional[CohortScheduler] = None
        # in-flight window: cohort -> launch record (device outputs + the
        # host live mask/stats/version the apply assembly reads)
        self._pending: Dict[int, Dict[str, Any]] = {}
        self._consumed: Dict[int, int] = {}  # cohort -> consumed slots
        self._next_cohort = 0
        # replay horizon in COHORT units (fedsim nan_client transients
        # fire on first realization only — same discipline as the
        # pipelined engine's round-unit horizon)
        self._cohort_horizon = 0
        self._restored = None
        self.restarts = 0
        self.quiesces = 0
        self._updates_run = 0
        self._cohorts_launched = 0
        self._host_stall_ms = 0.0
        # double-buffered rounds (cfg.async_double_buffer): the apply's
        # host fence is PARKED here and drained only after the NEXT
        # update's cohort launches have dispatched, so XLA schedules the
        # apply's collectives behind the new launches' compute instead of
        # the host serializing on them. Pure host scheduling — dispatch
        # order of the device programs is unchanged, so the K=W, C=1,
        # a=0 sync reduction stays bit-identical.
        self._double_buffer = bool(getattr(cfg, "async_double_buffer",
                                           False))
        self._deferred = None
        # staleness-aware (K, C) retune (schema v13): the controller's
        # decision point runs mid-update, so a retune is PARKED here and
        # applied at the top of the next update's loop iteration — a cold
        # window rebuild under the new schedule
        self._retune_pending = None
        self.retunes_applied = 0
        if session.controller is not None:
            session.controller.add_switch_listener(self._on_rung_switch)
            if getattr(session.controller.policy, "ADAPTS_ASYNC", False):
                session.controller.add_retune_listener(self._on_retune)

    # -- lifecycle ---------------------------------------------------------
    def start(self, resume_step: int = 0) -> "AsyncFederation":
        if self._scheduler is not None:
            return self  # idempotent, like PipelinedRounds.start
        self._init_window(int(resume_step), None)
        return self

    def restart(self, step: int) -> None:
        """Quiesce and rebuild the window at update ``step`` — the vault
        rollback path (``restore_extra`` first restores the snapshotted
        in-flight window; without one the window cold-restarts)."""
        self._drain_deferred()
        if self._scheduler is not None:
            self._scheduler.close()
            self._scheduler = None
        blob, self._restored = self._restored, None
        self._pending, self._consumed = {}, {}
        if blob is not None:
            # the snapshot's (K, C) wins: the window it carries was
            # captured under THAT schedule, and the controller's own blob
            # (restored alongside) re-notified the same pair — so any
            # parked retune is stale by construction
            k = int(blob.get("k", self._k))
            c = int(blob.get("c", self._c))
            if (k, c) != (self._k, self._c):
                self._k, self._c = k, c
                self.schedule = self._build_schedule()
            self._retune_pending = None
        self._init_window(int(step), blob)
        self.restarts += 1
        if self.spans is not None:
            with self.spans.span(f"async_recovery_restart:round{step}"):
                pass

    def close(self) -> None:
        self._drain_deferred()
        if self._scheduler is not None:
            self._scheduler.close()
            self._scheduler = None

    def _build_schedule(self) -> AsyncSchedule:
        """The pre-simulated arrival/consumption script for the CURRENT
        engine-local (K, C) — rebuilt whole on retune (same seed, so the
        arrival process is the one deterministic object it always was)."""
        return AsyncSchedule(
            seed=self.cfg.seed,
            num_workers=self.W,
            buffer_k=self._k,
            concurrency=self._c,
            arrival_rate=self.cfg.arrival_rate,
            num_updates=self.num_rounds,
        )

    def _build_scheduler(self, start_cohort: int) -> CohortScheduler:
        return CohortScheduler(
            session=self.session,
            sampler=self.sampler,
            lr_fn=self.lr_fn,
            launch_versions=self.schedule.launch_version,
            start_cohort=start_cohort,
            stop_cohort=self.schedule.num_cohorts,
            depth=max(1, self._c),
            microbatches=self.cfg.round_microbatches,
            spans=self.spans,
            replay_until=self._cohort_horizon,
        ).start()

    def _init_window(self, step: int, blob) -> None:
        """Stand the in-flight window up for update ``step``: from the
        vault blob when one matches (bit-identical replay), else by
        deriving the launched/consumed sets from the schedule and
        relaunching the unconsumed cohorts at the current params."""
        if blob is not None and int(blob.get("update", -1)) == step:
            self._pending = {
                int(c): dict(p) for c, p in blob["pending"].items()
            }
            self._consumed = {
                int(c): int(n) for c, n in blob["consumed"].items()
            }
            self._next_cohort = int(blob["next_cohort"])
            self._cohort_horizon = max(self._cohort_horizon,
                                       int(blob["cohort_horizon"]))
            self._scheduler = self._build_scheduler(self._next_cohort)
            return
        consumed: Dict[int, int] = {}
        for u in range(step):
            for (c, _s) in self.schedule.updates[u].slots:
                consumed[c] = consumed.get(c, 0) + 1
        launched = self.schedule.launched_before(step)
        need = {c for c in range(launched) if consumed.get(c, 0) < self.W}
        self._consumed = consumed
        self._next_cohort = launched
        start_c = min(need) if need else launched
        self._scheduler = self._build_scheduler(start_c)
        # the prefetcher's get() is strictly in-order: walk every cohort
        # in the window, relaunching only those with unconsumed slots
        for c in range(start_c, launched):
            work = self._scheduler.get(c)
            if c in need:
                self._launch_work(c, work)

    # -- launch ------------------------------------------------------------
    def _span(self, name: str, collective: bool = False, trace_id=None,
              parent=None):
        return self.spans.span(name, collective=collective,
                               trace_id=trace_id, parent=parent) if (
            self.spans is not None) else nullcontext()

    def _drain_deferred(self) -> None:
        """Fence the PREVIOUS update's parked apply (double-buffer mode).
        Called after the next update's launches dispatch — the drain span
        then measures only the collective time the launches failed to
        hide — and on every path that leaves the steady-state loop
        (restart/close/snapshot), so the window never rides an unfenced
        apply into the vault. The drain span carries the PARKED update's
        trace id + step (schema v11): it fences that round's apply, not
        the round whose loop iteration happens to run it."""
        if self._deferred is None:
            return
        (loss, step), self._deferred = self._deferred, None
        from commefficient_tpu.telemetry.trace import round_trace_id

        if self.spans is None:
            return
        with self.spans.span("async_apply_drain", collective=True,
                             step=step,
                             trace_id=round_trace_id(step)) as sp:
            if sp is not None:
                sp.fence(loss)

    def _launch_work(self, c: int, work) -> None:
        """Dispatch cohort ``c``'s launch program against the current
        params and park the outputs in the in-flight window."""
        sess = self.session
        env = work.env
        if env is not None and sess._client_blacklist is not None:
            env = sess._blacklist_env(env, work.client_ids)
        live = None
        stats: Dict[str, float] = {}
        fs = ()
        if env is not None:
            live = np.asarray(env.live, np.float32)
            stats = dict(env.stats)
            fs = (
                jax.device_put(jnp.asarray(env.live), sess._batch_sharding),
                jax.device_put(jnp.asarray(env.corrupt),
                               sess._batch_sharding),
            )
        launch_fn, _ = sess.async_round_fns(sess.active_rung)
        ids = jax.device_put(jnp.asarray(work.client_ids),
                             sess._batch_sharding)
        version = int(self.schedule.launch_version[c])
        st = sess.state
        from commefficient_tpu.telemetry.trace import (
            cohort_trace_id,
            round_trace_id,
        )

        # the cohort's trace id roots its whole lifecycle (launch ->
        # buffer residency -> consuming applies); its parent is the
        # server round whose params it launched against (schema v11)
        with self._span("async_launch", trace_id=cohort_trace_id(c),
                        parent=round_trace_id(version)):
            out = launch_fn(
                st.params_vec, st.client_vel, st.client_err, ids, work.batch,
                jnp.int32(version), jnp.float32(work.lr), env=fs,
            )
        self._pending[c] = {
            "out": out,
            "cids": np.asarray(work.client_ids),
            "live": live,
            "stats": stats,
            "version": version,
            "rung": int(sess.active_rung),
            # launch-time clock for the retroactive buffer-residency
            # span recorded when the cohort fully retires (absent on
            # vault-restored windows — the original launch time did not
            # survive the snapshot, so no residency span is recorded)
            "t_launch": time.perf_counter(),
        }
        self._cohorts_launched += 1
        self._cohort_horizon = max(self._cohort_horizon, c + 1)

    # -- (K, C) retune (staleness_aware control, schema v13) ---------------
    def _on_retune(self, step: int, k: int, c: int) -> None:
        """Controller retune listener — also re-fired by a state-blob
        load, so a no-op pair (checkpoint resume already built this
        schedule) must not force a spurious window rebuild."""
        if (int(k), int(c)) == (self._k, self._c):
            return
        self._retune_pending = (int(k), int(c))

    def _apply_retune(self, step: int) -> None:
        """Rebuild the schedule + in-flight window under the retuned
        (K, C) — a cold window restart like ``restart`` without a vault
        blob: the new schedule's pending cohorts relaunch against the
        CURRENT params, deterministic going forward (the same FedBuff
        trade the plain checkpoint resume makes)."""
        (self._k, self._c), self._retune_pending = self._retune_pending, None
        self._drain_deferred()
        if self._scheduler is not None:
            self._scheduler.close()
            self._scheduler = None
        self.schedule = self._build_schedule()
        self._pending, self._consumed = {}, {}
        self._init_window(int(step), None)
        self.retunes_applied += 1
        if self.spans is not None:
            with self.spans.span(
                    f"async_retune:round{step}:k{self._k}c{self._c}"):
                pass

    # -- the update loop ---------------------------------------------------
    def epoch_rounds(self, epoch: int, start_step: int):
        spe = self.steps_per_epoch
        for step in range(max(epoch * spe, start_step), (epoch + 1) * spe):
            # a retune parked by the PREVIOUS update's decision point
            # lands here, before this update reads its schedule spec
            if self._retune_pending is not None:
                self._apply_retune(step)
            spec = self.schedule.updates[step]
            stall = 0.0
            for c in spec.launches_before:
                t0 = time.perf_counter()
                work = self._scheduler.get(c)
                stall += time.perf_counter() - t0
                self._launch_work(c, work)
                self._next_cohort = c + 1
            self._host_stall_ms += stall * 1000.0
            # double buffer: update step-1's apply fences HERE, after this
            # update's cohort launches are already in flight on device
            self._drain_deferred()
            if self.profiler is not None:
                self.profiler.step(step)
            if self.spans is not None:
                self.spans.step(step)
            lr = float(self.lr_fn(step))
            metrics = self._apply_update(step, spec, lr)
            self._updates_run += 1
            yield step, lr, metrics

    def _slot_weights(self, spec: UpdateSpec) -> np.ndarray:
        """Per-slot aggregation weights: live mask x the polynomial
        staleness discount (FedBuff §4), padded to [W] with zeros."""
        w = np.zeros(self.W, np.float32)
        for i, (c, s) in enumerate(spec.slots):
            lv = self._pending[c]["live"]
            base = 1.0 if lv is None else float(lv[s])
            w[i] = base * (1.0 + spec.staleness[i]) ** (-self._alpha)
        return w

    def _update_stats(self, spec: UpdateSpec, w: np.ndarray,
                      wsum: float) -> Dict[str, float]:
        """The update's host scalars: the consumed-slot mixture of the
        contributing cohorts' fedsim stats (constant key set; at K=W, C=1
        exactly the single cohort's own stats — the ledger's masked
        billing then reconciles with the synchronous run byte-for-byte)
        plus the ``async/*`` overlap scalars."""
        W = self.W
        fs_stats: Dict[str, float] = {}
        if self.session.fedsim_env is not None:
            counts: Dict[int, int] = {}
            n_live = 0.0
            for (c, s) in spec.slots:
                counts[c] = counts.get(c, 0) + 1
                lv = self._pending[c]["live"]
                n_live += 1.0 if lv is None else float(lv[s])

            def mix(key: str) -> float:
                return sum(
                    (n / W) * float(self._pending[c]["stats"].get(key, 0.0))
                    for c, n in counts.items()
                )

            fs_stats = {
                "fedsim/participation_rate": n_live / W,
                "fedsim/dropped": mix("fedsim/dropped"),
                "fedsim/straggler_excluded": mix("fedsim/straggler_excluded"),
                "fedsim/all_dropped": float(wsum == 0.0),
                "fedsim/preempt": max(
                    float(self._pending[c]["stats"].get("fedsim/preempt",
                                                        0.0))
                    for c in counts
                ),
            }
        st = spec.staleness
        fs_stats.update({
            "async/staleness_mean": float(sum(st)) / max(len(st), 1),
            "async/staleness_max": float(max(st)) if st else 0.0,
            "async/buffer_fill": float(spec.buffer_fill_after),
            "async/concurrent_cohorts": float(spec.concurrent_after),
            "async/effective_participation": float(wsum),
        })
        return fs_stats

    def _apply_update(self, step: int, spec: UpdateSpec, lr: float):
        sess = self.session
        W, K = self.W, len(spec.slots)
        # fixed [W, ...] assembly at any K/C: padding repeats slot 0 at
        # weight 0 (the where-gate blocks even a NaN payload), so every
        # apply shares ONE compiled signature — zero retraces
        sel = list(spec.slots) + [spec.slots[0]] * (W - K)
        outs = [self._pending[c]["out"] for (c, _s) in sel]
        rows = jnp.stack([o[0][s] for o, (_c, s) in zip(outs, sel)])
        vel_rows = jnp.stack([o[1][s] for o, (_c, s) in zip(outs, sel)])
        err_rows = jnp.stack([o[2][s] for o, (_c, s) in zip(outs, sel)])
        loss_rows = jnp.stack([o[3][s] for o, (_c, s) in zip(outs, sel)])
        aux_rows = jax.tree.map(
            lambda *leaves: jnp.stack(leaves),
            *[jax.tree.map(lambda a, s=s: a[s], o[4])
              for o, (_c, s) in zip(outs, sel)],
        )
        cids = np.asarray([self._pending[c]["cids"][s] for (c, s) in sel])
        w = self._slot_weights(spec)
        wsum = float(np.float32(w.sum(dtype=np.float32)))
        bs = sess._batch_sharding

        def put(a):
            return jax.device_put(a, bs)

        fs_stats = self._update_stats(spec, w, wsum)
        # controller decision point BEFORE dispatch (may swap the rung:
        # the update then applies under the NEW rung's program — in-flight
        # rows are dense transmits, re-encoded under the new rung)
        sess._control_round_start(fs_stats)
        _, apply_fn = sess.async_round_fns(sess.active_rung)
        from commefficient_tpu.telemetry.trace import (
            cohort_trace_id,
            round_trace_id,
        )

        name = ("async_apply_dispatch" if self._double_buffer
                else "async_apply")
        with self._span(name, collective=not self._double_buffer,
                        trace_id=round_trace_id(step)) as sp:
            sess.state, metrics = apply_fn(
                sess.state, put(rows), put(vel_rows), put(err_rows),
                put(loss_rows), jax.tree.map(put, aux_rows),
                put(jnp.asarray(cids)), put(jnp.asarray(w)),
                jnp.float32(wsum), jnp.float32(lr),
            )
            if sp is not None:
                if self._double_buffer:
                    # park the fence target (with its step, so the drain
                    # span names the round it fences); _drain_deferred
                    # fences it after the NEXT update's launches dispatch
                    self._deferred = (metrics["loss"], step)
                else:
                    sp.fence(metrics["loss"])
        # mirror train_round's clock discipline: the availability/chaos
        # schedule and the controller key off the host round clock
        sess._round_clock += 1
        sess._replay_horizon = max(sess._replay_horizon, sess._round_clock)
        for (c, _s) in spec.slots:
            self._consumed[c] = self._consumed.get(c, 0) + 1
        for c in {cc for cc, _ in spec.slots}:
            if self._consumed.get(c, 0) >= W:
                p = self._pending.pop(c, None)  # fully consumed -> retire
                if (p is not None and self.spans is not None
                        and "t_launch" in p):
                    # retroactive buffer-residency span: launch ->
                    # retirement, on the cohort's own trace (schema v11)
                    self.spans.span_at(
                        "async_buffer_residency", p["t_launch"],
                        time.perf_counter(), step=step,
                        trace_id=cohort_trace_id(c),
                        parent=round_trace_id(p["version"]),
                    )
        stats = sess._host_round_stats(fs_stats)
        return {**metrics, **stats} if stats else metrics

    # -- rung switch marker ------------------------------------------------
    def _on_rung_switch(self, step: int, old: int, new: int) -> None:
        self.quiesces += 1
        if self.spans is not None:
            with self.spans.span(f"async_rung_switch:round{step}"):
                pass

    # -- vault riders ------------------------------------------------------
    def snapshot_extra(self) -> Dict[str, Any]:
        """Host copy of the in-flight window for the vault snapshot —
        restoring it replays the post-rollback tail bit-identically
        (pending outputs are NOT re-launched: the blacklist may have
        grown since, and the rows must be the ones the first pass saw)."""
        self._drain_deferred()
        pending = {
            int(c): {
                "out": jax.tree.map(np.asarray, p["out"]),
                "cids": np.asarray(p["cids"]).copy(),
                "live": None if p["live"] is None else np.asarray(
                    p["live"]).copy(),
                "stats": dict(p["stats"]),
                "version": int(p["version"]),
                "rung": int(p["rung"]),
            }
            for c, p in self._pending.items()
        }
        return {
            "update": int(self.session._round_clock),
            "next_cohort": int(self._next_cohort),
            "cohort_horizon": int(self._cohort_horizon),
            # the (K, C) the window was captured under — restart() rebuilds
            # the matching schedule before replaying it (retune rider)
            "k": int(self._k),
            "c": int(self._c),
            "consumed": {int(c): int(n)
                         for c, n in self._consumed.items()},
            "pending": pending,
        }

    def restore_extra(self, blob) -> None:
        """Stash a vault snapshot's window for the next ``restart``."""
        self._restored = blob

    # -- observability -----------------------------------------------------
    def stats(self) -> Dict[str, float]:
        return {
            "updates": self._updates_run,
            "cohorts_launched": self._cohorts_launched,
            "host_stall_ms": self._host_stall_ms,
            "restarts": self.restarts,
            "quiesces": self.quiesces,
            "retunes_applied": self.retunes_applied,
        }
