"""Checkpoint / resume — a strict superset of the reference's persistence.

The reference can only ``save_pretrained`` final GPT-2 weights
(fed_aggregator.py ~L260-280); killed runs restart from scratch (SURVEY.md
§5 "Checkpoint/resume"). Here the FULL federated state checkpoints through
Orbax: ``FedState`` (params vector, server momentum/error — dense or sketch
tables — HBM client rows, round counter) plus the host-offloaded client
stores. The sampler needs no state: it is deterministic from
``(seed, round)`` (data/sampler.py), so restoring ``FedState.step`` IS the
full training clock — resume reproduces the uninterrupted run bit-for-bit
(pinned by tests/test_checkpoint.py).
"""

from __future__ import annotations

import hashlib
import json
import os
import warnings
from typing import Optional

import jax
import numpy as np

from commefficient_tpu.parallel.round import FedState
from commefficient_tpu.utils.config import Config


def _spec_fingerprint(spec) -> np.ndarray:
    """The sketch-layout identity a checkpointed [r, c] table depends on.
    Equal table SHAPES do not imply equal layouts (r4: the adaptive
    scramble block changed the seed-derived permutation while shapes stayed
    identical) — decoding a table with a different layout silently yields
    garbage estimates, so restore refuses on mismatch."""
    families = {"fmix32": 1, "poly4": 2}  # stable (str hash is per-process)
    return np.asarray(
        [
            spec.d, spec.c, spec.r, spec.num_blocks, spec.seed,
            spec.chunk_m, spec.sblock, spec.band, spec.d_eff,
            spec.c_actual, families.get(spec.hash_family, 0),
        ],
        np.int64,
    )


def _to_saveable(session) -> dict:
    st = session.state
    out = {
        "fed_state": {
            f: (() if isinstance(getattr(st, f), tuple) else np.asarray(getattr(st, f)))
            for f in st._fields
        },
        "grad_size": session.grad_size,
    }
    if session.spec is not None:
        out["sketch_layout"] = _spec_fingerprint(session.spec)
    if session.host_vel is not None:
        out["host_vel"] = session.host_vel
    if session.host_err is not None:
        out["host_err"] = session.host_err
    if getattr(session, "controller", None) is not None:
        # adaptive-communication controller state (control/): active rung,
        # switch count, byte spend, policy slots — restoring it is what
        # makes a resumed run reproduce the rung sequence bit-exactly
        # (drains happen before saves, so the blob reflects every drained
        # round <= this checkpoint's step)
        out["control"] = session.controller.state_blob()
    if getattr(session, "_client_blacklist", None) is not None:
        # resilience/ skip_clients blacklist: session-cumulative and
        # monotone, so a resumed run must keep masking the clients a
        # recovery already condemned — without this leaf a preempt/resume
        # cycle would silently re-admit them
        out["blacklist"] = np.asarray(session._client_blacklist, np.int64)
    return out


def _sha256_file(path: str) -> str:
    """Chunked file digest shared by manifest write and verify — one
    idiom, so a chunk-size or algorithm change can't desync the two."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def commit_fed_state(session, fs: dict, *, origin: str = "checkpoint") -> FedState:
    """Re-commit a host-side fed_state leaf dict to ``session``'s mesh
    shardings and return the new FedState — shared by checkpoint restore
    and the resilience RollbackVault (resilience/vault.py), so the two
    in-place state-replacement paths can never drift.

    FSDP leaves go back to their P(workers) shards (a plain asarray would
    park the full padded state on ONE device — the exact memory wall FSDP
    removes), replicated-round leaves to the replicated sharding (else the
    donated round_fn compiles a second program against the
    SingleDeviceSharding layout, see FederatedSession.__init__). A leaf
    absent from ``fs`` (pre-PR2 checkpoints: no ``comp``) keeps the
    session's freshly initialized value, with a warning naming ``origin``.
    """
    if session.cfg.fsdp:
        from commefficient_tpu.parallel.fsdp import fsdp_state_shardings

        shardings = fsdp_state_shardings(session.cfg, session.mesh)
    else:
        shardings = FedState(*[session._replicated] * len(FedState._fields))
    leaves = {}
    for f in FedState._fields:
        if f not in fs:
            # legacy source with no compressor warm state — keep the
            # session's freshly initialized leaf (legacy modes: (); a
            # powersgd session restores everything else and restarts its
            # Q warm-up cold).
            leaves[f] = getattr(session.state, f)
            if not isinstance(leaves[f], tuple):
                warnings.warn(
                    f"{origin} predates the compressor warm-state leaf "
                    f"{f!r}; restored everything else and re-initialized "
                    "it (powersgd warm start restarts cold — one extra "
                    "power iteration of subspace tracking)."
                )
            continue
        leaves[f] = (
            () if isinstance(fs[f], (tuple, list)) and len(fs[f]) == 0
            else jax.device_put(
                jax.numpy.asarray(fs[f]), getattr(shardings, f)
            )
        )
    return FedState(**leaves)


class FedCheckpointer:
    """Orbax-backed checkpoint manager honoring ``cfg.checkpoint_dir`` /
    ``checkpoint_every`` / ``resume`` (the three config fields the reference
    names but VERDICT r1 found dead)."""

    def __init__(self, cfg: Config):
        self.cfg = cfg
        self.mngr = None
        if cfg.checkpoint_dir:
            import orbax.checkpoint as ocp

            self.mngr = ocp.CheckpointManager(
                os.path.abspath(cfg.checkpoint_dir),
                options=ocp.CheckpointManagerOptions(max_to_keep=3),
            )

    @property
    def enabled(self) -> bool:
        return self.mngr is not None

    def will_save(self, round_idx: int, *, force: bool = False) -> bool:
        """True iff ``maybe_save(round_idx)`` would write a checkpoint —
        lets callers flush buffered logs BEFORE the state is persisted (a
        resume fast-forwards past these rounds, so anything unflushed at
        save time would be lost for good)."""
        if not self.enabled:
            return False
        every = self.cfg.checkpoint_every
        return force or (every > 0 and round_idx > 0 and round_idx % every == 0)

    def maybe_save(self, session, round_idx: int, *, force: bool = False) -> bool:
        """Save if ``checkpoint_every`` divides ``round_idx`` (or forced).
        A step already on disk is never re-saved (the runner's
        end-of-training force-save may land on a boundary the loop
        already wrote). Every save also writes an integrity manifest
        sidecar (sizes + sha256 per file) that ``restore`` verifies —
        a truncated/corrupted step is then rejected with its reason
        instead of restored as garbage."""
        if not self.will_save(round_idx, force=force):
            return False
        if self.mngr.latest_step() == round_idx:
            return False
        import orbax.checkpoint as ocp

        self.mngr.save(
            round_idx, args=ocp.args.StandardSave(_to_saveable(session))
        )
        self.mngr.wait_until_finished()
        self._write_manifest(round_idx)
        return True

    def latest_step(self) -> Optional[int]:
        return self.mngr.latest_step() if self.enabled else None

    def discard_steps_after(self, step: int) -> None:
        """Resilience rollback support: retained checkpoints ABOVE the
        rollback step were saved from the rolled-back trajectory. A
        ``retry`` replay reproduces them bit-identically, but ``demote``/
        ``skip_clients`` fork — leaving the old step on disk would make
        the replay's ``maybe_save`` at that boundary a silent no-op and a
        later ``--resume`` restore a PRE-recovery state (stale rung floor
        / blacklist). Delete them so the replay re-saves its own."""
        if not self.enabled:
            return
        for s in sorted(int(x) for x in (self.mngr.all_steps() or [])):
            if s > int(step):
                self.mngr.delete(s)
        self.mngr.wait_until_finished()
        self._gc_manifests()

    def resave(self, session, step: int) -> bool:
        """Persist the CURRENT session state at ``step``, replacing any
        retained checkpoint there. Used after a FORKING recovery
        (demote/skip_clients): the rollback restored round ``step``'s
        params, but the policy then mutated session state the retained
        blob predates (the demotion floor, the blacklist) — a crash
        before the next boundary would otherwise ``--resume`` without
        the fork. No-op when checkpointing is off."""
        if not self.enabled:
            return False
        if int(step) in {int(s) for s in (self.mngr.all_steps() or [])}:
            self.mngr.delete(int(step))
            self.mngr.wait_until_finished()
        return self.maybe_save(session, int(step), force=True)

    # -- integrity manifests (resilience: checkpoint fallback) -------------
    def _root(self) -> str:
        return os.path.abspath(self.cfg.checkpoint_dir)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self._root(), str(int(step)))

    def _manifest_path(self, step: int) -> str:
        # sidecars live OUTSIDE the orbax step dirs (an extra file inside
        # one could be mistaken for an item); GC'd alongside rotation
        return os.path.join(self._root(), "manifests", f"{int(step)}.json")

    def _write_manifest(self, step: int) -> Optional[str]:
        """Hash every file of the committed step into
        ``<dir>/manifests/<step>.json`` (atomic write), and drop sidecars
        of rotated-away steps. Best-effort: a manifest failure must not
        kill the save (the checkpoint itself is already durable; restore
        just loses pre-verification for this step)."""
        try:
            step_dir = self._step_dir(step)
            files = {}
            for dirpath, _dirs, fnames in os.walk(step_dir):
                for fn in sorted(fnames):
                    p = os.path.join(dirpath, fn)
                    files[os.path.relpath(p, step_dir)] = {
                        "size": os.path.getsize(p),
                        "sha256": _sha256_file(p),
                    }
            path = self._manifest_path(step)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"step": int(step), "files": files}, f, indent=2)
            os.replace(tmp, path)
            self._gc_manifests()
            return path
        except Exception as e:  # noqa: BLE001 — observability, not data
            warnings.warn(
                f"checkpoint manifest for step {step} not written "
                f"({type(e).__name__}: {e}); restore will skip integrity "
                "verification for this step"
            )
            return None

    def _gc_manifests(self) -> None:
        mdir = os.path.join(self._root(), "manifests")
        if not os.path.isdir(mdir):
            return
        retained = {int(s) for s in (self.mngr.all_steps() or [])}
        for fn in os.listdir(mdir):
            stem, ext = os.path.splitext(fn)
            if ext == ".json" and stem.isdigit() and int(stem) not in retained:
                try:
                    os.remove(os.path.join(mdir, fn))
                except OSError:
                    pass

    def verify_step(self, step: int) -> Optional[str]:
        """Integrity-check the on-disk step against its manifest sidecar.
        Returns None when consistent (or when no sidecar exists — a
        legacy checkpoint has nothing to verify against), else a
        human-readable rejection reason naming the first mismatch."""
        path = self._manifest_path(step)
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                manifest = json.load(f)
            files = manifest["files"]
        except Exception as e:  # noqa: BLE001 — a bad sidecar IS a reason
            return f"unreadable manifest sidecar ({type(e).__name__}: {e})"
        step_dir = self._step_dir(step)
        for rel, info in sorted(files.items()):
            p = os.path.join(step_dir, rel)
            if not os.path.exists(p):
                return f"missing file {rel!r}"
            size = os.path.getsize(p)
            if size != info["size"]:
                return (f"size mismatch at {rel!r} ({size} B on disk, "
                        f"manifest says {info['size']} B)")
            if _sha256_file(p) != info["sha256"]:
                return f"sha256 mismatch at {rel!r}"
        return None

    def _saved_lacks_sketch_layout(self, step: int, exc: Exception) -> bool:
        """True if the on-disk checkpoint at ``step`` predates the r4
        sketch-layout stamp. Probes the saved item structure (ADVICE r4:
        orbax's exception text is not a stable interface); only if the
        metadata probe itself fails does it fall back to matching the
        exception text — worst case the raw orbax error propagates, which
        still fails safe."""
        try:
            meta = self.mngr.item_metadata(step)
            # ADVICE r5 #2: orbax returns a Mapping here in some versions
            # and an iterable-of-keys view in others — normalize before
            # membership tests so the probe is not version-coupled.
            keys = set(meta.keys()) if hasattr(meta, "keys") else set(meta)
            if not {"fed_state", "grad_size"} <= keys:
                # every checkpoint this module ever wrote has these
                # siblings; their absence means the probe surfaced some
                # OTHER structure (or a corrupt item) — do not classify
                # the stamp's absence as "pre-stamp" from it.
                return "sketch_layout" in str(exc)
            return "sketch_layout" not in keys
        except Exception:  # noqa: BLE001 — probe is best-effort
            return "sketch_layout" in str(exc)

    @staticmethod
    def _rung_template_candidates(session) -> list:
        """Rung indices whose state template is worth attempting a restore
        under: the active rung first, then ONE representative of every
        other distinct (momentum, error, comp) shape signature. A k-only
        ladder has a single signature (rung switches don't change state
        shapes), so restore never retries; a num_cols/rank ladder retries
        once per distinct geometry until the template matches the rung the
        checkpoint was saved at (the controller blob then names it
        exactly). ``[None]`` for control-less sessions."""
        rungs = getattr(session, "rungs", None)
        if rungs is None or len(rungs) <= 1:
            return [None]

        def sig(i):
            st = session._rung_state_struct(rungs[i])
            return tuple(
                tuple(getattr(st, f).shape)
                if hasattr(getattr(st, f), "shape") else ()
                for f in ("momentum", "error", "comp")
            )

        out = [session.active_rung]
        seen = {sig(session.active_rung)}
        for i in range(len(rungs)):
            s = sig(i)
            if s not in seen:
                seen.add(s)
                out.append(i)
        return out

    def _attempt_restore(self, step: int, template: dict):
        """One StandardRestore attempt, absorbing the known
        template/saved key differences: pre-PR2 checkpoints lack the
        ``comp`` FedState leaf; pre-control checkpoints lack the
        ``control`` blob — each retried with the key dropped (the session
        keeps its fresh leaf/state). The mismatch is detected from the
        exception because ``item_metadata`` returns None on a freshly
        opened manager — no handler registry yet — so a pre-restore
        structure probe is not available."""
        import orbax.checkpoint as ocp

        template = {**template, "fed_state": dict(template["fed_state"])}
        for _ in range(4):  # at most: full, ±blacklist, -control, -comp
            try:
                return self.mngr.restore(
                    step, args=ocp.args.StandardRestore(template)
                )
            except ValueError as e:
                msg = str(e)
                if "Dict key mismatch" not in msg:
                    raise
                if "blacklist" in msg:
                    if "blacklist" in template:
                        # checkpoint predates (or never had) a blacklist:
                        # the session keeps its own
                        template.pop("blacklist")
                    else:
                        # checkpoint CARRIES a blacklist this fresh
                        # session doesn't know yet — restore it (shape
                        # comes from the saved array)
                        template["blacklist"] = np.zeros(0, np.int64)
                    continue
                if "control" in template and "control" in msg:
                    # pre-control checkpoint into a controlled session:
                    # restore the rest; the controller starts at its
                    # initial rung (warned below, once restore succeeds)
                    template.pop("control")
                    continue
                if "comp" in template["fed_state"] and "comp" in msg:
                    # pre-PR2 checkpoint: retry with the 6-leaf template
                    template["fed_state"].pop("comp")
                    continue
                if "control" in msg and "control" not in template:
                    raise ValueError(
                        "checkpoint carries adaptive-control state "
                        "('control' blob) but this session was built "
                        "without a controller — restore with the same "
                        "control_policy/ladder the run was saved under "
                        f"(underlying: {e})"
                    ) from e
                raise
        raise ValueError("restore retries exhausted")  # unreachable

    def restore(self, session, step: Optional[int] = None) -> Optional[int]:
        """Restore into ``session`` in place; returns the restored round
        index (== FedState.step) or None if nothing to restore.

        Integrity fallback (resilience pillar 3): with ``step=None`` the
        walk starts at the latest retained step, pre-verifies it against
        its manifest sidecar, and on a mismatch — or ANY restore failure —
        falls back to the next older retained step with a warning naming
        the rejected step and the reason, only failing when the whole
        vault is exhausted (the final error chains every per-step
        failure). An EXPLICIT ``step`` is restored strictly: the caller
        named it, so a bad step raises instead of silently substituting
        an older one."""
        if not self.enabled:
            return None
        if step is not None:
            bad = self.verify_step(step)
            if bad is not None:
                raise ValueError(
                    f"checkpoint at step {step} failed integrity "
                    f"verification: {bad}"
                )
            return self._restore_step(session, step)
        steps = sorted((s for s in (self.mngr.all_steps() or [])),
                       reverse=True)
        if not steps:
            return None
        failures = []
        last_exc: Optional[Exception] = None
        for n, s in enumerate(steps):
            older = len(steps) - n - 1
            reason = self.verify_step(s)
            if reason is None:
                try:
                    return self._restore_step(session, s)
                except Exception as e:  # noqa: BLE001 — walk back
                    reason = f"{type(e).__name__}: {e}"
                    last_exc = last_exc or e
            failures.append((s, reason))
            warnings.warn(
                f"checkpoint at step {s} REJECTED ({reason})"
                + (f"; falling back to the next of {older} older retained "
                   "step(s)" if older else "; no older retained steps left")
            )
        raise ValueError(
            "restore failed at every retained checkpoint step — "
            + "; ".join(f"step {s}: {r}" for s, r in failures)
        ) from last_exc

    def _restore_step(self, session, step: int) -> int:
        """One step's restore (the pre-fallback restore semantics).

        Controlled sessions (control/ ladder): the checkpointed server
        state is laid out for the rung ACTIVE at save time, which a
        shape-changing ladder (num_cols/powersgd_rank) may make differ
        from the session's current template — restore walks the distinct
        rung layouts until one matches, then the restored ``control``
        blob re-activates the exact saved rung and policy state, so the
        resumed run reproduces the uninterrupted rung sequence."""
        candidates = self._rung_template_candidates(session)
        try:
            restored = None
            attempts = []  # (template label, exception) per failed layout
            for n, cand in enumerate(candidates):
                if cand is not None and cand != session.active_rung:
                    # rebuild the template in rung ``cand``'s layout; the
                    # migrated VALUES are irrelevant (overwritten on
                    # success) — only the shapes matter here
                    session.set_active_rung(cand, migrate=True)
                try:
                    restored = self._attempt_restore(
                        step, _to_saveable(session)
                    )
                    break
                except Exception as exc:  # noqa: BLE001 — try next layout
                    label = ("base template" if cand is None
                             else f"rung {cand} template")
                    attempts.append((label, exc))
                    if n == len(candidates) - 1:
                        if len(attempts) == 1:
                            raise
                        # every candidate failed: name EACH attempt and
                        # chain the FIRST (the active-rung template is
                        # tried first and is the likely save-time layout —
                        # a genuine corruption error there must not be
                        # masked by a later layout's shape mismatch)
                        raise ValueError(
                            "restore failed under every rung state "
                            "template — "
                            + "; ".join(
                                f"{lab}: {type(e).__name__}: {e}"
                                for lab, e in attempts
                            )
                            + " (the first attempt's failure is chained "
                            "as the cause)"
                        ) from attempts[0][1]
        except Exception as e:  # noqa: BLE001 — re-raise with provenance
            if session.spec is not None and self._saved_lacks_sketch_layout(
                step, e
            ):
                # NB the stamp's absence is the LIKELY cause, not a certain
                # one (review r5: a pre-stamp checkpoint can also fail for
                # an unrelated reason, e.g. a truncated write) — so the
                # original failure rides along in the message and as
                # __cause__.
                raise ValueError(
                    "restore failed and the checkpoint lacks the "
                    "sketch-layout stamp (pre-r4): its momentum/error "
                    "tables may have been written under a different "
                    "CountSketch layout (e.g. the pre-r4 scramble_block=8 "
                    "default) and cannot be safely decoded. Re-train, or "
                    "restore with a session whose "
                    "CountSketch(scramble_block=...) matches the run that "
                    "wrote the checkpoint. (If the layout is not the "
                    f"problem, the underlying failure was: {e})"
                ) from e
            raise
        if (getattr(session, "controller", None) is not None
                and "control" in restored):
            # activate the SAVED rung before the layout/shape checks below:
            # the restored leaves (and the sketch-layout stamp) are in that
            # rung's geometry, not necessarily the session's current one.
            # (Dispatch swap only — the leaves themselves load further
            # down; the controller's counters load after them.)
            saved_rung = int(np.asarray(restored["control"])[1])
            if 0 <= saved_rung < len(session.rungs):
                session.set_active_rung(saved_rung, migrate=False)
        if session.spec is not None and "sketch_layout" in restored:
            want = _spec_fingerprint(session.spec)
            got = np.asarray(restored["sketch_layout"])
            if not np.array_equal(want, got):
                raise ValueError(
                    "checkpoint sketch layout != this session's: the "
                    "[r, c] tables were written under a different "
                    f"CountSketch geometry (stamp {got.tolist()} vs "
                    f"{want.tolist()}; fields: d, c, r, num_blocks, seed, "
                    "chunk_m, sblock, band, d_eff, c_actual, "
                    "hash_family) — decoding them here would corrupt "
                    "training silently. Match the spec (e.g. pin "
                    "scramble_block) or re-train."
                )
        if restored["grad_size"] != session.grad_size:
            raise ValueError(
                f"checkpoint grad_size {restored['grad_size']} != model "
                f"{session.grad_size} — wrong model/config for this checkpoint"
            )
        fs = restored["fed_state"]
        # shared leaf-commit path (also the resilience RollbackVault's):
        # every leaf back onto its mesh sharding, missing legacy leaves
        # kept fresh with a warning
        session.state = commit_fed_state(
            session, fs, origin=f"checkpoint at step {step}"
        )
        if "host_vel" in restored:
            session.host_vel = np.asarray(restored["host_vel"])
        if "host_err" in restored:
            session.host_err = np.asarray(restored["host_err"])
        if getattr(session, "controller", None) is not None:
            if "control" in restored:
                # re-activates the saved rung (the restored leaves are
                # already in its layout — dispatch swap only, no
                # migration) + the policy's decision state, so the
                # resumed rung sequence is bit-identical to the
                # uninterrupted run's
                session.controller.load_state_blob(restored["control"])
            else:
                warnings.warn(
                    f"checkpoint at step {step} predates the adaptive-"
                    "communication controller; restored everything else — "
                    "the controller starts fresh (initial rung, zero byte "
                    "spend), so the resumed rung sequence is NOT the "
                    "uninterrupted run's"
                )
        if "blacklist" in restored:
            # resilience/ skip_clients: re-condemn the clients a recovery
            # blacklisted before the save — blacklist_clients validates
            # the session can actually mask them (fedsim), so a config
            # mismatch fails loudly instead of silently re-admitting them
            bl = np.asarray(restored["blacklist"], np.int64).ravel()
            if bl.size:
                session.blacklist_clients(bl)
        # the fedsim availability/chaos schedule keys off a host round
        # clock mirroring FedState.step — re-sync it so a resumed run
        # realizes the SAME masks the uninterrupted run would have
        session.sync_round_clock()
        return int(np.asarray(fs["step"]))

    def close(self):
        """Release the Orbax manager. Idempotent: the shared runner closes
        it in its ``finally`` block (crash paths included), and the train
        entries' own ``finally`` may close again — the second call is a
        no-op, not a double-close error."""
        if self.mngr is not None:
            self.mngr.close()
            self.mngr = None
