"""Learning-rate schedules.

The reference uses a cifar10-fast-style piecewise-linear schedule: 0 at
epoch 0, peaking at ``lr_scale`` at ``pivot_epoch``, decaying to 0 at
``num_epochs`` (``cv_train.py`` ~L30-120, SURVEY.md §2 "cv_train entry").
Expressed here as a pure function of the (possibly traced) step index so it
lives happily inside jit.
"""

from __future__ import annotations

import jax.numpy as jnp


def piecewise_linear_lr(
    step,
    *,
    steps_per_epoch: int,
    pivot_epoch: float,
    num_epochs: float,
    lr_scale: float,
):
    """LR at a given optimizer step (step may be a traced int array).

    Host ints take the pure-Python path: the jnp version puts a scalar op
    on the device EVERY round and the train loop's ``float(lr_fn(step))``
    then pays a full host<->device round trip (~100-400 ms through a TPU
    tunnel) — measured as 40 of a 42 s ResNet-9 epoch.
    """
    if isinstance(step, (int, float)):
        epoch = (step + 1) / steps_per_epoch
        up = epoch / max(pivot_epoch, 1e-8)
        down = (num_epochs - epoch) / max(num_epochs - pivot_epoch, 1e-8)
        return lr_scale * min(max(min(up, down), 0.0), 1.0)
    epoch = (step + 1) / steps_per_epoch
    up = epoch / jnp.maximum(pivot_epoch, 1e-8)
    down = (num_epochs - epoch) / jnp.maximum(num_epochs - pivot_epoch, 1e-8)
    frac = jnp.clip(jnp.minimum(up, down), 0.0, 1.0)
    return lr_scale * frac
