"""Version portability for the two JAX APIs the round engine leans on.

The parallel layer is written against the current ``jax.shard_map`` +
varying-manual-axes (vma) API: replicated inputs are explicitly marked
``pcast(..., to="varying")`` where AD must stay shard-local (round.py's
worker gradients), and left unvarying where the transpose's automatic
psum over the axis is the wanted behavior (tensor.py's TP/SP loss).

Older JAX (<= 0.4.x, e.g. the 0.4.37 in some lab containers) predates
both names: ``shard_map`` lives in ``jax.experimental.shard_map`` and
there is no vma system at all — in-body AD is always shard-local, which
is exactly the semantics the vma code gets via its explicit
``pcast(to="varying")``. So on old JAX:

  * ``shard_map`` delegates to the experimental module with
    ``check_rep=False`` (the rep checker is the part of the old API the
    vma-era out_specs were never written for);
  * ``pcast`` is the identity — the varying mark it would set is the
    old default.

The one semantic the old API cannot reproduce automatically is the
UNVARYING side: grad-of-replicated-params auto-psumming over a mesh axis
(tensor.py's model/seq loss relies on it — each model/seq shard computes
only ITS slice of the backward, and current JAX's vma transpose inserts
the psum that totals them). ``grad_extra_axes_psum`` below restores it
explicitly on old JAX (and is a no-op on vma JAX, where an explicit psum
on top of the automatic one would double-count). Everything on the
``workers`` axis (the whole federated round) is exact under both APIs
with no help.

All parallel-layer call sites import ``shard_map``/``pcast`` from here
instead of ``jax`` so the choice is made in one place.
"""

from __future__ import annotations

import jax

HAS_VMA = hasattr(jax, "shard_map")

if HAS_VMA:
    shard_map = jax.shard_map

    if hasattr(jax.lax, "pcast"):

        def pcast(x, axis_name, *, to):
            return jax.lax.pcast(x, axis_name, to=to)

    else:  # the 0.6.x window: shard_map is public but pcast is not yet
        # in jax.lax — only the one-way pvary (unvarying -> varying),
        # which is the only direction this codebase uses
        def pcast(x, axis_name, *, to):
            if to != "varying":
                raise NotImplementedError(
                    f"pcast(to={to!r}) needs jax.lax.pcast; this JAX only "
                    "provides pvary (to='varying')"
                )
            return jax.lax.pvary(x, axis_name)

else:  # pre-vma JAX
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, **kw):
        kw.setdefault("check_rep", False)
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )

    def pcast(x, axis_name, *, to):  # noqa: ARG001 — signature parity
        return x


def grad_extra_axes_psum(g, mesh, primary_axis):
    """Total a shard-local param-gradient over the mesh axes BEYOND the
    data axis — only on pre-vma JAX, only when such axes exist.

    Must be called INSIDE the round's shard_map, immediately after the
    raw gradient (before weight decay / clipping / DP noise, which apply
    to the TOTAL gradient exactly once). On vma JAX the value_and_grad
    transpose already summed over the unvarying model/seq axes, so this
    returns ``g`` untouched.

    Why pmean and not psum: pre-vma JAX keeps the legacy cyclic transpose
    ``T(psum) = psum`` inside shard_map bodies (the exact problem the vma
    redesign solved), so the cotangent arriving below the loss's final
    psum chain carries an extra factor of the axis size n — the per-shard
    gradients SUM to n x the true total. Measured on the TP/SP GPT-2 loss
    (model=2 / seq=2 / both): per-shard-sum norm is exactly n x the dense
    reference, and the MEAN matches it to 1.6e-7 max over all params.
    ``pmean`` therefore performs the correct totaling: psum / n.
    """
    if HAS_VMA or mesh is None:
        return g
    primary = (
        {primary_axis} if isinstance(primary_axis, str) else set(primary_axis)
    )
    extra = tuple(
        a
        for a, n in zip(mesh.axis_names, mesh.devices.shape)
        if a not in primary and n > 1
    )
    return jax.lax.pmean(g, extra) if extra else g


def grads_unreplicated_pmean(grads, specs, mesh):
    """Per-param version of the same correction for steps that apply their
    update INSIDE the shard_map (tensor.build_tp3d_train_step): total each
    gradient leaf over every mesh axis its param is REPLICATED on (absent
    from its PartitionSpec), leaving sharded-axis grads shard-local.

    No-op on vma JAX — there the transpose of an unvarying param already
    inserts this psum. Pre-vma, two legacy-transpose inflations must be
    undone (both measured EXACTLY on the tp3d step, pinned by
    tests/test_tensor_parallel.py::test_tp3d_train_step_matches_single_
    device_sgd):

      * replicated axes: same calibration as ``grad_extra_axes_psum`` —
        per-shard grads sum to n x the total, so their MEAN is the total
        (pmean over the axes absent from the spec);
      * sharded axes: the cotangent of a row/column-parallel param crosses
        that axis's activation psum exactly ONCE on every path (the
        Megatron pattern tensor.py uses — no compounding through the
        residual stream; measured ratio is exactly the axis size for
        every sharded leaf), and nothing averages it back out because the
        shard keeps its own slice — divide by the axis size explicitly.

    Must be called inside the shard_map body, on the raw grads, before
    the update."""
    if HAS_VMA or mesh is None:
        return grads

    def one(g, spec):
        used = set()
        for part in spec:
            if part is None:
                continue
            used.update(part if isinstance(part, tuple) else (part,))
        extra, sharded_n = (), 1
        for a, n in zip(mesh.axis_names, mesh.devices.shape):
            if n <= 1:
                continue
            if a in used:
                sharded_n *= n
            else:
                extra += (a,)
        if extra:
            g = jax.lax.pmean(g, extra)
        return g / sharded_n if sharded_n > 1 else g

    return jax.tree.map(one, grads, specs)
