"""Typed configuration system.

The reference threads a single flat ``argparse.Namespace`` through every
layer (``utils.py parse_args`` ~L20-180, SURVEY.md §2 "Config system"). We
keep the *flag names* for run-command parity (``--mode``, ``--k``,
``--num_rows``, ...), but back them with a frozen dataclass so the config is
hashable (usable as a static jit argument), documented, and validated at
construction instead of at first crash.
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass
from typing import Optional

MODES = ("uncompressed", "sketch", "true_topk", "local_topk", "fedavg")
ERROR_TYPES = ("none", "local", "virtual")


@dataclass(frozen=True)
class Config:
    """All knobs of a federated run. Field names follow the reference flags."""

    # --- compression / mode (reference: --mode, --k, --num_rows, --num_cols,
    # --num_blocks) ---
    mode: str = "uncompressed"
    k: int = 50_000  # sparsity of the extracted update (sketch/topk modes)
    # top-k selection kernel: "exact" (lax.top_k), "threshold" (binary-
    # searched magnitude threshold, ≤k nonzeros, no sort/scatter — the TPU
    # fast path), "approx" (lax.approx_max_k, ~0.95 recall).
    topk_method: str = "exact"
    num_rows: int = 5  # sketch rows r
    num_cols: int = 500_000  # sketch columns c
    num_blocks: int = 1  # memory chunking for full-d unsketch estimates
    do_topk_down: bool = False  # top-k compress the downlink too

    # --- momentum / error feedback (reference: --virtual_momentum,
    # --local_momentum, --error_type) ---
    virtual_momentum: float = 0.0  # server-side momentum factor rho
    local_momentum: float = 0.0  # per-client momentum factor
    error_type: str = "none"  # where error feedback lives

    # --- federation shape (reference: --num_clients, --num_workers,
    # --num_devices, --local_batch_size, --iid / --non_iid) ---
    num_clients: int = 16  # total virtual clients
    num_workers: int = 8  # participating clients per round
    num_devices: int = 1  # mesh size the workers are multiplexed onto
    local_batch_size: int = 8  # per-client batch per round
    iid: bool = True  # IID vs pathological-non-IID client sharding

    # --- fedavg (reference: --num_local_iters, --local_lr) ---
    num_local_iters: int = 1
    # None (default): local SGD steps run at the server schedule's current
    # lr and the net applied delta is the true FedAvg averaged weight delta.
    # Setting it decouples local from server lr (see round.py docstring).
    local_lr: Optional[float] = None

    # --- optimization (reference: --lr_scale, --pivot_epoch, --num_epochs,
    # --max_grad_norm, --weight_decay, --momentum_type) ---
    lr_scale: float = 0.4
    pivot_epoch: int = 5
    num_epochs: int = 24
    max_grad_norm: Optional[float] = None
    weight_decay: float = 5e-4
    # Zero momentum at the extracted/transmitted coordinates ("momentum
    # masking"/dampening). None = AUTO: True for the dense modes
    # (true_topk/local_topk — the reference's server and worker helpers
    # zero velocity at sent coords; measured: unmasked momentum overshoots
    # and true_topk decays from 0.47 to 0.10 over 24 epochs), False for
    # sketch (FetchSGD Alg 1 does not mask sketched momentum, and masking
    # via noisy estimates destabilizes — see round.py warning).
    momentum_dampening: Optional[bool] = None

    # --- model / dataset (reference: --model, --dataset_name,
    # --dataset_dir) ---
    model: str = "resnet9"
    dataset_name: str = "cifar10"
    dataset_dir: str = "./data"
    # None (default): derived from dataset_name (cifar10->10, cifar100->100,
    # femnist->62, imagenet->1000) — guards against silently training a
    # 10-class head on ImageNet (VERDICT r1 weak 6).
    num_classes: Optional[int] = None

    # --- GPT-2 workload (reference: --model_checkpoint, --num_candidates,
    # --max_history, --lm_coef, --mc_coef) ---
    model_checkpoint: str = "gpt2"
    num_candidates: int = 2
    max_history: int = 2
    lm_coef: float = 1.0
    mc_coef: float = 1.0
    max_seq_len: int = 256

    # --- privacy (reference: DP clip+noise flags, fed_worker.py ~L380-420) ---
    dp_noise_multiplier: float = 0.0

    # --- TPU fast path ---
    # Fuse the per-device clients' gradients into ONE flattened-batch grad
    # (2x faster than the per-client vmap on v5e). Mathematically identical
    # to the reference's average-of-per-client-gradients whenever no
    # per-client state/clip/noise is configured AND every sample carries
    # valid labels (true for the CV workloads; for GPT-2's masked LM loss
    # the flat mean weights clients by token count instead of equally, so
    # leave it off there). Ignored (vmap path used) for fedavg/local_topk
    # or when local momentum / local error / clip / DP noise is on.
    fuse_clients: bool = False

    # Keep the whole (uint8) training set resident in device HBM and ship
    # only [W, B] sample indices + the augmentation plan each round (~KBs
    # instead of the pixel batch). The host->device link is the real train
    # loop's bottleneck on tunneled TPUs (~40 MB/s measured); CIFAR-scale
    # sets (154 MB) fit HBM trivially. Auto-disabled by cv_train when the
    # dataset exceeds device_data_max_mb or the mode needs host batches.
    device_data: bool = True
    device_data_max_mb: int = 512

    # --- memory (TPU-native; SURVEY.md §7 hard-parts) ---
    # Keep [num_clients, D] client momentum/error rows in host RAM and move
    # only the round's W participant rows across PCIe — required at GPT-2
    # scale where num_clients * D does not fit HBM.
    offload_client_state: bool = False
    # Sketch matmul dtype ("float32" | "bfloat16"). Measured r2: NO speed
    # or accuracy difference on v5e (default f32 matmul precision is
    # already bf16-pass and the round is not matmul-bound) — kept as an
    # explicit knob for hardware where it matters.
    sketch_dtype: str = "float32"
    # CountSketch banded-bucket width (ops/countsketch.py v5): each chunk's
    # collision pool is band*stride buckets; larger = closer to classic
    # sketch statistics (stabler FetchSGD feedback), smaller = cheaper
    # matmuls. band=16 measured stable at paper-scale d/c=13.
    sketch_band: int = 16

    # --- misc (reference: --seed, --mesh shape additions are ours) ---
    seed: int = 42
    checkpoint_dir: str = ""
    checkpoint_every: int = 0  # rounds between checkpoints; 0 = off
    resume: bool = False
    tensorboard: bool = False
    logdir: str = "runs"
    profile_dir: str = ""  # jax.profiler trace of a few steady-state rounds
    # NB deliberate non-flags: sequence parallelism (ring attention) and the
    # model/seq mesh axes are library capabilities (parallel.make_mesh,
    # parallel.sequence.sp_gpt2_apply), not round-engine config — the
    # federated round itself is data-parallel, as in the reference.

    def __post_init__(self):
        if self.mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {self.mode!r}")
        if self.error_type not in ERROR_TYPES:
            raise ValueError(
                f"error_type must be one of {ERROR_TYPES}, got {self.error_type!r}"
            )
        if self.topk_method not in ("exact", "threshold", "approx"):
            raise ValueError(
                f"topk_method must be exact|threshold|approx, got {self.topk_method!r}"
            )
        if self.sketch_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"sketch_dtype must be float32|bfloat16, got {self.sketch_dtype!r}"
            )
        if self.num_workers % self.num_devices != 0:
            raise ValueError(
                "num_workers must be divisible by num_devices "
                f"({self.num_workers} % {self.num_devices} != 0)"
            )
        if self.num_clients < self.num_workers:
            raise ValueError("num_clients must be >= num_workers")

    @property
    def clients_per_device(self) -> int:
        return self.num_workers // self.num_devices

    @property
    def sampler_batch_size(self) -> int:
        """Samples the sampler draws per client per round. THE fedavg
        convention, kept in one place: a fedavg round batch carries
        ``num_local_iters`` microbatches of ``local_batch_size`` each."""
        return self.local_batch_size * (
            self.num_local_iters if self.mode == "fedavg" else 1
        )

    @property
    def resolved_num_classes(self) -> int:
        """num_classes if set, else derived from dataset_name."""
        if self.num_classes is not None:
            return self.num_classes
        return {"cifar10": 10, "cifar100": 100, "femnist": 62,
                "imagenet": 1000}.get(self.dataset_name, 10)

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)


def _add_flags(p: argparse.ArgumentParser) -> None:
    """One flag per Config field, reference-compatible names."""
    for f in dataclasses.fields(Config):
        name = "--" + f.name
        default = f.default
        ann = str(f.type)
        if f.type in ("bool", bool) or isinstance(default, bool):
            p.add_argument(
                name,
                type=lambda s: s.lower() in ("1", "true", "yes"),
                nargs="?",
                const=True,
                default=default,
            )
        elif "Optional" in ann or "None" in ann:
            if "bool" in ann:  # tri-state: None (auto) | true | false
                p.add_argument(
                    name,
                    type=lambda s: s.lower() in ("1", "true", "yes"),
                    nargs="?",
                    const=True,
                    default=default,
                )
            else:
                inner = float if "float" in ann else (int if "int" in ann else str)
                p.add_argument(name, type=inner, default=default)
        else:
            p.add_argument(name, type=type(default), default=default)


def parse_args(argv=None, defaults=None, **overrides) -> Config:
    """CLI -> Config. The analog of the reference's ``utils.parse_args``.

    ``defaults`` changes parser defaults (still user-overridable on the CLI,
    e.g. gpt2_train sets ``model="gpt2"``); ``overrides`` win over the CLI.
    """
    p = argparse.ArgumentParser(description="commefficient_tpu")
    _add_flags(p)
    if defaults:
        p.set_defaults(**defaults)
    ns = p.parse_args(argv)
    d = vars(ns)
    d.update(overrides)
    return Config(**d)
