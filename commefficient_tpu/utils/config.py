"""Typed configuration system.

The reference threads a single flat ``argparse.Namespace`` through every
layer (``utils.py parse_args`` ~L20-180, SURVEY.md §2 "Config system"). We
keep the *flag names* for run-command parity (``--mode``, ``--k``,
``--num_rows``, ...), but back them with a frozen dataclass so the config is
hashable (usable as a static jit argument), documented, and validated at
construction instead of at first crash.
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass
from typing import Optional

MODES = ("uncompressed", "sketch", "true_topk", "local_topk", "fedavg")
ERROR_TYPES = ("none", "local", "virtual")


@dataclass(frozen=True)
class Config:
    """All knobs of a federated run. Field names follow the reference flags."""

    # --- compression / mode (reference: --mode, --k, --num_rows, --num_cols,
    # --num_blocks) ---
    mode: str = "uncompressed"
    k: int = 50_000  # sparsity of the extracted update (sketch/topk modes)
    num_rows: int = 5  # sketch rows r
    num_cols: int = 500_000  # sketch columns c
    num_blocks: int = 1  # memory chunking for full-d unsketch estimates
    do_topk_down: bool = False  # top-k compress the downlink too

    # --- momentum / error feedback (reference: --virtual_momentum,
    # --local_momentum, --error_type) ---
    virtual_momentum: float = 0.0  # server-side momentum factor rho
    local_momentum: float = 0.0  # per-client momentum factor
    error_type: str = "none"  # where error feedback lives

    # --- federation shape (reference: --num_clients, --num_workers,
    # --num_devices, --local_batch_size, --iid / --non_iid) ---
    num_clients: int = 16  # total virtual clients
    num_workers: int = 8  # participating clients per round
    num_devices: int = 1  # mesh size the workers are multiplexed onto
    local_batch_size: int = 8  # per-client batch per round
    iid: bool = True  # IID vs pathological-non-IID client sharding

    # --- fedavg (reference: --num_local_iters, --local_lr) ---
    num_local_iters: int = 1
    local_lr: float = 0.1

    # --- optimization (reference: --lr_scale, --pivot_epoch, --num_epochs,
    # --max_grad_norm, --weight_decay, --momentum_type) ---
    lr_scale: float = 0.4
    pivot_epoch: int = 5
    num_epochs: int = 24
    max_grad_norm: Optional[float] = None
    weight_decay: float = 5e-4
    momentum_dampening: bool = False  # zero momentum at HH coords after send

    # --- model / dataset (reference: --model, --dataset_name,
    # --dataset_dir) ---
    model: str = "resnet9"
    dataset_name: str = "cifar10"
    dataset_dir: str = "./data"
    num_classes: int = 10

    # --- GPT-2 workload (reference: --model_checkpoint, --num_candidates,
    # --max_history, --lm_coef, --mc_coef) ---
    model_checkpoint: str = "gpt2"
    num_candidates: int = 2
    max_history: int = 2
    lm_coef: float = 1.0
    mc_coef: float = 1.0
    max_seq_len: int = 256

    # --- privacy (reference: DP clip+noise flags, fed_worker.py ~L380-420) ---
    dp_noise_multiplier: float = 0.0

    # --- misc (reference: --seed, --mesh shape additions are ours) ---
    seed: int = 42
    checkpoint_dir: str = ""
    checkpoint_every: int = 0  # rounds between checkpoints; 0 = off
    resume: bool = False
    tensorboard: bool = False
    logdir: str = "runs"
    # TPU-native extensions (no reference equivalent): extra mesh axes.
    tensor_parallel: int = 1
    sequence_parallel: int = 1

    def __post_init__(self):
        if self.mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {self.mode!r}")
        if self.error_type not in ERROR_TYPES:
            raise ValueError(
                f"error_type must be one of {ERROR_TYPES}, got {self.error_type!r}"
            )
        if self.num_workers % self.num_devices != 0:
            raise ValueError(
                "num_workers must be divisible by num_devices "
                f"({self.num_workers} % {self.num_devices} != 0)"
            )
        if self.num_clients < self.num_workers:
            raise ValueError("num_clients must be >= num_workers")

    @property
    def clients_per_device(self) -> int:
        return self.num_workers // self.num_devices

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)


def _add_flags(p: argparse.ArgumentParser) -> None:
    """One flag per Config field, reference-compatible names."""
    for f in dataclasses.fields(Config):
        name = "--" + f.name
        default = f.default
        ann = str(f.type)
        if f.type in ("bool", bool) or isinstance(default, bool):
            p.add_argument(
                name,
                type=lambda s: s.lower() in ("1", "true", "yes"),
                nargs="?",
                const=True,
                default=default,
            )
        elif "Optional" in ann or "None" in ann:
            inner = float if "float" in ann else (int if "int" in ann else str)
            p.add_argument(name, type=inner, default=default)
        else:
            p.add_argument(name, type=type(default), default=default)


def parse_args(argv=None, **overrides) -> Config:
    """CLI -> Config. The analog of the reference's ``utils.parse_args``."""
    p = argparse.ArgumentParser(description="commefficient_tpu")
    _add_flags(p)
    ns = p.parse_args(argv)
    d = vars(ns)
    d.update(overrides)
    return Config(**d)
