"""Typed configuration system.

The reference threads a single flat ``argparse.Namespace`` through every
layer (``utils.py parse_args`` ~L20-180, SURVEY.md §2 "Config system"). We
keep the *flag names* for run-command parity (``--mode``, ``--k``,
``--num_rows``, ...), but back them with a frozen dataclass so the config is
hashable (usable as a static jit argument), documented, and validated at
construction instead of at first crash.
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass
from typing import Optional

# mirrors the compress/ registry (compress.available_modes); the two are
# pinned equal by tests/test_mode_dispatch.py
MODES = ("uncompressed", "sketch", "true_topk", "local_topk", "fedavg",
         "powersgd")
ERROR_TYPES = ("none", "local", "virtual")
# mirrors the fedsim/ availability registry (fedsim.available_models);
# pinned equal by tests/test_fedsim.py — same no-cycle pattern as MODES
AVAILABILITY_MODELS = ("always", "bernoulli", "cohort", "poisson", "sine")
# mirrors the control/ policy registry (control.CONTROL_POLICIES); pinned
# equal by tests/test_control.py — same no-cycle pattern as MODES
CONTROL_POLICIES = ("none", "fixed", "budget_pacing", "ef_feedback",
                    "staleness_aware")
# mirrors the resilience/ recovery-policy registry (resilience.policy
# POLICIES); pinned equal by tests/test_mode_dispatch.py — same no-cycle
# pattern as MODES/CONTROL_POLICIES
RECOVER_POLICIES = ("none", "retry", "demote", "skip_clients")
# mirrors the clientstore/ store registry (clientstore.available_stores);
# pinned equal by tests/test_clientstore.py — same no-cycle pattern as MODES
CLIENT_STORES = ("device", "host", "mmap")


@dataclass(frozen=True)
class Config:
    """All knobs of a federated run. Field names follow the reference flags."""

    # --- compression / mode (reference: --mode, --k, --num_rows, --num_cols,
    # --num_blocks) ---
    mode: str = "uncompressed"
    k: int = 50_000  # sparsity of the extracted update (sketch/topk modes)
    # top-k selection kernel: "exact" (lax.top_k), "threshold" (binary-
    # searched magnitude threshold, ≤k nonzeros, no sort/scatter — the TPU
    # fast path), "approx" (lax.approx_max_k, ~0.95 recall).
    topk_method: str = "exact"
    num_rows: int = 5  # sketch rows r
    num_cols: int = 500_000  # sketch columns c
    # >1 bounds full-d unsketch-estimate transients to r*D/num_blocks via
    # the exact-gather path (slower; reference --num_blocks memory trade)
    num_blocks: int = 1
    do_topk_down: bool = False  # top-k compress the downlink too

    # --- powersgd (compress/powersgd.py; PowerSGD, arXiv:1905.13727) ---
    # rank r of the warm-started power-iteration approximation; the flat
    # [D] update is matricized near-square [n, m] (n ~ m ~ sqrt(D)), so the
    # factored downlink is r*(n+m) floats — compression ~ sqrt(D)/(2r).
    powersgd_rank: int = 4
    # carry Q = M^T P_hat across rounds in FedState (the paper's warm
    # start — one power iteration per round then tracks the top subspace);
    # False resamples a fresh Gaussian Q from (seed, step) each round.
    powersgd_warm_start: bool = True

    # --- momentum / error feedback (reference: --virtual_momentum,
    # --local_momentum, --error_type) ---
    virtual_momentum: float = 0.0  # server-side momentum factor rho
    local_momentum: float = 0.0  # per-client momentum factor
    error_type: str = "none"  # where error feedback lives

    # --- federation shape (reference: --num_clients, --num_workers,
    # --num_devices, --local_batch_size, --iid / --non_iid) ---
    num_clients: int = 16  # total virtual clients
    num_workers: int = 8  # participating clients per round
    num_devices: int = 1  # mesh size the workers are multiplexed onto
    local_batch_size: int = 8  # per-client batch per round
    iid: bool = True  # IID vs pathological-non-IID client sharding

    # --- fedavg (reference: --num_local_iters, --local_lr) ---
    num_local_iters: int = 1
    # None (default): local SGD steps run at the server schedule's current
    # lr and the net applied delta is the true FedAvg averaged weight delta.
    # Setting it decouples local from server lr (see round.py docstring).
    local_lr: Optional[float] = None

    # --- optimization (reference: --lr_scale, --pivot_epoch, --num_epochs,
    # --max_grad_norm, --weight_decay, --momentum_type) ---
    lr_scale: float = 0.4
    pivot_epoch: int = 5
    num_epochs: int = 24
    max_grad_norm: Optional[float] = None
    weight_decay: float = 5e-4
    # Zero momentum at the extracted/transmitted coordinates ("momentum
    # masking"/dampening). None = AUTO, resolved per mode on the
    # r4 four-corner evidence (see round.py build_round_fn): local_topk ->
    # True (reference behavior, applies with local momentum); true_topk ->
    # False (r4: unmasked 0.8923 vs masked 0.8595 at tuned lr on the v3
    # task — the earlier overshoot reading was a v2-task artifact; the
    # reference masks here, so set True explicitly for exact reference
    # behavior); sketch -> False (FetchSGD Alg 1; masking via noisy
    # estimates destabilizes — see round.py warning).
    momentum_dampening: Optional[bool] = None
    # momentum_dampening=True with mode=sketch subtracts sketches of NOISY
    # momentum estimates every round and measurably diverges at paper-scale
    # settings (round.py warning; ~step 70 where unmasked converges). It is
    # kept only for parity experiments and must be opted into explicitly.
    allow_unstable_sketch_dampening: bool = False
    # Virtual-error decay gamma: e <- gamma * e after each round's
    # extract-and-subtract (sketch + true_topk virtual error). 1.0 (default,
    # reference behavior) carries residual error indefinitely; < 1.0 leaks
    # stale error mass — the d/c-envelope mitigation probed by the r4 lab
    # (high d/c diverges through error-feedback SNR collapse; see
    # CHANGELOG_r3 regime account and scripts/sketch_lab.py --error_decay).
    error_decay: float = 1.0

    # --- model / dataset (reference: --model, --dataset_name,
    # --dataset_dir) ---
    model: str = "resnet9"
    dataset_name: str = "cifar10"
    dataset_dir: str = "./data"
    # Stand-in generator used when the real dataset is absent (zero-egress
    # environments): "flat" (legacy template+noise; gradient spectrum is
    # unrealistically flat — FetchSGD's heavy-hitter premise fails on it by
    # construction) or "concentrated" (shared low-rank backbone + localized
    # per-class texture patches + label noise; ResNet-9 gradients
    # concentrate like real CIFAR's — see scripts/grad_probe.py).
    synthetic_variant: str = "flat"
    # Label-noise fraction for the synthetic FEMNIST stand-in
    # (data/emnist.py): that fraction of each client's samples is relabeled
    # uniformly within the client's OWN class subset (non-IID structure
    # preserved), bounding the accuracy ceiling below 1.0 (see
    # _synthetic_femnist's ceiling math). Default 0.06 is the r5 value;
    # exposed so the pre-r5 (r4) noise-free stand-in is reconstructible for
    # audit with --label_noise 0 (ADVICE.md round-5 item). Ignored when
    # real LEAF data is on disk, and by the CIFAR synthetic (which has its
    # own fixed recipe).
    label_noise: float = 0.06
    # None (default): derived from dataset_name (cifar10->10, cifar100->100,
    # femnist->62, imagenet->1000) — guards against silently training a
    # 10-class head on ImageNet (VERDICT r1 weak 6).
    num_classes: Optional[int] = None

    # --- GPT-2 workload (reference: --model_checkpoint, --num_candidates,
    # --max_history, --lm_coef, --mc_coef) ---
    model_checkpoint: str = "gpt2"
    num_candidates: int = 2
    max_history: int = 2
    lm_coef: float = 1.0
    mc_coef: float = 1.0
    max_seq_len: int = 256

    # --- privacy (reference: DP clip+noise flags, fed_worker.py ~L380-420) ---
    dp_noise_multiplier: float = 0.0

    # --- TPU fast path ---
    # Fuse the per-device clients' gradients into ONE flattened-batch grad
    # (2x faster than the per-client vmap on v5e). Mathematically identical
    # to the reference's average-of-per-client-gradients whenever no
    # per-client state/clip/noise is configured AND every sample carries
    # valid labels (true for the CV workloads; for GPT-2's masked LM loss
    # the flat mean weights clients by token count instead of equally, so
    # leave it off there). Ignored (vmap path used) for fedavg/local_topk
    # or when local momentum / local error / clip / DP noise is on.
    fuse_clients: bool = False

    # Keep the whole (uint8) training set resident in device HBM and ship
    # only [W, B] sample indices + the augmentation plan each round (~KBs
    # instead of the pixel batch). The host->device link is the real train
    # loop's bottleneck on tunneled TPUs (~40 MB/s measured); CIFAR-scale
    # sets (154 MB) fit HBM trivially. Auto-disabled by cv_train when the
    # dataset exceeds device_data_max_mb or the mode needs host batches.
    device_data: bool = True
    device_data_max_mb: int = 512

    # --- memory (TPU-native; SURVEY.md §7 hard-parts) ---
    # Where the per-client momentum/error rows live (clientstore/ registry):
    # "device" (default — today's [num_clients, D] device arrays inside
    # FedState, bit-untouched; NOTHING clientstore-related is constructed,
    # the telemetry_level-0 discipline), "host" (pinned-numpy bank in host
    # RAM; only the round's W participant rows cross PCIe each round, C
    # bounded by host DRAM), "mmap" (the same cohort-streaming contract
    # over a memory-mapped file; C bounded by disk). host/mmap stream
    # cohort rows through the pipeline prefetcher when one is active and
    # write back asynchronously after the drain fence, so the compiled
    # round's HLO carries no [C, D]-scale gather and the strict O(W·k)
    # sparse-aggregate bound holds with no exemption (README
    # "Host-resident client state").
    client_store: str = "device"
    # LRU device cache capacity (rows) for hot cohort rows under a
    # host/mmap store — availability models make some clients far more
    # frequent than others, and a cached row skips both the host gather
    # and the H2D stage. 0 (default) = no cache (every round gathers from
    # the bank). Write-through-on-eviction keeps the bank authoritative.
    client_store_cache_rows: int = 0
    # Backing file for --client_store mmap ("" = a run-scoped temp file,
    # deleted on close). A named path persists across reopen — the store
    # contract pins gather-after-reopen equality.
    client_store_path: str = ""
    # DEPRECATED: whole-store host offload, superseded by the per-cohort
    # clientstore (--client_store host). Setting it warns and aliases to
    # client_store="host"; the flag will be removed.
    offload_client_state: bool = False
    # FSDP-shard the flat param vector AND dense server momentum/error over
    # the workers mesh axis (parallel/fsdp.py): persistent per-chip state
    # drops from up to 3x[D] to ~[D/W] (+ small replicated sketch tables).
    # Server modes only (uncompressed/true_topk/sketch, threshold top-k);
    # local modes shard their memory wall via --client_store host|mmap
    # instead.
    fsdp: bool = False
    # Model compute precision: "mixed" (default — flax module matmuls
    # bf16, params/residual-boundaries f32), "bfloat16" (params also cast
    # at the loss boundary: the FULL stream incl. GPT-2 embeddings/
    # residuals/tied head runs bf16 — an accuracy/memory control,
    # speed-neutral at single-chip microbatches per CHANGELOG_r3's
    # corrected measurement; see models/losses._resolve_compute_dtype), or
    # "float32" (true f32 throughout — the reference's precision).
    # Master params, gradients, compression, and the server update are
    # f32 in every mode; cross-entropies compute f32.
    compute_dtype: str = "mixed"
    # Sketch matmul dtype ("float32" | "bfloat16"). Measured r2: NO speed
    # or accuracy difference on v5e (default f32 matmul precision is
    # already bf16-pass and the round is not matmul-bound) — kept as an
    # explicit knob for hardware where it matters.
    sketch_dtype: str = "float32"
    # Sketch table STORAGE dtype ("float32" | "bfloat16") — distinct from
    # sketch_dtype (the matmul OPERAND dtype above). "bfloat16" stores
    # and psums the [r, c] tables in bf16 while every accumulation (the
    # in-row reductions, the server momentum/error algebra) stays f32:
    # table HBM traffic and the device_encode psum's collective bytes
    # halve (100 MB -> 50 MB per round per link at the GPT-2 5x5M
    # geometry), at ~2^-8 relative rounding per downcast — the compress/
    # LINEAR contract then holds to that pinned tolerance instead of
    # bit-exactly (tests/test_countsketch_bf16.py). "float32" (default)
    # is bit-untouched: every golden recording pins it.
    sketch_table_dtype: str = "float32"
    # Sketch-FUSED backward (parallel/round.py make_sketch_grad_one):
    # per-leaf custom_vjp taps sketch each param leaf's cotangent
    # directly into the [r, c] table during the backward pass, so
    # make_grad_one's ravel_pytree flat [D] grad — a 500 MB transient at
    # GPT-2 scale — is NEVER materialized in sketch mode (the compiled
    # round is pinned free of the flat_grad_concat marker). Linearity
    # makes it exact up to float summation order (pinned tolerance, not
    # bit-equal — hence opt-in; the default keeps golden parity
    # bit-untouched). Requires the fused flattened-batch path: mode=
    # sketch, fuse_clients, no local momentum/clip/DP-noise/fedsim
    # (validated at construction).
    sketch_fused_bwd: bool = False
    # CountSketch banded-bucket width (ops/countsketch.py v5): each chunk's
    # collision pool is band*stride buckets; larger = closer to classic
    # sketch statistics (stabler FetchSGD feedback), smaller = cheaper
    # matmuls. band=16 measured stable at paper-scale d/c=13.
    sketch_band: int = 16
    # Explicit CountSketch chunk size m (None = the measured adaptive rule,
    # ops/countsketch.py chunk_m). Lab knob for the d/c~100 regime.
    sketch_m: Optional[int] = None
    # Hash family: "fmix32" (production default) or "poly4" — seed-derived
    # 4-universal Mersenne polynomials, the reference csvec's guarantee
    # class, for lab A/B runs against fmix32 (see
    # ops/countsketch.py CountSketch.hash_family). With
    # sketch_backend="einsum" poly4 is CV-scale-only (host-materialized
    # [d_eff] sign vector); sketch_backend="pallas" evaluates the
    # polynomial in-kernel and lifts poly4 to GPT-2 scale.
    hash_family: str = "fmix32"
    # Sketch server-decode strategy for the REPLICATED round ("auto" |
    # "dense" | "sharded"). "dense": the legacy path — every chip
    # redundantly runs the full-D estimate_all -> top-k -> unsketch ->
    # re-sketch server extraction (at D=124M that IS the round; BENCH_r05
    # gpt2_sketch_vs_uncompressed=0.287). "sharded": the FSDP decode
    # discipline on replicated state — each chip estimates only its D/W
    # coordinate slice (estimate_at over offset global hashes), the
    # global top-<=k threshold uses scalar-only collectives, and ONE
    # ~W*k-pair all_gather of compacted candidates replaces the per-chip
    # full-D decode (requires topk_method='threshold'; mode='sketch').
    # "auto" (default): sharded exactly when it can win and cannot change
    # results — >1 worker device AND threshold top-k; single-device
    # rounds and exact/approx selections keep the dense path, so golden
    # recordings and CPU tier-1 defaults are bit-untouched. See README
    # "Sketch decode architecture".
    sketch_decode: str = "auto"
    # On-mesh aggregation strategy for the top-k modes ("auto" | "dense"
    # | "sparse"). "dense": the legacy full-[D] psum of the per-device
    # client-transmit sum. "sparse": the ops/collectives pair exchange —
    # compact the <=k-sparse transmit to (idx, val) buffers and move
    # O(W*k) pairs instead of O(D) slots (arXiv:2201.07598 style).
    # local_topk rebuilds the replicated dense aggregate from one
    # W*k-pair all_gather; true_topk re-homes server momentum/error onto
    # the workers axis (reduce-scatter aggregate + sharded threshold
    # select + candidate pair exchange, the FSDP decode discipline on the
    # replicated round — requires topk_method='threshold'); sketch keeps
    # its dense [r,c] table psum but rides the pair exchange for the
    # zero-HH EF re-sketch (sharded decode only). "auto" (default):
    # sparse exactly when it cannot change stored state shapes — mode
    # 'local_topk' AND >1 worker device AND topk_method='threshold';
    # 1-device meshes and every other mode keep the dense psum, so golden
    # recordings and level-0 HLO are bit-untouched. true_topk/sketch
    # engage only on an explicit "sparse" (their summation order or state
    # placement changes). See README "Sparse allreduce collective layer".
    aggregate: str = "auto"
    # Collective/compute overlap ("none" | "layerwise"). "layerwise"
    # chunks the round's aggregation collectives into independent
    # segments so XLA's latency-hiding scheduler can run them
    # concurrently with remaining compute: the sketch-FUSED backward
    # (sketch_fused_bwd) accumulates per-leaf-GROUP tables and psums
    # each group as its own collective the moment backprop finishes
    # producing it (FSDP-style bucketed overlap — early layers'
    # aggregation starts while later layers still differentiate), and
    # the sparse pair exchanges (local_topk / true_topk / the sketch
    # EF ride) split their W*k all_gather into segment gathers whose
    # ordered concatenation is BIT-equal to the monolithic gather
    # (pure data movement). Segmented psums are bit-equal to ONE psum
    # of the same segments (an all-reduce is elementwise; no
    # reassociation within a segment) — but per-GROUP table
    # accumulation reorders the per-chip cotangent fan-in, so the
    # fused-backward layerwise round tracks overlap="none" at the same
    # summation-order tolerance sketch_fused_bwd itself is pinned to.
    # "none" (default): nothing overlap-related is traced and the round
    # stays byte-identical to a pre-overlap build (the telemetry_level-0
    # discipline; golden recordings pin it). See README "Hiding the
    # collectives".
    overlap_collectives: str = "none"
    # CountSketch kernel backend for the matmul-path ops ("einsum" |
    # "pallas"). "einsum" (default): the banded one-hot einsum +
    # overlap-add — runs everywhere, the r1-r5 production path. "pallas":
    # tiled Pallas TPU kernels (ops/pallas/countsketch_kernels.py) that
    # generate hashes/signs/one-hots on the fly inside the kernel — no
    # [m, V] one-hot constant, no [nc, V] HBM round-trip, no [d_eff] sign
    # vector; targets the GPT-2-scale sketch-round gap (BENCH_r05:
    # sketch 0.50 s vs uncompressed 0.14 s). On CPU hosts the Pallas path
    # runs under interpret mode (slow; for tests/labs, not production).
    sketch_backend: str = "einsum"

    # --- mesh axes beyond the reference (TPU-native; VERDICT r2 item 3) ---
    # The federated round's mesh is (workers=num_devices, model=model_axis,
    # seq=seq_axis); total chips = product. model/seq > 1 shards each
    # client's loss COMPUTE (Megatron-style heads/MLP-hidden over `model`,
    # ring-attention tokens over `seq` — parallel/tensor.py
    # build_tp_flat_loss) while params/compression stay the replicated flat
    # vector, so every mode's server algebra is unchanged. Consumed by
    # gpt2_train; cv_train is data-parallel only (as is the reference).
    model_axis: int = 1
    seq_axis: int = 1
    # --- multi-host topology (commefficient_tpu/multihost/) ---
    # Declared host axis size: > 1 prepends a `hosts` axis to the mesh
    # ((hosts, workers, model, seq); parallel/mesh.py make_mesh), splits
    # the client population into per-host partitions (multihost/
    # topology.py), and routes every worker-axis collective over the
    # (hosts, workers) tuple. 1 (default) = the single-host 3-axis mesh,
    # byte-identical to a pre-multihost build. Works both with real
    # multi-process runs (--distributed) and mesh-faked on one process
    # (N virtual hosts over the local devices — the CI twin).
    num_hosts: int = 1
    # Call the jax.distributed bring-up at train entry (multihost/
    # bringup.py initialize_multihost): reads JAX_COORDINATOR_ADDRESS /
    # JAX_NUM_PROCESSES / JAX_PROCESS_ID and connects this process to
    # the pod before any device query. False (default): single-process —
    # mesh-faked multihost (num_hosts > 1) still works without it.
    distributed: bool = False
    # Bounded retry-with-backoff on the coordinator connect: a pod
    # bring-up races the coordinator process, so the first refused
    # connection is normal — retry up to N attempts total (exponential
    # backoff between them) before failing with an error naming the
    # coordinator address and the attempt count (multihost/bringup.py).
    distributed_connect_retries: int = 3

    # --- telemetry (commefficient_tpu/telemetry/; TPU-native, no reference
    # analog — the reference logs only train/loss + lr) ---
    # 0 = off (default): the jitted round is bit-identical to a pre-
    # telemetry program (nothing is traced; pinned by golden parity + the
    # HLO smoke test). 1 = health: diag/* norms + non-finite sentinel
    # in-graph, comm/* byte scalars, flight recorder. 2 = + compressor
    # fidelity (sketch round-trip estimation error — one extra sketch+
    # estimate pass per round; powersgd reconstruction residual — vector
    # ops only). See telemetry/ package docstring for per-level cost.
    telemetry_level: int = 0
    # Ring-buffer size of the divergence flight recorder: how many drained
    # round records ride in flight_<step>.json when a run goes non-finite
    # (telemetry/flight.py). Active at telemetry_level >= 1.
    flight_window: int = 16
    # Retrace budget for the jitted round (telemetry/xla_audit.py
    # RetraceSentinel): None (default) only counts — `xla/retraces` rides
    # the drained metrics at telemetry_level >= 1; an int N hard-fails
    # (RetraceError naming the offending argument-signature diff) on the
    # N+1-th retrace. A mid-run retrace silently recompiles the whole XLA
    # round — minutes at GPT-2 scale — so perf-critical runs should set 0.
    # The first trace is the expected compile and never counts.
    max_retraces: Optional[int] = None
    # Compiled-round XLA audit (telemetry/xla_audit.py) at train-entry
    # startup when telemetry_level >= 1: cost/memory analyses + HLO
    # collective walk -> perf_report.json + xla/* scalars. Costs ONE extra
    # AOT compile of the round (seconds at CV scale, minutes for GPT-2) —
    # set false to skip it on huge models where the double compile hurts.
    perf_audit: bool = True
    # Critical-path run report (telemetry/trace.py build_run_report):
    # written as run_report.json at train-loop close when telemetry_level
    # >= 1 — per-stage p50/p95 + attribution fractions + anomaly flags
    # over the recorded spans. Same opt-out discipline as perf_audit
    # (accuracy_run passes False so its headers never link a report that
    # will not exist). Free at level 0 either way (no spans recorder).
    run_report: bool = True

    # --- federated environment simulation (commefficient_tpu/fedsim/;
    # TPU-native — the reference assumes all num_workers arrive every
    # round) ---
    # Availability model emitting the per-round [num_workers] participation
    # mask from (round_idx, seed): "always" (default — nothing fedsim is
    # traced, the round stays bit-identical to a pre-fedsim build, same
    # discipline as --telemetry_level 0), "bernoulli" (iid per-client
    # dropout at dropout_prob), "sine" (diurnal: drop prob oscillates
    # 0..dropout_prob over availability_period rounds), "cohort"
    # (correlated outages: num_cohorts slot groups, each fully out with
    # prob dropout_prob). Masked clients transmit NOTHING and the server
    # renormalizes by the live count (fedsim/ package docstring).
    availability: str = "always"
    # Per-client drop probability (bernoulli), peak drop probability
    # (sine), or per-cohort outage probability (cohort). Must be in
    # [0, 1): 1.0 would drop every client every round and nothing would
    # ever train (a single all-dropped round is survivable — the guard
    # freezes params and flags fedsim/all_dropped — but a certainty of it
    # is a config error).
    dropout_prob: float = 0.0
    availability_period: int = 64  # sine period (rounds per diurnal cycle)
    num_cohorts: int = 4  # cohort model: slot i belongs to cohort i % n
    # poisson model: per-client arrival rate (1 / mean exponential delay,
    # in round-deadline units) — marginal participation probability is
    # 1 - exp(-rate), and rate=inf degenerates to "always" (delay 0).
    # Also paces the asyncfed/ continuous-time cohort arrival schedule
    # (asyncfed/schedule.py draws per-cohort delays at this rate).
    arrival_rate: float = 1.0
    # Scheduled chaos plan (fedsim/faults.py grammar): comma-separated
    # "kind@value[:rounds=A-B]" with kinds dropout (extra iid dropout),
    # straggler (deadline miss: excluded from aggregation + ledger live
    # bytes, local state untouched), nan_client (corrupt one live client's
    # payload at round value — proves the flight-recorder/DivergenceError
    # path; DETECTION needs telemetry_level >= 1), plus the elastic-fleet
    # events resize@W'/leave@n/join@n (deterministic per-round fleet
    # widths — the session prewarms a round program per realized width,
    # so a resize is a dispatch-table swap with zero retraces) and
    # shrink@W' (unscheduled loss: raises FleetShrinkError for the
    # resilience manager to roll back and re-enter at W'). Example:
    # "dropout@0.3:rounds=50-100,nan_client@120". Syntax validated here
    # (realized fleet widths via _validate_fleet); round indices are
    # validated against the run length at train-entry time (Config cannot
    # know steps_per_epoch).
    chaos: str = ""

    # --- pipelined round execution (commefficient_tpu/pipeline/;
    # TPU-native — the reference's host loop is fully serial) ---
    # Rounds of host-side round work (non-IID sampler draw + batch
    # assembly, fedsim environment realization, schedule lr, eager H2D
    # staging onto the mesh) realized AHEAD of the device by a background
    # worker thread, so round t+1's host serial time overlaps round t's
    # device compute. 0 (default): fully synchronous — NOTHING
    # pipeline-related is constructed and the round stays bit-identical
    # to a pre-pipeline build (the telemetry_level-0 discipline; golden
    # parity recordings pin it). Any depth is BIT-EXACT vs depth 0:
    # every prefetched input is a pure function of (seed, stream,
    # round_idx), controller decisions/drains keep their synchronous
    # order, and checkpoint saves fence the window (README "Pipelined
    # round execution" documents the determinism contract).
    pipeline_depth: int = 0
    # Scan-over-rounds device-resident execution (pipeline/scan_engine.py):
    # K > 1 executes K rounds per XLA dispatch via ``lax.scan`` on the
    # device-resident index path — sampler indices staged per EPOCH (one
    # H2D for the whole epoch's [spe, W, B] draws), telemetry packs
    # stacked by the scan and drained at scan exit, per-round python
    # dispatch overhead amortized K-fold. Blocks are CHOPPED at every
    # point the synchronous loop would act on state (epoch end,
    # checkpoint_every, snapshot_every, controller... see the engine
    # docstring), so the drained scalar sequence and the params are
    # pinned equal to K=1. 0/1 (default): the per-round dispatch path,
    # bit-untouched. Requires device_data (the index round) and is
    # mutually exclusive with the control plane, pipeline_depth and
    # preemption sources (validated at construction / train entry).
    scan_rounds: int = 0

    # --- buffered-asynchronous federation (commefficient_tpu/asyncfed/;
    # FedBuff-style — the reference's round is a synchronous barrier
    # over num_workers) ---
    # K: the server applies an update once K of the in-flight cohorts'
    # contributions have arrived. 0 (default): synchronous rounds —
    # NOTHING asyncfed-related is constructed and the round stays
    # bit-identical to a pre-asyncfed build (the telemetry_level-0 /
    # pipeline_depth-0 discipline). The correctness anchor:
    # async_buffer=num_workers with async_concurrency=1 and
    # staleness_exponent=0 reduces BIT-IDENTICALLY to the synchronous
    # round across every mode/error-type/fedsim combination
    # (tests/test_asyncfed.py pins it).
    async_buffer: int = 0
    # C: cohorts kept in flight concurrently. Each cohort is a full
    # W-slot launch against the server params AT ITS LAUNCH VERSION;
    # contributions from different cohorts interleave in the arrival
    # buffer. 1 = at most one cohort outstanding (still async when
    # async_buffer < num_workers: updates fire on partial cohorts).
    async_concurrency: int = 1
    # alpha: each arriving contribution is weighted by the polynomial
    # staleness discount (1 + s)^-alpha, where s = server versions
    # advanced since the contribution's cohort launched (FedBuff/
    # FedAsync-style). 0 = no discount (pure live-mask weighting).
    staleness_exponent: float = 0.0
    # Double-buffered round overlap (asyncfed/engine.py): defer the
    # host fence on update u's applied metrics until AFTER update
    # u+1's cohort launches have been dispatched, so the launch
    # programs' forward/backward queues behind the in-flight apply and
    # the device never waits on the host between an apply and the next
    # launches. Pure host scheduling — every value the engine computes
    # (staleness weights, consumed bookkeeping, the applied update) is
    # unchanged, so the K=W, C=1, alpha=0 anchor still reduces
    # BIT-IDENTICALLY to the synchronous round. Requires the asyncfed
    # engine (async_buffer > 0). False (default): the apply fences
    # inside its own span before the next launches (the measured
    # sequential baseline).
    async_double_buffer: bool = False

    # --- adaptive communication budget (commefficient_tpu/control/;
    # TPU-native — the reference fixes k/num_cols/rank once per run) ---
    # Rung-selection policy: "none" (default — NOTHING control-related is
    # built and the round stays bit-identical to a pre-control build, the
    # telemetry_level-0 discipline), "fixed" (round-range schedule via
    # control_schedule), "budget_pacing" (spend budget_mb evenly over the
    # remaining rounds, dropping to cheaper rungs as the ledger's cum
    # bytes approach the cap; hard BudgetExhaustedError when even the
    # cheapest rung would overshoot), "ef_feedback" (closed loop on the
    # diag/ef_residual_norm slope + level-2 fidelity, with hysteresis).
    control_policy: str = "none"
    # Compression ladder (control/ladder.py grammar): ";"-separated
    # "field=v1,v2,..." lists over k / num_cols / powersgd_rank, one value
    # per rung, ordered most-expensive first — e.g.
    # "k=60000,30000,10000". Every rung's round program is AOT-prewarmed
    # at run start, so a switch is a dispatch-table lookup, never a
    # mid-run retrace. Empty with budget_pacing = a single implicit rung
    # (pure budget cap enforcement, no switching).
    ladder: str = ""
    # Total communication budget in MB (decimal, 10^6 B) over the run's
    # cumulative ledger bytes (up + down, live-byte units under fedsim
    # masking — the same units comm/cum_bytes logs). 0 = no budget.
    # Enforced by the controller for ANY policy; required > 0 for
    # budget_pacing.
    budget_mb: float = 0.0
    # fixed-policy schedule: comma-separated "A-B=rung" round ranges
    # (B empty = open-ended), e.g. "0-99=2,100-=0". Rounds outside every
    # range run rung 0.
    control_schedule: str = ""
    # ef_feedback thresholds on the per-round RELATIVE slope of
    # diag/ef_residual_norm: slope > control_ef_up -> climb one rung
    # toward more bytes; slope < control_ef_down -> step one rung cheaper;
    # in between -> hold. up > down required (the dead band is half the
    # anti-oscillation story; the hysteresis window is the other half).
    control_ef_up: float = 0.15
    control_ef_down: float = 0.0
    # Worst level-2 fidelity (any diag/*_rel_err: sketch round-trip error,
    # powersgd reconstruction residual) above which ef_feedback climbs
    # regardless of the EF slope; 0 disables the fidelity trigger (it
    # needs telemetry_level >= 2 to have data).
    control_fidelity_max: float = 0.0
    # Minimum rounds between ef_feedback switches (hysteresis): within the
    # window the policy holds whatever the signals say, so the loop cannot
    # oscillate every round (tests/test_control.py pins the property).
    control_hysteresis: int = 8
    # staleness_aware band on the drained async/staleness_mean EMA (server
    # versions a contribution lags by, asyncfed/): above hi -> walk one
    # rung CHEAPER (stale cohorts' contributions are discounted anyway, so
    # spend fewer bytes on them) and shed concurrency; below lo -> climb
    # back / restore concurrency. hi > lo required (the dead band + the
    # shared control_hysteresis window are the anti-oscillation story,
    # exactly ef_feedback's).
    control_staleness_hi: float = 2.0
    control_staleness_lo: float = 0.5
    # staleness_aware band on the normalized buffer backlog
    # (async/buffer_fill / K — contributions still buffered after an
    # update fires, in buffer units): persistently over fill_hi the
    # arrival process outpaces the updates -> grow K back toward
    # --async_buffer (consume more per fire); under fill_lo while
    # staleness runs hot -> shrink K so updates fire sooner. The policy
    # adapts K/C toward this band and the controller re-tunes the
    # asyncfed engine at round granularity (FedBuff arXiv:2106.06639 §5
    # tunes these statically; ROADMAP item 4 makes it dynamic).
    control_fill_hi: float = 1.0
    control_fill_lo: float = 0.25

    # --- self-healing training (commefficient_tpu/resilience/;
    # TPU-native — the reference treats every failure as terminal) ---
    # Divergence recovery policy: "none" (default — NOTHING resilience-
    # related is constructed; the telemetry_level-0 discipline, golden
    # parity and level-0 HLO bit-untouched), "retry" (roll back to the
    # last vault snapshot and replay bit-identically — heals transient
    # faults; a recovered run matches the uninterrupted one bit-exactly),
    # "demote" (roll back AND floor the control/ ladder one rung cheaper
    # via the AOT-prewarmed switch path — needs a >= 2-rung ladder),
    # "skip_clients" (roll back AND blacklist the bad round's suspect
    # client ids from all future participation masks — needs fedsim;
    # unbiasedness preserved by linearity, renormalized by live count).
    # Detection rides the flight recorder, so != "none" needs
    # --telemetry_level >= 1. Recoveries exhausted (--max_recoveries) ->
    # the original DivergenceError re-raises with the recovery history
    # attached. See README "Failure handling & recovery".
    recover_policy: str = "none"
    # Rounds between in-memory rollback snapshots (resilience/vault.py):
    # each snapshot is preceded by a metric drain, so every snapshot in
    # the vault is certified finite (the divergence check runs in the
    # drain) and the rollback target is always pre-divergence. The vault
    # retains the last two snapshots host-side (~2x the FedState bytes of
    # host RAM); a baseline snapshot at the start round makes recovery
    # possible before the first boundary. Active iff recover_policy is
    # not "none".
    snapshot_every: int = 16
    # Recoveries before the run gives up and re-raises the original
    # DivergenceError (with the full recovery history attached). A
    # genuinely deterministic divergence replays identically under
    # "retry", so this bound is what terminates that loop.
    max_recoveries: int = 2
    # Install SIGTERM/SIGINT riders that request a preemption-safe
    # shutdown at round granularity: drain pending metrics, force-save a
    # checkpoint, write ledger/flight/spans, exit with the distinct code
    # resilience.EXIT_PREEMPTED (75). Off by default (no handler is
    # installed — constructs nothing). The fedsim chaos event
    # "preempt@R" injects the same request deterministically for tests.
    preempt_signals: bool = False

    # --- misc (reference: --seed; the mesh-shape flags above are ours) ---
    seed: int = 42
    checkpoint_dir: str = ""
    checkpoint_every: int = 0  # rounds between checkpoints; 0 = off
    resume: bool = False
    tensorboard: bool = False
    logdir: str = "runs"
    profile_dir: str = ""  # jax.profiler trace of a few steady-state rounds
    # Programmatic jax.profiler capture window over rounds "A-B"
    # (inclusive; telemetry/trace.py ProfilerWindow): arms start/stop
    # around exactly those rounds — clamped to the steady-state window
    # (MIN_WARMUP_STEPS) and fenced so deferred/in-flight work retires
    # outside the capture — into profile_dir (or <logdir>/profile_rounds
    # when profile_dir is unset). "" (default) constructs nothing.
    # Degrades gracefully (logged named reason) where the backend cannot
    # trace. This is the BENCH_r06 per-op TPU profile hook.
    profile_rounds: str = ""

    def __post_init__(self):
        if self.mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {self.mode!r}")
        if self.error_type not in ERROR_TYPES:
            raise ValueError(
                f"error_type must be one of {ERROR_TYPES}, got {self.error_type!r}"
            )
        if self.topk_method not in ("exact", "threshold", "approx"):
            raise ValueError(
                f"topk_method must be exact|threshold|approx, got {self.topk_method!r}"
            )
        if (
            self.mode == "sketch"
            and self.momentum_dampening is True
            and not self.allow_unstable_sketch_dampening
        ):
            raise ValueError(
                "momentum_dampening=True with mode='sketch' is a known-"
                "divergent combination (it re-sketches NOISY momentum "
                "estimates each round; measured to destabilize training at "
                "paper-scale settings — see round.py). FetchSGD Alg 1 does "
                "not mask sketched momentum: use momentum_dampening=None/"
                "False, or set allow_unstable_sketch_dampening=True for "
                "parity experiments."
            )
        if self.mode == "powersgd":
            if self.powersgd_rank < 1:
                raise ValueError(
                    f"powersgd_rank must be >= 1, got {self.powersgd_rank}"
                )
            if self.do_topk_down:
                raise ValueError(
                    "do_topk_down with mode='powersgd' is contradictory: "
                    "the downlink is already the factored rank-r pair "
                    "(r*(n+m) floats); top-k'ing the reconstructed delta "
                    "would only un-compress it. Drop one of the two flags."
                )
            if self.momentum_dampening is True:
                raise ValueError(
                    "momentum_dampening is undefined for mode='powersgd': "
                    "dampening zeroes momentum at EXTRACTED COORDINATES, "
                    "and a rank-r subspace update has no coordinate "
                    "selection to mask. Use momentum_dampening=None/False."
                )
        if self.label_noise < 0.0 or self.label_noise > 1.0:
            raise ValueError(
                f"label_noise must be in [0, 1], got {self.label_noise}"
            )
        if self.error_decay != 1.0 and self.error_type != "virtual":
            raise ValueError(
                "error_decay only acts on the server-side virtual error "
                f"bank (error_type='virtual'); with error_type="
                f"{self.error_type!r} it would be a silent no-op"
            )
        if self.compute_dtype not in ("mixed", "float32", "bfloat16"):
            raise ValueError(
                "compute_dtype must be mixed|float32|bfloat16, "
                f"got {self.compute_dtype!r}"
            )
        if self.hash_family not in ("fmix32", "poly4"):
            raise ValueError(
                f"hash_family must be fmix32|poly4, got {self.hash_family!r}"
            )
        if self.sketch_backend not in ("einsum", "pallas"):
            raise ValueError(
                "sketch_backend must be einsum|pallas, "
                f"got {self.sketch_backend!r}"
            )
        if self.sketch_decode not in ("auto", "dense", "sharded"):
            raise ValueError(
                "sketch_decode must be auto|dense|sharded, "
                f"got {self.sketch_decode!r}"
            )
        if self.sketch_decode == "sharded":
            if self.mode != "sketch":
                raise ValueError(
                    "sketch_decode='sharded' is the sketch server-decode "
                    f"strategy; mode={self.mode!r} has no sketch decode. "
                    "Leave sketch_decode='auto' (a no-op for other modes)."
                )
            if self.topk_method != "threshold":
                raise ValueError(
                    "sketch_decode='sharded' extracts the global top-<=k "
                    "with the sharded threshold kernel (scalar-only "
                    "collectives); set topk_method='threshold' (the TPU "
                    "fast path), or leave sketch_decode='auto' to keep "
                    f"topk_method={self.topk_method!r} on the dense decode"
                )
        if self.aggregate not in ("auto", "dense", "sparse"):
            raise ValueError(
                "aggregate must be auto|dense|sparse, "
                f"got {self.aggregate!r}"
            )
        if self.aggregate == "sparse":
            if self.mode not in ("local_topk", "true_topk", "sketch"):
                raise ValueError(
                    "aggregate='sparse' exchanges <=k-sparse (idx, val) "
                    f"pairs on-mesh; mode={self.mode!r} has no sparse "
                    "transmit. Leave aggregate='auto' (a no-op there)."
                )
            if self.fsdp:
                raise ValueError(
                    "aggregate='sparse' targets the replicated round; the "
                    "FSDP round already reduce-scatters O(D/W) per chip "
                    "and exchanges only W*k candidate pairs. Leave "
                    "aggregate='auto' under fsdp=True."
                )
            if self.mode == "true_topk" and self.topk_method != "threshold":
                raise ValueError(
                    "aggregate='sparse' with mode='true_topk' selects the "
                    "global top-<=k with the sharded threshold kernel; "
                    "set topk_method='threshold', or leave "
                    "aggregate='auto' to keep the dense psum with "
                    f"topk_method={self.topk_method!r}"
                )
            if self.mode == "sketch":
                if self.topk_method != "threshold":
                    raise ValueError(
                        "aggregate='sparse' with mode='sketch' rides the "
                        "sharded-decode pair exchange for the EF "
                        "re-sketch; set topk_method='threshold' (the "
                        "sharded decode's requirement), or leave "
                        "aggregate='auto'"
                    )
                if self.sketch_decode == "dense":
                    raise ValueError(
                        "aggregate='sparse' with mode='sketch' requires "
                        "the sharded server decode (its pair exchange is "
                        "what the EF re-sketch rides); remove "
                        "sketch_decode='dense' or leave aggregate='auto'"
                    )
        if self.synthetic_variant not in (
            "flat", "concentrated", "concentrated_v2"
        ):
            raise ValueError(
                "synthetic_variant must be flat|concentrated|"
                f"concentrated_v2, got {self.synthetic_variant!r}"
            )
        if self.sketch_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"sketch_dtype must be float32|bfloat16, got {self.sketch_dtype!r}"
            )
        if self.sketch_table_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                "sketch_table_dtype must be float32|bfloat16, "
                f"got {self.sketch_table_dtype!r}"
            )
        self._validate_client_store()
        self._validate_sketch_fused_bwd()
        self._validate_overlap_collectives()
        self._validate_scan_rounds()
        if self.num_workers % self.num_devices != 0:
            raise ValueError(
                "num_workers must be divisible by num_devices "
                f"({self.num_workers} % {self.num_devices} != 0). If you "
                "were resizing num_workers to model PARTIAL PARTICIPATION, "
                "don't — keep the round shape fixed and mask clients out "
                "with the fedsim environment instead (--availability "
                "bernoulli --dropout_prob p, or --chaos 'dropout@p'); "
                "masked clients transmit nothing and the server "
                "renormalizes by the live count"
            )
        if self.availability not in AVAILABILITY_MODELS:
            raise ValueError(
                f"availability must be one of {AVAILABILITY_MODELS}, got "
                f"{self.availability!r}"
            )
        if not 0.0 <= self.dropout_prob < 1.0:
            raise ValueError(
                f"dropout_prob must be in [0, 1), got {self.dropout_prob} "
                "(at 1.0 every client drops every round and nothing ever "
                "trains)"
            )
        if self.dropout_prob > 0 and self.availability == "always":
            raise ValueError(
                "dropout_prob > 0 has no effect with availability="
                "'always'; pick a model that uses it (bernoulli|sine|"
                "cohort), or schedule it via --chaos 'dropout@p'"
            )
        if self.availability_period < 1:
            raise ValueError(
                f"availability_period must be >= 1, got "
                f"{self.availability_period}"
            )
        if self.num_cohorts < 1:
            raise ValueError(
                f"num_cohorts must be >= 1, got {self.num_cohorts}"
            )
        if not self.arrival_rate > 0:  # rejects 0, negatives, and NaN
            raise ValueError(
                f"arrival_rate must be > 0 (rate=inf is the degenerate "
                f"everyone-arrives-instantly case), got {self.arrival_rate}"
            )
        if self.chaos:
            # syntax + range validation (ValueError with the grammar);
            # lazy import keeps the no-cycle layering (fedsim never
            # imports config)
            from commefficient_tpu.fedsim.faults import parse_chaos

            parse_chaos(self.chaos)
        if self.model_axis < 1 or self.seq_axis < 1:
            raise ValueError(
                f"model_axis/seq_axis must be >= 1, got "
                f"{self.model_axis}/{self.seq_axis}"
            )
        if self.num_clients < self.num_workers:
            raise ValueError("num_clients must be >= num_workers")
        if self.telemetry_level not in (0, 1, 2):
            raise ValueError(
                f"telemetry_level must be 0 (off), 1 (health) or 2 "
                f"(+fidelity), got {self.telemetry_level!r}"
            )
        if self.flight_window < 1:
            raise ValueError(
                f"flight_window must be >= 1, got {self.flight_window}"
            )
        if self.profile_rounds:
            # lazy import keeps the no-cycle layering (telemetry never
            # imports config); parse_profile_rounds raises the ValueError
            # naming the offending spec
            from commefficient_tpu.telemetry.trace import (
                parse_profile_rounds,
            )

            parse_profile_rounds(self.profile_rounds)
        if self.max_retraces is not None and self.max_retraces < 0:
            raise ValueError(
                f"max_retraces must be >= 0 (0 = fail on ANY retrace "
                f"beyond the first compile) or None (count only), got "
                f"{self.max_retraces}"
            )
        if self.pipeline_depth < 0:
            raise ValueError(
                f"pipeline_depth must be >= 0 (0 = synchronous), got "
                f"{self.pipeline_depth}"
            )
        self._validate_asyncfed()
        self._validate_multihost()
        self._validate_control()
        self._validate_resilience()
        self._validate_fleet()

    def _validate_fleet(self) -> None:
        """Elastic-fleet events (fedsim/faults.py FLEET_KINDS in the
        chaos plan). The realized per-round widths must shard the fixed
        device mesh and stay within the provisioned maximum
        (faults.validate_fleet); engines that cannot re-shape a round
        mid-run are refused here at construction. Runs LAST: it reads
        gates the other validators resolve."""
        if not self.fleet_enabled:
            return
        from commefficient_tpu.fedsim.faults import (
            parse_chaos,
            validate_fleet,
        )

        plan = parse_chaos(self.chaos)
        validate_fleet(plan, num_workers=self.num_workers,
                       num_devices=self.num_devices)
        if self.asyncfed_enabled:
            raise ValueError(
                "fleet events are incompatible with async_buffer > 0: the "
                "asyncfed schedule pre-simulates every cohort at the fixed "
                "width W, so a mid-run resize would orphan in-flight "
                "slots — model elastic participation there with "
                "availability='poisson' instead"
            )
        if self.scan_rounds > 1:
            raise ValueError(
                "fleet events are incompatible with scan_rounds > 1: a "
                "scanned block compiles ONE width for K rounds, and a "
                "resize inside the block could not swap programs — drop "
                "scan_rounds or the fleet events"
            )
        if self.pipeline_depth > 0:
            raise ValueError(
                "fleet events are incompatible with pipeline_depth > 0 "
                "for now: the prefetcher stages round payloads at the "
                "base width ahead of the resize decision point — run "
                "synchronous rounds with the fleet plan"
            )
        if self.fsdp:
            raise ValueError(
                "fleet events are incompatible with fsdp: the FSDP round "
                "shards server state [D/W] over the workers axis, so a "
                "width change would re-partition persistent state, not "
                "just the round program — use the replicated round"
            )
        if any(ev.kind == "shrink" for ev in plan):
            if not self.recovery_enabled:
                raise ValueError(
                    "shrink@W' models an unscheduled worker loss: it "
                    "raises FleetShrinkError for the resilience manager "
                    "to roll back and re-enter at W' — set "
                    "--recover_policy retry|demote (and its "
                    "--telemetry_level >= 1 requirement), or use "
                    "resize@W' for a scheduled, non-faulting change"
                )

    def _validate_client_store(self) -> None:
        """Client-state placement flags (clientstore/). Runs FIRST among
        the feature validators: the deprecated ``offload_client_state``
        flag aliases into ``client_store='host'`` here, and every later
        validator keys off the resolved ``client_state_hosted`` gate."""
        if self.client_store not in CLIENT_STORES:
            raise ValueError(
                f"client_store must be one of {CLIENT_STORES}, got "
                f"{self.client_store!r}"
            )
        if self.offload_client_state:
            import warnings

            warnings.warn(
                "offload_client_state is deprecated: the whole-store "
                "offload became the per-cohort client-state store — use "
                "--client_store host (identical semantics at whole-store "
                "granularity; adds mmap backing and the LRU device cache)",
                DeprecationWarning,
                stacklevel=3,
            )
            if self.client_store == "device":
                object.__setattr__(self, "client_store", "host")
        if self.client_store_cache_rows < 0:
            raise ValueError(
                f"client_store_cache_rows must be >= 0 (0 = no cache), "
                f"got {self.client_store_cache_rows}"
            )
        if self.client_store == "device":
            if self.client_store_cache_rows:
                raise ValueError(
                    "client_store_cache_rows caches host-store cohort rows "
                    "on device; with client_store='device' the whole bank "
                    "already lives in HBM — drop the cache flag or pick "
                    "--client_store host|mmap"
                )
            if self.client_store_path:
                raise ValueError(
                    "client_store_path backs the mmap store; with "
                    f"client_store={self.client_store!r} it would be "
                    "silently ignored — use --client_store mmap"
                )
        if self.client_store == "host" and self.client_store_path:
            raise ValueError(
                "client_store_path backs the mmap store; the host store "
                "is a RAM bank — use --client_store mmap to persist to "
                f"{self.client_store_path!r}"
            )
        if self.client_state_hosted and self.fsdp:
            raise ValueError(
                "client_store='host'/'mmap' streams per-cohort rows "
                "through the replicated round builder; the FSDP round "
                "shards server state instead (local modes host their "
                "memory wall via --client_store, server modes via "
                "--fsdp) — run one or the other"
            )

    def _validate_sketch_fused_bwd(self) -> None:
        """The sketch-fused backward produces the gradient directly as an
        encoded table, so it only exists on the fused flattened-batch
        path with nothing per-[D] configured — every blocker is named
        here at construction instead of at first trace."""
        if not self.sketch_fused_bwd:
            return
        if self.mode != "sketch":
            raise ValueError(
                "sketch_fused_bwd sketches per-leaf cotangents into the "
                f"CountSketch table; mode={self.mode!r} has no table — "
                "drop the flag or use mode='sketch'"
            )
        if not self.fuse_clients:
            raise ValueError(
                "sketch_fused_bwd needs the fused flattened-batch path "
                "(ONE gradient per device -> one table); with "
                "fuse_clients=False each client's grad would pay its own "
                "sketch — set fuse_clients=True"
            )
        if self.local_momentum > 0:
            raise ValueError(
                "sketch_fused_bwd is incompatible with local_momentum: "
                "per-client velocity needs the dense per-client gradient "
                "the fused backward never materializes"
            )
        if self.max_grad_norm is not None:
            raise ValueError(
                "sketch_fused_bwd is incompatible with max_grad_norm "
                "(clipping also forces the per-client vmap path; the "
                "fused-batch gate already excludes it)"
            )
        if self.dp_noise_multiplier > 0:
            raise ValueError(
                "sketch_fused_bwd is incompatible with DP noise: the "
                "noise is a [D]-vector draw, which is exactly the "
                "transient the fused backward exists to avoid"
            )
        if self.fedsim_enabled:
            raise ValueError(
                "sketch_fused_bwd needs the fused flattened-batch path, "
                "and fedsim masking is inherently per-client (it forces "
                "the vmap path) — run one or the other"
            )

    def _validate_overlap_collectives(self) -> None:
        """Layer-wise collective overlap (parallel/round.py +
        ops/collectives/). Only the value set is validated here — the
        knob is a pure collective-scheduling choice that composes with
        every mode (paths without a chunkable collective trace the same
        program as overlap='none')."""
        if self.overlap_collectives not in ("none", "layerwise"):
            raise ValueError(
                "overlap_collectives must be 'none' (monolithic "
                "aggregation collectives, the golden-pinned default) or "
                "'layerwise' (segmented collectives issued as the "
                f"backward produces them), got "
                f"{self.overlap_collectives!r}"
            )

    def _validate_scan_rounds(self) -> None:
        """Scan-over-rounds flags (pipeline/scan_engine.py). The engine
        executes K rounds per dispatch, so anything that must act
        host-side BETWEEN two arbitrary rounds is incompatible and
        refused here; boundaries the engine can honor by CHOPPING blocks
        (checkpoints, snapshots, epoch ends) need no constraint."""
        if self.scan_rounds < 0:
            raise ValueError(
                f"scan_rounds must be >= 0 (0/1 = per-round dispatch), "
                f"got {self.scan_rounds}"
            )
        if self.scan_rounds <= 1:
            return
        if not self.device_data:
            raise ValueError(
                "scan_rounds > 1 runs the device-resident index round "
                "inside lax.scan — the epoch's batches must already be "
                "in HBM; set device_data=True (host-batch rounds would "
                "serialize on H2D anyway)"
            )
        if self.client_state_hosted or self.fsdp:
            raise ValueError(
                "scan_rounds > 1 needs the device-resident index path, "
                "which excludes --client_store host|mmap and fsdp "
                "(host-resident rows cross PCIe between rounds)"
            )
        if self.control_enabled:
            raise ValueError(
                "scan_rounds > 1 is mutually exclusive with the control "
                "plane: the controller decides immediately-pre-dispatch "
                "per ROUND, and a scanned block admits no host decision "
                "between its rounds — run one or the other"
            )
        if self.pipeline_depth > 0:
            raise ValueError(
                "scan_rounds > 1 already stages the whole epoch's "
                "sampler indices up front (a superset of the "
                "prefetcher's depth-K window on the index path) — drop "
                "pipeline_depth"
            )
        if self.preempt_signals or "preempt@" in self.chaos:
            raise ValueError(
                "scan_rounds > 1 cannot honor round-granular preemption: "
                "the device state only exists at block boundaries, so a "
                "mid-block preempt would checkpoint the wrong round — "
                "disable preempt_signals / the preempt@ chaos event"
            )

    def _validate_asyncfed(self) -> None:
        """Buffered-asynchronous federation flags (asyncfed/). The async
        engine launches overlapping per-client cohorts and applies a
        staleness-weighted update once K contributions arrive, so anything
        that assumes one cohort per server version — or that removes the
        per-client transmit rows the launch program ships — is refused
        here at construction instead of at first trace (the
        _validate_scan_rounds discipline)."""
        if self.async_buffer < 0:
            raise ValueError(
                f"async_buffer must be >= 0 (0 = synchronous barrier "
                f"rounds), got {self.async_buffer}"
            )
        if self.async_concurrency < 1:
            raise ValueError(
                f"async_concurrency must be >= 1, got "
                f"{self.async_concurrency}"
            )
        if self.staleness_exponent < 0:
            raise ValueError(
                f"staleness_exponent must be >= 0 ((1+s)^-alpha is a "
                f"DISCOUNT; a negative alpha would amplify stale "
                f"contributions), got {self.staleness_exponent}"
            )
        if self.async_buffer == 0:
            if self.async_concurrency != 1:
                raise ValueError(
                    "async_concurrency > 1 has no effect without "
                    "--async_buffer K; set async_buffer > 0 to enable the "
                    "asyncfed engine"
                )
            if self.staleness_exponent != 0.0:
                raise ValueError(
                    "staleness_exponent has no effect without "
                    "--async_buffer K: synchronous rounds have staleness 0 "
                    "by construction"
                )
            if self.async_double_buffer:
                raise ValueError(
                    "async_double_buffer defers the asyncfed apply fence "
                    "behind the next cohort launches, which only exist "
                    "with --async_buffer K; set async_buffer > 0 to "
                    "enable the asyncfed engine"
                )
            return
        if self.async_buffer > self.num_workers:
            raise ValueError(
                f"async_buffer must be <= num_workers ("
                f"{self.num_workers}): an update consumes at most one full "
                f"cohort's W slots per in-flight cohort, and K > W would "
                f"just wait for the next cohort anyway — raise "
                f"async_concurrency instead, got {self.async_buffer}"
            )
        if self.fuse_clients or self.sketch_fused_bwd:
            raise ValueError(
                "async_buffer > 0 needs PER-CLIENT transmit rows (each "
                "arrival is weighted by its own staleness/live factor); "
                "the fused flattened-batch paths produce one device-level "
                "gradient — drop fuse_clients/sketch_fused_bwd"
            )
        if self.client_state_hosted or self.fsdp:
            raise ValueError(
                "async_buffer > 0 currently requires HBM-resident client "
                "state on the replicated engine (--client_store host|mmap "
                "and fsdp run their own round builders)"
            )
        if self.scan_rounds > 1:
            raise ValueError(
                "async_buffer > 0 is mutually exclusive with "
                "scan_rounds > 1: a scanned block admits no host-side "
                "arrival buffering between its rounds"
            )
        if self.pipeline_depth > 0:
            raise ValueError(
                "async_buffer > 0 supersedes pipeline_depth: the asyncfed "
                "engine owns its own cohort prefetch window "
                "(async_concurrency cohorts in flight) — drop "
                "pipeline_depth"
            )
        if self.preempt_signals or "preempt@" in self.chaos:
            raise ValueError(
                "async_buffer > 0 cannot yet honor round-granular "
                "preemption: in-flight cohorts would be abandoned "
                "mid-arrival — disable preempt_signals / the preempt@ "
                "chaos event"
            )

    def _validate_multihost(self) -> None:
        """Multi-host topology flags (multihost/). num_hosts > 1 reroutes
        every worker-axis collective over the (hosts, workers) tuple, so
        the two round builders that still hardcode the plain workers axis
        (fsdp, the tensor-parallel loss) are refused here at construction
        instead of producing a wrong-axis program at first trace."""
        if self.num_hosts < 1:
            raise ValueError(
                f"num_hosts must be >= 1, got {self.num_hosts}"
            )
        if self.distributed_connect_retries < 1:
            raise ValueError(
                f"distributed_connect_retries must be >= 1 (total connect "
                f"attempts, not extra retries), got "
                f"{self.distributed_connect_retries}"
            )
        if self.distributed and self.num_hosts < 2:
            raise ValueError(
                "distributed=True runs the jax.distributed bring-up to "
                "declare a host axis, which needs --num_hosts >= 2 (a "
                "single-host run has nothing to connect; mesh-faked "
                "multihost tests set num_hosts > 1 WITHOUT --distributed)"
            )
        if self.num_hosts == 1:
            return
        if self.num_hosts & (self.num_hosts - 1):
            raise ValueError(
                f"num_hosts must be a power of two, got {self.num_hosts}: "
                "the two-level butterfly aggregation schedules cross-host "
                "hops over a hypercube of hosts (ops/collectives/"
                "sparse_allreduce.py), which only exists at 2^n"
            )
        if self.num_devices % self.num_hosts != 0:
            raise ValueError(
                "num_devices must be divisible by num_hosts "
                f"({self.num_devices} % {self.num_hosts} != 0): the mesh "
                "is (hosts, workers, model, seq) with workers = "
                "num_devices / num_hosts chips per host"
            )
        if self.fsdp:
            raise ValueError(
                "num_hosts > 1 is incompatible with fsdp: the FSDP round "
                "builder (parallel/fsdp.py) names the plain workers axis "
                "in every shard spec and collective, so a declared host "
                "axis would silently exclude cross-host devices from its "
                "reduce-scatters — run the replicated round (the multihost "
                "path) or fsdp, not both"
            )
        if self.model_axis > 1 or self.seq_axis > 1:
            raise ValueError(
                "num_hosts > 1 is incompatible with model_axis/seq_axis "
                "> 1: the tensor-parallel loss (parallel/tensor.py) "
                "shards batch rows with the plain workers axis spec, "
                "which on a (hosts, workers, ...) mesh would replicate "
                "the batch across hosts instead of sharding it"
            )

    def _validate_resilience(self) -> None:
        """Self-healing flags (resilience/). Same late-validation split as
        control/: grammar/shape here, anything needing the run length or
        the realized session at train-entry/build time."""
        if self.recover_policy not in RECOVER_POLICIES:
            raise ValueError(
                f"recover_policy must be one of {RECOVER_POLICIES}, got "
                f"{self.recover_policy!r}"
            )
        if self.snapshot_every < 1:
            raise ValueError(
                f"snapshot_every must be >= 1 round, got "
                f"{self.snapshot_every}"
            )
        if self.max_recoveries < 1:
            raise ValueError(
                f"max_recoveries must be >= 1, got {self.max_recoveries} "
                "(use recover_policy='none' to disable recovery entirely)"
            )
        if self.recover_policy == "none":
            return
        if self.telemetry_level < 1:
            raise ValueError(
                f"recover_policy={self.recover_policy!r} recovers from the "
                "flight recorder's DivergenceError, which only fires at "
                "--telemetry_level >= 1 (the in-graph non-finite sentinel "
                "+ drain-time check) — at level 0 a divergence is never "
                "detected, so the policy would silently never act"
            )
        if self.recover_policy == "demote":
            if not self.control_enabled or not self.ladder:
                raise ValueError(
                    "recover_policy='demote' descends the control/ "
                    "compression ladder — configure a controller with a "
                    'ladder (e.g. --control_policy fixed --ladder '
                    '"k=60000,30000")'
                )
            from commefficient_tpu.control.ladder import parse_ladder

            if len(parse_ladder(self.ladder)) < 2:
                raise ValueError(
                    "recover_policy='demote' needs a ladder with >= 2 "
                    "rungs to demote between"
                )
        if self.recover_policy == "skip_clients" and not self.fedsim_enabled:
            raise ValueError(
                "recover_policy='skip_clients' masks blacklisted clients "
                "through the fedsim participation mask, but this config "
                "traces no masking (availability='always', no chaos) — "
                "enable fedsim (e.g. --availability bernoulli) or pick "
                "another policy"
            )

    def _validate_control(self) -> None:
        """Adaptive-communication-budget flags (control/). Grammar/shape
        validation happens here at construction; byte-cost ordering of the
        rungs needs the realized compressor geometry and is validated at
        session build, and schedule ranges vs the run length at
        train-entry time (the chaos-rounds pattern)."""
        if self.control_policy not in CONTROL_POLICIES:
            raise ValueError(
                f"control_policy must be one of {CONTROL_POLICIES}, got "
                f"{self.control_policy!r}"
            )
        # lazy imports keep the no-cycle layering (control never imports
        # config at runtime — the fedsim.faults pattern)
        rungs = ()
        if self.ladder:
            from commefficient_tpu.control.ladder import (
                LADDER_FIELDS,
                parse_ladder,
            )

            rungs = parse_ladder(self.ladder)  # syntax ValueError w/ grammar
            if self.control_policy == "none":
                raise ValueError(
                    "a ladder without a controller would silently never "
                    "switch — set control_policy (fixed | budget_pacing | "
                    "ef_feedback), or drop --ladder"
                )
            if self.mode != "powersgd" and any(
                    "powersgd_rank" in r for r in rungs):
                raise ValueError(
                    f"ladder field powersgd_rank has no effect with "
                    f"mode={self.mode!r} — the rung switch would be a "
                    "silent no-op; ladder fields must act on the active "
                    f"mode ({LADDER_FIELDS} minus the inert ones)"
                )
            if self.mode != "sketch" and any("num_cols" in r for r in rungs):
                raise ValueError(
                    f"ladder field num_cols has no effect with "
                    f"mode={self.mode!r} (no sketch table) — the rung "
                    "switch would be a silent no-op"
                )
            if (self.mode in ("uncompressed", "fedavg")
                    and not self.do_topk_down
                    and any("k" in r for r in rungs)):
                # (with do_topk_down, k sizes the downlink top-k — a k
                # ladder is then a real downlink-budget ladder)
                raise ValueError(
                    f"ladder field k has no effect with mode={self.mode!r} "
                    "(dense transmit, no top-k extraction) — the rung "
                    "switch would be a silent no-op"
                )
        if self.control_policy == "ef_feedback":
            if len(rungs) < 2:
                raise ValueError(
                    "control_policy='ef_feedback' needs a ladder with >= 2 "
                    "rungs to move between — pass --ladder (e.g. "
                    '"k=60000,30000,10000")'
                )
            if self.telemetry_level < 1:
                raise ValueError(
                    "control_policy='ef_feedback' consumes the drained "
                    "diag/ef_residual_norm telemetry — set "
                    "--telemetry_level >= 1 (>= 2 if control_fidelity_max "
                    "is used)"
                )
            if not self.control_ef_up > self.control_ef_down:
                raise ValueError(
                    f"control_ef_up ({self.control_ef_up}) must exceed "
                    f"control_ef_down ({self.control_ef_down}): the dead "
                    "band between them is what stops threshold flapping"
                )
        if self.control_policy == "staleness_aware":
            if not self.asyncfed_enabled:
                raise ValueError(
                    "control_policy='staleness_aware' acts on the drained "
                    "async/staleness_mean and async/buffer_fill scalars, "
                    "which only the asyncfed engine emits — set "
                    "--async_buffer K (synchronous rounds have staleness 0 "
                    "by construction, so the policy would never act)"
                )
            if len(rungs) < 2:
                raise ValueError(
                    "control_policy='staleness_aware' walks the "
                    "compression ladder by observed staleness — pass "
                    '--ladder with >= 2 rungs (e.g. "k=60000,30000")'
                )
            if self.telemetry_level < 1:
                raise ValueError(
                    "control_policy='staleness_aware' consumes drained "
                    "telemetry scalars — set --telemetry_level >= 1"
                )
            if not self.control_staleness_hi > self.control_staleness_lo:
                raise ValueError(
                    f"control_staleness_hi ({self.control_staleness_hi}) "
                    f"must exceed control_staleness_lo "
                    f"({self.control_staleness_lo}): the dead band between "
                    "them is what stops threshold flapping"
                )
            if not self.control_fill_hi > self.control_fill_lo >= 0:
                raise ValueError(
                    f"control_fill_hi ({self.control_fill_hi}) must exceed "
                    f"control_fill_lo ({self.control_fill_lo}) >= 0 — the "
                    "normalized backlog band the K/C re-tune targets"
                )
        if self.control_policy == "fixed":
            from commefficient_tpu.control.policy import parse_schedule

            sched = parse_schedule(self.control_schedule)
            if not sched:
                raise ValueError(
                    "control_policy='fixed' needs --control_schedule "
                    '(e.g. "0-99=2,100-=0")'
                )
            n_rungs = max(len(rungs), 1)
            for start, end, rung in sched:
                if rung >= n_rungs:
                    raise ValueError(
                        f"control_schedule names rung {rung}, but the "
                        f"ladder has {n_rungs} rung(s) (indices 0.."
                        f"{n_rungs - 1})"
                    )
        elif self.control_schedule:
            raise ValueError(
                "control_schedule only drives control_policy='fixed'; "
                f"with {self.control_policy!r} it would be silently ignored"
            )
        if self.budget_mb < 0:
            raise ValueError(f"budget_mb must be >= 0, got {self.budget_mb}")
        if self.control_policy == "budget_pacing" and not self.budget_mb > 0:
            raise ValueError(
                "control_policy='budget_pacing' paces against --budget_mb; "
                "set it > 0"
            )
        if self.budget_mb > 0 and self.control_policy == "none":
            raise ValueError(
                "budget_mb is enforced by the control plane; with "
                "control_policy='none' nothing would watch it — use "
                "control_policy='budget_pacing' (a ladder is optional: "
                "without one the budget is a pure hard cap)"
            )
        if self.control_hysteresis < 1:
            raise ValueError(
                f"control_hysteresis must be >= 1 round, got "
                f"{self.control_hysteresis}"
            )

    @property
    def clients_per_device(self) -> int:
        return self.num_workers // self.num_devices

    @property
    def fedsim_enabled(self) -> bool:
        """True when the federated-environment simulator must be threaded
        through the jitted round (any masking/chaos source is on). False
        keeps the round trace IDENTICAL to a fedsim-less build — the
        golden parity recordings pin that (fedsim/ package docstring)."""
        return self.availability != "always" or bool(self.chaos)

    @property
    def fleet_enabled(self) -> bool:
        """True when the chaos plan schedules any elastic-fleet event
        (resize/leave/join/shrink): the session then prewarms a round
        program per realized width and swaps programs at the schedule's
        transition rounds. False constructs NOTHING fleet-related — the
        fedsim_enabled gate discipline (golden parity and level-0 HLO
        bit-untouched). Implies ``fedsim_enabled`` (the plan is
        non-empty)."""
        if not self.chaos:
            return False
        from commefficient_tpu.fedsim.faults import has_fleet, parse_chaos

        return has_fleet(parse_chaos(self.chaos))

    @property
    def control_enabled(self) -> bool:
        """True when the adaptive-communication control plane must be
        built (multi-rung session + controller). False keeps the session
        single-rung and bit-identical to a pre-control build — the golden
        parity recordings pin that (control/ package docstring)."""
        return self.control_policy != "none"

    @property
    def recovery_enabled(self) -> bool:
        """True when the divergence rollback-and-recover machinery must be
        built (resilience/ vault + manager). False keeps the train loop on
        the untouched fast path with nothing resilience-related
        constructed — the fedsim/control/pipeline gate discipline. (The
        preemption guard has its own gate: ``preempt_signals`` or a
        ``preempt@R`` chaos event.)"""
        return self.recover_policy != "none"

    @property
    def client_state_hosted(self) -> bool:
        """True when per-client momentum/error rows live OUTSIDE the
        traced graph (clientstore/ host or mmap bank): the round functions
        take the cohort's [W, D] rows as arguments and FedState carries no
        [num_clients, D] leaves. False keeps today's device-resident
        arrays and constructs nothing clientstore-related — the
        fedsim_enabled/control_enabled gate discipline (golden parity and
        level-0 HLO bit-untouched)."""
        return self.client_store in ("host", "mmap")

    @property
    def pipeline_enabled(self) -> bool:
        """True when the pipelined round engine must be built (pipeline/
        package). False keeps the train loop on the legacy synchronous
        path with nothing pipeline-related constructed — the
        fedsim_enabled/control_enabled discipline."""
        return self.pipeline_depth > 0

    @property
    def asyncfed_enabled(self) -> bool:
        """True when the buffered-asynchronous engine must be built
        (asyncfed/ package). False keeps the train loop on the synchronous
        engines with nothing asyncfed-related constructed — the
        fedsim_enabled/pipeline_enabled gate discipline."""
        return self.async_buffer > 0

    @property
    def sampler_batch_size(self) -> int:
        """Samples the sampler draws per client per round: a fedavg round
        batch carries ``round_microbatches`` microbatches of
        ``local_batch_size`` each (derived from that property so the
        fedavg convention stays defined in exactly one place)."""
        return self.local_batch_size * (self.round_microbatches or 1)

    @property
    def round_microbatches(self) -> int:
        """Microbatches per client per round: ``num_local_iters`` for
        fedavg's [W, L, B/L, ...] batch convention, else 0 (flat [W, B]
        batches). THE mode-derived reshape knob, kept here so train loops
        and the index-round path never branch on mode strings
        (scripts/check_mode_dispatch.py)."""
        return self.num_local_iters if self.mode == "fedavg" else 0

    @property
    def resolved_num_classes(self) -> int:
        """num_classes if set, else derived from dataset_name."""
        if self.num_classes is not None:
            return self.num_classes
        return {"cifar10": 10, "cifar100": 100, "femnist": 62,
                "imagenet": 1000}.get(self.dataset_name, 10)

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)


def _add_flags(p: argparse.ArgumentParser) -> None:
    """One flag per Config field, reference-compatible names."""
    for f in dataclasses.fields(Config):
        name = "--" + f.name
        default = f.default
        ann = str(f.type)
        if f.type in ("bool", bool) or isinstance(default, bool):
            p.add_argument(
                name,
                type=lambda s: s.lower() in ("1", "true", "yes"),
                nargs="?",
                const=True,
                default=default,
            )
        elif "Optional" in ann or "None" in ann:
            if "bool" in ann:  # tri-state: None (auto) | true | false
                p.add_argument(
                    name,
                    type=lambda s: s.lower() in ("1", "true", "yes"),
                    nargs="?",
                    const=True,
                    default=default,
                )
            else:
                inner = float if "float" in ann else (int if "int" in ann else str)

                def opt(s, _inner=inner):
                    # Optional fields are resettable to None from the CLI
                    # ("--max_grad_norm none" turns clipping off even when
                    # an entry's defaults set it — without this, a default
                    # like gpt2_train's max_grad_norm=1.0 was one-way and
                    # e.g. --sketch_fused_bwd was unreachable there)
                    return None if s.lower() in ("none", "null") else _inner(s)

                p.add_argument(name, type=opt, default=default)
        else:
            p.add_argument(name, type=type(default), default=default)


def parse_args(argv=None, defaults=None, **overrides) -> Config:
    """CLI -> Config. The analog of the reference's ``utils.parse_args``.

    ``defaults`` changes parser defaults (still user-overridable on the CLI,
    e.g. gpt2_train sets ``model="gpt2"``); ``overrides`` win over the CLI.
    """
    p = argparse.ArgumentParser(description="commefficient_tpu")
    _add_flags(p)
    if defaults:
        p.set_defaults(**defaults)
    ns = p.parse_args(argv)
    d = vars(ns)
    d.update(overrides)
    return Config(**d)
