"""Config, schedules, logging — the L6 utility layer."""

from commefficient_tpu.utils.config import Config, parse_args
from commefficient_tpu.utils.schedule import piecewise_linear_lr
from commefficient_tpu.utils.logging import TableLogger, Timer, MetricsWriter

__all__ = [
    "Config",
    "parse_args",
    "piecewise_linear_lr",
    "TableLogger",
    "Timer",
    "MetricsWriter",
]
