"""Profiling hooks — ``jax.profiler`` traces around a window of rounds,
plus the shared micro-benchmark helpers (``fence``/``timeit``).

The reference's only tracing is a console Timer around epoch phases
(SURVEY.md §5 "Tracing/profiling"); the rebuild equivalent is a real XLA
trace viewable in TensorBoard/Perfetto. ``StepProfiler`` wraps a few
steady-state rounds (after compile/warmup) so the trace shows the real hot
path, not compilation. ``fence``/``timeit`` used to live (duplicated) in
scripts/profile_round.py; they are here so bench.py, profile_round and the
telemetry span recorder all share one fencing/warmup discipline.
"""

from __future__ import annotations

import time

import jax

# The first executed round compiles and the second fills the other donated-
# buffer layout (see bench.py's warmup note); a trace window that includes
# them measures XLA, not the round. start_step=0 used to do exactly that —
# now every window starts at least this many steps after the first executed
# round, and ``timeit`` warms with exactly this many calls (one warm call
# used to leave the second donated-buffer layout uncompiled, so the first
# timed rep paid a compile on donated paths).
MIN_WARMUP_STEPS = 2


def fence(x) -> float:
    """Synchronize on a pytree of device values and return a scalar from
    it. ``block_until_ready`` is unreliable through the axon TPU tunnel; a
    scalar FETCH is the only trustworthy fence there, so both are done."""
    import jax.numpy as jnp

    leaf = jax.tree.leaves(x)[0]
    leaf.block_until_ready()
    return float(jnp.sum(jnp.ravel(leaf)[:1]))


def timeit(name, fn, *args, reps: int = 10, warmup: int = MIN_WARMUP_STEPS):
    """Mean ms/call of ``fn(*args)`` over ``reps``, printed and returned.

    Warms with ``warmup`` calls (default MIN_WARMUP_STEPS=2: the first
    compiles, the second fills the other donated-buffer layout) and fences
    once before and once after the timed loop (steady-state pipelined
    dispatch, the bench.py methodology)."""
    out = None
    for _ in range(max(warmup, 1)):
        out = fn(*args)
    fence(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    fence(out)
    dt = (time.perf_counter() - t0) / reps * 1e3
    print(f"{name:42s} {dt:8.2f} ms")
    return dt


class StepProfiler:
    """Trace rounds [start_step, start_step + num_steps) into ``logdir``.

    Call ``step(i)`` once per executed training round (monotonic ``i``);
    call ``resume_at(step0)`` after a checkpoint restore so the window
    clamps to post-resume steps; call ``close()`` in a finally block.
    Inactive (zero overhead) when ``logdir`` is falsy.

    Window semantics: the trace starts at the first ``step()`` that lands
    INSIDE the window (not only on exact equality with ``start_step`` — a
    resume that fast-forwards into the middle of the window used to leave
    the trace permanently un-started, and one that started could never
    stop) and stops at the first step at/past the end. ``start_step`` is
    clamped to at least ``MIN_WARMUP_STEPS`` so ``start_step=0`` cannot
    trace compile+warmup.
    """

    def __init__(self, logdir: str, start_step: int = 5, num_steps: int = 3):
        self.logdir = logdir
        self.num_steps = num_steps
        self.start = max(start_step, MIN_WARMUP_STEPS)
        self.stop_at = self.start + num_steps
        self._active = False

    def resume_at(self, resume_step: int) -> None:
        """Clamp the window to post-resume steps: the resumed process's
        first executed round is ``resume_step`` and it compiles from
        scratch, so any window overlapping or predating it shifts to
        ``resume_step + MIN_WARMUP_STEPS`` (same length)."""
        floor = resume_step + MIN_WARMUP_STEPS
        if floor > self.start:
            self.start = floor
            self.stop_at = floor + self.num_steps

    def step(self, step_idx: int) -> None:
        if not self.logdir:
            return
        if self._active and step_idx >= self.stop_at:
            jax.profiler.stop_trace()
            self._active = False
        elif not self._active and self.start <= step_idx < self.stop_at:
            jax.profiler.start_trace(self.logdir)
            self._active = True

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
