"""Profiling hooks — ``jax.profiler`` traces around a window of rounds.

The reference's only tracing is a console Timer around epoch phases
(SURVEY.md §5 "Tracing/profiling"); the rebuild equivalent is a real XLA
trace viewable in TensorBoard/Perfetto. ``StepProfiler`` wraps a few
steady-state rounds (after compile/warmup) so the trace shows the real hot
path, not compilation.
"""

from __future__ import annotations

import jax


class StepProfiler:
    """Trace rounds [start_step, start_step + num_steps) into ``logdir``.

    Call ``step(i)`` once per training round; call ``close()`` in a finally
    block. Inactive (zero overhead) when ``logdir`` is falsy.
    """

    def __init__(self, logdir: str, start_step: int = 5, num_steps: int = 3):
        self.logdir = logdir
        self.start = start_step
        self.stop_at = start_step + num_steps
        self._active = False

    def step(self, step_idx: int) -> None:
        if not self.logdir:
            return
        if step_idx == self.start and not self._active:
            jax.profiler.start_trace(self.logdir)
            self._active = True
        elif step_idx >= self.stop_at and self._active:
            jax.profiler.stop_trace()
            self._active = False

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
