"""Console + TensorBoard logging.

Rebuilds the reference's observability layer (SURVEY.md §5: cifar10-fast
style ``TableLogger``/``Timer`` plus a TensorBoard ``SummaryWriter`` rooted
at an args-derived run dir — ``utils.py make_logdir`` ~L320-350,
``TableLogger``/``Timer`` ~L350-400). TensorBoard is optional: if no writer
backend is importable we degrade to console-only rather than crashing.

Since the telemetry PR this is also the drain point for the round-level
observability scalars: ``drain_round_metrics`` writes every namespaced
metric key (``diag/*`` in-graph diagnostics) and threads the optional
``telemetry.CommLedger``/``FlightRecorder`` riders; ``MetricsWriter``
stamps a run-header record and wall times so rows correlate across runs
(schema: README "Observability", scripts/check_telemetry_schema.py).
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional


class Timer:
    """Accumulating phase timer: ``t()`` returns seconds since last call."""

    def __init__(self):
        self._last = time.perf_counter()
        self.total = 0.0

    def __call__(self) -> float:
        now = time.perf_counter()
        dt = now - self._last
        self._last = now
        self.total += dt
        return dt


class TableLogger:
    """Aligned console table, one row per epoch (cifar10-fast style).

    Keys that first appear AFTER the header row was printed used to be
    silently dropped; now each new key warns once and is rendered in this
    and subsequent rows (the header line is not reprinted — the one-time
    warning names the column instead)."""

    def __init__(self, width: int = 12):
        self.width = width
        self._keys: Optional[list[str]] = None

    def append(self, row: dict) -> None:
        if self._keys is None:
            self._keys = list(row.keys())
            print(" | ".join(f"{k:>{self.width}s}" for k in self._keys))
        else:
            for k in row:
                if k not in self._keys:
                    print(f"TableLogger: new column {k!r} appeared after "
                          "the header row; rendering it in subsequent rows "
                          "(header not reprinted)", flush=True)
                    self._keys.append(k)
        cells = []
        for k in self._keys:
            v = row.get(k, "")
            if isinstance(v, float):
                cells.append(f"{v:>{self.width}.4f}")
            else:
                cells.append(f"{str(v):>{self.width}s}")
        print(" | ".join(cells), flush=True)


def make_logdir(cfg) -> str:
    """Run-dir name derived from the salient config fields (the reference
    derives it from args the same way)."""
    tag = f"{cfg.dataset_name}_{cfg.model}_{cfg.mode}_w{cfg.num_workers}_s{cfg.seed}"
    return os.path.join(cfg.logdir, tag + "_" + time.strftime("%Y%m%d-%H%M%S"))


class MetricsWriter:
    """Scalar metrics sink: TensorBoard if available, always a JSONL file.

    Scalar names match the reference's (train/loss, val/loss, val/acc, lr,
    ...) so curves are directly comparable; the telemetry PR adds the
    ``diag/*`` and ``comm/*`` namespaces (README "Observability" documents
    the full schema, scripts/check_telemetry_schema.py validates it).

    Every open writes a RUN-HEADER record first — config snapshot, jax
    version, device kind, wall-clock start — and every scalar record
    carries a wall-time field ``t``, so metrics.jsonl rows can be
    correlated across runs and with profiler traces. A resumed run appends
    a second header (one per process); records are self-describing by
    their ``type``/``name`` keys.
    """

    def __init__(self, logdir: str, enable_tensorboard: bool = False,
                 cfg=None, extra_header=None):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        self._jsonl = open(os.path.join(logdir, "metrics.jsonl"), "a")
        self._write_header(cfg, extra_header)
        self._tb = None
        if enable_tensorboard:
            try:
                from torch.utils.tensorboard import SummaryWriter  # type: ignore

                self._tb = SummaryWriter(logdir)
            except Exception as e:
                # degrade to JSONL-only, but SAY so (exception-hygiene):
                # the caller asked for tensorboard, and a silent None here
                # costs them the curves with no clue until hours later
                import warnings

                warnings.warn(
                    f"MetricsWriter: tensorboard unavailable "
                    f"({type(e).__name__}: {e}); logging JSONL-only"
                )
                self._tb = None

    def _write_header(self, cfg, extra_header=None) -> None:
        # lazy import: telemetry owns the versioned schema + the shared
        # run_metadata block (flight records embed the same one); the
        # config snapshot is sanitized like every other artifact so a
        # non-finite config float cannot poison line 1 with a bare NaN
        from commefficient_tpu.telemetry import (
            SCHEMA_VERSION,
            jsonable_tree,
            run_artifacts,
            run_metadata,
        )

        rec = {"type": "header", "schema_version": SCHEMA_VERSION,
               **run_metadata(cfg)}
        if cfg is not None:
            # v3: link the run to its profiling evidence (StepProfiler
            # trace logdir, the compiled-round perf_report.json) so a
            # metrics consumer can find them without guessing paths
            arts = run_artifacts(cfg, self.logdir)
            if arts:
                rec["artifacts"] = arts
        if extra_header:
            # v4: run-identifying blocks a caller supplies beyond the
            # config snapshot — e.g. the adaptive-communication controller
            # block (policy, ladder, initial rung: control.controller_header)
            rec.update(extra_header)
        self._jsonl.write(json.dumps(jsonable_tree(rec),
                                     allow_nan=False) + "\n")
        self._jsonl.flush()

    def scalar(self, name: str, value: float, step: int) -> None:
        # non-finite values (a diverging run's own loss — exactly the rows
        # forensics needs) are stringified "nan"/"inf"/"-inf" so the file
        # stays STRICT JSON per line (json.dumps would emit a bare NaN
        # token that jq/JS/strict parsers reject); allow_nan=False makes
        # any regression here a loud error, not a corrupt artifact
        from commefficient_tpu.telemetry import jsonable_scalar

        self._jsonl.write(
            json.dumps({"name": name, "value": jsonable_scalar(value),
                        "step": int(step), "t": time.time()},
                       allow_nan=False) + "\n"
        )
        if self._tb is not None:
            self._tb.add_scalar(name, float(value), int(step))

    def flush(self) -> None:
        self._jsonl.flush()
        if self._tb is not None:
            self._tb.flush()

    def close(self) -> None:
        self.flush()
        self._jsonl.close()
        if self._tb is not None:
            self._tb.close()


_PACKER_CACHE: dict = {}


def pack_metric_dicts(dicts):
    """Fetch N same-keyed dicts of device scalars as ONE host [N, K] array.

    Everything happens inside a single jitted program: on a tunneled TPU
    backend every EAGER op costs a full RPC (~25-60 ms measured), so
    stacking 48 rounds x 3 scalars eagerly took 7-9 s even fully cached,
    and leaf-wise device_get 56 s — the jitted pack + one fetch is ~0.2 s.
    Jit caches per (N, key set); train epochs and eval passes have constant
    N, so each shape compiles once per process.

    Returns (names, mat) with ``mat[j, i] == float(dicts[j][names[i]])``.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    names = tuple(sorted(dicts[0]))
    for j, m in enumerate(dicts):
        if tuple(sorted(m)) != names:
            # a mixed batch would silently index missing keys inside the
            # jitted pack (KeyError mid-trace at best) — reject it here
            # with the offending entry named
            raise ValueError(
                f"pack_metric_dicts: mixed key sets — dict {j} has "
                f"{tuple(sorted(m))}, expected {names}; all packed "
                "metric dicts must share one key set"
            )
    key = (len(dicts), names)
    pack = _PACKER_CACHE.get(key)
    if pack is None:

        @jax.jit
        def pack(ms):
            return jnp.stack(
                [
                    jnp.stack([jnp.asarray(m[k], jnp.float32) for k in names])
                    for m in ms
                ]
            )

        _PACKER_CACHE[key] = pack
    return names, np.asarray(pack(tuple(dicts)))


def drain_round_metrics(pending, writer, accumulate, ledger=None,
                        flight=None, controller=None) -> None:
    """Fetch buffered per-round DEVICE metrics and clear the buffer.

    Train loops append ``(step, lr, metrics)`` without fetching (a float()
    per round is a full dispatch fence that serializes the round pipeline
    — 10-100 ms each through a TPU tunnel) and drain at epoch end and
    before checkpoint writes (a resume fast-forwards past checkpointed
    rounds, so logs unflushed at save time would be lost for good). Writes
    the common train/loss + lr scalars plus every NAMESPACED metric key
    (``diag/*`` from the in-graph diagnostics — any key containing "/" is
    a scalar by schema); per-workload accumulation goes through
    ``accumulate(loss, metrics)``.

    Telemetry riders (both optional, telemetry_level >= 1):
      ``ledger`` — a telemetry.CommLedger; its per-round ``comm/*`` scalars
        are written at each drained step.
      ``flight`` — a telemetry.FlightRecorder; each drained round is
        recorded, then CHECKED in step order — a non-finite loss or a fired
        ``diag/nonfinite`` sentinel dumps flight_<step>.json and raises
        ``DivergenceError`` naming the first bad round. The buffer is
        cleared and the writer flushed even on that raise, so the bad
        rounds' scalars survive for the post-mortem.
      ``controller`` — a control.BudgetController (duck-typed
        ``observe_drained(step, scalars)``); each drained round's scalars
        feed the rung-selection policy in step order (the ``ef_feedback``
        loop's telemetry input).
    """
    if not pending:
        return
    names, mat = pack_metric_dicts([m for _, _, m in pending])
    try:
        for j, (s, s_lr, _) in enumerate(pending):
            metrics = {k: mat[j, i] for i, k in enumerate(names)}
            loss = float(metrics["loss"])
            if writer:
                writer.scalar("train/loss", loss, s)
                writer.scalar("lr", s_lr, s)
                for k in names:
                    if "/" in k:
                        writer.scalar(k, float(metrics[k]), s)
            # the round's metric dict rides along: a fedsim-masked ledger
            # recovers the live/avail client counts from its fedsim/*
            # scalars (telemetry/ledger.py masked accounting)
            comm = ledger.on_round(s, metrics) if ledger is not None else {}
            if writer:
                for k, v in comm.items():
                    writer.scalar(k, v, s)
            accumulate(loss, metrics)
            if controller is not None:
                controller.observe_drained(s, metrics)
            if flight is not None:
                flight.record(s, s_lr, {
                    **{k: float(metrics[k]) for k in names}, **comm,
                })
                flight.check(s, loss, metrics)  # may raise DivergenceError
    finally:
        pending.clear()
        if writer:
            writer.flush()
