"""Console + TensorBoard logging.

Rebuilds the reference's observability layer (SURVEY.md §5: cifar10-fast
style ``TableLogger``/``Timer`` plus a TensorBoard ``SummaryWriter`` rooted
at an args-derived run dir — ``utils.py make_logdir`` ~L320-350,
``TableLogger``/``Timer`` ~L350-400). TensorBoard is optional: if no writer
backend is importable we degrade to console-only rather than crashing.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional


class Timer:
    """Accumulating phase timer: ``t()`` returns seconds since last call."""

    def __init__(self):
        self._last = time.perf_counter()
        self.total = 0.0

    def __call__(self) -> float:
        now = time.perf_counter()
        dt = now - self._last
        self._last = now
        self.total += dt
        return dt


class TableLogger:
    """Aligned console table, one row per epoch (cifar10-fast style)."""

    def __init__(self, width: int = 12):
        self.width = width
        self._keys: Optional[list[str]] = None

    def append(self, row: dict) -> None:
        if self._keys is None:
            self._keys = list(row.keys())
            print(" | ".join(f"{k:>{self.width}s}" for k in self._keys))
        cells = []
        for k in self._keys:
            v = row.get(k, "")
            if isinstance(v, float):
                cells.append(f"{v:>{self.width}.4f}")
            else:
                cells.append(f"{str(v):>{self.width}s}")
        print(" | ".join(cells), flush=True)


def make_logdir(cfg) -> str:
    """Run-dir name derived from the salient config fields (the reference
    derives it from args the same way)."""
    tag = f"{cfg.dataset_name}_{cfg.model}_{cfg.mode}_w{cfg.num_workers}_s{cfg.seed}"
    return os.path.join(cfg.logdir, tag + "_" + time.strftime("%Y%m%d-%H%M%S"))


class MetricsWriter:
    """Scalar metrics sink: TensorBoard if available, always a JSONL file.

    Scalar names match the reference's (train/loss, val/loss, val/acc, lr,
    ...) so curves are directly comparable.
    """

    def __init__(self, logdir: str, enable_tensorboard: bool = False):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        self._jsonl = open(os.path.join(logdir, "metrics.jsonl"), "a")
        self._tb = None
        if enable_tensorboard:
            try:
                from torch.utils.tensorboard import SummaryWriter  # type: ignore

                self._tb = SummaryWriter(logdir)
            except Exception:
                self._tb = None

    def scalar(self, name: str, value: float, step: int) -> None:
        self._jsonl.write(
            json.dumps({"name": name, "value": float(value), "step": int(step)}) + "\n"
        )
        if self._tb is not None:
            self._tb.add_scalar(name, float(value), int(step))

    def flush(self) -> None:
        self._jsonl.flush()
        if self._tb is not None:
            self._tb.flush()

    def close(self) -> None:
        self.flush()
        self._jsonl.close()
        if self._tb is not None:
            self._tb.close()


_PACKER_CACHE: dict = {}


def pack_metric_dicts(dicts):
    """Fetch N same-keyed dicts of device scalars as ONE host [N, K] array.

    Everything happens inside a single jitted program: on a tunneled TPU
    backend every EAGER op costs a full RPC (~25-60 ms measured), so
    stacking 48 rounds x 3 scalars eagerly took 7-9 s even fully cached,
    and leaf-wise device_get 56 s — the jitted pack + one fetch is ~0.2 s.
    Jit caches per (N, key set); train epochs and eval passes have constant
    N, so each shape compiles once per process.

    Returns (names, mat) with ``mat[j, i] == float(dicts[j][names[i]])``.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    names = tuple(sorted(dicts[0]))
    key = (len(dicts), names)
    pack = _PACKER_CACHE.get(key)
    if pack is None:

        @jax.jit
        def pack(ms):
            return jnp.stack(
                [
                    jnp.stack([jnp.asarray(m[k], jnp.float32) for k in names])
                    for m in ms
                ]
            )

        _PACKER_CACHE[key] = pack
    return names, np.asarray(pack(tuple(dicts)))


def drain_round_metrics(pending, writer, accumulate) -> None:
    """Fetch buffered per-round DEVICE metrics and clear the buffer.

    Train loops append ``(step, lr, metrics)`` without fetching (a float()
    per round is a full dispatch fence that serializes the round pipeline
    — 10-100 ms each through a TPU tunnel) and drain at epoch end and
    before checkpoint writes (a resume fast-forwards past checkpointed
    rounds, so logs unflushed at save time would be lost for good). Writes
    the common train/loss + lr scalars; per-workload accumulation goes
    through ``accumulate(loss, metrics)``.
    """
    if not pending:
        return
    names, mat = pack_metric_dicts([m for _, _, m in pending])
    for j, (s, s_lr, _) in enumerate(pending):
        metrics = {k: mat[j, i] for i, k in enumerate(names)}
        loss = float(metrics["loss"])
        if writer:
            writer.scalar("train/loss", loss, s)
            writer.scalar("lr", s_lr, s)
        accumulate(loss, metrics)
    pending.clear()
    if writer:
        writer.flush()
