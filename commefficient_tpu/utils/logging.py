"""Console + TensorBoard logging.

Rebuilds the reference's observability layer (SURVEY.md §5: cifar10-fast
style ``TableLogger``/``Timer`` plus a TensorBoard ``SummaryWriter`` rooted
at an args-derived run dir — ``utils.py make_logdir`` ~L320-350,
``TableLogger``/``Timer`` ~L350-400). TensorBoard is optional: if no writer
backend is importable we degrade to console-only rather than crashing.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional


class Timer:
    """Accumulating phase timer: ``t()`` returns seconds since last call."""

    def __init__(self):
        self._last = time.perf_counter()
        self.total = 0.0

    def __call__(self) -> float:
        now = time.perf_counter()
        dt = now - self._last
        self._last = now
        self.total += dt
        return dt


class TableLogger:
    """Aligned console table, one row per epoch (cifar10-fast style)."""

    def __init__(self, width: int = 12):
        self.width = width
        self._keys: Optional[list[str]] = None

    def append(self, row: dict) -> None:
        if self._keys is None:
            self._keys = list(row.keys())
            print(" | ".join(f"{k:>{self.width}s}" for k in self._keys))
        cells = []
        for k in self._keys:
            v = row.get(k, "")
            if isinstance(v, float):
                cells.append(f"{v:>{self.width}.4f}")
            else:
                cells.append(f"{str(v):>{self.width}s}")
        print(" | ".join(cells), flush=True)


def make_logdir(cfg) -> str:
    """Run-dir name derived from the salient config fields (the reference
    derives it from args the same way)."""
    tag = f"{cfg.dataset_name}_{cfg.model}_{cfg.mode}_w{cfg.num_workers}_s{cfg.seed}"
    return os.path.join(cfg.logdir, tag + "_" + time.strftime("%Y%m%d-%H%M%S"))


class MetricsWriter:
    """Scalar metrics sink: TensorBoard if available, always a JSONL file.

    Scalar names match the reference's (train/loss, val/loss, val/acc, lr,
    ...) so curves are directly comparable.
    """

    def __init__(self, logdir: str, enable_tensorboard: bool = False):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        self._jsonl = open(os.path.join(logdir, "metrics.jsonl"), "a")
        self._tb = None
        if enable_tensorboard:
            try:
                from torch.utils.tensorboard import SummaryWriter  # type: ignore

                self._tb = SummaryWriter(logdir)
            except Exception:
                self._tb = None

    def scalar(self, name: str, value: float, step: int) -> None:
        self._jsonl.write(
            json.dumps({"name": name, "value": float(value), "step": int(step)}) + "\n"
        )
        if self._tb is not None:
            self._tb.add_scalar(name, float(value), int(step))

    def flush(self) -> None:
        self._jsonl.flush()
        if self._tb is not None:
            self._tb.flush()

    def close(self) -> None:
        self.flush()
        self._jsonl.close()
        if self._tb is not None:
            self._tb.close()


def drain_round_metrics(pending, writer, accumulate) -> None:
    """Fetch buffered per-round DEVICE metrics and clear the buffer.

    Train loops append ``(step, lr, metrics)`` without fetching (a float()
    per round is a full dispatch fence that serializes the round pipeline
    — 10-100 ms each through a TPU tunnel) and drain at epoch end and
    before checkpoint writes (a resume fast-forwards past checkpointed
    rounds, so logs unflushed at save time would be lost for good). Writes
    the common train/loss + lr scalars; per-workload accumulation goes
    through ``accumulate(loss, metrics)``.
    """
    for s, s_lr, metrics in pending:
        loss = float(metrics["loss"])
        if writer:
            writer.scalar("train/loss", loss, s)
            writer.scalar("lr", s_lr, s)
        accumulate(loss, metrics)
    pending.clear()
    if writer:
        writer.flush()
