"""Platform forcing: run multi-device code on a virtual CPU mesh.

The ambient environment pins jax to the single real TPU chip via the "axon"
PJRT plugin, whose sitecustomize hook (a) imports jax at interpreter start,
(b) force-sets ``jax_platforms=axon`` and (c) monkey-patches backend lookup
so the first jax op dials the TPU tunnel. For the test suite and the
driver's ``dryrun_multichip`` we instead want N virtual CPU devices
(``--xla_force_host_platform_device_count``) — the TPU-world analog of the
reference's virtual-worker simulation (SURVEY.md §4).

``force_virtual_cpu_devices(n)`` neutralizes all three hooks. It must run
BEFORE any jax backend initializes (importing jax is fine; running an op is
not). Used by ``tests/conftest.py`` and ``__graft_entry__.dryrun_multichip``.
"""

from __future__ import annotations

import os


def force_virtual_cpu_devices(n: int = 8) -> None:
    """Pin jax to ``n`` virtual CPU devices, deregistering the axon TPU hook.

    Idempotent; safe to call multiple times with the same ``n``. Raises if a
    conflicting device count was already baked into an initialized backend.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()

    import jax  # local import: sitecustomize may have imported it already
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")
