"""North-star evidence run: sketch vs uncompressed accuracy at iso-bytes.

VERDICT r1 item 7: demonstrate the FetchSGD accuracy story on ResNet-9 at
multi-round scale — final accuracy per mode alongside upload bytes/round.
Writes the results table to ACCURACY.md.

Runs on whatever CIFAR-10 is available: the real pickles if present under
--dataset_dir, else the deterministic synthetic stand-in (clearly labelled
— synthetic numbers are pipeline evidence, not paper numbers).

    python scripts/accuracy_run.py [--num_epochs 8] [--dataset_dir ./data]
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num_epochs", type=int, default=8)
    ap.add_argument("--dataset_dir", default="./data")
    ap.add_argument("--out", default="ACCURACY.md")
    ap.add_argument("--variant", default="concentrated",
                    help="synthetic stand-in when real data absent: "
                         "flat|concentrated (see data/cifar.py)")
    args = ap.parse_args()

    from commefficient_tpu.train.cv_train import (
        build_model_and_data,
        build_session_and_sampler,
        train_loop,
    )
    from commefficient_tpu.utils.config import Config

    base = dict(
        dataset_name="cifar10", dataset_dir=args.dataset_dir, model="resnet9",
        num_epochs=args.num_epochs, lr_scale=0.4, pivot_epoch=max(2, args.num_epochs // 4),
        num_clients=16, num_workers=8, num_devices=1, local_batch_size=64,
        weight_decay=5e-4, seed=42, topk_method="threshold",
        synthetic_variant=args.variant,
    )
    k = 50_000
    runs = [
        ("uncompressed", Config(mode="uncompressed", fuse_clients=True, **base)),
        ("sketch (FetchSGD, rho=0.9)", Config(
            mode="sketch", error_type="virtual", virtual_momentum=0.9,
            k=k, num_rows=5, num_cols=500_000, fuse_clients=True, **base)),
        ("sketch (FetchSGD, rho=0)", Config(
            mode="sketch", error_type="virtual", virtual_momentum=0.0,
            k=k, num_rows=5, num_cols=500_000, fuse_clients=True, **base)),
        ("true_topk", Config(
            mode="true_topk", error_type="virtual", virtual_momentum=0.9,
            k=k, fuse_clients=True, **base)),
        ("local_topk", Config(
            mode="local_topk", error_type="local", k=k, **base)),
        ("fedavg (4 local iters)", Config(
            mode="fedavg", num_local_iters=4, **base)),
    ]

    rows = []
    real = None
    for name, cfg in runs:
        train, test, real, model, params, loss_fn, augment = build_model_and_data(cfg)
        session, sampler = build_session_and_sampler(
            cfg, train, params, loss_fn, augment
        )
        bpr = session.bytes_per_round()
        t0 = time.time()
        val = train_loop(cfg, session, sampler, test)
        dt = time.time() - t0
        rows.append((name, bpr["upload_bytes"], bpr["download_bytes"],
                     val.get("accuracy", float("nan")), val["loss"], dt))
        print(f"== {name}: acc={rows[-1][3]:.4f} upload={bpr['upload_bytes']:,}B "
              f"({dt:.0f}s)", flush=True)
        _write(args, base, k, rows, real)  # incremental: survive interruption


def _write(args, base, k, rows, real):
    label = "REAL CIFAR-10" if real else (
        f"SYNTHETIC CIFAR stand-in, variant={args.variant!r} (real pickles "
        "not on disk; numbers are pipeline/compression-quality evidence, "
        "NOT paper accuracy)")
    lines = [
        "# Accuracy at iso-bytes — ResNet-9 federated CIFAR runs",
        "",
        f"Data: {label}. {base['num_epochs']} epochs, 8 workers/round, "
        f"local batch {base['local_batch_size']}, piecewise-linear lr "
        f"(peak {base['lr_scale']}). k={k}, sketch 5x500k. Produced by "
        "`python scripts/accuracy_run.py` on one TPU v5e chip.",
        "",
        "| mode | upload B/client/round | download B/round | final val acc | final val loss | train time (s) |",
        "|---|---|---|---|---|---|",
    ]
    for name, up, down, acc, loss, dt in rows:
        lines.append(f"| {name} | {up:,} | {down:,} | {acc:.4f} | {loss:.4f} | {dt:.0f} |")
    lines += [
        "",
        "The FetchSGD north star (BASELINE.md) is sketch matching the",
        "uncompressed baseline's accuracy at reduced upload bytes/round —",
        "compare the sketch rows against row 1 at the byte counts shown.",
    ]
    if real or args.variant != "flat":
        Path(args.out).write_text("\n".join(lines) + "\n")
        print(f"wrote {args.out} ({len(rows)} rows)", flush=True)
        return
    # the analysis below is specific to the FLAT synthetic stand-in
    lines += [
        "",
        "## Reading these numbers (r2 analysis)",
        "",
        "All five modes train STABLY (r2's CountSketch v5 banded layout fixed",
        "an outright divergence — see ops/countsketch.py postmortem and",
        "scripts/sketch_lab.py). The remaining sketch/true_topk accuracy gap",
        "on THIS dataset is a property of global-top-k error feedback on the",
        "synthetic stand-in, not of the sketch: an EXACT classic scatter",
        "sketch under identical server algebra scores the same in the lab",
        "(acc 0.315 vs 0.305/0.333 for v5 at 6 epochs), and single-shot",
        "heavy-hitter recall on a real ResNet gradient here is only ~0.38 at",
        "k=d/130 — the synthetic set's gradients are too FLAT for the",
        "FetchSGD premise (real CIFAR gradients concentrate; the paper's",
        "94%-at-iso-bytes result rides that structure). local_topk (exact",
        "per-client top-k + local error feedback) does not depend on global",
        "heavy hitters and reaches the best accuracy at 25x fewer upload",
        "bytes than uncompressed. Momentum note: rho=0.9 amplifies the burst",
        "dynamics on flat gradients (coordinates wait ~d/k rounds, then get",
        "their whole momentum-scaled backlog in one lump) and stalls here,",
        "while rho=0 reaches 0.66 at 2.6x fewer upload bytes — on real",
        "CIFAR, heavy hitters extract every round and rho=0.9 behaves.",
        "Re-run this script with real",
        "cifar-10-batches-py under --dataset_dir for paper-comparable rows.",
    ]
    Path(args.out).write_text("\n".join(lines) + "\n")
    print(f"wrote {args.out} ({len(rows)} rows)", flush=True)


if __name__ == "__main__":
    main()
