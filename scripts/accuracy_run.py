"""North-star evidence run: sketch vs uncompressed accuracy at iso-bytes.

VERDICT r1 item 7: demonstrate the FetchSGD accuracy story on ResNet-9 at
multi-round scale — final accuracy per mode alongside upload bytes/round.
Writes the results table to ACCURACY.md.

Runs on whatever CIFAR-10 is available: the real pickles if present under
--dataset_dir, else the deterministic synthetic stand-in (clearly labelled
— synthetic numbers are pipeline evidence, not paper numbers).

    python scripts/accuracy_run.py [--num_epochs 8] [--dataset_dir ./data]
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main():
    from commefficient_tpu.utils.config import AVAILABILITY_MODELS

    ap = argparse.ArgumentParser()
    ap.add_argument("--num_epochs", type=int, default=8)
    ap.add_argument("--dataset_dir", default="./data")
    ap.add_argument("--out", default="ACCURACY.md")
    ap.add_argument("--skip", type=int, default=0,
                    help="crash resume: skip the first N runs and carry "
                         "their rows over from the existing ACCURACY.md "
                         "table (the axon tunnel can drop a compile "
                         "mid-suite)")
    ap.add_argument("--variant", default="concentrated",
                    help="synthetic stand-in when real data absent: "
                         "flat|concentrated|concentrated_v2 (v2 = the "
                         "dense-SGD-hostile r2/r3 parameterization; see "
                         "data/cifar.py)")
    ap.add_argument("--telemetry_level", type=int, default=1,
                    choices=(0, 1, 2),
                    help="per-run telemetry (telemetry/ package): level 1 "
                         "writes the loss-vs-BYTES curve — the paper's "
                         "actual x-axis — into each run dir's "
                         "metrics.jsonl (comm/cum_bytes vs train/loss) + "
                         "comm_ledger.json; 0 restores the pre-telemetry "
                         "bit-identical round")
    ap.add_argument("--logdir", default="runs",
                    help="root for the per-run metrics/ledger/flight dirs")
    ap.add_argument("--budget_mb", type=float, default=None,
                    help="hard communication budget (decimal MB of "
                         "cumulative ledger bytes, up + down) applied to "
                         "EVERY run via the control plane "
                         "(control_policy=budget_pacing, no ladder — a "
                         "pure cap): runs that exhaust it stop with "
                         "BudgetExhaustedError and are recorded as honest "
                         "truncated rows (accuracy of the model at the "
                         "stop round), so loss-vs-bytes curves can be "
                         "read at a FIXED byte budget. NB budgeted rows "
                         "change the x-axis semantics — every run ends at "
                         "<= the same cum bytes instead of the same "
                         "round count (see ACCURACY.md).")
    ap.add_argument("--dropout", type=float, default=None,
                    help="fedsim bernoulli per-client dropout probability "
                         "applied to EVERY run: masked clients transmit "
                         "nothing, the server renormalizes by the live "
                         "count, and the ledger counts only live-client "
                         "bytes. NB masked runs log comm/* in FLEET bytes "
                         "(live x per-client), not the classic per-client-"
                         "link units — so for comparable 0%% vs 30%% "
                         "loss-vs-bytes curves run BOTH points through "
                         "this flag (--dropout 0.0 keeps full "
                         "participation but switches to the same fleet "
                         "accounting). Omit the flag entirely for the "
                         "classic per-client table.")
    ap.add_argument("--availability", default=None,
                    choices=sorted(AVAILABILITY_MODELS),
                    help="fedsim availability model for EVERY run (was "
                         "hardwired to bernoulli whenever --dropout was "
                         "given). --dropout still sets the decline "
                         "probability; the model-specific knobs below "
                         "shape who arrives. Passing --availability alone "
                         "(no --dropout) enables the environment at "
                         "dropout 0 in fleet byte units.")
    ap.add_argument("--arrival_rate", type=float, default=1.0,
                    help="poisson model: exponential arrival rate in "
                         "round-deadline units (participation 1-exp(-rate)"
                         "; inf = everyone instant). Also paces the "
                         "asyncfed cohort schedule when --async_buffer "
                         "style runs adopt this table's configs.")
    ap.add_argument("--availability_period", type=int, default=64,
                    help="sine model: rounds per diurnal cycle")
    ap.add_argument("--num_cohorts", type=int, default=4,
                    help="cohort model: number of correlated-outage groups")
    args = ap.parse_args()

    from commefficient_tpu.control import BudgetExhaustedError
    from commefficient_tpu.telemetry import DivergenceError
    from commefficient_tpu.train.cv_train import (
        build_model_and_data,
        build_session_and_sampler,
        train_loop,
    )
    from commefficient_tpu.utils.config import Config
    from commefficient_tpu.utils.logging import MetricsWriter, make_logdir

    base = dict(
        dataset_name="cifar10", dataset_dir=args.dataset_dir, model="resnet9",
        num_epochs=args.num_epochs,
        num_clients=16, num_workers=8, num_devices=1, local_batch_size=64,
        weight_decay=5e-4, seed=42, topk_method="threshold",
        synthetic_variant=args.variant,
        telemetry_level=args.telemetry_level, logdir=args.logdir,
        # the compiled-round audit costs one extra XLA compile PER RUN
        # (~30 s through a TPU tunnel) x a dozen table rows — this suite
        # measures accuracy-vs-bytes, not perf; bench.py owns the audited
        # perf numbers
        perf_audit=False,
        # same opt-out for the critical-path run report: a dozen table
        # rows would each write a run_report.json into the shared logdir
        # and ACCURACY.md rows would dangle links to whichever survived
        run_report=False,
    )
    if args.dropout is not None or args.availability is not None:
        # fedsim partial participation for the whole table (masking forces
        # the per-client vmap path; fuse_clients flags below are ignored).
        # An EXPLICIT --dropout 0.0 still enables the environment so the
        # ledger uses the same fleet live-byte units as the lossy runs —
        # that is what makes the 0%-vs-30% loss-vs-bytes comparison valid.
        # --availability picks the model (bernoulli stays the --dropout
        # shorthand default) and the model knobs ride along; Config
        # validation rejects nonsensical combinations.
        base.update(availability=args.availability or "bernoulli",
                    dropout_prob=args.dropout or 0.0,
                    arrival_rate=args.arrival_rate,
                    availability_period=args.availability_period,
                    num_cohorts=args.num_cohorts)
    if args.budget_mb is not None:
        # the control plane enforces the cap (controller accounting ==
        # ledger accounting exactly); no ladder -> a single implicit rung,
        # so this is the pure fixed-byte-budget x-axis, not adaptation
        base.update(control_policy="budget_pacing",
                    budget_mb=args.budget_mb)
    k = 50_000
    # Per-mode (lr_scale, pivot_epoch), tuned by scripts/archive/r3_sweep.py — the
    # FetchSGD paper tunes lr per compression config the same way (§5).
    # Momentum modes need ~(1-rho)x the SGD lr: with server momentum the
    # effective step is lr/(1-rho), so rho=0.9 at the SGD-tuned 0.4 was
    # training at effective lr 4.0 and stalling (the r3 pre-sweep table).
    piv = max(2, args.num_epochs // 4)
    # r4: schedules re-tuned on the v3 concentrated task by
    # scripts/archive/r4_retune.py (runs/r4_retune.log) — every grid single-peaked;
    # the v2-task optima transferred almost everywhere (sketch_rho0 and
    # local_topk moved to 0.8; true_topk runs the unmasked-momentum corner
    # whose tuned lr is 0.04 — see the four-corner ablation).
    sched = {
        "uncompressed": (0.8, piv),
        "uncompressed_mom": (0.06, piv),
        "sketch_rho09": (0.04, 2),
        "sketch_rho09_r7": (0.1, 2),
        # r5 fast geometry: chunk m pinned under the adaptive floor +
        # band=24 pool restore — 0.9004 at 1.69x uncompressed wall-clock
        # (runs/r5_sketch5.log; grid 0.06/0.1/0.15 interior at 0.1)
        "sketch_rho09_r7_fast": (0.1, 2),
        "sketch_rho0": (0.8, piv),
        # AUTO dampening now resolves False for true_topk (r4 four-corner
        # ablation) — tuned lr for the unmasked corner
        "true_topk": (0.04, 2),
        "local_topk": (0.8, piv),
        "fedavg": (0.4, piv),
    }

    def mk(name, **kw):
        lr, p = sched[name]
        return Config(lr_scale=lr, pivot_epoch=p, **kw, **base)

    runs = [
        ("uncompressed", mk("uncompressed", mode="uncompressed", fuse_clients=True)),
        ("uncompressed (momentum 0.9)", mk(
            "uncompressed_mom", mode="uncompressed", virtual_momentum=0.9,
            fuse_clients=True)),
        ("sketch (FetchSGD, rho=0.9)", mk(
            "sketch_rho09", mode="sketch", error_type="virtual",
            virtual_momentum=0.9, k=k, num_rows=5, num_cols=500_000,
            fuse_clients=True)),
        ("sketch (FetchSGD, rho=0.9, 7x357k)", mk(
            "sketch_rho09_r7", mode="sketch", error_type="virtual",
            virtual_momentum=0.9, k=k, num_rows=7, num_cols=357_143,
            fuse_clients=True)),
        ("sketch (7x357k, m=4096, band=24 — r5 fast geometry)", mk(
            "sketch_rho09_r7_fast", mode="sketch", error_type="virtual",
            virtual_momentum=0.9, k=k, num_rows=7, num_cols=357_143,
            sketch_m=4096, sketch_band=24, fuse_clients=True)),
        ("sketch (FetchSGD, rho=0)", mk(
            "sketch_rho0", mode="sketch", error_type="virtual",
            virtual_momentum=0.0, k=k, num_rows=5, num_cols=500_000,
            fuse_clients=True)),
        ("true_topk", mk(
            "true_topk", mode="true_topk", error_type="virtual",
            virtual_momentum=0.9, k=k, fuse_clients=True)),
        ("local_topk", mk("local_topk", mode="local_topk", error_type="local", k=k)),
        ("fedavg (4 local iters)", mk("fedavg", mode="fedavg", num_local_iters=4)),
    ]

    pre_rows = []
    if args.skip:
        old = Path(args.out).read_text().splitlines()
        tbl = [
            l for l in old
            if l.startswith("| ")
            and not l.startswith("| mode")
            and not l.startswith("|---")
        ]
        pre_rows = tbl[: args.skip]
        assert len(pre_rows) == args.skip, (
            f"--skip {args.skip} but only {len(pre_rows)} existing rows"
        )
    rows = []
    real = None
    for name, cfg in runs[args.skip:]:
        train, test, real, model, params, loss_fn, augment = build_model_and_data(cfg)
        session, sampler = build_session_and_sampler(
            cfg, train, params, loss_fn, augment
        )
        bpr = session.bytes_per_round()
        from commefficient_tpu.control import controller_header

        writer = MetricsWriter(make_logdir(cfg), cfg=cfg,
                               extra_header=controller_header(session))
        t0 = time.time()
        try:
            val = train_loop(cfg, session, sampler, test, writer)
        except DivergenceError as e:
            # one diverging config must not kill the suite: its flight
            # record has the forensics; the table gets an honest NaN row
            print(f"== {name}: DIVERGED — {e}", flush=True)
            val = {"loss": float("nan")}
        except BudgetExhaustedError as e:
            # the budget stopped the run BEFORE the unaffordable round:
            # the params are finite and every spent byte is within the
            # cap, so the honest truncated row is the model's accuracy AT
            # the stop round (the fixed-budget loss-vs-bytes point),
            # clearly labelled — mirroring the DivergenceError handling
            print(f"== {name}: BUDGET EXHAUSTED — {e}", flush=True)
            val = session.evaluate(test.eval_batches(512))
            name = f"{name} (budget-truncated @ round {e.step})"
        finally:
            writer.close()
        dt = time.time() - t0
        acc = val.get("accuracy", float("nan"))
        rows.append((name, cfg.lr_scale, cfg.pivot_epoch, cfg.dropout_prob,
                     cfg.budget_mb,
                     bpr["upload_bytes"], bpr["download_bytes"],
                     acc, val["loss"], dt))
        print(f"== {name}: acc={acc:.4f} upload={bpr['upload_bytes']:,}B "
              f"({dt:.0f}s)", flush=True)
        _write(args, base, k, rows, real, pre_rows)  # incremental


def _write(args, base, k, rows, real, pre_rows=()):
    label = "REAL CIFAR-10" if real else (
        f"SYNTHETIC CIFAR stand-in, variant={args.variant!r} (real pickles "
        "not on disk; numbers are pipeline/compression-quality evidence, "
        "NOT paper accuracy)")
    lines = [
        "# Accuracy at iso-bytes — ResNet-9 federated CIFAR runs",
        "",
        f"Data: {label}. {base['num_epochs']} epochs, 8 workers/round, "
        f"local batch {base['local_batch_size']}, piecewise-linear lr "
        "TUNED PER MODE by scripts/archive/r4_retune.py (the FetchSGD paper tunes "
        "lr per compression config, §5; momentum modes need ~(1-rho)x the "
        f"SGD lr — see accuracy_run.py). k={k}; sketch rows name their "
        "r x c split (identical table bytes). Produced by "
        "`python scripts/accuracy_run.py` on one TPU v5e chip.",
        "",
        "| mode | lr (peak) | pivot ep | dropout | budget MB | upload B/client/round | download B/round | final val acc | final val loss | train time (s) |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]
    ncols = lines[-2].count("|")
    for r in pre_rows:
        if r.count("|") != ncols:
            # --skip carries rows verbatim from the existing file; a row
            # written under an older column layout (e.g. pre-dropout-column)
            # would silently shift every cell — refuse instead
            raise SystemExit(
                f"--skip row has {r.count('|') - 1} columns, current table "
                f"has {ncols - 1} (the layout changed since that file was "
                f"written — rerun without --skip): {r}"
            )
    lines.extend(pre_rows)
    for name, lr, pv, drop, budget, up, down, acc, loss, dt in rows:
        budget_cell = f"{budget:g}" if budget else "—"
        lines.append(
            f"| {name} | {lr} | {pv} | {drop:g} | {budget_cell} | {up:,} | "
            f"{down:,} | {acc:.4f} | {loss:.4f} | {dt:.0f} |"
        )
    lines += [
        "",
        "The FetchSGD north star (BASELINE.md) is sketch matching the",
        "uncompressed baseline's accuracy at reduced upload bytes/round —",
        "compare the sketch rows against row 1 at the byte counts shown.",
        "",
        "Budgeted rows (`--budget_mb`, the control/ hard cap) CHANGE the",
        "loss-vs-bytes x-axis semantics: unbudgeted rows all end at the",
        "same ROUND count (cum bytes differ per mode), budgeted rows all",
        "end at <= the same CUM BYTES (round counts differ — cheap modes",
        "run the full schedule, expensive ones stop early as",
        "budget-truncated rows). Compare budgeted rows only against",
        "budgeted rows.",
    ]
    # Preserve any hand-written analysis section in the existing file: the
    # table is regenerated, the narrative (e.g. "## Reading these numbers
    # (r3)" in ACCURACY.md) is NOT this script's to destroy. Synthetic-run
    # narratives must NOT leak into a real-data report, so a real-CIFAR
    # run writes table-only (analyze it fresh).
    out_path = Path(args.out)
    marker = "\n## Reading these numbers"
    if out_path.exists() and not real:
        old = out_path.read_text()
        cut = old.find(marker)
        if cut != -1:
            lines += ["", old[cut:].strip()]
    out_path.write_text("\n".join(lines) + "\n")
    print(f"wrote {args.out} ({len(rows)} rows)", flush=True)


if __name__ == "__main__":
    main()
