"""Invariant linter entry point (path-based shim).

Exactly ``python -m commefficient_tpu.analysis`` — same flags
(``--rules``, ``--json``, ``--list-rules``, ``--root``), same exit codes
(0 clean / 1 findings / 2 usage), same last-stdout-line JSON summary —
for environments that invoke gate scripts by path:

    python scripts/lint.py
    python scripts/lint.py --rules traced-purity,rng-stream --json

See commefficient_tpu/analysis/__init__.py for the rule catalogue and
README "Static analysis & invariants" for the pragma grammar.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from commefficient_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
