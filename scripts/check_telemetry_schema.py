"""Validate telemetry artifacts against the versioned schema.

The telemetry subsystem writes six artifact kinds per run dir
(README "Observability" documents the full schema; the version lives in
``commefficient_tpu.telemetry.SCHEMA_VERSION``):

  * ``metrics.jsonl``     — one run-header record per process, then scalar
                            records ``{"name", "value", "step", "t"}``
  * ``comm_ledger.json``  — cumulative communication accounting; the
                            cumulative bytes must equal
                            ``rounds * bytes_per_round`` EXACTLY — or, for
                            fedsim masked runs (live_client_rounds /
                            avail_client_rounds present), the live-byte
                            sums ``live_client_rounds * upload_bytes`` /
                            ``avail_client_rounds * download_bytes``
  * ``flight_<step>.json``— divergence/crash flight record: metadata +
                            ring-buffered round records in step order
                            (+ the fedsim participation_history window)
  * ``perf_report.json``  — compiled-round XLA audit (v3,
                            telemetry/xla_audit.py): cost/memory analyses
                            (nulls + reason where the backend exposes
                            none), the HLO collective walk and its
                            ledger cross-check. The sketch SHARDED-decode
                            invariants are enforced HERE: every all-gather
                            <= the W*k candidate bound and the ledger-vs-
                            HLO byte delta within the recorded tolerance.
  * ``spans_<step>.json`` — host phase spans (v3, telemetry/spans.py) in
                            Chrome-trace/Perfetto event format; v11 adds
                            the optional args.trace_id/args.parent
                            correlation fields (rules enforced below)
  * ``run_report.json``   — critical-path run report (v11,
                            telemetry/trace.py build_run_report, written
                            by the train loop's close path and
                            scripts/analyze_run.py): per-stage exclusive
                            p50/p95 + attribution fractions summing to 1
                            and per-round DISJOINT stage times summing to
                            the round's wall-clock — both enforced here.

Consumers (plotting, run comparison, the driver's ACCURACY tooling) parse
these blind, so the writers and this checker are pinned to each other by
tests/test_telemetry_schema.py + tests/test_xla_audit.py — the tests write
artifacts through the REAL classes and validate them here, plus rejection
cases (same pattern as scripts/check_mode_dispatch.py). Validators are
hand-rolled: no jsonschema dependency in the container.

    python scripts/check_telemetry_schema.py <run_dir> [...]  # exit 1 on bad
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

# v2 (fedsim PR): fedsim/* scalar namespace, ledger masked live-byte
# accounting (live_client_rounds/avail_client_rounds + exactness
# invariant), flight participation_history; v3 (compiled-graph
# observability PR): xla/* scalar namespace, perf_report.json,
# spans_*.json, header/flight "artifacts" block; v4 (adaptive
# communication-budget PR): control/* scalar namespace, the ledger's
# per-rung "rungs" accounting block (cum bytes == sum over rungs of
# active-rung bytes, live-count-weighted under masking), header/flight
# "controller" block; v5 (pipelined round execution PR): pipeline/*
# scalar namespace (occupancy in [0, 1] and integer staged_rounds
# enforced below), spans thread_name "M" metadata events + per-lane
# tids; v6 (self-healing training PR): resilience/* scalar namespace
# (integer counters, preempt_requested in {0, 1}, rollback_round >= -1 —
# enforced below), the flight dump's recovery_history block (one entry
# per divergence rollback), and the fedsim/preempt scheduled-preemption
# stat; v7 (sparse allreduce collective layer PR): perf_report "aggregate"
# field + collectives "sparse_agg_bound"/"max_all_reduce_elems" — on
# aggregate == 'sparse' NO single all-reduce or all-gather may move more
# elements than sparse_agg_bound (enforced below; reduce-scatter is
# exempt by design: O(D/W) per link, sharded result); v8 (buffered-
# asynchronous federation PR): async/* scalar namespace (staleness_mean/
# staleness_max >= 0, integer buffer_fill >= 0 and concurrent_cohorts
# >= 0, effective_participation >= 0 — enforced below), perf_report
# engine "async" with a REQUIRED {buffer, concurrency,
# staleness_exponent} "async" block on async reports and the block
# FORBIDDEN on synchronous ones; v9 (hidden-collectives PR): the
# xla/exposed_collective_ms scalar (non-negative finite host gauge —
# enforced below), spans events' optional args.collective tag + the
# spans_*.json top-level exposed_collective_ms field, and perf_report's
# "overlap" block {collectives: 'none'|'layerwise', double_buffer} —
# REQUIRED when the report's config has a hiding mode on
# (overlap_collectives != 'none' or async_double_buffer), FORBIDDEN when
# both are off, and never all-off when present (enforced below); v10
# (clientstore PR): clientstore/* scalar namespace (cache_hit_rate in
# [0, 1], integer-valued evictions >= 0, h2d_stage_ms / writeback_ms
# >= 0 — enforced below) and perf_report collectives
# "sparse_agg_exemption" (null | 'client_state_writeback') — on a
# sparse-aggregate report whose config hosts client state
# (client_store host|mmap) ANY exemption is rejected: the hosted round
# takes cohort rows as arguments, so the strict W*k-class
# sparse_agg_bound must hold with no [C, D] writeback allowance
# (enforced below); v11 (round-tracing PR): trace/* scalar namespace
# (critical_stage an integer index into the TRACE_STAGES taxonomy, the
# *_exclusive_ms family finite >= 0 — enforced below), spans events'
# optional args.trace_id (non-empty string) and args.parent (only legal
# beside a trace_id, non-empty, != trace_id — enforced below), and the
# run_report.json artifact (validate_run_report: attribution fractions
# in [0, 1] summing to ~1, per-round disjoint exclusive stage times
# summing to the round's wall-clock); v12 (multihost PR): multihost/*
# scalar namespace (num_processes an integer >= 1, host_id an integer
# >= 0, cross_host_bytes / dcn_exposed_ms >= 0 — enforced below) and
# perf_report's "multihost" block {num_hosts >= 2, num_processes >= 1,
# host_id in [0, num_processes)} — REQUIRED when the report's config
# declares a host axis (num_hosts > 1), FORBIDDEN on single-host
# reports (enforced below); v13 (elastic-fleet PR): fleet/* scalar
# namespace (width a positive integer, resizes / shrink_recoveries
# non-negative integers — resizes additionally non-decreasing across a
# flight dump's step-ordered records — last_resize_round an integer
# >= -1 and <= the record's step: a resize cannot postdate the round
# reporting it — enforced below) and the staleness_aware control
# scalars control/async_k (positive integer), control/async_c
# (positive integer), control/retunes (non-negative integer). Older
# artifacts stay valid.
KNOWN_SCHEMA_VERSIONS = (1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13)

# scalar-name schema: bare "lr", or a namespaced name under one of the
# documented prefixes (README "Observability")
SCALAR_PREFIXES = ("train/", "val/", "diag/", "comm/", "fedsim/", "xla/",
                   "control/", "pipeline/", "resilience/", "async/",
                   "clientstore/", "trace/", "multihost/", "fleet/")

# pinned copy of telemetry.trace.STAGES (this checker imports nothing
# from the package by design — tests/test_telemetry_schema.py pins the
# two tuples against each other)
TRACE_STAGES = ("data", "h2d", "dispatch", "collective", "drain",
                "writeback", "idle")


class SchemaError(ValueError):
    pass


def _strict_loads(s: str):
    """json.loads that REJECTS bare NaN/Infinity tokens: Python's parser
    accepts them, but the schema promises strict JSON (non-finite values
    are stringified markers — telemetry.jsonable_scalar), so a writer
    regression must fail here, not at some downstream jq/JS consumer."""

    def _bad(tok):
        raise SchemaError(f"bare {tok} token — not strict JSON")

    return json.loads(s, parse_constant=_bad)


def _req(record: dict, field: str, types, where: str):
    if field not in record:
        raise SchemaError(f"{where}: missing required field {field!r}")
    if not isinstance(record[field], types):
        raise SchemaError(
            f"{where}: field {field!r} has type "
            f"{type(record[field]).__name__}, expected {types}"
        )
    return record[field]


def _check_version(record: dict, where: str) -> None:
    v = _req(record, "schema_version", int, where)
    if v not in KNOWN_SCHEMA_VERSIONS:
        raise SchemaError(
            f"{where}: unknown schema_version {v} "
            f"(known: {KNOWN_SCHEMA_VERSIONS})"
        )


def _check_controller_block(block: dict, where: str) -> None:
    """The v4 controller block (metrics run-header + flight dumps):
    enough to attribute a record to its rung/policy — policy + ladder
    identity, the rung at write/dump time, and (flight dumps) the switch
    count and budget state."""
    _req(block, "policy", str, where)
    _req(block, "ladder", str, where)
    rung = _req(block, "rung", int, where)
    n = _req(block, "num_rungs", int, where)
    if n < 1 or not 0 <= rung < n:
        raise SchemaError(
            f"{where}: rung {rung} outside [0, num_rungs={n})"
        )
    for f in ("switches", "rounds_seen", "budget_bytes",
              "budget_remaining_bytes"):
        if f in block and not isinstance(block[f], int):
            raise SchemaError(f"{where}: {f} must be an int")


def _check_header(rec: dict, where: str) -> None:
    _check_version(rec, where)
    _req(rec, "time", (int, float), where)
    _req(rec, "start_time", str, where)
    if "config" in rec:
        _req(rec, "config", dict, where)
    if "controller" in rec:
        _check_controller_block(
            _req(rec, "controller", dict, where), where + ":controller"
        )
    if "artifacts" in rec:
        # v3: links to this run's profiling evidence (StepProfiler trace
        # logdir, perf_report.json path) — string values only
        arts = _req(rec, "artifacts", dict, where)
        for k, v in arts.items():
            if not isinstance(v, str):
                raise SchemaError(
                    f"{where}: artifacts[{k!r}] must be a path string, "
                    f"got {type(v).__name__}"
                )


def _check_scalar_name(name: str, where: str,
                       allow_bare_aux: bool = False) -> None:
    """``allow_bare_aux``: flight records carry the round's RAW metric dict
    (the packed drain output), whose workload aux keys are bare identifiers
    (loss, correct, count, lm_loss, mc_loss, ...) next to the namespaced
    diag/comm scalars; metrics.jsonl names stay strictly namespaced."""
    if name == "lr":
        return
    if any(name.startswith(p) and len(name) > len(p)
           for p in SCALAR_PREFIXES):
        return
    if allow_bare_aux and name.isidentifier() and "/" not in name:
        return
    raise SchemaError(
        f"{where}: scalar name {name!r} outside the documented schema "
        f"(lr | {'|'.join(p + '*' for p in SCALAR_PREFIXES)}"
        + (" | bare aux identifier" if allow_bare_aux else "") + ")"
    )


def _check_scalar_value(v, name: str, where: str) -> None:
    """Numbers, or the "nan"/"inf"/"-inf" markers non-finite values are
    stringified to so every line stays strict JSON
    (telemetry.jsonable_scalar)."""
    if isinstance(v, bool) or (
        not isinstance(v, (int, float)) and v not in ("nan", "inf", "-inf")
    ):
        raise SchemaError(
            f"{where}: scalar {name!r} is neither a number nor a "
            f"nan/inf marker: {v!r}"
        )


def _check_pipeline_scalar(name: str, v, where: str) -> None:
    """v5 ``pipeline/*`` value invariants. These are host-computed gauges
    (never legitimately non-finite, unlike a diverging loss), so the
    nan/inf markers are rejected too: ``occupancy`` is staged/depth and
    must be a real fraction of the window; ``staged_rounds`` is a queue
    COUNT and must be a non-negative integer — a fractional or negative
    value means the writer miscounted, exactly what this check catches."""
    if not name.startswith("pipeline/"):
        return
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise SchemaError(
            f"{where}: {name!r} must be a finite number (host gauge), "
            f"got {v!r}"
        )
    if name == "pipeline/occupancy" and not 0.0 <= v <= 1.0:
        raise SchemaError(
            f"{where}: pipeline/occupancy {v} outside [0, 1] — occupancy "
            "is staged_rounds / pipeline_depth by definition"
        )
    if name == "pipeline/staged_rounds" and (v != int(v) or v < 0):
        raise SchemaError(
            f"{where}: pipeline/staged_rounds {v} is not a non-negative "
            "integer — it counts whole staged rounds"
        )
    if name == "pipeline/scan_rounds_per_dispatch" and (
            v != int(v) or v < 1):
        raise SchemaError(
            f"{where}: pipeline/scan_rounds_per_dispatch {v} is not a "
            "positive integer — it counts the scanned block's whole "
            "rounds (scan engine, pipeline/scan_engine.py)"
        )


def _check_resilience_scalar(name: str, v, where: str) -> None:
    """v6 ``resilience/*`` value invariants. Host-computed gauges like the
    pipeline/* family (never legitimately non-finite, so the nan/inf
    markers are rejected too): ``recoveries`` / ``rung_demotions`` /
    ``blacklisted_clients`` COUNT whole events/clients and must be
    non-negative integers; ``preempt_requested`` is a 0/1 flag;
    ``rollback_round`` is the last rollback target round, -1 when the run
    never rolled back."""
    if not name.startswith("resilience/"):
        return
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise SchemaError(
            f"{where}: {name!r} must be a finite number (host gauge), "
            f"got {v!r}"
        )
    if name in ("resilience/recoveries", "resilience/rung_demotions",
                "resilience/blacklisted_clients") and (v != int(v) or v < 0):
        raise SchemaError(
            f"{where}: {name} {v} is not a non-negative integer — it "
            "counts whole recovery events/clients"
        )
    if name == "resilience/preempt_requested" and v not in (0, 1, 0.0, 1.0):
        raise SchemaError(
            f"{where}: resilience/preempt_requested {v} is not a 0/1 flag"
        )
    if name == "resilience/rollback_round" and (v != int(v) or v < -1):
        raise SchemaError(
            f"{where}: resilience/rollback_round {v} must be an integer "
            ">= -1 (-1 = never rolled back)"
        )


def _check_async_scalar(name: str, v, where: str) -> None:
    """v8 ``async/*`` value invariants. Host-computed overlap gauges
    (asyncfed/engine.py), never legitimately non-finite: staleness is a
    server-version delta (>= 0 by construction); ``buffer_fill`` counts
    delivered-unconsumed contributions (non-negative integer);
    ``concurrent_cohorts`` counts in-flight cohorts after the top-up
    (non-negative integer; 0 only on trailing updates, where the
    schedule stops relaunching); ``effective_participation`` is the
    update's weight sum (>= 0; < K under staleness discounting)."""
    if not name.startswith("async/"):
        return
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise SchemaError(
            f"{where}: {name!r} must be a finite number (host gauge), "
            f"got {v!r}"
        )
    if name in ("async/staleness_mean", "async/staleness_max",
                "async/effective_participation") and v < 0:
        raise SchemaError(
            f"{where}: {name} {v} is negative — staleness is a server-"
            "version delta and participation a weight sum, both >= 0"
        )
    if name == "async/buffer_fill" and (v != int(v) or v < 0):
        raise SchemaError(
            f"{where}: async/buffer_fill {v} is not a non-negative "
            "integer — it counts delivered-unconsumed contributions"
        )
    if name == "async/concurrent_cohorts" and (v != int(v) or v < 0):
        raise SchemaError(
            f"{where}: async/concurrent_cohorts {v} is not a non-negative "
            "integer — it counts whole in-flight cohorts"
        )


def _check_clientstore_scalar(name: str, v, where: str) -> None:
    """v10 ``clientstore/*`` value invariants. Host-computed gauges from
    the CohortStreamer (clientstore/streamer.py), never legitimately
    non-finite: ``cache_hit_rate`` is hits/(hits+misses) over one round
    (a real fraction, 0.0 with no cache); ``evictions`` counts whole
    rows leaving the LRU cache; the ``*_ms`` pair are perf_counter
    timings of the H2D stage and the bank writeback."""
    if not name.startswith("clientstore/"):
        return
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise SchemaError(
            f"{where}: {name!r} must be a finite number (host gauge), "
            f"got {v!r}"
        )
    if name == "clientstore/cache_hit_rate" and not 0.0 <= v <= 1.0:
        raise SchemaError(
            f"{where}: clientstore/cache_hit_rate {v} outside [0, 1] — "
            "it is hits/(hits+misses) over one round"
        )
    if name == "clientstore/evictions" and (v != int(v) or v < 0):
        raise SchemaError(
            f"{where}: clientstore/evictions {v} is not a non-negative "
            "integer — it counts whole rows written through the cache"
        )
    if name in ("clientstore/h2d_stage_ms",
                "clientstore/writeback_ms") and v < 0:
        raise SchemaError(
            f"{where}: {name} {v} is negative — host wall-clock gauges "
            "are >= 0"
        )


def _check_multihost_scalar(name: str, v, where: str) -> None:
    """v12 ``multihost/*`` value invariants. Host-computed topology/
    traffic gauges (parallel/api.py under cfg.num_hosts > 1), never
    legitimately non-finite: ``num_processes`` is jax.process_count()
    (>= 1 — exactly 1 on the mesh-faked twin); ``host_id`` is
    jax.process_index() (a non-negative integer; the metrics stream is
    per-process so the < num_processes half of the invariant is enforced
    on the perf report's multihost block, where both live together);
    ``cross_host_bytes`` is the round's upload payload riding the host
    axis; ``dcn_exposed_ms`` an interval measure like
    xla/exposed_collective_ms."""
    if not name.startswith("multihost/"):
        return
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise SchemaError(
            f"{where}: {name!r} must be a finite number (host gauge), "
            f"got {v!r}"
        )
    if name == "multihost/num_processes" and (v != int(v) or v < 1):
        raise SchemaError(
            f"{where}: multihost/num_processes {v} is not a positive "
            "integer — it counts whole pod processes (1 = mesh-faked)"
        )
    if name == "multihost/host_id" and (v != int(v) or v < 0):
        raise SchemaError(
            f"{where}: multihost/host_id {v} is not a non-negative "
            "integer — it is this process's index in the pod"
        )
    if name in ("multihost/cross_host_bytes",
                "multihost/dcn_exposed_ms") and v < 0:
        raise SchemaError(
            f"{where}: {name} {v} is negative — byte counts and "
            "wall-clock exposure gauges are >= 0"
        )


def _check_fleet_scalar(name: str, v, where: str, step=None) -> None:
    """v13 ``fleet/*`` value invariants. Host-computed elastic-fleet
    gauges (parallel/api.py under cfg.fleet_enabled), schedule-derived
    and never legitimately non-finite: ``width`` is the round's REALIZED
    worker count (a positive integer — the width schedule never folds to
    zero, the config validator rejects it); ``resizes`` counts width
    transitions realized so far and ``shrink_recoveries`` completed
    shrink rollbacks (whole events); ``last_resize_round`` is the round
    the width last changed at, -1 before the first transition — and a
    resize cannot postdate the round reporting it, so when the record's
    ``step`` is known the value must be <= it."""
    if not name.startswith("fleet/"):
        return
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise SchemaError(
            f"{where}: {name!r} must be a finite number (host gauge), "
            f"got {v!r}"
        )
    if name == "fleet/width" and (v != int(v) or v < 1):
        raise SchemaError(
            f"{where}: fleet/width {v} is not a positive integer — it is "
            "the round's realized worker count"
        )
    if name in ("fleet/resizes", "fleet/shrink_recoveries") and (
            v != int(v) or v < 0):
        raise SchemaError(
            f"{where}: {name} {v} is not a non-negative integer — it "
            "counts whole width transitions / shrink rollbacks"
        )
    if name == "fleet/last_resize_round":
        if v != int(v) or v < -1:
            raise SchemaError(
                f"{where}: fleet/last_resize_round {v} must be an integer "
                ">= -1 (-1 = the width never changed)"
            )
        if step is not None and v > step:
            raise SchemaError(
                f"{where}: fleet/last_resize_round {v} postdates the "
                f"record's step {step} — a resize cannot come from the "
                "future"
            )


def _check_control_async_scalar(name: str, v, where: str) -> None:
    """v13 staleness_aware control scalars: the controller's live async
    geometry (control/controller.py, emitted only under an ADAPTS_ASYNC
    policy). ``async_k``/``async_c`` are the retuned buffer size and
    concurrency (positive integers — the controller clamps K >= 1,
    C >= 1); ``retunes`` counts applied (K, C) changes."""
    if name not in ("control/async_k", "control/async_c",
                    "control/retunes"):
        return
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise SchemaError(
            f"{where}: {name!r} must be a finite number (host gauge), "
            f"got {v!r}"
        )
    if name == "control/retunes":
        if v != int(v) or v < 0:
            raise SchemaError(
                f"{where}: control/retunes {v} is not a non-negative "
                "integer — it counts whole applied (K, C) retunes"
            )
    elif v != int(v) or v < 1:
        raise SchemaError(
            f"{where}: {name} {v} is not a positive integer — the "
            "controller clamps the async geometry to K >= 1, C >= 1"
        )


def _check_xla_scalar(name: str, v, where: str) -> None:
    """v9 ``xla/exposed_collective_ms`` value invariant: a host-computed
    cumulative gauge (interval arithmetic over the span recorder — never
    legitimately non-finite, so the nan/inf markers are rejected) and
    non-negative by construction: it measures un-overlapped collective
    wait, and negative time means the writer's interval subtraction
    broke."""
    if name != "xla/exposed_collective_ms":
        return
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise SchemaError(
            f"{where}: {name!r} must be a finite number (host gauge), "
            f"got {v!r}"
        )
    if v < 0:
        raise SchemaError(
            f"{where}: xla/exposed_collective_ms {v} is negative — "
            "exposed collective time is an interval measure, >= 0"
        )


def _check_trace_scalar(name: str, v, where: str) -> None:
    """v11 ``trace/*`` value invariants. Host-computed critical-path
    gauges (telemetry/trace.py CriticalPath), never legitimately
    non-finite: ``critical_stage`` is the INDEX of the round's binding
    stage in the TRACE_STAGES taxonomy (an integer by construction);
    the ``*_exclusive_ms`` family are disjoint interval measures and
    negative time means the exclusive-assignment subtraction broke."""
    if not name.startswith("trace/"):
        return
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise SchemaError(
            f"{where}: {name!r} must be a finite number (host gauge), "
            f"got {v!r}"
        )
    if name == "trace/critical_stage" and (
            v != int(v) or not 0 <= v < len(TRACE_STAGES)):
        raise SchemaError(
            f"{where}: trace/critical_stage {v} is not an integer index "
            f"into the {len(TRACE_STAGES)}-stage taxonomy "
            f"{TRACE_STAGES}"
        )
    if name.endswith("_exclusive_ms") and v < 0:
        raise SchemaError(
            f"{where}: {name} {v} is negative — exclusive stage times "
            "are disjoint interval measures, >= 0 by construction"
        )


def _check_recovery_history(hist, where: str) -> None:
    """v6 flight ``recovery_history`` block: one entry per divergence
    rollback, in recovery order."""
    if not isinstance(hist, list) or not hist:
        raise SchemaError(f"{where}: recovery_history must be a non-empty "
                          "list of recovery entries")
    for j, entry in enumerate(hist):
        w = f"{where}:recovery_history[{j}]"
        if not isinstance(entry, dict):
            raise SchemaError(f"{w}: expected an object")
        n = _req(entry, "recovery", int, w)
        if n != j + 1:
            raise SchemaError(
                f"{w}: recovery ordinal {n} out of order (expected {j + 1})"
            )
        _req(entry, "policy", str, w)
        fb = _req(entry, "first_bad_step", int, w)
        if fb < 0:
            raise SchemaError(f"{w}: negative first_bad_step")
        _req(entry, "outcome", str, w)
        if "rollback_to" in entry and entry["rollback_to"] is not None:
            rb = _req(entry, "rollback_to", int, w)
            if not 0 <= rb <= fb:
                raise SchemaError(
                    f"{w}: rollback_to {rb} outside [0, first_bad_step="
                    f"{fb}] — a rollback target must be pre-divergence"
                )


def validate_metrics_jsonl(path) -> int:
    """Validate a metrics.jsonl; returns the number of scalar records."""
    n_scalars = 0
    saw_header = False
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            where = f"{path}:{i}"
            try:
                rec = _strict_loads(line)
            except json.JSONDecodeError as e:
                raise SchemaError(f"{where}: not valid JSON ({e.msg})")
            except SchemaError as e:
                raise SchemaError(f"{where}: {e}")
            if not isinstance(rec, dict):
                raise SchemaError(f"{where}: record is not an object")
            if rec.get("type") == "header":
                # one header per process; a resumed run appends another
                _check_header(rec, where)
                saw_header = True
                continue
            if i == 1:
                raise SchemaError(
                    f"{where}: first record must be the run header "
                    "(type='header') — this file predates the header "
                    "schema or was truncated"
                )
            name = _req(rec, "name", str, where)
            _check_scalar_name(name, where)
            if "value" not in rec:
                raise SchemaError(f"{where}: missing required field 'value'")
            _check_scalar_value(rec["value"], name, where)
            _check_pipeline_scalar(name, rec["value"], where)
            _check_resilience_scalar(name, rec["value"], where)
            _check_async_scalar(name, rec["value"], where)
            _check_clientstore_scalar(name, rec["value"], where)
            _check_multihost_scalar(name, rec["value"], where)
            _check_xla_scalar(name, rec["value"], where)
            _check_trace_scalar(name, rec["value"], where)
            _check_control_async_scalar(name, rec["value"], where)
            step = _req(rec, "step", int, where)
            if step < 0:
                raise SchemaError(f"{where}: negative step {step}")
            _check_fleet_scalar(name, rec["value"], where, step=step)
            _req(rec, "t", (int, float), where)
            n_scalars += 1
    if not saw_header:
        raise SchemaError(f"{path}: no run-header record")
    return n_scalars


def validate_comm_ledger(path) -> dict:
    """Validate comm_ledger.json INCLUDING the exactness invariant.

    Full-participation ledgers: cumulative bytes == rounds *
    bytes_per_round. fedsim masked ledgers (the ``live_client_rounds`` /
    ``avail_client_rounds`` keys present): only live clients' uplink and
    available clients' downlink counted, so the invariant becomes
    ``cum_up_bytes == live_client_rounds * upload_bytes`` (with
    live_client_rounds = sum over rounds of that round's live count) and
    likewise for the downlink — exact ints, no tolerance."""
    where = str(path)
    with open(path) as f:
        rec = _strict_loads(f.read())
    _check_version(rec, where)
    _req(rec, "mode", str, where)
    nw = _req(rec, "num_workers", int, where)
    if nw < 1:
        raise SchemaError(f"{where}: num_workers must be >= 1, got {nw}")
    bpr = _req(rec, "bytes_per_round", dict, where)
    for k in ("upload_floats", "download_floats", "upload_bytes",
              "download_bytes"):
        if not isinstance(bpr.get(k), int):
            raise SchemaError(f"{where}: bytes_per_round[{k!r}] missing or "
                              "not an int")
    rounds = _req(rec, "rounds", int, where)
    up = _req(rec, "cum_up_bytes", int, where)
    down = _req(rec, "cum_down_bytes", int, where)
    total = _req(rec, "cum_bytes", int, where)
    masked = "live_client_rounds" in rec or "avail_client_rounds" in rec
    if masked:
        live = _req(rec, "live_client_rounds", int, where)
        avail = _req(rec, "avail_client_rounds", int, where)
        if not 0 <= live <= rounds * nw:
            raise SchemaError(
                f"{where}: live_client_rounds {live} outside "
                f"[0, rounds * num_workers] ({rounds} * {nw})"
            )
        if not live <= avail <= rounds * nw:
            raise SchemaError(
                f"{where}: avail_client_rounds {avail} outside "
                f"[live_client_rounds, rounds * num_workers]"
            )
    if "rungs" in rec:
        # v4 control/ ladder accounting: each round billed at its ACTIVE
        # rung's rate — the invariant is the sum over rungs of that
        # rung's rounds (live/avail counts when masked) x its
        # bytes_per_round. Exact ints, no tolerance, like the flat law.
        rungs = _req(rec, "rungs", list, where)
        if not rungs:
            raise SchemaError(f"{where}: empty rungs block")
        up_want = down_want = rounds_sum = 0
        live_sum = avail_sum = 0
        for i, r in enumerate(rungs):
            w = f"{where}:rungs[{i}]"
            if not isinstance(r, dict):
                raise SchemaError(f"{w}: expected an object")
            rb = _req(r, "bytes_per_round", dict, w)
            for k in ("upload_bytes", "download_bytes"):
                if not isinstance(rb.get(k), int):
                    raise SchemaError(
                        f"{w}: bytes_per_round[{k!r}] missing or not an int"
                    )
            n_r = _req(r, "rounds", int, w)
            if n_r < 0:
                raise SchemaError(f"{w}: negative rounds")
            rounds_sum += n_r
            if masked:
                live_r = _req(r, "live_client_rounds", int, w)
                avail_r = _req(r, "avail_client_rounds", int, w)
                live_sum += live_r
                avail_sum += avail_r
                up_want += live_r * rb["upload_bytes"]
                down_want += avail_r * rb["download_bytes"]
            else:
                up_want += n_r * rb["upload_bytes"]
                down_want += n_r * rb["download_bytes"]
        if rounds_sum != rounds:
            raise SchemaError(
                f"{where}: per-rung rounds sum to {rounds_sum}, ledger "
                f"counted {rounds}"
            )
        if masked and (live_sum != live or avail_sum != avail):
            raise SchemaError(
                f"{where}: per-rung live/avail client-rounds "
                f"({live_sum}/{avail_sum}) != ledger totals "
                f"({live}/{avail})"
            )
        up_law = ("sum_r live_r * up_r" if masked
                  else "sum_r rounds_r * up_r")
        down_law = ("sum_r avail_r * down_r" if masked
                    else "sum_r rounds_r * down_r")
    elif masked:
        up_want, down_want = (live * bpr["upload_bytes"],
                              avail * bpr["download_bytes"])
        up_law = "live_client_rounds * upload_bytes"
        down_law = "avail_client_rounds * download_bytes"
    else:
        up_want, down_want = (rounds * bpr["upload_bytes"],
                              rounds * bpr["download_bytes"])
        up_law = "rounds * upload_bytes"
        down_law = "rounds * download_bytes"
    if up != up_want:
        raise SchemaError(
            f"{where}: cum_up_bytes {up} != {up_law} ({up_want})"
        )
    if down != down_want:
        raise SchemaError(
            f"{where}: cum_down_bytes {down} != {down_law} ({down_want})"
        )
    if total != up + down:
        raise SchemaError(f"{where}: cum_bytes {total} != up + down")
    return rec


def validate_flight(path) -> dict:
    """Validate a flight_<step>.json record."""
    where = str(path)
    with open(path) as f:
        rec = _strict_loads(f.read())
    _check_version(rec, where)
    _req(rec, "reason", str, where)
    if "first_bad_step" in rec and rec["first_bad_step"] is not None:
        _req(rec, "first_bad_step", int, where)
    window = _req(rec, "window", int, where)
    if window < 1:
        raise SchemaError(f"{where}: window must be >= 1")
    _check_header({**_req(rec, "meta", dict, where),
                   "schema_version": rec["schema_version"]}, where + ":meta")
    records = _req(rec, "records", list, where)
    if len(records) > window:
        raise SchemaError(
            f"{where}: {len(records)} records exceed the ring window "
            f"{window}"
        )
    if "controller" in rec:
        # v4 ladder runs: the dump-time controller state surfaced
        # top-level by FlightRecorder.dump — a divergence is attributable
        # to a rung switch from here + the per-record control/rung scalars
        _check_controller_block(
            _req(rec, "controller", dict, where), where + ":controller"
        )
    if "recovery_history" in rec:
        # v6 self-healing runs: every rollback this run survived (policy,
        # first bad round, rollback target, outcome) — surfaced top-level
        # by FlightRecorder.dump via the attached resilience rider
        _check_recovery_history(rec["recovery_history"], where)
    if "participation_history" in rec:
        # fedsim runs: the [step, participation_rate] window surfaced
        # top-level by FlightRecorder.dump
        hist = _req(rec, "participation_history", list, where)
        if len(hist) > window:
            raise SchemaError(
                f"{where}: participation_history exceeds the ring window"
            )
        for j, pair in enumerate(hist):
            w = f"{where}:participation_history[{j}]"
            if (not isinstance(pair, list) or len(pair) != 2
                    or isinstance(pair[0], bool)
                    or not isinstance(pair[0], int)):
                raise SchemaError(f"{w}: expected [step, rate] pair")
            _check_scalar_value(pair[1], "fedsim/participation_rate", w)
    last = None
    last_resizes = None
    for j, r in enumerate(records):
        w = f"{where}:records[{j}]"
        step = _req(r, "step", int, w)
        if "lr" not in r:
            raise SchemaError(f"{w}: missing required field 'lr'")
        _check_scalar_value(r["lr"], "lr", w)  # number or nan/inf marker
        scalars = _req(r, "scalars", dict, w)
        for name, v in scalars.items():
            _check_scalar_name(name, w, allow_bare_aux=True)
            _check_scalar_value(v, name, w)
            _check_pipeline_scalar(name, v, w)
            _check_resilience_scalar(name, v, w)
            _check_async_scalar(name, v, w)
            _check_clientstore_scalar(name, v, w)
            _check_multihost_scalar(name, v, w)
            _check_xla_scalar(name, v, w)
            _check_trace_scalar(name, v, w)
            _check_control_async_scalar(name, v, w)
            _check_fleet_scalar(name, v, w, step=step)
        # v13: fleet/resizes counts realized width transitions — over the
        # dump's step-ordered ring it can only grow (a drop means the
        # writer re-derived the schedule wrong, or records from two runs
        # were spliced)
        if "fleet/resizes" in scalars:
            rz = scalars["fleet/resizes"]
            if last_resizes is not None and rz < last_resizes:
                raise SchemaError(
                    f"{w}: fleet/resizes fell from {last_resizes} to {rz} "
                    "— resize counts are non-decreasing in step order"
                )
            last_resizes = rz
        if last is not None and step <= last:
            raise SchemaError(f"{w}: records not in increasing step order")
        last = step
    return rec


def _check_analysis_block(block: dict, fields, where: str) -> None:
    """cost/memory analysis block: every field a non-negative number or
    null; degraded blocks must say why (non-empty unavailable_reason)."""
    for f in fields:
        v = block.get(f)
        if v is None:
            continue
        if isinstance(v, bool) or not isinstance(v, (int, float)) or v < 0:
            raise SchemaError(
                f"{where}: {f} must be a non-negative number or null, "
                f"got {v!r}"
            )
    if all(block.get(f) is None for f in fields):
        reason = block.get("unavailable_reason")
        if not isinstance(reason, str) or not reason:
            raise SchemaError(
                f"{where}: fully-degraded analysis must carry a non-empty "
                "unavailable_reason"
            )


def validate_perf_report(path) -> dict:
    """Validate a perf_report.json (v3, telemetry/xla_audit.py) INCLUDING
    the collective invariants: total_bytes == sum over ops, delta/
    within_tolerance arithmetic consistent — and on the sketch
    sharded-decode path, the PR-6 design claims are HARD requirements:
    every all-gather <= the recorded W*k bound and the ledger-vs-HLO byte
    delta within the recorded accounting tolerance."""
    where = str(path)
    with open(path) as f:
        rec = _strict_loads(f.read())
    _check_version(rec, where)
    if rec.get("kind") != "perf_report":
        raise SchemaError(f"{where}: kind must be 'perf_report', got "
                          f"{rec.get('kind')!r}")
    _req(rec, "generated_by", str, where)
    engine = _req(rec, "engine", str, where)
    if engine not in ("replicated", "fsdp", "async"):
        raise SchemaError(f"{where}: unknown engine {engine!r}")
    _req(rec, "mode", str, where)
    # v8: the overlap-geometry block is required exactly on async audits —
    # a synchronous report carrying one means the producer mislabeled the
    # engine (or vice versa), so both directions are hard errors
    if engine == "async":
        blk = _req(rec, "async", dict, where)
        for f, lo in (("buffer", 1), ("concurrency", 1),
                      ("staleness_exponent", 0)):
            v = blk.get(f)
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise SchemaError(
                    f"{where}:async: missing or non-numeric {f!r}"
                )
            if f != "staleness_exponent" and v != int(v):
                raise SchemaError(f"{where}:async: {f} must be an integer, "
                                  f"got {v!r}")
            if v < lo:
                raise SchemaError(f"{where}:async: {f} {v} below {lo}")
    elif "async" in rec:
        raise SchemaError(
            f"{where}: 'async' block present on a {engine!r} report — the "
            "overlap geometry is an async-engine property (schema v8)"
        )
    # v9: the collective-hiding block is required exactly when the
    # report's config has a hiding mode on — wall-clock rows must always
    # be attributable to their overlap setting, so a report silently
    # produced under layerwise overlap (block missing) and one carrying a
    # both-off block (mislabeled producer) are both hard errors
    cfg_blk = rec.get("meta", {}).get("config") or {}
    cfg_hiding = (cfg_blk.get("overlap_collectives", "none") != "none"
                  or bool(cfg_blk.get("async_double_buffer", False)))
    if "overlap" in rec:
        blk = _req(rec, "overlap", dict, where)
        ov = blk.get("collectives")
        if ov not in ("none", "layerwise"):
            raise SchemaError(
                f"{where}:overlap: collectives must be 'none' or "
                f"'layerwise', got {ov!r}"
            )
        db = blk.get("double_buffer")
        if not isinstance(db, bool):
            raise SchemaError(
                f"{where}:overlap: double_buffer must be a bool, got {db!r}"
            )
        if ov == "none" and not db:
            raise SchemaError(
                f"{where}: 'overlap' block with every hiding mode off — "
                "the block rides the report only when a mode is ON "
                "(schema v9)"
            )
        if cfg_blk and not cfg_hiding:
            raise SchemaError(
                f"{where}: 'overlap' block present but the report's config "
                "has overlap_collectives='none' and async_double_buffer "
                "off — mislabeled producer (schema v9)"
            )
    elif cfg_hiding:
        raise SchemaError(
            f"{where}: config has a collective-hiding mode on "
            f"(overlap_collectives="
            f"{cfg_blk.get('overlap_collectives', 'none')!r}, "
            f"async_double_buffer={cfg_blk.get('async_double_buffer')!r}) "
            "but the report carries no 'overlap' block (schema v9)"
        )
    # v12: the multihost block is required exactly when the report's
    # config declares a host axis — a pod report without one would leave
    # its wall-clock rows unattributable to a topology, and a single-host
    # report carrying one means the producer mislabeled the mesh
    cfg_multihost = int(cfg_blk.get("num_hosts", 1) or 1) > 1
    if "multihost" in rec:
        blk = _req(rec, "multihost", dict, where)
        nh = blk.get("num_hosts")
        if isinstance(nh, bool) or not isinstance(nh, int) or nh < 2:
            raise SchemaError(
                f"{where}:multihost: num_hosts must be an integer >= 2 "
                f"(the block only rides multi-host audits), got {nh!r}"
            )
        nproc = blk.get("num_processes")
        if isinstance(nproc, bool) or not isinstance(nproc, int) or nproc < 1:
            raise SchemaError(
                f"{where}:multihost: num_processes must be an integer "
                f">= 1 (1 = mesh-faked twin), got {nproc!r}"
            )
        hid = blk.get("host_id")
        if (isinstance(hid, bool) or not isinstance(hid, int)
                or not 0 <= hid < nproc):
            raise SchemaError(
                f"{where}:multihost: host_id {hid!r} outside "
                f"[0, num_processes={nproc}) — the writing process's "
                "index in the pod"
            )
        if cfg_blk and not cfg_multihost:
            raise SchemaError(
                f"{where}: 'multihost' block present but the report's "
                "config declares no host axis (num_hosts="
                f"{cfg_blk.get('num_hosts', 1)!r}) — mislabeled producer "
                "(schema v12)"
            )
    elif cfg_multihost:
        raise SchemaError(
            f"{where}: config declares a host axis (num_hosts="
            f"{cfg_blk.get('num_hosts')!r}) but the report carries no "
            "'multihost' block (schema v12)"
        )
    _check_header({**_req(rec, "meta", dict, where),
                   "schema_version": rec["schema_version"]}, where + ":meta")
    cost = _req(rec, "cost", dict, where)
    _check_analysis_block(
        cost, ("flops", "bytes_accessed", "transcendentals"), where + ":cost"
    )
    mem = _req(rec, "memory", dict, where)
    _check_analysis_block(
        mem, ("argument_bytes", "output_bytes", "temp_bytes", "alias_bytes",
              "peak_hbm_bytes"), where + ":memory",
    )
    coll = _req(rec, "collectives", dict, where)
    ops = _req(coll, "ops", dict, where + ":collectives")
    total = _req(coll, "total_bytes", int, where + ":collectives")
    op_sum = 0
    for op, stats in ops.items():
        w = f"{where}:collectives.ops[{op}]"
        if op not in ("all-gather", "all-reduce", "reduce-scatter",
                      "collective-permute"):
            raise SchemaError(f"{w}: unknown collective op")
        if not isinstance(stats, dict):
            raise SchemaError(f"{w}: expected {{count, bytes}}")
        c = _req(stats, "count", int, w)
        b = _req(stats, "bytes", int, w)
        if c < 1 or b < 0:
            raise SchemaError(f"{w}: count must be >= 1 and bytes >= 0")
        op_sum += b
    if total != op_sum:
        raise SchemaError(
            f"{where}: collectives.total_bytes {total} != sum over ops "
            f"({op_sum})"
        )
    # cross-check arithmetic (present iff the producer had ledger figures)
    if coll.get("ledger_up_bytes") is not None:
        up = _req(coll, "ledger_up_bytes", int, where + ":collectives")
        delta = _req(coll, "delta_bytes", int, where + ":collectives")
        tol = _req(coll, "tolerance_bytes", int, where + ":collectives")
        within = _req(coll, "within_tolerance", bool, where + ":collectives")
        if delta != total - up:
            raise SchemaError(
                f"{where}: delta_bytes {delta} != total_bytes - "
                f"ledger_up_bytes ({total - up})"
            )
        if within != (abs(delta) <= tol):
            raise SchemaError(
                f"{where}: within_tolerance {within} inconsistent with "
                f"|delta| {abs(delta)} vs tolerance {tol}"
            )
    # the sketch sharded-decode path's design claims are enforced, not
    # merely recorded (ISSUE 7 acceptance: checker-enforced invariant)
    if rec.get("sketch_decode") == "sharded":
        wk = coll.get("wk_bound")
        if not isinstance(wk, int) or wk < 1:
            raise SchemaError(
                f"{where}: sharded decode requires a positive wk_bound"
            )
        mag = coll.get("max_all_gather_elems")
        if mag is not None and mag > wk:
            raise SchemaError(
                f"{where}: sharded decode all-gather of {mag} elements "
                f"exceeds the W*k candidate bound ({wk}) — a d-sized "
                "collective leaked into the compiled round"
            )
        if coll.get("within_tolerance") is False:
            raise SchemaError(
                f"{where}: sharded decode ledger-vs-HLO delta "
                f"{coll.get('delta_bytes')} B outside the accounting "
                f"tolerance {coll.get('tolerance_bytes')} B"
            )
    # the sparse-aggregate path's O(W*k) on-mesh claim is likewise
    # enforced (v7, ISSUE 14 acceptance): neither replicating collective
    # may move a d-sized payload. reduce-scatter is exempt by design —
    # it moves O(D/W) per link and lands sharded, which is exactly the
    # layout the sparse decode consumes.
    if rec.get("aggregate") == "sparse":
        bound = coll.get("sparse_agg_bound")
        if not isinstance(bound, int) or bound < 1:
            raise SchemaError(
                f"{where}: sparse aggregation requires a positive "
                "sparse_agg_bound"
            )
        # v10: a hosted client store (--client_store host|mmap) passes the
        # cohort's rows as round ARGUMENTS, so the [C, D]-scale writeback
        # gather never exists in the HLO and the STRICT W*k-class bound
        # must hold — an exemption marker on such a report means the
        # producer inflated sparse_agg_bound it had no right to, so the
        # elems-vs-bound checks below would be vacuous. Reject it.
        exemption = coll.get("sparse_agg_exemption")
        if exemption is not None and exemption != "client_state_writeback":
            raise SchemaError(
                f"{where}: unknown sparse_agg_exemption {exemption!r} "
                "(known: 'client_state_writeback')"
            )
        hosted = cfg_blk.get("client_store", "device") in ("host", "mmap")
        if hosted and exemption is not None:
            raise SchemaError(
                f"{where}: sparse-aggregate report carries "
                f"sparse_agg_exemption={exemption!r} but its config hosts "
                "client state (client_store="
                f"{cfg_blk.get('client_store')!r}) — hosted rounds take "
                "cohort rows as arguments, so the strict W*k bound holds "
                "with NO writeback allowance (schema v10)"
            )
        for field, opname in (("max_all_gather_elems", "all-gather"),
                              ("max_all_reduce_elems", "all-reduce")):
            mx = coll.get(field)
            if mx is not None and mx > bound:
                raise SchemaError(
                    f"{where}: sparse aggregation {opname} of {mx} "
                    f"elements exceeds the pair-exchange bound ({bound}) "
                    "— a d-sized replicating collective leaked into the "
                    "compiled round"
                )
    return rec


def validate_spans(path) -> dict:
    """Validate a spans_<step>.json (v3, telemetry/spans.py): Chrome-trace
    complete events with step/fenced annotations."""
    where = str(path)
    with open(path) as f:
        rec = _strict_loads(f.read())
    _check_version(rec, where)
    if rec.get("kind") != "spans":
        raise SchemaError(
            f"{where}: kind must be 'spans', got {rec.get('kind')!r}"
        )
    if "exposed_collective_ms" in rec:
        # v9: the dump-level exposure figure (telemetry/spans.py
        # collective_exposure_ms) — same gauge invariant as the scalar
        _check_xla_scalar("xla/exposed_collective_ms",
                          rec["exposed_collective_ms"], where)
    events = _req(rec, "traceEvents", list, where)
    if not events:
        raise SchemaError(f"{where}: empty traceEvents")
    n_spans = 0
    for j, ev in enumerate(events):
        w = f"{where}:traceEvents[{j}]"
        if not isinstance(ev, dict):
            raise SchemaError(f"{w}: event is not an object")
        name = _req(ev, "name", str, w)
        if not name:
            raise SchemaError(f"{w}: empty event name")
        if ev.get("ph") == "M":
            # v5 thread-aware spans: lane-naming metadata (the prefetch
            # worker's track label) — the only metadata kind the writer
            # emits, so anything else is a writer bug
            if name != "thread_name":
                raise SchemaError(
                    f"{w}: unknown metadata event {name!r} (only "
                    "thread_name is in the schema)"
                )
            args = _req(ev, "args", dict, w)
            if not isinstance(args.get("name"), str) or not args["name"]:
                raise SchemaError(
                    f"{w}: thread_name metadata needs a non-empty "
                    "args.name"
                )
            mtid = _req(ev, "tid", int, w)
            if isinstance(mtid, bool) or mtid < 0:
                raise SchemaError(
                    f"{w}: tid must be a non-negative lane int, got "
                    f"{mtid!r}"
                )
            continue
        if ev.get("ph") != "X":
            raise SchemaError(
                f"{w}: ph must be 'X' (complete event) or 'M' "
                "(thread_name metadata, v5)"
            )
        for f_ in ("ts", "dur"):
            v = _req(ev, f_, (int, float), w)
            if v < 0:
                raise SchemaError(f"{w}: negative {f_}")
        tid = ev.get("tid")
        if isinstance(tid, bool) or not isinstance(tid, int) or tid < 0:
            raise SchemaError(
                f"{w}: tid must be a non-negative lane int, got {tid!r}"
            )
        args = _req(ev, "args", dict, w)
        _req(args, "step", int, w + ":args")
        if "collective" in args and args["collective"] is not True:
            # v9: the tag is only ever written as true (absent == false);
            # any other value means a writer regression
            raise SchemaError(
                f"{w}: args.collective must be true when present, got "
                f"{args['collective']!r}"
            )
        # v11 trace correlation: trace_id names the owning round/cohort
        # ("r<step>" / "c<cohort>"); parent is a causal link and only
        # means something on an id-carrying span — the writer
        # (telemetry/spans.py _record) never emits a bare parent, so one
        # here is a writer regression
        if "trace_id" in args and (
                not isinstance(args["trace_id"], str)
                or not args["trace_id"]):
            raise SchemaError(
                f"{w}: args.trace_id must be a non-empty string, got "
                f"{args['trace_id']!r}"
            )
        if "parent" in args:
            if "trace_id" not in args:
                raise SchemaError(
                    f"{w}: args.parent without args.trace_id — a parent "
                    "link rides only on id-carrying spans (schema v11)"
                )
            par = args["parent"]
            if not isinstance(par, str) or not par:
                raise SchemaError(
                    f"{w}: args.parent must be a non-empty string, got "
                    f"{par!r}"
                )
            if par == args["trace_id"]:
                raise SchemaError(
                    f"{w}: args.parent == args.trace_id ({par!r}) — a "
                    "span cannot be its own causal parent"
                )
        n_spans += 1
    if n_spans == 0:
        raise SchemaError(f"{where}: no complete ('X') span events")
    return rec


def validate_run_report(path) -> dict:
    """Validate a run_report.json (v11, telemetry/trace.py
    build_run_report) INCLUDING the attribution invariants: stage
    fractions in [0, 1] summing to ~1 over analyzed rounds (or all zero
    when nothing was attributed), per-round exclusive stage times
    finite, >= 0, and summing to the round's wall-clock — the
    disjointness guarantee CriticalPath makes; an overlap between two
    stages would push the sum past the wall and fail here."""
    where = str(path)
    with open(path) as f:
        rec = _strict_loads(f.read())
    _check_version(rec, where)
    if rec.get("kind") != "run_report":
        raise SchemaError(f"{where}: kind must be 'run_report', got "
                          f"{rec.get('kind')!r}")
    _req(rec, "generated_by", str, where)
    _req(rec, "sources", dict, where)
    n_rounds = _req(rec, "rounds_analyzed", int, where)
    if n_rounds < 0:
        raise SchemaError(f"{where}: negative rounds_analyzed")
    crit = _req(rec, "critical_stage", str, where)
    if crit not in TRACE_STAGES:
        raise SchemaError(
            f"{where}: critical_stage {crit!r} outside the stage "
            f"taxonomy {TRACE_STAGES}"
        )
    counts = _req(rec, "critical_counts", dict, where)
    if set(counts) != set(TRACE_STAGES):
        raise SchemaError(
            f"{where}: critical_counts keys {sorted(counts)} != the "
            "stage taxonomy"
        )
    for s, c in counts.items():
        if isinstance(c, bool) or not isinstance(c, int) or c < 0:
            raise SchemaError(
                f"{where}: critical_counts[{s!r}] must be a non-negative "
                f"integer, got {c!r}"
            )
    if sum(counts.values()) != n_rounds:
        raise SchemaError(
            f"{where}: critical_counts sum to {sum(counts.values())}, "
            f"but {n_rounds} round(s) were analyzed — every analyzed "
            "round has exactly one binding stage"
        )
    stages = _req(rec, "stages", dict, where)
    if set(stages) != set(TRACE_STAGES):
        raise SchemaError(
            f"{where}: stages keys {sorted(stages)} != the stage taxonomy"
        )
    frac_sum = 0.0
    for s, blk in stages.items():
        w = f"{where}:stages[{s}]"
        if not isinstance(blk, dict):
            raise SchemaError(f"{w}: expected an object")
        for f_ in ("p50_ms", "p95_ms", "total_ms"):
            v = _req(blk, f_, (int, float), w)
            if isinstance(v, bool) or v < 0:
                raise SchemaError(f"{w}: {f_} must be >= 0, got {v!r}")
        fr = _req(blk, "fraction", (int, float), w)
        if isinstance(fr, bool) or not 0.0 <= fr <= 1.0:
            raise SchemaError(
                f"{w}: fraction {fr!r} outside [0, 1]"
            )
        frac_sum += fr
    # fractions are total_ms / total wall per stage, idle the remainder
    # of every round — so they sum to 1 whenever anything was attributed
    # (and to exactly 0 for a spans-less report)
    if frac_sum != 0.0 and abs(frac_sum - 1.0) > 1e-6:
        raise SchemaError(
            f"{where}: stage fractions sum to {frac_sum!r}, expected ~1 "
            "(attribution must account for every analyzed microsecond, "
            "idle included)"
        )
    rounds = _req(rec, "rounds", list, where)
    if len(rounds) != n_rounds:
        raise SchemaError(
            f"{where}: {len(rounds)} per-round entries but "
            f"rounds_analyzed={n_rounds}"
        )
    for j, r in enumerate(rounds):
        w = f"{where}:rounds[{j}]"
        if not isinstance(r, dict):
            raise SchemaError(f"{w}: expected an object")
        step = _req(r, "step", int, w)
        if step < 0:
            raise SchemaError(f"{w}: negative step")
        wall = _req(r, "wall_ms", (int, float), w)
        if isinstance(wall, bool) or wall < 0:
            raise SchemaError(f"{w}: wall_ms must be >= 0, got {wall!r}")
        rc_ = _req(r, "critical_stage", str, w)
        if rc_ not in TRACE_STAGES:
            raise SchemaError(
                f"{w}: critical_stage {rc_!r} outside the stage taxonomy"
            )
        sm = _req(r, "stages_ms", dict, w)
        if set(sm) != set(TRACE_STAGES):
            raise SchemaError(
                f"{w}: stages_ms keys {sorted(sm)} != the stage taxonomy"
            )
        tot = 0.0
        for s, v in sm.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise SchemaError(
                    f"{w}: stages_ms[{s!r}] must be a number, got {v!r}"
                )
            if v < 0:
                raise SchemaError(
                    f"{w}: stages_ms[{s!r}] {v} is negative — exclusive "
                    "stage times are interval measures, >= 0"
                )
            tot += v
        # disjointness: exclusive times sum to EXACTLY the wall-clock
        # (idle is the remainder); a sum past the wall means two stages
        # were charged the same microseconds
        if tot > wall + max(1e-6, 1e-6 * wall):
            raise SchemaError(
                f"{w}: exclusive stage times sum to {tot} ms, past the "
                f"round's wall_ms {wall} — stages overlap (schema v11 "
                "requires a disjoint decomposition)"
            )
    anomalies = _req(rec, "anomalies", list, where)
    for j, a in enumerate(anomalies):
        w = f"{where}:anomalies[{j}]"
        if not isinstance(a, dict):
            raise SchemaError(f"{w}: expected an object")
        for f_ in ("kind", "metric", "detail"):
            if not isinstance(a.get(f_), str) or not a[f_]:
                raise SchemaError(
                    f"{w}: anomaly needs a non-empty string {f_!r}"
                )
    return rec


def validate_run_dir(run_dir) -> dict:
    """Validate every telemetry artifact found under one run dir; returns
    {artifact_path: summary}. Missing artifact kinds are fine (a level-0
    run has only metrics.jsonl)."""
    run_dir = Path(run_dir)
    out = {}
    metrics = run_dir / "metrics.jsonl"
    if metrics.exists():
        out[str(metrics)] = f"{validate_metrics_jsonl(metrics)} scalar(s)"
    ledger = run_dir / "comm_ledger.json"
    if ledger.exists():
        rec = validate_comm_ledger(ledger)
        out[str(ledger)] = (f"{rec['rounds']} round(s), "
                            f"{rec['cum_bytes']} cum bytes")
    for flight in sorted(run_dir.glob("flight_*.json")):
        rec = validate_flight(flight)
        out[str(flight)] = (f"{len(rec['records'])} record(s), "
                            f"reason: {rec['reason'][:60]}")
    perf = run_dir / "perf_report.json"
    if perf.exists():
        rec = validate_perf_report(perf)
        coll = rec.get("collectives", {})
        out[str(perf)] = (
            f"{rec['engine']}/{rec['mode']}, "
            f"{coll.get('total_bytes', 0)} collective B"
        )
    for spans in sorted(run_dir.glob("spans_*.json")):
        rec = validate_spans(spans)
        out[str(spans)] = f"{len(rec['traceEvents'])} span event(s)"
    report = run_dir / "run_report.json"
    if report.exists():
        rec = validate_run_report(report)
        out[str(report)] = (f"{rec['rounds_analyzed']} round(s), "
                            f"critical: {rec['critical_stage']}")
    if not out:
        raise SchemaError(f"{run_dir}: no telemetry artifacts found")
    return out


def main(argv) -> int:
    # the last stdout line is ALWAYS a machine-readable JSON summary —
    # {"kind": "telemetry_schema", "run_dirs": N, "artifacts": M,
    #  "failures": [...]} — on every exit path including usage errors,
    # the consumer contract scripts/check_bench_regression.py
    # established for gate scripts (pinned by tests/test_telemetry_schema)
    def summary_line(**kw):
        print(json.dumps({"kind": "telemetry_schema", **kw}))

    if not argv:
        print(__doc__)
        summary_line(run_dirs=0, artifacts=0, failures=[],
                     error="usage: pass one or more run dirs")
        return 2
    rc = 0
    n_artifacts = 0
    failures = []
    for run_dir in argv:
        try:
            for path, summary in validate_run_dir(run_dir).items():
                print(f"OK   {path}: {summary}")
                n_artifacts += 1
        # ValueError covers SchemaError and a truncated/corrupt
        # artifact's raw JSONDecodeError (both subclass it); OSError an
        # unreadable path — each must fail THIS run dir and still end
        # stdout with the summary line, not escape as a traceback (the
        # corrupted-artifact case is what a gate script exists to catch)
        except (OSError, ValueError) as e:
            print(f"FAIL {e}")
            failures.append(str(e))
            rc = 1
    summary_line(run_dirs=len(argv), artifacts=n_artifacts,
                 failures=failures)
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
