"""Gradient-concentration probe — the go/no-go gate for FetchSGD evidence.

r2 VERDICT item 1: before any 24-epoch accuracy run, verify that single-shot
sketch recall@k on REAL ResNet-9 round gradients reaches ~0.7+ on the
candidate dataset (the flat stand-in measures ~0.38, which is why sketch
rho=0.9 stalled there — FetchSGD's heavy-hitter extraction has nothing to
extract on a flat spectrum).

For each probe point (init + after each warmup epoch of real uncompressed
federated training) this reports, on the aggregated round gradient g:

  mass@k      ||top-k(g)||^2 / ||g||^2       (gradient concentration itself)
  recall@k    |topk(unsketch est) ∩ topk(g)| / k   (what the sketch recovers)
  wrecall@k   sum of |g| over recovered set / sum over true top-k
              (mass-weighted — the quantity error feedback actually cares
              about; misses on tied tiny coordinates barely matter)

    python scripts/grad_probe.py --variant concentrated [--epochs 3]
    python scripts/grad_probe.py --variant flat          # baseline ~0.38
"""

from __future__ import annotations

import argparse
import sys
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--variant", default="concentrated")
    ap.add_argument("--epochs", type=int, default=3, help="warmup epochs")
    ap.add_argument("--k_div", type=int, default=130, help="k = D // k_div")
    ap.add_argument("--c_div", type=int, default=13, help="c = D // c_div")
    ap.add_argument("--num_rows", type=int, default=5)
    ap.add_argument("--lr_scale", type=float, default=0.4)
    ap.add_argument("--probes_per_epoch", type=int, default=1)
    args = ap.parse_args()

    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    from commefficient_tpu.data import FedSampler, augment_batch
    from commefficient_tpu.data.cifar import (
        CIFAR10_MEAN, CIFAR10_STD, _synthetic_by_variant, device_normalizer,
    )
    from commefficient_tpu.data.fed_dataset import FedDataset
    from commefficient_tpu.models import ResNet9, classification_loss
    from commefficient_tpu.ops.countsketch import (
        CountSketch, estimate_all, sketch_vec,
    )
    from commefficient_tpu.parallel import FederatedSession
    from commefficient_tpu.utils.config import Config
    from commefficient_tpu.utils.schedule import piecewise_linear_lr

    model = ResNet9(num_classes=10)
    params = model.init(jax.random.key(42), jnp.zeros((1, 32, 32, 3)))
    loss_fn = classification_loss(
        model.apply, prep=device_normalizer(CIFAR10_MEAN, CIFAR10_STD)
    )
    vec, unravel = ravel_pytree(params)
    D = int(vec.size)
    K, C = D // args.k_div, D // args.c_div
    spec = CountSketch(d=D, c=C, r=args.num_rows, seed=42)
    print(f"variant={args.variant} D={D} k={K} c={C} "
          f"(c_actual={spec.c_actual})", flush=True)

    tr_raw, te_raw = _synthetic_by_variant(10, args.variant)
    train = FedDataset(dict(tr_raw), 16, seed=42)

    cfg = Config(
        mode="uncompressed", fuse_clients=True, num_clients=16, num_workers=8,
        num_devices=1, local_batch_size=64, weight_decay=5e-4, seed=42,
        num_epochs=max(args.epochs, 1), lr_scale=args.lr_scale,
        pivot_epoch=max(1, args.epochs // 2),
    )
    session = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(train, num_workers=8, local_batch_size=64, seed=42,
                         augment=augment_batch)
    session.maybe_attach_data(train, sampler, augment_batch)

    @jax.jit
    def probe(params_vec, batch):
        """One aggregated round gradient -> (mass@k, recall@k, wrecall@k)."""
        p = unravel(params_vec)
        g, _ = ravel_pytree(jax.grad(lambda q: loss_fn(q, batch)[0])(p))
        g = g.astype(jnp.float32) + cfg.weight_decay * params_vec
        ag = jnp.abs(g)
        topv, topi = jax.lax.top_k(ag, K)
        mass = jnp.sum(topv**2) / jnp.maximum(jnp.sum(ag**2), 1e-30)
        est = estimate_all(spec, sketch_vec(spec, g))
        _, hh = jax.lax.top_k(jnp.abs(est), K)
        sel = jnp.zeros((D,), jnp.bool_).at[hh].set(True)
        hit = sel[topi]
        recall = jnp.mean(hit.astype(jnp.float32))
        wrecall = jnp.sum(topv * hit) / jnp.maximum(jnp.sum(topv), 1e-30)
        return mass, recall, wrecall

    def probe_now(tag, epoch):
        # a big "round" batch: 512 raw (UNaugmented) samples — crop/flip/
        # cutout shifts early-conv gradient structure slightly, so these
        # recall numbers are the clean-image statistic, not exactly the
        # training-round statistic
        rng = np.random.default_rng(123 + epoch)
        idx = rng.choice(len(tr_raw["y"]), size=512, replace=False)
        batch = {"x": tr_raw["x"][idx], "y": tr_raw["y"][idx]}
        m, r, w = probe(session.state.params_vec, batch)
        print(f"  [{tag}] mass@k={float(m):.4f} recall@k={float(r):.4f} "
              f"wrecall@k={float(w):.4f}", flush=True)
        return float(r)

    probe_now("init", 0)
    steps = sampler.steps_per_epoch()
    lr_fn = partial(piecewise_linear_lr, steps_per_epoch=steps,
                    pivot_epoch=cfg.pivot_epoch, num_epochs=cfg.num_epochs,
                    lr_scale=cfg.lr_scale)
    step = 0
    for ep in range(args.epochs):
        for ids, idx, plan in sampler.epoch_indices(ep):
            session.train_round_indices(ids, idx, plan, float(lr_fn(step)))
            step += 1
        probe_now(f"epoch {ep + 1}", ep + 1)


if __name__ == "__main__":
    main()
