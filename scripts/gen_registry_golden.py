"""Regenerate the registry-port parity goldens (tests/golden/).

The compress/ registry refactor (PR 2) moved every mode's round algebra out
of parallel/round.py into per-mode compressor classes. The contract is that
the refactor is a MECHANICAL extraction: the traced XLA program — and
therefore every round output — is unchanged. This script pins that contract
by recording, for each legacy mode, the final params vector and per-round
losses of a short multi-round run on the standard 8-device virtual CPU mesh
(the same harness tier-1 uses). tests/test_compress_parity.py replays the
identical configs and compares against the recording.

The committed tests/golden/registry_parity.npz was generated at the LAST
pre-refactor commit (PR 1, 644a056), so it encodes the legacy dispatch's
behavior, not the registry's. Regenerate ONLY when a deliberate,
documented semantic change to a mode's algebra lands (record why in the
commit), with:

    JAX_PLATFORMS=cpu python scripts/gen_registry_golden.py
"""

from __future__ import annotations

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from commefficient_tpu.utils.platform import force_virtual_cpu_devices

force_virtual_cpu_devices(8)

OUT = Path(__file__).resolve().parent.parent / "tests" / "golden"


# One representative config per legacy mode, exercising the mode's full
# state machinery (momentum + error feedback where the mode supports it).
# Kept deliberately small so the parity test stays in the fast tier.
GOLDEN_CONFIGS = {
    "uncompressed": dict(mode="uncompressed", virtual_momentum=0.9),
    "sketch": dict(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                   k=40, num_rows=3, num_cols=256),
    "sketch_threshold": dict(mode="sketch", error_type="virtual",
                             virtual_momentum=0.9, k=40, num_rows=3,
                             num_cols=256, topk_method="threshold"),
    "true_topk": dict(mode="true_topk", error_type="virtual",
                      virtual_momentum=0.9, k=40),
    "local_topk": dict(mode="local_topk", error_type="local", k=30,
                       local_momentum=0.9),
    "fedavg": dict(mode="fedavg", num_local_iters=2, local_lr=0.1,
                   local_batch_size=8),
    "uncompressed_fused": dict(mode="uncompressed", virtual_momentum=0.9,
                               fuse_clients=True),
    "uncompressed_topk_down": dict(mode="uncompressed", do_topk_down=True,
                                   k=25),
}

N_ROUNDS = 4
LR = 0.2


def run_one(extra: dict):
    # imported late so force_virtual_cpu_devices runs first
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tests"))
    from test_round import BASE, _run

    from commefficient_tpu.utils.config import Config

    cfg = Config(**{**BASE, **extra})
    sess, losses = _run(cfg, n_rounds=N_ROUNDS, lr=LR)
    return np.asarray(sess.state.params_vec), np.asarray(losses, np.float64)


def main():
    os.makedirs(OUT, exist_ok=True)
    blobs = {}
    for name, extra in GOLDEN_CONFIGS.items():
        vec, losses = run_one(extra)
        blobs[f"{name}__params"] = vec
        blobs[f"{name}__losses"] = losses
        print(f"{name:24s} |params|={np.abs(vec).sum():.6f} "
              f"losses={losses.round(4).tolist()}")
    path = OUT / "registry_parity.npz"
    np.savez_compressed(path, **blobs)
    print(f"wrote {path} ({path.stat().st_size} bytes)")


if __name__ == "__main__":
    main()
