"""Classic-CountSketch control for the d/c~100 divergence (r3).

The r2 postmortem's decisive experiment, re-run in the GPT-2 sketch regime:
train the quarter/eighth-scale federated ResNet-9 with an EXACT textbook
CountSketch (per-row scatter-add over a global bucket pool, 4-universal-free
fmix32 hashing — the reference csvec's structure) under IDENTICAL FetchSGD
server algebra (virtual momentum rho, virtual error, top-k extract +
sketch-subtract). If THIS diverges at d/c~100 too, the banded layout is
exonerated and the instability is a property of the regime (100 coords per
bucket) on this workload — the fix is then defaults/documentation, not
layout work.

Runs on CPU (scatter is fine there) so it can proceed while the TPU is
busy:  JAX_PLATFORMS=cpu python scripts/classic_control.py --width 16
"""

from __future__ import annotations

import argparse
import sys
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--width", type=int, default=16)
    ap.add_argument("--c_div", type=int, default=100)
    ap.add_argument("--k_div", type=int, default=1000)
    ap.add_argument("--num_rows", type=int, default=5)
    ap.add_argument("--lr_scale", type=float, default=0.04)
    ap.add_argument("--rho", type=float, default=0.9)
    ap.add_argument("--num_epochs", type=int, default=12)
    ap.add_argument("--pivot_epoch", type=int, default=3)
    ap.add_argument("--variant", default="concentrated")
    args = ap.parse_args()

    import jax
    from jax._src import xla_bridge as xb

    xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    from commefficient_tpu.data import FedSampler, augment_batch
    from commefficient_tpu.data.cifar import (
        CIFAR10_MEAN, CIFAR10_STD, _synthetic_by_variant, device_normalizer,
    )
    from commefficient_tpu.data.fed_dataset import FedDataset
    from commefficient_tpu.models import ResNet9, classification_loss
    from commefficient_tpu.utils.schedule import piecewise_linear_lr

    model = ResNet9(num_classes=10, width=args.width)
    params = model.init(jax.random.key(42), jnp.zeros((1, 32, 32, 3)))
    loss_fn = classification_loss(
        model.apply, prep=device_normalizer(CIFAR10_MEAN, CIFAR10_STD)
    )
    vec, unravel = ravel_pytree(params)
    D = vec.size
    C, K, R = D // args.c_div, D // args.k_div, args.num_rows
    print(f"CLASSIC control: D={D} c={C} k={K} r={R} lr={args.lr_scale} "
          f"rho={args.rho}", flush=True)

    # textbook CountSketch: per-row global-pool bucket + sign hashes
    # (fmix32 — the hash family is already exonerated by the poly4 A/B)
    M1, M2 = np.uint32(0x85EBCA6B), np.uint32(0xC2B2AE35)

    def mix(x, key):
        x = (x ^ key).astype(np.uint32)
        with np.errstate(over="ignore"):
            x ^= x >> np.uint32(16); x *= M1
            x ^= x >> np.uint32(13); x *= M2
            x ^= x >> np.uint32(16)
        return x

    idx = np.arange(D, dtype=np.uint32)
    cols = np.stack([mix(idx, np.uint32(0xA5A5 + 7919 * r)) % np.uint32(C)
                     for r in range(R)])          # [R, D] int
    signs = np.stack([
        1.0 - 2.0 * (mix(idx, np.uint32(0x5A5A + 104729 * r)) & 1)
        for r in range(R)
    ]).astype(np.float32)                          # [R, D]
    cols_j = jnp.asarray(cols.astype(np.int32))
    signs_j = jnp.asarray(signs)

    def sk(v):  # [D] -> [R, C]
        return jnp.stack([
            jnp.zeros((C,), jnp.float32).at[cols_j[r]].add(v * signs_j[r])
            for r in range(R)
        ])

    def est(table):  # [R, C] -> [D] median estimate
        return jnp.median(
            jnp.stack([table[r, cols_j[r]] * signs_j[r] for r in range(R)]),
            axis=0,
        )

    tr_raw, te_raw = _synthetic_by_variant(10, args.variant)
    train = FedDataset(dict(tr_raw), 16, seed=42)
    sampler = FedSampler(train, num_workers=8, local_batch_size=64, seed=42,
                         augment=augment_batch)
    steps = sampler.steps_per_epoch()
    lr_fn = partial(piecewise_linear_lr, steps_per_epoch=steps,
                    pivot_epoch=args.pivot_epoch, num_epochs=args.num_epochs,
                    lr_scale=args.lr_scale)

    @jax.jit
    def round_step(w, mom, err, batch, lr):
        def per_worker_grad(b):
            (l, _), g = jax.value_and_grad(loss_fn, has_aux=True)(unravel(w), b)
            gv, _ = ravel_pytree(g)
            return gv + 5e-4 * w, l

        gs, ls = jax.vmap(per_worker_grad)(batch)
        agg = sk(jnp.mean(gs, axis=0))
        mom = args.rho * mom + agg
        err = err + lr * mom
        e_hat = est(err)
        thr = jnp.sort(jnp.abs(e_hat))[-K]
        upd = jnp.where(jnp.abs(e_hat) >= thr, e_hat, 0.0)
        err = err - sk(upd)
        return w - upd, mom, err, jnp.mean(ls)

    w = vec.astype(jnp.float32)
    mom = jnp.zeros((R, C), jnp.float32)
    err = jnp.zeros((R, C), jnp.float32)
    step = 0
    for ep in range(args.num_epochs):
        for _, batch in sampler.epoch(ep):
            b = {k: jnp.asarray(v) for k, v in batch.items()}
            w, mom, err, loss = round_step(w, mom, err, b, jnp.float32(lr_fn(step)))
            step += 1
        print(f"  ep{ep + 1}: train_loss={float(loss):.4f} "
              f"|err|max={float(jnp.abs(err).max()):.3e}", flush=True)


if __name__ == "__main__":
    main()
