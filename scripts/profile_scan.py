"""Scan-based stage profiler: true device time per stage, one fence total.

Per-call timing through the axon tunnel has a ~25 ms dispatch floor that
swamps every stage (scripts/profile_round.py r2 findings), so here each
stage runs inside a lax.scan with a scalar carry-dependency (preventing
loop-invariant hoisting) and the whole loop is fenced once:

    t_stage ~= (t_total - t_empty_scan) / n
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.flatten_util  # noqa: F401 — binds jax.flatten_util for the stages
import jax.numpy as jnp
import numpy as np


def scan_time(name, stage, n=20):
    """stage: (pert_scalar) -> scalar; scanned n times, chained via carry."""

    @jax.jit
    def run():
        def body(s, _):
            out = stage(s * 1e-30)
            # cast keeps the carry float32 even for bf16 stages (scan
            # requires identical carry input/output types)
            return out.astype(jnp.float32) * 1e-30, ()

        s, _ = jax.lax.scan(body, jnp.float32(0), None, length=n)
        return s

    float(run())  # compile + warm
    t0 = time.perf_counter()
    float(run())
    dt = (time.perf_counter() - t0) / n * 1e3
    print(f"{name:46s} {dt:8.2f} ms")
    return dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("-n", type=int, default=20)
    args = ap.parse_args()

    from commefficient_tpu.models import ResNet9, classification_loss
    from commefficient_tpu.ops import ravel_params
    from commefficient_tpu.ops.countsketch import (
        CountSketch, estimate_all, sketch_vec,
    )
    from commefficient_tpu.ops.topk import topk_threshold_dense

    print(f"devices: {jax.devices()}")
    workers, batch, k = 8, 64, 50_000
    model = ResNet9(num_classes=10)
    params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    loss_fn = classification_loss(model.apply)
    vec, unravel = ravel_params(params)
    d = int(vec.size)
    spec = CountSketch(
        d=d, c=500_000, r=5, seed=42,
        dtype=jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32,
    )
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(workers, batch, 32, 32, 3)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 10, size=(workers, batch)).astype(np.int32))
    v = jnp.asarray(rng.normal(size=(d,)).astype(np.float32))
    table = jax.jit(lambda v: sketch_vec(spec, v))(v)
    est = jax.jit(lambda t: estimate_all(spec, t))(table)
    n = args.n

    def grad_worker(s):
        def per_w(xx, yy):
            g = jax.grad(lambda p, b: loss_fn(p, b)[0])(
                unravel(vec + s), {"x": xx, "y": yy}
            )
            return jax.flatten_util.ravel_pytree(g)[0]

        return jnp.sum(jax.vmap(per_w)(x, y))

    def grad_mono(s):
        g = jax.grad(lambda p, b: loss_fn(p, b)[0])(
            unravel(vec + s),
            {"x": x.reshape(-1, 32, 32, 3), "y": y.reshape(-1)},
        )
        return jnp.sum(jax.flatten_util.ravel_pytree(g)[0])

    scan_time("empty scan (overhead floor)", lambda s: s, n)
    scan_time("fwd+bwd 8x64 (vmap per-worker)", grad_worker, n)
    scan_time("fwd+bwd batch 512 (monolithic)", grad_mono, n)
    scan_time("sketch_vec", lambda s: jnp.sum(sketch_vec(spec, v + s)), n)
    scan_time("estimate_all", lambda s: jnp.sum(estimate_all(spec, table + s)), n)
    scan_time("median only",
              lambda s: jnp.sum(jnp.median(jnp.stack([est + s, est, est, est, est]), axis=0)), n)
    scan_time("topk_threshold_dense",
              lambda s: jnp.sum(topk_threshold_dense(est + s, k)), n)
    scan_time("lax.top_k",
              lambda s: jnp.sum(jax.lax.top_k(jnp.abs(est + s), k)[0]), n)
    from commefficient_tpu.ops.countsketch import _scramble, _to_layout
    # _to_layout operates in scrambled space ([d_eff]) — feeding the raw
    # [d] vector crashes whenever d % scramble_block != 0
    scan_time("scramble + riffle layout (row 2)",
              lambda s: jnp.sum(_to_layout(spec, _scramble(spec, v + s), 2)), n)
    scan_time("signs (mix32 iota)",
              lambda s: jnp.sum(spec._row_signs(1) * (v + s)), n)

    # full rounds
    from commefficient_tpu.parallel import FederatedSession, make_mesh
    from commefficient_tpu.utils.config import Config

    for mode, extra in [
        ("uncompressed", {}),
        ("sketch", dict(error_type="virtual", virtual_momentum=0.9,
                        topk_method="threshold")),
    ]:
        cfg = Config(mode=mode, k=k, num_rows=5, num_cols=500_000,
                     num_clients=2 * workers, num_workers=workers,
                     num_devices=1, local_batch_size=batch,
                     weight_decay=5e-4, **extra)
        session = FederatedSession(cfg, params, loss_fn, mesh=make_mesh(1))
        ids = jnp.arange(workers, dtype=jnp.int32)
        data = {"x": x, "y": y}
        round_fn = session.round_fn

        @jax.jit
        def run(state):
            def body(s, _):
                s2, m = round_fn(s, ids, data, jnp.float32(0.1))
                return s2, m["loss"]

            return jax.lax.scan(body, state, None, length=n)

        st, losses = run(session.state)
        float(losses[-1])
        t0 = time.perf_counter()
        st, losses = run(st)
        float(losses[-1])
        dt = (time.perf_counter() - t0) / n * 1e3
        print(f"{'full round: ' + mode:46s} {dt:8.2f} ms "
              f"({workers * batch / dt * 1e3:,.0f} samples/s)")


if __name__ == "__main__":
    main()
