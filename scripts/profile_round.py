"""Stage-level timing of the bench round on the real chip (VERDICT r1 item 2).

Times each stage of the federated sketch round separately with scalar-fetch
fences (block_until_ready is unreliable through the axon tunnel), so the
perf work attacks measured hot spots instead of guesses. The sketch /
estimate / unsketch phases are timed for BOTH CountSketch backends
(einsum and pallas — ops/pallas/) so the r5 sketch-round gap is tracked
at phase granularity, and the server-DECODE phases (PR 6) are split
dense vs sharded-slice vs Pallas-fused. ``--d`` runs the phase split at
an arbitrary dimension — e.g. GPT-2 scale:

    python scripts/profile_round.py --d 124000000 --shards 8

times the decode phases at D=124M (c defaults to D/25, the stability
envelope floor) without needing a CV model of that size. Run WITHOUT the
test conftest so it dials the real TPU:

    python scripts/profile_round.py [--dtype bfloat16] [--reps 10] \
        [--sketch_backend pallas] [--d N] [--num_cols C] [--shards W]
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.flatten_util  # noqa: F401 — binds jax.flatten_util for the stages
import jax.numpy as jnp
import numpy as np

# shared micro-bench helpers (moved to utils.profiling so bench.py and the
# telemetry span recorder use the same fencing/warmup discipline; timeit
# now warms MIN_WARMUP_STEPS=2 calls — one warm call left the second
# donated-buffer layout uncompiled, so the first timed rep paid a compile
# on donated paths)
from commefficient_tpu.utils.profiling import fence, timeit  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument(
        "--sketch_backend", default="einsum", choices=("einsum", "pallas"),
        help="backend for the full-round ground-truth section; the "
        "per-phase sketch/unsketch breakdown always times BOTH backends",
    )
    ap.add_argument(
        "--mode", default="sketch", choices=("sketch", "powersgd"),
        help="compressor for the full-round ground-truth section (the "
        "sketch phase breakdown always runs; powersgd adds its own "
        "matricize/GS/reconstruct phase lines)",
    )
    ap.add_argument("--powersgd_rank", type=int, default=4)
    ap.add_argument(
        "--telemetry_level", type=int, default=0, choices=(0, 1, 2),
        help="telemetry level for the full-round ground-truth section: "
        "0 is the bit-identical pre-telemetry round (the default, so the "
        "headline number IS the no-overhead acceptance measurement); 1/2 "
        "time the in-graph diagnostics tax (level 2 adds the sketch "
        "round-trip fidelity / powersgd reconstruction residual)",
    )
    ap.add_argument(
        "--profile_rounds", default="",
        help="'A-B' inclusive round window arming a programmatic "
        "jax.profiler capture over the traced ground-truth rounds (the "
        "same telemetry.trace.ProfilerWindow --profile_rounds wires into "
        "the train loop: clamped past warmup, fenced at entry/exit, "
        "degrades with a named reason where the backend cannot trace); "
        "the trace lands in ./profile_round_trace",
    )
    ap.add_argument(
        "--d", type=int, default=0,
        help="override the sketch dimension for the phase split (0 = the "
        "ResNet-9 D). Set 124_000_000 to run the decode phases at GPT-2 "
        "scale — the model/ground-truth sections are skipped then (no "
        "CV model exists at that D; the decode numbers are the point)",
    )
    ap.add_argument(
        "--num_cols", type=int, default=0,
        help="sketch columns for the phase split (0 = 500k at CV scale, "
        "d//25 under --d — the stability envelope's c >= D/25 floor)",
    )
    ap.add_argument(
        "--shards", type=int, default=8,
        help="worker-mesh width W the sharded-decode phase lines model: "
        "each line times ONE shard's d/W slice work (the per-chip cost "
        "of the sharded decode; its collectives are scalar-only + one "
        "~W*k gather, negligible next to the slice work)",
    )
    args = ap.parse_args()

    from commefficient_tpu.models import ResNet9, classification_loss
    from commefficient_tpu.ops import ravel_params
    from commefficient_tpu.ops.countsketch import (
        CountSketch, estimate_all, estimate_at, sketch_sparse, sketch_vec,
        unsketch_sparse,
    )
    from commefficient_tpu.ops.topk import compact_nonzero

    print(f"devices: {jax.devices()}")
    workers, batch = 8, 256  # the bench r2 shape (2048 samples/round)
    if args.d:
        # decode-phase-only run at an arbitrary D (the GPT-2-scale split
        # VERDICT r5 asked for): no CV model exists at this dimension, so
        # the model fwd/bwd + powersgd + ground-truth sections are skipped
        model = params = loss_fn = vec = unravel = None
        d = args.d
    else:
        model = ResNet9(num_classes=10)
        params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
        loss_fn = classification_loss(model.apply)
        vec, unravel = ravel_params(params)
        d = int(vec.size)
    num_cols = args.num_cols or (max(500_000, d // 25) if args.d else 500_000)
    print(f"D = {d}")
    spec = CountSketch(
        d=d, c=num_cols, r=5, seed=42,
        dtype=jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32,
    )
    print(f"table: {spec.table_shape} (c_actual={spec.c_actual}, s={spec.s}, nc={spec.nc})")

    rng = np.random.default_rng(0)
    if not args.d:
        x = jnp.asarray(rng.normal(size=(workers * batch, 32, 32, 3)).astype(np.float32))
        y = jnp.asarray(rng.integers(0, 10, size=(workers * batch,)).astype(np.int32))
    v = jnp.asarray(rng.normal(size=(d,)).astype(np.float32))
    k = 50_000
    idx = jnp.asarray(rng.choice(d, size=k, replace=False).astype(np.int32))
    vals = jnp.asarray(rng.normal(size=(k,)).astype(np.float32))

    if not args.d:

        @jax.jit
        def fwd_bwd(pv, x, y):
            p = unravel(pv)
            (loss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(p, {"x": x, "y": y})
            g, _ = jax.flatten_util.ravel_pytree(grads)
            return g

        @jax.jit
        def per_worker_fwd_bwd(pv, x, y):
            # the actual bench shape: vmap over `workers` grads of `batch` each
            xs = x.reshape(workers, batch, 32, 32, 3)
            ys = y.reshape(workers, batch)
            gs = jax.vmap(lambda xx, yy: fwd_bwd(pv, xx, yy))(xs, ys)
            return jnp.sum(gs, 0)

    from commefficient_tpu.ops.countsketch import unsketch_dense
    from commefficient_tpu.ops.topk import topk_threshold_dense

    topk_j = jax.jit(lambda e: jax.lax.top_k(jnp.abs(e), k)[1])
    approx_j = jax.jit(lambda e: jax.lax.approx_max_k(jnp.abs(e), k)[1])
    thr_j = jax.jit(lambda e: topk_threshold_dense(e, k))
    ssp_j = jax.jit(lambda i, va: sketch_sparse(spec, i, va))
    scatter_j = jax.jit(lambda i, va: jnp.zeros(d, jnp.float32).at[i].set(va))

    r = args.reps
    t_modelw = 0.0
    if not args.d:
        timeit(f"fwd+bwd batch {workers*batch} (monolithic)", fwd_bwd, vec, x, y, reps=r)
        t_modelw = timeit(f"fwd+bwd {workers}x{batch} (vmap per-worker)", per_worker_fwd_bwd, vec, x, y, reps=r)

    # -- sketch/unsketch phase split, BOTH backends ------------------------
    # (the r5 VERDICT gap is a kernel property: the einsum path pays the
    # [m, V] one-hot constant + [nc, V] HBM round-trip + [d_eff] signs,
    # the Pallas path generates all three on the fly in-kernel). Off-TPU
    # the pallas legs run under interpret mode — minutes per call at this
    # d, meaningless as perf data — so they auto-skip there (same policy
    # as bench.py's GPT-2 legs; --sketch_backend pallas forces them).
    backends = ("einsum", "pallas")
    if jax.devices()[0].platform != "tpu" and args.sketch_backend != "pallas":
        print("[pallas] phase legs skipped on non-TPU host "
              "(pass --sketch_backend pallas to force interpret-mode timing)")
        backends = ("einsum",)
    phase = {}
    for backend in backends:
        sp = spec._replace(backend=backend)
        sketch_j = jax.jit(lambda v, sp=sp: sketch_vec(sp, v))
        est_j = jax.jit(lambda t, sp=sp: estimate_all(sp, t))
        unsk_j = jax.jit(lambda t, sp=sp: unsketch_sparse(sp, t, k))
        unskd_j = jax.jit(lambda t, sp=sp: unsketch_dense(sp, t, k))
        table = sketch_j(v)
        est = est_j(table)
        t_sk = timeit(f"[{backend}] sketch_vec (dense d)", sketch_j, v, reps=r)
        t_est = timeit(f"[{backend}] estimate_all", est_j, table, reps=r)
        timeit(f"[{backend}] unsketch_sparse (est+top_k)", unsk_j, table, reps=r)
        t_unskd = timeit(f"[{backend}] unsketch_dense (est+threshold)",
                         unskd_j, table, reps=r)
        phase[backend] = (t_sk, t_est, t_unskd)
        if backend == "einsum":
            # selection-kernel lines are backend-independent (they consume
            # the estimate vector) — time them once
            timeit("lax.top_k k=50k over d", topk_j, est, reps=r)
            timeit("approx_max_k k=50k over d", approx_j, est, reps=r)
            timeit("topk_threshold_dense k=50k", thr_j, est, reps=r)
            timeit("sketch_sparse k=50k (scatter)", ssp_j, idx, vals, reps=r)
            timeit("dense scatter of k", scatter_j, idx, vals, reps=r)

    # -- server-decode phase lines (PR 6: dense vs sharded vs fused) -------
    # The dense decode line is the per-chip cost EVERY chip of a
    # replicated mesh pays redundantly (est_all + threshold + the error
    # feedback's re-sketch); the sharded line is ONE shard's d/W slice of
    # the same extraction (estimate_at over offset global hashes +
    # threshold passes + candidate compaction + the slice sketch_sparse)
    # — its cross-chip traffic is scalar bisection collectives + one ~W*k
    # gather, negligible next to the slice work, so the single-device
    # stand-in here times the real per-chip decode cost. The fused line
    # swaps the slice estimate for the Pallas estimate_at kernel
    # (ops/pallas/decode_kernels.py).
    W = args.shards
    S = -(-d // W)
    sidx = jnp.minimum(jnp.arange(S, dtype=jnp.int32), d - 1)
    table = jax.jit(lambda vv: sketch_vec(spec, vv))(v)

    dense_dec_j = jax.jit(
        lambda t: sketch_vec(spec, unsketch_dense(spec, t, k))
    )

    def shard_decode(t):
        est = estimate_at(spec, t, sidx)
        sel = topk_threshold_dense(est, k)
        loc, val = compact_nonzero(sel, k)
        return sketch_sparse(spec, jnp.minimum(loc, d - 1), val)

    timeit("[decode dense] est_all+threshold+resketch (per chip)",
           dense_dec_j, table, reps=r)
    timeit(f"[decode sharded W={W}] per-shard slice "
           "(est_at+thr+compact+slice-sketch)",
           jax.jit(shard_decode), table, reps=r)
    if jax.devices()[0].platform == "tpu" or args.sketch_backend == "pallas":
        from commefficient_tpu.ops.pallas import estimate_at_pallas
        from commefficient_tpu.ops.pallas.decode_kernels import (
            VMEM_TABLE_BYTES,
        )

        sp_p = spec._replace(backend="pallas")
        if spec.r * spec.c_actual * 4 > VMEM_TABLE_BYTES:
            print("[decode fused] table exceeds the kernel's VMEM guard "
                  f"({spec.r * spec.c_actual * 4 / 2**20:.0f} MiB) — "
                  "estimate_at_pallas falls back to the gather path at "
                  "this geometry")
        timeit(f"[decode fused W={W}] estimate_at_pallas slice",
               jax.jit(lambda t: estimate_at_pallas(sp_p, t, sidx)),
               table, reps=r)
    else:
        print("[decode fused] pallas slice skipped on non-TPU host "
              "(pass --sketch_backend pallas to force interpret mode)")

    # -- aggregation phase lines (sparse-allreduce PR) ---------------------
    # single-device stand-ins, same convention as the decode lines above:
    # the dense line is the W-way [D] reduction every chip's all-reduce
    # realizes; the sparse line is the pair-exchange realization — compact
    # each chip's <= k-sparse transmit, then scatter-add all W*k gathered
    # (idx, val) pairs into the dense aggregate. Cross-chip it moves
    # O(W*k) elements instead of O(D); on one chip the lines compare the
    # two realizations' arithmetic.
    from commefficient_tpu.ops.collectives import scatter_add_pairs
    from commefficient_tpu.ops.topk import (
        compact_nonzero as _compact,
        topk_threshold_dense as _thr_dense,
    )

    sparse_bufs = jax.jit(jax.vmap(lambda key: _thr_dense(
        jax.random.normal(key, (d,)), k)))(
            jax.random.split(jax.random.key(0), W))

    def dense_agg(bufs):
        return jnp.sum(bufs, axis=0)

    def sparse_agg(bufs):
        loc, val = jax.vmap(lambda b: _compact(b, k))(bufs)
        return scatter_add_pairs(d, loc.reshape(-1), val.reshape(-1))

    timeit(f"[aggregate dense] W-way [D] reduction (W={W})",
           jax.jit(dense_agg), sparse_bufs, reps=r)
    timeit(f"[aggregate sparse W={W}] compact + {W}x{k // 1000}k-pair "
           "scatter-add",
           jax.jit(sparse_agg), sparse_bufs, reps=r)

    # -- sketch-fused backward phase line (sketch-gap PR) ------------------
    # the fused path produces the grad DIRECTLY as a table (per-leaf
    # custom_vjp cotangent sketches — no flat [D] concat, no separate
    # sketch pass); its honest comparator is the dense path's grad +
    # sketch_vec SUM, which is what the legacy round pays per device.
    if not args.d:
        from commefficient_tpu.parallel.round import make_sketch_grad_one
        from commefficient_tpu.utils.config import Config as _Cfg

        _fb_cfg = _Cfg(mode="sketch", error_type="virtual", k=k,
                       num_rows=5, num_cols=num_cols,
                       topk_method="threshold", fuse_clients=True,
                       sketch_fused_bwd=True, weight_decay=0.0,
                       num_clients=2 * workers, num_workers=workers,
                       local_batch_size=batch)

        grad_table = jax.jit(
            make_sketch_grad_one(_fb_cfg, loss_fn, unravel, None, spec,
                                 d=d)
        )
        bflat = {"x": x, "y": y}
        dense_then_sketch = jax.jit(
            lambda pv, xx, yy: sketch_vec(spec, fwd_bwd(pv, xx, yy))
        )
        timeit(f"[sketch fused-bwd] grad->table (batch {workers*batch})",
               lambda pv, b: grad_table(pv, b, None)[0], vec, bflat,
               reps=r)
        timeit("[sketch fused-bwd] dense grad + sketch_vec (comparator)",
               dense_then_sketch, vec, x, y, reps=r)

    print()
    for backend, (t_sk, t_est, t_unskd) in phase.items():
        total = t_modelw + t_sk + t_unskd + t_sk
        print(f"[{backend}] round ≈ model {t_modelw:.1f} + sketch {t_sk:.1f} "
              f"+ unsketch_dense {t_unskd:.1f} (est {t_est:.1f} + select "
              f"{t_unskd - t_est:.1f}) + resketch {t_sk:.1f} = {total:.1f} ms"
              f" -> {workers * batch / total * 1e3:,.0f} samples/s "
              f"(bench does {workers * batch}/round)")
    if args.d:
        return  # decode-phase-only run (no CV model at this D)

    # -- powersgd phase split (PR 2: compress/powersgd.py) -----------------
    # the server-side cost the mode adds per round: matricize + P = M Q +
    # Gram-Schmidt + Q_new = M^T P_hat + rank-r reconstruct — all MXU work
    from commefficient_tpu.compress.powersgd import gram_schmidt, matrix_shape

    n_rows_m, m_cols_m = matrix_shape(d)
    rank = args.powersgd_rank
    q0 = jnp.asarray(rng.normal(size=(m_cols_m, rank)).astype(np.float32))

    @jax.jit
    def powersgd_approx(vec, Q):
        M = jnp.pad(vec, (0, n_rows_m * m_cols_m - d)).reshape(
            n_rows_m, m_cols_m)
        P_hat = gram_schmidt(M @ Q)
        Q_new = M.T @ P_hat
        return (P_hat @ Q_new.T).reshape(-1)[:d], Q_new

    gs_j = jax.jit(gram_schmidt)
    p0 = jnp.asarray(rng.normal(size=(n_rows_m, rank)).astype(np.float32))
    timeit(f"[powersgd] GS orthonormalize [n={n_rows_m}, r={rank}]",
           gs_j, p0, reps=r)
    t_psgd = timeit(
        f"[powersgd] full approx (matricize+P+GS+Q+reconstruct) r={rank}",
        powersgd_approx, v, q0, reps=r)
    total = t_modelw + t_psgd
    print(f"[powersgd] round ≈ model {t_modelw:.1f} + approx {t_psgd:.1f} "
          f"= {total:.1f} ms -> {workers * batch / total * 1e3:,.0f} "
          f"samples/s")

    # ground truth: the EXACT bench config (bench.py r2: fuse_clients,
    # batch 256, num_blocks 1) so this number reconciles against bench.py
    from commefficient_tpu.parallel import FederatedSession, make_mesh
    from commefficient_tpu.utils.config import Config

    bench_batch = batch  # == the bench r2 shape profiled above
    common = dict(error_type="virtual", virtual_momentum=0.9,
                  topk_method="threshold", fuse_clients=True,
                  num_clients=2 * workers, num_workers=workers,
                  num_devices=1, local_batch_size=bench_batch,
                  weight_decay=5e-4, telemetry_level=args.telemetry_level)
    if args.mode == "powersgd":
        cfg = Config(mode="powersgd", powersgd_rank=rank, **common)
    else:
        cfg = Config(mode="sketch", k=k, num_rows=5, num_cols=500_000,
                     num_blocks=1, sketch_backend=args.sketch_backend,
                     **common)
    session = FederatedSession(cfg, params, loss_fn, mesh=make_mesh(1))
    ids = jnp.arange(workers, dtype=jnp.int32)
    data = {"x": jnp.asarray(rng.normal(
                size=(workers, bench_batch, 32, 32, 3)).astype(np.float32)),
            "y": jnp.asarray(rng.integers(
                0, 10, size=(workers, bench_batch)).astype(np.int32))}
    # compiled-round audit (telemetry/xla_audit.py): the artifact's OWN
    # FLOPs/peak-HBM/collective numbers printed next to the measured lines
    # so the hand model and the compiler can be diffed (ISSUE 7); the
    # audit's AOT trace doubles as the round's first compile-cache fill
    try:
        audit = session.audit_compiled_round(np.asarray(ids), data, 0.1)
        print(audit.describe())
        if audit.cost.get("flops") is not None:
            from commefficient_tpu.telemetry.xla_audit import chip_peak_flops

            peak, kind, assumed = chip_peak_flops()
            floor_ms = audit.cost["flops"] / peak * 1e3
            print(f"[audited] {audit.cost['flops'] / 1e9:.2f} GFLOP/round "
                  f"-> compute-bound floor {floor_ms:.3f} ms on {kind}"
                  + (" (peak assumed)" if assumed else ""))
    except Exception as e:  # noqa: BLE001 — the audit must not kill the lab
        print(f"[audited] compiled-round audit unavailable: {e}")
    # -- asyncfed phase lines (buffered-async PR) --------------------------
    # the engine's round splits into cohort LAUNCH (one cohort's W
    # per-client grads + encode — device work paid once per cohort, then
    # amortized over ceil(W/K) server updates), ARRIVAL (the host-side
    # continuous-time schedule simulation + per-update slot bookkeeping —
    # the only work the buffered-async layer adds on the critical path),
    # and APPLY (the staleness-weighted K-row server update). These lines
    # dispatch THE compiled pair the engine itself reuses
    # (session.async_round_fns), so the split reconciles against
    # AsyncFederation's async_launch/async_apply spans.
    if args.mode == "sketch":
        try:
            from commefficient_tpu.asyncfed import AsyncSchedule

            K, C = workers // 2, 2
            acfg = cfg.replace(fuse_clients=False, async_buffer=K,
                               async_concurrency=C, staleness_exponent=0.5)
            asess = FederatedSession(acfg, params, loss_fn, mesh=make_mesh(1))
            launch_fn, apply_fn = asess.async_round_fns()
            ast = asess.state
            t0 = time.perf_counter()
            for _ in range(r):
                sch = AsyncSchedule(seed=acfg.seed, num_workers=workers,
                                    buffer_k=K, concurrency=C,
                                    arrival_rate=1.0, num_updates=50)
            dt_arr = (time.perf_counter() - t0) / r * 1e3
            print(f"[async arrival] 50-update host schedule (K={K}, C={C}): "
                  f"{dt_arr:.2f} ms ({dt_arr / 50 * 1e3:.0f} us/update)")
            launch_j = lambda: launch_fn(  # noqa: E731
                ast.params_vec, ast.client_vel, ast.client_err, ids, data,
                jnp.int32(0), jnp.float32(0.1))
            out = launch_j()
            fence(out[3])
            t0 = time.perf_counter()
            for _ in range(r):
                out = launch_j()
            fence(out[3])
            dt_l = (time.perf_counter() - t0) / r * 1e3
            print(f"[async launch] cohort W={workers} grads+encode: "
                  f"{dt_l:.2f} ms")
            weights = jnp.ones((workers,), jnp.float32)
            # donated first arg: thread the returned state back through
            ast, m = apply_fn(ast, *out, ids, weights,
                              jnp.float32(workers), jnp.float32(0.1))
            fence(m["loss"])
            t0 = time.perf_counter()
            for _ in range(r):
                ast, m = apply_fn(ast, *out, ids, weights,
                                  jnp.float32(workers), jnp.float32(0.1))
            fence(m["loss"])
            dt_a = (time.perf_counter() - t0) / r * 1e3
            print(f"[async apply] staleness-weighted {workers}-row server "
                  f"update: {dt_a:.2f} ms (launch amortized over "
                  f"~{-(-workers // K)} updates -> "
                  f"{dt_l / -(-workers // K) + dt_a:.2f} ms/update)")
            # double-buffer twin (hide-the-collectives PR): the sequential
            # engine fences each apply's loss before dispatching the next
            # update; the double-buffered engine (--async_double_buffer)
            # defers that fence one update, so update i+1 is already in
            # XLA's queue while apply i's collectives run. The twin lines
            # time the same apply chain under both fence disciplines — the
            # delta is the host stall the deferred fence removes.
            t0 = time.perf_counter()
            for _ in range(r):
                ast, m = apply_fn(ast, *out, ids, weights,
                                  jnp.float32(workers), jnp.float32(0.1))
                fence(m["loss"])  # per-update fence = sequential engine
            dt_seq = (time.perf_counter() - t0) / r * 1e3
            print(f"[async sequential] apply + per-update fence: "
                  f"{dt_seq:.2f} ms/update")
            prev = None
            t0 = time.perf_counter()
            for _ in range(r):
                ast, m = apply_fn(ast, *out, ids, weights,
                                  jnp.float32(workers), jnp.float32(0.1))
                if prev is not None:
                    fence(prev)  # drained AFTER the next apply dispatches
                prev = m["loss"]
            fence(prev)
            dt_db = (time.perf_counter() - t0) / r * 1e3
            print(f"[async double-buffer] apply + deferred fence: "
                  f"{dt_db:.2f} ms/update (overlap delta "
                  f"{dt_seq - dt_db:+.2f} ms/update)")
        except Exception as e:  # noqa: BLE001 — lab line, never kills the run
            print(f"[async] phase lines unavailable: {e}")

    round_fn = session.round_fn
    n = 10

    @jax.jit
    def run_rounds(state):
        def body(s, _):
            s2, m = round_fn(s, ids, data, jnp.float32(0.1))
            return s2, m["loss"]
        return jax.lax.scan(body, state, None, length=n)

    tag = args.mode if args.mode != "sketch" else args.sketch_backend
    if args.telemetry_level:
        tag += f"+telemetry_l{args.telemetry_level}"
    # per-round python dispatch twin FIRST (what the default train loop
    # pays), then the scanned block — the [scan xK] delta is exactly the
    # dispatch overhead the scan engine (pipeline/scan_engine.py,
    # --scan_rounds) amortizes
    state = session.state
    for _ in range(2):  # compile + warm both donated layouts
        state, m = round_fn(state, ids, data, jnp.float32(0.1))
    fence(m["loss"])
    t0 = time.perf_counter()
    for _ in range(n):
        state, m = round_fn(state, ids, data, jnp.float32(0.1))
    fence(m["loss"])
    dt_loop = (time.perf_counter() - t0) / n * 1e3
    print(f"per-round dispatch [{tag}]: {dt_loop:.2f} ms -> "
          f"{workers * bench_batch / dt_loop * 1e3:,.0f} samples/s")
    # -- critical path (round-tracing PR) ----------------------------------
    # a SEPARATE n-round loop with a PhaseSpans recorder and a per-round
    # fence, decomposed by the SAME CriticalPath analyzer the run reports
    # use (telemetry/trace.py — reused, not reimplemented). The per-round
    # fence makes each dispatch span the true device+host round latency,
    # so this loop is slower than the free-running line above by design.
    # --profile_rounds A-B arms a programmatic jax.profiler capture
    # window over exactly these rounds.
    try:
        from commefficient_tpu.telemetry.spans import PhaseSpans
        from commefficient_tpu.telemetry.trace import (
            STAGES, CriticalPath, ProfilerWindow, round_trace_id,
        )

        spans = PhaseSpans(".", start_step=2, num_steps=n)
        window = None
        if args.profile_rounds:
            window = ProfilerWindow(
                args.profile_rounds, "profile_round_trace",
                fence_fn=lambda: fence(state.params_vec))
        for i in range(n):
            step = 2 + i
            spans.step(step)
            if window is not None:
                window.step(step)
            with spans.span("round_dispatch", collective=True, step=step,
                            trace_id=round_trace_id(step)) as sp:
                state, m = round_fn(state, ids, data, jnp.float32(0.1))
                sp.fence(m["loss"])
        if window is not None:
            window.step(2 + n)
            window.close()
        cp = CriticalPath(spans.events)
        bds = [bd for bd in (cp.round_breakdown(s) for s in cp.steps())
               if bd is not None]
        wall = sum(bd["wall_ms"] for bd in bds) / len(bds)
        tot = {s: sum(bd["stages_ms"][s] for bd in bds) / len(bds)
               for s in STAGES}
        crit = max(STAGES, key=lambda s: tot[s])
        parts = " + ".join(f"{s} {tot[s]:.2f}" for s in STAGES
                           if tot[s] > 0)
        print(f"[critical path] {len(bds)} fenced round(s): {parts} "
              f"= {wall:.2f} ms/round; binding stage: {crit}")
    except Exception as e:  # noqa: BLE001 — lab line, never kills the run
        print(f"[critical path] unavailable: {e}")
    # layerwise-overlap twin (hide-the-collectives PR): the same round
    # with the aggregation psum and the top-k gathers split into
    # per-leaf-group segments (--overlap_collectives layerwise) so XLA
    # can run each segment's ring concurrently with the next segment's
    # reduction work. The delta vs the sequential line above is the
    # exposed-collective time the chunking hides (≈0 on a one-chip mesh
    # — there is no cross-chip ring to hide there).
    if args.mode == "sketch":
        try:
            ov_sess = FederatedSession(
                cfg.replace(overlap_collectives="layerwise"),
                params, loss_fn, mesh=make_mesh(1))
            ov_fn = ov_sess.round_fn
            ov_state = ov_sess.state
            for _ in range(2):  # compile + warm both donated layouts
                ov_state, m = ov_fn(ov_state, ids, data, jnp.float32(0.1))
            fence(m["loss"])
            t0 = time.perf_counter()
            for _ in range(n):
                ov_state, m = ov_fn(ov_state, ids, data, jnp.float32(0.1))
            fence(m["loss"])
            dt_ov = (time.perf_counter() - t0) / n * 1e3
            print(f"[overlap layerwise] per-round dispatch: {dt_ov:.2f} ms "
                  f"-> {workers * bench_batch / dt_ov * 1e3:,.0f} samples/s "
                  f"(overlap delta vs sequential "
                  f"{dt_loop - dt_ov:+.2f} ms/round)")
        except Exception as e:  # noqa: BLE001 — lab line, never kills the run
            print(f"[overlap layerwise] twin unavailable: {e}")
    state, losses = run_rounds(state)
    fence(losses)
    t0 = time.perf_counter()
    state, losses = run_rounds(state)
    fence(losses)
    dt = (time.perf_counter() - t0) / n * 1e3
    print(f"[scan x{n}] full round [{tag}]: {dt:.2f} ms -> "
          f"{workers * bench_batch / dt * 1e3:,.0f} samples/s "
          f"(dispatch overhead amortized: {dt_loop - dt:+.2f} ms/round)")


if __name__ == "__main__":
    main()
