"""Bench regression gate: compare the latest BENCH_*.json to the trajectory.

The repo carries one ``BENCH_rNN.json`` per build round (the driver wraps
bench.py's stdout JSON line in ``{"parsed": {...}}``), but until this
script nothing *read* the trajectory — a 20% throughput regression rode a
green test suite straight into main. This gate compares the LATEST bench
record against the median of the prior ones, metric by metric, with
per-metric noise tolerances, and exits nonzero on regression:

    python scripts/check_bench_regression.py            # repo-root BENCH_r*
    python scripts/check_bench_regression.py --dir D --glob 'BENCH_r*.json'
    python scripts/check_bench_regression.py --tolerance 0.2

Comparison rules:

  * Direction is per metric kind: throughput-ish metrics (``value``,
    ``*_tokens_per_sec``, ``mfu``/``*_mfu``, ``vs_baseline``,
    ``*_vs_uncompressed``) regress DOWN; latency-ish (``*_sec_per_round``)
    regress UP. Everything else (strings, provenance, ``*_error``/
    ``*_skipped`` markers, audited byte counts) is informational.
  * Baseline = MEDIAN of the prior records carrying that metric — robust
    to one outlier round, unlike best-ever (which ratchets noise) or
    last-only (which lets a slow drift through one step at a time).
  * Tolerance: relative, default 15% (the suite's wall-clock measurements
    are load-dependent; CHANGES.md round 3 measured ~40% spread under
    load). Per-metric overrides in ``TOLERANCES``.
  * Apples-to-apples (the bench provenance satellite): prior records whose
    ``chip`` differs from the latest record's are EXCLUDED from the
    baseline — a v4 number is not a regression baseline for a v5e run.
    Records without a ``chip`` key (pre-provenance rounds) are kept.
  * A metric new in the latest record, or with no comparable history, is
    UNGATED — but no longer silently: new metrics are counted in the exit
    summary and the JSON summary line, and ``--max_new_metrics N`` turns
    "more than N gated-direction metrics with no history" into exit 1. A
    renamed metric looks exactly like a new one, so without the guard a
    rename could dodge the gate forever (every round "new", never
    compared); the driver passes the expected churn (usually 0 between
    feature PRs).
  * No BENCH files or only one -> pass (nothing to compare).

The last stdout line is a machine-readable JSON summary:
``{"kind": "bench_regression", "gated": N, "regressions": [...],
"new_metrics": [...], "skipped_chip_records": K}`` — so the driver (and
tests) consume the result without scraping the prose.

Exit codes: 0 pass, 1 regression (or new-metric guard tripped), 2 usage
error. Wired into tier-1 by tests/test_bench_regression.py, which
includes a detects-regression self-test on a synthetic BENCH pair (same
pattern as scripts/check_mode_dispatch.py).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from statistics import median

# default relative noise tolerance; per-metric overrides below (exact
# names, plus the MFU family via tolerance_for's suffix rule)
DEFAULT_TOLERANCE = 0.15
TOLERANCES = {
    # MFU divides two measured quantities of the same run — steadier than
    # raw throughput, so the whole family (mfu, *_mfu, *_audited_mfu)
    # gets a tighter band
    "mfu": 0.10,
    # sketch-gap PR: the headline GPT-2 ratios divide two measurements of
    # the same run on the same mesh (load cancels), so they get the tight
    # band too — this is what makes the 0.6x sketch-vs-uncompressed
    # target TRAJECTORY-enforced: once an optimized record lands, any
    # later drop below median*(1-0.10) fails the gate. The other new
    # gpt2_sketch_* legs (gpt2_sketch_scan_*) gate through the generic
    # suffix rules (_tokens_per_sec/_mfu/_vs_uncompressed all UP);
    # *_rounds_per_dispatch is configuration, not measurement —
    # informational by having no gated suffix.
    "gpt2_sketch_vs_uncompressed": 0.10,
    "gpt2_sketch_scan_vs_uncompressed": 0.10,
    # sparse-aggregate PR: the *_sparse_agg_vs_dense twins divide two
    # measurements of the same run on the same mesh (load cancels) — the
    # tight ratio band, same reasoning as the gpt2 ratios above
    "local_topk_sparse_agg_vs_dense": 0.10,
    "true_topk_sparse_agg_vs_dense": 0.10,
    # asyncfed PR: the update-rate ratio divides two same-mesh
    # measurements (tight band); the time-to-loss ratio folds in the loss
    # trajectory under a stochastic straggler schedule, so it keeps the
    # default wider band (no entry)
    "sketch_async_vs_sync": 0.10,
    # hidden-collectives PR: both overlap ratios divide two same-mesh
    # measurements of the same program shape (load cancels), so they get
    # the tight band — and gate UP: overlapped must not lose to
    # sequential. The band makes the design claim trajectory-enforced,
    # same pattern as sketch_async_vs_sync above.
    "sketch_overlap_layerwise_vs_sequential": 0.10,
    "async_double_buffered_vs_sequential": 0.10,
    # clientstore PR: host-resident client state vs the device-resident
    # twin on the same mesh. Same-run ratio, but the host twin's
    # numerator includes real host-side work (cohort gather + async
    # writeback drain), which is load-dependent in a way the in-graph
    # twins above are not — so it keeps the default 15% band
    # deliberately (no entry would mean the same; this comment is the
    # registration the bench leg's docstring points at).
    "local_topk_hostclient_vs_device": DEFAULT_TOLERANCE,
    # multihost PR: the mesh-faked 2-host round vs its single-host twin
    # on the same devices — a same-run ratio of two same-shape programs
    # (load cancels), so it gets the tight band and gates UP: declaring
    # the host axis must not cost throughput (the tuple-axis psum lowers
    # to ONE all-reduce; tests/test_multihost.py pins the HLO)
    "sketch_multihost_vs_singlehost": 0.10,
}

# pipeline PR: the sketch_pipelined leg's samples/s + occupancy are gated
# (throughput-ish; occupancy falling means the prefetcher stopped hiding
# host time). Its *_host_stall_ms stays INFORMATIONAL on purpose: near-zero
# stalls make relative tolerances meaningless (0.2 ms vs a 0.1 ms median
# is +100% of noise), so the stall regression shows up through occupancy
# and samples/s instead.
# round-tracing PR: the sketch_traced leg's per-stage
# sketch_traced_*_exclusive_ms rows and sketch_traced_wall_ms are
# INFORMATIONAL by the same rule (*_exclusive_ms / *_wall_ms carry no
# gated suffix) — they measure a fenced-every-round diagnostic loop,
# wall-clock-excluded from twin comparisons exactly like the
# xla/exposed_collective_ms family; sketch_traced_critical_stage is a
# stage NAME (string — never gated by construction). A real attribution
# regression shows up through the gated headline/pipelined rows, with
# these rows saying WHICH stage moved.
LOWER_IS_BETTER_SUFFIXES = ("_sec_per_round",)
HIGHER_IS_BETTER_KEYS = ("value", "mfu", "vs_baseline")
HIGHER_IS_BETTER_SUFFIXES = ("_tokens_per_sec", "_mfu", "_vs_uncompressed",
                             "_samples_per_sec", "_occupancy", "_vs_dense",
                             # asyncfed PR: both twins' server-update rates
                             # and the async/sync ratios gate up
                             # (*_time_to_loss_sec itself stays
                             # informational — its ratio carries the gate)
                             "_updates_per_sec", "_rounds_per_sec",
                             "_vs_sync",
                             # hidden-collectives PR: overlapped vs
                             # sequential twins — the ratio gates up
                             # (*_exposed_collective_ms stays
                             # informational: near-zero ms makes relative
                             # bands meaningless, like *_host_stall_ms)
                             "_vs_sequential",
                             # clientstore PR: the hosted round must not
                             # lose to its device-resident twin
                             # (*_cache_hit_rate and *_h2d_stage_ms stay
                             # informational — near-zero ms again, and the
                             # hit rate is config, not performance)
                             "_vs_device",
                             # multihost PR: the mesh-faked 2-host round
                             # must not lose to its flat single-host twin
                             "_vs_singlehost")
# resilience/control PRs: every *_retraces leg gauge is a hard invariant,
# not a throughput — the AOT-prewarm contract says rung switches and
# rollback restores never retrace, so ANY non-zero value fails outright
# (no history or tolerance involved; a relative band on an
# all-zero trajectory would divide by zero anyway)
# elastic-fleet PR: sketch_elastic_retraces joins the family through this
# suffix — a width resize dispatches a prewarmed per-width program, so
# any retrace across the leg's shrink+grow transitions fails outright.
# sketch_elastic_samples_per_sec gates UP via the generic suffix;
# sketch_elastic_resize_ms stays INFORMATIONAL (microsecond-scale
# dispatch-table swaps make relative bands meaningless, the
# *_host_stall_ms rule) and sketch_elastic_resizes is schedule
# configuration, not measurement.
EXACT_ZERO_SUFFIXES = ("_retraces",)


def metric_direction(name: str):
    """'up' (higher is better), 'down' (lower is better), or None
    (informational — never gated)."""
    if name.endswith(LOWER_IS_BETTER_SUFFIXES):
        return "down"
    if name in HIGHER_IS_BETTER_KEYS or name.endswith(
        HIGHER_IS_BETTER_SUFFIXES
    ):
        return "up"
    return None


def load_bench(path: str) -> dict:
    """The metric dict of one BENCH file: the driver wrapper's ``parsed``
    block when present, else the object itself (a raw bench.py line)."""
    with open(path) as f:
        rec = json.load(f)
    if isinstance(rec, dict) and isinstance(rec.get("parsed"), dict):
        rec = rec["parsed"]
    if not isinstance(rec, dict):
        raise ValueError(f"{path}: not a bench record")
    return rec


def tolerance_for(name: str, default: float) -> float:
    if name in TOLERANCES:
        return TOLERANCES[name]
    if name == "mfu" or name.endswith("_mfu"):  # the whole MFU family
        return TOLERANCES["mfu"]
    return default


def check_regression(history, latest, default_tolerance=DEFAULT_TOLERANCE):
    """(regressions, new_metrics, notes) comparing ``latest`` (metric
    dict) against ``history`` (list of metric dicts, oldest first). Each
    regression is a dict naming the metric, direction, latest value,
    baseline and bound; ``new_metrics`` lists the gated-direction metrics
    that had NO comparable history (ungated this round — the
    ``--max_new_metrics`` guard's input)."""
    regressions, new_metrics, notes = [], [], []
    chip = latest.get("chip")
    comparable = []
    for h in history:
        if chip and h.get("chip") and h["chip"] != chip:
            notes.append(
                f"skipping a prior record on {h['chip']!r} "
                f"(latest ran on {chip!r})"
            )
            continue
        comparable.append(h)
    for name, v in sorted(latest.items()):
        if (name.endswith(EXACT_ZERO_SUFFIXES)
                and isinstance(v, (int, float)) and not isinstance(v, bool)):
            if v != 0:
                regressions.append({
                    "metric": name,
                    "direction": "exact_zero",
                    "latest": v,
                    "baseline_median": 0,
                    "bound": 0,
                    "tolerance": 0.0,
                    "n_prior": len(comparable),
                })
            continue
        direction = metric_direction(name)
        if direction is None or not isinstance(v, (int, float)) \
                or isinstance(v, bool):
            continue
        prior = [
            h[name] for h in comparable
            if isinstance(h.get(name), (int, float))
            and not isinstance(h.get(name), bool)
        ]
        if not prior:
            new_metrics.append(name)
            notes.append(f"{name}: no comparable history (new metric?)")
            continue
        base = median(prior)
        tol = tolerance_for(name, default_tolerance)
        if direction == "up":
            bound = base * (1.0 - tol)
            bad = v < bound
        else:
            bound = base * (1.0 + tol)
            bad = v > bound
        if bad:
            regressions.append({
                "metric": name,
                "direction": direction,
                "latest": v,
                "baseline_median": base,
                "bound": bound,
                "tolerance": tol,
                "n_prior": len(prior),
            })
    return regressions, new_metrics, notes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="compare the latest BENCH_*.json against the trajectory"
    )
    ap.add_argument("--dir", default=".", help="directory holding the files")
    ap.add_argument("--glob", default="BENCH_r*.json",
                    help="bench-record pattern, sorted lexically "
                    "(BENCH_r01 < BENCH_r02 < ...); the last one is the "
                    "record under test")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="default relative noise tolerance "
                    f"(default {DEFAULT_TOLERANCE}; per-metric overrides "
                    "in TOLERANCES)")
    ap.add_argument("--max_new_metrics", type=int, default=None,
                    help="fail (exit 1) when MORE than this many "
                    "gated-direction metrics have no comparable history — "
                    "a renamed metric reads as 'new' every round and would "
                    "otherwise dodge the gate forever (default: no limit; "
                    "the driver passes the expected churn, usually 0)")
    def summary_line(**kw):
        # machine-readable result, ALWAYS the last stdout line on every
        # exit path (the driver/tests consume this instead of scraping
        # the prose)
        print(json.dumps({"kind": "bench_regression", **kw}))

    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        # argparse already printed usage to stderr; --help exits 0 and
        # keeps argparse's behavior, but a bad/unknown flag must still
        # honor the summary-line contract on stdout
        if e.code in (0, None):
            raise
        summary_line(compared=False, gated=0, regressions=[],
                     new_metrics=[], skipped_chip_records=0,
                     error="argument parsing failed (see usage on stderr)")
        return 2

    def usage_error(msg):
        print(msg)
        summary_line(compared=False, gated=0, regressions=[],
                     new_metrics=[], skipped_chip_records=0, error=msg)
        return 2

    if args.tolerance < 0:
        return usage_error("tolerance must be >= 0")
    if args.max_new_metrics is not None and args.max_new_metrics < 0:
        return usage_error("max_new_metrics must be >= 0")

    paths = sorted(glob.glob(os.path.join(args.dir, args.glob)))
    if len(paths) < 2:
        print(f"nothing to compare ({len(paths)} bench record(s) match "
              f"{args.glob!r} in {args.dir!r}) — pass")
        summary_line(compared=False, gated=0, regressions=[],
                     new_metrics=[], skipped_chip_records=0)
        return 0
    try:
        history = [load_bench(p) for p in paths[:-1]]
        latest = load_bench(paths[-1])
    except (ValueError, json.JSONDecodeError, OSError) as e:
        # the summary-line contract holds on EVERY exit path — a consumer
        # json-parsing the last line must not choke on the prose error
        print(f"unreadable bench record: {e}")
        summary_line(compared=False, gated=0, regressions=[],
                     new_metrics=[], skipped_chip_records=0,
                     error=f"unreadable bench record: {e}")
        return 2
    regressions, new_metrics, notes = check_regression(
        history, latest, args.tolerance
    )
    for n in notes:
        print(f"note: {n}")
    gated = sorted(
        k for k in latest
        if metric_direction(k) and isinstance(latest[k], (int, float))
    )
    n_skipped = len(notes) - len(new_metrics)  # chip-provenance skips
    print(f"latest: {paths[-1]} vs {len(paths) - 1} prior record(s); "
          f"{len(gated)} gated metric(s), {len(new_metrics)} ungated as "
          "new/no-history")
    rc = 0
    for r in regressions:
        arrow = "fell below" if r["direction"] == "up" else "rose above"
        print(
            f"REGRESSION {r['metric']}: {r['latest']:g} {arrow} "
            f"{r['bound']:g} (median of {r['n_prior']} prior: "
            f"{r['baseline_median']:g}, tolerance {r['tolerance']:.0%})"
        )
        rc = 1
    if (args.max_new_metrics is not None
            and len(new_metrics) > args.max_new_metrics):
        print(
            f"NEW-METRIC GUARD: {len(new_metrics)} gated-direction "
            f"metric(s) have no comparable history "
            f"({', '.join(new_metrics)}) — more than the allowed "
            f"{args.max_new_metrics}. A renamed metric dodges the gate as "
            "a perpetual 'new' one; re-register the rename or raise "
            "--max_new_metrics for a round that really adds legs."
        )
        rc = 1
    if rc == 0:
        print("OK — no metric regressed past its tolerance")
    summary_line(compared=True, gated=len(gated), regressions=regressions,
                 new_metrics=new_metrics, skipped_chip_records=n_skipped)
    return rc


if __name__ == "__main__":
    sys.exit(main())
