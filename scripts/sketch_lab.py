"""Sketch-mode stability lab — quarter-scale ResNet-9 federated training.

The fast iteration loop used to debug FetchSGD-mode convergence (r2): a
width-32 ResNet-9 (D ~= 1.6M) on the synthetic CIFAR stand-in with
paper-scale RATIOS (c = D/13, k = D/130), 6 epochs of the real pipeline
(device-resident data path), ~90 s per run on one chip.

    python scripts/sketch_lab.py --lr_scale 0.4 --virtual_momentum 0.9 \
        [--band 16] [--num_rows 5] [--num_epochs 6]

Findings this script produced (2026-07-30, full postmortem in
ops/countsketch.py): at lr 0.4 + rho 0.9 the disjoint-pool layouts (v3
riffles, v4 + scramble) diverge (train loss 459 / NaN by epoch 6) while an
EXACT classic scatter sketch under identical server algebra converges to
acc 0.315 — and the v5 BANDED layout matches classic (acc 0.340 at band=16
and 0.333 at band=8 with the shipped default matmul precision; 0.305 at
band=16 under the since-removed Precision.HIGHEST forcing). Under a constant-lr offline loop everything
including classic eventually destabilizes (topk-EF burst dynamics on flat
synthetic gradients), so always validate with this script's real
triangular-schedule pipeline, and with a multi-epoch run — single-shot
estimate quality measured IDENTICAL across layouts (recall@k ~0.38).
"""

from __future__ import annotations

import argparse
import sys
import warnings
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main():
    warnings.filterwarnings("ignore")
    ap = argparse.ArgumentParser()
    ap.add_argument("--lr_scale", type=float, default=0.4)
    ap.add_argument("--virtual_momentum", type=float, default=0.9)
    ap.add_argument("--num_rows", type=int, default=5)
    ap.add_argument("--num_epochs", type=int, default=6)
    ap.add_argument("--pivot_epoch", type=int, default=2)
    ap.add_argument("--width", type=int, default=32)
    ap.add_argument("--band", type=int, default=16)
    ap.add_argument("--c_div", type=int, default=13, help="c = D / c_div")
    ap.add_argument("--k_div", type=int, default=130, help="k = D / k_div")
    ap.add_argument("--variant", default="flat",
                    help="synthetic stand-in: flat|concentrated|concentrated_v2")
    ap.add_argument("--mode", default="sketch",
                    help="sketch|uncompressed|true_topk|local_topk")
    ap.add_argument("--compute_dtype", default="float32",
                    help="model fwd/bwd dtype: float32 | bfloat16")
    ap.add_argument("--hash_family", default="fmix32",
                    help="fmix32 (production) | poly4 (4-universal "
                         "Mersenne-poly A/B backstop, VERDICT r2 item 7)")
    ap.add_argument("--m", type=int, default=None,
                    help="override the adaptive chunk size (d/c~100 regime "
                         "experiments)")
    ap.add_argument("--error_decay", type=float, default=1.0,
                    help="virtual-error decay gamma (d/c-envelope "
                         "mitigation, r4): e <- gamma*e after each round's "
                         "extract-and-subtract")
    args = ap.parse_args()

    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    from commefficient_tpu.data import FedSampler, augment_batch
    from commefficient_tpu.data.cifar import (
        CIFAR10_MEAN, CIFAR10_STD, _synthetic_by_variant, device_normalizer,
    )
    from commefficient_tpu.data.fed_dataset import FedDataset
    from commefficient_tpu.models import ResNet9, classification_loss
    from commefficient_tpu.parallel import FederatedSession
    from commefficient_tpu.utils.config import Config
    from commefficient_tpu.utils.schedule import piecewise_linear_lr

    from commefficient_tpu.models.losses import model_dtype

    model = ResNet9(num_classes=10, width=args.width,
                    dtype=model_dtype(args.compute_dtype))
    params = model.init(jax.random.key(42), jnp.zeros((1, 32, 32, 3)))
    loss_fn = classification_loss(
        model.apply, prep=device_normalizer(CIFAR10_MEAN, CIFAR10_STD),
        compute_dtype=args.compute_dtype,
    )
    D = ravel_pytree(params)[0].size
    C, K = D // args.c_div, D // args.k_div
    print(f"D={D} c={C} k={K} lr={args.lr_scale} rho={args.virtual_momentum}")

    tr_raw, te_raw = _synthetic_by_variant(10, args.variant)
    train = FedDataset(dict(tr_raw), 16, seed=42)
    test = FedDataset(dict(te_raw), 1, seed=42)

    cfg = Config(
        mode=args.mode,
        error_type=(
            "virtual" if args.mode in ("sketch", "true_topk")
            else ("local" if args.mode == "local_topk" else "none")
        ),
        virtual_momentum=(
            args.virtual_momentum if args.mode in ("sketch", "true_topk") else 0.0
        ),
        k=K, num_rows=args.num_rows, num_cols=C, topk_method="threshold",
        sketch_band=args.band, hash_family=args.hash_family, sketch_m=args.m,
        fuse_clients=True, num_clients=16, num_workers=8, num_devices=1,
        local_batch_size=64, weight_decay=5e-4, seed=42,
        num_epochs=args.num_epochs, lr_scale=args.lr_scale,
        pivot_epoch=args.pivot_epoch, error_decay=args.error_decay,
    )
    session = FederatedSession(cfg, params, loss_fn)
    if session.spec is not None:
        print(f"spec: band={session.spec.band} V={session.spec.V_row(0)} "
              f"s={session.spec.s} scramble_block={session.spec.sblock} "
              f"c_actual={session.spec.c_actual}")
    sampler = FedSampler(train, num_workers=8, local_batch_size=64, seed=42,
                         augment=augment_batch)
    session.maybe_attach_data(train, sampler, augment_batch)
    steps = sampler.steps_per_epoch()
    lr_fn = partial(piecewise_linear_lr, steps_per_epoch=steps,
                    pivot_epoch=cfg.pivot_epoch, num_epochs=cfg.num_epochs,
                    lr_scale=cfg.lr_scale)
    step = 0
    for ep in range(cfg.num_epochs):
        for ids, idx, plan in sampler.epoch_indices(ep):
            m = session.train_round_indices(ids, idx, plan, float(lr_fn(step)))
            step += 1
        print(f"  ep{ep + 1}: train_loss={float(np.asarray(m['loss'])):.4f}",
              flush=True)
    val = session.evaluate(test.eval_batches(512))
    print(f"== acc={val.get('accuracy'):.4f} loss={val['loss']:.4f}")


if __name__ == "__main__":
    main()
