"""Shared helpers for the r5 lab scripts (review r5: the JSON-record
logging block was copy-pasted per script)."""

from __future__ import annotations

import json
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent


def log_json(log_path: Path, rec: dict) -> None:
    """Print a run record and append it to the suite's log file."""
    print("==", json.dumps(rec), flush=True)
    log_path.parent.mkdir(exist_ok=True)
    with log_path.open("a") as f:
        f.write(json.dumps(rec) + "\n")
