"""Quantify threshold-top-k tie loss on real gradients (VERDICT r2 item 10).

``ops.topk.topk_threshold_dense`` selects by a binary-searched magnitude
threshold and DROPS exact ties at the threshold, so its selection can have
fewer than k nonzeros. Under error feedback the dropped mass is retained for
later rounds; in no-EF paths it is simply lost. This script measures how
often that fires at production scale (k=50k, d=6.5M) on REAL ResNet-9 round
gradients (fresh + partially trained, both synthetic variants), reporting:

  dropped      k - nnz(threshold selection)
  mass_gap     (||topk_exact||_1 - ||sel_threshold||_1) / ||topk_exact||_1

    python scripts/topk_tie_loss.py
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main():
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    from commefficient_tpu.data.cifar import (
        CIFAR10_MEAN, CIFAR10_STD, _synthetic_by_variant, device_normalizer,
    )
    from commefficient_tpu.models import ResNet9, classification_loss
    from commefficient_tpu.ops.topk import topk_dense, topk_threshold_dense

    model = ResNet9(num_classes=10)
    params = model.init(jax.random.key(42), jnp.zeros((1, 32, 32, 3)))
    loss_fn = classification_loss(
        model.apply, prep=device_normalizer(CIFAR10_MEAN, CIFAR10_STD)
    )
    vec, unravel = ravel_pytree(params)
    D = int(vec.size)
    K = 50_000

    @jax.jit
    def grad_at(params_vec, batch, wd):
        g, _ = ravel_pytree(
            jax.grad(lambda q: loss_fn(q, batch)[0])(unravel(params_vec))
        )
        return g.astype(jnp.float32) + wd * params_vec

    @jax.jit
    def compare(g):
        exact = topk_dense(g, K)
        thr = topk_threshold_dense(g, K)
        nnz = jnp.sum(thr != 0)
        l1_exact = jnp.sum(jnp.abs(exact))
        l1_thr = jnp.sum(jnp.abs(thr))
        return nnz, l1_exact, l1_thr

    @jax.jit
    def sgd_step(params_vec, batch, lr):
        return params_vec - lr * grad_at(params_vec, batch, 5e-4)

    print(f"D={D} k={K}")
    for variant in ("flat", "concentrated"):
        tr, _ = _synthetic_by_variant(10, variant)
        rng = np.random.default_rng(0)
        pv = vec.astype(jnp.float32)
        # a few SGD steps so "trained" gradients are probed too
        for stage, steps in (("init", 0), ("after 50 steps", 50)):
            for _ in range(steps):
                i = rng.choice(len(tr["y"]), size=512, replace=False)
                pv = sgd_step(pv, {"x": tr["x"][i], "y": tr["y"][i]}, 0.05)
            i = rng.choice(len(tr["y"]), size=512, replace=False)
            g = grad_at(pv, {"x": tr["x"][i], "y": tr["y"][i]}, 5e-4)
            nnz, l1e, l1t = compare(g)
            dropped = K - int(nnz)
            gap = (float(l1e) - float(l1t)) / max(float(l1e), 1e-30)
            print(f"  {variant:12s} {stage:15s} dropped={dropped:6d} "
                  f"({100 * dropped / K:.4f}% of k)  l1 mass_gap={gap:.3e}",
                  flush=True)


if __name__ == "__main__":
    main()
