#!/bin/bash
# r4 d/c envelope sweep (VERDICT r3 weak 3 / next 5): map the 25-50 gap at
# quarter scale and test error_decay as the mitigation at/past the cliff.
# Each run ~2-4 min on one v5e chip; appends to runs/r4_envelope.log via tee.
set -u
cd "$(dirname "$0")/.."
mkdir -p runs
log() { echo "== $*" | tee -a runs/r4_envelope.log; }

run() {
  local name="$1"; shift
  out=$(python scripts/sketch_lab.py --num_epochs 12 --lr_scale 0.04 \
        --pivot_epoch 2 --virtual_momentum 0.9 "$@" 2>&1 | tail -2)
  log "$name: $out"
}

# the gap: d/c in {25 (control), 30, 35, 40, 50 (known divergent)}
for dc in 25 30 35 40 50; do
  run "dc${dc}" --c_div "$dc" --k_div $((dc * 10))
done
# mitigation: error decay at the boundary and past it
for dc in 35 40 50; do
  for g in 0.95 0.9; do
    run "dc${dc}_decay${g}" --c_div "$dc" --k_div $((dc * 10)) --error_decay "$g"
  done
done
