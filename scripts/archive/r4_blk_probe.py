import sys
from pathlib import Path
sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(1, str(Path(__file__).resolve().parents[2] / "scripts"))
import time, numpy as np, jax, jax.numpy as jnp
from commefficient_tpu.ops.countsketch import CountSketch, sketch_vec, estimate_all

d = 6_573_130
rng = np.random.default_rng(0)
v = jnp.asarray(rng.normal(size=(d,)).astype(np.float32))

def scan_time(name, stage, n=20):
    @jax.jit
    def run():
        def body(s, _):
            return stage(s * 1e-30).astype(jnp.float32) * 1e-30, ()
        s, _ = jax.lax.scan(body, jnp.float32(0), None, length=n)
        return s
    float(run())
    t0 = time.perf_counter(); float(run())
    print(f"{name:48s} {(time.perf_counter()-t0)/n*1e3:8.2f} ms", flush=True)

for blk in (32, 64, 128):
    spec = CountSketch(d=d, c=500_000, r=5, seed=42, scramble_block=blk)
    table = jax.jit(lambda vv: sketch_vec(spec, vv))(v)
    scan_time(f"sketch_vec blk={blk}", lambda s, sp=spec: jnp.sum(sketch_vec(sp, v + s)))
    scan_time(f"estimate_all blk={blk}", lambda s, sp=spec, t=table: jnp.sum(estimate_all(sp, t + s)))
