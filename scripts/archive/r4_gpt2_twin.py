"""r4 GPT-2 iso-budget twin: uncompressed vs sketch at the same budget
(VERDICT r3 missing 2 / next-round item 3).

Protocol (the r3 sweep methodology applied at language scale): GPT-2-small
(D~=124M) on the synthetic PersonaChat stand-in, fixed 6-epoch budget, lr
tuned PER MODE over a small grid, token-weighted val nll after every epoch
(printed by gpt2_train's table). Sketch config is the in-envelope 5x5M
table (d/c~=25, ~5x upload compression — the reference's own GPT-2 run
compresses ~3.9x uplink, FetchSGD §5).

    python scripts/archive/r4_gpt2_twin.py sweep       # the lr grids, both modes
    python scripts/archive/r4_gpt2_twin.py one --mode sketch --lr 0.08
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(1, str(Path(__file__).resolve().parents[2] / "scripts"))

LOG = Path(__file__).resolve().parents[2] / "runs" / "r4_gpt2_twin.log"


def run_one(mode: str, lr: float, *, epochs=6, pivot=2, seq=256, batch=4,
            workers=8, clients=32, rows=5, cols=5_000_000, k=50_000,
            extra_argv=()):
    from commefficient_tpu.train import gpt2_train

    argv = [
        "--model", "gpt2", "--dataset_dir", "./data",
        "--num_epochs", str(epochs), "--pivot_epoch", str(pivot),
        "--num_clients", str(clients), "--num_workers", str(workers),
        "--num_devices", "1", "--local_batch_size", str(batch),
        "--max_seq_len", str(seq), "--lr_scale", str(lr),
        "--seed", "42", "--topk_method", "threshold",
        "--mode", mode,
    ]
    if mode == "sketch":
        argv += ["--error_type", "virtual", "--virtual_momentum", "0.9",
                 "--k", str(k), "--num_rows", str(rows),
                 "--num_cols", str(cols), "--fuse_clients", "true"]
    else:
        argv += ["--fuse_clients", "true"]
    argv += list(extra_argv)
    t0 = time.time()
    val = gpt2_train.main(argv)
    dt = time.time() - t0
    rec = {"mode": mode, "lr": lr, "pivot": pivot, "epochs": epochs,
           "nll": round(float(val["nll"]), 4),
           "ppl": round(float(val["ppl"]), 1),
           "mc_acc": round(float(val["mc_accuracy"]), 4),
           "seconds": round(dt)}
    if mode == "sketch" and (rows, cols) != (5, 5_000_000):
        rec["table"] = f"{rows}x{cols}"
    if extra_argv:
        rec["extra"] = list(extra_argv)
    print("==", json.dumps(rec), flush=True)
    LOG.parent.mkdir(exist_ok=True)
    with LOG.open("a") as f:
        f.write(json.dumps(rec) + "\n")
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("cmd", choices=["sweep", "one"])
    ap.add_argument("--mode", default="sketch")
    ap.add_argument("--lr", type=float, default=0.16)
    ap.add_argument("--epochs", type=int, default=6)
    args = ap.parse_args()

    if args.cmd == "one":
        run_one(args.mode, args.lr, epochs=args.epochs)
        return
    # lr grids: uncompressed around the reference's gpt2 lr territory;
    # sketch an order lower (server momentum rho=0.9 => effective lr/(1-rho),
    # the r3 effective-lr account)
    for lr in (0.08, 0.16, 0.32):
        run_one("uncompressed", lr, epochs=args.epochs)
    for lr in (0.02, 0.04, 0.08):
        run_one("sketch", lr, epochs=args.epochs)


if __name__ == "__main__":
    main()
