"""r4 dense-ceiling lab — why does tuned dense SGD stop at 0.61 while
local_topk reaches 0.93 (VERDICT r3 missing 1 / weak 1)?

Runs named full-scale configs WITH per-epoch train/val rows (the r3 sweeps
recorded only final val acc, so underfit-vs-overfit was never separated).
Each run prints a cifar10-fast-style table; results append to
runs/r4_dense_lab.log.

    python scripts/archive/r4_dense_lab.py ceiling_diag      # run a named suite
    python scripts/archive/r4_dense_lab.py one uncompressed --lr 0.8 --epochs 48
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(1, str(Path(__file__).resolve().parents[2] / "scripts"))

LOG = Path(__file__).resolve().parents[2] / "runs" / "r4_dense_lab.log"


def run_one(name: str, *, variant: str = "concentrated", epochs: int = 24,
            **kw):
    from commefficient_tpu.train.cv_train import (
        build_model_and_data,
        build_session_and_sampler,
        train_loop,
    )
    from commefficient_tpu.utils.config import Config
    from commefficient_tpu.utils.logging import TableLogger

    base = dict(
        dataset_name="cifar10", dataset_dir="./data", model="resnet9",
        num_epochs=epochs,
        num_clients=16, num_workers=8, num_devices=1, local_batch_size=64,
        weight_decay=5e-4, seed=42, topk_method="threshold",
        synthetic_variant=variant,
    )
    base.update(kw)
    cfg = Config(**base)
    train, test, real, model, params, loss_fn, augment = build_model_and_data(cfg)
    session, sampler = build_session_and_sampler(cfg, train, params, loss_fn, augment)
    t0 = time.time()
    table = TableLogger()
    val = train_loop(cfg, session, sampler, test, table=table)
    dt = time.time() - t0
    line = (f"{name}: acc={val.get('accuracy', float('nan')):.4f} "
            f"loss={val['loss']:.4f} ({dt:.0f}s) cfg={kw} epochs={epochs}")
    print("==", line, flush=True)
    LOG.parent.mkdir(exist_ok=True)
    with LOG.open("a") as f:
        f.write(line + "\n")
    return val


SUITES = {
    # Phase A: separate underfit from overfit, and test the two cheapest
    # dense-ceiling hypotheses (more epochs; the unexplored momentum grid).
    "ceiling_diag": [
        ("unc_0.8p6_e24", dict(mode="uncompressed", fuse_clients=True,
                               lr_scale=0.8, pivot_epoch=6)),
        ("loc_0.4p6_e24", dict(mode="local_topk", error_type="local",
                               k=50_000, lr_scale=0.4, pivot_epoch=6)),
        ("unc_0.8p6_e72", dict(mode="uncompressed", fuse_clients=True,
                               lr_scale=0.8, pivot_epoch=6), 72),
        ("unc_mom_0.2p6_e24", dict(mode="uncompressed", fuse_clients=True,
                                   virtual_momentum=0.9, lr_scale=0.2,
                                   pivot_epoch=6)),
    ],
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("suite")
    ap.add_argument("mode", nargs="?")
    ap.add_argument("--lr", type=float, default=0.4)
    ap.add_argument("--pivot", type=int, default=6)
    ap.add_argument("--epochs", type=int, default=24)
    ap.add_argument("--variant", default="concentrated")
    ap.add_argument("--k", type=int, default=50_000)
    args = ap.parse_args()

    if args.suite == "one":
        kw = dict(mode=args.mode, lr_scale=args.lr, pivot_epoch=args.pivot)
        if args.mode == "local_topk":
            kw.update(error_type="local", k=args.k)
        else:
            kw.update(fuse_clients=True)
        run_one(f"{args.mode}_{args.lr}p{args.pivot}_e{args.epochs}",
                variant=args.variant, epochs=args.epochs, **kw)
        return

    for spec in SUITES[args.suite]:
        name, kw = spec[0], spec[1]
        epochs = spec[2] if len(spec) > 2 else 24
        run_one(name, epochs=epochs, **kw)


if __name__ == "__main__":
    main()
