"""r5 residual decomposition (VERDICT r4 weak 6 / next-round item 7).

Tuned dense SGD reaches 0.8999 on the v3 task whose label-noise ceiling is
~0.946; ACCURACY.md attributes the 4.6-pt residual to "residual
conditioning plus augmentation/jitter irreducibility" — asserted, never
isolated. This control grid decomposes it knob by knob, dense mode at the
tuned schedule (0.8:6, 24 ep), one knob off per run:

  * no_augment      train-time cutout/crop/flip off (augment=None)
  * no_jitter       generator amp_jitter=0, jitter_px=0
  * no_dropout      generator patch_dropout=0
  * all_off         all three
  * base            v3 defaults (reproduces the 0.8999 row)

If a knob recovers >2 pts, dense was NOT at its task ceiling and the
north-star row needs re-running (VERDICT's criterion). Any variant that
moves gets an lr confirmation at 0.4/1.2 (`one --lr`).

    python scripts/archive/r5_residual.py grid
    python scripts/archive/r5_residual.py one --name no_augment --lr 1.2
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(1, str(Path(__file__).resolve().parents[2] / "scripts"))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from labutil import ROOT, log_json

LOG = ROOT / "runs" / "r5_residual.log"

VARIANTS = {
    "base": (dict(), True),
    "no_augment": (dict(), False),
    "no_jitter": (dict(amp_jitter=0.0, jitter_px=0), True),
    "no_dropout": (dict(patch_dropout=0.0), True),
    "all_off": (dict(amp_jitter=0.0, jitter_px=0, patch_dropout=0.0), False),
}


MODE_KW = {
    # tuned schedules from the r4/r5 accuracy table (lr overridable)
    "uncompressed": dict(mode="uncompressed", fuse_clients=True),
    "sketch7": dict(mode="sketch", error_type="virtual",
                    virtual_momentum=0.9, k=50_000, num_rows=7,
                    num_cols=357_143, fuse_clients=True),
    "local_topk": dict(mode="local_topk", error_type="local", k=50_000),
}


def run_one(name: str, gen_kw: dict, use_augment: bool, *, lr=0.8, pivot=6,
            epochs=24, seed=42, mode="uncompressed"):
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.data import FedDataset, augment_batch
    from commefficient_tpu.data.cifar import (
        CIFAR10_MEAN,
        CIFAR10_STD,
        _synthetic_cifar_concentrated,
        device_normalizer,
    )
    from commefficient_tpu.models import ResNet9, classification_loss
    from commefficient_tpu.train.cv_train import (
        build_session_and_sampler,
        train_loop,
    )
    from commefficient_tpu.utils.config import Config
    from commefficient_tpu.utils.logging import TableLogger

    cfg = Config(
        dataset_name="cifar10", model="resnet9", num_epochs=epochs,
        num_clients=16, num_workers=8, num_devices=1, local_batch_size=64,
        weight_decay=5e-4, seed=seed, topk_method="threshold",
        lr_scale=lr, pivot_epoch=pivot, **MODE_KW[mode],
    )
    train_d, test_d = _synthetic_cifar_concentrated(10, **gen_kw)
    train = FedDataset(dict(train_d), cfg.num_clients, iid=True, seed=cfg.seed)
    test = FedDataset(dict(test_d), 1, iid=True, seed=cfg.seed)
    model = ResNet9(num_classes=10)
    params = model.init(jax.random.key(cfg.seed), jnp.zeros((1, 32, 32, 3)))
    loss_fn = classification_loss(
        model.apply, prep=device_normalizer(CIFAR10_MEAN, CIFAR10_STD)
    )
    session, sampler = build_session_and_sampler(
        cfg, train, params, loss_fn, augment_batch if use_augment else None
    )
    t0 = time.time()
    val = train_loop(cfg, session, sampler, test, table=TableLogger())
    dt = time.time() - t0
    rec = {"name": name, "mode": mode, "lr": lr, "epochs": epochs,
           "augment": use_augment, "gen": gen_kw,
           "acc": round(float(val.get("accuracy", float("nan"))), 4),
           "loss": round(float(val["loss"]), 4), "seconds": round(dt)}
    log_json(LOG, rec)
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("cmd", choices=["grid", "one", "noaug"])
    ap.add_argument("--name", default="base")
    ap.add_argument("--mode", default="uncompressed", choices=list(MODE_KW))
    ap.add_argument("--lr", type=float, default=0.8)
    ap.add_argument("--pivot", type=int, default=6)
    ap.add_argument("--epochs", type=int, default=24)
    args = ap.parse_args()

    if args.cmd == "one":
        gen_kw, use_aug = VARIANTS[args.name]
        run_one(args.name, gen_kw, use_aug, lr=args.lr, pivot=args.pivot,
                epochs=args.epochs, mode=args.mode)
        return
    if args.cmd == "noaug":
        # the verdict's re-run criterion fired (no_augment recovered >2
        # pts): the north-star modes, no-augment pipeline, tuned
        # schedules (dense lr bracketed since its optimum may shift)
        run_one("no_augment", dict(), False, lr=0.6, mode="uncompressed")
        run_one("no_augment", dict(), False, lr=1.0, mode="uncompressed")
        run_one("no_augment", dict(), False, lr=0.1, pivot=2, mode="sketch7")
        run_one("no_augment", dict(), False, lr=0.15, pivot=2, mode="sketch7")
        run_one("no_augment", dict(), False, lr=0.8, mode="local_topk")
        return
    for name, (gen_kw, use_aug) in VARIANTS.items():
        run_one(name, gen_kw, use_aug, epochs=args.epochs)


if __name__ == "__main__":
    main()
