"""r5 GPT-2 twin follow-up (VERDICT r4 missing 1 / next-round item 1).

Two defects in the r4 twin evidence, and the runs that close them:

1. The uncompressed 6-ep lr grid was truncated at its best EDGE point
   (2.56, still improving 1.28->2.56). `extend` runs 5.12 and 10.24 so the
   optimum is interior (or divergence marks the boundary).
2. Both modes sat ~0.9 nats above random (nll ~9.9-10.0 vs ln 50257 =
   10.82) on the 6-epoch budget — no discriminative power. `deep` reruns
   both modes at 24 epochs (pivot 4) around each mode's 6-ep optimum so
   the comparison happens where the models actually learn.

Reuses r4_gpt2_twin.run_one (same model/config/protocol) but logs to
runs/r5_gpt2_twin.log so rounds stay separable.

    python scripts/archive/r5_gpt2_twin.py extend
    python scripts/archive/r5_gpt2_twin.py deep
    python scripts/archive/r5_gpt2_twin.py one --mode sketch --lr 0.32 --epochs 24
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(1, str(Path(__file__).resolve().parents[2] / "scripts"))
sys.path.insert(0, str(Path(__file__).resolve().parent))

import r4_gpt2_twin as twin

from labutil import ROOT

twin.LOG = ROOT / "runs" / "r5_gpt2_twin.log"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("cmd", choices=["extend", "deep", "one", "variants"])
    ap.add_argument("--mode", default="sketch")
    ap.add_argument("--lr", type=float, default=0.32)
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--pivot", type=int, default=None)
    args = ap.parse_args()

    if args.cmd == "variants":
        # same-bytes sketch variants probing the 0.16-nat 24-ep gap:
        # (a) gamma=0.95 — d/c 24.9 sits at the undecayed cliff's edge;
        #     mild decay cheaply buys error-bank SNR headroom
        # (b) r=7 x 3.57M (same 25M-float table) — the CV result says the
        #     stronger median beats per-row width; d/c/row 34.9 needs
        #     gamma=0.9 (fitted envelope: rho*(0.9) ~ 45)
        twin.run_one("sketch", 0.08, epochs=24, pivot=4,
                     extra_argv=("--error_decay", "0.95"))
        twin.run_one("sketch", 0.08, epochs=24, pivot=4,
                     rows=7, cols=3_571_428,
                     extra_argv=("--error_decay", "0.9"))
        return

    if args.cmd == "extend":
        # past-the-edge points for the uncompressed 6-ep grid
        for lr in (5.12, 10.24):
            twin.run_one("uncompressed", lr, epochs=6, pivot=2)
    elif args.cmd == "deep":
        # 24-ep discriminative budget, grids centered on each mode's 6-ep
        # optimum (uncompressed: whatever `extend` finds; sketch: 0.32).
        for lr in (1.28, 2.56, 5.12):
            twin.run_one("uncompressed", lr, epochs=24, pivot=4)
        for lr in (0.16, 0.32, 0.64):
            twin.run_one("sketch", lr, epochs=24, pivot=4)
    else:
        pivot = args.pivot if args.pivot is not None else max(2, args.epochs // 6)
        twin.run_one(args.mode, args.lr, epochs=args.epochs, pivot=pivot)


if __name__ == "__main__":
    main()
