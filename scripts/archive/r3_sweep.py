"""Sketch rho=0.9 schedule sweep at full scale (north-star tuning).

The r3 accuracy run showed sketch/true_topk with rho=0.9 destabilizing
during the 24-epoch lr ramp at lr_scale=0.4 (while rho=0 matches
uncompressed): with server momentum 0.9 the effective step is
lr/(1-rho) = 10x lr, so lr 0.4 + rho 0.9 is effective-lr 4.0 — far above
the uncompressed baseline's 0.4. This sweeps (lr_scale, pivot_epoch) for
the flagship sketch config to find the stable schedule; the FetchSGD paper
tunes lr per compression config the same way (§5).

    python scripts/archive/r3_sweep.py [--mode sketch] [--epochs 24]
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(1, str(Path(__file__).resolve().parents[2] / "scripts"))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", default="sketch")
    ap.add_argument("--epochs", type=int, default=24)
    ap.add_argument("--variant", default="concentrated")
    ap.add_argument("--rho", type=float, default=0.9)
    ap.add_argument("--grid", default="0.4:2,0.2:6,0.1:6,0.04:6",
                    help="comma list of lr:pivot pairs")
    ap.add_argument("--compute_dtype", default="float32")
    ap.add_argument("--num_rows", type=int, default=5)
    ap.add_argument("--num_cols", type=int, default=500_000)
    ap.add_argument("--k", type=int, default=50_000)
    ap.add_argument("--apply_rho_to_all", action="store_true",
                    help="use --rho as server momentum for ANY mode (e.g. "
                         "an uncompressed momentum-SGD baseline), not just "
                         "sketch/true_topk")
    args = ap.parse_args()

    from commefficient_tpu.train.cv_train import (
        build_model_and_data,
        build_session_and_sampler,
        train_loop,
    )
    from commefficient_tpu.utils.config import Config

    k = args.k
    for pair in args.grid.split(","):
        lr_s, piv_s = pair.split(":")
        lr, piv = float(lr_s), int(piv_s)
        cfg = Config(
            dataset_name="cifar10", dataset_dir="./data", model="resnet9",
            synthetic_variant=args.variant, num_epochs=args.epochs,
            lr_scale=lr, pivot_epoch=piv, num_clients=16, num_workers=8,
            num_devices=1, local_batch_size=64, weight_decay=5e-4, seed=42,
            topk_method="threshold", mode=args.mode,
            error_type="virtual" if args.mode in ("sketch", "true_topk") else "none",
            virtual_momentum=(
                args.rho
                if args.apply_rho_to_all or args.mode in ("sketch", "true_topk")
                else 0.0
            ),
            k=k, num_rows=args.num_rows, num_cols=args.num_cols,
            fuse_clients=True, compute_dtype=args.compute_dtype,
        )
        train, test, real, model, params, loss_fn, augment = (
            build_model_and_data(cfg)
        )
        session, sampler = build_session_and_sampler(
            cfg, train, params, loss_fn, augment
        )
        t0 = time.time()
        val = train_loop(cfg, session, sampler, test)
        print(f"== {args.mode} rho={args.rho} lr={lr} pivot={piv}: "
              f"acc={val.get('accuracy', float('nan')):.4f} "
              f"loss={val['loss']:.4f} ({time.time() - t0:.0f}s)", flush=True)


if __name__ == "__main__":
    main()
