"""r5: can r=5 x 500k reach the r=7 headline accuracy (VERDICT r4 weak 4 /
next-round item 5)?

The accuracy-winning sketch row (7x357k, 0.8997) costs 296 s vs 131 s
uncompressed (2.26x); the r=5 x 500k split costs ~190 s (1.45x — under the
2x target) but peaked at 0.8857 in r4. Its r4 grid was {0.04, 0.08, 0.15}
at pivot 2 with the BEST POINT AT THE LOW EDGE (0.04) — the optimum was
never bracketed. This lab brackets it and tries the two free levers that
keep upload bytes identical (the table IS the upload):

  * lr below 0.04 / later pivot (schedule space the r4 grid never entered)
  * k = 100k (extraction width; bytes unchanged, more mass recovered per
    round at d/c = 13 where collisions are mild)

    python scripts/archive/r5_sketch5.py grid
    python scripts/archive/r5_sketch5.py one --lr 0.03 --pivot 2 --k 50000
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(1, str(Path(__file__).resolve().parents[2] / "scripts"))
sys.path.insert(0, str(Path(__file__).resolve().parent))

import r4_retune as retune

retune.LOG = Path(__file__).resolve().parents[2] / "runs" / "r5_sketch5.log"

BASE = dict(mode="sketch", error_type="virtual", virtual_momentum=0.9,
            num_rows=5, num_cols=500_000, fuse_clients=True)


R7 = dict(mode="sketch", error_type="virtual", virtual_momentum=0.9,
          k=50_000, num_rows=7, num_cols=357_143, fuse_clients=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("cmd", choices=["grid", "one", "geom", "geom2"])
    ap.add_argument("--lr", type=float, default=0.04)
    ap.add_argument("--pivot", type=int, default=2)
    ap.add_argument("--k", type=int, default=50_000)
    ap.add_argument("--epochs", type=int, default=24)
    args = ap.parse_args()

    if args.cmd == "geom2":
        # m=4096 (1.60x wall-clock) lost 0.6 pts at the m=8192-tuned lr;
        # the geometry change moves collision noise, so re-bracket lr and
        # try band=24 (restores ~78% of the default collision-pool size
        # at ~+8% cost) before conceding the accuracy delta.
        retune.run_one("sketch7_m4096", dict(R7, sketch_m=4096), 0.06, 2,
                       epochs=args.epochs)
        retune.run_one("sketch7_m4096", dict(R7, sketch_m=4096), 0.15, 2,
                       epochs=args.epochs)
        retune.run_one("sketch7_m4096_band24",
                       dict(R7, sketch_m=4096, sketch_band=24), 0.1, 2,
                       epochs=args.epochs)
        return
    if args.cmd == "geom":
        # r7x357k with the chunk size PINNED below the adaptive >=256-
        # bucket floor (r5_r7probe: the floor forces m=8192/s=432 and a
        # 1.42x per-row window; m=4096 -> -18% op cost, m=2048 -> -48%).
        # Does r=7's stronger median tolerate the smaller pools the r3
        # postmortem ruled out at r=3/5? Accuracy + wall-clock decide.
        retune.run_one("sketch7_m4096", dict(R7, sketch_m=4096), 0.1, 2,
                       epochs=args.epochs)
        retune.run_one("sketch7_m2048", dict(R7, sketch_m=2048), 0.1, 2,
                       epochs=args.epochs)
        return
    if args.cmd == "one":
        retune.run_one(f"sketch5_k{args.k//1000}k", dict(BASE, k=args.k),
                       args.lr, args.pivot, epochs=args.epochs)
        return
    for k, lr, pivot in [
        (50_000, 0.02, 2),
        (50_000, 0.03, 2),
        (50_000, 0.04, 4),
        (100_000, 0.04, 2),
        (100_000, 0.06, 2),
        (100_000, 0.03, 2),
    ]:
        retune.run_one(f"sketch5_k{k//1000}k", dict(BASE, k=k), lr, pivot,
                       epochs=args.epochs)


if __name__ == "__main__":
    main()
