import sys
from pathlib import Path
sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(1, str(Path(__file__).resolve().parents[2] / "scripts"))
import time, numpy as np, jax, jax.numpy as jnp
from commefficient_tpu.ops.countsketch import CountSketch, sketch_vec, estimate_all

d = 6_573_130
rng = np.random.default_rng(0)
v = jnp.asarray(rng.normal(size=(d,)).astype(np.float32))

def scan_time(name, stage, n=20):
    @jax.jit
    def run():
        def body(s, _):
            return stage(s * 1e-30).astype(jnp.float32) * 1e-30, ()
        s, _ = jax.lax.scan(body, jnp.float32(0), None, length=n)
        return s
    float(run())
    t0 = time.perf_counter(); float(run())
    print(f"{name:48s} {(time.perf_counter()-t0)/n*1e3:8.2f} ms", flush=True)

est5 = jnp.asarray(rng.normal(size=(5, d)).astype(np.float32))
def med5(x):
    a, b, c, dd, e = x[0], x[1], x[2], x[3], x[4]
    mn, mx = jnp.minimum, jnp.maximum
    a, b = mn(a, b), mx(a, b)
    c, dd = mn(c, dd), mx(c, dd)
    a, c = mn(a, c), mx(a, c)
    b, dd = mn(b, dd), mx(b, dd)
    b, c = mn(b, c), mx(b, c)
    return mx(b, mn(c, e))
scan_time("jnp.median [5,d]", lambda s: jnp.sum(jnp.median(est5 + s, axis=0)))
scan_time("median5 network", lambda s: jnp.sum(med5(est5 + s)))
chk = np.asarray(med5(est5)); ref = np.asarray(jnp.median(est5, axis=0))
print("network == jnp.median:", np.array_equal(chk, ref), flush=True)

for blk in (8, 256):
    spec = CountSketch(d=d, c=500_000, r=5, seed=42, scramble_block=blk)
    table = jax.jit(lambda vv: sketch_vec(spec, vv))(v)
    scan_time(f"sketch_vec blk={blk}", lambda s, sp=spec: jnp.sum(sketch_vec(sp, v + s)))
    scan_time(f"estimate_all blk={blk}", lambda s, sp=spec, t=table: jnp.sum(estimate_all(sp, t + s)))
