"""r5 BASELINE #5 redo — make fedavg's value measurable (VERDICT r4
missing item / next-round item 4).

The r4 table fixed lr 0.1 for both modes on a 100-class synthetic
ImageNet where both sat at ~4% — demonstrating only that the code runs.
fedavg's actual claim (reference fed_worker.py ~L240-290; McMahan et al.)
is FEWER COMMUNICATION ROUNDS at comparable accuracy: with L local steps
per round it uploads once where plain SGD uploads L times.

Design (iso-steps / iso-bytes triad, per-config tuned lr, honest-CV task
— the v3 concentrated CIFAR stand-in where dense SGD demonstrably trains
to 0.8999, so differences are measurable). NB the fedavg microbatch
convention: a round consumes ``num_local_iters * local_batch_size``
samples per client (cv_train reshapes to [W, L, B]), so the fedavg leg
sets local_batch_size=16 to hold 64 samples/client/round across the triad:

  * fedavg      L=4 steps x microbatch 16 = 64 samples/round
                -> R rounds, R uploads, 4R local steps
  * iso-steps   uncompressed B=16, 1 step x 16 samples/round
                -> 4R rounds, 4R uploads, 4R steps (same minibatch 16)
  * iso-bytes   uncompressed B=64, 1 step x 64 samples/round
                -> R rounds, R uploads, R steps (batch 64 each)

fedavg "wins" if it beats iso-bytes (same uploads, more local work) while
approaching iso-steps (same optimization work, 4x the uploads).

    python scripts/archive/r5_fedavg.py grid                 # tuned triad, CIFAR v3
    python scripts/archive/r5_fedavg.py imagenet             # tuned ImageNet redo
    python scripts/archive/r5_fedavg.py one --config fedavg --lr 0.4
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(1, str(Path(__file__).resolve().parents[2] / "scripts"))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from labutil import ROOT, log_json

LOG = ROOT / "runs" / "r5_fedavg.log"

# (mode flags, local_batch_size) per triad leg — see module docstring for
# the samples/round accounting behind each batch size
CONFIGS = {
    "fedavg": (["--mode", "fedavg", "--num_local_iters", "4"], 16),
    "iso_steps": (["--mode", "uncompressed", "--fuse_clients", "true"], 16),
    "iso_bytes": (["--mode", "uncompressed", "--fuse_clients", "true"], 64),
}

TASKS = {
    "cifar_v3": [
        "--dataset_name", "cifar10", "--model", "resnet9",
        "--synthetic_variant", "concentrated", "--iid", "true",
    ],
    "imagenet": [
        "--dataset_name", "imagenet", "--model", "fixup_resnet50",
        "--num_classes", "100",
    ],
}


def run(task: str, config: str, lr: float, *, epochs=24, seed=42):
    from commefficient_tpu.train import cv_train

    mode_kw, batch = CONFIGS[config]
    t0 = time.time()
    val = cv_train.main(TASKS[task] + [
        "--num_clients", "16", "--num_workers", "8", "--num_devices", "1",
        "--local_batch_size", str(batch),
        "--num_epochs", str(epochs), "--lr_scale", str(lr),
        "--pivot_epoch", str(max(2, epochs // 4)),
        "--topk_method", "threshold", "--dataset_dir", "./data",
        "--weight_decay", "5e-4", "--seed", str(seed),
    ] + mode_kw)
    dt = time.time() - t0
    log_json(LOG, {
        "task": task, "config": config, "lr": lr, "epochs": epochs,
        "batch": batch,
        "acc": round(float(val.get("accuracy", float("nan"))), 4),
        "loss": round(float(val["loss"]), 4), "seconds": round(dt),
    })


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("cmd", choices=["grid", "imagenet", "one"])
    ap.add_argument("--config", default="fedavg", choices=list(CONFIGS))
    ap.add_argument("--lr", type=float, default=0.4)
    ap.add_argument("--epochs", type=int, default=24)
    ap.add_argument("--task", default="cifar_v3", choices=list(TASKS))
    args = ap.parse_args()

    if args.cmd == "one":
        run(args.task, args.config, args.lr, epochs=args.epochs)
        return
    if args.cmd == "grid":
        # full triad at a small per-config grid around the tuned dense
        # optimum (0.8 at B=64; the B=16 legs see 4x the rounds / smaller
        # batches so their per-round lr wants to sit lower)
        for config, lrs in [
            ("iso_bytes", (0.4, 0.8, 1.6)),
            ("iso_steps", (0.2, 0.4, 0.8)),
            ("fedavg", (0.2, 0.4, 0.8)),
        ]:
            for lr in lrs:
                run("cifar_v3", config, lr, epochs=args.epochs)
    else:
        # tuned ImageNet redo: short-budget grid, then report the 12-ep
        # triad at each config's best short-budget lr (run manually via
        # `one` after reading the grid)
        for config in ("iso_bytes", "fedavg"):
            for lr in (0.1, 0.2, 0.4):
                run("imagenet", config, lr, epochs=4)


if __name__ == "__main__":
    main()
