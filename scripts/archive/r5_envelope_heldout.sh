#!/bin/bash
# r5 held-out validation of the fitted d/c envelope model
# (parallel/envelope.py; VERDICT r4 item 6). The model was fitted ONLY to
# the r4 sweep's gamma in {1, 0.95, 0.9}; these three points test its
# predictions at gammas it never saw:
#   gamma=0.925 -> rho* ~ 39.8  => d/c 35 should TRAIN, d/c 45 should FAIL
#   gamma=0.85  -> rho* ~ 55.4  => d/c 50 should TRAIN
# Same harness/geometry as r4 (k/c=0.1, rho=0.9, 12-epoch quarter-scale).
set -u
cd "$(dirname "$0")/.."
mkdir -p runs
log() { echo "== $*" | tee -a runs/r5_envelope_heldout.log; }

run() {
  # ADVICE r5 #5: check the pipeline status — a crashed lab run used to
  # have its traceback tail captured as if it were a result row.
  local name="$1"; shift
  local out rc
  out=$(set -o pipefail; python scripts/sketch_lab.py --num_epochs 12 \
        --lr_scale 0.04 --pivot_epoch 2 --virtual_momentum 0.9 "$@" 2>&1 \
        | tail -2); rc=$?
  if [ "$rc" -ne 0 ]; then
    log "$name: FAILED (exit $rc) — last output: $out"
  else
    log "$name: $out"
  fi
}

run "dc35_decay0.925_predict_TRAIN" --c_div 35 --k_div 350 --error_decay 0.925
run "dc45_decay0.925_predict_FAIL"  --c_div 45 --k_div 450 --error_decay 0.925
run "dc50_decay0.85_predict_TRAIN"  --c_div 50 --k_div 500 --error_decay 0.85
