"""r5 GPT-2-scale FSDP artifact (VERDICT r4 missing 3 / next-round item 3).

Three measurements at D = 124M (GPT-2-small), none of which existed before:

  `account` — on the 8-device virtual-CPU mesh, build the FSDP session at
  D=124M and record per_chip_state_floats (analytic) AND the committed
  per-device shard bytes (measured from the device buffers), for
  sketch(5x5M) and uncompressed, vs the replicated round's footprint.

  `chip` — on the real chip (1-device mesh: the FSDP code path with its
  extraction/update kernels, degenerate collectives), wall-clock the
  sketch round fsdp=true vs fsdp=false via gpt2_train at a 1-epoch
  budget: the FSDP code-path overhead at GPT-2 scale.

  `cpu_round` — optional: execute ONE sketch+fsdp round at D=124M on the
  8-device CPU mesh (slow on one core; proves the full path runs at scale,
  not just at test size).

    python scripts/archive/r5_fsdp_gpt2.py account
    python scripts/archive/r5_fsdp_gpt2.py chip
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(1, str(Path(__file__).resolve().parents[2] / "scripts"))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from labutil import ROOT, log_json

LOG = ROOT / "runs" / "r5_fsdp_gpt2.log"


def _log(rec):
    log_json(LOG, rec)


def _gpt2_small_params():
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.models.gpt2 import GPT2Config, GPT2DoubleHeads

    gcfg = GPT2Config(vocab_size=50262, n_positions=1024, n_embd=768,
                      n_layer=12, n_head=12)
    model = GPT2DoubleHeads(gcfg)
    ids = jnp.zeros((1, 1, 8), jnp.int32)
    params = model.init(jax.random.key(0), ids, token_type_ids=ids,
                        mc_token_ids=jnp.zeros((1, 1), jnp.int32))
    return gcfg, model, params


def run_account(n_devices=8):
    from commefficient_tpu.utils.platform import force_virtual_cpu_devices

    force_virtual_cpu_devices(n_devices)
    import jax

    from commefficient_tpu.models import gpt2_double_heads_loss
    from commefficient_tpu.ops.param_utils import ravel_params
    from commefficient_tpu.parallel import FederatedSession, make_mesh
    from commefficient_tpu.parallel.fsdp import per_chip_state_floats
    from commefficient_tpu.utils.config import Config

    gcfg, model, params = _gpt2_small_params()
    d = int(ravel_params(params)[0].size)
    loss_fn = gpt2_double_heads_loss(model.apply)
    mesh = make_mesh(n_devices)
    base = dict(
        num_clients=2 * n_devices, num_workers=n_devices,
        num_devices=n_devices, local_batch_size=1, weight_decay=0.0,
        topk_method="threshold", device_data=False, fsdp=True,
    )
    for name, cfg in [
        ("sketch_5x5M", Config(mode="sketch", error_type="virtual",
                               virtual_momentum=0.9, k=50_000, num_rows=5,
                               num_cols=5_000_000, **base)),
        ("uncompressed_mom", Config(mode="uncompressed",
                                    virtual_momentum=0.9, **base)),
    ]:
        session = FederatedSession(cfg, params, loss_fn, mesh=mesh)
        acct = per_chip_state_floats(cfg, d, session.spec, n_devices)
        # measured: committed bytes of the persistent state on device 0
        dev0 = jax.devices()[0]
        measured = 0
        for leaf in session.state:
            if hasattr(leaf, "addressable_shards"):
                for sh in leaf.addressable_shards:
                    if sh.device == dev0:
                        measured += sh.data.nbytes
        _log({
            "part": "account", "config": name, "d": d,
            "n_devices": n_devices,
            "per_chip_floats": acct,
            "measured_dev0_bytes": int(measured),
            "measured_dev0_floats": int(measured // 4),
            "replicated_per_chip_floats": int(acct["replicated_equivalent"]),
            "ratio": round(acct["replicated_equivalent"] / acct["total"], 2),
        })


def run_chip(epochs=1):
    from commefficient_tpu.train import gpt2_train

    for name, extra in [
        ("sketch_fsdp", ["--fsdp", "true"]),
        ("sketch_replicated", []),
    ]:
        argv = [
            "--model", "gpt2", "--dataset_dir", "./data",
            "--num_epochs", str(epochs), "--pivot_epoch", "1",
            "--num_clients", "32", "--num_workers", "8",
            "--num_devices", "1", "--local_batch_size", "4",
            "--max_seq_len", "256", "--lr_scale", "0.32",
            "--seed", "42", "--topk_method", "threshold",
            "--mode", "sketch", "--error_type", "virtual",
            "--virtual_momentum", "0.9", "--k", "50000",
            "--num_rows", "5", "--num_cols", "5000000",
            "--fuse_clients", "true", "--device_data", "false",
        ] + extra
        t0 = time.time()
        val = gpt2_train.main(argv)
        dt = time.time() - t0
        _log({"part": "chip", "config": name, "epochs": epochs,
              "nll": round(float(val["nll"]), 4), "seconds": round(dt)})


def run_cpu_round(n_devices=8):
    from commefficient_tpu.utils.platform import force_virtual_cpu_devices

    force_virtual_cpu_devices(n_devices)
    import numpy as np

    from commefficient_tpu.models import gpt2_double_heads_loss
    from commefficient_tpu.parallel import FederatedSession, make_mesh, mask_gpt2
    from commefficient_tpu.utils.config import Config

    gcfg, model, params = _gpt2_small_params()
    loss_fn = gpt2_double_heads_loss(model.apply)
    mesh = make_mesh(n_devices)
    cfg = Config(
        mode="sketch", error_type="virtual", virtual_momentum=0.9,
        k=50_000, num_rows=5, num_cols=5_000_000,
        num_clients=2 * n_devices, num_workers=n_devices,
        num_devices=n_devices, local_batch_size=1, weight_decay=0.0,
        topk_method="threshold", device_data=False, fsdp=True,
    )
    session = FederatedSession(cfg, params, loss_fn, mesh=mesh,
                               mask_batch=mask_gpt2)
    rng = np.random.default_rng(0)
    T = 64
    ids = rng.integers(0, 50257, size=(n_devices, 1, 1, T)).astype(np.int32)
    lm = ids.copy()
    lm[..., : T // 2] = -100
    batch = {
        "input_ids": ids, "token_type_ids": ids, "lm_labels": lm,
        "mc_token_ids": np.full((n_devices, 1, 1), T - 1, np.int32),
        "mc_labels": np.zeros((n_devices, 1), np.int32),
    }
    client_ids = np.arange(n_devices, dtype=np.int32)
    t0 = time.time()
    m = session.train_round(client_ids, batch, lr=0.1)
    dt = time.time() - t0
    _log({"part": "cpu_round", "d": session.grad_size,
          "loss": round(float(np.asarray(m["loss"])), 4),
          "seconds": round(dt)})


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("cmd", choices=["account", "chip", "cpu_round"])
    ap.add_argument("--epochs", type=int, default=1)
    args = ap.parse_args()
    if args.cmd == "account":
        run_account()
    elif args.cmd == "chip":
        run_chip(epochs=args.epochs)
    else:
        run_cpu_round()


if __name__ == "__main__":
    main()
