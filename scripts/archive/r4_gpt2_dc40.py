import sys
from pathlib import Path
sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(1, str(Path(__file__).resolve().parents[1]))
from r4_gpt2_twin import run_one  # sibling in scripts/archive/
# d/c=40 + error_decay 0.9 at GPT-2 scale: 5 x 3.11M table (~8x upload
# compression), the envelope-extension claim run for real.
from commefficient_tpu.train import gpt2_train

argv = [
    "--model", "gpt2", "--dataset_dir", "./data",
    "--num_epochs", "6", "--pivot_epoch", "2",
    "--num_clients", "32", "--num_workers", "8",
    "--num_devices", "1", "--local_batch_size", "4",
    "--max_seq_len", "256", "--lr_scale", "0.32",
    "--seed", "42", "--topk_method", "threshold",
    "--mode", "sketch", "--error_type", "virtual", "--virtual_momentum", "0.9",
    "--k", "50000", "--num_rows", "5", "--num_cols", "3111111",
    "--fuse_clients", "true", "--error_decay", "0.9",
]
import json, time
t0 = time.time()
val = gpt2_train.main(argv)
print("==", json.dumps({"config": "sketch 5x3.11M dc40 decay0.9 lr0.32",
                        "nll": round(float(val["nll"]), 4),
                        "ppl": round(float(val["ppl"]), 1),
                        "mc_acc": round(float(val["mc_accuracy"]), 4),
                        "seconds": round(time.time() - t0)}), flush=True)
