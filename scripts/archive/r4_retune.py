"""Per-mode (lr, pivot) retune on the v3 concentrated task, r3_sweep
methodology (the paper tunes lr per compression config, FetchSGD §5).
Feeds the tuned schedules into scripts/accuracy_run.py's `sched` table.

    python scripts/archive/r4_retune.py all          # every mode's grid
    python scripts/archive/r4_retune.py sketch7      # one group
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(1, str(Path(__file__).resolve().parents[2] / "scripts"))

LOG = Path(__file__).resolve().parents[2] / "runs" / "r4_retune.log"

K = 50_000

GROUPS = {
    # name -> (cfg_kw, [(lr, pivot), ...])
    "uncompressed": (
        dict(mode="uncompressed", fuse_clients=True),
        [(0.4, 6), (0.6, 6), (1.0, 6)],  # 0.8:6 known: 0.8999
    ),
    "uncompressed_mom": (
        dict(mode="uncompressed", virtual_momentum=0.9, fuse_clients=True),
        [(0.06, 6), (0.1, 6), (0.15, 6)],
    ),
    "sketch5": (
        dict(mode="sketch", error_type="virtual", virtual_momentum=0.9,
             k=K, num_rows=5, num_cols=500_000, fuse_clients=True),
        [(0.04, 2), (0.08, 2), (0.15, 2)],
    ),
    "sketch7": (
        dict(mode="sketch", error_type="virtual", virtual_momentum=0.9,
             k=K, num_rows=7, num_cols=357_143, fuse_clients=True),
        [(0.06, 2), (0.1, 2), (0.15, 2), (0.2, 2)],
    ),
    "sketch_rho0": (
        dict(mode="sketch", error_type="virtual", virtual_momentum=0.0,
             k=K, num_rows=5, num_cols=500_000, fuse_clients=True),
        [(0.4, 6), (0.8, 6)],
    ),
    "true_topk": (
        dict(mode="true_topk", error_type="virtual", virtual_momentum=0.9,
             k=K, fuse_clients=True),
        [(0.04, 2), (0.1, 2), (0.15, 2)],
    ),
    "local_topk": (
        dict(mode="local_topk", error_type="local", k=K),
        [(0.4, 6), (0.8, 6)],
    ),
    # VERDICT r3 weak 4: the (dampen x rho) corners for true_topk at tuned
    # lr — is the AUTO dampen default actually the best corner? The
    # (rho=0.9, dampen=True) corner is the "true_topk" group above (AUTO
    # resolves to True for dense modes); rho=0 is the dampening-inert
    # baseline corner (momentum not carried round-to-round).
    "true_topk_nodampen": (
        dict(mode="true_topk", error_type="virtual", virtual_momentum=0.9,
             momentum_dampening=False, k=K, fuse_clients=True),
        [(0.04, 2), (0.02, 2)],
    ),
    "true_topk_rho0": (
        dict(mode="true_topk", error_type="virtual", virtual_momentum=0.0,
             k=K, fuse_clients=True),
        [(0.4, 6), (0.8, 6)],
    ),
    "fedavg": (
        dict(mode="fedavg", num_local_iters=4),
        [(0.4, 6), (0.8, 6)],
    ),
}


def run_one(name, cfg_kw, lr, pivot, epochs=24):
    from commefficient_tpu.train.cv_train import (
        build_model_and_data,
        build_session_and_sampler,
        train_loop,
    )
    from commefficient_tpu.utils.config import Config

    cfg = Config(
        dataset_name="cifar10", dataset_dir="./data", model="resnet9",
        num_epochs=epochs, num_clients=16, num_workers=8, num_devices=1,
        local_batch_size=64, weight_decay=5e-4, seed=42,
        topk_method="threshold", synthetic_variant="concentrated",
        lr_scale=lr, pivot_epoch=pivot, **cfg_kw,
    )
    train, test, real, model, params, loss_fn, augment = build_model_and_data(cfg)
    session, sampler = build_session_and_sampler(cfg, train, params, loss_fn, augment)
    t0 = time.time()
    val = train_loop(cfg, session, sampler, test)
    dt = time.time() - t0
    line = (f"{name} {lr}:{pivot}: acc={val.get('accuracy', float('nan')):.4f} "
            f"loss={val['loss']:.4f} ({dt:.0f}s)"
            + (" [REAL CIFAR]" if real else ""))
    print("==", line, flush=True)
    LOG.parent.mkdir(exist_ok=True)
    with LOG.open("a") as f:
        f.write(line + "\n")
    return val


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("group")
    ap.add_argument("--epochs", type=int, default=24)
    args = ap.parse_args()
    names = list(GROUPS) if args.group == "all" else [args.group]
    for n in names:
        cfg_kw, grid = GROUPS[n]
        for lr, piv in grid:
            run_one(n, cfg_kw, lr, piv, epochs=args.epochs)


if __name__ == "__main__":
    main()
