"""r5 BASELINE #3 redo (VERDICT r4 missing 2 / next-round item 2).

The r4 FEMNIST table compared an untuned uncompressed baseline (lr fixed
at local_topk's 0.2) against local_topk memorizing a ceiling-free stand-in
to 1.0000. This redo applies the repo's own methodology:

  * the stand-in now carries 6% within-client label noise (Bayes ceiling
    ~0.947 — data/emnist.py), so nothing can report 1.0000;
  * PER-MODE lr tuning with the doubling-grid protocol (r4_retune.py),
    extended past any edge optimum;
  * the final table quotes each mode at ITS OWN tuned lr, with the full
    grids appended for audit.

    python scripts/archive/r5_femnist.py grid            # both modes, doubling grid
    python scripts/archive/r5_femnist.py one --mode local_topk --lr 0.4
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(1, str(Path(__file__).resolve().parents[2] / "scripts"))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from labutil import ROOT, log_json

LOG = ROOT / "runs" / "r5_femnist.log"

MODES = {
    "local_topk": ["--mode", "local_topk", "--error_type", "local",
                   "--k", "20000"],
    "uncompressed": ["--mode", "uncompressed", "--fuse_clients", "true"],
}


def run_one(mode: str, lr: float, *, epochs=20, seed=42):
    from commefficient_tpu.train import cv_train

    t0 = time.time()
    val = cv_train.main([
        "--dataset_name", "femnist", "--model", "resnet9",
        "--num_clients", "100", "--num_workers", "8",
        "--num_devices", "1", "--local_batch_size", "16",
        "--num_epochs", str(epochs), "--lr_scale", str(lr),
        "--pivot_epoch", str(max(2, epochs // 4)),
        "--topk_method", "threshold", "--dataset_dir", "./data",
        "--weight_decay", "5e-4", "--seed", str(seed),
    ] + MODES[mode])
    dt = time.time() - t0
    rec = {"mode": mode, "lr": lr, "epochs": epochs,
           "acc": round(float(val.get("accuracy", float("nan"))), 4),
           "loss": round(float(val["loss"]), 4), "seconds": round(dt)}
    log_json(LOG, rec)
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("cmd", choices=["grid", "one"])
    ap.add_argument("--mode", default="local_topk")
    ap.add_argument("--lr", type=float, default=0.2)
    ap.add_argument("--epochs", type=int, default=20)
    args = ap.parse_args()

    if args.cmd == "one":
        run_one(args.mode, args.lr, epochs=args.epochs)
        return
    # doubling grids; extend manually past any edge optimum (`one`)
    for mode in ("uncompressed", "local_topk"):
        for lr in (0.05, 0.1, 0.2, 0.4, 0.8):
            run_one(mode, lr, epochs=args.epochs)


if __name__ == "__main__":
    main()
