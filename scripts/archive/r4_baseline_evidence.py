"""BASELINE #3 / #5 accuracy evidence (VERDICT r3 missing 3 / item 8):

  #3  FEMNIST naturally-non-IID local_topk (reference README command,
      data_utils/fed_emnist.py) — accuracy run on the LEAF data if present,
      else the naturally-non-IID synthetic stand-in.
  #5  ImageNet FixupResNet-50 fedavg — convergence run with the train-time
      RandomResizedCrop+flip augmentation path active (data/imagenet.py).

Appends result sections to ACCURACY.md (below the CIFAR table) and logs to
runs/r4_baseline_evidence.log.

    python scripts/archive/r4_baseline_evidence.py femnist
    python scripts/archive/r4_baseline_evidence.py imagenet
    python scripts/archive/r4_baseline_evidence.py all
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(1, str(Path(__file__).resolve().parents[2] / "scripts"))

ROOT = Path(__file__).resolve().parents[2]
LOG = ROOT / "runs" / "r4_baseline_evidence.log"


def _train(overrides):
    from commefficient_tpu.train import cv_train

    t0 = time.time()
    val = cv_train.main(overrides)
    return val, time.time() - t0


def run_femnist(epochs=20):
    """BASELINE #3: local_topk + local error on naturally-non-IID FEMNIST.
    100 clients (LEAF users), 8 participate/round — the reference's
    femnist README shape at synthetic-stand-in scale."""
    rows = []
    for name, mode_kw in [
        ("local_topk (k=20k, local err)", ["--mode", "local_topk",
                                           "--error_type", "local",
                                           "--k", "20000"]),
        ("uncompressed baseline", ["--mode", "uncompressed",
                                   "--fuse_clients", "true"]),
    ]:
        val, dt = _train([
            "--dataset_name", "femnist", "--model", "resnet9",
            "--num_clients", "100", "--num_workers", "8",
            "--num_devices", "1", "--local_batch_size", "16",
            "--num_epochs", str(epochs), "--lr_scale", "0.2",
            "--pivot_epoch", str(max(2, epochs // 4)),
            "--topk_method", "threshold", "--dataset_dir", "./data",
            "--weight_decay", "5e-4", "--seed", "42",
        ] + mode_kw)
        rows.append((name, val.get("accuracy", float("nan")), val["loss"], dt))
        _log(f"femnist {name}: acc={rows[-1][1]:.4f} ({dt:.0f}s)")
    return rows


def run_imagenet(epochs=12):
    """BASELINE #5: FixupResNet-50 fedavg on the ImageNet pipeline
    (synthetic fallback if no imagenet on disk), RRC+flip augmentation
    active via cv_train's ImageNetAugment wiring."""
    rows = []
    for name, mode_kw in [
        ("fedavg (4 local iters)", ["--mode", "fedavg",
                                    "--num_local_iters", "4"]),
        ("uncompressed baseline", ["--mode", "uncompressed",
                                   "--fuse_clients", "true"]),
    ]:
        val, dt = _train([
            "--dataset_name", "imagenet", "--model", "fixup_resnet50",
            "--num_classes", "100",
            "--num_clients", "16", "--num_workers", "8",
            "--num_devices", "1", "--local_batch_size", "16",
            "--num_epochs", str(epochs), "--lr_scale", "0.1",
            "--pivot_epoch", str(max(2, epochs // 4)),
            "--topk_method", "threshold", "--dataset_dir", "./data",
            "--weight_decay", "5e-4", "--seed", "42",
        ] + mode_kw)
        rows.append((name, val.get("accuracy", float("nan")), val["loss"], dt))
        _log(f"imagenet {name}: acc={rows[-1][1]:.4f} ({dt:.0f}s)")
    return rows


def _log(line):
    print("==", line, flush=True)
    LOG.parent.mkdir(exist_ok=True)
    with LOG.open("a") as f:
        f.write(line + "\n")


def _append_section(title: str, intro: str, rows, epochs: int):
    acc_md = ROOT / "ACCURACY.md"
    lines = ["", f"## {title}", "", intro, "",
             "| config | final val acc | final val loss | train time (s) |",
             "|---|---|---|---|"]
    for name, acc, loss, dt in rows:
        lines.append(f"| {name} | {acc:.4f} | {loss:.4f} | {dt:.0f} |")
    text = acc_md.read_text() if acc_md.exists() else ""
    marker = f"## {title}"
    if marker in text:  # regenerate in place
        head, _, rest = text.partition(marker)
        tail = ""
        nxt = rest.find("\n## ")
        if nxt != -1:
            tail = rest[nxt:]
        text = head.rstrip() + "\n" + "\n".join(lines[1:]) + tail
    else:
        text = text.rstrip() + "\n" + "\n".join(lines) + "\n"
    acc_md.write_text(text)
    print(f"wrote section: {title}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("which", choices=["femnist", "imagenet", "all"])
    ap.add_argument("--epochs", type=int, default=None)
    args = ap.parse_args()
    if args.which in ("femnist", "all"):
        e = args.epochs or 20
        rows = run_femnist(e)
        _append_section(
            "FEMNIST non-IID local_topk (BASELINE #3)",
            f"Naturally-non-IID FEMNIST (LEAF if on disk, else the per-user-"
            f"style synthetic stand-in), 100 clients / 8 per round, "
            f"{e} epochs, lr 0.2. local_topk uploads 2k floats/client "
            "vs D=6.6M uncompressed (~165x).",
            rows, e,
        )
    if args.which in ("imagenet", "all"):
        e = args.epochs or 12
        rows = run_imagenet(e)
        _append_section(
            "ImageNet FixupResNet-50 fedavg (BASELINE #5)",
            f"ImageNet pipeline (synthetic stand-in if no imagenet on disk) "
            f"with train-time RandomResizedCrop+flip active, FixupResNet-50 "
            f"(no BatchNorm — federated averaging safe), 16 clients / 8 per "
            f"round, {e} epochs.",
            rows, e,
        )


if __name__ == "__main__":
    main()
