"""r4 generator lab — find a concentrated-variant parameterization that
dense SGD can train to the label-noise ceiling (VERDICT r4 item 1, branch
"fix the generator": 24-epoch tuned dense SGD caps at ~0.61 train-acc 0.56
— underfitting — while local_topk fits to 0.93, so the stand-in fails to
reproduce real-CIFAR's dense-SGD trainability).

Mechanism under test: the rank-12 1/f background at pixel std 30 is a
low-rank nuisance subspace with enormous per-direction variance; the stable
lr is capped by those directions (divergence at lr>=1.2), starving the
class-signal directions — a conditioning pathology that per-coordinate
error-feedback methods (local_topk) sidestep.

    python scripts/archive/r4_gen_lab.py probe     # mechanism probes (bg ablation)
    python scripts/archive/r4_gen_lab.py one --bg_scale 10 --bg_rank 48 --lr 0.8
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(1, str(Path(__file__).resolve().parents[2] / "scripts"))

LOG = Path(__file__).resolve().parents[2] / "runs" / "r4_gen_lab.log"


def run_one(name: str, gen_kw: dict, *, mode="uncompressed", lr=0.8,
            pivot=6, epochs=24, k=50_000, seed=42, **cfg_kw):
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.data import FedDataset, augment_batch
    from commefficient_tpu.data.cifar import (
        CIFAR10_MEAN,
        CIFAR10_STD,
        _synthetic_cifar_concentrated,
        device_normalizer,
    )
    from commefficient_tpu.models import ResNet9, classification_loss
    from commefficient_tpu.train.cv_train import (
        build_session_and_sampler,
        train_loop,
    )
    from commefficient_tpu.utils.config import Config
    from commefficient_tpu.utils.logging import TableLogger

    base = dict(
        dataset_name="cifar10", model="resnet9", num_epochs=epochs,
        num_clients=16, num_workers=8, num_devices=1, local_batch_size=64,
        weight_decay=5e-4, seed=seed, topk_method="threshold",
        lr_scale=lr, pivot_epoch=pivot,
    )
    if mode == "local_topk":
        base.update(mode="local_topk", error_type="local", k=k)
    elif mode == "sketch":
        base.update(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                    k=k, fuse_clients=True)
    else:
        base.update(mode=mode, fuse_clients=True)
    base.update(cfg_kw)
    cfg = Config(**base)

    train_d, test_d = _synthetic_cifar_concentrated(10, **gen_kw)
    train = FedDataset(dict(train_d), cfg.num_clients, iid=True, seed=cfg.seed)
    test = FedDataset(dict(test_d), 1, iid=True, seed=cfg.seed)
    model = ResNet9(num_classes=10)
    params = model.init(jax.random.key(cfg.seed), jnp.zeros((1, 32, 32, 3)))
    loss_fn = classification_loss(
        model.apply, prep=device_normalizer(CIFAR10_MEAN, CIFAR10_STD)
    )
    session, sampler = build_session_and_sampler(
        cfg, train, params, loss_fn, augment_batch
    )
    t0 = time.time()
    val = train_loop(cfg, session, sampler, test, table=TableLogger())
    dt = time.time() - t0
    line = (f"{name}: acc={val.get('accuracy', float('nan')):.4f} "
            f"loss={val['loss']:.4f} ({dt:.0f}s) mode={mode} lr={lr}:{pivot} "
            f"e{epochs} gen={gen_kw}")
    print("==", line, flush=True)
    LOG.parent.mkdir(exist_ok=True)
    with LOG.open("a") as f:
        f.write(line + "\n")
    return val


SUITES = {
    # Mechanism: is the high-variance low-rank background what breaks dense
    # SGD? bg=0 isolates it; the others test "keep a background but spread
    # its variance" (higher rank at fixed total pixel std) and "shrink it".
    # RESULT (runs/r4_gen_lab.log): bg0 0.8510 / bg10 0.7931 / rank96
    # 0.6476 vs 0.6149 at bg30-rank12 — background variance IS the dense-
    # SGD killer; spreading its rank barely helps.
    "probe": [
        ("bg0", dict(bg_scale=0.0)),
        ("bg10", dict(bg_scale=10.0)),
        ("bg30_rank96", dict(bg_rank=96)),
    ],
    # Tune on the reduced-background tasks (the probe lrs were tuned on
    # bg30) + lower the irreducible-error knobs: patch_dropout 0.25 alone
    # makes ~1.6% of samples patchless (unclassifiable) and interacts with
    # cutout augmentation, so the honest ceiling sits below the label-noise
    # ceiling the accuracy table quotes.
    "tune": [
        ("bg0_lr1.2", dict(bg_scale=0.0), dict(lr=1.2)),
        ("bg0_mom_lr0.1", dict(bg_scale=0.0),
         dict(lr=0.1, virtual_momentum=0.9)),
        ("bg0_drop0.1", dict(bg_scale=0.0, patch_dropout=0.1), dict()),
        ("bg5", dict(bg_scale=5.0), dict()),
        ("bg10_mom_lr0.1", dict(bg_scale=10.0),
         dict(lr=0.1, virtual_momentum=0.9)),
        ("bg0_e48", dict(bg_scale=0.0), dict(epochs=48)),
    ],
    # v3 candidates: tune RESULT — dropout 0.25->0.1 recovers 5.5 pts
    # (0.8510 -> 0.9059 at bg0); any background costs (bg5 0.83, bg10
    # 0.79); momentum/longer-budget do NOT fix the background pathology
    # (bg10_mom 0.789; bg0_e48 0.836 < bg0_e24 0.851). Candidates keep a
    # small background if affordable, drop irreducibility, and test a
    # stronger class signal.
    "v3": [
        ("bg5_drop0.1", dict(bg_scale=5.0, patch_dropout=0.1), dict()),
        ("bg0_drop0.1_cs60", dict(bg_scale=0.0, patch_dropout=0.1,
                                  class_scale=60.0), dict()),
        ("bg5_drop0.1_cs60", dict(bg_scale=5.0, patch_dropout=0.1,
                                  class_scale=60.0), dict()),
        ("bg0_drop0.1_lr0.6", dict(bg_scale=0.0, patch_dropout=0.1),
         dict(lr=0.6)),
    ],
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("suite")
    ap.add_argument("--mode", default="uncompressed")
    ap.add_argument("--lr", type=float, default=0.8)
    ap.add_argument("--pivot", type=int, default=6)
    ap.add_argument("--epochs", type=int, default=24)
    ap.add_argument("--bg_scale", type=float, default=None)
    ap.add_argument("--bg_rank", type=int, default=None)
    ap.add_argument("--class_scale", type=float, default=None)
    ap.add_argument("--noise_scale", type=float, default=None)
    ap.add_argument("--patches_per_class", type=int, default=None)
    args = ap.parse_args()

    if args.suite == "one":
        gen_kw = {
            k: getattr(args, k)
            for k in ("bg_scale", "bg_rank", "class_scale", "noise_scale",
                      "patches_per_class")
            if getattr(args, k) is not None
        }
        run_one(
            f"{args.mode}_{args.lr}p{args.pivot}_e{args.epochs}_{gen_kw}",
            gen_kw, mode=args.mode, lr=args.lr, pivot=args.pivot,
            epochs=args.epochs,
        )
        return
    for spec in SUITES[args.suite]:
        name, gen_kw = spec[0], spec[1]
        run_kw = dict(spec[2]) if len(spec) > 2 else {}
        lr = run_kw.pop("lr", args.lr)
        epochs = run_kw.pop("epochs", args.epochs)
        run_kw.setdefault("mode", args.mode)
        run_kw.setdefault("pivot", args.pivot)
        run_one(name, gen_kw, lr=lr, epochs=epochs, **run_kw)


if __name__ == "__main__":
    main()
