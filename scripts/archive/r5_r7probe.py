"""r5: where does r=7x357k's disproportionate cost go? (VERDICT item 5)

Full-run arithmetic says r=5x500k pays +59 s over uncompressed on the
24-ep CV run while r=7x357k pays +165 s — 2.8x, where row-linear would be
1.4x. Suspect: the GEOMETRY. The adaptive chunk rule grows m until each
chunk owns >= 256 buckets; at c=357k that regime differs from c=500k
(bigger m -> wider [nc, m] x [m, V] einsums per row and a different
scramble-block realization).

This probe prints the realized geometry and scan-timed sketch_vec /
estimate_all for the two production specs plus r=7 variants with pinned
m and band, so the fix (if any) is a measured geometry pin, not a guess.

    python scripts/archive/r5_r7probe.py
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))
sys.path.insert(1, str(Path(__file__).resolve().parents[2] / "scripts"))
sys.path.insert(0, str(Path(__file__).resolve().parent))

import jax
import jax.numpy as jnp
import numpy as np

from profile_scan import scan_time  # the carry-chained lax.scan harness

from labutil import ROOT, log_json

LOG = ROOT / "runs" / "r5_r7probe.log"


def probe(name, spec, v, n=20):
    from commefficient_tpu.ops.countsketch import estimate_all, sketch_vec

    table = jax.jit(lambda x: sketch_vec(spec, x))(v)
    geo = dict(r=spec.r, c=spec.c, c_actual=spec.c_actual, m=spec.chunk_m,
               sblock=spec.sblock, band=spec.band,
               s=spec.s, d_eff=spec.d_eff)
    t_sk = scan_time(f"{name} sketch_vec",
                     lambda s: jnp.sum(sketch_vec(spec, v + s)), n)
    t_es = scan_time(f"{name} estimate_all",
                     lambda s: jnp.sum(estimate_all(spec, table + s)), n)
    log_json(LOG, {"name": name, **geo,
                   "sketch_ms": round(t_sk, 2), "estimate_ms": round(t_es, 2)})


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--gpt2", action="store_true",
                    help="probe the GPT-2-scale 5x5M geometry instead "
                    "(the floor binds there too: c=5M forces m=8192)")
    args = ap.parse_args()

    print(f"devices: {jax.devices()}")
    from commefficient_tpu.ops.countsketch import CountSketch

    if args.gpt2:
        d = 124_444_417  # GPT-2-small twin grad size
        rng = np.random.default_rng(0)
        v = jnp.asarray(rng.normal(size=(d,)).astype(np.float32))
        scan_time("empty scan (overhead floor)", lambda s: s, 8)
        probe("g_r5x5M_default", CountSketch(d=d, c=5_000_000, r=5, seed=42),
              v, 8)
        probe("g_r5x5M_m4096",
              CountSketch(d=d, c=5_000_000, r=5, seed=42, m=4096), v, 8)
        probe("g_r5x5M_m4096_band24",
              CountSketch(d=d, c=5_000_000, r=5, seed=42, m=4096, band=24),
              v, 8)
        return

    d = 6_598_654  # ResNet-9 CV grad size (the accuracy-table model)
    rng = np.random.default_rng(0)
    v = jnp.asarray(rng.normal(size=(d,)).astype(np.float32))
    scan_time("empty scan (overhead floor)", lambda s: s)
    probe("r5x500k_default", CountSketch(d=d, c=500_000, r=5, seed=42), v)
    probe("r7x357k_default", CountSketch(d=d, c=357_143, r=7, seed=42), v)
    for m in (2048, 4096, 8192):
        probe(f"r7x357k_m{m}",
              CountSketch(d=d, c=357_143, r=7, seed=42, m=m), v)
    probe("r7x357k_band8",
          CountSketch(d=d, c=357_143, r=7, seed=42, band=8), v)
    probe("r5x500k_band8",
          CountSketch(d=d, c=500_000, r=5, seed=42, band=8), v)


if __name__ == "__main__":
    main()
