"""Turn one run directory into a versioned ``run_report.json``.

The critical-path CLI (telemetry/trace.py owns the analysis; this script
only drives it): reads whatever artifacts the run dir holds —
``spans_*.json`` (the per-round stage decomposition), ``metrics.jsonl``
(the anomaly series), ``flight_*`` / ``perf_report.json`` (provenance) —
and writes ``run_report.json`` next to them:

  * per-stage exclusive-time p50/p95 over the analyzed rounds,
  * critical-path attribution fractions summing to 1 (idle included —
    unattributed wall-clock is a finding, not a rounding error),
  * the modal binding stage + per-stage binding counts,
  * anomaly flags: stall spikes (pipeline/host_stall_ms), staleness
    drift (async/staleness_mean), cache-hit collapse
    (clientstore/cache_hit_rate).

    python scripts/analyze_run.py RUN_DIR [RUN_DIR ...] [--out NAME]

``--out`` renames the report file inside each run dir (default
``run_report.json``). The last stdout line is ALWAYS a machine-readable
JSON summary — ``{"kind": "analyze_run", "run_dirs": N, "reports": M,
"failures": [...]}`` — on every exit path, the gate-script contract
scripts/check_bench_regression.py established. Reports validate under
``scripts/check_telemetry_schema.py`` (schema v11 validate_run_report).
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _human_lines(report: dict) -> None:
    stages = report["stages"]
    print(f"{report['run_dir']}: {report['rounds_analyzed']} round(s) "
          f"analyzed, critical stage: {report['critical_stage']}")
    for name, blk in stages.items():
        print(f"  {name:11s} p50 {blk['p50_ms']:9.3f} ms   "
              f"p95 {blk['p95_ms']:9.3f} ms   "
              f"{100.0 * blk['fraction']:5.1f}% of wall")
    for a in report["anomalies"]:
        print(f"  ANOMALY [{a['kind']}] {a['metric']}: {a['detail']}")


def main(argv) -> int:
    def summary_line(**kw):
        print(json.dumps({"kind": "analyze_run", **kw}))

    out_name = "run_report.json"
    if "--out" in argv:
        i = argv.index("--out")
        if i + 1 >= len(argv):
            print(__doc__)
            summary_line(run_dirs=0, reports=0, failures=[],
                         error="--out needs a file name")
            return 2
        out_name = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    if not argv:
        print(__doc__)
        summary_line(run_dirs=0, reports=0, failures=[],
                     error="usage: pass one or more run dirs")
        return 2

    # heavy import AFTER usage handling so `analyze_run.py` with no args
    # answers instantly even where jax takes seconds to import
    from commefficient_tpu.telemetry import build_run_report, jsonable_tree

    rc = 0
    reports = 0
    failures = []
    for run_dir in argv:
        try:
            report = build_run_report(run_dir,
                                      generated_by="scripts/analyze_run.py")
            path = os.path.join(run_dir, out_name)
            with open(path, "w") as f:
                json.dump(jsonable_tree(report), f, indent=1,
                          allow_nan=False)
            _human_lines(report)
            print(f"wrote {path}")
            reports += 1
        # ValueError covers an empty/corrupt run dir (build_run_report
        # raises it, json decode errors subclass it); OSError an
        # unreadable path — each fails THIS dir and still ends stdout
        # with the summary line instead of a traceback
        except (OSError, ValueError) as e:
            print(f"FAIL {run_dir}: {e}")
            failures.append(f"{run_dir}: {e}")
            rc = 1
    summary_line(run_dirs=len(argv), reports=reports, failures=failures)
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
