"""Lint: registry-keyed dispatch must not leak out of its home package.

The compress/ registry refactor (PR 2) moved every mode's algebra behind
``compress.get_compressor``; the control/ subsystem (PR 8) did the same
for rung-selection policies behind ``control.policy.get_policy``; the
resilience/ subsystem (PR 10) for recovery policies behind
``resilience.policy.get_recovery_policy``. The invariant that keeps a new
compressor (or policy) a one-file PR is that NOBODY else branches on the
registry's key strings. This script walks the ``commefficient_tpu``
package ASTs and fails on any

  * comparison involving a dispatch name/attribute
    (``cfg.mode == "sketch"``, ``mode != 'fedavg'``,
    ``cfg.control_policy in (...)``),
  * dict/registry subscript keyed by a dispatch expression
    (``{...}[cfg.mode]``, ``POLICIES[cfg.control_policy]``),
  * ``match cfg.mode:`` / ``match cfg.control_policy:`` statement,

outside that family's allowlist:

  * ``mode``           -> ``compress/`` (the registry owns mode dispatch)
                          + ``utils/config.py`` (CLI validation and
                          mode-derived conveniences like
                          ``round_microbatches`` live with the flags)
  * ``control_policy`` -> ``control/`` (the policy registry)
                          + ``utils/config.py`` (flag validation; other
                          layers gate on ``cfg.control_enabled``)
  * ``recover_policy`` -> ``resilience/`` (the recovery-policy registry)
                          + ``utils/config.py`` (flag validation; other
                          layers gate on ``cfg.recovery_enabled``)

AST-based so docstrings/comments that merely MENTION modes or policies
never false-positive.

Scope is the library package only: tests, bench.py, and scripts are
harnesses that parametrize over modes by construction. Wired into tier-1
via tests/test_mode_dispatch.py.

    python scripts/check_mode_dispatch.py        # exit 1 on violations
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PACKAGE = REPO / "commefficient_tpu"

# dispatch family -> (paths, relative to the package root, where that
# family's dispatch is LEGAL)
FAMILIES = {
    "mode": ("compress/", "utils/config.py"),
    "control_policy": ("control/", "utils/config.py"),
    "recover_policy": ("resilience/", "utils/config.py"),
}


def _dispatch_name(node: ast.AST):
    """The family name for expressions naming a dispatch key (``mode``,
    ``*.mode``, ``control_policy``, ``*.control_policy``), else None."""
    if isinstance(node, ast.Name) and node.id in FAMILIES:
        return node.id
    if isinstance(node, ast.Attribute) and node.attr in FAMILIES:
        return node.attr
    return None


def scan_file(path: Path, families=None) -> list:
    """[(lineno, family, snippet)] of dispatch violations in one file.
    ``families``: restrict to these family names (default: all)."""
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:  # a broken file is its own CI problem
        return [(e.lineno or 0, "?", f"unparseable: {e.msg}")]
    lines = src.splitlines()
    out = []

    def hit(node, family):
        if families is not None and family not in families:
            return
        ln = getattr(node, "lineno", 0)
        snippet = lines[ln - 1].strip() if 0 < ln <= len(lines) else ""
        out.append((ln, family, snippet))

    for node in ast.walk(tree):
        if isinstance(node, ast.Compare):
            for expr in [node.left, *node.comparators]:
                fam = _dispatch_name(expr)
                if fam is not None:
                    hit(node, fam)
                    break
        elif isinstance(node, ast.Subscript):
            fam = _dispatch_name(node.slice)
            if fam is not None:
                hit(node, fam)
        elif isinstance(node, ast.Match):
            fam = _dispatch_name(node.subject)
            if fam is not None:
                hit(node, fam)
    return sorted(out)  # ast.walk is BFS; report in source order


def scan_package(package_root: Path = PACKAGE) -> dict:
    """{relative_path: [(lineno, family, snippet)]} over the package,
    per-family allowlists applied."""
    violations = {}
    for path in sorted(package_root.rglob("*.py")):
        rel = path.relative_to(package_root).as_posix()
        # only lint each family where its own allowlist does NOT cover
        # this file — a file may be home to one family and off-limits to
        # the other (utils/config.py is allowlisted for both; control/
        # may validate policies but not branch on cfg.mode)
        banned = tuple(
            fam for fam, allowed in FAMILIES.items()
            if not any(rel == a or rel.startswith(a) for a in allowed)
        )
        if not banned:
            continue
        hits = scan_file(path, families=banned)
        if hits:
            violations[rel] = hits
    return violations


def main() -> int:
    violations = scan_package()
    for rel, hits in violations.items():
        for ln, fam, snippet in hits:
            home = FAMILIES.get(fam, ("?",))[0]
            print(f"commefficient_tpu/{rel}:{ln}: {fam}-string dispatch "
                  f"outside {home}: {snippet}")
    if violations:
        n = sum(len(h) for h in violations.values())
        print(f"\n{n} violation(s). Mode dispatch belongs in "
              "commefficient_tpu/compress/ (the registry), control-policy "
              "dispatch in commefficient_tpu/control/, recovery-policy "
              "dispatch in commefficient_tpu/resilience/, or "
              "utils/config.py (flag validation/conveniences); route "
              "other layers through compress.get_compressor / "
              "control.build_controller / resilience.build_resilience / "
              "Config properties (cfg.control_enabled, "
              "cfg.recovery_enabled, cfg.round_microbatches).")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
