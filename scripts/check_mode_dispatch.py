"""Lint: registry-keyed dispatch must not leak out of its home package.

Since the invariant-linter PR this script is a THIN SHIM over the
framework analyzer ``commefficient_tpu/analysis/dispatch.py`` (the
``registry-dispatch`` rule of ``python -m commefficient_tpu.analysis``),
which carries the full rationale and the family allowlists. The CLI and
exit semantics here are unchanged from the original script:

    python scripts/check_mode_dispatch.py        # exit 1 on violations

  * exit 0 — no violations; 1 — violations (one prose line each, plus
    the routing epilogue); 2 — usage error (the script takes no args).
  * ``scan_file(path, families=None)`` and ``scan_package()`` keep
    their original signatures and return shapes (re-exported from the
    analyzer), so tests/test_mode_dispatch.py and any caller importing
    this file keep working unchanged.
  * NEW: the last stdout line is a machine-readable JSON summary
    ``{"kind": "mode_dispatch", "violations": N, "files": M, ...}`` on
    EVERY exit path — the same consumer contract as
    scripts/check_bench_regression.py and the analysis CLI.

Violations honor the framework pragma grammar
(``# lint: allow[registry-dispatch] <reason>``), like every other rule.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from commefficient_tpu.analysis import dispatch as _dispatch  # noqa: E402
from commefficient_tpu.analysis.core import PackageIndex  # noqa: E402

# re-exports: the original module-level API, now framework-backed
FAMILIES = _dispatch.FAMILIES
PACKAGE = _dispatch.PACKAGE
scan_file = _dispatch.scan_file
scan_package = _dispatch.scan_package


def _summary_line(**kw) -> None:
    print(json.dumps({"kind": "mode_dispatch", **kw}))


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv:
        msg = f"usage: {Path(__file__).name} takes no arguments"
        print(msg)
        _summary_line(violations=0, files=0, findings=[], error=msg)
        return 2
    index = PackageIndex(PACKAGE)
    # an unparseable package file fails the gate (original-script
    # semantics: "a broken file is its own CI problem" — it could hide
    # any amount of dispatch), alongside the dispatch findings proper
    findings = index.parse_findings()
    findings += [f for f in _dispatch.analyze(index)
                 if not index.suppressed(f)]
    findings.sort()
    for f in findings:
        print(f"commefficient_tpu/{f.path}:{f.lineno}: "
              f"{f.message.split(' — ')[0]}: {f.snippet}")
    if findings:
        print(f"\n{len(findings)} violation(s). Mode dispatch belongs in "
              "commefficient_tpu/compress/ (the registry), control-policy "
              "dispatch in commefficient_tpu/control/, recovery-policy "
              "dispatch in commefficient_tpu/resilience/, or "
              "utils/config.py (flag validation/conveniences); route "
              "other layers through compress.get_compressor / "
              "control.build_controller / resilience.build_resilience / "
              "Config properties (cfg.control_enabled, "
              "cfg.recovery_enabled, cfg.round_microbatches).")
    _summary_line(
        violations=len(findings),
        files=len({f.path for f in findings}),
        findings=[f.to_dict() for f in findings],
    )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
