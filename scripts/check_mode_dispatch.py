"""Lint: compression-mode dispatch must not leak out of compress/.

The compress/ registry refactor (PR 2) moved every mode's algebra behind
``compress.get_compressor``; the invariant that keeps a new compressor a
one-file PR is that NOBODY else branches on mode strings. This script
walks the ``commefficient_tpu`` package ASTs and fails on any

  * comparison involving a ``mode`` name/attribute
    (``cfg.mode == "sketch"``, ``mode != 'fedavg'``, ``cfg.mode in (...)``),
  * dict/registry subscript keyed by a ``mode`` expression
    (``{...}[cfg.mode]``),
  * ``match cfg.mode:`` statement,

outside the allowlist: ``compress/`` (the registry owns mode dispatch) and
``utils/config.py`` (CLI validation + mode-derived conveniences like
``round_microbatches`` live with the flag definitions). AST-based so
docstrings/comments that merely MENTION modes never false-positive.

Scope is the library package only: tests, bench.py, and scripts are
harnesses that parametrize over modes by construction. Wired into tier-1
via tests/test_mode_dispatch.py.

    python scripts/check_mode_dispatch.py        # exit 1 on violations
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PACKAGE = REPO / "commefficient_tpu"

# paths (relative to the package root) where mode dispatch is LEGAL
ALLOWED = ("compress/", "utils/config.py")


def _is_modeish(node: ast.AST) -> bool:
    """True for expressions naming the mode: ``mode``, ``*.mode``."""
    if isinstance(node, ast.Name) and node.id == "mode":
        return True
    if isinstance(node, ast.Attribute) and node.attr == "mode":
        return True
    return False


def scan_file(path: Path) -> list:
    """[(lineno, snippet)] of mode-dispatch violations in one file."""
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:  # a broken file is its own CI problem
        return [(e.lineno or 0, f"unparseable: {e.msg}")]
    lines = src.splitlines()
    out = []

    def hit(node):
        ln = getattr(node, "lineno", 0)
        snippet = lines[ln - 1].strip() if 0 < ln <= len(lines) else ""
        out.append((ln, snippet))

    for node in ast.walk(tree):
        if isinstance(node, ast.Compare):
            if _is_modeish(node.left) or any(
                _is_modeish(c) for c in node.comparators
            ):
                hit(node)
        elif isinstance(node, ast.Subscript):
            if _is_modeish(node.slice):
                hit(node)
        elif isinstance(node, ast.Match):
            if _is_modeish(node.subject):
                hit(node)
    return out


def scan_package(package_root: Path = PACKAGE) -> dict:
    """{relative_path: [(lineno, snippet)]} over the package, allowlist
    applied."""
    violations = {}
    for path in sorted(package_root.rglob("*.py")):
        rel = path.relative_to(package_root).as_posix()
        if any(rel == a or rel.startswith(a) for a in ALLOWED):
            continue
        hits = scan_file(path)
        if hits:
            violations[rel] = hits
    return violations


def main() -> int:
    violations = scan_package()
    for rel, hits in violations.items():
        for ln, snippet in hits:
            print(f"commefficient_tpu/{rel}:{ln}: mode-string dispatch "
                  f"outside compress/: {snippet}")
    if violations:
        n = sum(len(h) for h in violations.values())
        print(f"\n{n} violation(s). Mode dispatch belongs in "
              "commefficient_tpu/compress/ (the registry) or "
              "utils/config.py (flag validation/conveniences); route "
              "other layers through compress.get_compressor / Config "
              "properties.")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
