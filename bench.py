"""Benchmark: federated ResNet-9/CIFAR-10 training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Measures the headline metric from BASELINE.json — samples/sec/chip of the
full federated training step (8 virtual workers multiplexed on the chip,
sketch-mode compression + server unsketch update, the FetchSGD hot path) on
real CIFAR-shaped data. ``vs_baseline`` normalizes against an A100-class
reference throughput for ResNet-9 federated training (the reference
publishes no tables — BASELINE.json ``published: {}`` — so the denominator
is the documented estimate below, not a measured upstream number).

r2 changes: the round uses the TPU fast paths — banded matmul CountSketch
(ops/countsketch.py v5: one [m, V] one-hot einsum + overlap-add per row;
the band buys FetchSGD-stable collision statistics at some MXU cost — see
the module postmortem), threshold top-k selection (ops/topk.py: no sort,
no scatter), and the fused flattened-batch gradient (round.py
fuse_clients, numerically identical here — pinned by tests). Methodology
is the same python-loop dispatch as r1 with one scalar-fetch fence at the
end (steady-state pipelined dispatch); a lax.scan-of-rounds variant was
measured ~50x slower through the axon tunnel runtime
(scripts/profile_scan.py) and is NOT used.
"""

from __future__ import annotations

import json
import time

import numpy as np

# A100-class ResNet-9 CIFAR training throughput (samples/s) — cifar10-fast
# lineage trains 50k x ~25 epochs in ~60-75 s on one fast GPU (~17-20k
# samples/s); the reference's federated wrapper adds compression overhead.
# Used only as a fixed denominator so vs_baseline is comparable across rounds.
BASELINE_SAMPLES_PER_SEC = 20_000.0


def main():
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.models import ResNet9, classification_loss
    from commefficient_tpu.parallel import FederatedSession, make_mesh
    from commefficient_tpu.utils.config import Config

    # 8 virtual workers x 256-sample local batches (FetchSGD's CIFAR configs
    # run local batches up to 500/client, paper §5) = 2048 samples/round.
    workers, batch = 8, 256
    cfg = Config(
        mode="sketch",
        error_type="virtual",
        virtual_momentum=0.9,
        k=50_000,
        num_rows=5,
        num_cols=500_000,
        num_blocks=4,
        topk_method="threshold",
        fuse_clients=True,
        num_clients=2 * workers,
        num_workers=workers,
        num_devices=1,
        local_batch_size=batch,
        weight_decay=5e-4,
    )
    model = ResNet9(num_classes=10)
    params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    loss_fn = classification_loss(model.apply)
    session = FederatedSession(cfg, params, loss_fn, mesh=make_mesh(1))

    rng = np.random.default_rng(0)
    # Device-resident batch: models a prefetching input pipeline (the steady
    # state of real training, where H2D overlaps compute). The round itself —
    # grads, compression, aggregation, server update — is what's timed.
    ids = jnp.asarray(
        rng.choice(cfg.num_clients, size=workers, replace=False).astype(np.int32)
    )
    data = {
        "x": jnp.asarray(rng.normal(size=(workers, batch, 32, 32, 3)).astype(np.float32)),
        "y": jnp.asarray(rng.integers(0, 10, size=(workers, batch)).astype(np.int32)),
    }
    state, round_fn = session.state, session.round_fn
    lr = jnp.float32(0.1)

    # compile + warmup: the first TWO calls compile (donated-buffer layouts
    # differ between the fresh state and the returned state), so warm both.
    # NB: block_until_ready is unreliable through the axon tunnel; a scalar
    # fetch is the only trustworthy fence.
    for _ in range(3):
        state, m = round_fn(state, ids, data, lr)
        assert np.isfinite(float(m["loss"]))

    n_rounds = 20
    t0 = time.perf_counter()
    for _ in range(n_rounds):
        state, m = round_fn(state, ids, data, lr)
    assert np.isfinite(float(m["loss"]))  # fence
    dt = time.perf_counter() - t0

    samples_per_sec = n_rounds * workers * batch / dt
    print(
        json.dumps(
            {
                "metric": "fed_resnet9_sketch_train_samples_per_sec_per_chip",
                "value": round(samples_per_sec, 2),
                "unit": "samples/s",
                "vs_baseline": round(samples_per_sec / BASELINE_SAMPLES_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
