"""Benchmark: federated ResNet-9/CIFAR-10 training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Measures the headline metric from BASELINE.json — samples/sec/chip of the
full federated training step (8 virtual workers multiplexed on the chip,
sketch-mode compression + server unsketch update, the FetchSGD hot path) on
real CIFAR-shaped data. ``vs_baseline`` normalizes against an A100-class
reference throughput for ResNet-9 federated training (the reference
publishes no tables — BASELINE.json ``published: {}`` — so the denominator
is the documented estimate below, not a measured upstream number).

r2 changes: the round uses the TPU fast paths — banded matmul CountSketch
(ops/countsketch.py v5: one [m, V] one-hot einsum + overlap-add per row;
the band buys FetchSGD-stable collision statistics at some MXU cost — see
the module postmortem), threshold top-k selection (ops/topk.py: no sort,
no scatter), and the fused flattened-batch gradient (round.py
fuse_clients, numerically identical here — pinned by tests). Methodology
is the same python-loop dispatch as r1 with one scalar-fetch fence at the
end (steady-state pipelined dispatch) for the CV headline; the r2 note
that a lax.scan-of-rounds variant measured ~50x slower held for the
axon-tunnel runtime of that round (scripts/profile_scan.py) — the
sketch-gap PR re-opens the question per chip with the opt-in scan
engine (pipeline/scan_engine.py) and the ``gpt2_sketch_scan_*`` leg
below, which MEASURES the scan dispatch win/loss on the bench chip
instead of assuming either way (the CV headline methodology is
unchanged).

Pipelined leg (pipeline/ PR): ``sketch_pipelined_*`` keys on the headline
line measure the depth-2 pipelined engine against its synchronous twin on
the SAME session — both paying real per-round host work (sampler batch
assembly + H2D), since that host serial time is what the pipeline hides;
the engine's mean occupancy and residual host stall ride along
(check_bench_regression gates samples/s + occupancy).

GPT-2 legs: the BASELINE #4 sketch round rides the headline line per
SKETCH BACKEND (einsum = legacy keys, pallas = ``gpt2_sketch_pallas_*``)
next to its uncompressed twin — the r5 VERDICT's 3.5x sketch-round gap is
a kernel property, so both realizations are tracked. Since the sketch-gap
PR the sketch legs run the OPTIMIZED hot path (sketch_fused_bwd: per-leaf
cotangent sketches replace the flat [D] grad concat; bf16 tables with
f32 accumulation: half the table HBM + psum bytes at unchanged num_cols
— below iso-bytes), and a ``gpt2_sketch_scan_*`` leg times 8 rounds per
lax.scan dispatch (the scan-engine amortization). The 0.6x
``gpt2_sketch_vs_uncompressed`` target is gated by
scripts/check_bench_regression.py once the first optimized record lands.
On CPU hosts the GPT-2 legs auto-skip (``gpt2_skipped`` key;
--gpt2/--no-gpt2 override).
"""

from __future__ import annotations

import argparse
import functools
import json
import time

import numpy as np

# A100-class ResNet-9 CIFAR training throughput (samples/s) — cifar10-fast
# lineage trains 50k x ~25 epochs in ~60-75 s on one fast GPU (~17-20k
# samples/s); the reference's federated wrapper adds compression overhead.
# Used only as a fixed denominator so vs_baseline is comparable across rounds.
BASELINE_SAMPLES_PER_SEC = 20_000.0

# Peak dense matmul throughput of the bench chip, for the MFU line
# (VERDICT r3 weak 5: anchor perf to hardware, not to the estimate above).
# TPU v5e (v5 lite): 197 TFLOP/s bf16 / 394 int8 (public spec). The table
# itself lives in telemetry/xla_audit.py since the compiled-graph
# observability PR, so bench, profile_round and the audit share one
# denominator; a chip we don't recognize falls back to v5e's.


def _chip_peak_flops() -> tuple[float, str, bool]:
    """(peak bf16 FLOP/s, device_kind, fallback_used). ADVICE r4: an
    unrecognized chip silently got v5e's peak and the MFU line was wrong
    with no indication — now the kind and any fallback are reported."""
    from commefficient_tpu.telemetry.xla_audit import chip_peak_flops

    return chip_peak_flops()


def _audit_leg(session, ids, batch, sec_per_round):
    """Audited keys for one bench leg from the COMPILED round artifact
    (telemetry/xla_audit.py): the compiler's own FLOP count and the
    derived peak-HBM next to the legacy hand-model numbers, so the two
    can be diffed across rounds. NB ``cost_analysis()`` reports the
    PER-DEVICE SPMD module (verified on the 8-dev CPU mesh), so audited
    MFU is per-device FLOPs over ONE chip's peak — no device-count
    division (dividing by nd again under-reported multichip legs nd-fold)
    — and ``audited_flops_per_round`` is the per-device figure, which on
    replicated sections counts each chip's redundant copy of the work.
    Failures degrade to an ``audit_error`` key — the measured row must
    survive a broken analysis. Returns (keys dict, audit | None)."""
    from commefficient_tpu.telemetry.xla_audit import audited_mfu

    try:
        audit = session.audit_compiled_round(ids, batch, 0.1)
    except Exception as e:  # noqa: BLE001
        return {"audit_error": f"{type(e).__name__}: {e}"[:200]}, None
    out = {}
    flops = audit.cost.get("flops")
    if flops is not None:
        out["audited_flops_per_round"] = flops
        if sec_per_round:
            peak, _, _ = _chip_peak_flops()
            out["audited_mfu"] = round(
                audited_mfu(flops, sec_per_round, peak), 4
            )
    if audit.memory.get("peak_hbm_bytes") is not None:
        out["audited_peak_hbm_bytes"] = audit.memory["peak_hbm_bytes"]
    out["audited_collective_bytes"] = audit.collectives["total_bytes"]
    return out, audit


def resnet9_train_flops_per_sample() -> float:
    """Analytic fwd+bwd FLOPs/sample for ResNet-9 at 32x32 (the model term
    of the MFU line; sketch/top-k FLOPs are excluded, so sketch-mode MFU is
    an UNDERestimate of chip utilization — the conservative direction).

    Convs: 2*H*W*Cin*Cout*9 each; backward ~2x forward (dL/dx + dL/dW).
    """
    convs = [
        (32, 3, 64),     # prep
        (32, 64, 128),   # layer1 conv (pool after)
        (16, 128, 128), (16, 128, 128),   # residual 1
        (16, 128, 256),  # layer2 conv (pool after)
        (8, 256, 512),   # layer3 conv (pool after)
        (4, 512, 512), (4, 512, 512),     # residual 2
    ]
    fwd = sum(2 * h * h * cin * cout * 9 for h, cin, cout in convs)
    fwd += 2 * 512 * 10  # head
    return 3.0 * fwd  # fwd + ~2x for backward


def gpt2_flops_per_token(n_params: int, n_layer: int, n_embd: int,
                         seq: int) -> float:
    """Analytic train (fwd+bwd) FLOPs per processed token for the GPT-2
    double-heads model: ``6*D + 12*L*T*E``.

    6*D with D = TOTAL params (incl. embeddings) is the right count here,
    not an overcount: the input embedding rows do no matmul FLOPs, but the
    TIED lm_head matmul (2*V*E/token fwd) almost exactly replaces them
    (V*E ~ the embedding table), so 6*D_total ~ 6*D_nonemb + 6*V*E. The
    12*L*T*E term is the QK^T/AV attention work (4*T*E per layer fwd, x3
    for backward). Sketch/compression FLOPs are EXCLUDED, as in the
    ResNet-9 MFU line — the conservative direction."""
    return 6.0 * n_params + 12.0 * n_layer * seq * n_embd


def _measure_gpt2(mode: str, n_rounds: int = 10, sketch_backend: str = "einsum",
                  scan_rounds: int = 0):
    """tokens/s + MFU of the full federated GPT-2-small round (one chip),
    sketch 5x5M (the BASELINE #4 shape) or uncompressed. ``sketch_backend``
    picks the CountSketch kernel realization (einsum | pallas) — the r5+
    sketch-round gap is a kernel property, so the bench carries both.

    Since the sketch-gap PR the sketch legs run the OPTIMIZED hot path
    (sketch_fused_bwd + bf16 tables — the configuration the
    gpt2_sketch_vs_uncompressed >= 0.6 target is gated on; bytes are
    BELOW iso: bf16 halves the psum payload at unchanged num_cols), and
    ``scan_rounds`` > 1 times K rounds per dispatch through a
    lax.scan-of-rounds block (the scan-engine dispatch amortization,
    pipeline/scan_engine.py — fixed staged batch, so the leg isolates
    dispatch overhead exactly).
    Returns (tokens_per_sec, mfu, seconds_per_round, audited-keys dict)."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.models import gpt2_double_heads_loss
    from commefficient_tpu.models.gpt2 import GPT2Config, GPT2DoubleHeads
    from commefficient_tpu.ops.param_utils import ravel_params
    from commefficient_tpu.parallel import FederatedSession, mask_gpt2
    from commefficient_tpu.utils.config import Config

    W, B, N, T = 8, 4, 2, 256
    gcfg = GPT2Config(vocab_size=50262, n_positions=1024, n_embd=768,
                      n_layer=12, n_head=12)
    model = GPT2DoubleHeads(gcfg)
    ids0 = jnp.zeros((1, 1, 8), jnp.int32)
    params = model.init(jax.random.key(0), ids0, token_type_ids=ids0,
                        mc_token_ids=jnp.zeros((1, 1), jnp.int32))
    # *_multichip modes spread the 8 workers over every local chip
    # (largest power-of-2 divisor) — the sharded-decode leg needs a real
    # workers mesh, and its uncompressed twin must run on the SAME mesh
    # so the _vs_uncompressed ratio isolates the decode, not added chips
    nd = 1
    if mode.endswith("_multichip") or mode == "sketch_sharded":
        nd = next(n for n in (8, 4, 2, 1)
                  if len(jax.devices()) >= n and W % n == 0)
    base = dict(num_clients=2 * W, num_workers=W, num_devices=nd,
                local_batch_size=B, weight_decay=0.0,
                topk_method="threshold", device_data=False,
                fuse_clients=True)
    if mode in ("sketch", "sketch_sharded"):
        cfg = Config(mode="sketch", error_type="virtual",
                     virtual_momentum=0.9, k=50_000, num_rows=5,
                     num_cols=5_000_000, sketch_backend=sketch_backend,
                     sketch_decode=("sharded" if mode == "sketch_sharded"
                                    else "auto"),
                     # the sketch-gap PR's hot path: per-leaf cotangent
                     # sketches replace the flat [D] grad concat, tables
                     # store/psum bf16 with f32 accumulation
                     sketch_fused_bwd=True,
                     sketch_table_dtype="bfloat16",
                     **base)
    elif mode == "powersgd":
        # rank-4 warm-started PowerSGD (compress/powersgd.py): D=124M
        # matricizes ~[11.2k, 11.2k], downlink r*(n+m) ~ 89k floats
        cfg = Config(mode="powersgd", error_type="virtual",
                     virtual_momentum=0.9, powersgd_rank=4, **base)
    else:
        cfg = Config(mode="uncompressed", virtual_momentum=0.9, **base)
    session = FederatedSession(cfg, params, gpt2_double_heads_loss(model.apply),
                               mask_batch=mask_gpt2)

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 50257, size=(W, B, N, T)).astype(np.int32))
    lm = np.full((W, B, N, T), -100, np.int32)
    lm[..., N - 1, T // 2:] = np.asarray(ids)[..., N - 1, T // 2:]
    batch = {
        "input_ids": ids, "token_type_ids": ids,
        "lm_labels": jnp.asarray(lm),
        "mc_token_ids": jnp.full((W, B, N), T - 1, jnp.int32),
        "mc_labels": jnp.zeros((W, B), jnp.int32),
    }
    client_ids = jnp.arange(W, dtype=jnp.int32)
    state, round_fn = session.state, session.round_fn
    lr = jnp.float32(0.1)
    from commefficient_tpu.utils.profiling import fence

    if scan_rounds > 1:
        # scan-of-rounds dispatch amortization: ONE jitted block runs K
        # rounds (the inlined round trace — same program the per-round
        # path dispatches K times), fixed staged batch
        K = scan_rounds

        # donate the state like the per-round twin (round_fn donates its
        # arg 0): without it the leg holds input AND output FedState
        # (~600 MB extra at GPT-2 scale) and biases the very dispatch
        # delta it isolates
        @functools.partial(jax.jit, donate_argnums=(0,))
        def run_block(state):
            def body(s, _):
                s2, mm = round_fn(s, client_ids, batch, lr)
                return s2, mm["loss"]

            return jax.lax.scan(body, state, None, length=K)

        for _ in range(2):  # compile + warm the donated layout
            state, losses = run_block(state)
            assert np.isfinite(fence(losses[-1]))
        reps = max(1, n_rounds // K)
        t0 = time.perf_counter()
        for _ in range(reps):
            state, losses = run_block(state)
        assert np.isfinite(fence(losses[-1]))
        dt = time.perf_counter() - t0
        n_rounds = reps * K
    else:
        for _ in range(3):  # compile + warm both donated-buffer layouts
            state, m = round_fn(state, client_ids, batch, lr)
            assert np.isfinite(fence(m["loss"]))
        t0 = time.perf_counter()
        for _ in range(n_rounds):
            state, m = round_fn(state, client_ids, batch, lr)
        assert np.isfinite(fence(m["loss"]))  # scalar-fetch fence
        dt = time.perf_counter() - t0
    d = int(ravel_params(params)[0].size)
    tokens = n_rounds * W * B * N * T  # every candidate's tokens do compute
    peak, _, _ = _chip_peak_flops()
    tps = tokens / dt
    # MFU against the peak of the chips the leg USED (nd > 1 for the
    # multichip/sharded legs) — dividing an nd-chip throughput by one
    # chip's peak would report an MFU that can exceed 1.0
    mfu = tps * gpt2_flops_per_token(d, gcfg.n_layer, gcfg.n_embd, T) / (
        peak * nd
    )
    # audited twin of the hand-model numbers, from the compiled artifact
    # (one extra AOT compile per leg — tracked perf beats bench wall-clock).
    # The scan leg reuses the per-round leg's program, so re-auditing it
    # would only pay the AOT compile twice for the same artifact.
    audit_keys = {}
    if scan_rounds <= 1:
        audit_keys, _ = _audit_leg(
            session, np.arange(W, dtype=np.int32), batch, dt / n_rounds
        )
    return tps, mfu, dt / n_rounds, audit_keys


def _headline_cfg():
    from commefficient_tpu.utils.config import Config

    # 8 virtual workers x 256-sample local batches (FetchSGD's CIFAR configs
    # run local batches up to 500/client, paper §5) = 2048 samples/round.
    workers, batch = 8, 256
    return Config(
        mode="sketch",
        error_type="virtual",
        virtual_momentum=0.9,
        k=50_000,
        num_rows=5,
        num_cols=500_000,
        num_blocks=1,  # r3: num_blocks>1 now really chunks (slower); 1 keeps
        # the computation identical to the r1/r2 headline runs
        topk_method="threshold",
        fuse_clients=True,
        num_clients=2 * workers,
        num_workers=workers,
        num_devices=1,
        local_batch_size=batch,
        weight_decay=5e-4,
    )


def _measure(cfg, n_rounds: int = 20, audit_box: dict = None) -> float:
    """samples/s of the full federated round under ``cfg`` (one chip).
    ``audit_box``: a dict to fill with the leg's audited keys + the
    CompiledRoundAudit itself (headline leg only — matrix legs skip the
    extra AOT compile)."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.models import ResNet9, classification_loss
    from commefficient_tpu.parallel import FederatedSession, make_mesh

    workers, batch = cfg.num_workers, cfg.local_batch_size
    from commefficient_tpu.models.losses import model_dtype

    model = ResNet9(num_classes=10, dtype=model_dtype(cfg.compute_dtype))
    params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    loss_fn = classification_loss(model.apply, compute_dtype=cfg.compute_dtype)
    session = FederatedSession(cfg, params, loss_fn, mesh=make_mesh(1))

    rng = np.random.default_rng(0)
    # Device-resident batch: models a prefetching input pipeline (the steady
    # state of real training, where H2D overlaps compute). The round itself —
    # grads, compression, aggregation, server update — is what's timed.
    ids = jnp.asarray(
        rng.choice(cfg.num_clients, size=workers, replace=False).astype(np.int32)
    )
    shape = (workers, batch, 32, 32, 3)
    if cfg.mode == "fedavg":  # microbatch convention [W, L, B/L, ...]
        L = cfg.num_local_iters
        shape = (workers, L, batch // L, 32, 32, 3)
    data = {
        "x": jnp.asarray(rng.normal(size=shape).astype(np.float32)),
        "y": jnp.asarray(
            rng.integers(0, 10, size=shape[:-3]).astype(np.int32)
        ),
    }
    state, round_fn = session.state, session.round_fn
    lr = jnp.float32(0.1)

    # fedsim legs (sketch_dropout30): the masked round consumes one RoundEnv
    # per round; realize the real environment's schedule up front so the
    # timed loop measures the in-graph masking, not host mask draws
    envs = [()] * (3 + n_rounds)
    if cfg.fedsim_enabled:
        from commefficient_tpu.fedsim import build_environment

        fe = build_environment(cfg)
        envs = [
            (jnp.asarray(e.live), jnp.asarray(e.corrupt),
             jnp.float32(e.live_count))
            for e in fe.round_envs(0, 3 + n_rounds)
        ]

    # compile + warmup: the first TWO calls compile (donated-buffer layouts
    # differ between the fresh state and the returned state), so warm both.
    # NB: block_until_ready is unreliable through the axon tunnel; a scalar
    # fetch is the only trustworthy fence (utils.profiling.fence does both).
    from commefficient_tpu.utils.profiling import fence

    for i in range(3):
        state, m = round_fn(state, ids, data, lr, env=envs[i])
        assert np.isfinite(fence(m["loss"]))

    t0 = time.perf_counter()
    for i in range(n_rounds):
        state, m = round_fn(state, ids, data, lr, env=envs[3 + i])
    assert np.isfinite(fence(m["loss"]))
    dt = time.perf_counter() - t0
    sps = n_rounds * workers * batch / dt
    if audit_box is not None:
        keys, audit = _audit_leg(
            session, np.asarray(ids), data, dt / n_rounds
        )
        audit_box.update(keys)
        audit_box["_audit"] = audit
        audit_box["_cfg"] = cfg
    return sps


def _measure_pipeline(base_cfg, n_rounds: int = 8, depth: int = 2) -> dict:
    """Pipelined round execution (pipeline/ PR) vs its synchronous twin on
    the headline sketch round, through the REAL engine. Unlike the other
    legs' device-resident batches, BOTH twins pay real per-round host
    work — non-IID sampler draw + [W*B] batch assembly + H2D ``device_put``
    — because that host serial time is exactly what the pipeline hides.
    The sync twin runs it on the critical path between dispatches (the
    depth-0 train loop); the pipelined twin stages ``depth`` rounds ahead
    on the worker thread. Reports samples/s for both, the engine's mean
    occupancy/residual host stall, and ``host_stall_delta_ms`` = mean
    per-round host realization time minus the residual stall — the host
    milliseconds per round the pipeline moved off the critical path."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.data import FedDataset, FedSampler
    from commefficient_tpu.models import ResNet9, classification_loss
    from commefficient_tpu.models.losses import model_dtype
    from commefficient_tpu.parallel import FederatedSession, make_mesh
    from commefficient_tpu.pipeline import PipelinedRounds
    from commefficient_tpu.utils.profiling import fence

    cfg = base_cfg.replace(pipeline_depth=depth, device_data=False)
    W, B = cfg.num_workers, cfg.local_batch_size
    model = ResNet9(num_classes=10, dtype=model_dtype(cfg.compute_dtype))
    params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    loss_fn = classification_loss(model.apply, compute_dtype=cfg.compute_dtype)
    session = FederatedSession(cfg, params, loss_fn, mesh=make_mesh(1))
    rng = np.random.default_rng(0)
    n = 4 * W * B  # enough rows that per-client draws stay CIFAR-shaped
    ds = FedDataset(
        {"x": rng.integers(0, 256, size=(n, 32, 32, 3)).astype(np.uint8),
         "y": rng.integers(0, 10, size=(n,)).astype(np.int32)},
        cfg.num_clients, iid=True, seed=0,
    )
    sampler = FedSampler(ds, num_workers=W, local_batch_size=B, seed=0)

    def lr_fn(_step):
        return 0.1

    def run_sync(start):
        t0 = time.perf_counter()
        for r in range(start, start + n_rounds):
            ids, batch = sampler.sample_round(r)
            m = session.train_round(ids, batch, 0.1)
        fence(m["loss"])
        return time.perf_counter() - t0

    # compile + warm both donated-buffer layouts (bench warmup discipline)
    run_sync(0)
    dt_sync = run_sync(n_rounds)
    start = 2 * n_rounds
    stop = start + n_rounds
    engine = PipelinedRounds(
        cfg, session, sampler, lr_fn, num_rounds=stop, steps_per_epoch=stop
    ).start(start)
    try:
        t0 = time.perf_counter()
        for _s, _lr, m in engine.epoch_rounds(0, start):
            pass
        fence(m["loss"])
        dt_pipe = time.perf_counter() - t0
    finally:
        engine.close()
    st = engine.stats()
    return {
        "sketch_pipelined_samples_per_sec": round(n_rounds * W * B / dt_pipe, 2),
        "sketch_pipeline_sync_samples_per_sec": round(
            n_rounds * W * B / dt_sync, 2
        ),
        "sketch_pipelined_sec_per_round": round(dt_pipe / n_rounds, 4),
        "sketch_pipelined_depth": depth,
        "sketch_pipelined_occupancy": round(st["occupancy"], 4),
        "sketch_pipelined_host_stall_ms": round(st["host_stall_ms"], 2),
        "sketch_pipelined_host_stall_delta_ms": round(
            st["prefetch_host_ms"] - st["host_stall_ms"], 2
        ),
    }


def _measure_traced(base_cfg, n_rounds: int = 8) -> dict:
    """Critical-path attribution of the headline sketch round (trace PR):
    the REAL dispatch path with a PhaseSpans recorder attached — every
    span stamped with its round's trace id — decomposed by
    telemetry.trace.CriticalPath into DISJOINT exclusive stage times.
    Reports the mean per-round exclusive ms per stage plus the binding
    stage's name. Every measured round fences (the recorder window covers
    the whole loop), so the dispatch span is the true device+host round
    latency and the decomposition accounts for real wall-clock — these
    rows are therefore slower than the headline by design and stay
    INFORMATIONAL (no gated suffix; scripts/check_bench_regression.py
    registers them next to *_host_stall_ms)."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.data import FedDataset, FedSampler
    from commefficient_tpu.models import ResNet9, classification_loss
    from commefficient_tpu.models.losses import model_dtype
    from commefficient_tpu.parallel import FederatedSession, make_mesh
    from commefficient_tpu.telemetry.spans import PhaseSpans
    from commefficient_tpu.telemetry.trace import (
        STAGES, CriticalPath, round_trace_id,
    )
    from commefficient_tpu.utils.profiling import fence

    cfg = base_cfg.replace(device_data=False)
    W, B = cfg.num_workers, cfg.local_batch_size
    model = ResNet9(num_classes=10, dtype=model_dtype(cfg.compute_dtype))
    params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    loss_fn = classification_loss(model.apply, compute_dtype=cfg.compute_dtype)
    session = FederatedSession(cfg, params, loss_fn, mesh=make_mesh(1))
    rng = np.random.default_rng(0)
    n = 4 * W * B
    ds = FedDataset(
        {"x": rng.integers(0, 256, size=(n, 32, 32, 3)).astype(np.uint8),
         "y": rng.integers(0, 10, size=(n,)).astype(np.int32)},
        cfg.num_clients, iid=True, seed=0,
    )
    sampler = FedSampler(ds, num_workers=W, local_batch_size=B, seed=0)

    # compile + warm both donated layouts BEFORE attaching the recorder:
    # the traced window must hold steady-state rounds only
    for r in range(3):
        ids, batch = sampler.sample_round(r)
        m = session.train_round(ids, batch, 0.1)
    fence(m["loss"])

    # logdir enables recording; nothing dumps (close() is never called)
    spans = PhaseSpans(".", start_step=3, num_steps=n_rounds)
    session.spans = spans
    try:
        for r in range(3, 3 + n_rounds):
            spans.step(r)
            # the sampler draw is the leg's data stage — the train loops
            # record it via wrap_iter/prefetch; here we bracket it by hand
            with spans.span("data_load", step=r,
                            trace_id=round_trace_id(r)):
                ids, batch = sampler.sample_round(r)
            m = session.train_round(ids, batch, 0.1)
        fence(m["loss"])
    finally:
        session.spans = None

    cp = CriticalPath(spans.events)
    bds = [bd for bd in (cp.round_breakdown(s) for s in cp.steps())
           if bd is not None and bd["step"] >= 3]
    if not bds:
        return {"sketch_traced_error": "no rounds decomposed"}
    tot = {s: sum(bd["stages_ms"][s] for bd in bds) for s in STAGES}
    out = {
        "sketch_traced_critical_stage": max(STAGES, key=lambda s: tot[s]),
        "sketch_traced_rounds": len(bds),
        "sketch_traced_wall_ms": round(
            sum(bd["wall_ms"] for bd in bds) / len(bds), 3),
    }
    for s in STAGES:
        out[f"sketch_traced_{s}_exclusive_ms"] = round(tot[s] / len(bds), 3)
    return out


def _measure_ladder_switch(base_cfg, n_rounds: int = 8) -> dict:
    """Cost of a mid-run compression-ladder rung switch (control/ PR) on
    the headline sketch round: a 2-rung k-ladder under a fixed schedule
    that switches halfway. Reports the steady samples/s, the wall-clock of
    the FIRST round after the switch (state migration + the prewarmed
    rung's first dispatch — its XLA backend-compile, but never a
    re-trace), and the sentinel's retrace count, which must be 0 — the
    whole point of AOT rung prewarming."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.control import build_controller
    from commefficient_tpu.models import ResNet9, classification_loss
    from commefficient_tpu.models.losses import model_dtype
    from commefficient_tpu.parallel import FederatedSession, make_mesh
    from commefficient_tpu.utils.profiling import fence

    half = n_rounds // 2
    cfg = base_cfg.replace(
        control_policy="fixed",
        control_schedule=f"0-{half - 1}=0,{half}-=1",
        ladder=f"k={base_cfg.k},{max(base_cfg.k // 2, 1)}",
    )
    model = ResNet9(num_classes=10, dtype=model_dtype(cfg.compute_dtype))
    params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    loss_fn = classification_loss(model.apply, compute_dtype=cfg.compute_dtype)
    session = FederatedSession(cfg, params, loss_fn, mesh=make_mesh(1))
    ctrl = build_controller(cfg, session, num_rounds=n_rounds + 3)

    rng = np.random.default_rng(0)
    W, B = cfg.num_workers, cfg.local_batch_size
    ids = rng.choice(cfg.num_clients, size=W, replace=False).astype(np.int32)
    batch = {
        "x": rng.normal(size=(W, B, 32, 32, 3)).astype(np.float32),
        "y": rng.integers(0, 10, size=(W, B)).astype(np.int32),
    }
    session.prewarm_rungs(ids, batch, 0.1)
    # warm rung 0 (compile + donated-layout second compile) OUTSIDE the
    # schedule by driving the session's round clock through rounds 0..2 of
    # a schedule that holds rung 0 until the switch
    times = []
    for r in range(3 + n_rounds):
        t0 = time.perf_counter()
        m = session.train_round(ids, batch, 0.1)
        assert np.isfinite(fence(m["loss"]))
        times.append(time.perf_counter() - t0)
    # the switch fires at round index `half` (clock r == half)
    switch_ms = times[half] * 1e3
    steady = times[3:half] + times[half + 1:]
    sps = W * B / (sum(steady) / len(steady))
    return {
        "sketch_ladder_steady": round(sps, 2),
        "sketch_ladder_switch_round_ms": round(switch_ms, 1),
        "sketch_ladder_retraces": session.retrace_sentinel.retraces,
    }


def _measure_recovery(base_cfg, n_rounds: int = 4) -> dict:
    """Cost of the resilience/ self-healing primitives on the headline
    sketch round: the vault snapshot capture (a deliberate host sync —
    the per-`--snapshot_every` tax a recovery-enabled run pays) and the
    rollback restore (snapshot -> leaf re-commit through the same
    checkpoint path), plus the sentinel's retrace count across a
    post-rollback dispatch — which must be 0: the restored leaves land on
    their original shardings, so the round re-dispatches the same
    compiled program."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.models import ResNet9, classification_loss
    from commefficient_tpu.models.losses import model_dtype
    from commefficient_tpu.parallel import FederatedSession, make_mesh
    from commefficient_tpu.resilience import RollbackVault
    from commefficient_tpu.utils.profiling import fence

    cfg = base_cfg
    model = ResNet9(num_classes=10, dtype=model_dtype(cfg.compute_dtype))
    params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    loss_fn = classification_loss(model.apply, compute_dtype=cfg.compute_dtype)
    session = FederatedSession(cfg, params, loss_fn, mesh=make_mesh(1))

    rng = np.random.default_rng(0)
    W, B = cfg.num_workers, cfg.local_batch_size
    ids = rng.choice(cfg.num_clients, size=W, replace=False).astype(np.int32)
    batch = {
        "x": rng.normal(size=(W, B, 32, 32, 3)).astype(np.float32),
        "y": rng.integers(0, 10, size=(W, B)).astype(np.int32),
    }
    for _ in range(2):  # compile + donated-layout warmup
        fence(session.train_round(ids, batch, 0.1)["loss"])
    vault = RollbackVault(snapshot_every=1)
    t0 = time.perf_counter()
    snap = vault.snapshot(session, 2)
    snapshot_ms = (time.perf_counter() - t0) * 1e3
    for _ in range(n_rounds):
        fence(session.train_round(ids, batch, 0.1)["loss"])
    t0 = time.perf_counter()
    vault.restore(session, snap)
    rollback_ms = (time.perf_counter() - t0) * 1e3
    fence(session.train_round(ids, batch, 0.1)["loss"])
    return {
        "sketch_resilience_snapshot_ms": round(snapshot_ms, 1),
        "sketch_resilience_snapshot_mb": round(snap.nbytes / 2**20, 1),
        "sketch_resilience_rollback_ms": round(rollback_ms, 1),
        "sketch_resilience_retraces": session.retrace_sentinel.retraces,
    }


def _measure_sparse_agg(base, n_rounds: int = 10) -> dict:
    """Sparse-aggregate PR: the O(W*k) pair-exchange aggregation vs its
    dense-psum twin, per mode, on the SAME multi-device mesh and round
    shape. The ``_vs_dense`` ratio (sparse sps / dense sps, higher is
    better — registered in scripts/check_bench_regression.py) is the
    leg's design claim: at bench scale (D ~ 6.5M, k = 50k) the exchange
    drops from O(D) to O(W*k) elements, so sparse must not lose to
    dense. Requires a multi-device host — on one chip the sparse
    schedule is degenerate (Config warns) and the comparison is
    meaningless, so the leg reports a skip marker instead of a fake 1.0."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.models import ResNet9, classification_loss
    from commefficient_tpu.models.losses import model_dtype
    from commefficient_tpu.parallel import FederatedSession, make_mesh
    from commefficient_tpu.utils.profiling import fence

    n_dev = len(jax.devices())
    if n_dev < 2:
        return {"sparse_agg_skipped": f"single-device host ({n_dev} chip)"}

    out: dict = {}
    B = base.local_batch_size
    for mode, extra in (
        ("local_topk", dict(error_type="local", virtual_momentum=0.0,
                            fuse_clients=False, client_store="host")),
        ("true_topk", dict(error_type="virtual", virtual_momentum=0.9)),
    ):
        twin_cfg = base.replace(
            mode=mode, k=50_000, topk_method="threshold",
            num_devices=n_dev, num_workers=n_dev, num_clients=2 * n_dev,
            **extra,
        )
        name = f"{mode}_sparse_agg"
        try:
            model = ResNet9(
                num_classes=10, dtype=model_dtype(twin_cfg.compute_dtype)
            )
            params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
            loss_fn = classification_loss(
                model.apply, compute_dtype=twin_cfg.compute_dtype
            )
            rng = np.random.default_rng(0)
            ids = jnp.asarray(np.arange(n_dev, dtype=np.int32))
            data = {
                "x": jnp.asarray(
                    rng.normal(size=(n_dev, B, 32, 32, 3)).astype(np.float32)
                ),
                "y": jnp.asarray(
                    rng.integers(0, 10, size=(n_dev, B)).astype(np.int32)
                ),
            }
            sps = {}
            for agg in ("dense", "sparse"):
                session = FederatedSession(
                    twin_cfg.replace(aggregate=agg), params, loss_fn,
                    mesh=make_mesh(n_dev),
                )
                state, round_fn = session.state, session.round_fn
                # hosted banks (clientstore/): the round takes the
                # cohort's rows as donated arguments and returns the
                # updated ones — thread them through the timing loop so
                # the bank writeback stays off the measured path
                hosted = session._streamer is not None
                vel = err = ()
                if hosted:
                    cohort = session._streamer.gather(np.asarray(ids))
                    vel, err = cohort.vel, cohort.err

                def step(state, vel, err):
                    if hosted:
                        return round_fn(state, ids, data, jnp.float32(0.1),
                                        vel, err)
                    state, m = round_fn(state, ids, data, jnp.float32(0.1))
                    return state, m, vel, err

                for _ in range(3):  # compile + donated-layout warmup
                    state, m, vel, err = step(state, vel, err)
                    assert np.isfinite(fence(m["loss"]))
                t0 = time.perf_counter()
                for _ in range(n_rounds):
                    state, m, vel, err = step(state, vel, err)
                assert np.isfinite(fence(m["loss"]))
                dt = time.perf_counter() - t0
                sps[agg] = n_rounds * n_dev * B / dt
                if hosted:
                    session.close_client_store()
            out[name] = round(sps["sparse"], 2)
            out[f"{name}_vs_dense"] = round(sps["sparse"] / sps["dense"], 3)
        except Exception as e:  # noqa: BLE001 — per-leg error isolation
            out[f"{name}_error"] = f"{type(e).__name__}: {e}"[:200]
    return out


def _measure_hostclient(base, n_rounds: int = 10) -> dict:
    """clientstore PR: the hosted round (per-client vel/err banks in host
    RAM, cohort rows streamed per round) vs its device-resident twin on
    the SAME mesh and round shape. The ``_vs_device`` ratio (host sps /
    device sps, higher is better — registered in
    scripts/check_bench_regression.py) is the leg's design claim: with
    the cohort gather staged H2D and the writeback async, hosting the
    [C, D] banks must not cost the round loop more than noise — while
    bounding C by host RAM/disk instead of HBM (the C = 1e6 smoke in
    tests/test_clientstore.py). Sliding cohorts (overlap W-1 per round)
    exercise the LRU device cache, whose hit rate and H2D stage time ride
    along as informational gauges; the retrace gauge is the hard zero."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.models import ResNet9, classification_loss
    from commefficient_tpu.models.losses import model_dtype
    from commefficient_tpu.parallel import FederatedSession, make_mesh
    from commefficient_tpu.utils.profiling import fence

    n_dev = len(jax.devices())
    out: dict = {}
    B = base.local_batch_size
    C = 4 * n_dev
    twin = base.replace(
        mode="local_topk", error_type="local", local_momentum=0.9,
        virtual_momentum=0.0, fuse_clients=False, k=50_000,
        topk_method="threshold", num_devices=n_dev, num_workers=n_dev,
        num_clients=C, telemetry_level=1,
    )
    name = "local_topk_hostclient"
    try:
        model = ResNet9(num_classes=10, dtype=model_dtype(twin.compute_dtype))
        params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
        loss_fn = classification_loss(
            model.apply, compute_dtype=twin.compute_dtype
        )
        rng = np.random.default_rng(0)
        data = {
            "x": jnp.asarray(
                rng.normal(size=(n_dev, B, 32, 32, 3)).astype(np.float32)
            ),
            "y": jnp.asarray(
                rng.integers(0, 10, size=(n_dev, B)).astype(np.int32)
            ),
        }
        sps, gauges = {}, {}
        for store in ("device", "host"):
            cfg = twin.replace(
                client_store=store,
                client_store_cache_rows=2 * n_dev if store == "host" else 0,
            )
            session = FederatedSession(cfg, params, loss_fn,
                                       mesh=make_mesh(n_dev))

            def one_round(r):
                # sliding cohort: W-1 clients repeat from round r-1, so
                # the device cache sees real hits AND real evictions
                ids = (np.arange(n_dev, dtype=np.int32) + r) % C
                return session.train_round(ids, data, 0.1)

            for r in range(3):  # compile + donated-layout warmup
                m = one_round(r)
                assert np.isfinite(fence(m["loss"]))
            hit = h2d = 0.0
            t0 = time.perf_counter()
            for r in range(3, 3 + n_rounds):
                m = one_round(r)
                hit += float(m.get("clientstore/cache_hit_rate", 0.0))
                h2d += float(m.get("clientstore/h2d_stage_ms", 0.0))
            assert np.isfinite(fence(m["loss"]))
            dt = time.perf_counter() - t0
            sps[store] = n_rounds * n_dev * B / dt
            if store == "host":
                gauges = {
                    f"{name}_cache_hit_rate": round(hit / n_rounds, 3),
                    f"{name}_h2d_stage_ms": round(h2d / n_rounds, 3),
                    f"{name}_retraces": session.retrace_sentinel.retraces,
                }
                session.close_client_store()
        out[f"{name}_samples_per_sec"] = round(sps["host"], 2)
        out[f"{name}_vs_device"] = round(sps["host"] / sps["device"], 3)
        out.update(gauges)
    except Exception as e:  # noqa: BLE001 — per-leg error isolation
        out[f"{name}_error"] = f"{type(e).__name__}: {e}"[:200]
    return out


def _measure_async(base, n_updates: int = 8) -> dict:
    """Buffered-async PR: the asyncfed engine vs its synchronous twin on
    the headline sketch round under ~40% stragglers (poisson arrivals at
    rate 0.9: participation 1-exp(-0.9) ~ 0.59). Both twins run the SAME
    task, sampler stream, and per-client vmap round body (async requires
    per-client rows, so the sync twin drops fuse_clients too — the ratio
    isolates the SCHEDULE, not the fusion). The sync twin pays one full
    barrier round per server update; the async engine fires on the Kth
    arrival with C cohorts in flight, so it lands more server updates per
    unit wall-clock on the same hardware budget. Reported:

      * sketch_async_updates_per_sec / sketch_async_sync_rounds_per_sec —
        server-update rates of the two twins (both gated up);
      * sketch_async_vs_sync — their ratio (tight band in
        scripts/check_bench_regression.py; the leg's design claim);
      * sketch_async_time_to_loss_sec + the _vs_sync ratio — wall seconds
        for the async run to first reach the sync twin's final training
        loss (the staleness-discounting quality story under stragglers;
        if never reached, the full async duration is reported — honest
        pessimism, and the ratio then gates the shortfall);
      * sketch_async_retraces — hard-zero invariant (one compiled
        launch/apply pair per rung at ANY concurrency).
    """
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.asyncfed import AsyncFederation
    from commefficient_tpu.data import FedDataset, FedSampler
    from commefficient_tpu.models import ResNet9, classification_loss
    from commefficient_tpu.models.losses import model_dtype
    from commefficient_tpu.parallel import FederatedSession, make_mesh
    from commefficient_tpu.utils.profiling import fence

    W, B = base.num_workers, base.local_batch_size
    K, C, rate = max(W // 2, 1), 2, 0.9
    common = dict(fuse_clients=False, device_data=False,
                  availability="poisson", arrival_rate=rate)
    cfg_async = base.replace(async_buffer=K, async_concurrency=C,
                             staleness_exponent=0.5, **common)
    cfg_sync = base.replace(**common)

    model = ResNet9(num_classes=10, dtype=model_dtype(base.compute_dtype))
    params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    loss_fn = classification_loss(model.apply,
                                  compute_dtype=base.compute_dtype)
    rng = np.random.default_rng(0)
    n = 4 * W * B
    ds = FedDataset(
        {"x": rng.integers(0, 256, size=(n, 32, 32, 3)).astype(np.uint8),
         "y": rng.integers(0, 10, size=(n,)).astype(np.int32)},
        base.num_clients, iid=True, seed=0,
    )

    def run_sync():
        session = FederatedSession(cfg_sync, params, loss_fn,
                                   mesh=make_mesh(1))
        sampler = FedSampler(ds, num_workers=W, local_batch_size=B, seed=0)
        losses = []
        for r in range(2):  # compile + donated-layout warmup
            ids, batch = sampler.sample_round(r)
            fence(session.train_round(ids, batch, 0.1)["loss"])
        t0 = time.perf_counter()
        for r in range(2, 2 + n_updates):
            ids, batch = sampler.sample_round(r)
            m = session.train_round(ids, batch, 0.1)
            losses.append(float(fence(m["loss"])))
        return time.perf_counter() - t0, losses

    def run_async():
        session = FederatedSession(cfg_async, params, loss_fn,
                                   mesh=make_mesh(1))
        sampler = FedSampler(ds, num_workers=W, local_batch_size=B, seed=0)
        total = 2 + n_updates
        engine = AsyncFederation(cfg_async, session, sampler,
                                 lambda _s: 0.1, total,
                                 steps_per_epoch=total).start()
        losses, stamps = [], []
        try:
            t0 = None
            for step, _lr, m in engine.epoch_rounds(0, 0):
                loss = float(fence(m["loss"]))
                if step == 1:  # warmup: both compiled layouts dispatched
                    t0 = time.perf_counter()
                elif step >= 2:
                    losses.append(loss)
                    stamps.append(time.perf_counter() - t0)
            dt = time.perf_counter() - t0
        finally:
            engine.close()
        return dt, losses, stamps, session.retrace_sentinel.retraces

    dt_sync, sync_losses = run_sync()
    dt_async, async_losses, stamps, retraces = run_async()
    target = sync_losses[-1]
    reached = [t for t, l in zip(stamps, async_losses) if l <= target]
    t2l = reached[0] if reached else dt_async
    return {
        "sketch_async_buffer": K,
        "sketch_async_concurrency": C,
        "sketch_async_straggler_rate": round(float(np.exp(-rate)), 3),
        "sketch_async_updates_per_sec": round(n_updates / dt_async, 3),
        "sketch_async_sync_rounds_per_sec": round(n_updates / dt_sync, 3),
        "sketch_async_vs_sync": round(dt_sync / dt_async, 3),
        "sketch_async_time_to_loss_sec": round(t2l, 3),
        "sketch_async_time_to_loss_vs_sync": round(dt_sync / t2l, 3),
        "sketch_async_retraces": retraces,
    }


def _measure_overlap(base, n_rounds: int = 10, n_updates: int = 8) -> dict:
    """Hidden-collectives PR: the two overlap modes vs their sequential
    twins, on the SAME mesh and round shape (the ratios divide two
    measurements of the same run, so load cancels — both get the tight
    band in scripts/check_bench_regression.py and gate UP).

      * sketch_overlap_layerwise_samples_per_sec / _vs_sequential — the
        fused sketch
        round with the table psum + candidate pair-gathers chunked into
        per-leaf-group segments (``--overlap_collectives layerwise``)
        against the monolithic-collective twin;
      * async_double_buffered_updates_per_sec / _vs_sequential — the
        asyncfed engine with the apply fence deferred behind the next
        cohort's launches (``--async_double_buffer``) against the
        sequential-fence twin, spans attached to BOTH so the fence
        discipline (the only thing the double buffer moves) is active;
        the leg also reports both twins' exposed_collective_ms (the new
        v9 metric, informational — near-zero ms bands are noise).

    Requires a multi-device host: on one chip there is no cross-chip
    collective to hide, so both legs report a skip marker instead of a
    fake 1.0 ratio."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from commefficient_tpu.asyncfed import AsyncFederation
    from commefficient_tpu.data import FedDataset, FedSampler
    from commefficient_tpu.models import ResNet9, classification_loss
    from commefficient_tpu.models.losses import model_dtype
    from commefficient_tpu.parallel import FederatedSession, make_mesh
    from commefficient_tpu.telemetry import PhaseSpans
    from commefficient_tpu.utils.profiling import fence

    n_dev = len(jax.devices())
    if n_dev < 2:
        reason = (f"single-device host ({n_dev} chip) — no cross-chip "
                  "collective to hide")
        return {"sketch_overlap_layerwise_skipped": reason,
                "async_double_buffered_skipped": reason}

    out: dict = {}
    B = base.local_batch_size
    cfg = base.replace(num_devices=n_dev, num_workers=n_dev,
                       num_clients=2 * n_dev)
    model = ResNet9(num_classes=10, dtype=model_dtype(cfg.compute_dtype))
    params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    loss_fn = classification_loss(model.apply,
                                  compute_dtype=cfg.compute_dtype)
    rng = np.random.default_rng(0)

    # -- leg 1: layerwise-segmented collectives on the fused sketch round
    try:
        ids = jnp.asarray(np.arange(n_dev, dtype=np.int32))
        data = {
            "x": jnp.asarray(
                rng.normal(size=(n_dev, B, 32, 32, 3)).astype(np.float32)
            ),
            "y": jnp.asarray(
                rng.integers(0, 10, size=(n_dev, B)).astype(np.int32)
            ),
        }
        sps = {}
        for ov in ("none", "layerwise"):
            session = FederatedSession(
                cfg.replace(overlap_collectives=ov), params, loss_fn,
                mesh=make_mesh(n_dev),
            )
            state, round_fn = session.state, session.round_fn
            for _ in range(3):  # compile + donated-layout warmup
                state, m = round_fn(state, ids, data, jnp.float32(0.1))
                assert np.isfinite(fence(m["loss"]))
            t0 = time.perf_counter()
            for _ in range(n_rounds):
                state, m = round_fn(state, ids, data, jnp.float32(0.1))
            assert np.isfinite(fence(m["loss"]))
            sps[ov] = n_rounds * n_dev * B / (time.perf_counter() - t0)
        out["sketch_overlap_layerwise_samples_per_sec"] = round(
            sps["layerwise"], 2
        )
        out["sketch_overlap_layerwise_vs_sequential"] = round(
            sps["layerwise"] / sps["none"], 3
        )
    except Exception as e:  # noqa: BLE001 — per-leg error isolation
        out["sketch_overlap_layerwise_error"] = (
            f"{type(e).__name__}: {e}"[:200]
        )

    # -- leg 2: double-buffered asyncfed apply fencing
    try:
        W = n_dev
        cfg_a = cfg.replace(
            fuse_clients=False, device_data=False,
            async_buffer=W, async_concurrency=1,
        )
        n = 4 * W * B
        ds = FedDataset(
            {"x": rng.integers(0, 256, size=(n, 32, 32, 3)).astype(np.uint8),
             "y": rng.integers(0, 10, size=(n,)).astype(np.int32)},
            cfg_a.num_clients, iid=True, seed=0,
        )

        def run_engine(double_buffer: bool):
            cfg_run = cfg_a.replace(async_double_buffer=double_buffer)
            session = FederatedSession(cfg_run, params, loss_fn,
                                       mesh=make_mesh(n_dev))
            spans = PhaseSpans(tempfile.mkdtemp(prefix="bench_overlap_"))
            session.spans = spans
            sampler = FedSampler(ds, num_workers=W, local_batch_size=B,
                                 seed=0)
            total = 2 + n_updates
            engine = AsyncFederation(cfg_run, session, sampler,
                                     lambda _s: 0.1, total,
                                     steps_per_epoch=total,
                                     spans=spans).start()
            last = None
            try:
                t0 = None
                for step, _lr, m in engine.epoch_rounds(0, 0):
                    # no per-update fence: the fence discipline under
                    # test is the engine's own (spans-armed) one
                    last = m["loss"]
                    if step == 1:  # warmup: both compiled layouts done
                        fence(last)
                        t0 = time.perf_counter()
                assert np.isfinite(fence(last))
                dt = time.perf_counter() - t0
            finally:
                engine.close()
            stall = engine.stats()["host_stall_ms"]
            return dt, spans.collective_exposure_ms(), stall

        dt_seq, exp_seq, _ = run_engine(False)
        dt_db, exp_db, stall_db = run_engine(True)
        out.update({
            "async_double_buffered_updates_per_sec": round(
                n_updates / dt_db, 3
            ),
            "async_double_buffered_vs_sequential": round(dt_seq / dt_db, 3),
            "async_double_buffered_exposed_collective_ms": round(exp_db, 3),
            "async_sequential_exposed_collective_ms": round(exp_seq, 3),
            "async_double_buffered_host_stall_ms": round(stall_db, 3),
        })
    except Exception as e:  # noqa: BLE001
        out["async_double_buffered_error"] = (
            f"{type(e).__name__}: {e}"[:200]
        )
    return out


def _measure_elastic(base, n_rounds: int = 8) -> dict:
    """Elastic-fleet PR (schema v13): the headline sketch round under a
    scheduled width resize (8 -> 4 for three rounds, then back) through
    the REAL width ladder — one shrink and one grow transition inside
    the timed window. The design claim is the retrace gauge: every
    realized width dispatches a prewarmed per-width program, so a resize
    is a dispatch-table swap (``sketch_elastic_resize_ms`` totals the
    swap cost — microseconds, not a re-trace) and
    ``sketch_elastic_retraces`` must be EXACTLY 0 (gated by
    scripts/check_bench_regression.py). Samples/s counts each round's
    REALIZED width — the fleet does less work while shrunk, and the leg
    reports the real rate, not the base-width fiction."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.models import ResNet9, classification_loss
    from commefficient_tpu.models.losses import model_dtype
    from commefficient_tpu.parallel import FederatedSession, make_mesh
    from commefficient_tpu.utils.profiling import fence

    cfg = base.replace(chaos="resize@4:rounds=3-5")
    W, B = cfg.num_workers, cfg.local_batch_size
    model = ResNet9(num_classes=10, dtype=model_dtype(cfg.compute_dtype))
    params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    loss_fn = classification_loss(model.apply,
                                  compute_dtype=cfg.compute_dtype)
    session = FederatedSession(cfg, params, loss_fn, mesh=make_mesh(1))
    rng = np.random.default_rng(0)
    ids = rng.choice(cfg.num_clients, size=W, replace=False).astype(np.int32)
    batch = {
        "x": rng.normal(size=(W, B, 32, 32, 3)).astype(np.float32),
        "y": rng.integers(0, 10, size=(W, B)).astype(np.int32),
    }
    # AOT-lower every width's round program (the runner's prewarm path) —
    # without it the first shrunk round would pay a fresh trace and the
    # retrace gauge below would catch it
    session.prewarm_rungs(ids, batch, 0.1)
    env = session.fedsim_env
    # warmup: rounds 0-2 run at the base width (the resize window opens
    # at round 3) — compile + donated-layout warmup outside the window
    for _ in range(3):
        fence(session.train_round(ids, batch, 0.1)["loss"])
    t0 = time.perf_counter()
    samples = 0
    for r in range(3, 3 + n_rounds):
        m = session.train_round(ids, batch, 0.1)
        samples += env.width_at(r) * B  # the round's REALIZED width
    assert np.isfinite(fence(m["loss"]))
    dt = time.perf_counter() - t0
    resizes = sum(1 for rr, _w in env.transitions if rr < 3 + n_rounds)
    return {
        "sketch_elastic_samples_per_sec": round(samples / dt, 2),
        "sketch_elastic_resizes": resizes,
        "sketch_elastic_resize_ms": round(session._fleet_resize_ms, 3),
        "sketch_elastic_retraces": session.retrace_sentinel.retraces,
    }


def _measure_multihost(base, n_rounds: int = 10) -> dict:
    """Multihost PR: the mesh-faked 2-host sketch round (4-axis
    ``(hosts, workers, model, seq)`` mesh, the table psum riding the
    ``(hosts, workers)`` tuple axis) vs its single-host twin on the SAME
    devices and round shape. The ``sketch_multihost_vs_singlehost``
    ratio (multihost sps / singlehost sps, higher is better — registered
    in scripts/check_bench_regression.py) is the leg's design claim:
    declaring the host axis re-SHAPES the mesh without adding a second
    reduction, so the 2-host round must not lose to the flat one (XLA
    lowers the tuple-axis psum to one all-reduce; tests/test_multihost.py
    pins the HLO). Requires >= 2 devices split evenly across the 2
    virtual hosts — a single-chip host reports a named skip marker
    instead of a fake 1.0."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.models import ResNet9, classification_loss
    from commefficient_tpu.models.losses import model_dtype
    from commefficient_tpu.parallel import FederatedSession
    from commefficient_tpu.utils.profiling import fence

    n_dev = len(jax.devices())
    if n_dev < 2 or n_dev % 2:
        return {"sketch_multihost_skipped": (
            f"{n_dev} device(s) — the mesh-faked twin needs an even "
            "multi-device host (2 virtual hosts x n chips; set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 on cpu)"
        )}

    out: dict = {}
    B = base.local_batch_size
    cfg = base.replace(num_devices=n_dev, num_workers=n_dev,
                       num_clients=2 * n_dev)
    try:
        model = ResNet9(num_classes=10, dtype=model_dtype(cfg.compute_dtype))
        params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
        loss_fn = classification_loss(model.apply,
                                      compute_dtype=cfg.compute_dtype)
        rng = np.random.default_rng(0)
        ids = jnp.asarray(np.arange(n_dev, dtype=np.int32))
        data = {
            "x": jnp.asarray(
                rng.normal(size=(n_dev, B, 32, 32, 3)).astype(np.float32)
            ),
            "y": jnp.asarray(
                rng.integers(0, 10, size=(n_dev, B)).astype(np.int32)
            ),
        }
        sps = {}
        for hosts in (1, 2):
            # no explicit mesh: the session builds its own from the
            # config, which is exactly the num_hosts dispatch under test
            session = FederatedSession(cfg.replace(num_hosts=hosts),
                                       params, loss_fn)
            state, round_fn = session.state, session.round_fn
            for _ in range(3):  # compile + donated-layout warmup
                state, m = round_fn(state, ids, data, jnp.float32(0.1))
                assert np.isfinite(fence(m["loss"]))
            t0 = time.perf_counter()
            for _ in range(n_rounds):
                state, m = round_fn(state, ids, data, jnp.float32(0.1))
            assert np.isfinite(fence(m["loss"]))
            sps[hosts] = n_rounds * n_dev * B / (time.perf_counter() - t0)
        out["sketch_multihost_samples_per_sec"] = round(sps[2], 2)
        out["sketch_multihost_vs_singlehost"] = round(sps[2] / sps[1], 3)
    except Exception as e:  # noqa: BLE001 — per-leg error isolation
        out["sketch_multihost_error"] = f"{type(e).__name__}: {e}"[:200]
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--matrix", action="store_true",
        help="also time the non-headline federated paths (sketch-vmap with "
        "clipping, local_topk + local error, fedavg) and write "
        "BENCH_MATRIX.json; the headline line stays the LAST stdout line",
    )
    # ADVICE r5 #3: the two GPT-2-small legs dominate wall-clock and are
    # meaningless on a CPU host (interpret-mode XLA, minutes per round) —
    # default AUTO skips them off-TPU so the CV headline stays cheap.
    # --gpt2 forces them on anywhere; --no-gpt2 forces them off anywhere.
    gp = ap.add_mutually_exclusive_group()
    gp.add_argument("--gpt2", dest="gpt2", action="store_true", default=None,
                    help="force the GPT-2-small legs even on a CPU host")
    gp.add_argument("--no-gpt2", dest="gpt2", action="store_false",
                    help="skip the GPT-2-small legs on any host")
    args = ap.parse_args()

    rows = {}
    if args.matrix:
        # The paths the reference actually calls federated (VERDICT r2 item
        # 5): clip/DP/local-state configs are vmap-per-client (the fused
        # flat-batch identity needs nothing per-client), so they pay W
        # separate gradient passes at B instead of one at W*B.
        base = _headline_cfg()
        matrix = {
            "sketch_vmap_clip": base.replace(
                fuse_clients=False, max_grad_norm=1.0
            ),
            "local_topk_local_err": base.replace(
                mode="local_topk", error_type="local", virtual_momentum=0.0,
                fuse_clients=False,
            ),
            "fedavg_4local": base.replace(
                mode="fedavg", error_type="none", virtual_momentum=0.0,
                num_local_iters=4,
            ),
            "uncompressed_fused": base.replace(
                mode="uncompressed", error_type="none", virtual_momentum=0.0,
            ),
            # r3 mixed precision: model fwd/bwd in bf16 (native MXU),
            # master params / grads / sketch algebra stay f32 —
            # lab-validated accuracy parity (CHANGELOG_r3)
            "sketch_fused_bf16": base.replace(compute_dtype="bfloat16"),
            # PR 2: rank-4 PowerSGD vs the sketch headline at the same
            # round shape (server-side GS power iteration replaces the
            # unsketch extract)
            "powersgd_r4_fused": base.replace(mode="powersgd",
                                              powersgd_rank=4),
            # PR 3 telemetry: the level-2 in-graph diagnostics (norms +
            # sentinel + sketch round-trip fidelity) riding the headline
            # round — tracks the observability tax against the level-0
            # headline (which is bit-identical to pre-telemetry rounds)
            "sketch_telemetry_l2": base.replace(telemetry_level=2),
            # fedsim PR: the headline sketch round under bernoulli 30%
            # dropout — masked per-client transmits (vmap path: masking
            # disables the fused fast path) + live-count renormalization.
            # Tracks the partial-participation tax against the fused
            # headline AND against sketch_vmap_clip (its vmap twin).
            "sketch_dropout30": base.replace(
                availability="bernoulli", dropout_prob=0.3
            ),
        }
        for name, cfg in matrix.items():
            # per-leg error isolation (the GPT-2 legs' pattern): one leg's
            # failure must not discard the others' measured rows
            try:
                sps = _measure(cfg)
            except Exception as e:  # noqa: BLE001
                rows[f"{name}_error"] = f"{type(e).__name__}: {e}"[:200]
                print(json.dumps({"metric": name,
                                  "error": rows[f"{name}_error"]}))
                continue
            rows[name] = round(sps, 2)
            print(json.dumps({"metric": name, "value": rows[name],
                              "unit": "samples/s"}))
        # control PR: the rung-switch cost on the headline sketch round —
        # 2-rung k-ladder, fixed schedule switching halfway. The retrace
        # count is the design claim (0: the switch dispatches a prewarmed
        # program); switch_round_ms is its one-off backend-compile +
        # state-migration cost; steady sps tracks the (expected-zero)
        # controller host tax vs the headline.
        try:
            ctl = _measure_ladder_switch(base)
        except Exception as e:  # noqa: BLE001
            rows["sketch_ladder_error"] = f"{type(e).__name__}: {e}"[:200]
            print(json.dumps({"metric": "sketch_ladder_switch",
                              "error": rows["sketch_ladder_error"]}))
        else:
            rows.update(ctl)
            print(json.dumps({"metric": "sketch_ladder_switch", **ctl}))
        # resilience PR: snapshot/rollback primitive cost on the headline
        # round — the recovery tax is paid per --snapshot_every boundary
        # (snapshot) and per divergence (rollback); retraces must be 0
        # (the restore re-commits leaves onto their original shardings).
        try:
            res = _measure_recovery(base)
        except Exception as e:  # noqa: BLE001
            rows["sketch_resilience_error"] = f"{type(e).__name__}: {e}"[:200]
            print(json.dumps({"metric": "sketch_resilience",
                              "error": rows["sketch_resilience_error"]}))
        else:
            rows.update(res)
            print(json.dumps({"metric": "sketch_resilience", **res}))
        # sparse-aggregate PR: pair-exchange vs dense-psum twins per topk
        # mode on the multi-device mesh (per-mode error isolation happens
        # inside; a single-device host yields only a skip marker)
        try:
            sa = _measure_sparse_agg(base)
        except Exception as e:  # noqa: BLE001
            rows["sparse_agg_error"] = f"{type(e).__name__}: {e}"[:200]
            print(json.dumps({"metric": "sparse_agg",
                              "error": rows["sparse_agg_error"]}))
        else:
            rows.update(sa)
            print(json.dumps({"metric": "sparse_agg", **sa}))
        # clientstore PR: the host-resident client-state round vs its
        # device-resident twin (per-leg error isolation happens inside)
        try:
            hc = _measure_hostclient(base)
        except Exception as e:  # noqa: BLE001
            rows["local_topk_hostclient_error"] = \
                f"{type(e).__name__}: {e}"[:200]
            print(json.dumps({"metric": "local_topk_hostclient",
                              "error": rows["local_topk_hostclient_error"]}))
        else:
            rows.update(hc)
            print(json.dumps({"metric": "local_topk_hostclient", **hc}))
        # asyncfed PR: the buffered-async engine vs its synchronous twin
        # under ~40% poisson stragglers — server-update rate, time to the
        # sync twin's final loss, and the hard-zero retrace invariant
        try:
            asy = _measure_async(base)
        except Exception as e:  # noqa: BLE001
            rows["sketch_async_error"] = f"{type(e).__name__}: {e}"[:200]
            print(json.dumps({"metric": "sketch_async",
                              "error": rows["sketch_async_error"]}))
        else:
            rows.update(asy)
            print(json.dumps({"metric": "sketch_async", **asy}))
        # hidden-collectives PR: layerwise-segmented collectives and the
        # double-buffered asyncfed apply vs their sequential twins (skip
        # markers on a single-device host — nothing cross-chip to hide)
        try:
            ovl = _measure_overlap(base)
        except Exception as e:  # noqa: BLE001
            rows["sketch_overlap_error"] = f"{type(e).__name__}: {e}"[:200]
            print(json.dumps({"metric": "sketch_overlap",
                              "error": rows["sketch_overlap_error"]}))
        else:
            rows.update(ovl)
            print(json.dumps({"metric": "sketch_overlap", **ovl}))
        # round-tracing PR: critical-path attribution of the headline
        # sketch round — mean exclusive ms per stage + the binding
        # stage's name (every measured round fenced, so rows are
        # honest wall-clock but slower than the headline by design:
        # informational, never gated)
        try:
            tr = _measure_traced(base)
        except Exception as e:  # noqa: BLE001
            rows["sketch_traced_error"] = f"{type(e).__name__}: {e}"[:200]
            print(json.dumps({"metric": "sketch_traced",
                              "error": rows["sketch_traced_error"]}))
        else:
            rows.update(tr)
            print(json.dumps({"metric": "sketch_traced", **tr}))
        # multihost PR: the mesh-faked 2-host round vs its single-host
        # twin (per-leg error isolation happens inside; an odd/single
        # device host yields only a named skip marker)
        try:
            mh = _measure_multihost(base)
        except Exception as e:  # noqa: BLE001
            rows["sketch_multihost_error"] = f"{type(e).__name__}: {e}"[:200]
            print(json.dumps({"metric": "sketch_multihost",
                              "error": rows["sketch_multihost_error"]}))
        else:
            rows.update(mh)
            print(json.dumps({"metric": "sketch_multihost", **mh}))
        # elastic-fleet PR: the headline round across a scheduled width
        # shrink + grow through the real width ladder — resize cost and
        # the hard-zero retrace gauge (per-leg error isolation as above)
        try:
            el = _measure_elastic(base)
        except Exception as e:  # noqa: BLE001
            rows["sketch_elastic_error"] = f"{type(e).__name__}: {e}"[:200]
            print(json.dumps({"metric": "sketch_elastic",
                              "error": rows["sketch_elastic_error"]}))
        else:
            rows.update(el)
            print(json.dumps({"metric": "sketch_elastic", **el}))

    # pipeline PR: the pipelined-execution leg rides the HEADLINE line
    # (gated by scripts/check_bench_regression.py — occupancy + samples/s
    # directions registered there), with the same per-leg error isolation
    # as the GPT-2 legs: an engine failure must not discard the headline.
    pipe: dict = {}
    try:
        pipe = _measure_pipeline(_headline_cfg())
        print(json.dumps({"metric": "sketch_pipelined", **pipe}))
    except Exception as e:  # noqa: BLE001
        pipe = {"sketch_pipelined_error": f"{type(e).__name__}: {e}"[:200]}
        print(json.dumps({"metric": "sketch_pipelined",
                          "error": pipe["sketch_pipelined_error"]}))

    audit_box: dict = {}
    headline = _measure(_headline_cfg(), audit_box=audit_box)
    headline_audit = audit_box.pop("_audit", None)
    headline_cfg = audit_box.pop("_cfg", None)
    peak, chip, assumed = _chip_peak_flops()
    mfu = headline * resnet9_train_flops_per_sample() / peak
    # GPT-2 line (VERDICT r4 weak 5 / item 8): language-scale perf was
    # wall-clock seconds in lab logs with nobody tracking regressions —
    # now tokens/s + MFU for the BASELINE #4 sketch round and its
    # uncompressed twin ride the same headline JSON line every round.
    gpt2 = {}
    import jax

    run_gpt2 = (
        args.gpt2
        if args.gpt2 is not None
        else jax.devices()[0].platform != "cpu"
    )
    if not run_gpt2:
        gpt2 = {"gpt2_skipped": (
            "cpu host (auto; pass --gpt2 to force)"
            if args.gpt2 is None else "--no-gpt2"
        )}
    else:
        # the sketch leg runs PER BACKEND (the r5 3.5x sketch-round gap
        # is a kernel property): einsum keeps the legacy key names so
        # BENCH_r* rows stay comparable; pallas gets suffixed keys. Each
        # leg fails INDEPENDENTLY (per-leg *_error key) — a Mosaic/pallas
        # failure must not discard the measured legacy einsum rows, and
        # the CV headline must survive any of them.
        legs = [("uncompressed", "einsum", "gpt2_uncompressed", 0),
                ("sketch", "einsum", "gpt2_sketch", 0),
                # scan-engine dispatch amortization on the SAME optimized
                # sketch config: 8 rounds per lax.scan dispatch (the
                # sketch-gap PR; pipeline/scan_engine.py is the train-loop
                # realization, this leg isolates the dispatch win)
                ("sketch", "einsum", "gpt2_sketch_scan", 8),
                # per-mode leg (PR 2): the PowerSGD round rides the same
                # line so its GS/matmul server cost is tracked vs the twins
                ("powersgd", "einsum", "gpt2_powersgd", 0)]
        if len(jax.devices()) > 1:
            # sharded-decode leg (PR 6): the change that targets the
            # headline gpt2_sketch_vs_uncompressed gap — each chip decodes
            # only its D/W slice, ~W*k candidate pairs replace the full-D
            # server extraction. Its uncompressed twin runs on the SAME
            # multichip mesh so the ratio isolates the decode (a 1-chip
            # denominator would credit the added chips to the decode).
            # Single-chip hosts skip both: with one worker device the
            # 'sharded' decode is the degenerate full-range gather path
            # (strictly worse — auto picks dense there), not a
            # measurement of the design.
            legs.append(("uncompressed_multichip", "einsum",
                         "gpt2_uncompressed_multichip", 0))
            legs.append(("sketch_sharded", "einsum", "gpt2_sketch_sharded",
                         0))
        else:
            gpt2["gpt2_sketch_sharded_skipped"] = (
                "sharded decode needs a >1-device workers mesh (auto "
                "resolves dense on one chip; nothing to measure)"
            )
        if jax.default_backend() == "tpu":
            # the pallas kernels compile through Mosaic only on TPU; any
            # other backend (a GPU host forced past the cpu auto-skip)
            # would run them under interpret mode — minutes per call at
            # D=124M, a stalled bench rather than a measurement
            legs.append(("sketch", "pallas", "gpt2_sketch_pallas", 0))
        else:
            gpt2["gpt2_sketch_pallas_skipped"] = (
                "pallas leg needs a TPU backend (interpret mode is not a "
                "measurement)"
            )
        for m, backend, key, scan in legs:
            try:
                tps, gmfu, spr, audit_keys = _measure_gpt2(
                    m, sketch_backend=backend, scan_rounds=scan
                )
            except Exception as e:  # noqa: BLE001
                gpt2[f"{key}_error"] = f"{type(e).__name__}: {e}"[:200]
                continue
            gpt2[f"{key}_tokens_per_sec"] = round(tps, 1)
            gpt2[f"{key}_mfu"] = round(gmfu, 4)
            gpt2[f"{key}_sec_per_round"] = round(spr, 4)
            if scan:
                gpt2[f"{key}_rounds_per_dispatch"] = scan
            for ak, av in audit_keys.items():
                # audited per-leg FLOPs / peak-HBM / MFU from the compiled
                # artifact, next to the hand-model numbers above
                gpt2[f"{key}_{ak}"] = av
        for key in ("gpt2_sketch", "gpt2_sketch_scan", "gpt2_sketch_pallas",
                    "gpt2_powersgd", "gpt2_sketch_sharded"):
            num = gpt2.get(f"{key}_tokens_per_sec")
            # the sharded leg compares against its SAME-mesh uncompressed
            # twin; everything else against the 1-chip baseline
            den = gpt2.get(
                "gpt2_uncompressed_multichip_tokens_per_sec"
                if key == "gpt2_sketch_sharded"
                else "gpt2_uncompressed_tokens_per_sec"
            )
            if num is not None and den:
                gpt2[f"{key}_vs_uncompressed"] = round(num / den, 4)
    import jaxlib

    line = {
        "metric": "fed_resnet9_sketch_train_samples_per_sec_per_chip",
        "value": round(headline, 2),
        "unit": "samples/s",
        "vs_baseline": round(headline / BASELINE_SAMPLES_PER_SEC, 4),
        # model-FLOPs utilization: samples/s x analytic ResNet-9
        # fwd+bwd FLOPs / chip bf16 peak — hardware-anchored, unlike
        # vs_baseline's A100-class estimate (VERDICT r3 weak 5)
        "mfu": round(mfu, 4),
        "chip": chip,
        # run provenance, so trajectory comparisons (scripts/
        # check_bench_regression.py) are apples-to-apples across hosts
        "devices": len(jax.devices()),
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        # audited twin of mfu/headline from the compiled round artifact
        # (telemetry/xla_audit.py; `audit_error` when it degraded)
        **audit_box,
        # pipelined-execution leg (pipeline/ PR): depth-2 vs synchronous
        # host staging, engine occupancy + residual host stall
        **pipe,
        **gpt2,
    }
    if assumed:
        # MFU denominator is a guess on this hardware — say so in-band
        line["peak_flops_assumed"] = peak
    if headline_audit is not None:
        # the schema-valid perf_report.json artifact for the headline
        # round (acceptance: bench writes one; checker-validated)
        try:
            headline_audit.write(".", generated_by="bench",
                                 cfg=headline_cfg)
        except Exception as e:  # noqa: BLE001
            line["perf_report_error"] = f"{type(e).__name__}: {e}"[:200]
    if args.matrix:
        rows["sketch_fused_headline"] = round(headline, 2)
        rows["mfu_model_flops"] = round(mfu, 4)
        rows["chip"] = chip
        if assumed:  # same in-band marker as the headline line
            rows["peak_flops_assumed"] = peak
        rows.update(audit_box)
        rows.update(pipe)
        rows.update(gpt2)
        with open("BENCH_MATRIX.json", "w") as f:
            json.dump(rows, f, indent=2)
    print(json.dumps(line))


if __name__ == "__main__":
    main()
