"""KV-cache decoding (models/generate.py) vs full re-forward oracle."""

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.models.generate import generate
from commefficient_tpu.models.gpt2 import GPT2Config, GPT2DoubleHeads

CFG = GPT2Config(vocab_size=97, n_positions=48, n_embd=32, n_layer=2,
                 n_head=4, dtype=jnp.float32)


def _setup(seed=0, B=2, T0=9):
    model = GPT2DoubleHeads(CFG)
    rng = np.random.default_rng(seed)
    ids = jnp.asarray(rng.integers(0, CFG.vocab_size, size=(B, T0)).astype(np.int32))
    params = model.init(jax.random.key(1), ids[:, None, :])
    return model, params, ids


def _oracle_greedy(model, params, ids, n_new):
    """Append argmax tokens by re-running the FULL dense model each step."""
    for _ in range(n_new):
        lm, _ = model.apply(params, ids[:, None, :])
        nxt = jnp.argmax(lm[:, 0, -1], -1).astype(jnp.int32)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    return ids


def test_greedy_matches_full_reforward():
    model, params, ids = _setup()
    want = _oracle_greedy(model, params, ids, 7)
    got = generate(CFG, params, ids, 7)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_single_new_token():
    model, params, ids = _setup(seed=3)
    want = _oracle_greedy(model, params, ids, 1)
    got = generate(CFG, params, ids, 1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_token_types_affect_decode():
    model, params, ids = _setup(seed=4)
    tt = jnp.full(ids.shape, 5, jnp.int32)
    out_a = generate(CFG, params, ids, 4, token_type_ids=tt, new_token_type=5)
    out_b = generate(CFG, params, ids, 4)
    assert out_a.shape == out_b.shape == (ids.shape[0], ids.shape[1] + 4)
    # type embeddings change the logits, so decodes should diverge
    assert not np.array_equal(np.asarray(out_a), np.asarray(out_b))


def test_eos_fills_tail():
    model, params, ids = _setup(seed=5)
    # force eos immediately: every token is eos once the first one is hit
    out = generate(CFG, params, ids, 6, eos_token_id=int(
        np.asarray(generate(CFG, params, ids, 1))[0, -1]
    ))
    tail = np.asarray(out)[0, ids.shape[1]:]
    assert (tail == tail[0]).all()  # first new token is eos -> all eos


def test_sampling_is_seeded_and_in_topk():
    model, params, ids = _setup(seed=6)
    r = jax.random.key(7)
    a = generate(CFG, params, ids, 5, temperature=0.8, top_k=4, rng=r)
    b = generate(CFG, params, ids, 5, temperature=0.8, top_k=4, rng=r)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))  # same seed
    c = generate(CFG, params, ids, 5, temperature=0.8, top_k=4,
                 rng=jax.random.key(8))
    assert a.shape == c.shape
    # every sampled token must be inside the step's top-4 set: verify for
    # the FIRST new token, whose distribution we can recompute exactly
    lm, _ = model.apply(params, ids[:, None, :])
    top4 = np.asarray(jax.lax.top_k(lm[:, 0, -1], 4)[1])
    first = np.asarray(a)[:, ids.shape[1]]
    for row in range(first.shape[0]):
        assert first[row] in top4[row]
