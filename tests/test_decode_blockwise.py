"""VMEM-blockwise fused estimate kernel (ops/pallas/decode_kernels.py).

The pre-blockwise ``estimate_at_pallas`` SILENTLY fell back to the
unfused gather path whenever the [r, c] table exceeded its 12 MiB VMEM
guard — which made the fused kernel inert at exactly the scale it was
built for (the GPT-2 5x5M table is ~100 MB). Now the table streams
through VMEM in column blocks; pinned here under interpret mode:

  * the blocked path is BIT-equal to ``estimate_at`` (each coordinate's
    column lands in exactly one block per row, so the masked
    accumulation sums one value and zeros — no float reassociation), at
    a real above-guard geometry (D >= 1.2M, table > 12 MiB) under the
    poly4 hash family, and at a small forced-many-block geometry;
  * the single-block fast path (table within the guard) is untouched;
  * engagement is LOGGED once (the silent-fallback satellite), naming
    the table bytes, the budget and the block count.
"""

import logging

import jax.numpy as jnp
import numpy as np
import pytest

import commefficient_tpu.ops.pallas.decode_kernels as dk
from commefficient_tpu.ops.countsketch import CountSketch, estimate_at
from commefficient_tpu.ops.pallas.decode_kernels import (
    VMEM_TABLE_BYTES,
    estimate_at_pallas,
)


def _random_table(spec, seed=0):
    # kernel parity needs a table, not a VALID sketch — random values
    # exercise the same gather/median math at a fraction of the cost
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        rng.normal(size=spec.table_shape).astype(np.float32)
    )


@pytest.mark.parametrize("family", ["fmix32", "poly4"])
def test_blockwise_above_guard_bit_equal_at_gpt2ish_scale(family):
    """The satellite geometry: D >= 1.2M (odd — every padding seam), a
    table over the REAL 12 MiB guard (r=3, c_actual > 1.05M floats), the
    4-universal poly4 family included. The blocked path must engage and
    be bit-equal to the unfused gather estimate."""
    d = 1_200_003
    spec = CountSketch(d=d, c=1_100_000, r=3, seed=11, hash_family=family)
    r, c_actual = spec.table_shape
    assert r * c_actual * 4 > VMEM_TABLE_BYTES, (
        "geometry must exceed the single-block budget or this test "
        "pins nothing"
    )
    table = _random_table(spec)
    rng = np.random.default_rng(1)
    idx = jnp.asarray(rng.choice(d, size=4096, replace=False).astype(np.int32))
    got = np.asarray(estimate_at_pallas(spec, table, idx))
    want = np.asarray(estimate_at(spec, table, idx))
    np.testing.assert_array_equal(got, want)


def test_blockwise_many_blocks_bit_equal(monkeypatch):
    """Force a many-block split on a small geometry (budget shrunk to a
    few KiB) — covers block-boundary seams (columns at multiples of CB,
    the padded tail block) cheaply, r=5 median network included."""
    spec = CountSketch(d=50_011, c=8_000, r=5, seed=7)
    monkeypatch.setattr(dk, "VMEM_TABLE_BYTES", 1 << 14)  # CB ~ 768
    assert spec.table_shape[0] * spec.table_shape[1] * 4 > (1 << 14)
    table = _random_table(spec, seed=2)
    rng = np.random.default_rng(3)
    idx = jnp.asarray(rng.choice(50_011, size=1025, replace=False).astype(
        np.int32))
    got = np.asarray(estimate_at_pallas(spec, table, idx))
    want = np.asarray(estimate_at(spec, table, idx))
    np.testing.assert_array_equal(got, want)


def test_single_block_fast_path_bit_equal():
    spec = CountSketch(d=10_000, c=2_000, r=5, seed=7)
    assert spec.table_shape[0] * spec.table_shape[1] * 4 <= VMEM_TABLE_BYTES
    table = _random_table(spec, seed=4)
    rng = np.random.default_rng(5)
    idx = jnp.asarray(rng.choice(10_000, size=513, replace=False).astype(
        np.int32))
    np.testing.assert_array_equal(
        np.asarray(estimate_at_pallas(spec, table, idx)),
        np.asarray(estimate_at(spec, table, idx)),
    )


def test_blockwise_engagement_logged_once(monkeypatch, caplog):
    """The silent-fallback satellite: above-budget tables must SAY so —
    one log record naming the table MiB, the budget and the block count;
    repeated calls at the same geometry stay quiet."""
    spec = CountSketch(d=20_000, c=4_000, r=3, seed=9)
    monkeypatch.setattr(dk, "VMEM_TABLE_BYTES", 1 << 14)
    monkeypatch.setattr(dk, "_blockwise_logged", set())
    table = _random_table(spec, seed=6)
    idx = jnp.arange(256, dtype=jnp.int32)
    with caplog.at_level(logging.INFO, logger=dk.logger.name):
        estimate_at_pallas(spec, table, idx)
        first = [r for r in caplog.records if "column blocks" in r.message]
        estimate_at_pallas(spec, table, idx)
        second = [r for r in caplog.records if "column blocks" in r.message]
    assert len(first) == 1, "engagement must be logged"
    assert len(second) == 1, "…exactly once per geometry"
    msg = first[0].getMessage()
    assert "VMEM" in msg and "block" in msg


def test_bf16_table_estimates_in_f32():
    """A bf16-STORED table estimates identically to its f32 upcast (the
    kernel reads f32; only the storage rounding differs — and here the
    bf16 table IS the reference input, so equality is exact)."""
    spec = CountSketch(d=10_000, c=2_000, r=3, seed=7,
                       table_dtype=jnp.bfloat16)
    table = _random_table(spec).astype(jnp.bfloat16)
    idx = jnp.arange(512, dtype=jnp.int32)
    got = np.asarray(estimate_at_pallas(spec, table, idx))
    want = np.asarray(estimate_at(spec, table.astype(jnp.float32), idx))
    np.testing.assert_array_equal(got, want)
