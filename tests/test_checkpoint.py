"""Checkpoint/resume: kill-mid-training and resume must reproduce the
uninterrupted run bit-for-bit (VERDICT r1 item 4 'done' criterion)."""

import numpy as np
import pytest

from commefficient_tpu.data import FedSampler
from commefficient_tpu.parallel import FederatedSession
from commefficient_tpu.utils.checkpoint import FedCheckpointer
from commefficient_tpu.utils.config import Config

from tests.test_round import BASE, _setup


def _train(sess, sampler, cfg, start, stop, ckpt=None):
    for r in range(start, stop):
        ids, batch = sampler.sample_round(r)
        sess.train_round(ids, batch, lr=0.1 + 0.02 * r)  # varying lr
        if ckpt is not None:
            ckpt.maybe_save(sess, r + 1)


@pytest.mark.parametrize("mode,extra", [
    ("sketch", dict(error_type="virtual", virtual_momentum=0.9, k=40,
                    num_rows=3, num_cols=512)),
    ("local_topk", dict(error_type="local", local_momentum=0.9, k=30)),
    ("local_topk", dict(error_type="local", k=30, offload_client_state=True)),
    # powersgd: the warm-start Q rides in FedState.comp and must survive
    # the kill/restore for the resumed run to be bit-for-bit (PR 2)
    ("powersgd", dict(error_type="virtual", virtual_momentum=0.9,
                      powersgd_rank=2)),
])
def test_kill_and_resume_reproduces_uninterrupted_run(tmp_path, mode, extra):
    cfg = Config(mode=mode, **extra, **BASE)

    # uninterrupted: 8 rounds
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess_a = FederatedSession(cfg, params, loss_fn)
    samp = FedSampler(ds, num_workers=cfg.num_workers,
                      local_batch_size=cfg.local_batch_size, seed=1)
    _train(sess_a, samp, cfg, 0, 8)

    # interrupted: 4 rounds, checkpoint, fresh process state, restore, 4 more
    ck_cfg = cfg.replace(checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=4)
    ds2, params2, loss_fn2 = _setup(cfg.num_clients)
    sess_b = FederatedSession(ck_cfg, params2, loss_fn2)
    ckpt = FedCheckpointer(ck_cfg)
    _train(sess_b, samp, ck_cfg, 0, 4, ckpt)
    ckpt.close()

    sess_c = FederatedSession(ck_cfg, params2, loss_fn2)  # fresh state
    ckpt2 = FedCheckpointer(ck_cfg)
    resumed = ckpt2.restore(sess_c)
    assert resumed == 4
    _train(sess_c, samp, ck_cfg, 4, 8)
    ckpt2.close()

    np.testing.assert_array_equal(
        np.asarray(sess_a.state.params_vec), np.asarray(sess_c.state.params_vec)
    )
    if mode == "local_topk" and not extra.get("offload_client_state"):
        np.testing.assert_array_equal(
            np.asarray(sess_a.state.client_err), np.asarray(sess_c.state.client_err)
        )
    if extra.get("offload_client_state"):
        np.testing.assert_array_equal(sess_a.host_err, sess_c.host_err)


def test_checkpointer_disabled_without_dir():
    cfg = Config(mode="uncompressed", **BASE)
    ck = FedCheckpointer(cfg)
    assert not ck.enabled
    assert ck.restore(None) is None
    assert not ck.maybe_save(None, 10)


def test_restore_rejects_mismatched_model(tmp_path):
    cfg = Config(mode="uncompressed", checkpoint_dir=str(tmp_path / "ck"),
                 checkpoint_every=1, **BASE)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    samp = FedSampler(ds, num_workers=cfg.num_workers,
                      local_batch_size=cfg.local_batch_size, seed=1)
    ck = FedCheckpointer(cfg)
    _train(sess, samp, cfg, 0, 1, ck)
    ck.close()

    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    class Other(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(x)

    other = Other()
    oparams = other.init(jax.random.key(0), jnp.zeros((1, 8)))
    from commefficient_tpu.models.losses import classification_loss
    sess2 = FederatedSession(cfg, oparams, classification_loss(other.apply))
    ck2 = FedCheckpointer(cfg)
    with pytest.raises(ValueError, match="grad_size"):
        ck2.restore(sess2)
    ck2.close()


def test_restore_refuses_mismatched_sketch_layout(tmp_path):
    """A sketch checkpoint's [r, c] tables are only decodable under the
    layout that wrote them: equal shapes do NOT imply equal layouts (r4's
    adaptive scramble block changed the permutation at unchanged shapes),
    so restore must refuse on a fingerprint mismatch instead of silently
    corrupting training."""
    base = dict(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                k=40, num_rows=3, num_cols=512,
                checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
                **BASE)
    cfg = Config(**base)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    samp = FedSampler(ds, num_workers=cfg.num_workers,
                      local_batch_size=cfg.local_batch_size, seed=1)
    ckpt = FedCheckpointer(cfg)
    _train(sess, samp, cfg, 0, 2, ckpt)
    ckpt.close()

    # same shapes, different layout: force a different scramble block via a
    # spec override (the exact r3->r4 hazard)
    sess2 = FederatedSession(cfg, params, loss_fn)
    # (at this tiny scale the adaptive default already resolves to 8, so
    # pin a genuinely different block)
    sess2.spec = sess2.spec._replace(scramble_block=16)
    ckpt2 = FedCheckpointer(cfg)
    with pytest.raises(ValueError, match="sketch layout"):
        ckpt2.restore(sess2)
    ckpt2.close()

    # matching session restores fine
    sess3 = FederatedSession(cfg, params, loss_fn)
    ckpt3 = FedCheckpointer(cfg)
    assert ckpt3.restore(sess3) == 2
    ckpt3.close()


def test_restore_accepts_pre_comp_checkpoint(tmp_path):
    """Checkpoints written BEFORE the compress/ registry (PR 2) have a
    6-leaf fed_state (no ``comp``); StandardRestore raises 'Dict key
    mismatch' on any template/saved key difference, so restore must adapt
    its template instead of stranding every old checkpoint."""
    import orbax.checkpoint as ocp

    from commefficient_tpu.utils.checkpoint import _to_saveable

    cfg = Config(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                 k=40, num_rows=3, num_cols=512,
                 checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
                 **BASE)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    samp = FedSampler(ds, num_workers=cfg.num_workers,
                      local_batch_size=cfg.local_batch_size, seed=1)
    _train(sess, samp, cfg, 0, 2)

    # write a LEGACY-format checkpoint: today's state, pre-PR2 key set
    blob = _to_saveable(sess)
    assert blob["fed_state"].pop("comp") == ()
    import os

    mngr = ocp.CheckpointManager(
        os.path.abspath(cfg.checkpoint_dir),
        options=ocp.CheckpointManagerOptions(max_to_keep=3),
    )
    mngr.save(2, args=ocp.args.StandardSave(blob))
    mngr.wait_until_finished()
    mngr.close()

    sess2 = FederatedSession(cfg, params, loss_fn)
    ck = FedCheckpointer(cfg)
    assert ck.restore(sess2) == 2
    ck.close()
    np.testing.assert_array_equal(
        np.asarray(sess.state.params_vec), np.asarray(sess2.state.params_vec)
    )
    np.testing.assert_array_equal(
        np.asarray(sess.state.error), np.asarray(sess2.state.error)
    )
    assert sess2.state.comp == ()
