"""End-to-end smoke tests: cv_train loop, graft entry points."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.train.cv_train import main as cv_main


def test_cv_train_femnist_end_to_end(tmp_path):
    """BASELINE config #3 shape (shrunk): femnist non-IID, local_topk+error."""
    val = cv_main(
        [],
        dataset_name="femnist",
        model="resnet9",
        mode="local_topk",
        error_type="local",
        k=2000,
        num_clients=6,
        num_workers=4,
        num_devices=4,
        local_batch_size=32,  # 1-core CPU budget: 5 rounds, not 44
        num_epochs=1,
        pivot_epoch=1,
        lr_scale=0.1,
        dataset_dir=str(tmp_path),
        logdir=str(tmp_path / "runs"),
        seed=0,
    )
    assert np.isfinite(val["loss"])
    assert 0.0 <= val["accuracy"] <= 1.0


@pytest.mark.slow  # ~37s ResNet-9 compile: tier-1 budget (PR 18) — a
# mode-twin of the femnist e2e above; powersgd algebra and round parity
# keep their own tier-1 coverage in tests/test_powersgd.py
def test_cv_train_powersgd_end_to_end(tmp_path):
    """PR 2 acceptance: mode=powersgd trains end-to-end through the real
    cv_train entry (CLI flags -> Config -> compress/ registry -> round),
    warm-started rank-2 with virtual error feedback, on the femnist
    stand-in (the cheapest real dataset path on the 1-core CPU budget)."""
    val = cv_main(
        [],
        dataset_name="femnist",
        model="resnet9",
        mode="powersgd",
        error_type="virtual",
        virtual_momentum=0.9,
        powersgd_rank=2,
        num_clients=6,
        num_workers=4,
        num_devices=4,
        local_batch_size=32,
        num_epochs=1,
        pivot_epoch=1,
        lr_scale=0.1,
        dataset_dir=str(tmp_path),
        logdir=str(tmp_path / "runs"),
        seed=0,
    )
    assert np.isfinite(val["loss"])
    assert 0.0 <= val["accuracy"] <= 1.0


@pytest.mark.slow  # same path as test_cv_train_takes_device_data_path_e2e
# (femnist, uncompressed, cv_main) which stays in the default tier
def test_cv_train_uncompressed_single_worker(tmp_path):
    """BASELINE config #1: uncompressed, 1 worker, CPU-runnable."""
    val = cv_main(
        [],
        dataset_name="femnist",
        mode="uncompressed",
        num_clients=2,
        num_workers=1,
        num_devices=1,
        local_batch_size=16,
        num_epochs=1,
        pivot_epoch=1,
        lr_scale=0.05,
        dataset_dir=str(tmp_path),
        logdir=str(tmp_path / "runs"),
        seed=0,
    )
    assert np.isfinite(val["loss"])


def test_graft_entry_compiles():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (64, 10)


@pytest.mark.slow  # the driver runs dryrun_multichip directly every round;
# the suite's copy is belt-and-braces for local iteration
def test_graft_dryrun_multichip_8():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


@pytest.mark.slow  # the pieces hold default-tier coverage separately:
# fixup forward (test_models), imagenet loader (test_data), RRC augmenter
# (test_imagenet_augment), cv_train e2e (femnist tests)
def test_cv_train_imagenet_fixup_end_to_end(tmp_path):
    """BASELINE config #5 shape (shrunk): FixupResNet-50 on ImageNet via
    the real npy-cache path (a tiny 64-image cache written here —
    the synthetic fallback's 20k images are TPU-run scale, not CPU-test
    scale), uncompressed over the mesh. Also regression-tests that
    num_classes reaches the loader (labels < head size)."""
    import os

    rng = np.random.default_rng(3)
    root = tmp_path / "imagenet"
    os.makedirs(root)
    np.save(root / "imagenet_x.npy",  # 32x32: conv compile cost, 1-core CPU
            rng.integers(0, 256, size=(64, 32, 32, 3)).astype(np.uint8))
    np.save(root / "imagenet_y.npy",
            rng.integers(0, 10, size=(64,)).astype(np.int32))
    val = cv_main(
        [],
        dataset_name="imagenet",
        model="fixup_resnet50",
        num_classes=10,
        mode="uncompressed",
        num_clients=4,
        num_workers=2,
        num_devices=2,
        local_batch_size=2,
        num_epochs=1,
        pivot_epoch=1,
        lr_scale=0.05,
        dataset_dir=str(tmp_path),
        logdir=str(tmp_path / "runs"),
        seed=0,
    )
    assert np.isfinite(val["loss"])
    assert 0.0 <= val["accuracy"] <= 1.0
