"""Compiled-graph performance observability (ISSUE 7) on the 8-device CPU
mesh: the XLA cost/memory/collective audit and its ledger cross-check
(dense vs sharded sketch decode), the retrace sentinel, host phase spans,
the perf_report.json schema round-trip through the checker, and the
level-0 no-added-ops HLO pin (golden registry parity is carried by
tests/test_compress_parity.py — the audit adds NOTHING to the traced
round, pinned here by byte-identical lowered HLO)."""

import glob
import importlib.util
import json
import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.data import FedDataset, FedSampler
from commefficient_tpu.models.losses import classification_loss
from commefficient_tpu.parallel import FederatedSession
from commefficient_tpu.telemetry import PhaseSpans, RetraceError
from commefficient_tpu.telemetry.xla_audit import (
    RetraceSentinel,
    collective_audit,
    signature_diff,
)
from commefficient_tpu.utils.config import Config
from commefficient_tpu.utils.logging import MetricsWriter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _checker():
    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(REPO, "scripts", "check_telemetry_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TinyMLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(16)(x))
        return nn.Dense(4)(x)


BASE = dict(num_clients=12, num_workers=8, num_devices=8, local_batch_size=4,
            weight_decay=0.0, seed=5)
SKETCH = dict(mode="sketch", error_type="virtual", virtual_momentum=0.9,
              k=40, num_rows=3, num_cols=256, topk_method="threshold")


def _setup(num_clients=12, n=400):
    rng = np.random.default_rng(0)
    w = rng.normal(size=(8, 4))
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = np.argmax(x @ w + 0.1 * rng.normal(size=(n, 4)), axis=1).astype(
        np.int32
    )
    ds = FedDataset({"x": x, "y": y}, num_clients, iid=True, seed=0)
    model = TinyMLP()
    params = model.init(jax.random.key(0), jnp.zeros((1, 8)))
    return ds, params, classification_loss(model.apply)


def _session_and_round0(cfg):
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.sampler_batch_size, seed=1)
    ids, batch = sampler.sample_round(0)
    return sess, sampler, ids, batch


# ---------------------------------------------------------------------------
# collective audit + ledger cross-check (tentpole piece 2)
# ---------------------------------------------------------------------------

def test_collective_cross_check_dense_vs_sharded():
    """The ISSUE-7 acceptance cross-check: on BOTH sketch decode paths the
    compiled round's collective bytes reconcile with the CommLedger's
    analytic accounting (dense: the table psum IS the per-link upload, so
    the delta is scalar slop; sharded: the known extra design traffic —
    EF re-sketch psum + <= W*k candidate gathers — is inside the recorded
    tolerance), and the sharded round's gathers respect the PR-6 bound."""
    audits = {}
    for dec in ("dense", "sharded"):
        cfg = Config(telemetry_level=1, sketch_decode=dec, **SKETCH, **BASE)
        sess, _, ids, batch = _session_and_round0(cfg)
        audits[dec] = (sess, sess.audit_compiled_round(ids, batch, 0.2))
    for dec, (sess, audit) in audits.items():
        coll = audit.collectives
        assert coll["ledger_up_bytes"] == sess.bytes_per_round()[
            "upload_bytes"
        ]
        assert coll["within_tolerance"], (
            f"{dec}: ledger-vs-HLO delta {coll['delta_bytes']} B outside "
            f"the accounting tolerance {coll['tolerance_bytes']} B"
        )
        assert coll["total_bytes"] > 0  # the psum must be visible
        assert audit.cost["flops"] and audit.cost["flops"] > 0
        assert audit.memory["peak_hbm_bytes"] > 0
    # dense: no gathers at all (the PR-6 dense-round property)
    assert audits["dense"][1].collectives["max_all_gather_elems"] is None
    assert audits["dense"][1].sketch_decode == "dense"
    # sharded: every gather within the W*k candidate bound
    sh = audits["sharded"][1].collectives
    assert sh["wk_bound"] == 8 * SKETCH["k"]
    assert sh["max_all_gather_elems"] is not None
    assert sh["max_all_gather_elems"] <= sh["wk_bound"]
    # the sharded round's decode genuinely moves less FLOPs than dense
    assert (audits["sharded"][1].cost["flops"]
            < audits["dense"][1].cost["flops"])


def test_collective_audit_parses_variadic_and_async_forms():
    """Direct parser pins: tuple-shaped (variadic) all-reduces sum their
    components, async -start/-done pairs count once, and dtype sizes are
    honored."""
    text = """
  %all-reduce.1 = f32[3,264]{1,0} all-reduce(f32[3,264]{1,0} %x), channel_id=1
  %ar2 = (f32[8]{0}, s32[4]{0}) all-reduce(f32[8]{0} %a, s32[4]{0} %b), channel_id=2
  %ag = (bf16[1,27]{1,0}, bf16[8,27]{1,0}) all-gather-start(bf16[1,27]{1,0} %c), channel_id=3
  %agd = bf16[8,27]{1,0} all-gather-done((bf16[1,27]{1,0}, bf16[8,27]{1,0}) %ag)
  %rs = f32[16]{0} reduce-scatter(f32[128]{0} %d), channel_id=4
"""
    out = collective_audit(text)
    assert out["ops"]["all-reduce"] == {"count": 2,
                                        "bytes": 3 * 264 * 4 + 8 * 4 + 4 * 4}
    # the TPU async tuple form (operand, output): ONLY the transferred
    # output buffer counts — the operand alias must not inflate the bytes
    # or push max_all_gather_elems past the W*k bound
    assert out["ops"]["all-gather"] == {"count": 1, "bytes": 8 * 27 * 2}
    assert out["ops"]["reduce-scatter"] == {"count": 1, "bytes": 64}
    assert out["max_all_gather_elems"] == 8 * 27
    assert out["total_bytes"] == sum(v["bytes"] for v in out["ops"].values())
    assert collective_audit("no collectives here")["total_bytes"] == 0


def test_fsdp_round_audits():
    """The audit works on the second engine too (fsdp round_fn): analyses
    present, collectives nonzero (reduce-scatter/all-gather are the FSDP
    round's fabric)."""
    cfg = Config(fsdp=True, telemetry_level=1, **SKETCH, **BASE)
    sess, _, ids, batch = _session_and_round0(cfg)
    audit = sess.audit_compiled_round(ids, batch, 0.2)
    assert audit.engine == "fsdp"
    assert audit.sketch_decode is None  # the knob is moot under fsdp
    assert audit.cost["flops"] and audit.cost["flops"] > 0
    assert audit.collectives["total_bytes"] > 0


# ---------------------------------------------------------------------------
# retrace sentinel (tentpole piece 3)
# ---------------------------------------------------------------------------

def test_retrace_sentinel_zero_across_clean_run_fires_on_dtype():
    """ISSUE-7 acceptance: zero retraces across a clean 5-round run
    (including the audit's AOT trace, which seeds the first signature);
    a dtype-changing input fires the sentinel and the diff NAMES the
    offending leaf."""
    cfg = Config(telemetry_level=1, **SKETCH, **BASE)
    sess, sampler, ids, batch = _session_and_round0(cfg)
    sess.audit_compiled_round(ids, batch, 0.2)
    assert sess.retrace_sentinel.traces == 1
    for r in range(5):
        ids_r, b = sampler.sample_round(r)
        m = sess.train_round(ids_r, b, 0.2)
        assert m["xla/retraces"] == 0.0
    assert sess.retrace_sentinel.retraces == 0
    b2 = {"x": jnp.asarray(b["x"], jnp.bfloat16), "y": b["y"]}
    m2 = sess.train_round(ids_r, b2, 0.2)
    assert m2["xla/retraces"] == 1.0
    diff = sess.retrace_sentinel.last_diff()
    assert "'x'" in diff and "float32" in diff and "bfloat16" in diff


def test_max_retraces_hard_fails_naming_the_diff():
    cfg = Config(telemetry_level=1, max_retraces=0, **SKETCH, **BASE)
    sess, sampler, ids, batch = _session_and_round0(cfg)
    sess.train_round(ids, batch, 0.2)  # first trace: the expected compile
    b2 = {"x": jnp.asarray(batch["x"], jnp.bfloat16), "y": batch["y"]}
    with pytest.raises(RetraceError, match="bfloat16"):
        sess.train_round(ids, b2, 0.2)


def test_sentinel_tracks_streams_independently():
    """Two jitted programs (host-batch round + index round) each get one
    free first trace — neither counts as a retrace of the other."""
    s = RetraceSentinel()
    s.hook_for("a")(jnp.zeros(3))
    s.hook_for("b")(jnp.zeros(4))
    assert s.traces == 2 and s.retraces == 0
    s.hook_for("a")(jnp.zeros(3, jnp.int32))
    assert s.retraces == 1
    assert "int32" in s.last_diff()
    with s.suspended():
        s.hook_for("a")(jnp.zeros(9))
    assert s.retraces == 1  # suspended traces aren't recorded


def test_signature_diff_names_weak_type_flips():
    """The classic invisible retrace: python float vs jnp scalar differs
    only in weak type — the diff must still say so."""
    import jax.tree_util  # noqa: F401

    from commefficient_tpu.telemetry.xla_audit import describe_signature

    @jax.jit
    def probe(x):
        sigs.append(describe_signature((x,), {}))
        return x + 1

    sigs = []
    probe(jnp.float32(1.0))
    probe(1.0)  # weak-typed f32 — retraces
    assert len(sigs) == 2
    d = signature_diff(sigs[0], sigs[1])
    assert "weak" in d


def test_level0_round_hlo_not_changed_by_observability():
    """The level-0 no-added-ops pin: the lowered round HLO is
    byte-identical whether or not the sentinel is armed (its hook is pure
    python at trace time), and still free of the telemetry sentinel op —
    the bit-identity discipline of PR 3 survives this PR."""
    texts = []
    for max_retraces in (None, 3):
        cfg = Config(telemetry_level=0, max_retraces=max_retraces,
                     **SKETCH, **BASE)
        sess, _, ids, batch = _session_and_round0(cfg)
        lowered = sess.round_fn.lower(
            sess.state, jnp.asarray(ids),
            {k: jnp.asarray(v) for k, v in batch.items()}, jnp.float32(0.2),
        )
        texts.append(lowered.as_text())
    assert texts[0] == texts[1]
    assert "is_finite" not in texts[0]


# ---------------------------------------------------------------------------
# phase spans (tentpole piece 4)
# ---------------------------------------------------------------------------

def test_spans_record_fence_window_and_validate(tmp_path):
    spans = PhaseSpans(str(tmp_path), start_step=2, num_steps=2)
    for step in range(5):
        spans.step(step)
        with spans.span("round_dispatch") as h:
            h.fence(jnp.ones(3))
        with spans.span("device_put"):
            pass
    for item, want in zip(spans.wrap_iter([1, 2, 3], "data_load"),
                          [1, 2, 3]):
        assert item == want
    path = spans.close()
    assert os.path.basename(path) == "spans_0.json"
    rec = _checker().validate_spans(path)
    evs = [e for e in rec["traceEvents"] if e["name"] == "round_dispatch"]
    # fences only inside the [2, 4) steady-state window
    assert [e["args"]["fenced"] for e in evs] == [False, False, True, True,
                                                 False]
    assert {e["name"] for e in rec["traceEvents"]} == {
        "round_dispatch", "device_put", "data_load"
    }


def test_spans_disabled_is_inert(tmp_path):
    spans = PhaseSpans("")
    with spans.span("x") as h:
        assert h is None
    assert list(spans.wrap_iter([7])) == [7]
    assert spans.close() is None
    assert not spans.events


def test_spans_resume_shifts_window():
    spans = PhaseSpans("unused-but-truthy", start_step=2, num_steps=3)
    spans.resume_at(100)
    assert spans.start == 102 and spans.stop_at == 105


# ---------------------------------------------------------------------------
# perf_report.json <-> checker round-trip + enforcement self-tests
# ---------------------------------------------------------------------------

def _write_report(tmp_path, dec="sharded"):
    cfg = Config(telemetry_level=1, sketch_decode=dec, **SKETCH, **BASE)
    sess, _, ids, batch = _session_and_round0(cfg)
    audit = sess.audit_compiled_round(ids, batch, 0.2)
    path = audit.write(str(tmp_path), generated_by="test", cfg=cfg)
    return path


def test_perf_report_roundtrips_through_checker(tmp_path):
    mod = _checker()
    path = _write_report(tmp_path)
    rec = mod.validate_perf_report(path)
    assert rec["generated_by"] == "test"
    assert rec["sketch_decode"] == "sharded"
    assert rec["meta"]["config"]["mode"] == "sketch"
    # validate_run_dir picks the report up alongside other artifacts
    out = mod.validate_run_dir(str(tmp_path))
    assert any(p.endswith("perf_report.json") for p in out)


def test_checker_enforces_wk_bound(tmp_path):
    """A d-sized collective leaking into the sharded round must FAIL the
    checker, not just be recorded."""
    mod = _checker()
    path = _write_report(tmp_path)
    with open(path) as f:
        rec = json.load(f)
    rec["collectives"]["max_all_gather_elems"] = (
        rec["collectives"]["wk_bound"] + 1
    )
    with open(path, "w") as f:
        json.dump(rec, f)
    with pytest.raises(mod.SchemaError, match="W\\*k"):
        mod.validate_perf_report(path)


def _write_sparse_report(tmp_path):
    cfg = Config(telemetry_level=1, mode="true_topk", k=9,
                 topk_method="threshold", error_type="virtual",
                 virtual_momentum=0.9, aggregate="sparse", **BASE)
    sess, _, ids, batch = _session_and_round0(cfg)
    audit = sess.audit_compiled_round(ids, batch, 0.2)
    return audit.write(str(tmp_path), generated_by="test", cfg=cfg)


def test_checker_enforces_sparse_agg_gather_bound(tmp_path):
    """ISSUE 14 acceptance: an all-gather over the pair-exchange bound on
    a sparse-aggregate report must FAIL the checker — the O(W*k) claim is
    machine-enforced, not prose."""
    mod = _checker()
    path = _write_sparse_report(tmp_path)
    rec = mod.validate_perf_report(path)  # genuine artifact passes
    assert rec["aggregate"] == "sparse"
    with open(path) as f:
        rec = json.load(f)
    rec["collectives"]["max_all_gather_elems"] = (
        rec["collectives"]["sparse_agg_bound"] + 1
    )
    with open(path, "w") as f:
        json.dump(rec, f)
    with pytest.raises(mod.SchemaError, match="pair-exchange bound"):
        mod.validate_perf_report(path)


def test_checker_enforces_sparse_agg_reduce_bound(tmp_path):
    """Same gate for all-reduce: a dense psum sneaking back into a round
    claiming sparse aggregation is a checker failure (reduce-scatter is
    exempt — O(D/W) per link, sharded result)."""
    mod = _checker()
    path = _write_sparse_report(tmp_path)
    with open(path) as f:
        rec = json.load(f)
    rec["collectives"]["max_all_reduce_elems"] = (
        rec["collectives"]["sparse_agg_bound"] + 1
    )
    with open(path, "w") as f:
        json.dump(rec, f)
    with pytest.raises(mod.SchemaError, match="all-reduce.*pair-exchange"):
        mod.validate_perf_report(path)


def test_checker_rejects_sparse_agg_without_bound(tmp_path):
    """aggregate='sparse' with a missing/degenerate bound is malformed —
    the claim would be unenforceable."""
    mod = _checker()
    path = _write_sparse_report(tmp_path)
    for bad in (None, 0):
        with open(path) as f:
            rec = json.load(f)
        rec["collectives"]["sparse_agg_bound"] = bad
        with open(path, "w") as f:
            json.dump(rec, f)
        with pytest.raises(mod.SchemaError, match="sparse_agg_bound"):
            mod.validate_perf_report(path)


def test_checker_enforces_sharded_tolerance(tmp_path):
    mod = _checker()
    path = _write_report(tmp_path)
    with open(path) as f:
        rec = json.load(f)
    # fake an out-of-tolerance delta CONSISTENTLY (delta arithmetic intact)
    coll = rec["collectives"]
    coll["ledger_up_bytes"] = 0
    coll["delta_bytes"] = coll["total_bytes"]
    coll["tolerance_bytes"] = 1
    coll["within_tolerance"] = False
    with open(path, "w") as f:
        json.dump(rec, f)
    with pytest.raises(mod.SchemaError, match="tolerance"):
        mod.validate_perf_report(path)


def test_checker_rejects_inconsistent_delta_and_totals(tmp_path):
    mod = _checker()
    path = _write_report(tmp_path, dec="dense")
    with open(path) as f:
        rec = json.load(f)
    rec["collectives"]["delta_bytes"] += 4
    with open(path, "w") as f:
        json.dump(rec, f)
    with pytest.raises(mod.SchemaError, match="delta_bytes"):
        mod.validate_perf_report(path)
    with open(path) as f:
        rec = json.load(f)
    rec["collectives"]["total_bytes"] += 4
    rec["collectives"]["delta_bytes"] += 4  # keep delta consistent
    with open(path, "w") as f:
        json.dump(rec, f)
    with pytest.raises(mod.SchemaError, match="total_bytes"):
        mod.validate_perf_report(path)


def test_checker_requires_reason_when_degraded(tmp_path):
    mod = _checker()
    path = _write_report(tmp_path, dec="dense")
    with open(path) as f:
        rec = json.load(f)
    rec["cost"] = {"flops": None, "bytes_accessed": None,
                   "transcendentals": None, "unavailable_reason": None}
    with open(path, "w") as f:
        json.dump(rec, f)
    with pytest.raises(mod.SchemaError, match="unavailable_reason"):
        mod.validate_perf_report(path)


def test_checker_rejects_bad_span_events(tmp_path):
    mod = _checker()
    path = tmp_path / "spans_0.json"
    good = {"schema_version": 3, "kind": "spans",
            "traceEvents": [{"name": "x", "ph": "X", "ts": 0.0, "dur": 1.0,
                             "pid": 0, "tid": 0, "args": {"step": 0}}]}
    path.write_text(json.dumps(good))
    mod.validate_spans(path)  # sanity: the good one passes
    bad = dict(good)
    bad["traceEvents"] = [{**good["traceEvents"][0], "ph": "B"}]
    path.write_text(json.dumps(bad))
    with pytest.raises(mod.SchemaError, match="ph"):
        mod.validate_spans(path)
    bad["traceEvents"] = [{**good["traceEvents"][0], "args": {}}]
    path.write_text(json.dumps(bad))
    with pytest.raises(mod.SchemaError, match="step"):
        mod.validate_spans(path)


# ---------------------------------------------------------------------------
# the real train-loop path: artifacts written + linked + schema-valid
# ---------------------------------------------------------------------------

def test_cv_train_loop_writes_and_links_perf_artifacts(tmp_path):
    """cv_train.train_loop at level 1 on the TinyMLP task: perf_report +
    spans land in the run dir, every artifact (incl. the new ones)
    validates, the xla/* scalars rode metrics.jsonl, and the run header +
    flight metadata link to the perf report (the artifact-links
    satellite)."""
    from commefficient_tpu.train.cv_train import train_loop

    cfg = Config(telemetry_level=1, num_epochs=1, pivot_epoch=1,
                 lr_scale=0.1, **SKETCH, **BASE)
    ds, params, loss_fn = _setup(cfg.num_clients)
    test_ds = FedDataset({"x": ds.data["x"][:40], "y": ds.data["y"][:40]},
                         1, seed=0)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.sampler_batch_size, seed=1)
    run_dir = str(tmp_path / "run")
    writer = MetricsWriter(run_dir, cfg=cfg)
    try:
        train_loop(cfg, sess, sampler, test_ds, writer, eval_batch_size=32)
    finally:
        writer.close()
    assert os.path.exists(os.path.join(run_dir, "perf_report.json"))
    assert glob.glob(os.path.join(run_dir, "spans_*.json"))
    out = _checker().validate_run_dir(run_dir)
    kinds = {os.path.basename(p) for p in out}
    assert {"metrics.jsonl", "comm_ledger.json", "perf_report.json"} <= kinds
    assert any(k.startswith("spans_") for k in kinds)
    names = set()
    header = None
    with open(os.path.join(run_dir, "metrics.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("type") == "header":
                header = rec
            elif "name" in rec:
                names.add(rec["name"])
    assert {"xla/retraces", "xla/collective_bytes",
            "xla/ledger_delta_bytes", "xla/audited_flops"} <= names
    assert header["artifacts"]["perf_report"] == os.path.join(
        run_dir, "perf_report.json"
    )
    # a clean run's sentinel stayed at zero
    with open(os.path.join(run_dir, "metrics.jsonl")) as f:
        retraces = [json.loads(l)["value"] for l in f
                    if '"xla/retraces"' in l]
    assert retraces and all(v == 0.0 for v in retraces)


def test_flight_meta_links_artifacts(tmp_path):
    from commefficient_tpu.telemetry import build_telemetry_riders

    cfg = Config(telemetry_level=1, **SKETCH, **BASE)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    writer = MetricsWriter(str(tmp_path / "run"), cfg=cfg)
    try:
        _, flight = build_telemetry_riders(cfg, sess, writer)
    finally:
        writer.close()
    assert flight.meta["artifacts"]["perf_report"].endswith(
        "perf_report.json"
    )
    # no dangling link when the audit is opted out (accuracy_run does)
    from commefficient_tpu.telemetry import run_artifacts

    assert "perf_report" not in run_artifacts(
        cfg.replace(perf_audit=False), str(tmp_path)
    )


def test_gpt2_train_entry_writes_perf_report(tmp_path):
    """The second train entry (acceptance: BOTH entries write a
    schema-valid perf_report.json) — tiny-config CPU e2e at level 1."""
    from commefficient_tpu.train import gpt2_train

    gpt2_train.main(
        [],
        model="gpt2_tiny",
        num_epochs=1,
        num_clients=4,
        num_workers=2,
        num_devices=2,
        local_batch_size=2,
        max_seq_len=64,
        num_candidates=2,
        mode="uncompressed",
        telemetry_level=1,
        logdir=str(tmp_path / "runs"),
    )
    run_dirs = glob.glob(str(tmp_path / "runs" / "*"))
    assert len(run_dirs) == 1
    path = os.path.join(run_dirs[0], "perf_report.json")
    assert os.path.exists(path)
    rec = _checker().validate_perf_report(path)
    assert rec["generated_by"] == "train/gpt2_train"
    assert rec["mode"] == "uncompressed"
