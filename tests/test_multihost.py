"""Multi-host execution (multihost/): topology, per-host data plane,
mesh-faked twins, and the REAL 2-process jax.distributed leg.

Two execution modes, one semantics:

* **real multi-process** — two OS processes x 4 virtual CPU devices
  joined through ``multihost.initialize_multihost`` (Gloo standing in for
  DCN), one 8-device ``(hosts, workers, model, seq)`` global mesh, and a
  federated sketch round whose psum crosses the process boundary. Runs
  wherever the probe says cross-process CPU collectives work (this
  container's jaxlib rejects them — a toolchain property, so the leg
  SKIPs here and runs on real pods).
* **mesh-faked twin** — ``num_hosts=2`` on ONE process over the suite's 8
  virtual devices: same 4-axis mesh, same tuple-axis collectives, no
  process boundary. The twin is pinned BIT-EQUAL (params array-equal,
  drained loss sequence identical) to the flat single-host run across
  modes, fedsim masking, and checkpoint resume — the CI-runnable proof
  that declaring the host axis re-shapes the mesh without changing a
  single reduction.

Plus the traffic pins: the compiled multihost sketch round lowers its
table psum to exactly ONE all-reduce whose replica group spans the pod,
and the two-level butterfly keeps log2(W) hops.
"""

import os
import re
import socket
import subprocess
import sys

import numpy as np
import pytest

_CHILD = os.path.join(os.path.dirname(__file__), "multihost_child.py")


# ---------------------------------------------------------------------------
# real 2-process leg (probe-gated)
# ---------------------------------------------------------------------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# minimal two-process jax.distributed bring-up: init + the cross-process
# replicated device_put the federated session does first (device_put with a
# multi-process sharding runs multihost_utils.assert_equal, whose
# broadcast_one_to_all psum is the op this container's jaxlib rejects with
# "Multiprocess computations aren't implemented on the CPU backend")
_PROBE = """
import sys
import jax
jax.distributed.initialize(coordinator_address="127.0.0.1:%d",
                           num_processes=2, process_id=int(sys.argv[1]))
import numpy as np
from jax.experimental import multihost_utils
multihost_utils.broadcast_one_to_all(np.zeros(1, np.float32))
print("PROBE_OK")
"""


@pytest.fixture(scope="module")
def multiprocess_cpu_probe():
    """Env probe: can THIS container run two-process jax.distributed
    collectives on CPU at all? Some jaxlib CPU builds (this container's
    0.4.37 among them) reject every cross-process computation with
    'Multiprocess computations aren't implemented on the CPU backend' —
    a toolchain property, not a regression in this repo. The probe runs
    the minimal init + one cross-process broadcast; on failure the real
    test SKIPs with the diagnosis (and still runs wherever distributed
    init works)."""
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("JAX_", "XLA_"))
    }
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _PROBE % port, str(i)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(2)
    ]
    outs, timed_out = [], False
    try:
        for p in procs:
            try:
                out, _ = p.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                timed_out = True
                out = "(probe timed out after 120s)"
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    if timed_out or any(p.returncode != 0 for p in procs):
        tail = "\n".join(o[-400:] for o in outs)
        known = "Multiprocess computations aren't implemented" in tail
        pytest.skip(
            "two-process jax.distributed is broken in this environment: "
            + ("this jaxlib's CPU backend rejects cross-process "
               "computations ('Multiprocess computations aren't "
               "implemented on the CPU backend') — a container/toolchain "
               "limitation, not a repo regression"
               if known else
               f"probe failed with an unrecognized error:\n{tail}")
            + " — skipping the federated two-process round; it runs "
            "wherever distributed init works (e.g. real multi-host TPU)."
        )


def test_two_process_federated_round(multiprocess_cpu_probe):
    """The real leg: two processes bring up through multihost/
    (initialize_multihost + make_global_mesh + per-host data planes) and
    run sketch rounds over the pod mesh — both must report the SAME loss
    (the aggregation is global)."""
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        # the child builds its own jax env from scratch
        if not k.startswith(("JAX_", "XLA_"))
    }
    procs = [
        subprocess.Popen(
            [sys.executable, _CHILD, str(i), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=280)
            outs.append(out)
    finally:
        # a crashed child leaves its peer blocked in the cross-process
        # psum forever — never leak the pair past the test
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
    losses = []
    for out in outs:
        m = re.search(r"MULTIHOST_OK pid=\d+ loss=([0-9.]+)", out)
        assert m, out[-2000:]
        losses.append(float(m.group(1)))
    assert losses[0] == losses[1], f"processes disagree: {losses}"


# ---------------------------------------------------------------------------
# everything below runs in-process on the suite's 8 virtual devices
# ---------------------------------------------------------------------------

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from commefficient_tpu.data import FedDataset, FedSampler  # noqa: E402
from commefficient_tpu.multihost import (  # noqa: E402
    HostDataPlane,
    assemble_cohort,
    assemble_rows,
    build_host_bank,
    build_topology,
    client_partition,
    global_client_ids,
    round_env_slice,
    slot_partition,
    validate_mesh_topology,
)
from commefficient_tpu.parallel import FederatedSession  # noqa: E402
from commefficient_tpu.parallel.mesh import (  # noqa: E402
    HOSTS,
    WORKERS,
    make_mesh,
    worker_axes,
    worker_axis_size,
)
from commefficient_tpu.utils.config import Config  # noqa: E402
from commefficient_tpu.utils.jax_compat import shard_map  # noqa: E402

from tests.test_round import BASE, _setup  # noqa: E402


# -- topology --------------------------------------------------------------

def test_partitions_tile_their_ranges():
    """Slot and client partitions are contiguous, host-major, and tile
    the global range exactly — every id owned by exactly one host."""
    assert slot_partition(8, 2, 0) == (0, 4)
    assert slot_partition(8, 2, 1) == (4, 8)
    with pytest.raises(ValueError, match="divisible"):
        slot_partition(8, 3, 0)
    with pytest.raises(ValueError, match="host_id"):
        slot_partition(8, 2, 2)
    # balanced-to-within-one client split, remainder to the first hosts
    for C, H in ((12, 2), (13, 2), (10, 4), (7, 4)):
        ranges = [client_partition(C, H, h) for h in range(H)]
        flat = [c for lo, hi in ranges for c in range(lo, hi)]
        assert flat == list(range(C)), (C, H, ranges)
        sizes = [hi - lo for lo, hi in ranges]
        assert max(sizes) - min(sizes) <= 1
    with pytest.raises(ValueError, match="host_id"):
        client_partition(12, 2, -1)


def test_build_topology_and_mesh_cross_check():
    cfg = Config(mode="uncompressed", num_hosts=2, **BASE)
    topos = [build_topology(cfg, host_id=h) for h in range(2)]
    for h, t in enumerate(topos):
        assert t.host_id == h
        assert t.chips_per_host == 4
        assert t.workers_per_host == 4
        assert t.slot_range == (4 * h, 4 * h + 4)
    t0 = topos[0]
    assert t0.owns_client(t0.client_range[0])
    assert not t0.owns_client(topos[1].client_range[0])
    assert t0.local_client(t0.client_range[0]) == 0
    with pytest.raises(ValueError, match="partition"):
        t0.local_client(topos[1].client_range[0])
    # host_id defaults to jax.process_index() (0 in this suite)
    assert build_topology(cfg).host_id == 0
    validate_mesh_topology(make_mesh(8, hosts=2), t0)
    with pytest.raises(ValueError, match="mesh declares"):
        validate_mesh_topology(make_mesh(8), t0)


# -- mesh hosts axis -------------------------------------------------------

def test_make_mesh_hosts_axis():
    """make_mesh(hosts=) declares the 4-axis mesh WITHOUT reordering
    devices (host h's rows are exactly its contiguous device block), and
    the 3-axis shape is untouched for every existing caller."""
    flat = make_mesh(8)
    assert flat.axis_names == (WORKERS, "model", "seq")
    assert flat.devices.shape == (8, 1, 1)
    assert worker_axes(flat) == WORKERS
    m = make_mesh(8, hosts=2)
    assert m.axis_names == (HOSTS, WORKERS, "model", "seq")
    assert m.devices.shape == (2, 4, 1, 1)
    assert worker_axes(m) == (HOSTS, WORKERS)
    assert worker_axis_size(m) == worker_axis_size(flat) == 8
    # identical flat device order: the 4-axis mesh is a reshape, not a
    # permutation — this is what makes the twin runs byte-comparable
    assert list(m.devices.reshape(-1)) == list(flat.devices.reshape(-1))
    # hosts=1 stays 3-axis (no degenerate axis for single-host runs)
    assert make_mesh(8, hosts=1).axis_names == flat.axis_names


def test_config_refuses_incompatible_multihost_knobs():
    base = dict(BASE)
    with pytest.raises(ValueError, match="power"):
        Config(mode="uncompressed", num_hosts=3, **{**base, "num_workers": 6,
                                                    "num_devices": 6})
    with pytest.raises(ValueError, match="num_hosts"):
        Config(mode="uncompressed", distributed=True, **base)
    with pytest.raises(ValueError, match="workers axis"):
        Config(mode="uncompressed", num_hosts=2, fsdp=True, **base)
    with pytest.raises(ValueError, match="workers axis"):
        Config(mode="uncompressed", num_hosts=2, model_axis=2,
               **{**base, "num_devices": 16, "num_workers": 16,
                  "num_clients": 32})
    with pytest.raises(ValueError, match="num_hosts"):
        Config(mode="uncompressed", num_hosts=16, **base)


# -- mesh-faked twin bit-equality (THE acceptance pin) ---------------------

def _twin_run(cfg, n_rounds=3, ckpt_at=None, tmp_path=None):
    """(losses, params_vec) after ``n_rounds`` — optionally killing the
    session at ``ckpt_at`` and resuming from its checkpoint."""
    from commefficient_tpu.utils.checkpoint import FedCheckpointer

    ds, params, loss_fn = _setup(cfg.num_clients)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    sess = FederatedSession(cfg, params, loss_fn)
    ckpt = FedCheckpointer(cfg) if ckpt_at is not None else None
    losses = []
    for r in range(n_rounds):
        ids, batch = sampler.sample_round(r)
        m = sess.train_round(ids, batch, lr=0.1 + 0.02 * r)
        losses.append(float(m["loss"]))
        if ckpt is not None:
            ckpt.maybe_save(sess, r + 1)
        if ckpt_at is not None and r + 1 == ckpt_at:
            # kill: fresh process state, restore, continue
            ckpt.close()
            ds2, params2, loss_fn2 = _setup(cfg.num_clients)
            sess = FederatedSession(cfg, params2, loss_fn2)
            ckpt = FedCheckpointer(cfg)
            assert ckpt.restore(sess) == ckpt_at
    if ckpt is not None:
        ckpt.close()
    return losses, np.asarray(sess.state.params_vec)


@pytest.mark.parametrize("mode,extra", [
    ("uncompressed", dict(error_type="none", virtual_momentum=0.0)),
    ("sketch", dict(error_type="virtual", virtual_momentum=0.9, k=40,
                    num_rows=3, num_cols=512)),
    ("local_topk", dict(error_type="local", local_momentum=0.9, k=30)),
])
def test_meshfaked_twin_bit_equal(mode, extra):
    """The central pin: the 2-virtual-host run (4-axis mesh, tuple-axis
    collectives) is BIT-equal to the flat single-host run on the same
    inputs — drained loss sequence identical, final params array-equal.
    The host axis may only re-shape the mesh, never change a sum."""
    losses1, params1 = _twin_run(Config(mode=mode, **extra, **BASE))
    losses2, params2 = _twin_run(
        Config(mode=mode, **extra, num_hosts=2, **BASE))
    assert losses1 == losses2, (losses1, losses2)
    np.testing.assert_array_equal(params1, params2)


def test_meshfaked_twin_bit_equal_fedsim_masking():
    """fedsim composition: the bernoulli dropout masks are a pure
    function of (seed, round), so the masked multihost round must stay
    bit-equal to its single-host twin — renormalization included."""
    extra = dict(error_type="virtual", virtual_momentum=0.9, k=40,
                 num_rows=3, num_cols=512, availability="bernoulli",
                 dropout_prob=0.3)
    losses1, params1 = _twin_run(Config(mode="sketch", **extra, **BASE))
    losses2, params2 = _twin_run(
        Config(mode="sketch", **extra, num_hosts=2, **BASE))
    assert losses1 == losses2
    np.testing.assert_array_equal(params1, params2)


def test_meshfaked_twin_bit_equal_checkpoint_resume(tmp_path):
    """Kill-and-resume on the 2-host mesh reproduces the uninterrupted
    single-host run bit-for-bit — the checkpoint round-trips the 4-axis
    shardings and the twin equality survives a process boundary."""
    extra = dict(error_type="virtual", virtual_momentum=0.9, k=40,
                 num_rows=3, num_cols=512)
    losses1, params1 = _twin_run(
        Config(mode="sketch", **extra, **BASE), n_rounds=4)
    cfg2 = Config(mode="sketch", **extra, num_hosts=2,
                  checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
                  **BASE)
    losses2, params2 = _twin_run(cfg2, n_rounds=4, ckpt_at=2)
    assert losses1 == losses2
    np.testing.assert_array_equal(params1, params2)


# -- compiled traffic pins -------------------------------------------------

def test_hlo_multihost_sketch_single_cross_host_all_reduce():
    """The aggregation-plane pin: the compiled 2-host sketch round
    (dense decode, telemetry 0) lowers the table psum over the
    ``(hosts, workers)`` tuple axis to exactly ONE all-reduce, and its
    replica group spans the whole pod — one reduction, not one per
    level, and nothing left behind on the intra-host axis."""
    cfg = Config(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                 k=40, num_rows=3, num_cols=512, sketch_decode="dense",
                 telemetry_level=0, num_hosts=2, **BASE)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    ids, batch = sampler.sample_round(0)
    args = [sess.state, jnp.asarray(ids),
            {k: jnp.asarray(v) for k, v in batch.items()}, jnp.float32(0.2)]
    text = sess.round_fn.lower(*args).compile().as_text()
    ars = [ln for ln in text.splitlines()
           if re.search(r"=\s*[^=]*all-reduce(-start)?\(", ln)]
    assert len(ars) == 1, (
        f"expected exactly ONE all-reduce in the multihost sketch round, "
        f"found {len(ars)}: "
        + "; ".join(ln.strip()[:100] for ln in ars)
    )
    m = re.search(r"replica_groups=\{\{([\d,]+)\}\}", ars[0])
    assert m, f"unparseable replica_groups: {ars[0].strip()[:200]}"
    group = sorted(int(x) for x in m.group(1).split(","))
    assert group == list(range(8)), (
        f"the table psum's replica group must span the pod, got {group}"
    )


def test_butterfly_two_level_hop_count_and_equivalence():
    """The two-level butterfly on the 4-axis mesh: intra-host hops over
    ``workers`` first, cross-host over ``hosts`` last — still exactly
    log2(W) hops total (2 ppermutes per hop: indices + values), and the
    result equals dense psum-then-slice."""
    from commefficient_tpu.ops.collectives.sparse_allreduce import (
        sparse_allreduce_sharded,
    )

    rng = np.random.default_rng(3)
    d, k, W, H = 512, 5, 8, 2
    dense = np.zeros((W, d), np.float32)
    for w in range(W):
        sup = rng.choice(d, size=k, replace=False)
        dense[w, sup] = rng.normal(size=k).astype(np.float32)
    mesh = make_mesh(W, hosts=H)
    f = jax.jit(shard_map(
        lambda v: sparse_allreduce_sharded(
            v[0], k, (HOSTS, WORKERS), axis_size=W,
            axis_sizes=(H, W // H))[None],
        mesh=mesh, in_specs=(P((HOSTS, WORKERS)),),
        out_specs=P((HOSTS, WORKERS)),
    ))
    out = np.asarray(f(jnp.asarray(dense))).reshape(-1)
    np.testing.assert_allclose(out, dense.sum(axis=0), atol=1e-6)
    text = f.lower(
        jax.ShapeDtypeStruct((W, d), jnp.float32)).compile().as_text()
    hops = [ln for ln in text.splitlines()
            if re.search(r"=\s*[^=]*collective-permute(-start)?\(", ln)]
    n_hops = int(np.log2(W))
    assert len(hops) == 2 * n_hops, (
        f"two-level schedule must keep log2(W)={n_hops} hops "
        f"(2 ppermutes each), found {len(hops)} permutes"
    )
    assert "all-reduce" not in text
    assert "all-gather" not in text


def test_multihost_scalars_ride_level1_rounds():
    """Telemetry (schema v12): a num_hosts > 1 session's rounds carry the
    multihost/* topology scalars at level >= 1 — and single-host rounds
    carry none (constant key set per config)."""
    extra = dict(error_type="virtual", virtual_momentum=0.9, k=40,
                 num_rows=3, num_cols=512, telemetry_level=1)
    ds, params, loss_fn = _setup(12)
    sampler = FedSampler(ds, num_workers=8, local_batch_size=4, seed=1)
    ids, batch = sampler.sample_round(0)
    m2 = FederatedSession(
        Config(mode="sketch", num_hosts=2, **extra, **BASE),
        params, loss_fn).train_round(ids, batch, 0.2)
    assert m2["multihost/num_processes"] == 1.0  # mesh-faked twin
    assert m2["multihost/host_id"] == 0.0
    assert m2["multihost/cross_host_bytes"] >= 0.0
    assert m2["multihost/dcn_exposed_ms"] >= 0.0
    m1 = FederatedSession(
        Config(mode="sketch", **extra, **BASE),
        params, loss_fn).train_round(ids, batch, 0.2)
    assert not any(k.startswith("multihost/") for k in m1)


# -- per-host data plane ---------------------------------------------------

def _plane_fixture(num_hosts=2, num_clients=12, seed=7):
    cfg = Config(mode="uncompressed", num_hosts=num_hosts,
                 **{**BASE, "num_clients": num_clients})
    ds, _, _ = _setup(num_clients)
    planes = [
        HostDataPlane(ds, build_topology(cfg, host_id=h),
                      local_batch_size=cfg.local_batch_size, seed=seed)
        for h in range(num_hosts)
    ]
    return cfg, ds, planes


def test_dataplane_partitioned_draws_deterministic():
    """Each host draws its slots from its OWN client partition on its own
    stream: deterministic per (host, round), distinct ids within a draw,
    never a foreign client — and the global id vector is host-major."""
    cfg, _, planes = _plane_fixture()
    for rnd in range(3):
        for p in planes:
            ids = p.sample_clients(rnd)
            assert ids.shape == (4,)
            assert len(set(ids.tolist())) == 4
            lo, hi = p.topology.client_range
            assert ((ids >= lo) & (ids < hi)).all(), (ids, (lo, hi))
            np.testing.assert_array_equal(ids, p.sample_clients(rnd))
        np.testing.assert_array_equal(
            global_client_ids(planes, rnd),
            np.concatenate([p.sample_clients(rnd) for p in planes]))
    # different streams: the two hosts' round-0 LOCAL draws differ
    local = [p.sample_clients(0) - p.topology.client_range[0]
             for p in planes]
    assert not np.array_equal(local[0], local[1])
    # sample_round realizes the same draw it samples
    ids, batch = planes[0].sample_round(1)
    np.testing.assert_array_equal(ids, planes[0].sample_clients(1))
    assert batch["x"].shape[:2] == (4, cfg.local_batch_size)


def test_dataplane_refuses_mismatched_geometry():
    cfg, ds, _ = _plane_fixture()
    with pytest.raises(ValueError, match="clients"):
        HostDataPlane(ds, build_topology(cfg.replace(num_clients=20),
                                         host_id=0),
                      local_batch_size=4)
    # a partition smaller than its slot count cannot draw w/o replacement
    # (unreachable through a valid Config, which keeps num_clients >=
    # num_workers — pinned on a hand-built topology)
    from commefficient_tpu.multihost import HostTopology

    ds8, _, _ = _setup(8)
    starved = HostTopology(num_hosts=2, host_id=0, num_workers=8,
                           num_clients=8, chips_per_host=4,
                           slot_range=(0, 4), client_range=(0, 2))
    with pytest.raises(ValueError, match="distinct cohort slots"):
        HostDataPlane(ds8, starved, local_batch_size=4)


def test_assemble_rows_and_cohort():
    """assemble_rows lifts host-major slices into ONE worker-sharded
    global array (shards never straddle hosts); assemble_cohort is the
    twin's bridge from N planes to train_round inputs."""
    mesh = make_mesh(8, hosts=2)
    rows = {h: np.arange(4 * 3, dtype=np.float32).reshape(4, 3) + 100 * h
            for h in range(2)}
    arr = assemble_rows(mesh, rows, num_hosts=2)
    np.testing.assert_array_equal(
        np.asarray(arr), np.concatenate([rows[0], rows[1]]))
    assert arr.sharding.spec == P((HOSTS, WORKERS))
    with pytest.raises(ValueError, match="every host"):
        assemble_rows(mesh, {0: rows[0]}, num_hosts=2)
    with pytest.raises(ValueError, match="rows"):
        assemble_rows(mesh, {0: rows[0], 1: rows[1][:2]}, num_hosts=2)
    # cohort bridge over real per-host planes
    _, _, planes = _plane_fixture()
    parts = [p.sample_round(0) for p in planes]
    ids, batch = assemble_cohort(mesh, parts)
    np.testing.assert_array_equal(
        ids, np.concatenate([parts[0][0], parts[1][0]]))
    for k in parts[0][1]:
        np.testing.assert_array_equal(
            np.asarray(batch[k]),
            np.concatenate([parts[0][1][k], parts[1][1][k]]))


def test_round_env_slices_tile_the_global_env():
    """fedsim: every host realizes the same global RoundEnv and keeps its
    slot rows; live_count and stats stay GLOBAL on every slice."""
    from commefficient_tpu.fedsim import build_environment

    cfg = Config(mode="uncompressed", num_hosts=2,
                 availability="bernoulli", dropout_prob=0.4, **BASE)
    env = build_environment(cfg).round_env(0)
    topos = [build_topology(cfg, host_id=h) for h in range(2)]
    slices = [round_env_slice(env, t) for t in topos]
    np.testing.assert_array_equal(
        np.concatenate([s.live for s in slices]), env.live)
    np.testing.assert_array_equal(
        np.concatenate([s.corrupt for s in slices]), env.corrupt)
    for s in slices:
        assert s.live_count == env.live_count
        assert s.stats == env.stats


def test_host_bank_partition_sized_and_refuses_foreign_ids():
    """clientstore (the PR 17 remainder): each host's bank holds only its
    partition's rows, addressed by GLOBAL ids; a foreign id is a named
    error, not a silent wrong-row gather."""
    cfg = Config(mode="local_topk", error_type="local", k=30,
                 client_store="host", num_hosts=2, **BASE)
    topo = build_topology(cfg, host_id=1)
    bank = build_host_bank(cfg, topo, row_dim=16,
                           needs_vel=False, needs_err=True)
    assert bank is not None
    try:
        assert bank.err_array().shape == (topo.clients_per_host, 16)
        lo, hi = topo.client_range
        own = np.arange(lo, min(lo + 2, hi), dtype=np.int32)
        cohort = bank.gather(own)  # global ids translate through the topo
        assert cohort.err.shape[0] == own.size
        foreign = np.asarray([0], dtype=np.int32)  # host 0's client
        with pytest.raises(ValueError, match="partition"):
            bank.gather(foreign)
    finally:
        bank.close()
    # same construction gate as the single-host streamer
    dev_cfg = cfg.replace(client_store="device")
    assert build_host_bank(dev_cfg, topo, row_dim=16,
                           needs_vel=False, needs_err=True) is None
