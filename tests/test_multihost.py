"""Multi-host bring-up: a REAL 2-process jax.distributed cluster on CPU.

The reference has nothing like this (its world is one host's shared
memory); SURVEY.md §5 "Distributed communication backend" names multi-host
via jax.distributed as the rebuild's capability extension. This test runs
it for real: two OS processes x 4 virtual CPU devices joined through
``initialize_distributed()``, one 8-device global mesh, and a federated
sketch round whose psum crosses the process boundary (Gloo standing in for
DCN). Both processes must report the SAME loss — the aggregation is global.
"""

import os
import re
import socket
import subprocess
import sys

import pytest

_CHILD = os.path.join(os.path.dirname(__file__), "multihost_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# minimal two-process jax.distributed bring-up: init + the cross-process
# replicated device_put the federated session does first (device_put with a
# multi-process sharding runs multihost_utils.assert_equal, whose
# broadcast_one_to_all psum is the op this container's jaxlib rejects with
# "Multiprocess computations aren't implemented on the CPU backend")
_PROBE = """
import sys
import jax
jax.distributed.initialize(coordinator_address="127.0.0.1:%d",
                           num_processes=2, process_id=int(sys.argv[1]))
import numpy as np
from jax.experimental import multihost_utils
multihost_utils.broadcast_one_to_all(np.zeros(1, np.float32))
print("PROBE_OK")
"""


@pytest.fixture(scope="module")
def multiprocess_cpu_probe():
    """Env probe: can THIS container run two-process jax.distributed
    collectives on CPU at all? Some jaxlib CPU builds (this container's
    0.4.37 among them) reject every cross-process computation with
    'Multiprocess computations aren't implemented on the CPU backend' —
    a toolchain property, not a regression in this repo. The probe runs
    the minimal init + one cross-process broadcast; on failure the real
    test SKIPs with the diagnosis (and still runs wherever distributed
    init works)."""
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("JAX_", "XLA_"))
    }
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _PROBE % port, str(i)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(2)
    ]
    outs, timed_out = [], False
    try:
        for p in procs:
            try:
                out, _ = p.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                timed_out = True
                out = "(probe timed out after 120s)"
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    if timed_out or any(p.returncode != 0 for p in procs):
        tail = "\n".join(o[-400:] for o in outs)
        known = "Multiprocess computations aren't implemented" in tail
        pytest.skip(
            "two-process jax.distributed is broken in this environment: "
            + ("this jaxlib's CPU backend rejects cross-process "
               "computations ('Multiprocess computations aren't "
               "implemented on the CPU backend') — a container/toolchain "
               "limitation, not a repo regression"
               if known else
               f"probe failed with an unrecognized error:\n{tail}")
            + " — skipping the federated two-process round; it runs "
            "wherever distributed init works (e.g. real multi-host TPU)."
        )


def test_two_process_federated_round(multiprocess_cpu_probe):
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        # the child builds its own jax env from scratch
        if not k.startswith(("JAX_", "XLA_"))
    }
    procs = [
        subprocess.Popen(
            [sys.executable, _CHILD, str(i), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=280)
            outs.append(out)
    finally:
        # a crashed child leaves its peer blocked in the cross-process
        # psum forever — never leak the pair past the test
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
    losses = []
    for out in outs:
        m = re.search(r"MULTIHOST_OK pid=\d+ loss=([0-9.]+)", out)
        assert m, out[-2000:]
        losses.append(float(m.group(1)))
    assert losses[0] == losses[1], f"processes disagree: {losses}"
