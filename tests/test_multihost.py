"""Multi-host bring-up: a REAL 2-process jax.distributed cluster on CPU.

The reference has nothing like this (its world is one host's shared
memory); SURVEY.md §5 "Distributed communication backend" names multi-host
via jax.distributed as the rebuild's capability extension. This test runs
it for real: two OS processes x 4 virtual CPU devices joined through
``initialize_distributed()``, one 8-device global mesh, and a federated
sketch round whose psum crosses the process boundary (Gloo standing in for
DCN). Both processes must report the SAME loss — the aggregation is global.
"""

import os
import re
import socket
import subprocess
import sys

_CHILD = os.path.join(os.path.dirname(__file__), "multihost_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_federated_round():
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        # the child builds its own jax env from scratch
        if not k.startswith(("JAX_", "XLA_"))
    }
    procs = [
        subprocess.Popen(
            [sys.executable, _CHILD, str(i), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=280)
            outs.append(out)
    finally:
        # a crashed child leaves its peer blocked in the cross-process
        # psum forever — never leak the pair past the test
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
    losses = []
    for out in outs:
        m = re.search(r"MULTIHOST_OK pid=\d+ loss=([0-9.]+)", out)
        assert m, out[-2000:]
        losses.append(float(m.group(1)))
    assert losses[0] == losses[1], f"processes disagree: {losses}"
