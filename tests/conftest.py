"""Test harness: run everything on a virtual 8-device CPU mesh.

This is the TPU-world analog of the reference's virtual-worker simulation
(SURVEY.md §4): multi-device semantics are exercised on CPU with
``--xla_force_host_platform_device_count=8`` so every shard_map/psum path is
tested without real chips.

The ambient environment pins jax to the single real TPU chip via the "axon"
PJRT plugin, whose sitecustomize hook (a) imports jax at interpreter start,
(b) force-sets ``jax_platforms=axon`` and (c) monkey-patches backend lookup
so the first jax op dials the TPU tunnel — far too slow (and single-device)
for a test suite. We neutralize all three here: deregister the axon backend
factory before any backend initializes, and pin platforms back to cpu.
bench.py is the path that intentionally uses the real chip.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402  (sitecustomize may have imported it already)
from jax._src import xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")
