"""Test harness: run everything on a virtual 8-device CPU mesh.

This is the TPU-world analog of the reference's virtual-worker simulation
(SURVEY.md §4): multi-device semantics are exercised on CPU with
``--xla_force_host_platform_device_count=8`` so every shard_map/psum path is
tested without real chips. The axon-TPU neutralization lives in
``commefficient_tpu.utils.platform`` (shared with the driver's
``__graft_entry__.dryrun_multichip``).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from commefficient_tpu.utils.platform import force_virtual_cpu_devices  # noqa: E402

force_virtual_cpu_devices(8)
