"""The compress/ subsystem boundary, enforced in tier-1.

Two invariants: (1) no mode-string dispatch outside compress/ +
utils/config.py (scripts/check_mode_dispatch.py, so the registry boundary
can't silently erode), and (2) the registry and the CLI's MODES tuple stay
in sync (a registered-but-unlisted mode would be unreachable from the CLI;
a listed-but-unregistered one would crash at session build)."""

import importlib.util
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint():
    spec = importlib.util.spec_from_file_location(
        "check_mode_dispatch",
        os.path.join(REPO, "scripts", "check_mode_dispatch.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_no_mode_dispatch_outside_compress():
    lint = _lint()
    violations = lint.scan_package()
    assert not violations, (
        "mode-string dispatch leaked outside compress/ + utils/config.py:\n"
        + "\n".join(
            f"  commefficient_tpu/{rel}:{ln}: {snip}"
            for rel, hits in violations.items()
            for ln, snip in hits
        )
    )


def test_lint_actually_detects_violations(tmp_path):
    """The lint must FLAG the patterns it claims to (guards against the
    checker rotting into a vacuous pass)."""
    lint = _lint()
    bad = tmp_path / "bad.py"
    bad.write_text(
        "def f(cfg, mode):\n"
        "    if cfg.mode == 'sketch':\n"
        "        pass\n"
        "    if mode in ('fedavg', 'local_topk'):\n"
        "        pass\n"
        "    x = {'a': 1}[cfg.mode]\n"
        "    # a comment saying cfg.mode == 'sketch' must NOT count\n"
        "    s = \"docstrings mentioning mode == 'sketch' neither\"\n"
    )
    hits = lint.scan_file(bad)
    assert [ln for ln, _ in hits] == [2, 4, 6]

    clean = tmp_path / "clean.py"
    clean.write_text(
        "def g(cfg, comp):\n"
        "    if comp.dense_delta and cfg.do_topk_down:\n"
        "        pass\n"
        "    return cfg.mode  # reading (not branching on) mode is fine\n"
    )
    assert lint.scan_file(clean) == []


def test_lint_allowlists_compress_and_config():
    lint = _lint()
    pkg = os.path.join(REPO, "commefficient_tpu")
    # the allowed homes really do contain dispatch (sanity: the allowlist
    # is load-bearing, not decorative)
    reg = lint.scan_file(
        __import__("pathlib").Path(pkg, "utils", "config.py")
    )
    assert reg, "utils/config.py is expected to branch on mode (validation)"


def test_registry_matches_config_modes():
    from commefficient_tpu.compress import available_modes
    from commefficient_tpu.utils.config import MODES

    assert set(available_modes()) == set(MODES)


def test_unknown_mode_rejected_with_registered_list():
    from commefficient_tpu.compress import compressor_class

    with pytest.raises(ValueError, match="registered"):
        compressor_class("bogus")
