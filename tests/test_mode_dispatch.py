"""The compress/, control/ and resilience/ subsystem boundaries,
enforced in tier-1.

Two invariant families: (1) no registry-key string dispatch outside its
home package + utils/config.py — mode -> compress/, control_policy ->
control/, recover_policy -> resilience/ (scripts/check_mode_dispatch.py,
so the registry boundaries can't silently erode); (2) each registry and
its CLI tuple stay in sync — MODES, CONTROL_POLICIES, RECOVER_POLICIES
(a registered-but-unlisted entry would be unreachable from the CLI; a
listed-but-unregistered one would crash at build)."""

import importlib.util
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint():
    spec = importlib.util.spec_from_file_location(
        "check_mode_dispatch",
        os.path.join(REPO, "scripts", "check_mode_dispatch.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_no_mode_dispatch_outside_compress():
    lint = _lint()
    violations = lint.scan_package()
    assert not violations, (
        "registry-keyed dispatch leaked outside its home package:\n"
        + "\n".join(
            f"  commefficient_tpu/{rel}:{ln} [{fam}]: {snip}"
            for rel, hits in violations.items()
            for ln, fam, snip in hits
        )
    )


def test_lint_actually_detects_violations(tmp_path):
    """The lint must FLAG the patterns it claims to (guards against the
    checker rotting into a vacuous pass)."""
    lint = _lint()
    bad = tmp_path / "bad.py"
    bad.write_text(
        "def f(cfg, mode):\n"
        "    if cfg.mode == 'sketch':\n"
        "        pass\n"
        "    if mode in ('fedavg', 'local_topk'):\n"
        "        pass\n"
        "    x = {'a': 1}[cfg.mode]\n"
        "    # a comment saying cfg.mode == 'sketch' must NOT count\n"
        "    s = \"docstrings mentioning mode == 'sketch' neither\"\n"
    )
    hits = lint.scan_file(bad)
    assert [(ln, fam) for ln, fam, _ in hits] == [
        (2, "mode"), (4, "mode"), (6, "mode")
    ]

    clean = tmp_path / "clean.py"
    clean.write_text(
        "def g(cfg, comp):\n"
        "    if comp.dense_delta and cfg.do_topk_down:\n"
        "        pass\n"
        "    return cfg.mode  # reading (not branching on) mode is fine\n"
    )
    assert lint.scan_file(clean) == []


def test_lint_detects_control_policy_dispatch(tmp_path):
    """The control_policy family (PR 8): branching on the policy string
    outside control/ must be flagged, through every node kind the lint
    claims (Compare / Subscript / match); gating on cfg.control_enabled
    must NOT be."""
    lint = _lint()
    bad = tmp_path / "bad_ctrl.py"
    bad.write_text(
        "def f(cfg):\n"
        "    if cfg.control_policy == 'ef_feedback':\n"
        "        pass\n"
        "    h = {'fixed': 1}[cfg.control_policy]\n"
        "    match cfg.control_policy:\n"
        "        case 'none':\n"
        "            pass\n"
    )
    hits = lint.scan_file(bad)
    assert [(ln, fam) for ln, fam, _ in hits] == [
        (2, "control_policy"), (4, "control_policy"),
        (5, "control_policy"),
    ]

    clean = tmp_path / "clean_ctrl.py"
    clean.write_text(
        "def g(cfg, session):\n"
        "    if cfg.control_enabled:\n"
        "        pass\n"
        "    return cfg.control_policy  # reading it is fine\n"
    )
    assert lint.scan_file(clean) == []


def test_lint_detects_recover_policy_dispatch(tmp_path):
    """The recover_policy family (resilience/ PR): branching on the
    recovery-policy string outside resilience/ must be flagged; gating on
    cfg.recovery_enabled must NOT be."""
    lint = _lint()
    bad = tmp_path / "bad_resil.py"
    bad.write_text(
        "def f(cfg):\n"
        "    if cfg.recover_policy == 'retry':\n"
        "        pass\n"
        "    h = {'demote': 1}[cfg.recover_policy]\n"
        "    match cfg.recover_policy:\n"
        "        case 'skip_clients':\n"
        "            pass\n"
    )
    hits = lint.scan_file(bad)
    assert [(ln, fam) for ln, fam, _ in hits] == [
        (2, "recover_policy"), (4, "recover_policy"),
        (5, "recover_policy"),
    ]

    clean = tmp_path / "clean_resil.py"
    clean.write_text(
        "def g(cfg, session):\n"
        "    if cfg.recovery_enabled:\n"
        "        pass\n"
        "    return cfg.recover_policy  # reading it is fine\n"
    )
    assert lint.scan_file(clean) == []


def test_lint_family_restriction(tmp_path):
    """scan_file(families=...) is what scan_package uses to apply
    per-family allowlists — a file allowed for one family must still be
    linted for the other."""
    lint = _lint()
    mixed = tmp_path / "mixed.py"
    mixed.write_text(
        "def f(cfg):\n"
        "    if cfg.mode == 'sketch':\n"
        "        pass\n"
        "    if cfg.control_policy == 'fixed':\n"
        "        pass\n"
    )
    only_mode = lint.scan_file(mixed, families=("mode",))
    assert [(ln, fam) for ln, fam, _ in only_mode] == [(2, "mode")]
    only_ctrl = lint.scan_file(mixed, families=("control_policy",))
    assert [(ln, fam) for ln, fam, _ in only_ctrl] == [
        (4, "control_policy")
    ]


def test_lint_allowlists_compress_config_and_control():
    lint = _lint()
    pkg = os.path.join(REPO, "commefficient_tpu")
    # the allowed homes really do contain dispatch (sanity: the allowlist
    # is load-bearing, not decorative)
    from pathlib import Path

    cfg_hits = lint.scan_file(Path(pkg, "utils", "config.py"))
    assert any(fam == "mode" for _, fam, _ in cfg_hits), (
        "utils/config.py is expected to branch on mode (validation)"
    )
    assert any(fam == "control_policy" for _, fam, _ in cfg_hits), (
        "utils/config.py is expected to branch on control_policy "
        "(validation)"
    )
    assert any(fam == "recover_policy" for _, fam, _ in cfg_hits), (
        "utils/config.py is expected to branch on recover_policy "
        "(validation)"
    )
    pol_hits = lint.scan_file(Path(pkg, "control", "policy.py"))
    assert any(fam == "control_policy" for _, fam, _ in pol_hits), (
        "control/policy.py is expected to branch on control_policy "
        "(the policy registry)"
    )


def test_script_json_summary_on_every_exit_path(capsys):
    """The shim keeps the original exit semantics AND ends stdout with
    the machine-readable JSON summary on every path (the gate-script
    consumer contract scripts/check_bench_regression.py established)."""
    import json

    lint = _lint()

    def last(capsys):
        return json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    assert lint.main([]) == 0  # the real package is clean
    s = last(capsys)
    assert s["kind"] == "mode_dispatch"
    assert s["violations"] == 0 and s["findings"] == []

    assert lint.main(["unexpected-arg"]) == 2  # usage error
    s = last(capsys)
    assert s["kind"] == "mode_dispatch" and "error" in s


def test_script_shim_is_framework_backed():
    """The shim's scan functions ARE the framework analyzer's — one
    implementation, two entry points (the porting satellite's point)."""
    from commefficient_tpu.analysis import dispatch

    lint = _lint()
    assert lint.scan_file is dispatch.scan_file
    assert lint.scan_package is dispatch.scan_package
    assert lint.FAMILIES is dispatch.FAMILIES


def test_script_fails_on_unparseable_file(tmp_path, capsys, monkeypatch):
    """Original-script semantics preserved by the shim: a syntax-broken
    package file fails the gate (it could hide any amount of dispatch),
    it does not silently pass."""
    import json

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "broken.py").write_text("def f(:\n")
    lint = _lint()
    monkeypatch.setattr(lint, "PACKAGE", pkg)
    assert lint.main([]) == 1
    out = capsys.readouterr().out.strip().splitlines()
    summary = json.loads(out[-1])
    assert summary["violations"] == 1
    assert summary["findings"][0]["rule"] == "parse"


def test_dispatch_violations_honor_pragma(tmp_path):
    """A reasoned pragma suppresses a dispatch violation through the
    framework runner (scan_file itself stays raw — the shim and module
    CLIs apply suppression)."""
    from commefficient_tpu.analysis import run_analyzers

    root = tmp_path / "pkg"
    (root / "train").mkdir(parents=True)
    (root / "train" / "loop.py").write_text(
        "def f(cfg):\n"
        "    # lint: allow[registry-dispatch] migration shim, one release\n"
        "    if cfg.mode == 'sketch':\n"
        "        pass\n"
        "    if cfg.mode == 'fedavg':  # no pragma: still a violation\n"
        "        pass\n"
    )
    findings, _ = run_analyzers(root=root, rules=["registry-dispatch"])
    assert [(f.rule, f.lineno) for f in findings] == [
        ("registry-dispatch", 5)
    ]


def test_registry_matches_config_modes():
    from commefficient_tpu.compress import available_modes
    from commefficient_tpu.utils.config import MODES

    assert set(available_modes()) == set(MODES)


def test_policy_registry_matches_config_policies():
    from commefficient_tpu.control.policy import POLICIES
    from commefficient_tpu.utils.config import CONTROL_POLICIES

    assert set(POLICIES) | {"none"} == set(CONTROL_POLICIES)


def test_recovery_registry_matches_config_policies():
    from commefficient_tpu.resilience.policy import POLICIES
    from commefficient_tpu.utils.config import RECOVER_POLICIES

    assert set(POLICIES) | {"none"} == set(RECOVER_POLICIES)


def test_unknown_mode_rejected_with_registered_list():
    from commefficient_tpu.compress import compressor_class

    with pytest.raises(ValueError, match="registered"):
        compressor_class("bogus")
