"""Config + schedule unit tests."""

import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.utils import Config, parse_args, piecewise_linear_lr


def test_defaults_valid():
    cfg = Config()
    assert cfg.mode == "uncompressed"
    assert cfg.clients_per_device == 8


def test_cli_roundtrip():
    cfg = parse_args(
        [
            "--mode", "sketch",
            "--k", "100",
            "--num_rows", "3",
            "--num_cols", "1000",
            "--virtual_momentum", "0.9",
            "--error_type", "virtual",
            "--num_clients", "40",
            "--num_workers", "4",
            "--iid", "false",
        ]
    )
    assert cfg.mode == "sketch" and cfg.k == 100 and cfg.num_rows == 3
    assert cfg.virtual_momentum == 0.9 and cfg.error_type == "virtual"
    assert not cfg.iid


def test_validation():
    with pytest.raises(ValueError):
        Config(mode="bogus")
    with pytest.raises(ValueError):
        Config(num_workers=3, num_devices=2)
    with pytest.raises(ValueError):
        Config(num_clients=2, num_workers=8)
    with pytest.raises(ValueError):
        Config(synthetic_variant="bogus")
    with pytest.raises(ValueError, match="sketch_backend"):
        Config(sketch_backend="cuda")
    with pytest.raises(ValueError, match="pipeline_depth"):
        Config(pipeline_depth=-1)
    # 0 = synchronous (nothing constructed), any positive depth is legal
    assert not Config(pipeline_depth=0).pipeline_enabled
    assert Config(pipeline_depth=3).pipeline_enabled
    assert parse_args(["--pipeline_depth", "2"]).pipeline_depth == 2


def test_sketch_backend_cli_reaches_spec():
    # the backend flag must flow CLI -> Config -> CountSketch (the Pallas
    # dispatch is a spec property, ops/countsketch.py)
    cfg = parse_args(["--sketch_backend", "pallas"])
    assert cfg.sketch_backend == "pallas"
    from commefficient_tpu.ops.countsketch import CountSketch

    spec = CountSketch(d=1000, c=200, r=3, backend=cfg.sketch_backend)
    assert spec.backend == "pallas"


def test_sketch_dampening_gated():
    # known-divergent combination requires explicit opt-in (VERDICT r2 item 9)
    with pytest.raises(ValueError, match="momentum_dampening"):
        Config(mode="sketch", momentum_dampening=True)
    # explicit opt-in for parity experiments still works
    cfg = Config(mode="sketch", momentum_dampening=True,
                 allow_unstable_sketch_dampening=True)
    assert cfg.momentum_dampening is True
    # AUTO (None) and False are unaffected
    Config(mode="sketch", momentum_dampening=None)
    Config(mode="sketch", momentum_dampening=False)
    # dense-mode dampening unaffected
    Config(mode="true_topk", momentum_dampening=True)


def test_powersgd_flags_cli_roundtrip():
    cfg = parse_args(
        [
            "--mode", "powersgd",
            "--powersgd_rank", "7",
            "--powersgd_warm_start", "false",
            "--error_type", "virtual",
            "--virtual_momentum", "0.9",
        ]
    )
    assert cfg.mode == "powersgd"
    assert cfg.powersgd_rank == 7
    assert cfg.powersgd_warm_start is False
    # defaults
    cfg2 = parse_args(["--mode", "powersgd"])
    assert cfg2.powersgd_rank == 4 and cfg2.powersgd_warm_start is True


def test_powersgd_validation():
    with pytest.raises(ValueError, match="powersgd_rank"):
        Config(mode="powersgd", powersgd_rank=0)
    with pytest.raises(ValueError, match="do_topk_down"):
        Config(mode="powersgd", do_topk_down=True)
    with pytest.raises(ValueError, match="dampening"):
        Config(mode="powersgd", momentum_dampening=True)
    # AUTO/False dampening fine; rank flags don't disturb other modes
    Config(mode="powersgd", momentum_dampening=None)
    Config(mode="sketch", powersgd_rank=9)


def test_label_noise_cli_and_validation():
    assert parse_args(["--label_noise", "0.0"]).label_noise == 0.0
    assert parse_args(["--label_noise", "0.25"]).label_noise == 0.25
    with pytest.raises(ValueError, match="label_noise"):
        Config(label_noise=1.5)
    with pytest.raises(ValueError, match="label_noise"):
        Config(label_noise=-0.1)


def test_round_microbatches_property():
    # the mode-derived reshape knob train loops use instead of branching on
    # mode strings (scripts/check_mode_dispatch.py boundary)
    assert Config(mode="fedavg", num_local_iters=4).round_microbatches == 4
    assert Config(mode="uncompressed").round_microbatches == 0
    assert Config(mode="powersgd", num_local_iters=4).round_microbatches == 0


def test_piecewise_linear_shape():
    kw = dict(steps_per_epoch=10, pivot_epoch=5, num_epochs=20, lr_scale=0.4)
    lrs = np.array(
        [float(piecewise_linear_lr(jnp.asarray(s), **kw)) for s in range(200)]
    )
    peak = lrs.argmax()
    assert abs(peak - 49) <= 1  # peak at pivot_epoch
    assert lrs[0] < 0.01 and lrs[-1] < 0.01  # ~0 at both ends
    np.testing.assert_allclose(lrs.max(), 0.4, atol=0.01)
    assert np.all(np.diff(lrs[: peak + 1]) >= -1e-9)  # monotone up
    assert np.all(np.diff(lrs[peak:]) <= 1e-9)  # monotone down
