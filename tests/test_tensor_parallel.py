"""Tensor-parallel GPT-2 (parallel/tensor.py): exactness vs the dense
single-device model on the virtual 8-CPU mesh — TP alone, TP x SP, and the
full 3-axis dp x tp x sp train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.models.gpt2 import GPT2Config, GPT2DoubleHeads
from commefficient_tpu.models.losses import gpt2_double_heads_loss
from commefficient_tpu.parallel.mesh import make_mesh
from commefficient_tpu.parallel.tensor import (
    build_tp3d_train_step,
    tp_gpt2_apply,
    tp_shard_params,
    tp_transform_params,
    tp_untransform_params,
)

T = 64
CFG = GPT2Config(vocab_size=128, n_positions=T, n_embd=32, n_layer=2,
                 n_head=4, dtype=jnp.float32)


def _setup(seed=0, B=2, N=2):
    model = GPT2DoubleHeads(CFG)
    rng = np.random.default_rng(seed)
    ids = jnp.asarray(rng.integers(0, CFG.vocab_size, size=(B, N, T)).astype(np.int32))
    tt = jnp.asarray(rng.integers(0, CFG.vocab_size, size=(B, N, T)).astype(np.int32))
    mc = jnp.asarray(rng.integers(0, T, size=(B, N)).astype(np.int32))
    params = model.init(jax.random.key(0), ids, token_type_ids=tt, mc_token_ids=mc)
    return model, params, ids, tt, mc


def test_tp_transform_roundtrip():
    model, params, *_ = _setup()
    back = tp_untransform_params(tp_transform_params(params, CFG), CFG)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params, back,
    )


@pytest.mark.parametrize(
    "axes",
    [
        # default tier keeps the MIXED case (exercises both tp and sp
        # paths); the single-axis cases are the slow tier — same code
        # paths, one axis trivial (1-core CPU suite budget, VERDICT r2
        # item 8)
        pytest.param((1, 4, 1), marks=pytest.mark.slow),
        (1, 2, 2),
        pytest.param((1, 1, 4), marks=pytest.mark.slow),
    ],
)
def test_tp_forward_matches_dense(axes):
    mesh = make_mesh(*axes)
    model, params, ids, tt, mc = _setup()
    lm_d, mc_d = model.apply(params, ids, token_type_ids=tt, mc_token_ids=mc)
    tp = tp_shard_params(mesh, params, CFG)
    lm_t, mc_t = tp_gpt2_apply(mesh, model, tp, ids, token_type_ids=tt,
                               mc_token_ids=mc)
    np.testing.assert_allclose(np.asarray(lm_t), np.asarray(lm_d), atol=3e-4)
    np.testing.assert_allclose(np.asarray(mc_t), np.asarray(mc_d), atol=3e-4)


@pytest.mark.slow  # branch variant of test_tp_forward_matches_dense
def test_tp_forward_no_mc_head():
    mesh = make_mesh(1, 2, 1)
    model, params, ids, tt, _ = _setup()
    lm_d, _ = model.apply(params, ids, token_type_ids=tt)
    tp = tp_shard_params(mesh, params, CFG)
    lm_t, mc_t = tp_gpt2_apply(mesh, model, tp, ids, token_type_ids=tt)
    assert mc_t is None
    np.testing.assert_allclose(np.asarray(lm_t), np.asarray(lm_d), atol=3e-4)


def test_tp_rejects_indivisible_sequence():
    mesh = make_mesh(1, 1, 4)
    model, params, *_ = _setup()
    ids = jnp.zeros((1, 1, T + 2), jnp.int32)
    tp = tp_shard_params(mesh, params, CFG)
    with pytest.raises(ValueError, match="divide"):
        tp_gpt2_apply(mesh, model, tp, ids)


@pytest.mark.parametrize(
    "compute_dtype",
    [
        "mixed",
        # bf16 variant pins the compute_dtype plumbing through
        # build_tp_flat_loss; precision-looser compare, slow tier
        pytest.param("bfloat16", marks=pytest.mark.slow),
    ],
)
def test_federated_tp_sp_round_matches_dp_oracle(compute_dtype):
    """VERDICT r2 item 3 'done' criterion: a workers=2 x model=2 x seq=2
    federated SKETCH round trajectory matches the DP-only oracle — the TP/SP
    axes shard each client's loss compute without changing the compression
    or server algebra."""
    from commefficient_tpu.data import FedSampler, load_fed_personachat
    from commefficient_tpu.data.fed_dataset import FedDataset
    from commefficient_tpu.models import GPT2DoubleHeads, gpt2_double_heads_loss
    from commefficient_tpu.parallel import FederatedSession, mask_gpt2
    from commefficient_tpu.parallel.tensor import build_tp_flat_loss
    from commefficient_tpu.utils.config import Config

    cfg_kw = dict(
        mode="sketch", error_type="virtual", virtual_momentum=0.9, k=200,
        num_rows=3, num_cols=10_000, num_epochs=1, num_clients=4,
        num_workers=2, num_devices=2, local_batch_size=2, max_seq_len=T,
        weight_decay=0.0, lr_scale=0.05, pivot_epoch=1, device_data=False,
    )
    train, test, real, vocab = load_fed_personachat(
        "./nonexistent", num_clients=4, num_candidates=2, max_history=2,
        max_seq_len=T, base_vocab=CFG.vocab_size - 5, seed=0,
    )
    gcfg = GPT2Config(
        vocab_size=vocab, n_positions=T, n_embd=CFG.n_embd,
        n_layer=CFG.n_layer, n_head=CFG.n_head, dtype=jnp.float32,
    )
    model = GPT2DoubleHeads(gcfg)
    sample = next(iter(FedDataset(dict(train.data), 1, seed=0).eval_batches(1)))
    params = model.init(
        jax.random.key(0),
        jnp.asarray(sample["input_ids"][:1]),
        token_type_ids=jnp.asarray(sample["token_type_ids"][:1]),
        mc_token_ids=jnp.asarray(sample["mc_token_ids"][:1]),
    )
    dense_loss = gpt2_double_heads_loss(model.apply, compute_dtype=compute_dtype)

    def run(cfg):
        if cfg.model_axis > 1 or cfg.seq_axis > 1:
            mesh = make_mesh(cfg.num_devices, cfg.model_axis, cfg.seq_axis)
            sess = FederatedSession(
                cfg, params,
                build_tp_flat_loss(gcfg, mesh, compute_dtype=compute_dtype),
                mesh=mesh,
                eval_loss_fn=dense_loss, mask_batch=mask_gpt2,
            )
        else:
            sess = FederatedSession(cfg, params, dense_loss,
                                    mask_batch=mask_gpt2)
        sampler = FedSampler(train, num_workers=2, local_batch_size=2, seed=3)
        losses = []
        for r in range(4):
            ids, batch = sampler.sample_round(r)
            m = sess.train_round(ids, batch, 0.05)
            losses.append(float(np.asarray(m["loss"])))
        return losses, np.asarray(sess.state.params_vec)

    # NB Config.compute_dtype is inert here — both sessions' precision
    # comes from the loss closures built above
    oracle_losses, oracle_params = run(Config(**cfg_kw))
    tp_losses, tp_params = run(Config(**cfg_kw, model_axis=2, seq_axis=2))
    # bf16: sharded reduction orders differ at bf16 resolution, so the
    # trajectories track rather than match; the param atol additionally
    # absorbs top-k selection-boundary flips (a coordinate extracted in
    # one path and banked in the other — measured: ~3 of 32k params, abs
    # diff < 7e-3, after 4 rounds)
    lt = (2e-4, 2e-4) if compute_dtype == "mixed" else (2e-2, 2e-2)
    # pt: (rtol, atol, flip cap) — the cap bounds a flipped coordinate's
    # magnitude and must sit ABOVE the flip-detection atol (a flip is by
    # definition a diff exceeding the atol), scaled per dtype.
    pt = (2e-3, 2e-4, 1e-2) if compute_dtype == "mixed" else (5e-2, 1e-2, 5e-2)
    np.testing.assert_allclose(tp_losses, oracle_losses, rtol=lt[0], atol=lt[1])
    # params: strict tolerance for the bulk, but a FEW isolated
    # selection-boundary flips are fp-rounding lottery, not error — the
    # rank-k boundary of the unsketch extraction flips under any
    # perturbation of summation order (e.g. pre-vma JAX realizes the
    # model/seq grad total as an explicit psum, utils/jax_compat), and a
    # flipped coordinate differs by the full extracted value. A systematic
    # gradient error flips thousands of coordinates AND breaks the loss
    # trajectory pinned above.
    diff = np.abs(tp_params - oracle_params)
    flipped = diff > pt[1] + pt[0] * np.abs(oracle_params)
    assert int(flipped.sum()) <= 8, (
        f"{int(flipped.sum())} of {diff.size} params outside tolerance "
        f"(max abs diff {diff.max():.2e})"
    )
    assert float(diff[flipped].max(initial=0.0)) < pt[2]


@pytest.mark.parametrize(
    "axes,eval_bs",
    [
        ((2, 2, 2), 4),  # rows shard over workers (4 % 2 == 0)
        pytest.param((1, 2, 2), 3, marks=pytest.mark.slow),  # replicated rows
    ],
)
def test_tp_eval_matches_dense_eval(axes, eval_bs):
    """VERDICT r3 missing 5 'done' criterion: the model/seq-sharded eval
    path (build_tp_eval_fn) reproduces the dense jit-replicated eval's
    metrics — incl. on a ragged final batch (padded rows masked via
    _valid), so models that NEED the model axis to fit can validate."""
    from commefficient_tpu.data import load_fed_personachat
    from commefficient_tpu.ops.param_utils import ravel_params
    from commefficient_tpu.parallel import FederatedSession, mask_gpt2
    from commefficient_tpu.parallel.tensor import (
        build_tp_eval_fn,
        build_tp_flat_loss,
    )
    from commefficient_tpu.utils.config import Config

    train, test, real, vocab = load_fed_personachat(
        "./nonexistent", num_clients=4, num_candidates=2, max_history=2,
        max_seq_len=T, base_vocab=CFG.vocab_size - 5, seed=0,
    )
    gcfg = GPT2Config(
        vocab_size=vocab, n_positions=T, n_embd=CFG.n_embd,
        n_layer=CFG.n_layer, n_head=CFG.n_head, dtype=jnp.float32,
    )
    model = GPT2DoubleHeads(gcfg)
    sample = next(iter(test.eval_batches(1)))
    params = model.init(
        jax.random.key(0),
        jnp.asarray(sample["input_ids"][:1]),
        token_type_ids=jnp.asarray(sample["token_type_ids"][:1]),
        mc_token_ids=jnp.asarray(sample["mc_token_ids"][:1]),
    )
    dense_loss = gpt2_double_heads_loss(model.apply)
    cfg = Config(
        mode="uncompressed", num_epochs=1, num_clients=4,
        num_workers=axes[0], num_devices=axes[0], local_batch_size=2,
        max_seq_len=T, model_axis=axes[1], seq_axis=axes[2],
        device_data=False,
    )
    mesh = make_mesh(*axes)
    tp_sess = FederatedSession(
        cfg, params, build_tp_flat_loss(gcfg, mesh), mesh=mesh,
        eval_fn=build_tp_eval_fn(gcfg, mesh, ravel_params(params)[1]),
        mask_batch=mask_gpt2,
    )
    dense_cfg = cfg.replace(model_axis=1, seq_axis=1)
    dense_sess = FederatedSession(
        dense_cfg, params, dense_loss, mask_batch=mask_gpt2
    )
    got = tp_sess.evaluate(test.eval_batches(eval_bs))
    want = dense_sess.evaluate(test.eval_batches(eval_bs))
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=2e-4, atol=2e-4,
                                   err_msg=k)


@pytest.mark.slow  # the federated composition below (dp oracle test) holds
# the default-tier coverage for the 3-axis step
def test_tp3d_train_step_matches_single_device_sgd():
    """One dp x tp x sp SGD step == one dense single-device SGD step."""
    mesh = make_mesh(2, 2, 2)
    model, params, ids, tt, mc = _setup(B=4)
    rng = np.random.default_rng(7)
    lm_labels = np.asarray(ids).copy()
    lm_labels[..., : T // 2] = -100  # mask a prefix, as the workload does
    batch = {
        "input_ids": ids,
        "token_type_ids": tt,
        "lm_labels": jnp.asarray(lm_labels),
        "mc_token_ids": mc,
        "mc_labels": jnp.asarray(rng.integers(0, 2, size=(4,)).astype(np.int32)),
    }
    lr = 0.1

    # oracle: dense loss -> plain SGD
    loss_fn = gpt2_double_heads_loss(model.apply)
    (loss_d, aux_d), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        params, batch
    )
    dense_new = jax.tree.map(lambda p, g: p - lr * g, params, grads)

    tp = tp_shard_params(mesh, params, CFG)
    step = build_tp3d_train_step(mesh, model)
    new_tp, metrics = step(tp, batch, jnp.float32(lr))

    np.testing.assert_allclose(float(metrics["loss"]), float(loss_d), atol=2e-4)
    np.testing.assert_allclose(
        float(metrics["lm_loss"]), float(aux_d["lm_loss"]), atol=2e-4
    )
    back = tp_untransform_params(new_tp, CFG)
    flat_a = jax.tree.leaves(jax.tree.map(np.asarray, dense_new))
    flat_b = jax.tree.leaves(jax.tree.map(np.asarray, back))
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(b, a, atol=5e-4)
