"""Device-resident data path: bit-equality with the host batch path.

The index-driven round (FederatedSession.attach_data /
train_round_indices) must train EXACTLY like the host path — same sampled
rows, same augmentation, same resulting parameters — because the sampler
draws indices/plans with the identical rng sequence and the device
gather+augment mirrors the numpy/native pixel ops.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.data import FedSampler, augment_batch, prefetch
from commefficient_tpu.data.cifar import CifarAugment, device_augment
from commefficient_tpu.data.fed_dataset import FedDataset
from commefficient_tpu.models import ResNet9, classification_loss
from commefficient_tpu.models.losses import softmax_cross_entropy  # noqa: F401
from commefficient_tpu.parallel import FederatedSession, make_mesh
from commefficient_tpu.utils.config import Config


def _toy_ds(n=512, num_clients=8, seed=0, uint8=True):
    rng = np.random.default_rng(seed)
    if uint8:
        x = rng.integers(0, 256, size=(n, 32, 32, 3)).astype(np.uint8)
    else:
        x = rng.normal(size=(n, 32, 32, 3)).astype(np.float32)
    y = rng.integers(0, 10, size=n).astype(np.int32)
    return FedDataset({"x": x, "y": y}, num_clients, seed=seed)


def _mlp_loss():
    """Tiny linear model over flattened pixels; loss_fn convention."""

    def loss_fn(params, batch, rng=None):
        x = batch["x"].astype(jnp.float32).reshape(batch["x"].shape[0], -1)
        logits = x @ params["w"] + params["b"]
        loss = softmax_cross_entropy(logits, batch["y"])
        correct = jnp.sum(jnp.argmax(logits, -1) == batch["y"]).astype(jnp.float32)
        return loss, {"correct": correct,
                      "count": jnp.asarray(batch["y"].size, jnp.float32)}

    params = {
        "w": np.zeros((32 * 32 * 3, 10), np.float32),
        "b": np.zeros((10,), np.float32),
    }
    return params, loss_fn


def test_device_augment_matches_numpy_bitexact():
    aug = CifarAugment()
    rng = np.random.default_rng(3)
    for uint8 in (True, False):
        if uint8:
            x = rng.integers(0, 256, size=(40, 32, 32, 3)).astype(np.uint8)
        else:
            x = rng.normal(size=(40, 32, 32, 3)).astype(np.float32)
        p = aug.plan(rng, 40)
        want = aug.apply(x.copy(), p)
        got = np.asarray(
            device_augment(
                jnp.asarray(x),
                jnp.asarray(p.ys), jnp.asarray(p.xs), jnp.asarray(p.flips),
                jnp.asarray(p.cys), jnp.asarray(p.cxs),
                fill=aug._fill(x.dtype, 3),
            )
        )
        np.testing.assert_array_equal(got, want)


def _run_paths(cfg, ds, augment, rounds=3):
    """Train `rounds` rounds via host-batch and via device-index paths;
    return both final param vectors."""
    params, loss_fn = _mlp_loss()
    finals = []
    for use_idx in (False, True):
        session = FederatedSession(cfg, params, loss_fn)
        sampler = FedSampler(
            ds, num_workers=cfg.num_workers,
            local_batch_size=cfg.local_batch_size, seed=cfg.seed,
            augment=augment,
        )
        if use_idx:
            session.attach_data(ds.data, augment)
        for r in range(rounds):
            lr = 0.1 + 0.05 * r
            if use_idx:
                ids, idx, plan = sampler.sample_round_indices(r)
                session.train_round_indices(ids, idx, plan, lr)
            else:
                ids, batch = sampler.sample_round(r)
                if cfg.mode == "fedavg":
                    L = cfg.num_local_iters
                    batch = {
                        k: v.reshape(v.shape[0], L, v.shape[1] // L, *v.shape[2:])
                        for k, v in batch.items()
                    }
                session.train_round(ids, batch, lr)
        finals.append(np.asarray(session.state.params_vec))
    return finals


def test_index_path_matches_batch_path_uncompressed():
    cfg = Config(mode="uncompressed", num_clients=8, num_workers=4,
                 num_devices=1, local_batch_size=8, weight_decay=0.0, seed=7,
                 fuse_clients=True)
    a, b = _run_paths(cfg, _toy_ds(), augment_batch)
    np.testing.assert_allclose(a, b, rtol=0, atol=0)


def test_index_path_matches_batch_path_sketch():
    cfg = Config(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                 k=64, num_rows=3, num_cols=2048, num_clients=8,
                 num_workers=4, num_devices=1, local_batch_size=8,
                 weight_decay=0.0, seed=7, topk_method="threshold")
    a, b = _run_paths(cfg, _toy_ds(), augment_batch)
    np.testing.assert_allclose(a, b, rtol=0, atol=0)


def test_index_path_matches_batch_path_fedavg_no_augment():
    # L=1 included: the host path reshapes to [W, 1, B, ...] for fedavg
    # unconditionally, and the index path must too (code-review r2 find 1)
    for L in (1, 2):
        cfg = Config(mode="fedavg", num_local_iters=L, num_clients=8,
                     num_workers=4, num_devices=1, local_batch_size=8,
                     weight_decay=0.0, seed=3)
        a, b = _run_paths(cfg, _toy_ds(), None)
        np.testing.assert_allclose(a, b, rtol=0, atol=0)


def test_uint8_cutout_fills_dataset_mean():
    """Cutout on uint8 must fill the per-channel MEAN bytes, not black —
    the reference cuts out AFTER normalization where 0.0 IS the mean."""
    from commefficient_tpu.data.cifar import CIFAR10_MEAN

    aug = CifarAugment()
    x = np.full((1, 32, 32, 3), 200, np.uint8)
    p = aug.plan(np.random.default_rng(0), 1)
    out = aug.apply(x, p)
    cut_vals = out[out != 200]
    assert cut_vals.size > 0
    expect = np.round(255.0 * CIFAR10_MEAN).astype(np.uint8)
    assert set(np.unique(cut_vals)) <= set(expect.tolist())
    # float input keeps the 0.0 fill (already-normalized space)
    xf = np.full((1, 32, 32, 3), 5.0, np.float32)
    outf = aug.apply(xf, p)
    assert set(np.unique(outf)) <= {0.0, 5.0}


def test_prefetch_consumer_abandon_stops_producer():
    import time

    produced = []

    def gen():
        for i in range(1000):
            produced.append(i)
            yield i

    it = prefetch(gen(), depth=2)
    assert next(it) == 0
    it.close()  # abandon mid-stream
    time.sleep(0.5)
    n = len(produced)
    time.sleep(0.3)
    assert len(produced) == n, "producer kept running after consumer close"


def test_index_path_multidevice():
    n_dev = min(8, jax.device_count())
    cfg = Config(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                 k=64, num_rows=3, num_cols=2048, num_clients=2 * n_dev,
                 num_workers=n_dev, num_devices=n_dev, local_batch_size=4,
                 weight_decay=0.0, seed=1, topk_method="threshold")
    params, loss_fn = _mlp_loss()
    ds = _toy_ds(num_clients=2 * n_dev)
    session = FederatedSession(cfg, params, loss_fn, mesh=make_mesh(n_dev))
    sampler = FedSampler(ds, num_workers=n_dev, local_batch_size=4, seed=1,
                         augment=augment_batch)
    session.attach_data(ds.data, augment_batch)
    for r in range(2):
        ids, idx, plan = sampler.sample_round_indices(r)
        m = session.train_round_indices(ids, idx, plan, 0.1)
    assert np.isfinite(float(np.asarray(m["loss"])))


@pytest.mark.slow  # r5 tier budget: the e2e EXERCISE of the device-data
# path stays default-tier via test_train_entry's femnist e2e (device_data
# defaults true there too) and the index==batch parity tests above; this
# 70s test only adds the spy ASSERTION that the path was taken
def test_cv_train_takes_device_data_path_e2e(tmp_path):
    """cv_train end-to-end (femnist: small, augment-free) must take the
    device-data path by default and produce finite metrics."""
    from commefficient_tpu.train import cv_train

    built = {}
    orig = cv_train.build_session_and_sampler

    def spy(*a, **k):
        session, sampler = orig(*a, **k)
        built["session"] = session
        return session, sampler

    cv_train.build_session_and_sampler = spy
    try:
        val = cv_train.main(
            [],
            dataset_name="femnist",
            mode="uncompressed",
            num_clients=4,
            num_workers=2,
            num_devices=1,
            local_batch_size=16,  # 1-core CPU budget: 15 rounds, not 30
            num_epochs=1,
            pivot_epoch=1,
            lr_scale=0.05,
            dataset_dir=str(tmp_path),
            logdir=str(tmp_path / "runs"),
            seed=0,
        )
    finally:
        cv_train.build_session_and_sampler = orig
    assert built["session"]._dev_data is not None, "device-data path not taken"
    assert np.isfinite(val["loss"])
