"""Native C++ loader vs numpy: bit-exact equality + fused-sampler contracts."""

import numpy as np
import pytest

from commefficient_tpu import native
from commefficient_tpu.data import FedSampler, augment_batch, prefetch
from commefficient_tpu.data.cifar import CifarAugment
from commefficient_tpu.data.fed_dataset import FedDataset


def _toy_images(n=64, h=32, w=32, c=3, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n, h, w, c)).astype(np.float32)


def _toy_dataset(n=256, num_clients=8, seed=0):
    rng = np.random.default_rng(seed)
    return FedDataset(
        {
            "x": rng.normal(size=(n, 32, 32, 3)).astype(np.float32),
            "y": rng.integers(0, 10, size=n).astype(np.int32),
        },
        num_clients,
        seed=seed,
    )


def test_native_builds():
    # the baked-in toolchain must build the kernel; if this fails the
    # framework still runs (numpy fallback) but the native path is part of
    # the deliverable, so the suite flags it loudly.
    assert native.available(), "native fedloader failed to build with g++"


@pytest.mark.skipif(not native.available(), reason="no native lib")
def test_gather_augment_matches_numpy_bitexact():
    aug = CifarAugment()
    data = _toy_images(n=128)
    rng = np.random.default_rng(7)
    idx = rng.integers(0, data.shape[0], size=96).astype(np.int64)
    p = aug.plan(rng, 96)
    got = native.gather_augment(data, idx, p, fill=aug._fill(data.dtype, 3))
    want = aug.apply(np.ascontiguousarray(data[idx]), p)
    assert got.dtype == np.float32
    np.testing.assert_array_equal(got, want)


@pytest.mark.skipif(not native.available(), reason="no native lib")
def test_gather_augment_uint8_matches_numpy():
    """The training pipeline ships uint8 batches (device-side
    normalization); the u8 kernel must match the numpy path exactly."""
    aug = CifarAugment()
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, size=(100, 32, 32, 3)).astype(np.uint8)
    idx = rng.integers(0, 100, size=64).astype(np.int64)
    p = aug.plan(rng, 64)
    got = native.gather_augment(data, idx, p, fill=aug._fill(data.dtype, 3))
    want = aug.apply(np.ascontiguousarray(data[idx]), p)
    assert got.dtype == np.uint8
    np.testing.assert_array_equal(got, want)


@pytest.mark.skipif(not native.available(), reason="no native lib")
def test_gather_rows_uint8_and_int32():
    rng = np.random.default_rng(12)
    idx = np.asarray([5, 0, 5, 9], np.int64)
    for dt in (np.uint8, np.int32, np.float32):
        data = rng.integers(0, 100, size=(10, 7)).astype(dt)
        np.testing.assert_array_equal(native.gather_rows(data, idx), data[idx])


@pytest.mark.skipif(not native.available(), reason="no native lib")
def test_plain_gather_matches_numpy():
    data = _toy_images(n=50)
    idx = np.asarray([3, 3, 49, 0, 17], np.int64)
    np.testing.assert_array_equal(native.gather_augment(data, idx), data[idx])
    np.testing.assert_array_equal(native.gather_rows(data, idx), data[idx])


def test_vectorized_augment_matches_legacy_loop():
    """The vectorized CifarAugment.apply must reproduce the r1 per-image
    loop (crop -> flip -> cutout with clamped window) exactly."""
    aug = CifarAugment()
    x = _toy_images(n=40)
    p = aug.plan(np.random.default_rng(3), 40)
    got = aug.apply(x, p)
    n, h, w, _ = x.shape
    padded = np.pad(x, ((0, 0), (4, 4), (4, 4), (0, 0)), mode="reflect")
    for i in range(n):
        img = padded[i, p.ys[i] : p.ys[i] + h, p.xs[i] : p.xs[i] + w]
        if p.flips[i]:
            img = img[:, ::-1]
        img = img.copy()
        y0, y1 = max(0, p.cys[i] - 4), min(h, p.cys[i] + 4)
        x0, x1 = max(0, p.cxs[i] - 4), min(w, p.cxs[i] + 4)
        img[y0:y1, x0:x1] = 0.0
        np.testing.assert_array_equal(got[i], img)


def test_fused_sampler_shapes_and_determinism():
    ds = _toy_dataset()
    s = FedSampler(ds, num_workers=4, local_batch_size=8, seed=1,
                   augment=augment_batch)
    assert s._fusable
    ids1, b1 = s.sample_round(5)
    ids2, b2 = s.sample_round(5)
    assert b1["x"].shape == (4, 8, 32, 32, 3)
    assert b1["y"].shape == (4, 8)
    np.testing.assert_array_equal(ids1, ids2)
    np.testing.assert_array_equal(b1["x"], b2["x"])
    # every gathered row must belong to its client's shard
    for wi, cid in enumerate(ids1):
        client_set = {tuple(np.round(r, 4)) for r in
                      ds.data["x"][ds.client_indices[cid]][:, 0, 0, :]}
        # augmentation moves pixels; check labels instead
        labels = set(ds.data["y"][ds.client_indices[cid]].tolist())
        assert set(b1["y"][wi].tolist()) <= labels


def test_fused_gather_no_augment_matches_dataset_rows():
    ds = _toy_dataset()
    s = FedSampler(ds, num_workers=4, local_batch_size=8, seed=2, augment=None)
    assert s._fusable
    ids, b = s.sample_round(0)
    # reproduce the index draws and compare the gathered pixels exactly
    rng = np.random.default_rng((2, 0))
    clients = rng.choice(ds.num_clients, size=4, replace=False)
    np.testing.assert_array_equal(ids, clients.astype(np.int32))
    flat = np.concatenate(
        [ds.client_batch_indices(int(c), 8, rng) for c in clients]
    )
    np.testing.assert_array_equal(b["x"], ds.data["x"][flat].reshape(4, 8, 32, 32, 3))
    np.testing.assert_array_equal(b["y"], ds.data["y"][flat].reshape(4, 8))


def test_prefetch_order_and_exception():
    assert list(prefetch(iter(range(100)), depth=3)) == list(range(100))

    def boom():
        yield 1
        raise ValueError("producer failed")

    it = prefetch(boom())
    assert next(it) == 1
    with pytest.raises(ValueError, match="producer failed"):
        next(it)
