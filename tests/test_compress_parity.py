"""Registry-port parity: the compress/ refactor changed NO round output.

tests/golden/registry_parity.npz was recorded at the last pre-refactor
commit (scripts/gen_registry_golden.py documents how and when to
regenerate): final params vector + per-round losses for one representative
config per legacy mode on the standard 8-device virtual CPU mesh. The
registry port was a mechanical extraction, so outputs must be bit-identical
on this platform; the assertions allow only fp32-noise headroom (1e-6
relative) for the paths whose op ORDER the legacy round never pinned
(XLA may re-fuse across the extracted function boundaries).
"""

import os

import numpy as np
import pytest
from test_round import _final_vec, _run, BASE

from commefficient_tpu.utils.config import Config

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden",
                      "registry_parity.npz")

# must match scripts/gen_registry_golden.py exactly
GOLDEN_CONFIGS = {
    "uncompressed": dict(mode="uncompressed", virtual_momentum=0.9),
    "sketch": dict(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                   k=40, num_rows=3, num_cols=256),
    "sketch_threshold": dict(mode="sketch", error_type="virtual",
                             virtual_momentum=0.9, k=40, num_rows=3,
                             num_cols=256, topk_method="threshold"),
    "true_topk": dict(mode="true_topk", error_type="virtual",
                      virtual_momentum=0.9, k=40),
    "local_topk": dict(mode="local_topk", error_type="local", k=30,
                       local_momentum=0.9),
    "fedavg": dict(mode="fedavg", num_local_iters=2, local_lr=0.1,
                   local_batch_size=8),
    "uncompressed_fused": dict(mode="uncompressed", virtual_momentum=0.9,
                               fuse_clients=True),
    "uncompressed_topk_down": dict(mode="uncompressed", do_topk_down=True,
                                   k=25),
}
N_ROUNDS = 4
LR = 0.2


@pytest.fixture(scope="module")
def golden():
    assert os.path.exists(GOLDEN), (
        "tests/golden/registry_parity.npz missing — regenerate with "
        "JAX_PLATFORMS=cpu python scripts/gen_registry_golden.py (see that "
        "script's docstring for when regeneration is legitimate)"
    )
    return np.load(GOLDEN)


@pytest.mark.parametrize("name", sorted(GOLDEN_CONFIGS))
def test_registry_round_outputs_match_pre_refactor(name, golden):
    cfg = Config(**{**BASE, **GOLDEN_CONFIGS[name]})
    sess, losses = _run(cfg, n_rounds=N_ROUNDS, lr=LR)
    want_params = golden[f"{name}__params"]
    want_losses = golden[f"{name}__losses"]
    np.testing.assert_allclose(
        np.asarray(losses, np.float64), want_losses, rtol=1e-6,
        err_msg=f"{name}: per-round losses drifted from the pre-refactor "
        "recording",
    )
    np.testing.assert_allclose(
        _final_vec(sess), want_params, rtol=0, atol=1e-6,
        err_msg=f"{name}: final params drifted from the pre-refactor "
        "recording",
    )
