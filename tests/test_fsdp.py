"""FSDP round (parallel/fsdp.py) vs the replicated oracle on the 8-device
CPU mesh (VERDICT r3 missing 4): same losses and final params, with the
persistent [D] state REALLY sharded ~D/W per chip."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.data import FedDataset, FedSampler
from commefficient_tpu.models.losses import classification_loss
from commefficient_tpu.parallel import FederatedSession
from commefficient_tpu.utils.config import Config

from tests.test_round import TinyMLP, D_IN, _setup

BASE = dict(num_clients=12, num_workers=8, num_devices=8, local_batch_size=4,
            weight_decay=0.0, seed=5, topk_method="threshold")


def _run(cfg, n_rounds=5, lr=0.3):
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                        local_batch_size=cfg.local_batch_size, seed=1)
    losses = []
    for r in range(n_rounds):
        ids, batch = sampler.sample_round(r)
        m = sess.train_round(ids, batch, lr)
        losses.append(float(m["loss"]))
    return sess, losses


def _vec(sess):
    v = np.asarray(sess.state.params_vec)
    return v[: sess.grad_size]


MODES = [
    dict(mode="uncompressed"),
    dict(mode="uncompressed", virtual_momentum=0.9),
    pytest.param(dict(mode="uncompressed", do_topk_down=True, k=64),
                 marks=pytest.mark.slow),
    dict(mode="true_topk", error_type="virtual", virtual_momentum=0.9, k=64),
    pytest.param(dict(mode="true_topk", error_type="none",
                      virtual_momentum=0.9, k=64), marks=pytest.mark.slow),
    dict(mode="sketch", error_type="virtual", virtual_momentum=0.9, k=32,
         num_rows=3, num_cols=80),
    pytest.param(dict(mode="sketch", error_type="none", virtual_momentum=0.0,
                      k=32, num_rows=3, num_cols=80),
                 marks=pytest.mark.slow),
]


@pytest.mark.parametrize("kw", MODES)
def test_fsdp_matches_replicated_oracle(kw):
    kw = dict(kw)
    s_rep, l_rep = _run(Config(**kw, **BASE))
    s_fs, l_fs = _run(Config(**kw, fsdp=True, **BASE))
    np.testing.assert_allclose(l_fs, l_rep, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(_vec(s_fs), _vec(s_rep), atol=2e-5)


def test_fsdp_state_is_really_sharded():
    """The memory claim, checked against the runtime: every persistent [D]
    leaf's largest per-device shard is ~D/W, not D."""
    cfg = Config(mode="true_topk", error_type="virtual", virtual_momentum=0.9,
                 k=64, fsdp=True, **BASE)
    sess, _ = _run(cfg, n_rounds=2)
    d, W = sess.grad_size, 8
    dp = -(-d // W) * W
    for name in ("params_vec", "momentum", "error"):
        arr = getattr(sess.state, name)
        assert arr.shape == (dp,), name
        per_dev = max(s.data.size for s in arr.addressable_shards)
        assert per_dev == dp // W, (name, per_dev, dp // W)

    from commefficient_tpu.parallel.fsdp import per_chip_state_floats

    acct = per_chip_state_floats(cfg, d, None, W)
    assert acct["total"] == 3 * dp // W
    assert acct["replicated_equivalent"] == 3 * d


def test_fsdp_sketch_tables_replicated_params_sharded():
    cfg = Config(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                 k=32, num_rows=3, num_cols=80, fsdp=True, **BASE)
    sess, _ = _run(cfg, n_rounds=2)
    d, W = sess.grad_size, 8
    dp = -(-d // W) * W
    per_dev = max(s.data.size for s in sess.state.params_vec.addressable_shards)
    assert per_dev == dp // W
    # sketch momentum/error stay [r, c] tables (small, replicated)
    assert sess.state.momentum.shape == sess.spec.table_shape
    per_dev_m = max(s.data.size for s in sess.state.momentum.addressable_shards)
    assert per_dev_m == sess.state.momentum.size  # replicated


def test_fsdp_eval_and_params_roundtrip():
    """Eval + the params property see the unpadded [D] vector."""
    ds, params, loss_fn = _setup(12)
    cfg = Config(mode="uncompressed", fsdp=True, **BASE)
    sess = FederatedSession(cfg, params, loss_fn)
    out = sess.evaluate(ds.eval_batches(64))
    assert np.isfinite(out["loss"])
    flat_a = jax.tree.leaves(jax.tree.map(np.asarray, sess.params))
    flat_b = jax.tree.leaves(jax.tree.map(np.asarray, params))
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_fsdp_checkpoint_restore_keeps_shardings(tmp_path):
    """Restore must re-commit FSDP leaves to their P(workers) shards — a
    plain asarray would park the full padded state on one device (the
    memory wall FSDP removes) and trigger a second round_fn compile."""
    from commefficient_tpu.utils.checkpoint import FedCheckpointer

    ds, params, loss_fn = _setup(12)
    cfg = Config(mode="true_topk", error_type="virtual", virtual_momentum=0.9,
                 k=64, fsdp=True, checkpoint_dir=str(tmp_path),
                 checkpoint_every=2, **BASE)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=8, local_batch_size=4, seed=1)
    ckpt = FedCheckpointer(cfg)
    for r in range(2):
        ids, batch = sampler.sample_round(r)
        sess.train_round(ids, batch, 0.3)
        ckpt.maybe_save(sess, r + 1)
    want = np.asarray(sess.state.params_vec)

    sess2 = FederatedSession(cfg, params, loss_fn)
    step = ckpt.restore(sess2)
    ckpt.close()
    assert step == 2
    np.testing.assert_allclose(np.asarray(sess2.state.params_vec), want)
    d, W = sess2.grad_size, 8
    dp = -(-d // W) * W
    for name in ("params_vec", "momentum", "error"):
        arr = getattr(sess2.state, name)
        per_dev = max(s.data.size for s in arr.addressable_shards)
        assert per_dev == dp // W, name
    # and the restored session keeps training (no recompile crash)
    ids, batch = sampler.sample_round(2)
    m = sess2.train_round(ids, batch, 0.3)
    assert np.isfinite(float(m["loss"]))


def test_fsdp_rejects_local_modes():
    with pytest.raises(NotImplementedError, match="offload_client_state"):
        ds, params, loss_fn = _setup(12)
        FederatedSession(
            Config(mode="local_topk", error_type="local", k=64, fsdp=True,
                   **BASE),
            params, loss_fn,
        )


def test_fsdp_composes_with_tp_sp_axes():
    """FSDP x model/seq composition (VERDICT r4 missing 3): the FSDP
    round's P(workers) state specs replicate over the model/seq axes, and
    build_tp_flat_loss's MODEL/SEQ collectives run inside the same
    shard_map — so a dp2 x tp2 x sp2 mesh with fsdp=True must match the
    replicated round on the identical mesh bit-for-bit."""
    from commefficient_tpu.models import gpt2_double_heads_loss
    from commefficient_tpu.models.gpt2 import GPT2Config, GPT2DoubleHeads
    from commefficient_tpu.parallel import make_mesh, mask_gpt2
    from commefficient_tpu.parallel.tensor import build_tp_flat_loss

    rng = np.random.default_rng(0)
    wk, tp_sz, sq = 2, 2, 2
    mesh3 = make_mesh(wk, tp_sz, sq)
    T = 16 * sq
    gcfg = GPT2Config(vocab_size=256, n_positions=T, n_embd=32, n_layer=2,
                      n_head=4, dtype=jnp.float32)
    gmodel = GPT2DoubleHeads(gcfg)
    B, N = 2, 2
    ids = rng.integers(0, 256, size=(wk, B, N, T)).astype(np.int32)
    gparams = gmodel.init(jax.random.key(0), jnp.asarray(ids[0]),
                          token_type_ids=jnp.asarray(ids[0]),
                          mc_token_ids=jnp.zeros((B, N), jnp.int32))
    lm = ids.copy()
    lm[..., : T // 2] = -100
    batch = {"input_ids": ids, "token_type_ids": ids, "lm_labels": lm,
             "mc_token_ids": rng.integers(0, T, size=(wk, B, N)).astype(np.int32),
             "mc_labels": rng.integers(0, N, size=(wk, B)).astype(np.int32)}
    cfg = Config(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                 k=64, num_rows=3, num_cols=2048,
                 num_clients=2 * wk, num_workers=wk, num_devices=wk,
                 model_axis=tp_sz, seq_axis=sq, local_batch_size=B,
                 weight_decay=0.0, device_data=False, fsdp=True,
                 topk_method="threshold")
    cids = np.arange(wk, dtype=np.int32)
    finals = []
    for fsdp in (True, False):
        sess = FederatedSession(
            cfg.replace(fsdp=fsdp), gparams,
            build_tp_flat_loss(gcfg, mesh3), mesh=mesh3,
            mask_batch=mask_gpt2,
            eval_loss_fn=gpt2_double_heads_loss(gmodel.apply),
        )
        for r in range(2):
            m = sess.train_round(cids, batch, lr=0.05)
        assert np.isfinite(float(np.asarray(m["loss"])))
        finals.append(np.asarray(sess.state.params_vec)[: sess.grad_size])
    np.testing.assert_array_equal(finals[0], finals[1])
