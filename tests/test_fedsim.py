"""fedsim/ — availability models, chaos plans, and masked-round algebra.

The load-bearing pin is UNBIASEDNESS: a masked round with live cohort S
must equal (atol 1e-6) an unmasked round run with exactly the clients in
S, for every registered compression mode — masking commutes with every
``device_encode`` because the encode is linear (the compress/ psum-safety
contract) and the server renormalizes by the live count. Kept on the
TinyMLP task (no d=6.6M sketches on CPU — tier-1 budget).
"""

import importlib.util
import json
import os

import numpy as np
import pytest
from test_round import BASE, _final_vec, _setup

from commefficient_tpu.fedsim import (
    ChaosEvent,
    available_models,
    build_environment,
    parse_chaos,
    validate_chaos_rounds,
)
from commefficient_tpu.fedsim.env import FedEnvironment, RoundEnv
from commefficient_tpu.parallel import FederatedSession
from commefficient_tpu.utils.config import AVAILABILITY_MODELS, Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _schema_checker():
    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(REPO, "scripts", "check_telemetry_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# availability models
# ---------------------------------------------------------------------------

def test_availability_registry_matches_config_tuple():
    """config.AVAILABILITY_MODELS mirrors the fedsim registry (the no-cycle
    pattern MODES uses for compress/)."""
    assert tuple(sorted(AVAILABILITY_MODELS)) == available_models()


def _env(**kw):
    defaults = dict(num_workers=8, num_clients=16, seed=7,
                    availability="bernoulli", dropout_prob=0.4,
                    availability_period=16, num_cohorts=4, chaos="")
    defaults.update(kw)
    return FedEnvironment(Config(**defaults))


@pytest.mark.parametrize("model", sorted(AVAILABILITY_MODELS))
def test_masks_deterministic_and_resume_stable(model):
    """Masks are pure functions of (seed, round_idx): two independently
    constructed environments (a resume) realize identical masks; a
    different seed realizes different ones (for the stochastic models)."""
    kw = dict(availability=model,
              dropout_prob=0.0 if model == "always" else 0.4)
    a, b = _env(**kw), _env(**kw)
    masks = [a.round_env(r).live for r in range(30)]
    for r in range(30):
        np.testing.assert_array_equal(masks[r], b.round_env(r).live)
    if model != "always":
        other = _env(seed=8, **kw)
        assert any(
            not np.array_equal(masks[r], other.round_env(r).live)
            for r in range(30)
        )


def test_always_and_sine_and_cohort_shapes():
    env = _env(availability="always", dropout_prob=0.0)
    r = env.round_env(0)
    assert r.live.tolist() == [1.0] * 8 and r.live_count == 8.0
    assert r.stats["fedsim/participation_rate"] == 1.0
    # sine: the realized drop probability oscillates — at a high peak prob
    # the trough rounds (sin == -1 -> p = 0) are all-live by construction
    env = _env(availability="sine", dropout_prob=0.9, availability_period=16)
    trough = env.round_env(12).live  # sin(2*pi*12/16) == -1
    assert trough.sum() == 8
    # cohort: slots of one cohort share their fate (slot i -> cohort i % n)
    env = _env(availability="cohort", dropout_prob=0.5, num_cohorts=4)
    for r in range(20):
        live = env.round_env(r).live
        for c in range(4):
            assert len({float(v) for v in live[c::4]}) == 1


def test_poisson_registered_and_rate_inf_is_always():
    """poisson (the asyncfed arrival model's round-granular projection) is
    a first-class availability model; rate -> inf means delay 0, so with
    no decline knob every slot makes every round — exactly ``always``."""
    assert "poisson" in AVAILABILITY_MODELS
    env = _env(availability="poisson", dropout_prob=0.0,
               arrival_rate=float("inf"))
    always = _env(availability="always", dropout_prob=0.0)
    for r in range(20):
        np.testing.assert_array_equal(env.round_env(r).live,
                                      always.round_env(r).live)
        assert env.round_env(r).live_count == 8.0


def test_poisson_marginal_participation_tracks_rate():
    """Realized participation over many rounds approaches 1 - exp(-rate)
    (each slot arrives iff its exponential delay fits one deadline)."""
    rate = 2.0
    env = _env(availability="poisson", dropout_prob=0.0, arrival_rate=rate)
    live = np.concatenate([env.round_env(r).live for r in range(200)])
    assert abs(live.mean() - (1.0 - np.exp(-rate))) < 0.03


def test_poisson_dropout_composes_and_rng_cursor_is_knob_independent():
    """dropout_prob composes (reachable-yet-declining clients), and the
    arrival-rate knob cannot shift the shared round rng's cursor: at
    rate=inf every arrival succeeds, so the only masking left is the
    decline draw — which must realize IDENTICALLY across rates' streams."""
    a = _env(availability="poisson", dropout_prob=0.4,
             arrival_rate=float("inf"))
    b = _env(availability="poisson", dropout_prob=0.4, arrival_rate=50.0)
    declines_seen = False
    for r in range(30):
        la = a.round_env(r).live
        # rate=50 arrivals virtually always make the deadline; any miss can
        # only REMOVE clients relative to the rate=inf mask, never add
        lb = b.round_env(r).live
        assert not np.any(lb > la)
        declines_seen = declines_seen or la.sum() < 8
    assert declines_seen, "dropout_prob=0.4 must realize some declines"


def test_poisson_rejects_bad_arrival_rate():
    for bad in (0.0, -1.0, float("nan")):
        with pytest.raises(ValueError, match="arrival_rate"):
            Config(num_workers=8, num_clients=16, availability="poisson",
                   arrival_rate=bad)


# ---------------------------------------------------------------------------
# chaos plans
# ---------------------------------------------------------------------------

def test_chaos_parser_grammar():
    plan = parse_chaos("dropout@0.3:rounds=50-100,nan_client@120,"
                       "straggler@0.2")
    assert plan == (
        ChaosEvent("dropout", 0.3, 50, 100),
        ChaosEvent("nan_client", 120.0, 120, 120),
        ChaosEvent("straggler", 0.2, 0, None),
    )
    assert parse_chaos("") == ()
    assert parse_chaos("dropout@0.5:rounds=7-7")[0].end == 7


@pytest.mark.parametrize("bad", [
    "bogus@1",               # unknown kind
    "dropout@1.5",           # probability outside [0, 1)
    "dropout@x",             # not a number
    "dropout@0.3:rounds=9-5",  # descending range
    "dropout@0.3:r=5",       # unknown option
    "nan_client@-1",         # negative round
    "nan_client@1.5",        # fractional round
    # the counted nan_client@N:rounds=A-B form (resilience PR) takes a
    # client COUNT >= 1 before the window — 0/fractional still rejected
    "nan_client@0:rounds=1-2",
    "nan_client@1.5:rounds=1-2",
    "dropout",               # no @value
])
def test_chaos_parser_rejects(bad):
    with pytest.raises(ValueError, match="chaos"):
        parse_chaos(bad)


def test_chaos_rounds_validated_against_run_length():
    plan = parse_chaos("dropout@0.3:rounds=50-100,nan_client@120")
    validate_chaos_rounds(plan, 121)  # just fits
    with pytest.raises(ValueError, match="120"):
        validate_chaos_rounds(plan, 120)  # nan round never fires
    with pytest.raises(ValueError, match="only 60 rounds"):
        validate_chaos_rounds(parse_chaos("dropout@0.3:rounds=50-100"), 60)


def test_chaos_events_realize_straggler_and_nan():
    env = _env(availability="always", dropout_prob=0.0,
               chaos="straggler@0.5:rounds=0-99,nan_client@3")
    seen_straggler = False
    for r in range(20):
        re = env.round_env(r)
        s = re.stats
        # stragglers are excluded from the live mask but counted apart
        # from dropped (they DID download + compute)
        assert s["fedsim/dropped"] == 0.0
        assert (s["fedsim/straggler_excluded"]
                == 8 - re.live.sum() == 8 - re.live_count)
        seen_straggler |= s["fedsim/straggler_excluded"] > 0
        if r == 3 and re.live_count > 0:
            assert re.corrupt.sum() == 1
            assert re.live[np.argmax(re.corrupt)] == 1.0  # a LIVE client
        else:
            assert re.corrupt.sum() == 0
    assert seen_straggler


# ---------------------------------------------------------------------------
# Config validation (satellite)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kw,match", [
    (dict(dropout_prob=-0.1), r"dropout_prob"),
    (dict(dropout_prob=1.0), r"dropout_prob"),  # [0, 1): 1.0 rejected
    (dict(availability="bogus"), r"availability"),
    (dict(dropout_prob=0.5), r"always"),  # prob without a model using it
    (dict(availability="sine", dropout_prob=0.5, availability_period=0),
     r"availability_period"),
    (dict(availability="cohort", dropout_prob=0.5, num_cohorts=0),
     r"num_cohorts"),
    (dict(chaos="dropout@1.5"), r"chaos"),
])
def test_config_rejects_bad_fedsim_knobs(kw, match):
    with pytest.raises(ValueError, match=match):
        Config(**kw)


def test_divisibility_error_hints_at_masking():
    """num_workers resizing is NOT how partial participation is modeled —
    the error must point at the fedsim mask instead."""
    with pytest.raises(ValueError, match="mask"):
        Config(num_workers=6, num_devices=4, num_clients=8)


def test_fedsim_enabled_gate():
    assert not Config().fedsim_enabled
    assert Config(availability="bernoulli", dropout_prob=0.3).fedsim_enabled
    assert Config(chaos="nan_client@1").fedsim_enabled


def test_env_override_on_disabled_session_rejected():
    """A session built without fedsim traced no masking — an explicit env
    override must be rejected, not silently dropped while its stats leak
    into the metrics."""
    from commefficient_tpu.data import FedSampler

    cfg = Config(**BASE)  # availability='always': fedsim disabled
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    ids, batch = FedSampler(ds, num_workers=8, local_batch_size=4,
                            seed=1).sample_round(0)
    with pytest.raises(ValueError, match="fedsim_enabled"):
        sess.train_round(ids, batch, 0.3, env=_cohort_env(S))


# ---------------------------------------------------------------------------
# masked-round unbiasedness (satellite) — all six modes, TinyMLP
# ---------------------------------------------------------------------------

MODE_CONFIGS = {
    "uncompressed": dict(mode="uncompressed", virtual_momentum=0.9),
    "sketch": dict(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                   k=40, num_rows=3, num_cols=256),
    "true_topk": dict(mode="true_topk", error_type="virtual",
                      virtual_momentum=0.9, k=40, momentum_dampening=False),
    "local_topk": dict(mode="local_topk", error_type="local", k=30,
                       local_momentum=0.9),
    "fedavg": dict(mode="fedavg", num_local_iters=2, local_lr=0.1,
                   local_batch_size=8),
    "powersgd": dict(mode="powersgd", error_type="virtual",
                     virtual_momentum=0.9, powersgd_rank=2),
}
S = np.array([0, 2, 3, 5, 7])  # the live cohort (5 of 8 slots)


def _cohort_env(live_slots, num_workers=8, corrupt_slot=None):
    live = np.zeros(num_workers, np.float32)
    live[live_slots] = 1.0
    corrupt = np.zeros(num_workers, np.float32)
    if corrupt_slot is not None:
        corrupt[corrupt_slot] = 1.0
    n = float(live.sum())
    return RoundEnv(
        live=live, corrupt=corrupt, live_count=np.float32(n),
        stats={"fedsim/participation_rate": n / num_workers,
               "fedsim/dropped": num_workers - n,
               "fedsim/straggler_excluded": 0.0,
               "fedsim/all_dropped": float(n == 0)},
    )


def _rounds(cfg, sampler_bs, env=None, subset=None, n_rounds=3, lr=0.3):
    """Run rounds through a fresh session; ``env`` drives the masked run,
    ``subset`` restricts the batch to cohort rows for the oracle run."""
    from commefficient_tpu.data import FedSampler

    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=8, local_batch_size=sampler_bs,
                         seed=1)
    m = None
    for r in range(n_rounds):
        ids, batch = sampler.sample_round(r)
        L = cfg.round_microbatches
        if L:
            batch = {
                k: v.reshape(v.shape[0], L, v.shape[1] // L, *v.shape[2:])
                for k, v in batch.items()
            }
        if subset is not None:
            ids, batch = ids[subset], {k: v[subset] for k, v in batch.items()}
        m = sess.train_round(ids, batch, lr, env=env)
    return sess, m


@pytest.mark.parametrize("name", sorted(MODE_CONFIGS))
def test_masked_round_unbiased_per_mode(name):
    """Masked round with live cohort S == unmasked round over exactly S:
    masking commutes with device_encode (linear) and the live-count
    renormalization matches the smaller round's /|S| average. Same clients,
    same batches, same per-client noise rngs — the ONLY difference is who
    transmits."""
    kw = dict(MODE_CONFIGS[name])
    base = dict(BASE)
    base["local_batch_size"] = kw.pop("local_batch_size",
                                      base["local_batch_size"])
    bs = base["local_batch_size"] * (kw.get("num_local_iters", 1)
                                     if name == "fedavg" else 1)
    base.pop("num_workers"), base.pop("num_devices")
    cfg_masked = Config(num_workers=8, num_devices=8,
                        availability="bernoulli", dropout_prob=0.5,
                        **base, **kw)
    cfg_oracle = Config(num_workers=len(S), num_devices=1, **base, **kw)
    sm, metrics = _rounds(cfg_masked, bs, env=_cohort_env(S))
    so, _ = _rounds(cfg_oracle, bs, subset=S)
    assert metrics["fedsim/participation_rate"] == len(S) / 8
    np.testing.assert_allclose(
        _final_vec(sm), _final_vec(so), atol=1e-6,
        err_msg=f"{name}: masked round is NOT the cohort-S round",
    )


def test_masked_round_leaves_dropped_client_state_untouched():
    """local_topk: a dropped client's error/momentum rows carry forward
    unmodified (it never participated); live clients' rows move."""
    kw = dict(MODE_CONFIGS["local_topk"])
    base = {**BASE}
    base.pop("num_workers"), base.pop("num_devices")
    cfg = Config(num_workers=8, num_devices=8, availability="bernoulli",
                 dropout_prob=0.5, **base, **kw)
    sess, _ = _rounds(cfg, base["local_batch_size"], env=_cohort_env(S),
                      n_rounds=1)
    err = np.asarray(sess.state.client_err)
    vel = np.asarray(sess.state.client_vel)
    from commefficient_tpu.data import FedSampler

    ids, _ = FedSampler(_setup(cfg.num_clients)[0], num_workers=8,
                        local_batch_size=4, seed=1).sample_round(0)
    dropped = np.setdiff1d(np.arange(8), S)
    # error rows start at zero: dropped participants' rows must STAY zero,
    # live participants' must not
    assert np.all(err[ids[dropped]] == 0.0)
    assert np.all(vel[ids[dropped]] == 0.0)
    assert np.any(err[ids[S]] != 0.0)


def test_corrupt_flag_on_dead_client_cannot_poison():
    """Documented ordering invariant: the live mask is applied AFTER
    corruption, so a corrupt flag on a non-live slot injects nothing —
    only a LIVE corrupted client can poison the aggregate (matters for
    explicit RoundEnv overrides; the env builder already targets live
    slots)."""
    base = {**BASE}
    base.pop("num_workers"), base.pop("num_devices")
    cfg = Config(num_workers=8, num_devices=8, availability="bernoulli",
                 dropout_prob=0.5, mode="uncompressed", **base)
    # corrupt slot 1, which is NOT in the live cohort S
    assert 1 not in S
    sess, m = _rounds(cfg, base["local_batch_size"],
                      env=_cohort_env(S, corrupt_slot=1), n_rounds=1)
    assert np.all(np.isfinite(_final_vec(sess)))
    assert np.isfinite(float(m["loss"]))


def test_all_dropped_round_freezes_everything():
    """Zero live clients: params + momentum frozen bitwise, the sentinel
    stat flags it, and nothing divides by zero."""
    base = {**BASE}
    base.pop("num_workers"), base.pop("num_devices")
    cfg = Config(num_workers=8, num_devices=8, availability="bernoulli",
                 dropout_prob=0.5, mode="uncompressed", virtual_momentum=0.9,
                 **base)
    sess, _ = _rounds(cfg, base["local_batch_size"], env=_cohort_env(S),
                      n_rounds=2)
    before = _final_vec(sess).copy()
    mom = np.asarray(sess.state.momentum).copy()
    from commefficient_tpu.data import FedSampler

    ids, batch = FedSampler(_setup(cfg.num_clients)[0], num_workers=8,
                            local_batch_size=4, seed=1).sample_round(5)
    m = sess.train_round(ids, batch, 0.3, env=_cohort_env([]))
    assert m["fedsim/all_dropped"] == 1.0
    assert np.array_equal(before, _final_vec(sess))
    assert np.array_equal(mom, np.asarray(sess.state.momentum))
    assert np.isfinite(float(m["loss"]))
    assert int(np.asarray(sess.state.step)) == 3  # the round still counts


def test_masked_offload_matches_hbm_client_state():
    """offload_client_state changes only the row plumbing — masked rounds
    must be bit-identical between host-resident and HBM client state."""
    from commefficient_tpu.data import FedSampler

    base = {**BASE}
    base.pop("num_workers"), base.pop("num_devices")
    kw = dict(mode="local_topk", error_type="local", k=30,
              local_momentum=0.9, availability="bernoulli",
              dropout_prob=0.5)

    def run(offload):
        cfg = Config(num_workers=8, num_devices=8, device_data=False,
                     offload_client_state=offload, **base, **kw)
        ds, params, loss_fn = _setup(cfg.num_clients)
        sess = FederatedSession(cfg, params, loss_fn)
        sampler = FedSampler(ds, num_workers=8, local_batch_size=4, seed=1)
        for r in range(3):
            ids, batch = sampler.sample_round(r)
            sess.train_round(ids, batch, 0.3, env=_cohort_env(S))
        return _final_vec(sess)

    np.testing.assert_array_equal(run(False), run(True))


def test_masked_fsdp_matches_masked_replicated():
    """The FSDP round applies the same mask semantics as the replicated
    round (mask -> renormalize -> freeze guard), sharded."""
    base = {**BASE}
    base.pop("num_workers"), base.pop("num_devices")
    kw = dict(mode="true_topk", error_type="virtual", virtual_momentum=0.9,
              k=40, topk_method="threshold", momentum_dampening=False)
    cfg_r = Config(num_workers=8, num_devices=8, availability="bernoulli",
                   dropout_prob=0.5, **base, **kw)
    cfg_f = cfg_r.replace(fsdp=True)
    sr, _ = _rounds(cfg_r, base["local_batch_size"], env=_cohort_env(S))
    sf, _ = _rounds(cfg_f, base["local_batch_size"], env=_cohort_env(S))
    np.testing.assert_allclose(
        _final_vec(sr), np.asarray(sf.state.params_vec)[: sf.grad_size],
        atol=1e-5,
    )


# ---------------------------------------------------------------------------
# ledger live-byte accounting + schema (satellites)
# ---------------------------------------------------------------------------

def test_ledger_masked_accounting_is_exact(tmp_path):
    """cum bytes == sum of live_i x per-client bytes EXACTLY, through the
    compressor's mask-aware hook, and the schema checker enforces it."""
    from commefficient_tpu.compress import get_compressor
    from commefficient_tpu.telemetry import CommLedger

    cfg = Config(mode="local_topk", error_type="local", k=10,
                 availability="bernoulli", dropout_prob=0.3)
    comp = get_compressor(cfg, d=1000)
    bpr = {"upload_floats": 20, "download_floats": 1000,
           "upload_bytes": 80, "download_bytes": 4000}
    led = CommLedger(bpr, mode="local_topk", num_workers=8, masked=True,
                     compressor=comp)
    lives = [5, 8, 0, 3]
    for s, live in enumerate(lives):
        scal = {"fedsim/participation_rate": live / 8,
                "fedsim/dropped": float(8 - live) if live else 8.0,
                "fedsim/straggler_excluded": 0.0}
        out = led.on_round(s, scal)
        assert out["comm/up_bytes"] == live * 80
    assert led.cum_up_bytes == sum(lives) * 80
    summ = led.summary()
    assert summ["live_client_rounds"] == sum(lives)
    led.write(str(tmp_path))
    mod = _schema_checker()
    mod.validate_comm_ledger(tmp_path / "comm_ledger.json")
    # tampering with the live sum must fail the invariant
    bad = json.loads((tmp_path / "comm_ledger.json").read_text())
    bad["live_client_rounds"] += 1
    (tmp_path / "comm_ledger.json").write_text(json.dumps(bad))
    with pytest.raises(mod.SchemaError, match="live_client_rounds"):
        mod.validate_comm_ledger(tmp_path / "comm_ledger.json")


def test_flight_dump_carries_participation_history(tmp_path):
    from commefficient_tpu.telemetry import FlightRecorder

    fl = FlightRecorder(logdir=str(tmp_path), window=8)
    for s in range(5):
        fl.record(s, 0.1, {"loss": 1.0, "fedsim/participation_rate": 0.75})
    path = fl.dump(4, reason="test", first_bad_step=None)
    rec = json.loads(open(path).read())
    assert rec["participation_history"] == [[s, 0.75] for s in range(5)]
    _schema_checker().validate_flight(path)


# ---------------------------------------------------------------------------
# end-to-end through cv_train (satellite + acceptance)
# ---------------------------------------------------------------------------

def _cv_kwargs(tmp_path, **kw):
    base = dict(
        dataset_name="femnist", model="resnet9", num_clients=6,
        num_workers=4, num_devices=4, local_batch_size=32, num_epochs=1,
        pivot_epoch=1, lr_scale=0.1, telemetry_level=1,
        dataset_dir=str(tmp_path), logdir=str(tmp_path / "runs"), seed=0,
    )
    base.update(kw)
    return base


def _run_dir(tmp_path):
    runs = sorted((tmp_path / "runs").iterdir())
    assert len(runs) == 1
    return runs[0]


@pytest.mark.slow  # ~51 s of ResNet-9 cv_main compiles (r20 tier budget);
# every assertion holds tier-1 siblings: the femnist CLI e2e keeps the
# cv_main surface, test_resilience pins nan_client divergence + flight at
# TinyMLP scale, and test_fleet's shrink twin pins the ledger exactness
# invariant over the ENTIRE comm_ledger.json
def test_cv_train_dropout_nan_client_ledger_and_flight(tmp_path):
    """One bernoulli@0.3 cv_train run under chaos, covering the whole
    observable surface in a single ResNet-9 compile (tier-1 budget):

      * chaos nan_client end-to-end — the DivergenceError names the
        injected round (the in-graph sentinel sees the corrupted params at
        round 2 itself), and the flight dump carries the participation
        history window;
      * fedsim/participation_rate rides metrics.jsonl for every drained
        round;
      * the ledger — written on crash like any partial ledger — is exact
        over the drained rounds: cum bytes == live-client sum x per-client
        bytes (checker-enforced AND recomputed from the logged rates)."""
    from commefficient_tpu.telemetry import DivergenceError
    from commefficient_tpu.train.cv_train import main as cv_main

    with pytest.raises(DivergenceError) as ei:
        cv_main([], **_cv_kwargs(
            tmp_path, mode="local_topk", error_type="local", k=2000,
            availability="bernoulli", dropout_prob=0.3,
            chaos="nan_client@2",
        ))
    assert ei.value.step == 2
    run = _run_dir(tmp_path)
    mod = _schema_checker()
    mod.validate_run_dir(run)  # masked ledger invariant enforced inside
    flights = sorted(run.glob("flight_*.json"))
    assert flights, "no flight dump written"
    rec = json.loads(flights[0].read_text())
    hist = rec["participation_history"]
    assert [s for s, _ in hist] == [r["step"] for r in rec["records"]]
    rates = [
        json.loads(line) for line in open(run / "metrics.jsonl")
        if '"fedsim/participation_rate"' in line
    ]
    assert [r["step"] for r in rates] == [0, 1, 2]  # drained up to the raise
    ledger = json.loads((run / "comm_ledger.json").read_text())
    live_sum = round(sum(r["value"] for r in rates) * 4)  # W = 4
    assert ledger["live_client_rounds"] == live_sum
    assert ledger["cum_up_bytes"] == live_sum * ledger["bytes_per_round"][
        "upload_bytes"]


@pytest.mark.slow  # the d~6.6M CountSketch einsum costs minutes on CPU
def test_cv_train_bernoulli_sketch_completes(tmp_path):
    """Acceptance twin of the test above in sketch mode (the paper's
    headline compressor) — slow tier, same assertions."""
    from commefficient_tpu.train.cv_train import main as cv_main

    val = cv_main([], **_cv_kwargs(
        tmp_path, mode="sketch", error_type="virtual", virtual_momentum=0.9,
        k=2000, num_rows=3, num_cols=100_000, topk_method="threshold",
        availability="bernoulli", dropout_prob=0.3,
    ))
    assert np.isfinite(val["loss"])
    run = _run_dir(tmp_path)
    _schema_checker().validate_run_dir(run)
    ledger = json.loads((run / "comm_ledger.json").read_text())
    assert ledger["cum_up_bytes"] == (
        ledger["live_client_rounds"] * ledger["bytes_per_round"]["upload_bytes"]
    )


