"""Sharded sparse sketch decode (PR 6): decode-path equivalence + HLO pins.

The replicated round's sketch server update can decode dense (legacy:
every chip repeats the full-D estimate -> top-k -> unsketch -> re-sketch)
or sharded (``SketchCompressor.server_update_sharded``: each chip
estimates its D/W coordinate slice, the global threshold uses scalar-only
collectives, and one ~W*k candidate all_gather replaces the full-D work).
Pinned here, on the virtual 8-device CPU mesh:

  * dense vs sharded vs Pallas-fused final params atol 1e-6 (bit-equal on
    CPU for the threshold kernel: integer-count bisection + the gather
    estimate path being bit-equal to the matmul path) across error_type
    none/virtual, error_decay, rho>0, degenerate top-k ties, and
    fedsim-masked (+ all-dropped) rounds;
  * the compiled sharded round contains NO full-d ``estimate_all`` (the
    named_scope marker in ops/countsketch.py), NO dense-decode branch
    (round.py's ``server_decode_dense`` marker), and no all-gather beyond
    the ~W*k candidate exchange — the acceptance criterion's traffic
    claim, checked on real lowered shapes;
  * byte accounting and the CommLedger exactness invariant are identical
    across decode paths (decode is server-side; accounting must not
    drift);
  * the dampening branch's sparse support-estimate (satellite fix) equals
    the legacy full-D ``estimate_all`` formula.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from test_round import BASE, _final_vec, _run, _setup

from commefficient_tpu.data import FedSampler
from commefficient_tpu.fedsim import RoundEnv
from commefficient_tpu.ops.countsketch import (
    CountSketch,
    estimate_all,
    estimate_at,
    sketch_sparse,
    sketch_vec,
)
from commefficient_tpu.ops.topk import compact_nonzero, topk_threshold_dense
from commefficient_tpu.parallel import FederatedSession
from commefficient_tpu.telemetry import CommLedger
from commefficient_tpu.utils.config import Config

SKETCH = dict(mode="sketch", k=40, num_rows=3, num_cols=256,
              topk_method="threshold")

# the error-feedback/momentum corners the dense<->sharded algebra must
# agree on (ISSUE 6 satellite: none/virtual, error_decay, rho>0)
DECODE_CASES = {
    "virtual_rho": dict(error_type="virtual", virtual_momentum=0.9),
    "virtual_decay": dict(error_type="virtual", virtual_momentum=0.9,
                          error_decay=0.9),
    "virtual_norho": dict(error_type="virtual"),
    "none_rho": dict(error_type="none", virtual_momentum=0.9),
}


@pytest.mark.parametrize("name", sorted(DECODE_CASES))
def test_sharded_decode_matches_dense(name):
    kw = {**SKETCH, **DECODE_CASES[name]}
    sd, ld = _run(Config(sketch_decode="dense", **kw, **BASE),
                  n_rounds=4, lr=0.2)
    ss, ls = _run(Config(sketch_decode="sharded", **kw, **BASE),
                  n_rounds=4, lr=0.2)
    np.testing.assert_allclose(ls, ld, rtol=1e-6,
                               err_msg=f"{name}: losses drifted")
    np.testing.assert_allclose(
        _final_vec(ss), _final_vec(sd), atol=1e-6,
        err_msg=f"{name}: sharded decode is NOT the dense decode",
    )


def test_pallas_fused_decode_matches_dense():
    """backend='pallas' twins: the sharded decode's fused estimate_at
    kernel (ops/pallas/decode_kernels.py) against the same backend's
    dense decode — isolates the DECODE difference (einsum-vs-pallas encode
    parity is pinned by tests/test_countsketch_pallas.py)."""
    kw = {**SKETCH, "error_type": "virtual", "virtual_momentum": 0.9,
          "sketch_backend": "pallas"}
    sd, _ = _run(Config(sketch_decode="dense", **kw, **BASE),
                 n_rounds=2, lr=0.2)
    ss, _ = _run(Config(sketch_decode="sharded", **kw, **BASE),
                 n_rounds=2, lr=0.2)
    np.testing.assert_allclose(_final_vec(ss), _final_vec(sd), atol=1e-6)


def test_auto_resolution_and_validation():
    """auto = sharded iff >1 worker device AND threshold top-k; explicit
    'sharded' demands the threshold kernel + sketch mode at Config time."""
    ds, params, loss_fn = _setup()
    kw = {**SKETCH, "error_type": "virtual", "virtual_momentum": 0.9}
    sess = FederatedSession(Config(**kw, **BASE), params, loss_fn)
    assert sess.sketch_decode_resolved == "sharded"
    # exact top-k keeps the dense path (tie-breaking semantics preserved)
    sess = FederatedSession(
        Config(**{**kw, "topk_method": "exact"}, **BASE), params, loss_fn
    )
    assert sess.sketch_decode_resolved == "dense"
    # single-device mesh: no redundant work to remove -> dense
    sess = FederatedSession(
        Config(**kw, **{**BASE, "num_devices": 1}), params, loss_fn
    )
    assert sess.sketch_decode_resolved == "dense"
    with pytest.raises(ValueError, match="threshold"):
        Config(**{**kw, "topk_method": "exact"},
               sketch_decode="sharded", **BASE)
    with pytest.raises(ValueError, match="sketch"):
        Config(mode="uncompressed", sketch_decode="sharded", **BASE)
    with pytest.raises(ValueError, match="auto|dense|sharded"):
        Config(sketch_decode="bogus", **BASE)
    # degenerate explicit sharded on a 1-device mesh: works, but warns
    with pytest.warns(UserWarning, match="degenerate"):
        FederatedSession(
            Config(**kw, sketch_decode="sharded",
                   **{**BASE, "num_devices": 1}),
            params, loss_fn,
        )


def test_degenerate_topk_ties_drop_identically():
    """>k coordinates tying at the max magnitude: no threshold selects
    <=k, so BOTH decode paths must honor the at-most-k contract by
    dropping the tied set entirely (ops/topk.py degenerate-tie contract;
    error feedback retains it for later rounds)."""
    from commefficient_tpu.compress import get_compressor
    from commefficient_tpu.parallel.mesh import WORKERS, make_mesh
    from commefficient_tpu.utils.jax_compat import shard_map

    P = jax.sharding.PartitionSpec
    d, k, Wd = 4096, 30, 8
    cfg = Config(mode="sketch", error_type="none", k=k, num_rows=3,
                 num_cols=32768, topk_method="threshold",
                 sketch_decode="sharded", **BASE)
    spec = CountSketch(d=d, c=32768, r=3, seed=0)
    comp = get_compressor(cfg, d=d, spec=spec)
    v = jnp.zeros(d).at[jnp.arange(0, d, 64)].set(1.0)  # 64 tied maxima
    agg = sketch_vec(spec, v)
    # precondition: the tie really reaches the estimates (c >> d, so the
    # 64 heavy coords estimate exactly 1.0 and outnumber k)
    est = estimate_all(spec, agg)
    assert int(jnp.sum(jnp.abs(est) >= jnp.max(jnp.abs(est)))) > k
    delta, _, _, _ = comp.server_update((), (), (), agg, jnp.float32(0.1),
                                        jnp.int32(0))
    assert float(jnp.max(jnp.abs(delta))) == 0.0, "dense must drop ties"

    mesh = make_mesh(Wd)
    dec = shard_map(
        lambda a: comp.server_update_sharded(
            (), (), (), a, jnp.float32(0.1), jnp.int32(0),
            axis_name=WORKERS, Wd=Wd, d=d,
        ),
        mesh=mesh, in_specs=(P(),), out_specs=(P(),) * 5,
    )
    g_idx, g_val, _, _, _ = jax.jit(dec)(agg)
    assert float(jnp.max(jnp.abs(g_val))) == 0.0, "sharded must drop ties"
    assert g_idx.shape == (Wd * k,)


def _cohort_env(live_slots, num_workers=8):
    live = np.zeros(num_workers, np.float32)
    live[live_slots] = 1.0
    n = float(live.sum())
    return RoundEnv(
        live=live, corrupt=np.zeros(num_workers, np.float32),
        live_count=np.float32(n),
        stats={"fedsim/participation_rate": n / num_workers,
               "fedsim/dropped": num_workers - n,
               "fedsim/straggler_excluded": 0.0,
               "fedsim/all_dropped": float(n == 0)},
    )


def _masked_run(decode, env, n_rounds=3):
    kw = {**SKETCH, "error_type": "virtual", "virtual_momentum": 0.9}
    cfg = Config(sketch_decode=decode, availability="bernoulli",
                 dropout_prob=0.5, **kw, **BASE)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=8, local_batch_size=4, seed=1)
    m = None
    for r in range(n_rounds):
        ids, batch = sampler.sample_round(r)
        m = sess.train_round(ids, batch, 0.3, env=env)
    return sess, sampler, m


def test_fedsim_masked_sharded_matches_dense():
    """Masking is pre-encode, so it commutes with the decode unchanged: a
    masked sharded round equals the masked dense round."""
    S = [0, 2, 3, 5, 7]
    sd, _, _ = _masked_run("dense", _cohort_env(S))
    ss, _, m = _masked_run("sharded", _cohort_env(S))
    assert m["fedsim/participation_rate"] == len(S) / 8
    np.testing.assert_allclose(_final_vec(ss), _final_vec(sd), atol=1e-6)


def test_fedsim_all_dropped_round_freezes_sharded():
    """Zero live clients under the sharded decode: the candidate values
    zero out (the k-sparse scatter applies nothing) and every server-state
    leaf carries forward — the sparse form of the all-dropped guard."""
    ss, sampler, _ = _masked_run("sharded", _cohort_env([0, 2, 3, 5, 7]))
    before = _final_vec(ss).copy()
    mom = np.asarray(ss.state.momentum).copy()
    err = np.asarray(ss.state.error).copy()
    ids, batch = sampler.sample_round(5)
    m = ss.train_round(ids, batch, 0.3, env=_cohort_env([]))
    assert m["fedsim/all_dropped"] == 1.0
    assert np.array_equal(before, _final_vec(ss))
    assert np.array_equal(mom, np.asarray(ss.state.momentum))
    assert np.array_equal(err, np.asarray(ss.state.error))
    assert np.isfinite(float(m["loss"]))


def test_offload_sharded_matches_hbm_client_state():
    """The offloaded-client-state round_fn variant threads the sharded
    decode identically (local momentum rows ride host RAM; decode is
    server-side)."""
    kw = {**SKETCH, "error_type": "virtual", "virtual_momentum": 0.9,
          "local_momentum": 0.9, "sketch_decode": "sharded"}
    s_hbm, _ = _run(Config(**kw, **BASE), n_rounds=3, lr=0.2)
    s_off, _ = _run(Config(offload_client_state=True, **kw, **BASE),
                    n_rounds=3, lr=0.2)
    np.testing.assert_allclose(_final_vec(s_off), _final_vec(s_hbm),
                               atol=1e-6)


def test_device_index_path_sharded_matches_dense():
    """The device-resident-data round (attach_data/train_round_indices)
    threads the decode through the same build_round_fn — pin it anyway:
    an index-driven sharded round equals the index-driven dense round."""
    from test_device_data import _mlp_loss, _toy_ds, augment_batch

    from commefficient_tpu.parallel.mesh import make_mesh

    finals = []
    for dec in ("dense", "sharded"):
        cfg = Config(mode="sketch", error_type="virtual",
                     virtual_momentum=0.9, k=64, num_rows=3, num_cols=2048,
                     num_clients=16, num_workers=8, num_devices=8,
                     local_batch_size=4, weight_decay=0.0, seed=1,
                     topk_method="threshold", sketch_decode=dec)
        params, loss_fn = _mlp_loss()
        ds = _toy_ds(num_clients=16)
        session = FederatedSession(cfg, params, loss_fn, mesh=make_mesh(8))
        sampler = FedSampler(ds, num_workers=8, local_batch_size=4, seed=1,
                             augment=augment_batch)
        session.attach_data(ds.data, augment_batch)
        for r in range(3):
            ids, idx, plan = sampler.sample_round_indices(r)
            session.train_round_indices(ids, idx, plan, 0.1)
        finals.append(np.asarray(session.state.params_vec))
    np.testing.assert_allclose(finals[1], finals[0], atol=1e-6)


def test_sharded_telemetry_scalars_match_dense():
    """The sparse diagnostics path (diagnostics_sparse/fidelity_sparse)
    reports the SAME scalars as the dense path: update_norm sums disjoint
    candidate values, fidelity re-estimates at the same support."""
    kw = {**SKETCH, "error_type": "virtual", "virtual_momentum": 0.9,
          "telemetry_level": 2}
    mets = {}
    for dec in ("dense", "sharded"):
        cfg = Config(sketch_decode=dec, **kw, **BASE)
        ds, params, loss_fn = _setup(cfg.num_clients)
        sess = FederatedSession(cfg, params, loss_fn)
        sampler = FedSampler(ds, num_workers=8, local_batch_size=4, seed=1)
        ids, batch = sampler.sample_round(0)
        mets[dec] = sess.train_round(ids, batch, 0.2)
    for key in ("diag/grad_norm", "diag/update_norm",
                "diag/ef_residual_norm", "diag/ef_residual_max",
                "diag/sketch_est_rel_err"):
        a = float(np.asarray(mets["dense"][key]))
        b = float(np.asarray(mets["sharded"][key]))
        np.testing.assert_allclose(b, a, rtol=1e-4, err_msg=key)
    assert float(np.asarray(mets["sharded"]["diag/nonfinite"])) == 0.0


def _compiled_round_text(cfg):
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=8, local_batch_size=4, seed=1)
    ids, batch = sampler.sample_round(0)
    lowered = sess.round_fn.lower(
        sess.state, jnp.asarray(ids),
        {k: jnp.asarray(v) for k, v in batch.items()}, jnp.float32(0.2),
    )
    return sess, lowered.compile().as_text()


def test_hlo_sharded_round_has_no_dense_decode():
    """PR-6 acceptance HLO pin (precedent: the telemetry level-0 pin): the
    compiled sharded round contains NO full-d ``estimate_all`` (the
    named_scope marker every full-d estimate carries), NO dense server
    decode branch (round.py's ``server_decode_dense`` marker), and its
    only all-gathers are the ~W*k candidate exchange — nothing d-sized
    ever crosses the ICI. The dense round proves both markers detect what
    they claim to."""
    kw = {**SKETCH, "k": 10, "error_type": "virtual",
          "virtual_momentum": 0.9}
    sess_d, text_d = _compiled_round_text(
        Config(sketch_decode="dense", **kw, **BASE)
    )
    assert "estimate_all" in text_d  # marker validity
    assert "server_decode_dense" in text_d
    assert "sketch_decode_sharded" not in text_d
    assert "all-gather(" not in text_d  # the dense round has NO gathers

    sess_s, text_s = _compiled_round_text(
        Config(sketch_decode="sharded", **kw, **BASE)
    )
    assert "estimate_all" not in text_s
    assert "server_decode_dense" not in text_s
    assert "sketch_decode_sharded" in text_s
    d, Wd, k = sess_s.grad_size, 8, 10
    gathers = [
        ln for ln in text_s.splitlines() if "all-gather(" in ln and "=" in ln
    ]
    assert gathers, "the candidate exchange must exist"
    assert Wd * k < d  # the traffic claim is non-trivial at this geometry
    for ln in gathers:
        shape = re.search(r"=\s+\w+\[([\d,]+)\]", ln)
        assert shape, f"unparsed all-gather line: {ln!r}"
        n_elems = int(np.prod([int(x) for x in shape.group(1).split(",")]))
        assert n_elems <= Wd * k, (
            f"all-gather of {n_elems} elements exceeds the W*k candidate "
            f"exchange ({Wd * k}); a d-sized collective leaked in: {ln!r}"
        )


def test_accounting_invariant_across_decode_paths():
    """Decode is server-side: upload/download accounting and the
    CommLedger exactness invariant must be byte-identical across decode
    paths (the ledger-invariance satellite)."""
    ds, params, loss_fn = _setup()
    kw = {**SKETCH, "error_type": "virtual", "virtual_momentum": 0.9}
    bpr, ledgers = {}, {}
    for dec in ("dense", "sharded", "auto"):
        sess = FederatedSession(Config(sketch_decode=dec, **kw, **BASE),
                                params, loss_fn)
        bpr[dec] = sess.bytes_per_round()
        assert sess.compressor.masked_upload_floats(5) == (
            5 * sess.compressor.upload_floats()
        )
        led = CommLedger(bpr[dec], mode="sketch", num_workers=8,
                         masked=True, compressor=sess.compressor)
        scal = {"fedsim/participation_rate": 5 / 8, "fedsim/dropped": 3.0}
        rows = [led.on_round(r, scal) for r in range(3)]
        ledgers[dec] = (rows, led.cum_up_bytes, led.cum_down_bytes)
    assert bpr["dense"] == bpr["sharded"] == bpr["auto"]
    assert ledgers["dense"] == ledgers["sharded"] == ledgers["auto"]
    # and the exactness invariant holds for the masked rounds:
    # cum_up_bytes == live_client_rounds x upload_bytes
    _, cum_up, _ = ledgers["sharded"]
    assert cum_up == 3 * 5 * bpr["sharded"]["upload_bytes"]


def test_dampening_support_estimate_matches_legacy_formula():
    """Satellite fix regression (compress/sketch.py dampening branch): the
    sparse support-estimate (compact_nonzero + estimate_at +
    sketch_sparse) equals the legacy full-D formula
    ``sketch_vec(where(update != 0, estimate_all(m), 0))`` it replaced."""
    rng = np.random.default_rng(3)
    spec = CountSketch(d=4096, c=2048, r=3, seed=1)
    m_tab = sketch_vec(spec, jnp.asarray(
        rng.normal(size=4096).astype(np.float32)))
    update = topk_threshold_dense(
        jnp.asarray(rng.normal(size=4096).astype(np.float32)), 50
    )
    legacy = sketch_vec(
        spec, jnp.where(update != 0, estimate_all(spec, m_tab), 0.0)
    )
    idx, val = compact_nonzero(update, 50)
    sparse = sketch_sparse(
        spec, idx,
        jnp.where(val != 0, estimate_at(spec, m_tab, idx), 0.0),
    )
    np.testing.assert_allclose(np.asarray(sparse), np.asarray(legacy),
                               atol=1e-6)


def test_dampening_e2e_dense_matches_sharded():
    """Both decode paths' sparse dampening branches agree end to end (the
    combination is gated as unstable at paper scale — parity-experiment
    flag — but its algebra must still be decode-invariant)."""
    kw = {**SKETCH, "error_type": "virtual", "virtual_momentum": 0.9,
          "momentum_dampening": True,
          "allow_unstable_sketch_dampening": True}
    with pytest.warns(UserWarning, match="dampening"):
        sd, _ = _run(Config(sketch_decode="dense", **kw, **BASE),
                     n_rounds=3, lr=0.2)
    with pytest.warns(UserWarning, match="dampening"):
        ss, _ = _run(Config(sketch_decode="sharded", **kw, **BASE),
                     n_rounds=3, lr=0.2)
    np.testing.assert_allclose(_final_vec(ss), _final_vec(sd), atol=1e-6)


def test_dampening_lr_zero_round_decode_invariant():
    """Regression (review find): with error_type='none' the applied slice
    is lr-scaled, but the dampening mask must come from the UNSCALED
    selection support — at lr == 0 (a warmup round) the dense path still
    dampens momentum at the would-be update's support, so the sharded
    path must too, or the two decodes' momentum diverges from round 1."""
    import warnings

    kw = {**SKETCH, "error_type": "none", "virtual_momentum": 0.9,
          "momentum_dampening": True,
          "allow_unstable_sketch_dampening": True}
    finals, moms = [], []
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for dec in ("dense", "sharded"):
            cfg = Config(sketch_decode=dec, **kw, **BASE)
            ds, params, loss_fn = _setup(cfg.num_clients)
            sess = FederatedSession(cfg, params, loss_fn)
            sampler = FedSampler(ds, num_workers=8, local_batch_size=4,
                                 seed=1)
            for r, lr in enumerate((0.0, 0.2, 0.2)):  # warmup-style lr=0
                ids, batch = sampler.sample_round(r)
                sess.train_round(ids, batch, lr)
            finals.append(_final_vec(sess))
            moms.append(np.asarray(sess.state.momentum))
    np.testing.assert_allclose(moms[1], moms[0], atol=1e-6,
                               err_msg="momentum diverged at the lr=0 round")
    np.testing.assert_allclose(finals[1], finals[0], atol=1e-6)


def test_estimate_at_pallas_matches_gather_path():
    """The fused decode kernel is bit-equal to ``estimate_at`` under
    interpret mode, both hash families, including duplicate + clipped
    padding indices (the candidate-buffer contract)."""
    from commefficient_tpu.ops.pallas import estimate_at_pallas

    rng = np.random.default_rng(0)
    for hf in ("fmix32", "poly4"):
        spec = CountSketch(d=5000, c=1024, r=5, seed=3, hash_family=hf)
        table = sketch_vec(
            spec, jnp.asarray(rng.normal(size=5000).astype(np.float32))
        )
        idx = jnp.asarray(
            rng.choice(5000, size=700, replace=False).astype(np.int32)
        ).at[:5].set(0)  # duplicates, like gathered padding rows
        a = estimate_at(spec, table, idx)
        b = estimate_at_pallas(spec, table, idx)
        assert np.array_equal(np.asarray(a), np.asarray(b)), hf


def test_estimate_at_pallas_vmem_fallback():
    """A table beyond the VMEM guard silently falls back to the unfused
    gather path — backend='pallas' stays dialable at any scale."""
    from commefficient_tpu.ops.pallas import decode_kernels

    spec = CountSketch(d=200, c=64, r=3, seed=0)
    table = sketch_vec(spec, jnp.ones(200))
    idx = jnp.arange(50, dtype=jnp.int32)
    want = estimate_at(spec, table, idx)
    old = decode_kernels.VMEM_TABLE_BYTES
    try:
        decode_kernels.VMEM_TABLE_BYTES = 1  # force the fallback
        got = decode_kernels.estimate_at_pallas(spec, table, idx)
    finally:
        decode_kernels.VMEM_TABLE_BYTES = old
    assert np.array_equal(np.asarray(got), np.asarray(want))


def test_compact_nonzero_contract():
    v = jnp.zeros(20).at[jnp.asarray([3, 7, 15])].set(
        jnp.asarray([1.5, -2.0, 0.25])
    )
    idx, val = compact_nonzero(v, 5)
    assert idx.shape == val.shape == (5,)
    np.testing.assert_array_equal(np.asarray(idx), [3, 7, 15, 0, 0])
    np.testing.assert_array_equal(np.asarray(val), [1.5, -2.0, 0.25, 0, 0])
    # k greater than the vector length clamps the buffer
    idx, val = compact_nonzero(jnp.asarray([0.0, 2.0, 0.0]), 10)
    assert idx.shape == (3,) and float(val[0]) == 2.0
    # all-zero input: full padding, scatter-safe
    idx, val = compact_nonzero(jnp.zeros(8), 4)
    assert not np.any(np.asarray(val))
    # jit + reconstruction round-trip at exactly k nonzeros
    dense = jnp.zeros(64).at[jnp.arange(0, 64, 8)].set(1.0 + jnp.arange(8))
    idx, val = jax.jit(lambda v: compact_nonzero(v, 8))(dense)
    np.testing.assert_array_equal(
        np.asarray(jnp.zeros(64).at[idx].add(val)), np.asarray(dense)
    )
