"""StepProfiler window-edge tests (telemetry PR satellite).

Two previously-broken edges: start_step=0 traced compile+warmup, and a
checkpoint resume landing inside/past the window left the trace
permanently un-started (exact-equality start) or un-stopped. The
jax.profiler calls are monkeypatched — these tests pin WINDOW semantics,
not trace content."""

import pytest

from commefficient_tpu.utils.profiling import MIN_WARMUP_STEPS, StepProfiler


@pytest.fixture
def trace(monkeypatch):
    events = []
    monkeypatch.setattr("jax.profiler.start_trace",
                        lambda logdir: events.append("start"))
    monkeypatch.setattr("jax.profiler.stop_trace",
                        lambda: events.append("stop"))
    return events


def _drive(p, steps):
    windows = []
    for s in steps:
        before = p._active
        p.step(s)
        if p._active and not before:
            windows.append(["start", s])
        if before and not p._active:
            windows[-1].append(s)
    return windows


def test_start_step_zero_clamped_past_warmup(trace):
    """start_step=0 must NOT trace the compile/warmup rounds."""
    p = StepProfiler("dir", start_step=0, num_steps=2)
    windows = _drive(p, range(8))
    p.close()
    assert windows == [["start", MIN_WARMUP_STEPS, MIN_WARMUP_STEPS + 2]]
    assert trace == ["start", "stop"]


def test_resume_past_window_clamps_forward(trace):
    """Resume fast-forwarded PAST stop_at: the window must shift to
    post-resume steps (it used to never start — and a started trace never
    stopped — because start matched on exact equality)."""
    p = StepProfiler("dir", start_step=5, num_steps=3)  # window [5, 8)
    p.resume_at(20)
    windows = _drive(p, range(20, 30))
    p.close()
    start = 20 + MIN_WARMUP_STEPS
    assert windows == [["start", start, start + 3]]
    assert trace == ["start", "stop"]


def test_resume_inside_window_clamps_forward(trace):
    """Resume landing INSIDE the window: trace only post-resume steps."""
    p = StepProfiler("dir", start_step=5, num_steps=3)
    p.resume_at(6)
    windows = _drive(p, range(6, 16))
    p.close()
    assert windows == [["start", 6 + MIN_WARMUP_STEPS,
                        6 + MIN_WARMUP_STEPS + 3]]


def test_resume_before_window_keeps_configured_window(trace):
    """A resume well before the window must not move it."""
    p = StepProfiler("dir", start_step=10, num_steps=2)
    p.resume_at(3)
    windows = _drive(p, range(3, 16))
    p.close()
    assert windows == [["start", 10, 12]]


def test_entering_mid_window_without_resume_still_stops(trace):
    """Even if a caller forgets resume_at, a step sequence entering the
    window mid-way starts the trace and STOPS it at the window end (the old
    exact-equality start could leave a trace running forever)."""
    p = StepProfiler("dir", start_step=5, num_steps=3)
    windows = _drive(p, range(6, 12))
    p.close()
    assert windows == [["start", 6, 8]]
    assert trace == ["start", "stop"]


def test_close_stops_active_trace(trace):
    p = StepProfiler("dir", start_step=2, num_steps=10)
    p.step(2)
    assert trace == ["start"]
    p.close()
    assert trace == ["start", "stop"]
    p.close()  # idempotent
    assert trace == ["start", "stop"]


def test_inactive_without_logdir(trace):
    p = StepProfiler("", start_step=0, num_steps=5)
    for s in range(10):
        p.step(s)
    p.close()
    assert trace == []


def test_default_window_unchanged():
    """The production default (start 5) predates the clamp and must not
    move — only start_step below the warmup floor is clamped."""
    p = StepProfiler("dir")
    assert p.start == 5 and p.stop_at == 8
