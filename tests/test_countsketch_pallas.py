"""Pallas-backend equivalence tests for the CountSketch hot path.

The ``backend='pallas'`` kernels (ops/pallas/countsketch_kernels.py) must
produce the SAME tables/estimates as the banded-einsum reference path up to
fp32 summation-order rounding — on CPU they run under Pallas interpret mode,
so these tests pin the kernel math itself (hash generation, in-kernel signs,
fused overlap-add, the transposed estimate contraction, the median network)
without a TPU.

Also pinned here:
  * the 16-bit-limb Mersenne multiply (``_modmul31``/``_poly4_u32``) is
    bit-identical to the host uint64 evaluation — the arithmetic that lets
    poly4 run without uint64 (TPU kernels have none);
  * the Pallas path NEVER materializes a [d_eff] sign vector (the property
    that unlocks poly4 at GPT-2 scale, VERDICT r5 missing #2) — enforced
    by poisoning ``_row_signs`` and running the full path at D > 1M.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.ops.countsketch import (
    _MERSENNE_P,
    _modmul31,
    _poly4_eval,
    _poly4_u32,
    CountSketch,
    estimate_all,
    estimate_at,
    sketch_add_vec,
    sketch_vec,
    unsketch,
)
from commefficient_tpu.ops.pallas import median_rows_pallas

D, C, R = 10_000, 2_000, 5


def planted_vector(d, k, rng, heavy=100.0, noise=1.0):
    v = rng.normal(0, noise, size=d).astype(np.float32)
    idx = rng.choice(d, size=k, replace=False)
    v[idx] += heavy * rng.choice([-1.0, 1.0], size=k)
    return jnp.asarray(v), np.asarray(idx)


def assert_close(a, b, rtol=3e-6):
    """fp32 closeness scaled to the data (summation order differs between
    the backends, so exact equality is not the contract)."""
    a, b = np.asarray(a), np.asarray(b)
    scale = max(np.abs(a).max(), 1.0)
    np.testing.assert_allclose(a, b, rtol=0, atol=rtol * scale)


# -- the in-kernel Mersenne arithmetic --------------------------------------


def test_modmul31_bit_exact_vs_host_uint64():
    rng = np.random.default_rng(0)
    p = int(_MERSENNE_P)
    a = rng.integers(0, p, size=4096).astype(np.uint32)
    x = rng.integers(0, p, size=4096).astype(np.uint32)
    # edge operands: 0, 1, p-1 in both slots
    edges = np.array([0, 1, p - 1], np.uint32)
    a = np.concatenate([a, edges, np.full(3, p - 1, np.uint32)])
    x = np.concatenate([x, np.full(3, p - 1, np.uint32), edges])
    got = np.asarray(_modmul31(jnp.asarray(a), jnp.asarray(x)))
    want = ((a.astype(np.uint64) * x.astype(np.uint64)) % np.uint64(p)).astype(
        np.uint32
    )
    np.testing.assert_array_equal(got, want)


def test_poly4_u32_bit_exact_vs_host_uint64():
    rng = np.random.default_rng(1)
    coeffs = rng.integers(1, int(_MERSENNE_P), size=4).astype(np.uint64)
    x = rng.integers(0, int(_MERSENNE_P), size=8192).astype(np.uint64)
    want = _poly4_eval(x, coeffs)
    got = _poly4_u32(
        jnp.asarray(x.astype(np.uint32)), tuple(int(c) for c in coeffs)
    )
    np.testing.assert_array_equal(np.asarray(got).astype(np.uint64), want)


# -- backend equivalence across geometries and hash families ----------------

GEOMETRIES = [
    # (d, c, r, m): CV-like even geometry and a padded ODD d that exercises
    # every padding seam (scramble block, per-row riffle padding, chunk tail)
    (D, C, R, None),
    (20_011, 4_000, 3, 512),
]


@pytest.mark.parametrize("family", ["fmix32", "poly4"])
@pytest.mark.parametrize("d,c,r,m", GEOMETRIES)
def test_sketch_and_estimate_match_einsum(family, d, c, r, m):
    spec_e = CountSketch(d=d, c=c, r=r, m=m, seed=7, hash_family=family)
    spec_p = spec_e._replace(backend="pallas")
    rng = np.random.default_rng(2)
    v, _ = planted_vector(d, 20, rng)
    te = sketch_vec(spec_e, v)
    tp = sketch_vec(spec_p, v)
    assert te.shape == tp.shape == spec_e.table_shape
    assert_close(te, tp)
    # estimate: run each backend on ITS OWN table (the round-trip each
    # backend actually performs) and on the shared einsum table (isolates
    # the estimate kernel)
    assert_close(estimate_all(spec_e, te), estimate_all(spec_p, tp))
    assert_close(estimate_all(spec_e, te), estimate_all(spec_p, te))


@pytest.mark.parametrize("family", [
    # fmix32 roundtrip rides the slow tier (r20 budget): the family's
    # pallas==einsum equivalence stays tier-1 via the estimate-match
    # parametrizations below; poly4 (the default) keeps the roundtrip.
    pytest.param("fmix32", marks=pytest.mark.slow),
    "poly4",
])
def test_add_linearity_and_unsketch_roundtrip(family):
    spec_e = CountSketch(d=D, c=C, r=R, seed=7, hash_family=family)
    spec_p = spec_e._replace(backend="pallas")
    rng = np.random.default_rng(3)
    v, hh = planted_vector(D, 10, rng)
    w = jnp.asarray(rng.normal(size=D).astype(np.float32))
    # sketch_add_vec through the pallas dispatch == einsum accumulate
    t0 = sketch_vec(spec_p, w)
    assert_close(sketch_add_vec(spec_p, t0, v), sketch_vec(spec_e, w + v))
    # linearity holds WITHIN the pallas backend (aggregation contract)
    assert_close(
        sketch_vec(spec_p, v + w), sketch_vec(spec_p, v) + sketch_vec(spec_p, w)
    )
    # unsketch recovers the same planted heavy hitters through either backend
    rec_e = np.asarray(unsketch(spec_e, sketch_vec(spec_e, v), k=10))
    rec_p = np.asarray(unsketch(spec_p, sketch_vec(spec_p, v), k=10))
    assert set(np.nonzero(rec_p)[0]) == set(np.nonzero(rec_e)[0])
    assert set(hh.tolist()) <= set(np.nonzero(rec_p)[0].tolist())
    assert_close(rec_e, rec_p, rtol=1e-5)


def test_num_blocks_estimation_is_backend_agnostic():
    # num_blocks > 1 takes the exact gather path regardless of backend —
    # same VALUES as the matmul path (bit-equal on CPU between backends,
    # since neither backend's kernels run)
    spec_e = CountSketch(d=D, c=C, r=3, num_blocks=4, seed=7)
    spec_p = spec_e._replace(backend="pallas")
    rng = np.random.default_rng(4)
    v, _ = planted_vector(D, 10, rng)
    table = sketch_vec(spec_e, v)
    np.testing.assert_array_equal(
        np.asarray(estimate_all(spec_e, table)),
        np.asarray(estimate_all(spec_p, table)),
    )


def test_unknown_backend_fails_loudly():
    spec = CountSketch(d=D, c=C, r=3, seed=7, backend="cuda")
    v = jnp.zeros(D, jnp.float32)
    with pytest.raises(ValueError, match="backend"):
        sketch_vec(spec, v)
    with pytest.raises(ValueError, match="backend"):
        estimate_all(spec, jnp.zeros(spec.table_shape, jnp.float32))


# -- the median kernel ------------------------------------------------------


@pytest.mark.parametrize("r", [1, 2, 3, 4, 5, 7])
def test_median_rows_pallas_matches_jnp_median(r):
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(r, 3001)).astype(np.float32))
    got = np.asarray(median_rows_pallas(x))
    np.testing.assert_array_equal(got, np.median(np.asarray(x), axis=0))


# -- poly4 at production scale (the capability the kernels unlock) ----------


def test_poly4_at_gpt2_scale_without_sign_materialization(monkeypatch):
    """VERDICT r5 missing #2 / acceptance: poly4 usable at D >= 1M through
    the Pallas path, with NO [d_eff] sign vector ever materialized. The
    einsum path's host sign table is the exact thing poisoning _row_signs
    forbids — the kernels must never touch it."""
    d = 1_200_003  # odd: exercises every padding seam at scale
    spec_e = CountSketch(d=d, c=d // 25, r=3, seed=11, hash_family="poly4")
    spec_p = spec_e._replace(backend="pallas")
    rng = np.random.default_rng(6)
    v, hh = planted_vector(d, 16, rng)
    te = sketch_vec(spec_e, v)  # einsum reference table (signs via host)

    def _poisoned(self, row):
        raise AssertionError(
            "pallas backend materialized the [d_eff] sign vector"
        )

    monkeypatch.setattr(CountSketch, "_row_signs", _poisoned)
    tp = sketch_vec(spec_p, v)
    assert_close(te, tp)
    est_p = estimate_all(spec_p, tp)
    # verify the estimate kernel against the independent exact gather path
    # on the planted coordinates plus a random probe set
    probe = np.concatenate([hh, rng.choice(d, size=256, replace=False)])
    probe = jnp.asarray(np.unique(probe).astype(np.uint32))
    ref = estimate_at(spec_e._replace(backend="einsum"), tp, probe)
    assert_close(np.asarray(est_p)[np.asarray(probe)], ref, rtol=1e-5)
    # the planted heavy hitters survive the full pallas round-trip (top-64
    # margin: at d/c=25 with r=3, median-of-3 collision phantoms can edge
    # individual coordinates in a strict top-16 — recovery, not ranking,
    # is the property under test)
    rec = np.asarray(est_p)
    order = np.argsort(-np.abs(rec))[:64]
    assert set(hh.tolist()) <= set(order.tolist())
