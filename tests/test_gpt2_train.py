"""gpt2_train workload tests (BASELINE config #4, tiny-config CPU e2e)."""

import json
import os

import numpy as np
import pytest


def test_gpt2_train_e2e_uncompressed(tmp_path):
    from commefficient_tpu.train import gpt2_train

    val = gpt2_train.main(
        [],
        model="gpt2_tiny",
        num_epochs=1,
        num_clients=4,
        num_workers=2,
        num_devices=2,
        local_batch_size=2,
        max_seq_len=64,
        num_candidates=2,
        mode="uncompressed",
        checkpoint_dir=str(tmp_path / "ck"),
    )
    assert np.isfinite(val["nll"]) and val["ppl"] > 0
    assert 0.0 <= val["mc_accuracy"] <= 1.0
    # save_pretrained wrote an HF-style checkpoint
    assert (tmp_path / "ck" / "config.json").exists()
    assert (tmp_path / "ck" / "flax_model.msgpack").exists()
    cfg = json.loads((tmp_path / "ck" / "config.json").read_text())
    assert cfg["vocab_size"] == 512 + 5  # base vocab + special tokens


def test_gpt2_train_e2e_sketch_trains(tmp_path):
    """Sketch mode on the GPT-2 twin-loss path: loss decreases over epochs."""
    from commefficient_tpu.train import gpt2_train
    from commefficient_tpu.utils.logging import TableLogger

    rows = []

    class Capture(TableLogger):
        def append(self, row):
            rows.append(row)
            super().append(row)

    from commefficient_tpu.data import load_fed_personachat
    from commefficient_tpu.data.sampler import FedSampler
    from commefficient_tpu.parallel import FederatedSession, mask_gpt2
    from commefficient_tpu.utils.config import Config

    cfg = Config(
        model="gpt2_tiny", dataset_name="personachat", mode="sketch",
        error_type="virtual", virtual_momentum=0.9, k=400, num_rows=3,
        num_cols=20_000, num_epochs=3, num_clients=4, num_workers=2,
        num_devices=2, local_batch_size=2, max_seq_len=64, weight_decay=0.0,
        lr_scale=0.05, pivot_epoch=1,
    )
    train, test, real, hf, gcfg, model, params, loss_fn = (
        gpt2_train.build_model_and_data(cfg)
    )
    session = FederatedSession(cfg, params, loss_fn, mask_batch=mask_gpt2)
    sampler = FedSampler(train, num_workers=2, local_batch_size=2, seed=1)
    gpt2_train.train_loop(cfg, session, sampler, test, table=Capture())
    assert len(rows) == 3
    # epoch 2 runs at peak lr (pivot_epoch=1); epoch 3's lr decays to ~0, so
    # compare while the schedule is active
    assert rows[1]["train_loss"] < rows[0]["train_loss"]
    assert np.isfinite(rows[-1]["val_ppl"])


# ~14 s standalone (gpt2_tiny, 1 epoch, 2 depths): pins the SECOND
# workload entry's pipeline wiring through the shared runner; the full
# bit-exactness contract holds deeper coverage in tests/test_pipeline.py
@pytest.mark.slow  # r20 tier budget: the depth-0 twin here is the only
# unique surface (gpt2 entry x pipeline flag plumbing); the contract
# itself stays tier-1 in test_pipeline's TinyMLP runner pins
def test_gpt2_train_pipelined_depth2_matches_depth0(tmp_path):
    """gpt2_train.train_loop at --pipeline_depth 2 == depth 0 bitwise
    (final params), through the shared runner's engine wiring."""
    from commefficient_tpu.train import gpt2_train
    from commefficient_tpu.data.sampler import FedSampler
    from commefficient_tpu.parallel import FederatedSession, mask_gpt2
    from commefficient_tpu.utils.config import Config

    def run(depth):
        cfg = Config(
            model="gpt2_tiny", dataset_name="personachat",
            mode="true_topk", error_type="virtual", virtual_momentum=0.9,
            k=400, topk_method="threshold", num_epochs=1, num_clients=4,
            num_workers=2, num_devices=2, local_batch_size=2,
            max_seq_len=64, weight_decay=0.0, lr_scale=0.05,
            pivot_epoch=1, pipeline_depth=depth,
        )
        train, test, _real, _hf, _gcfg, _model, params, loss_fn = (
            gpt2_train.build_model_and_data(cfg)
        )
        session = FederatedSession(cfg, params, loss_fn,
                                   mask_batch=mask_gpt2)
        sampler = FedSampler(train, num_workers=2, local_batch_size=2,
                             seed=1)
        session.maybe_attach_data(train, sampler)
        gpt2_train.train_loop(cfg, session, sampler, test)
        return np.asarray(session.state.params_vec)

    np.testing.assert_array_equal(run(0), run(2))


def test_ppl_token_weighted_under_ragged_batches():
    """nll must be identical whether the val set is evaluated in one exact
    batch or in batches whose final one is ragged/padded — true only under
    token weighting (VERDICT r2 item 6: row-weighted per-batch means bias
    ppl when the tail batch is padded and rows carry unequal token counts)."""
    import dataclasses

    from commefficient_tpu.train import gpt2_train
    from commefficient_tpu.parallel import FederatedSession, mask_gpt2
    from commefficient_tpu.utils.config import Config

    cfg = Config(
        model="gpt2_tiny", dataset_name="personachat", mode="uncompressed",
        num_epochs=1, num_clients=4, num_workers=2, num_devices=2,
        local_batch_size=2, max_seq_len=64, num_candidates=2,
    )
    train, test, real, hf, gcfg, model, params, loss_fn = (
        gpt2_train.build_model_and_data(cfg)
    )
    n = len(next(iter(test.data.values())))
    # make per-row token counts strongly unequal (the synthetic stand-in's
    # rows are near-uniform, which would hide row-weighting bias): keep only
    # the last few label tokens in half the rows
    from commefficient_tpu.models.losses import IGNORE_INDEX

    lab = np.array(test.data["lm_labels"])
    lab[: n // 2, :, : lab.shape[-1] - 6] = IGNORE_INDEX
    test.data["lm_labels"] = lab
    # a batch size that does NOT divide the set => ragged padded tail
    bs = 4
    while n % bs == 0:
        bs += 1
    session = FederatedSession(cfg, params, loss_fn, mask_batch=mask_gpt2)
    ragged = gpt2_train.evaluate_ppl(session, test, bs)
    exact = gpt2_train.evaluate_ppl(session, test, n)
    assert ragged["nll"] == pytest.approx(exact["nll"], rel=1e-5)

    # Aggregation semantics pinned with a stub (at random init every token's
    # nll is ~log V, so a real model can't expose row-weighting bias): two
    # batches with unequal token counts — token weighting must yield the
    # exact totals, and differ from the row-weighted mean.
    import jax.numpy as jnp

    fake = [
        {"lm_loss": jnp.float32(1.0), "lm_loss_sum": jnp.float32(100.0),
         "token_count": jnp.float32(100.0), "loss_sum": jnp.float32(4.0)},
        {"lm_loss": jnp.float32(2.0), "lm_loss_sum": jnp.float32(20.0),
         "token_count": jnp.float32(10.0), "loss_sum": jnp.float32(2.0)},
    ]
    calls = iter(fake)
    session.eval_fn = lambda pv, b: next(calls)
    batches = [
        {"input_ids": np.zeros((4, 1)), "_valid": np.float32(4)},
        {"input_ids": np.zeros((4, 1)), "_valid": np.float32(2)},
    ]
    out = session.evaluate(batches)
    assert out["lm_loss_sum"] == pytest.approx(120.0)
    assert out["token_count"] == pytest.approx(110.0)
    token_weighted = out["lm_loss_sum"] / out["token_count"]
    row_weighted = out["lm_loss"]  # (1.0*4 + 2.0*2) / 6
    assert token_weighted == pytest.approx(120.0 / 110.0)
    assert row_weighted == pytest.approx(8.0 / 6.0)
    assert abs(token_weighted - row_weighted) > 0.1


def test_hf_gpt2_weight_mapping_roundtrip(tmp_path):
    """A torch GPT-2 state dict written to disk maps into our tree: mapped
    leaves match, and the special-token embedding rows keep fresh init."""
    torch = pytest.importorskip("torch")
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.models import GPT2Config, GPT2DoubleHeads
    from commefficient_tpu.models.hf_gpt2 import load_hf_gpt2_params

    gcfg = GPT2Config(vocab_size=101, n_positions=32, n_embd=16, n_layer=2, n_head=2)
    hf_vocab = 96  # ours = hf + 5 specials
    g = torch.Generator().manual_seed(0)
    sd = {
        "transformer.wte.weight": torch.randn(hf_vocab, 16, generator=g),
        "transformer.wpe.weight": torch.randn(32, 16, generator=g),
        "transformer.ln_f.weight": torch.randn(16, generator=g),
        "transformer.ln_f.bias": torch.randn(16, generator=g),
    }
    for i in range(2):
        p = f"transformer.h.{i}."
        sd[p + "ln_1.weight"] = torch.randn(16, generator=g)
        sd[p + "ln_1.bias"] = torch.randn(16, generator=g)
        sd[p + "ln_2.weight"] = torch.randn(16, generator=g)
        sd[p + "ln_2.bias"] = torch.randn(16, generator=g)
        sd[p + "attn.c_attn.weight"] = torch.randn(16, 48, generator=g)
        sd[p + "attn.c_attn.bias"] = torch.randn(48, generator=g)
        sd[p + "attn.c_proj.weight"] = torch.randn(16, 16, generator=g)
        sd[p + "attn.c_proj.bias"] = torch.randn(16, generator=g)
        sd[p + "mlp.c_fc.weight"] = torch.randn(16, 64, generator=g)
        sd[p + "mlp.c_fc.bias"] = torch.randn(64, generator=g)
        sd[p + "mlp.c_proj.weight"] = torch.randn(64, 16, generator=g)
        sd[p + "mlp.c_proj.bias"] = torch.randn(16, generator=g)
    ckdir = tmp_path / "gpt2-local"
    os.makedirs(ckdir)
    torch.save(sd, ckdir / "pytorch_model.bin")

    model = GPT2DoubleHeads(gcfg)
    ids = jnp.zeros((1, 2, 8), jnp.int32)
    params = model.init(jax.random.key(0), ids, token_type_ids=ids,
                        mc_token_ids=jnp.zeros((1, 2), jnp.int32))
    fresh_wte = np.asarray(params["params"]["transformer"]["wte"]).copy()
    mapped, loaded = load_hf_gpt2_params(str(ckdir), gcfg, params, seed=0)
    assert loaded
    wte = np.asarray(mapped["params"]["transformer"]["wte"])
    np.testing.assert_allclose(wte[:hf_vocab], sd["transformer.wte.weight"].numpy(),
                               rtol=1e-6)
    np.testing.assert_allclose(wte[hf_vocab:], fresh_wte[hf_vocab:], rtol=1e-6)
    k = np.asarray(
        mapped["params"]["transformer"]["h_1"]["attn"]["c_attn"]["kernel"]
    )
    np.testing.assert_allclose(
        k, sd["transformer.h.1.attn.c_attn.weight"].numpy(), rtol=1e-6
    )
    # the mapped model still runs
    lm, mc = model.apply(mapped, ids, token_type_ids=ids,
                         mc_token_ids=jnp.zeros((1, 2), jnp.int32))
    assert np.isfinite(np.asarray(lm)).all()

    # missing checkpoint -> graceful no-op
    _, loaded2 = load_hf_gpt2_params(str(tmp_path / "nope"), gcfg, params)
    assert not loaded2
