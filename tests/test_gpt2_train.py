"""gpt2_train workload tests (BASELINE config #4, tiny-config CPU e2e)."""

import json
import os

import numpy as np
import pytest


def test_gpt2_train_e2e_uncompressed(tmp_path):
    from commefficient_tpu.train import gpt2_train

    val = gpt2_train.main(
        [],
        model="gpt2_tiny",
        num_epochs=1,
        num_clients=4,
        num_workers=2,
        num_devices=2,
        local_batch_size=2,
        max_seq_len=64,
        num_candidates=2,
        mode="uncompressed",
        checkpoint_dir=str(tmp_path / "ck"),
    )
    assert np.isfinite(val["nll"]) and val["ppl"] > 0
    assert 0.0 <= val["mc_accuracy"] <= 1.0
    # save_pretrained wrote an HF-style checkpoint
    assert (tmp_path / "ck" / "config.json").exists()
    assert (tmp_path / "ck" / "flax_model.msgpack").exists()
    cfg = json.loads((tmp_path / "ck" / "config.json").read_text())
    assert cfg["vocab_size"] == 512 + 5  # base vocab + special tokens


def test_gpt2_train_e2e_sketch_trains(tmp_path):
    """Sketch mode on the GPT-2 twin-loss path: loss decreases over epochs."""
    from commefficient_tpu.train import gpt2_train
    from commefficient_tpu.utils.logging import TableLogger

    rows = []

    class Capture(TableLogger):
        def append(self, row):
            rows.append(row)
            super().append(row)

    from commefficient_tpu.data import load_fed_personachat
    from commefficient_tpu.data.sampler import FedSampler
    from commefficient_tpu.parallel import FederatedSession, mask_gpt2
    from commefficient_tpu.utils.config import Config

    cfg = Config(
        model="gpt2_tiny", dataset_name="personachat", mode="sketch",
        error_type="virtual", virtual_momentum=0.9, k=400, num_rows=3,
        num_cols=20_000, num_epochs=3, num_clients=4, num_workers=2,
        num_devices=2, local_batch_size=2, max_seq_len=64, weight_decay=0.0,
        lr_scale=0.05, pivot_epoch=1,
    )
    train, test, real, hf, gcfg, model, params, loss_fn = (
        gpt2_train.build_model_and_data(cfg)
    )
    session = FederatedSession(cfg, params, loss_fn, mask_batch=mask_gpt2)
    sampler = FedSampler(train, num_workers=2, local_batch_size=2, seed=1)
    gpt2_train.train_loop(cfg, session, sampler, test, table=Capture())
    assert len(rows) == 3
    # epoch 2 runs at peak lr (pivot_epoch=1); epoch 3's lr decays to ~0, so
    # compare while the schedule is active
    assert rows[1]["train_loss"] < rows[0]["train_loss"]
    assert np.isfinite(rows[-1]["val_ppl"])


def test_hf_gpt2_weight_mapping_roundtrip(tmp_path):
    """A torch GPT-2 state dict written to disk maps into our tree: mapped
    leaves match, and the special-token embedding rows keep fresh init."""
    torch = pytest.importorskip("torch")
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.models import GPT2Config, GPT2DoubleHeads
    from commefficient_tpu.models.hf_gpt2 import load_hf_gpt2_params

    gcfg = GPT2Config(vocab_size=101, n_positions=32, n_embd=16, n_layer=2, n_head=2)
    hf_vocab = 96  # ours = hf + 5 specials
    g = torch.Generator().manual_seed(0)
    sd = {
        "transformer.wte.weight": torch.randn(hf_vocab, 16, generator=g),
        "transformer.wpe.weight": torch.randn(32, 16, generator=g),
        "transformer.ln_f.weight": torch.randn(16, generator=g),
        "transformer.ln_f.bias": torch.randn(16, generator=g),
    }
    for i in range(2):
        p = f"transformer.h.{i}."
        sd[p + "ln_1.weight"] = torch.randn(16, generator=g)
        sd[p + "ln_1.bias"] = torch.randn(16, generator=g)
        sd[p + "ln_2.weight"] = torch.randn(16, generator=g)
        sd[p + "ln_2.bias"] = torch.randn(16, generator=g)
        sd[p + "attn.c_attn.weight"] = torch.randn(16, 48, generator=g)
        sd[p + "attn.c_attn.bias"] = torch.randn(48, generator=g)
        sd[p + "attn.c_proj.weight"] = torch.randn(16, 16, generator=g)
        sd[p + "attn.c_proj.bias"] = torch.randn(16, generator=g)
        sd[p + "mlp.c_fc.weight"] = torch.randn(16, 64, generator=g)
        sd[p + "mlp.c_fc.bias"] = torch.randn(64, generator=g)
        sd[p + "mlp.c_proj.weight"] = torch.randn(64, 16, generator=g)
        sd[p + "mlp.c_proj.bias"] = torch.randn(16, generator=g)
    ckdir = tmp_path / "gpt2-local"
    os.makedirs(ckdir)
    torch.save(sd, ckdir / "pytorch_model.bin")

    model = GPT2DoubleHeads(gcfg)
    ids = jnp.zeros((1, 2, 8), jnp.int32)
    params = model.init(jax.random.key(0), ids, token_type_ids=ids,
                        mc_token_ids=jnp.zeros((1, 2), jnp.int32))
    fresh_wte = np.asarray(params["params"]["transformer"]["wte"]).copy()
    mapped, loaded = load_hf_gpt2_params(str(ckdir), gcfg, params, seed=0)
    assert loaded
    wte = np.asarray(mapped["params"]["transformer"]["wte"])
    np.testing.assert_allclose(wte[:hf_vocab], sd["transformer.wte.weight"].numpy(),
                               rtol=1e-6)
    np.testing.assert_allclose(wte[hf_vocab:], fresh_wte[hf_vocab:], rtol=1e-6)
    k = np.asarray(
        mapped["params"]["transformer"]["h_1"]["attn"]["c_attn"]["kernel"]
    )
    np.testing.assert_allclose(
        k, sd["transformer.h.1.attn.c_attn.weight"].numpy(), rtol=1e-6
    )
    # the mapped model still runs
    lm, mc = model.apply(mapped, ids, token_type_ids=ids,
                         mc_token_ids=jnp.zeros((1, 2), jnp.int32))
    assert np.isfinite(np.asarray(lm)).all()

    # missing checkpoint -> graceful no-op
    _, loaded2 = load_hf_gpt2_params(str(tmp_path / "nope"), gcfg, params)
    assert not loaded2
