"""Bench regression gate (scripts/check_bench_regression.py) self-tests:
a within-tolerance trajectory passes, a real regression is DETECTED (the
vacuous-pass guard, same pattern as scripts/check_mode_dispatch.py), and
the provenance/direction rules hold — all on synthetic BENCH pairs."""

import importlib.util
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _gate():
    spec = importlib.util.spec_from_file_location(
        "check_bench_regression",
        os.path.join(REPO, "scripts", "check_bench_regression.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write(tmp_path, name, parsed):
    # the driver's wrapper format ({"parsed": {...}} around the bench line)
    (tmp_path / name).write_text(json.dumps({"parsed": parsed}))


BASELINE = {
    "metric": "fed_resnet9_sketch_train_samples_per_sec_per_chip",
    "value": 32000.0, "unit": "samples/s", "vs_baseline": 1.6,
    "mfu": 0.375, "chip": "TPU v5 lite",
    "gpt2_sketch_tokens_per_sec": 32000.0,
    "gpt2_sketch_sec_per_round": 0.50,
    "gpt2_sketch_vs_uncompressed": 0.29,
}


def test_within_tolerance_passes(tmp_path):
    mod = _gate()
    _write(tmp_path, "BENCH_r01.json", BASELINE)
    _write(tmp_path, "BENCH_r02.json",
           {**BASELINE, "value": 31000.0, "mfu": 0.36,
            "gpt2_sketch_sec_per_round": 0.52})
    assert mod.main(["--dir", str(tmp_path)]) == 0


def test_detects_throughput_regression(tmp_path):
    """The detects-regression self-test: a 40% headline drop must exit
    nonzero and name the metric."""
    mod = _gate()
    _write(tmp_path, "BENCH_r01.json", BASELINE)
    _write(tmp_path, "BENCH_r02.json", {**BASELINE, "value": 19000.0})
    assert mod.main(["--dir", str(tmp_path)]) == 1
    regs, _, _ = mod.check_regression([BASELINE],
                                      {**BASELINE, "value": 19000.0})
    assert [r["metric"] for r in regs] == ["value"]
    assert regs[0]["direction"] == "up"


def test_detects_latency_regression(tmp_path):
    """*_sec_per_round is lower-is-better: a rise past tolerance gates."""
    mod = _gate()
    _write(tmp_path, "BENCH_r01.json", BASELINE)
    _write(tmp_path, "BENCH_r02.json",
           {**BASELINE, "gpt2_sketch_sec_per_round": 0.80})
    assert mod.main(["--dir", str(tmp_path)]) == 1


def test_median_baseline_is_outlier_robust(tmp_path):
    """One freak-fast prior round must not turn a normal round into a
    'regression' — the baseline is the MEDIAN of the trajectory."""
    mod = _gate()
    hist = [BASELINE, {**BASELINE, "value": 64000.0}, BASELINE]
    regs, _, _ = mod.check_regression(hist, dict(BASELINE))
    assert regs == []


def test_cross_chip_records_are_excluded(tmp_path):
    """Provenance satellite: a prior record from different hardware is not
    a baseline (apples-to-apples across hosts)."""
    mod = _gate()
    _write(tmp_path, "BENCH_r01.json",
           {**BASELINE, "chip": "TPU v4", "value": 90000.0})
    _write(tmp_path, "BENCH_r02.json", BASELINE)
    # the v4 90k number would gate the v5e 32k run without the exclusion
    assert mod.main(["--dir", str(tmp_path)]) == 0
    regs, new, notes = mod.check_regression(
        [{**BASELINE, "chip": "TPU v4", "value": 90000.0}], dict(BASELINE)
    )
    assert regs == []
    assert any("TPU v4" in n for n in notes)
    # every gated metric lost its history to the chip exclusion — they are
    # all reported as new/no-history, not silently passed
    assert "value" in new


def test_single_record_and_informational_keys_pass(tmp_path):
    mod = _gate()
    _write(tmp_path, "BENCH_r01.json", BASELINE)
    assert mod.main(["--dir", str(tmp_path)]) == 0  # nothing to compare
    # error/skip markers and audited byte counts never gate
    for key in ("gpt2_sketch_error", "gpt2_skipped",
                "audited_collective_bytes", "audited_peak_hbm_bytes",
                "chip", "jax"):
        assert mod.metric_direction(key) is None
    assert mod.metric_direction("gpt2_sketch_pallas_tokens_per_sec") == "up"
    assert mod.metric_direction("audited_mfu") == "up"
    # the tighter MFU band covers the whole family, not just the bare key
    for name in ("mfu", "gpt2_sketch_mfu", "gpt2_sketch_audited_mfu"):
        assert mod.tolerance_for(name, mod.DEFAULT_TOLERANCE) == 0.10


def test_raw_bench_line_format_accepted(tmp_path):
    """Files holding the bare bench.py JSON line (no driver wrapper) are
    accepted too."""
    mod = _gate()
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(BASELINE))
    _write(tmp_path, "BENCH_r02.json", BASELINE)
    assert mod.main(["--dir", str(tmp_path)]) == 0


def test_new_metrics_counted_and_guarded(tmp_path, capsys):
    """Pipeline-PR satellite: 'no comparable history' is no longer a
    silent pass — new metrics are counted in the summary/JSON output, and
    --max_new_metrics turns a rename (perpetually 'new', never compared)
    into a gate failure."""
    mod = _gate()
    _write(tmp_path, "BENCH_r01.json", BASELINE)
    # a rename: the old key vanishes, a 'new' one appears with no history
    renamed = {k: v for k, v in BASELINE.items()
               if k != "gpt2_sketch_tokens_per_sec"}
    renamed["gpt2_sketch_v2_tokens_per_sec"] = 32000.0
    assert mod.main(["--dir", str(tmp_path)]) == 0
    assert mod.main(["--dir", str(tmp_path), "--max_new_metrics", "0"]) == 0
    _write(tmp_path, "BENCH_r02.json", renamed)
    capsys.readouterr()
    # unguarded: still a pass, but the JSON summary names the new metric
    assert mod.main(["--dir", str(tmp_path)]) == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["kind"] == "bench_regression"
    assert summary["new_metrics"] == ["gpt2_sketch_v2_tokens_per_sec"]
    assert summary["regressions"] == []
    # guarded: the rename can no longer dodge the gate
    assert mod.main(["--dir", str(tmp_path), "--max_new_metrics", "0"]) == 1
    assert mod.main(["--dir", str(tmp_path), "--max_new_metrics", "1"]) == 0
    # check_regression surfaces the list directly too
    _, new, _ = mod.check_regression([BASELINE], renamed)
    assert new == ["gpt2_sketch_v2_tokens_per_sec"]


def test_retraces_gauge_gated_exact_zero(tmp_path):
    """Resilience PR: every *_retraces leg gauge is a hard invariant —
    ANY non-zero value fails, with or without history (a relative band
    on an all-zero trajectory would never fire)."""
    mod = _gate()
    assert mod.metric_direction("sketch_resilience_retraces") is None
    # zero passes, even as the metric's first appearance
    _write(tmp_path, "BENCH_r01.json", BASELINE)
    _write(tmp_path, "BENCH_r02.json",
           {**BASELINE, "sketch_resilience_retraces": 0,
            "sketch_ladder_retraces": 0})
    assert mod.main(["--dir", str(tmp_path)]) == 0
    # a retrace fails outright and names the gauge
    regs, _, _ = mod.check_regression(
        [BASELINE], {**BASELINE, "sketch_resilience_retraces": 1})
    assert [r["metric"] for r in regs] == ["sketch_resilience_retraces"]
    assert regs[0]["direction"] == "exact_zero"
    _write(tmp_path, "BENCH_r03.json",
           {**BASELINE, "sketch_ladder_retraces": 2})
    assert mod.main(["--dir", str(tmp_path)]) == 1


def test_pipeline_leg_metrics_registered():
    """The sketch_pipelined bench leg's gate-worthy keys have directions
    (throughput + occupancy gate; the near-zero stall stays
    informational — relative tolerance on ~0 ms is noise)."""
    mod = _gate()
    assert mod.metric_direction("sketch_pipelined_samples_per_sec") == "up"
    assert mod.metric_direction("sketch_pipeline_sync_samples_per_sec") \
        == "up"
    assert mod.metric_direction("sketch_pipelined_occupancy") == "up"
    assert mod.metric_direction("sketch_pipelined_host_stall_ms") is None


def test_gpt2_sketch_gap_metrics_registered_and_gated(tmp_path):
    """Sketch-gap PR: the new gpt2_sketch_* legs gate UP (tokens/s,
    _vs_uncompressed — the 0.6x target is trajectory-enforced once an
    optimized record lands), the headline ratios carry the tight 10%
    band (two measurements of one run — load cancels), and the scan
    leg's rounds_per_dispatch stays informational (configuration, not
    measurement)."""
    mod = _gate()
    assert mod.metric_direction("gpt2_sketch_vs_uncompressed") == "up"
    assert mod.metric_direction("gpt2_sketch_scan_vs_uncompressed") == "up"
    assert mod.metric_direction("gpt2_sketch_scan_tokens_per_sec") == "up"
    assert mod.metric_direction("gpt2_sketch_scan_mfu") == "up"
    assert mod.metric_direction("gpt2_sketch_scan_rounds_per_dispatch") \
        is None
    assert mod.tolerance_for("gpt2_sketch_vs_uncompressed", 0.15) == 0.10
    assert mod.tolerance_for("gpt2_sketch_scan_vs_uncompressed",
                             0.15) == 0.10
    # trajectory enforcement self-test: an optimized record (0.62) in the
    # history, then a drop back toward the pre-PR ratio (0.29) must gate
    good = {**BASELINE, "gpt2_sketch_vs_uncompressed": 0.62,
            "gpt2_sketch_scan_tokens_per_sec": 90_000.0}
    bad = {**BASELINE, "gpt2_sketch_vs_uncompressed": 0.29,
           "gpt2_sketch_scan_tokens_per_sec": 60_000.0}
    _write(tmp_path, "BENCH_r01.json", good)
    _write(tmp_path, "BENCH_r02.json", bad)
    assert mod.main(["--dir", str(tmp_path)]) == 1
    regs, _, _ = mod.check_regression([good], bad)
    names = {r["metric"] for r in regs}
    assert "gpt2_sketch_vs_uncompressed" in names
    assert "gpt2_sketch_scan_tokens_per_sec" in names


def test_sparse_agg_metrics_registered_and_gated(tmp_path):
    """ISSUE 14 satellite: the sparse-aggregation bench legs gate on
    their _vs_dense ratio (higher is better, tight 10% band — twin runs
    of one geometry, load cancels); the bare samples/s rows stay
    informational, and error/skip markers never gate."""
    mod = _gate()
    for name in ("local_topk_sparse_agg_vs_dense",
                 "true_topk_sparse_agg_vs_dense"):
        assert mod.metric_direction(name) == "up"
        assert mod.tolerance_for(name, 0.15) == 0.10
    assert mod.metric_direction("local_topk_sparse_agg") is None
    assert mod.metric_direction("true_topk_sparse_agg") is None
    assert mod.metric_direction("local_topk_sparse_agg_error") is None
    assert mod.metric_direction("sparse_agg_skipped") is None
    # detects-regression self-test: sparse advantage collapsing (1.4x ->
    # 0.9x) past the band must gate and name the ratio
    good = {**BASELINE, "local_topk_sparse_agg_vs_dense": 1.4}
    bad = {**BASELINE, "local_topk_sparse_agg_vs_dense": 0.9}
    _write(tmp_path, "BENCH_r01.json", good)
    _write(tmp_path, "BENCH_r02.json", bad)
    assert mod.main(["--dir", str(tmp_path)]) == 1
    regs, _, _ = mod.check_regression([good], bad)
    assert [r["metric"] for r in regs] == ["local_topk_sparse_agg_vs_dense"]
    assert regs[0]["direction"] == "up"
    # within the band passes
    regs, _, _ = mod.check_regression(
        [good], {**BASELINE, "local_topk_sparse_agg_vs_dense": 1.33})
    assert regs == []


def test_json_summary_always_last_line(tmp_path, capsys):
    """The machine-readable summary is the last stdout line in every exit
    path (nothing-to-compare included)."""
    mod = _gate()
    assert mod.main(["--dir", str(tmp_path)]) == 0  # no records at all
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary == {"kind": "bench_regression", "compared": False,
                       "gated": 0, "regressions": [], "new_metrics": [],
                       "skipped_chip_records": 0}
    _write(tmp_path, "BENCH_r01.json", BASELINE)
    _write(tmp_path, "BENCH_r02.json", {**BASELINE, "value": 19000.0})
    assert mod.main(["--dir", str(tmp_path)]) == 1
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert [r["metric"] for r in summary["regressions"]] == ["value"]
    # error exits too: an unreadable record and a usage error both still
    # end with a parseable summary carrying the error text
    (tmp_path / "BENCH_r03.json").write_text("{truncated")
    assert mod.main(["--dir", str(tmp_path)]) == 2
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "unreadable" in summary["error"]
    assert mod.main(["--dir", str(tmp_path), "--tolerance", "-1"]) == 2
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["error"] == "tolerance must be >= 0"
    # an argparse-level usage error (unknown flag) honors the contract too
    assert mod.main(["--max-new-metrics", "0"]) == 2
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "argument parsing failed" in summary["error"]


def test_async_metrics_registered_and_gated(tmp_path):
    """asyncfed PR: the buffered-async twin legs gate on their update
    rates and _vs_sync ratios (higher is better; the update-rate ratio
    carries the tight 10% band — twin runs of one geometry, load
    cancels), the retrace gauge is hard-zero, and the geometry/provenance
    rows stay informational."""
    mod = _gate()
    for name in ("sketch_async_updates_per_sec",
                 "sketch_async_sync_rounds_per_sec",
                 "sketch_async_vs_sync",
                 "sketch_async_time_to_loss_vs_sync"):
        assert mod.metric_direction(name) == "up"
    assert mod.tolerance_for("sketch_async_vs_sync", 0.15) == 0.10
    # time-to-loss folds in a stochastic straggler schedule — default band
    assert mod.tolerance_for("sketch_async_time_to_loss_vs_sync",
                             0.15) == 0.15
    for name in ("sketch_async_buffer", "sketch_async_concurrency",
                 "sketch_async_straggler_rate",
                 "sketch_async_time_to_loss_sec", "sketch_async_error"):
        assert mod.metric_direction(name) is None
    # detects-regression self-test: the async advantage collapsing
    # (1.5x -> 1.0x) past the band must gate and name the ratio
    good = {**BASELINE, "sketch_async_vs_sync": 1.5,
            "sketch_async_updates_per_sec": 3.0,
            "sketch_async_retraces": 0}
    bad = {**BASELINE, "sketch_async_vs_sync": 1.0,
           "sketch_async_updates_per_sec": 1.9,
           "sketch_async_retraces": 0}
    _write(tmp_path, "BENCH_r01.json", good)
    _write(tmp_path, "BENCH_r02.json", bad)
    assert mod.main(["--dir", str(tmp_path)]) == 1
    regs, _, _ = mod.check_regression([good], bad)
    names = {r["metric"] for r in regs}
    assert names == {"sketch_async_vs_sync",
                     "sketch_async_updates_per_sec"}
    # within the band passes
    regs, _, _ = mod.check_regression(
        [good], {**good, "sketch_async_vs_sync": 1.42})
    assert regs == []
    # a retrace at ANY concurrency fails outright (the one-compiled-pair-
    # per-rung contract)
    regs, _, _ = mod.check_regression(
        [good], {**good, "sketch_async_retraces": 1})
    assert [r["metric"] for r in regs] == ["sketch_async_retraces"]
    assert regs[0]["direction"] == "exact_zero"


def test_overlap_metrics_registered_and_gated(tmp_path):
    """Hide-the-collectives PR: the overlap twin legs gate on their
    _vs_sequential ratios (higher is better, tight 10% band — twin runs
    of one geometry on one host, load cancels); the exposure/stall
    millisecond rows and the skip markers stay informational (near-zero
    ms readings are noise, not a gate)."""
    mod = _gate()
    for name in ("sketch_overlap_layerwise_vs_sequential",
                 "async_double_buffered_vs_sequential",
                 "sketch_overlap_layerwise_samples_per_sec",
                 "async_double_buffered_updates_per_sec"):
        assert mod.metric_direction(name) == "up"
    assert mod.tolerance_for("sketch_overlap_layerwise_vs_sequential",
                             0.15) == 0.10
    assert mod.tolerance_for("async_double_buffered_vs_sequential",
                             0.15) == 0.10
    for name in ("async_double_buffered_exposed_collective_ms",
                 "async_sequential_exposed_collective_ms",
                 "async_double_buffered_host_stall_ms",
                 "sketch_overlap_layerwise_skipped",
                 "async_double_buffered_skipped",
                 "sketch_overlap_error"):
        assert mod.metric_direction(name) is None
    # detects-regression self-test: the overlap advantage collapsing
    # below median * (1 - 0.10) must gate and name both ratios
    good = {**BASELINE, "sketch_overlap_layerwise_vs_sequential": 1.10,
            "async_double_buffered_vs_sequential": 1.20}
    bad = {**BASELINE, "sketch_overlap_layerwise_vs_sequential": 0.95,
           "async_double_buffered_vs_sequential": 1.00}
    _write(tmp_path, "BENCH_r01.json", good)
    _write(tmp_path, "BENCH_r02.json", bad)
    assert mod.main(["--dir", str(tmp_path)]) == 1
    regs, _, _ = mod.check_regression([good], bad)
    assert {r["metric"] for r in regs} == {
        "sketch_overlap_layerwise_vs_sequential",
        "async_double_buffered_vs_sequential"}
    assert all(r["direction"] == "up" for r in regs)
    # within the band passes
    regs, _, _ = mod.check_regression(
        [good], {**good, "sketch_overlap_layerwise_vs_sequential": 1.05,
                 "async_double_buffered_vs_sequential": 1.12})
    assert regs == []


def test_sketch_traced_rows_are_informational(tmp_path):
    """Round-tracing PR: the sketch_traced_* critical-path rows ride the
    matrix for attribution (which stage moved), never for gating —
    no exclusive-time family or stage-name string may acquire a gated
    suffix, and wildly different attribution between rounds must not
    fail the gate (a real regression still gates via the headline)."""
    mod = _gate()
    for name in ("sketch_traced_wall_ms", "sketch_traced_data_exclusive_ms",
                 "sketch_traced_collective_exclusive_ms",
                 "sketch_traced_idle_exclusive_ms",
                 "sketch_traced_critical_stage", "sketch_traced_rounds",
                 "sketch_traced_error"):
        assert mod.metric_direction(name) is None, name
    good = {**BASELINE, "sketch_traced_wall_ms": 12.0,
            "sketch_traced_critical_stage": "collective",
            "sketch_traced_collective_exclusive_ms": 8.0}
    moved = {**BASELINE, "sketch_traced_wall_ms": 50.0,
             "sketch_traced_critical_stage": "h2d",
             "sketch_traced_collective_exclusive_ms": 0.5}
    _write(tmp_path, "BENCH_r01.json", good)
    _write(tmp_path, "BENCH_r02.json", moved)
    assert mod.main(["--dir", str(tmp_path)]) == 0
    # the detects-regression guard still bites with traced rows present
    regs, _, _ = mod.check_regression([good], {**moved, "value": 19000.0})
    assert [r["metric"] for r in regs] == ["value"]


def test_multihost_metrics_registered_and_gated(tmp_path):
    """ISSUE 19 satellite: the multihost bench leg gates on its
    _vs_singlehost ratio (higher is better, tight 10% band — twin runs
    of one geometry on the same devices, load cancels); the bare
    samples/s row gates through the generic _samples_per_sec suffix,
    and error/skip markers never gate."""
    mod = _gate()
    assert mod.metric_direction("sketch_multihost_vs_singlehost") == "up"
    assert mod.tolerance_for("sketch_multihost_vs_singlehost", 0.15) == 0.10
    assert mod.metric_direction("sketch_multihost_samples_per_sec") == "up"
    assert mod.metric_direction("sketch_multihost_error") is None
    assert mod.metric_direction("sketch_multihost_skipped") is None
    # detects-regression self-test: the host axis growing a cost
    # (1.0x -> 0.8x) past the band must gate and name the ratio
    good = {**BASELINE, "sketch_multihost_vs_singlehost": 1.0}
    bad = {**BASELINE, "sketch_multihost_vs_singlehost": 0.8}
    _write(tmp_path, "BENCH_r01.json", good)
    _write(tmp_path, "BENCH_r02.json", bad)
    assert mod.main(["--dir", str(tmp_path)]) == 1
    regs, _, _ = mod.check_regression([good], bad)
    assert [r["metric"] for r in regs] == ["sketch_multihost_vs_singlehost"]
    assert regs[0]["direction"] == "up"
    # within the band passes
    regs, _, _ = mod.check_regression(
        [good], {**BASELINE, "sketch_multihost_vs_singlehost": 0.95})
    assert regs == []


def test_elastic_metrics_registered_and_gated(tmp_path):
    """ISSUE 20 satellite: the elastic bench leg gates on two axes —
    throughput (generic _samples_per_sec suffix) and the zero-retrace
    pin (_retraces is exact-zero, no history needed). resize_ms and the
    resize count stay informational: the first is microsecond-scale
    dispatch bookkeeping, the second is schedule configuration."""
    mod = _gate()
    assert mod.metric_direction("sketch_elastic_samples_per_sec") == "up"
    assert mod.metric_direction("sketch_elastic_resize_ms") is None
    assert mod.metric_direction("sketch_elastic_resizes") is None
    assert mod.metric_direction("sketch_elastic_error") is None
    # a single record with a nonzero retrace count fails with NO prior
    # history: the exact-zero gate is absolute, not relative
    broken = {**BASELINE, "sketch_elastic_samples_per_sec": 900.0,
              "sketch_elastic_retraces": 1.0}
    regs, _, _ = mod.check_regression([], broken)
    assert [r["metric"] for r in regs] == ["sketch_elastic_retraces"]
    assert regs[0]["direction"] == "exact_zero"
    _write(tmp_path, "BENCH_r01.json",
           {**BASELINE, "sketch_elastic_samples_per_sec": 900.0,
            "sketch_elastic_retraces": 0.0})
    _write(tmp_path, "BENCH_r02.json", broken)
    assert mod.main(["--dir", str(tmp_path)]) == 1
    # detects-regression self-test: elastic throughput collapsing past
    # tolerance gates and names the metric
    good = {**BASELINE, "sketch_elastic_samples_per_sec": 1000.0,
            "sketch_elastic_retraces": 0.0}
    bad = {**BASELINE, "sketch_elastic_samples_per_sec": 500.0,
           "sketch_elastic_retraces": 0.0}
    regs, _, _ = mod.check_regression([good], bad)
    assert [r["metric"] for r in regs] == ["sketch_elastic_samples_per_sec"]
    assert regs[0]["direction"] == "up"
    # healthy pair passes end to end
    _write(tmp_path, "BENCH_r01.json", good)
    _write(tmp_path, "BENCH_r02.json",
           {**good, "sketch_elastic_samples_per_sec": 980.0})
    assert mod.main(["--dir", str(tmp_path)]) == 0
